#!/bin/bash
# One-pass capture of all chip-side evidence, safe to run unattended the
# moment the TPU tunnel comes back:
#   1. bench.py            -> CHIP_BENCH.json (all MFU rows, watchdogged)
#   2. bench_kernels.py    -> BENCH_KERNELS.json (flash fwd/bwd, ring
#                             partials, int8/bf16 matmul ceilings)
#   3. bench_ssd.py        -> BENCH_SSD.json (fused SSD kernel vs XLA)
#   4. 194m training run on the learnable dummy stream + eval_ppl
#                          -> EVAL.json
# Every step is timeout-guarded and failure-isolated; the script always
# runs to the end and prints a summary of what was captured.
set -u
cd "$(dirname "$0")/.."
log() { echo "[chip_evidence $(date +%H:%M:%S)] $*"; }

log "probing chip"
if ! timeout 90 python -c "import jax, jax.numpy as jnp; print(float(jnp.sum(jax.jit(lambda a: a@a)(jnp.ones((8,8))))))" 2>/dev/null; then
    log "chip unavailable - aborting (nothing written)"
    exit 1
fi
log "chip is up"

log "1/4 bench.py (full row sweep, subprocess watchdogs)"
# 10 rows x 900s worst-case watchdog each; typical ~2-5 min/row
timeout 10000 python bench.py | tee CHIP_BENCH.json || log "bench.py failed"

log "2/4 bench_kernels.py"
timeout 2400 python scripts/bench_kernels.py || log "bench_kernels failed"

log "3/4 bench_ssd.py"
timeout 2400 python scripts/bench_ssd.py || log "bench_ssd failed"

log "3b/4 profile_mamba.py (component attribution for the mamba MFU)"
timeout 2400 python scripts/profile_mamba.py > /dev/null || log "profile_mamba failed"

log "4/4 eval: REAL arrow corpus -> train llama3_194m -> eval_ppl (fresh vs trained)"
rm -rf /tmp/eval_ckpt /tmp/eval_data
DATA_ARGS="--data_path=/tmp/eval_data --datasets=dataset_1 --weights=1 \
    --file_type=arrow --vocab_size=4096 --logical_shards=64"
timeout 600 python scripts/gen_arrow_data.py /tmp/eval_data \
    --n_shards=4 --docs_per_shard=2500 --doc_len=1000 --vocab=4096 \
    || log "corpus generation failed"
# fresh-init perplexity over the same stream: the before number that
# makes the after number meaningful
timeout 1200 python eval_ppl.py $DATA_ARGS --eval_batches=16 \
    --ckpt_load_path= --model_variant=llama3_194m_4k \
    --batch_size=4 --seq_length=4096 \
    > /tmp/eval_ppl_fresh.json 2>/tmp/eval_ppl_fresh.err \
    || log "fresh eval_ppl failed"
timeout 2400 python -u main_training_llama.py $DATA_ARGS \
    --num_steps=600 --report_interval=100 --checkpoint_interval=600 \
    --ckpt_save_path=/tmp/eval_ckpt --ckpt_load_path=/tmp/eval_ckpt \
    --model_variant=llama3_194m_4k --batch_size=4 --seq_length=4096 \
    --fsdp_activation_checkpointing=True --selective_checkpointing=0.5 \
    > /tmp/eval_train.log 2>&1 || log "eval training failed"
tail -n 3 /tmp/eval_train.log
timeout 1200 python eval_ppl.py $DATA_ARGS --eval_batches=16 \
    --ckpt_load_path=/tmp/eval_ckpt --model_variant=llama3_194m_4k \
    --batch_size=4 --seq_length=4096 > /tmp/eval_ppl.json 2>/tmp/eval_ppl.err \
    || log "eval_ppl failed"
python - <<'EOF' || true
import json

def last_json(path):
    try:
        with open(path) as f:
            lines = [l for l in f.read().splitlines() if l.strip().startswith("{")]
        return json.loads(lines[-1]) if lines else None
    except (OSError, json.JSONDecodeError):
        return None

trained = last_json("/tmp/eval_ppl.json")
fresh = last_json("/tmp/eval_ppl_fresh.json")
if trained:
    if fresh:
        trained["fresh_init_ppl"] = fresh.get("ppl")
        trained["ppl_improvement"] = (
            round(fresh["ppl"] / trained["ppl"], 2)
            if trained.get("ppl") and fresh.get("ppl") else None
        )
    trained["setup"] = (
        "llama3_194m_4k trained 600 steps (bs=4, seq=4096, ~9.8M tokens) on "
        "a generated REAL arrow corpus (4 shards x 2500 noisy-counter docs, "
        "scripts/gen_arrow_data.py) through the production 7-layer data "
        "pipeline on one v5e chip, then evaluated in place with eval_ppl.py "
        "(params-only sharded load). fresh_init_ppl is the same stream "
        "before training — the drop evidences arrow streaming -> training "
        "-> quality end to end; corpus-level parity with the reference's "
        "MMLU 0.50 needs the multi-pod 2T-token run (docs/evaluation.md)."
    )
    with open("EVAL.json", "w") as f:
        json.dump(trained, f, indent=1)
    print("EVAL.json:", json.dumps(trained)[:200])
else:
    print("no eval_ppl output; EVAL.json not written")
EOF

log "done; captured:"
for f in CHIP_BENCH.json BENCH_KERNELS.json BENCH_SSD.json PROFILE_MAMBA.json EVAL.json; do
    [ -f "$f" ] && echo "  $f: $(head -c 120 "$f")"
done
