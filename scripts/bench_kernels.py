"""Flash-attention kernel microbench at Llama2-7B head shapes.

Compares this repo's Pallas kernel against the two public TPU kernels
bundled with jax (jax.experimental.pallas.ops.tpu.{flash_attention,
splash_attention}) on the real chip. Writes BENCH_KERNELS.json at the
repo root.

Conventions (recorded in the JSON):
- shapes: B=1, 32 heads, S=4096, head_dim=128, causal, bf16;
- fwd FLOPs = 2 matmuls * 2*B*N*S^2*H / 2 (causal);
- fwd+bwd counted at 4.5x fwd for the separate-dq/dkv designs (9 matmul
  passes: 2 fwd + 7 bwd incl. recompute) — the FLOPs actually executed;
- timing: best of 3 reps x 60 iters, synced by host transfer (float());
  dispatch overhead amortizes across the 60-iter window (a single
  dispatch through the tunnel costs ~ms and poisons small-iter timings).

Context for the numbers: a plain 8192^3 bf16 matmul sustains ~150 TF/s
on this v5e (76% of the 197 TF/s peak); causal flash attention at these
shapes lands at ~50-60 TF/s for every implementation measured here —
the practical causal-attention ceiling on this chip, not a kernel gap.
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

B, N, S, H = 1, 32, 4096, 128
FWD_FLOPS = 2 * 2 * B * N * S * S * H // 2  # causal


def time_fn(fn, *args, iters=60, reps=3):
    out = fn(*args)
    _ = float(jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32)))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _ = float(jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32)))
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def bench(name, fwd, grad, rows, time_scale=1.0):
    """time_scale multiplies measured time (e.g. head-count normalization)."""
    print(f"# benching {name}", file=sys.stderr)
    t = time_fn(*fwd) * time_scale
    rows.append(
        {
            "kernel": name,
            "pass": "fwd",
            "ms": round(t * 1e3, 3),
            "tf_s": round(FWD_FLOPS / t / 1e12, 1),
        }
    )
    t = time_fn(*grad) * time_scale
    rows.append(
        {
            "kernel": name,
            "pass": "fwd+bwd",
            "ms": round(t * 1e3, 3),
            "tf_s_at_4.5x": round(FWD_FLOPS * 4.5 / t / 1e12, 1),
        }
    )


def main():
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (B, S, N, H), jnp.bfloat16)
    k = jax.random.normal(kk, (B, S, N, H), jnp.bfloat16)
    v = jax.random.normal(kv, (B, S, N, H), jnp.bfloat16)
    rows = []

    # ---- ours
    from fms_fsdp_tpu.ops.flash_attention import flash_attention

    ours_fwd = jax.jit(functools.partial(flash_attention, causal=True))

    def ours_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True).astype(jnp.float32))

    bench(
        "fms_fsdp_tpu (this repo)",
        (ours_fwd, q, k, v),
        (jax.jit(jax.grad(ours_loss, argnums=(0, 1, 2))), q, k, v),
        rows,
    )

    # ---- ours, kv-streamed forward variant (flash_kernel_variant="kvgrid"):
    # kv blocks walked by the grid with Mosaic double-buffering instead
    # of staging the whole stream in VMEM; fwd-only (bwd is shared)
    from fms_fsdp_tpu.ops.flash_attention import _flash_fwd_kvgrid

    qb, kb, vb = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    kvgrid_fwd = jax.jit(
        functools.partial(
            _flash_fwd_kvgrid,
            scale=H**-0.5,
            causal=True,
            block_q=512,
            block_k=512,
            interpret=False,
        )
    )
    print("# benching kvgrid fwd variant", file=sys.stderr)
    t = time_fn(kvgrid_fwd, qb, kb, vb)
    rows.append(
        {
            "kernel": "fms_fsdp_tpu kvgrid fwd variant",
            "pass": "fwd",
            "ms": round(t * 1e3, 3),
            "tf_s": round(FWD_FLOPS / t / 1e12, 1),
        }
    )

    # ---- block-size sweep (fwd, both families): the race that picks the
    # shipped defaults (VERDICT r3 item 2). 512/512 is omitted — the
    # headline rows above already time both families there at higher
    # iters. Skippable: BENCH_NO_SWEEP=1.
    if not os.environ.get("BENCH_NO_SWEEP"):
        from fms_fsdp_tpu.ops import flash_attention as fa

        for bq, bk in [
            (256, 256), (256, 512), (512, 256),
            (512, 1024), (1024, 512), (1024, 1024),
        ]:
            for fam, fn in (
                ("resident", fa._flash_fwd),
                ("kvgrid", _flash_fwd_kvgrid),
            ):
                # pin the family: _flash_fwd dispatches through
                # _use_kvgrid, so an ambient kvgrid override would make
                # the "resident" rows silently measure the kvgrid kernel
                fa.set_kernel_variant(fam)
                f = jax.jit(
                    functools.partial(
                        fn, scale=H**-0.5, causal=True,
                        block_q=bq, block_k=bk, interpret=False,
                    )
                )
                print(f"# sweep {fam} bq={bq} bk={bk}", file=sys.stderr)
                try:
                    t = time_fn(f, qb, kb, vb, iters=30)
                except Exception as e:  # noqa: BLE001 — record, keep sweeping
                    rows.append(
                        {
                            "kernel": f"{fam} fwd bq={bq} bk={bk}",
                            "pass": "fwd",
                            "error": f"{type(e).__name__}: {e}"[:160],
                        }
                    )
                    continue
                rows.append(
                    {
                        "kernel": f"{fam} fwd bq={bq} bk={bk}",
                        "pass": "fwd",
                        "ms": round(t * 1e3, 3),
                        "tf_s": round(FWD_FLOPS / t / 1e12, 1),
                    }
                )
        fa.set_kernel_variant(None)  # restore import-time default

    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))

    # ---- jax bundled flash_attention (best blocks found by sweep: 512)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes as FABlocks,
        flash_attention as jax_fa,
    )

    bs = FABlocks(
        block_q=512, block_k_major=512, block_k=512, block_b=1,
        block_q_major_dkv=512, block_k_major_dkv=512, block_k_dkv=512,
        block_q_dkv=512, block_k_major_dq=512, block_k_dq=512, block_q_dq=512,
    )
    jfa = functools.partial(jax_fa, causal=True, sm_scale=H**-0.5, block_sizes=bs)
    jfa_fwd = jax.jit(jfa)

    def jfa_loss(q, k, v):
        return jnp.sum(jfa(q, k, v).astype(jnp.float32))

    bench(
        "jax.pallas flash_attention",
        (jfa_fwd, qt, kt, vt),
        (jax.jit(jax.grad(jfa_loss, argnums=(0, 1, 2))), qt, kt, vt),
        rows,
    )

    # ---- splash attention (best blocks found by sweep: 512/1024)
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    # 8 of the 32 heads: the full-head mask constants exceed the tunnel's
    # compile-request size limit; per-head work is identical, so numbers
    # are normalized by the head count (recorded in the kernel label).
    NSP = 8
    mask = sm.MultiHeadMask([sm.CausalMask((S, S)) for _ in range(NSP)])
    sbs = sk.BlockSizes(
        block_q=512, block_kv=1024, block_kv_compute=1024,
        block_q_dkv=512, block_kv_dkv=1024, block_kv_dkv_compute=1024,
        block_q_dq=512, block_kv_dq=1024,
    )
    kernel = sk.make_splash_mha(
        mask=mask, head_shards=1, q_seq_shards=1, block_sizes=sbs
    )
    q3, k3, v3 = qt[0, :NSP] * (H**-0.5), kt[0, :NSP], vt[0, :NSP]
    sp_fwd = jax.jit(kernel)

    def sp_loss(q, k, v):
        return jnp.sum(kernel(q, k, v).astype(jnp.float32))

    bench(
        f"jax.pallas splash_attention ({NSP}/32 heads, normalized)",
        (sp_fwd, q3, k3, v3),
        (jax.jit(jax.grad(sp_loss, argnums=(0, 1, 2))), q3, k3, v3),
        rows,
        time_scale=N / NSP,
    )

    # ---- ring-attention building blocks (VERDICT r2 item 9): the
    # off-diagonal per-step work of the ring backward — flash_dq +
    # flash_dkv partials against a visiting kv chunk (causal=False, the
    # fully-visible case) — plus the forward partial+merge, at 8k local
    # sequence. The collectives need a real multi-chip pod; the per-step
    # kernel work is what one chip can evidence.
    from fms_fsdp_tpu.ops.flash_attention import flash_dkv, flash_dq

    SR, NR = 8192, 8  # 8k local seq; 8 heads fit the partial's VMEM budget
    qr = jax.random.normal(kq, (B, NR, SR, H), jnp.bfloat16)
    kr = jax.random.normal(kk, (B, NR, SR, H), jnp.bfloat16)
    vr = jax.random.normal(kv, (B, NR, SR, H), jnp.bfloat16)
    dor = jax.random.normal(kq, (B, NR, SR, H), jnp.bfloat16)
    lse_r = jax.random.normal(kk, (B, NR, SR, 1), jnp.float32) + 8.0
    delta_r = jax.random.normal(kv, (B, NR, SR, 1), jnp.float32)
    ring_kw = dict(
        scale=H**-0.5, causal=False, block_q=512, block_k=512, interpret=False
    )
    dq_fn = jax.jit(functools.partial(flash_dq, **ring_kw, out_dtype=jnp.float32))
    dkv_fn = jax.jit(functools.partial(flash_dkv, **ring_kw))
    # one ring backward step = dq partial + dkv partial
    ring_bwd_flops = 4 * 2 * B * NR * SR * SR * H + 3 * 2 * B * NR * SR * SR * H
    t_dq = time_fn(dq_fn, qr, kr, vr, dor, lse_r, delta_r, iters=20)
    t_dkv = time_fn(dkv_fn, qr, kr, vr, dor, lse_r, delta_r, iters=20)
    rows.append(
        {
            "kernel": f"ring bwd step (flash_dq+flash_dkv partials, "
            f"S_local={SR}, {NR} heads)",
            "pass": "bwd-partial",
            "ms": round((t_dq + t_dkv) * 1e3, 3),
            "tf_s": round(ring_bwd_flops / (t_dq + t_dkv) / 1e12, 1),
        }
    )

    # forward partial + lse merge (the per-step fwd work of the ring loop)
    def ring_fwd_step(acc, lse_run, q, k, v):
        o, lse = flash_attention(
            jnp.swapaxes(q, 1, 2),
            jnp.swapaxes(k, 1, 2),
            jnp.swapaxes(v, 1, 2),
            causal=False,
            return_lse=True,
        )
        o, lse = jnp.swapaxes(o, 1, 2), jnp.swapaxes(lse, 1, 2)
        new_lse = jnp.logaddexp(lse_run, lse)
        acc = acc * jnp.exp(lse_run - new_lse) + o.astype(jnp.float32) * jnp.exp(
            lse - new_lse
        )
        return acc, new_lse

    acc0 = jnp.zeros((B, NR, SR, H), jnp.float32)
    lse0 = jnp.full((B, NR, SR, 1), -1e30, jnp.float32)
    fwd_step = jax.jit(ring_fwd_step)
    t_fs = time_fn(fwd_step, acc0, lse0, qr, kr, vr, iters=20)
    ring_fwd_flops = 2 * 2 * B * NR * SR * SR * H  # full (non-causal) partial
    rows.append(
        {
            "kernel": f"ring fwd step (flash partial + lse merge, "
            f"S_local={SR}, {NR} heads)",
            "pass": "fwd-partial",
            "ms": round(t_fs * 1e3, 3),
            "tf_s": round(ring_fwd_flops / t_fs / 1e12, 1),
        }
    )

    # ---- calibration: plain matmul ceiling
    a = jax.random.normal(kq, (8192, 8192), jnp.bfloat16)
    b2 = jax.random.normal(kk, (8192, 8192), jnp.bfloat16)
    mm = jax.jit(lambda a, b: a @ b)
    t = time_fn(mm, a, b2)
    rows.append(
        {
            "kernel": "plain 8192^3 bf16 matmul (ceiling)",
            "pass": "fwd",
            "ms": round(t * 1e3, 3),
            "tf_s": round(2 * 8192**3 / t / 1e12, 1),
        }
    )

    from fms_fsdp_tpu.utils.flops import peak_flops_per_chip

    result = {
        "shapes": f"B={B} heads={N} S={S} head_dim={H} causal bf16",
        "chip": os.environ.get("PALLAS_AXON_TPU_GEN", "v5e"),
        "peak_bf16_tf_s": round(peak_flops_per_chip() / 1e12),
        "notes": [
            "run-to-run variance through the tunneled chip is ~+/-15% on fwd",
            "splash at 8 heads underestimates its full-batch amortization: a "
            "32-head run (done before the compile-size limit was understood) "
            "measured 52.8 TF/s fwd / 95.9 at 4.5x fwd+bwd",
        ],
        "rows": rows,
    }
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_KERNELS.json",
    )
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
