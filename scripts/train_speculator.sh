#!/bin/bash
# Speculator training launch (ref:scripts/train_speculator.sh analog).

set -euo pipefail

SPEC_ARGS="\
--model_variant=llama2_7b
--model_path=/ckpts/base
--ckpt_load_path=/spec_ckpts
--ckpt_save_path=/spec_ckpts
--data_path=/data
--sharding_strategy=tp
--tp_size=8
--batch_size=8
--seq_length=4096
--n_speculator_heads=3
--speculator_width=4096
--stage2_start_step=15000
--num_steps=30000
--report_interval=100
--checkpoint_interval=2000
"

python speculator/train_speculator.py ${SPEC_ARGS} "$@"
