#!/usr/bin/env bash
# Probe the TPU tunnel forever; the moment it answers, run the full
# chip-evidence capture (scripts/chip_evidence.sh) once, unattended.
# Probe timestamps land in PROBELOG.txt (NOTES.md cites them when the
# tunnel stays dead a whole round, per VERDICT r3 item 1).
cd "$(dirname "$0")/.."
LOG=PROBELOG.txt
while true; do
  ts=$(date -u +"%Y-%m-%dT%H:%M:%SZ")
  if timeout 180 python -c "import jax; d=jax.devices(); assert d and d[0].platform=='tpu', d; print(d)" >/tmp/probe_out 2>&1; then
    echo "$ts ALIVE: $(cat /tmp/probe_out | tail -1)" >> "$LOG"
    echo "$ts launching chip_evidence.sh" >> "$LOG"
    rm -f CHIP_BENCH.json  # a stale committed capture must not satisfy the completion check
    bash scripts/chip_evidence.sh >> chip_evidence_run.log 2>&1
    echo "$(date -u +"%Y-%m-%dT%H:%M:%SZ") chip_evidence.sh finished rc=$?" >> "$LOG"
    python scripts/summarize_chip_evidence.py >> chip_evidence_run.log 2>&1 || true
    # add each artifact individually (several are optional — a single
    # missing pathspec would abort the whole add), and commit only the
    # evidence paths so operator-staged WIP is never swept in
    evidence=""
    for f in CHIP_BENCH.json BENCH_KERNELS.json BENCH_SSD.json \
             PROFILE_MAMBA.json EVAL.json DECISIONS_r04.md PROBELOG.txt; do
      [ -e "$f" ] && git add "$f" && evidence="$evidence $f"
    done
    [ -n "$evidence" ] && git commit -q \
      -m "Record chip evidence captured by the unattended probe loop" \
      -- $evidence || true
    # only stop once a real headline row landed — a tunnel that died
    # mid-capture (chip_evidence aborts or bench errors out) means we
    # should keep probing and try the capture again later. TOP-LEVEL
    # keys only: per-row "error" entries for non-headline rows are
    # recorded-and-acceptable, not grounds to redo the whole capture.
    if python -c '
import json, sys
r = json.load(open("CHIP_BENCH.json"))
sys.exit(0 if "vs_baseline" in r and "error" not in r else 1)' 2>/dev/null; then
      echo "$(date -u +"%Y-%m-%dT%H:%M:%SZ") capture complete - probe loop exiting" >> "$LOG"
      break
    fi
    echo "$(date -u +"%Y-%m-%dT%H:%M:%SZ") capture incomplete - resuming probes" >> "$LOG"
  else
    rc=$?
    tail_line=$(tail -1 /tmp/probe_out 2>/dev/null | cut -c1-120)
    echo "$ts DEAD rc=$rc ${tail_line}" >> "$LOG"
  fi
  sleep 600
done
