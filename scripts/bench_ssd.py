"""Mamba-path kernel microbench at mamba_9.8b shapes (ref:config_utils.py:162-185).

Times the chunked SSD scan (both the group-factored XLA formulation and
the Pallas intra-chunk kernel) and the depthwise causal conv1d on the
real chip, fwd and fwd+bwd. Writes BENCH_SSD.json at the repo root.

Measured v5e facts this records (see ops/ssd.py docstrings):
- the XLA einsum formulation beats the Pallas intra-chunk kernel ~2x at
  these shapes (tiny per-head matmuls + per-chunk head-major relayouts);
  ``kernel="auto"`` therefore resolves to XLA.
- conv1d as shifted FMAs with a bf16 pad: a few ms fwd+bwd vs ~29ms for
  XLA's grouped conv. Run-to-run variance through the tunneled chip is
  ~+/-15-30%; the JSON records one run, the orderings are stable.

Timing comes from scripts/bench_kernels.py::time_fn: best of 3 reps x N
amortized iters, synced by host transfer (block_until_ready does not
drain the tunneled TPU queue).
"""

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from bench_kernels import time_fn
from fms_fsdp_tpu.ops.ssd import causal_conv1d, ssd_scan

# mamba_9.8b Mamba2 layer shapes: d_inner 8192, headdim 64 -> 128 heads,
# d_state 128, ngroups 1, conv width 4 over d_inner + 2*G*N channels
B, S, H, P, G, N = 2, 4096, 128, 64, 1, 128
CONV_C, CONV_W = H * P + 2 * G * N, 4


def main():
    ks = jax.random.split(jax.random.PRNGKey(0), 6)
    x = jax.random.normal(ks[0], (B, S, H, P), jnp.bfloat16)
    dt = jax.nn.softplus(jax.random.normal(ks[1], (B, S, H), jnp.float32))
    A = -jnp.exp(jax.random.normal(ks[2], (H,), jnp.float32))
    Bm = jax.random.normal(ks[3], (B, S, G, N), jnp.bfloat16)
    Cm = jax.random.normal(ks[4], (B, S, G, N), jnp.bfloat16)
    D = jnp.ones((H,), jnp.float32)

    cx = jax.random.normal(ks[5], (B, S, CONV_C), jnp.bfloat16)
    cw = jax.random.normal(ks[0], (CONV_C, CONV_W), jnp.float32) * 0.1
    cb = jnp.zeros((CONV_C,), jnp.float32)

    rows = []

    def add(name, fwd_fn, grad_fn, args):
        print(f"# benching {name}", file=sys.stderr)
        t_f = time_fn(jax.jit(fwd_fn), *args, iters=30)
        t_g = time_fn(jax.jit(grad_fn), *args, iters=15)
        rows.append(
            {
                "kernel": name,
                "fwd_ms": round(t_f * 1e3, 3),
                "fwd_bwd_ms": round(t_g * 1e3, 3),
            }
        )

    # chunk sweep for both formulations: the fused kernel's VMEM residency
    # ((L, L) decay product + per-group state) and the XLA path's
    # materialized (B, L, L, G, R) weight tensor trade off differently
    # with L, so the shipped "auto" choice is the measured best pair
    for mode, chunk in (
        ("xla", 128),
        ("xla", 256),
        ("xla", 512),
        ("pallas", 128),
        ("pallas", 256),
        ("pallas", 512),
    ):
        fwd = functools.partial(ssd_scan, kernel=mode, chunk_size=chunk)

        def loss(x, dt, A, Bm, Cm, D, fwd=fwd):
            return jnp.sum(fwd(x, dt, A, Bm, Cm, D).astype(jnp.float32))

        add(
            f"ssd_scan[{mode},L={chunk}]",
            fwd,
            jax.grad(loss, argnums=(0, 1, 3, 4)),
            (x, dt, A, Bm, Cm, D),
        )

    def closs(cx, cw, cb):
        return jnp.sum(causal_conv1d(cx, cw, cb).astype(jnp.float32))

    add(
        "causal_conv1d",
        causal_conv1d,
        jax.grad(closs, argnums=(0, 1, 2)),
        (cx, cw, cb),
    )

    out = {
        "shapes": (
            f"SSD: B={B} S={S} H={H} P={P} G={G} N={N} chunk swept bf16; "
            f"conv1d: C={CONV_C} W={CONV_W}"
        ),
        "chip": os.environ.get("PALLAS_AXON_TPU_GEN", "v5e"),
        "rows": rows,
    }
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_SSD.json",
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
