"""On-device kernel autotune sweep -> KERNEL_TUNING.json.

For every (kernel, shape signature, dtype) in the bench-derived suite,
enumerate the legal tile candidates (fms_fsdp_tpu/tune/candidates.py —
divisibility + static VMEM pruning, no device needed), time the
survivors on the attached chip (fwd+bwd, proper warmup and
``block_until_ready``), and write the winners into the schema-versioned
tuning table the trace-time lookup reads
(fms_fsdp_tpu/tune/{table,lookup}.py).

Robustness contract mirrors bench.py / aot_lower_kernels.py: the parent
never imports jax; every candidate times in its own ``--measure``
subprocess under a watchdog, so one Mosaic hang or OOM yields an error
entry instead of killing the sweep. Measured entries replace
cost-model-seeded ones; a failed candidate simply never wins.

Modes:
    python scripts/autotune_kernels.py              # full on-chip sweep
    python scripts/autotune_kernels.py --dry-run    # candidate gen +
        VMEM pruning only: pure host arithmetic, no jax import, runs on
        any CI box (exercised by tests/test_tune.py and pytest.yml)
    python scripts/autotune_kernels.py --lookup-only [--chip v5e]
        # resolve the whole suite through the committed table (exact /
        # nearest / default per entry) without timing anything
    python scripts/autotune_kernels.py --seed-cost-model [--chip v5e]
        # (re)seed table entries from the cost model without a chip —
        # never overwrites measured entries

Env: AUTOTUNE_CANDIDATE_TIMEOUT_S (default 420), AUTOTUNE_STEPS,
AUTOTUNE_REPS, FMS_TUNE_CHIP (chip key override for the table).
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from fms_fsdp_tpu.tune import candidates as cand  # noqa: E402  (pure host code)
from fms_fsdp_tpu.tune.table import (  # noqa: E402
    TuningTable,
    default_table_path,
    validate_table,
)

CANDIDATE_TIMEOUT_S = int(os.environ.get("AUTOTUNE_CANDIDATE_TIMEOUT_S", "420"))
STEPS = int(os.environ.get("AUTOTUNE_STEPS", "10"))
REPS = int(os.environ.get("AUTOTUNE_REPS", "3"))

# The sweep suite: every distinct kernel signature the bench rows
# (bench.py ROWS) trace, in the training dtype. Keyed exactly as the
# trace-time lookup keys them, so a sweep win is a guaranteed exact hit.
SUITE = [
    # flash: llama2_7b headline (32q/32kv heads, head 128, seq 4096)
    ("flash_attention",
     {"batch": 2, "nq": 32, "nkv": 32, "seq_q": 4096, "seq_k": 4096,
      "head": 128},
     "bfloat16"),
    # flash: llama3_194m_4k (8 MHA heads)
    ("flash_attention",
     {"batch": 4, "nq": 8, "nkv": 8, "seq_q": 4096, "seq_k": 4096,
      "head": 128},
     "bfloat16"),
    # flash: the 16k / 32k long-context rows (kv-streamed territory)
    ("flash_attention",
     {"batch": 1, "nq": 8, "nkv": 8, "seq_q": 16384, "seq_k": 16384,
      "head": 128},
     "bfloat16"),
    ("flash_attention",
     {"batch": 1, "nq": 8, "nkv": 8, "seq_q": 32768, "seq_k": 32768,
      "head": 128},
     "bfloat16"),
    # SSD: mamba_9.8b head geometry (128 heads x P=64, N=128, 1 group)
    ("ssd",
     {"batch": 2, "seq": 4096, "heads": 128, "headdim": 64, "groups": 1,
      "dstate": 128},
     "bfloat16"),
    ("ssd",
     {"batch": 1, "seq": 16384, "heads": 128, "headdim": 64, "groups": 1,
      "dstate": 128},
     "bfloat16"),
    # fused CE: 7B-shaped head (d 4096, 32k vocab) and the 194m head
    # (d 1024, 128k vocab) the long-context rows run
    ("fused_ce", {"d_model": 4096, "vocab": 32000}, "bfloat16"),
    ("fused_ce", {"d_model": 1024, "vocab": 128256}, "bfloat16"),
    # paged decode (serving): 7B-shaped GQA decode batch and the
    # high-throughput small-model shape bench_serving.py drives
    ("paged_decode",
     {"batch": 8, "nq": 32, "nkv": 8, "head": 128, "max_seq": 4096},
     "bfloat16"),
    ("paged_decode",
     {"batch": 16, "nq": 8, "nkv": 8, "head": 128, "max_seq": 2048},
     "bfloat16"),
    # dcn_bucket (parallel/overlap.py): the bucketed cross-slice gradient
    # reduction schedule. grad_mb = the grad tree's total wire MB —
    # 7B at bf16 wire (~13.4GB), the 194m-shaped model (~372MB), and the
    # 7B again on a 4-slice world at the 1-byte fp8 wire. leaves matches
    # the scan-stacked llama param tree (11 top-level leaves).
    ("dcn_bucket",
     {"grad_mb": 13344, "leaves": 11, "slices": 2, "wire_bytes": 2},
     "bfloat16"),
    ("dcn_bucket",
     {"grad_mb": 372, "leaves": 11, "slices": 2, "wire_bytes": 2},
     "bfloat16"),
    ("dcn_bucket",
     {"grad_mb": 6672, "leaves": 11, "slices": 4, "wire_bytes": 1},
     "bfloat16"),
]


def suite_candidates(chip: str):
    """[(kernel, sig, dtype, [candidate, ...]), ...] — pure host work."""
    out = []
    for kernel, sig, dtype in SUITE:
        gen = cand.CANDIDATES[kernel]
        out.append((kernel, sig, dtype, gen(sig, dtype, chip)))
    return out


def _default_config(kernel: str) -> dict:
    if kernel == "flash_attention":
        return {
            "family": None,
            "block_q": cand.FLASH_DEFAULT_BLOCK_Q,
            "block_k": cand.FLASH_DEFAULT_BLOCK_K,
        }
    if kernel == "ssd":
        return {"chunk": cand.SSD_DEFAULT_CHUNK}
    if kernel == "paged_decode":
        return {
            "page_size": cand.PAGED_DEFAULT_PAGE_SIZE,
            "block_kv": cand.PAGED_DEFAULT_BLOCK_KV,
        }
    if kernel == "dcn_bucket":
        return {"bucket_mb": cand.DCN_BUCKET_DEFAULT_MB}
    return {"chunk": cand.CE_DEFAULT_CHUNK}


def _cost_model_pick(kernel: str, sig: dict, cands: list, dtype: str,
                     chip: str) -> dict:
    """Chipless seed: prefer the static default when it survived
    pruning (it is the measured-in-anger configuration the shipped
    kernels were sized around), else the largest legal tile — bigger
    tiles amortize more loop overhead per DMA under the budget.
    dcn_bucket candidates carry a modeled exposed-latency cost instead
    of a VMEM footprint, so there the cheapest candidate wins."""
    if kernel == "dcn_bucket":
        if not cands:
            return _default_config(kernel)
        best = min(cands, key=lambda c: c.get("cost_us", float("inf")))
        return _strip(best)
    default = _default_config(kernel)
    if kernel == "paged_decode":
        # keep the measured-in-anger page size, but take the widest
        # legal block_kv at it: the v2 kernel fetches block_kv//page_size
        # pages per grid step, and more positions per cell amortize the
        # per-step overhead (tie-break the cost model can price blind)
        at_ps = [c for c in cands
                 if c.get("page_size") == default["page_size"]]
        if at_ps:
            best = max(at_ps, key=lambda c: c["block_kv"])
            return _strip(best)
        return default
    for c in cands:
        if all(c.get(k) == v for k, v in default.items() if k != "family"):
            d = dict(default)
            if kernel == "flash_attention":
                d["family"] = (
                    "resident" if sig["seq_k"] <= cand.resident_max_seq(
                        sig["head"], dtype, chip) else "kvgrid"
                )
            return d
    if not cands:
        return default
    best = max(cands, key=lambda c: c.get("vmem_bytes",
                                          c.get("working_set_bytes", 0)))
    return {k: v for k, v in best.items()
            if k not in ("vmem_bytes", "working_set_bytes")}


# -- child: time one candidate ----------------------------------------------


def _measure_child(spec_json: str):
    spec = json.loads(spec_json)
    kernel, sig, dtype, config = (
        spec["kernel"], spec["sig"], spec["dtype"], spec["config"],
    )
    import jax
    import jax.numpy as jnp

    # pin everything: the candidate under test must be exactly what
    # runs, never a table resolution of it
    from fms_fsdp_tpu.tune.lookup import configure_kernel_tuning

    configure_kernel_tuning("off")
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

    if kernel == "flash_attention":
        from fms_fsdp_tpu.ops.flash_attention import flash_attention

        b, nq, nkv, sq, sk, h = (
            sig["batch"], sig["nq"], sig["nkv"], sig["seq_q"],
            sig["seq_k"], sig["head"],
        )
        q = jax.random.normal(jax.random.PRNGKey(0), (b, sq, nq, h), dt)
        kv = jax.random.normal(jax.random.PRNGKey(1), (b, sk, nkv, h), dt)

        def loss(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=True,
                    block_q=config["block_q"], block_k=config["block_k"],
                    variant=config.get("family"),
                    # quant candidates must time the path production
                    # runs: the q/k wire round-trip + kernel, not the
                    # bare unquantized kernel
                    quant=config.get("quant"),
                ).astype(jnp.float32)
            )

        f = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        args = (q, kv, kv)
    elif kernel == "ssd":
        from fms_fsdp_tpu.ops.ssd import ssd_scan

        b, s, hh, p, g, n = (
            sig["batch"], sig["seq"], sig["heads"], sig["headdim"],
            sig["groups"], sig["dstate"],
        )
        x = jax.random.normal(jax.random.PRNGKey(0), (b, s, hh, p), dt)
        dts = jax.nn.softplus(
            jax.random.normal(jax.random.PRNGKey(1), (b, s, hh))
        )
        A = -jnp.exp(jax.random.normal(jax.random.PRNGKey(2), (hh,)))
        Bm = jax.random.normal(jax.random.PRNGKey(3), (b, s, g, n), dt)
        Cm = jax.random.normal(jax.random.PRNGKey(4), (b, s, g, n), dt)

        def loss(x, Bm, Cm):
            return jnp.sum(
                ssd_scan(
                    x, dts, A, Bm, Cm, chunk_size=config["chunk"]
                ).astype(jnp.float32)
            )

        f = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        args = (x, Bm, Cm)
    elif kernel == "paged_decode":
        from fms_fsdp_tpu.ops.paged_attention import paged_attention_kernel

        b, nq, nkv, h, max_seq = (
            sig["batch"], sig["nq"], sig["nkv"], sig["head"],
            sig["max_seq"],
        )
        ps = config["page_size"]
        maxp = max_seq // ps
        # pool sized for the batch at capacity; sequential page tables
        # with rows at ~3/4 capacity (the ragged steady state)
        pool = b * maxp + 2
        kp = jax.random.normal(jax.random.PRNGKey(0), (pool, ps, nkv, h), dt)
        vp = jax.random.normal(jax.random.PRNGKey(1), (pool, ps, nkv, h), dt)
        q = jax.random.normal(jax.random.PRNGKey(2), (b, nq, h), dt)
        import numpy as np

        table = np.arange(2, 2 + b * maxp, dtype=np.int32).reshape(b, maxp)
        lens = np.full((b,), (3 * max_seq) // 4, np.int32)

        bkv = int(config.get("block_kv", ps))
        f = jax.jit(
            lambda q, kp, vp, t, l: paged_attention_kernel(
                q, kp, vp, t, l, block_kv=bkv
            )
        )
        args = (q, kp, vp, jnp.asarray(table), jnp.asarray(lens))
    elif kernel == "dcn_bucket":
        # time the SCHEDULE, not a kernel: K sequential bucket-sized
        # all-reduces over every attached device (on a multi-slice host
        # that path crosses the DCN; single-slice sweeps measure the
        # interconnect they have). Payload per reduce = one bucket's
        # wire bytes in fp32 elements, K = ceil(grad_mb / bucket_mb) —
        # the same arithmetic the cost model prices.
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = np.array(jax.devices())
        mesh = Mesh(devs, ("x",))
        bucket_mb = int(config["bucket_mb"])
        total_mb = int(sig["grad_mb"])
        k_buckets = max(1, -(-total_mb // bucket_mb))
        nbytes = min(bucket_mb, total_mb) * 1024 * 1024
        n = max(1, nbytes // 4)
        x = jax.device_put(
            jnp.ones((len(devs), n), jnp.float32),
            NamedSharding(mesh, P("x")),
        )
        reduce_fn = jax.jit(
            lambda a: jnp.sum(a, axis=0),
            out_shardings=NamedSharding(mesh, P()),
        )

        def f(a, _k=k_buckets):
            out = None
            for _ in range(_k):
                out = reduce_fn(a)
            return out

        args = (x,)
    else:  # fused_ce
        from fms_fsdp_tpu.ops.fused_ce import fused_linear_cross_entropy

        d, v = sig["d_model"], sig["vocab"]
        toks = 8192  # one bench-row step's worth of tokens (bs*seq scale)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, toks, d), dt)
        w = jax.random.normal(jax.random.PRNGKey(1), (d, v), dt)
        labels = jax.random.randint(
            jax.random.PRNGKey(2), (1, toks), 0, v, dtype=jnp.int32
        )

        def loss(x, w):
            return fused_linear_cross_entropy(x, w, labels, config["chunk"])

        f = jax.jit(jax.grad(loss, argnums=(0, 1)))
        args = (x, w)

    # warmup/compile, then best-of-REPS amortized timing
    out = f(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = f(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / STEPS)
    print("AUTOTUNE_JSON:" + json.dumps({"ms": best * 1e3}))


# -- parent ------------------------------------------------------------------


def _detect_chip() -> str:
    """Chip key via a probe subprocess (the parent never imports jax)."""
    code = (
        "from fms_fsdp_tpu.tune.lookup import chip_kind;"
        "print('CHIP:' + chip_kind())"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, timeout=240, text=True, cwd=REPO,
        )
        for line in (proc.stdout or "").splitlines():
            if line.startswith("CHIP:"):
                return line.split(":", 1)[1].strip()
    except subprocess.TimeoutExpired:
        pass
    return "unknown"


def _time_candidate(kernel, sig, dtype, config):
    spec = json.dumps(
        {"kernel": kernel, "sig": sig, "dtype": dtype, "config": config}
    )
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--measure", spec],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=CANDIDATE_TIMEOUT_S, text=True, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {CANDIDATE_TIMEOUT_S}s"
    for line in (proc.stdout or "").splitlines():
        if line.startswith("AUTOTUNE_JSON:"):
            try:
                return json.loads(line[len("AUTOTUNE_JSON:"):])["ms"], None
            except (json.JSONDecodeError, KeyError):
                break
    tail = " | ".join((proc.stdout or "").strip().splitlines()[-3:])
    return None, f"rc={proc.returncode}: {tail}"[:300]


def _strip(config: dict) -> dict:
    return {k: v for k, v in config.items()
            if k not in ("vmem_bytes", "working_set_bytes", "cost_us")}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dry-run", action="store_true",
                    help="candidate generation + VMEM pruning only")
    ap.add_argument("--lookup-only", action="store_true",
                    help="resolve the suite through the table, no timing")
    ap.add_argument("--seed-cost-model", action="store_true",
                    help="write cost-model picks for entries lacking "
                         "measured data")
    ap.add_argument("--chip", default=os.environ.get("FMS_TUNE_CHIP", ""),
                    help="chip key for the table (default: detect)")
    ap.add_argument("--table", default=default_table_path())
    ap.add_argument("--measure", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.measure:
        _measure_child(args.measure)
        return

    chip = args.chip or ("v5e" if args.dry_run else _detect_chip())

    if args.dry_run:
        report = []
        for kernel, sig, dtype, cands in suite_candidates(chip):
            report.append(
                {
                    "kernel": kernel, "signature": sig, "dtype": dtype,
                    "chip": chip, "legal_candidates": len(cands),
                    "candidates": cands,
                    "cost_model_pick": _cost_model_pick(
                        kernel, sig, cands, dtype, chip
                    ),
                }
            )
        doc = {"mode": "dry_run", "chip": chip, "suite": report}
        if os.path.exists(args.table):
            with open(args.table) as f:
                doc["table_violations"] = validate_table(json.load(f))
        print(json.dumps(doc, indent=1))
        return

    if args.lookup_only:
        from fms_fsdp_tpu.tune.lookup import (
            configure_kernel_tuning,
            resolve_ce_chunk,
            resolve_dcn_bucket,
            resolve_flash,
            resolve_paged_decode,
            resolve_ssd_chunk,
            choices,
        )

        configure_kernel_tuning("auto", args.table, chip=chip)
        resolved = []
        for kernel, sig, dtype in SUITE:
            if kernel == "flash_attention":
                bq, bk, fam, qnt, how = resolve_flash(
                    (sig["batch"], sig["seq_q"], sig["nq"], sig["head"]),
                    (sig["batch"], sig["seq_k"], sig["nkv"], sig["head"]),
                    dtype, chip=chip,
                )
                r = {"block_q": bq, "block_k": bk, "family": fam,
                     "quant": qnt, "how": how}
            elif kernel == "ssd":
                L = resolve_ssd_chunk(
                    (sig["batch"], sig["seq"], sig["heads"],
                     sig["headdim"]),
                    sig["groups"], sig["dstate"], dtype,
                    requested=cand.SSD_DEFAULT_CHUNK, chip=chip,
                )
                r = {"chunk": L, "how": choices()["ssd"]["how"]}
            elif kernel == "paged_decode":
                ps, bkv, how = resolve_paged_decode(
                    sig["batch"], sig["nq"], sig["nkv"], sig["head"],
                    sig["max_seq"], dtype, chip=chip,
                )
                r = {"page_size": ps, "block_kv": bkv, "how": how}
            elif kernel == "dcn_bucket":
                mb = resolve_dcn_bucket(
                    sig["grad_mb"], sig["leaves"], sig["slices"],
                    sig["wire_bytes"], requested=0, chip=chip,
                )
                r = {"bucket_mb": mb,
                     "how": choices()["dcn_bucket"]["how"]}
            else:
                c = resolve_ce_chunk(
                    sig["d_model"], sig["vocab"], dtype,
                    requested=cand.CE_DEFAULT_CHUNK, chip=chip,
                )
                r = {"chunk": c, "how": choices()["ce"]["how"]}
            resolved.append(
                {"kernel": kernel, "signature": sig, "resolved": r}
            )
        print(json.dumps(
            {"mode": "lookup_only", "chip": chip, "resolved": resolved},
            indent=1,
        ))
        return

    # write modes: load (or create) the table
    try:
        table = TuningTable.load(args.table)
    except (OSError, ValueError):
        table = TuningTable(path=args.table)

    if args.seed_cost_model:
        for kernel, sig, dtype, cands in suite_candidates(chip):
            pick = _cost_model_pick(kernel, sig, cands, dtype, chip)
            table.add(kernel, chip, dtype, sig, pick, source="cost_model")
        table.save(args.table)
        print(json.dumps({"mode": "seed_cost_model", "chip": chip,
                          "entries": len(table.doc["entries"])}))
        return

    # full sweep
    results = []
    for kernel, sig, dtype, cands in suite_candidates(chip):
        timed = []
        for config in cands:
            config = _strip(config)
            ms, err = _time_candidate(kernel, sig, dtype, config)
            status = f"{ms:.3f}ms" if ms is not None else f"ERR {err}"
            print(f"[tune] {kernel} {sig} {config}: {status}", flush=True)
            timed.append({"config": config, "ms": ms, "error": err})
        ok = [t for t in timed if t["ms"] is not None]
        if ok:
            win = min(ok, key=lambda t: t["ms"])
            table.add(kernel, chip, dtype, sig, win["config"],
                      source="measured", measured_ms=round(win["ms"], 4))
        results.append(
            {"kernel": kernel, "signature": sig, "timed": timed,
             "winner": (win["config"] if ok else None)}
        )
    table.save(args.table)
    print(json.dumps(
        {"mode": "sweep", "chip": chip, "table": args.table,
         "swept": len(results),
         "winners": sum(1 for r in results if r["winner"])},
    ))


if __name__ == "__main__":
    main()
