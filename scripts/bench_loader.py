"""Host data-pipeline throughput benchmark.

Two modes, one JSON (BENCH_LOADER.json):
- arrow: synthetic pre-tokenized arrow shards (~256MB of uint32 tokens),
  the production path (mmap'd zero-copy slicing).
- parquet: synthetic raw-text parquet shards tokenized on the fly with a
  locally-built BPE tokenizer — the reference's ParquetHandler path
  (ref:fms_fsdp/utils/dataset_utils.py:371-457). This is compute-bound on
  the tokenizer, which is where worker parallelism matters
  (ref:dataloader_utils.py:144-146 gets it from torch worker processes;
  we get it from threaded pipeline workers — tokenizers' rust encode
  releases the GIL).

Both run the full 7-layer stateful pipeline exactly as
main_training_llama assembles it and report tokens/sec pulled on the
host against per-chip device demand.

Device demand reference points (BENCH_r02): llama3_194m_4k consumes
~65k tok/s/chip, the 7B-shaped row ~30k tok/s/chip; an 8-chip host
therefore needs ~0.5M tok/s at the 194m rate. Pass/fail bar per
VERDICT item 8: host throughput >= 2x device demand per host.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa


def build_dataset(root, n_files=8, docs_per_file=2000, doc_len=1000):
    schema = pa.schema([pa.field("tokens", pa.uint32())])
    os.makedirs(os.path.join(root, "dataset_1"), exist_ok=True)
    rng = np.random.default_rng(0)
    meta = []
    for f in range(n_files):
        path = os.path.join(root, "dataset_1", f"shard_{f}.arrow")
        with pa.ipc.new_file(path, schema) as w:
            for _ in range(docs_per_file):
                doc = rng.integers(0, 32000, size=doc_len, dtype=np.uint32)
                w.write(pa.record_batch([pa.array(doc)], schema))
        meta.append(
            (f"/dataset_1/shard_{f}.arrow", docs_per_file, docs_per_file * doc_len)
        )
    os.makedirs(os.path.join(root, "meta"), exist_ok=True)
    with open(os.path.join(root, "meta", "combined_counts.csv"), "w") as f:
        f.write("dataset/filename,documents,tokens\n")
        for name, d, t in meta:
            f.write(f"{name},{d},{t}\n")
    return sum(m[2] for m in meta)


# one vocabulary for BOTH the tokenizer training corpus and the parquet
# docs: if they diverge, most words tokenize to <unk> and the benchmark
# silently measures far less BPE merge work
_WORDS = [f"w{i:05d}" for i in range(4000)]


def build_tokenizer(tok_dir, vocab_size=8192):
    """Train a small BPE tokenizer offline (no hub access) and save it in
    HF AutoTokenizer layout."""
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers

    os.makedirs(tok_dir, exist_ok=True)
    tok = Tokenizer(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size, special_tokens=["<unk>", "<s>", "</s>"]
    )
    rng = np.random.default_rng(7)
    corpus = (
        " ".join(rng.choice(_WORDS, size=64).tolist()) for _ in range(4000)
    )
    tok.train_from_iterator(corpus, trainer)
    tok.save(os.path.join(tok_dir, "tokenizer.json"))
    with open(os.path.join(tok_dir, "tokenizer_config.json"), "w") as f:
        json.dump(
            {
                "tokenizer_class": "PreTrainedTokenizerFast",
                "bos_token": "<s>",
                "eos_token": "</s>",
                "unk_token": "<unk>",
            },
            f,
        )
    return tok_dir


def build_parquet_dataset(root, n_files=4, docs_per_file=400, words_per_doc=700):
    """Raw-text parquet shards; docs are random word sequences so the BPE
    tokenizer does real merge work per doc."""
    import pyarrow.parquet as pq

    os.makedirs(os.path.join(root, "dataset_1"), exist_ok=True)
    rng = np.random.default_rng(1)
    words = _WORDS
    meta = []
    for f in range(n_files):
        docs = [
            " ".join(rng.choice(words, size=words_per_doc).tolist())
            for _ in range(docs_per_file)
        ]
        path = os.path.join(root, "dataset_1", f"shard_{f}.parquet")
        pq.write_table(pa.table({"text": docs}), path)
        meta.append((f"/dataset_1/shard_{f}.parquet", docs_per_file))
    os.makedirs(os.path.join(root, "meta"), exist_ok=True)
    with open(os.path.join(root, "meta", "combined_counts.csv"), "w") as f:
        f.write("dataset/filename,documents,tokens\n")
        for name, d in meta:
            f.write(f"{name},{d},{d * words_per_doc}\n")


def build_mixed_dataset(root, n_files=2, docs_per_file=1000, doc_len=1000):
    """Three weighted arrow corpora for the mixed-mode row (same token
    format as build_dataset, split across corpus directories)."""
    schema = pa.schema([pa.field("tokens", pa.uint32())])
    rng = np.random.default_rng(3)
    meta = []
    for name in ("dataset_1", "dataset_2", "dataset_3"):
        os.makedirs(os.path.join(root, name), exist_ok=True)
        for f in range(n_files):
            path = os.path.join(root, name, f"shard_{f}.arrow")
            with pa.ipc.new_file(path, schema) as w:
                for _ in range(docs_per_file):
                    doc = rng.integers(0, 32000, size=doc_len, dtype=np.uint32)
                    w.write(pa.record_batch([pa.array(doc)], schema))
            meta.append(
                (f"/{name}/shard_{f}.arrow", docs_per_file,
                 docs_per_file * doc_len)
            )
    os.makedirs(os.path.join(root, "meta"), exist_ok=True)
    with open(os.path.join(root, "meta", "combined_counts.csv"), "w") as f:
        f.write("dataset/filename,documents,tokens\n")
        for name, d, t in meta:
            f.write(f"{name},{d},{t}\n")
    return sum(m[2] for m in meta)


def run_mode(mode, num_workers, n_batches, worker_mode="thread"):
    from fms_fsdp_tpu.config import TrainConfig
    from fms_fsdp_tpu.data import get_data_loader

    mix_extras = {}
    if mode == "arrow":
        root = "/tmp/bench_loader_data"
        if not os.path.exists(os.path.join(root, "meta")):
            total = build_dataset(root)
            print(f"# built {total/1e6:.0f}M tokens", file=sys.stderr)
        extra = dict(file_type="arrow", vocab_size=32000)
    elif mode == "mixed":
        root = "/tmp/bench_loader_mixed"
        if not os.path.exists(os.path.join(root, "meta")):
            total = build_mixed_dataset(root)
            print(f"# built {total/1e6:.0f}M mixed tokens", file=sys.stderr)
        extra = dict(
            file_type="arrow",
            vocab_size=32000,
            datasets="dataset_1,dataset_2,dataset_3",
            weights="2,1,1",
        )
    else:
        root = "/tmp/bench_loader_parquet"
        tok_dir = "/tmp/bench_loader_tok"
        if not os.path.exists(os.path.join(root, "meta")):
            build_parquet_dataset(root)
            print("# built parquet text shards", file=sys.stderr)
        if not os.path.exists(os.path.join(tok_dir, "tokenizer.json")):
            build_tokenizer(tok_dir)
            print("# trained local BPE tokenizer", file=sys.stderr)
        extra = dict(
            file_type="hf_parquet",
            tokenizer_path=tok_dir,
            col_name="text",
            vocab_size=8192,
        )

    cfg = TrainConfig(
        data_path=root,
        datasets=extra.pop("datasets", "dataset_1"),
        weights=extra.pop("weights", "1"),
        seq_length=4096,
        batch_size=4,
        bos_token=None,
        eos_token=0,
        logical_shards=64,
        num_workers=num_workers,
        worker_mode=worker_mode,
        ckpt_load_path=os.path.join(root, "_no_ckpt"),
        resuming_dataset=False,
        **extra,
    )
    loader = get_data_loader(cfg, rank=0, world_size=1)
    it = iter(loader)

    for _ in range(10):  # warmup
        next(it)
    t0 = time.perf_counter()
    for _ in range(n_batches):
        next(it)
    dt = time.perf_counter() - t0
    tok_s = n_batches * cfg.batch_size * cfg.seq_length / dt
    if mode == "mixed":
        # per-corpus goodput: realized token shares from the live
        # mixing layer x pulled throughput
        from fms_fsdp_tpu.data import loader_mix_stats

        mix = loader_mix_stats(loader) or {"tokens": {}, "quarantined": []}
        total = sum(mix["tokens"].values()) or 1
        mix_extras = {
            "per_corpus_tokens_per_sec": {
                n: round(tok_s * t / total) for n, t in mix["tokens"].items()
            },
            "realized_shares": {
                n: round(t / total, 3) for n, t in mix["tokens"].items()
            },
        }
    if hasattr(loader, "shutdown"):
        loader.shutdown()
    return tok_s, mix_extras


def main():
    demand_194m = 65_000 * 8  # tok/s, 8-chip host at the 194m rate
    demand_7b = 30_000 * 8

    rows = []
    nw = int(os.environ.get("BENCH_WORKERS", "8"))
    plans = [
        ("arrow", 1, 200, "thread"),
        # weighted 3-corpus mixing over the same arrow path: the mix
        # overhead vs the flat corpus (SamplingDataset bookkeeping +
        # per-corpus reader churn) and per-corpus goodput become
        # regression-measurable
        ("mixed", 1, 200, "thread"),
        ("parquet", 1, 40, "thread"),
        # worker scaling, both parallelism models: threads lean on the
        # tokenizer's GIL-releasing rust encode; processes are the
        # reference's torch-DataLoader model, immune to GIL contention
        # in the pure-Python pipeline stages (needs a multi-CPU host to
        # show scaling — 1-CPU hosts measure contention, NOTES.md r3)
        ("parquet", nw, 40, "thread"),
        ("parquet", nw, 40, "process"),
    ]
    flat_arrow_tok_s = None
    for mode, workers, n_batches, wmode in plans:
        tok_s, mix_extras = run_mode(mode, workers, n_batches, wmode)
        row = {
            "pipeline": mode,
            "num_workers": workers,
            "worker_mode": wmode,
            "tokens_per_sec": round(tok_s),
            "vs_8chip_194m_demand": round(tok_s / demand_194m, 2),
            "vs_8chip_7b_demand": round(tok_s / demand_7b, 2),
        }
        if mode == "arrow":
            flat_arrow_tok_s = tok_s
        if mode == "mixed":
            row.update(mix_extras)
            if flat_arrow_tok_s:
                # < 1.0 = the mix costs throughput vs the flat corpus
                row["mix_vs_flat_corpus"] = round(tok_s / flat_arrow_tok_s, 2)
        rows.append(row)
        print(json.dumps(rows[-1]), file=sys.stderr)

    result = {
        "metric": "host dataloader throughput (1 process)",
        "host_cpus": os.cpu_count(),
        "rows": rows,
        # headline keeps the arrow production-path number
        "tokens_per_sec": rows[0]["tokens_per_sec"],
        "num_workers": rows[0]["num_workers"],
        "vs_8chip_194m_demand": rows[0]["vs_8chip_194m_demand"],
        "vs_8chip_7b_demand": rows[0]["vs_8chip_7b_demand"],
    }
    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_LOADER.json",
    )
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
