"""Host data-pipeline throughput benchmark.

Builds a synthetic arrow dataset (~256MB of uint32 tokens), runs the full
7-layer stateful pipeline exactly as main_training_llama assembles it, and
reports tokens/sec pulled on the host against per-chip device demand.

Device demand reference points (BENCH_r02): llama3_194m_4k consumes
~65k tok/s/chip, the 7B-shaped row ~30k tok/s/chip; an 8-chip host
therefore needs ~0.5M tok/s at the 194m rate. Pass/fail bar per
VERDICT item 8: host throughput >= 2x device demand per host.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa


def build_dataset(root, n_files=8, docs_per_file=2000, doc_len=1000):
    schema = pa.schema([pa.field("tokens", pa.uint32())])
    os.makedirs(os.path.join(root, "dataset_1"), exist_ok=True)
    rng = np.random.default_rng(0)
    meta = []
    for f in range(n_files):
        path = os.path.join(root, "dataset_1", f"shard_{f}.arrow")
        with pa.ipc.new_file(path, schema) as w:
            for _ in range(docs_per_file):
                doc = rng.integers(0, 32000, size=doc_len, dtype=np.uint32)
                w.write(pa.record_batch([pa.array(doc)], schema))
        meta.append(
            (f"/dataset_1/shard_{f}.arrow", docs_per_file, docs_per_file * doc_len)
        )
    os.makedirs(os.path.join(root, "meta"), exist_ok=True)
    with open(os.path.join(root, "meta", "combined_counts.csv"), "w") as f:
        f.write("dataset/filename,documents,tokens\n")
        for name, d, t in meta:
            f.write(f"{name},{d},{t}\n")
    return sum(m[2] for m in meta)


def main():
    from fms_fsdp_tpu.config import TrainConfig
    from fms_fsdp_tpu.data import get_data_loader

    root = "/tmp/bench_loader_data"
    if not os.path.exists(os.path.join(root, "meta")):
        total = build_dataset(root)
        print(f"# built {total/1e6:.0f}M tokens", file=sys.stderr)

    cfg = TrainConfig(
        data_path=root,
        datasets="dataset_1",
        weights="1",
        seq_length=4096,
        batch_size=4,
        vocab_size=32000,
        bos_token=None,
        eos_token=0,
        logical_shards=64,
        num_workers=int(os.environ.get("BENCH_WORKERS", "1")),
        ckpt_load_path=os.path.join(root, "_no_ckpt"),
        resuming_dataset=False,
    )
    loader = get_data_loader(cfg, rank=0, world_size=1)
    it = iter(loader)

    # warmup
    for _ in range(10):
        next(it)

    n_batches = 200
    t0 = time.perf_counter()
    for _ in range(n_batches):
        next(it)
    dt = time.perf_counter() - t0
    tok_s = n_batches * cfg.batch_size * cfg.seq_length / dt

    demand_194m = 65_000 * 8  # tok/s, 8-chip host at the 194m rate
    demand_7b = 30_000 * 8
    result = {
        "metric": "host dataloader throughput (arrow pipeline, 1 process)",
        "tokens_per_sec": round(tok_s),
        "num_workers": cfg.num_workers,
        "vs_8chip_194m_demand": round(tok_s / demand_194m, 2),
        "vs_8chip_7b_demand": round(tok_s / demand_7b, 2),
    }
    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_LOADER.json",
    )
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
