"""Generate a learnable REAL arrow corpus on disk for the evidence
eval leg (chip_evidence.sh step 4) — the same generator the e2e tests
use (fms_fsdp_tpu/data/synth.py), scaled up, so EVAL.json exercises
arrow streaming -> training -> falling perplexity through the
production entry points instead of the in-memory dummy stream.

Usage:
    python scripts/gen_arrow_data.py /tmp/eval_data \
        --n_shards=4 --docs_per_shard=2500 --doc_len=1000 --vocab=4096
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from fms_fsdp_tpu.data.synth import build_arrow_corpus


def main(argv):
    assert argv and not argv[0].startswith("--"), (
        "first arg must be the output root directory"
    )
    root, kwargs = argv[0], {}
    for a in argv[1:]:
        assert a.startswith("--") and "=" in a, f"bad arg {a!r}"
        k, v = a[2:].split("=", 1)
        kwargs[k] = float(v) if k == "noise" else int(v)
    out = build_arrow_corpus(root, **kwargs)
    n = kwargs.get("n_shards", 3)
    d = kwargs.get("docs_per_shard", 60)
    ln = kwargs.get("doc_len", 90)
    print(f"wrote {n} shards x {d} docs x {ln} tokens under {out}")


if __name__ == "__main__":
    main(sys.argv[1:])
