"""Component-attribution profile for the Mamba family's MFU (VERDICT r3
weak #4: mamba bf16 measured 0.52 MFU with no evidence of where it goes).

Times each component of the mamba_9.8b Mamba2 layer at the bench-row
shapes (B=2, S=4096, d_model 4096, d_inner 8192, 128 heads of 64,
d_state 128, MLP 14336) individually — fwd and fwd+bwd — alongside the
full train-step time from the same protocol bench.py uses, then prints
each component's share of the step and its achieved TF/s vs the chip
peak. The gap rows (share large + TF/s low) are where the MFU goes.

Writes PROFILE_MAMBA.json at the repo root. Chip-gated: run via
scripts/chip_evidence.sh or standalone on a live TPU.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

if os.environ.get("BENCH_FORCE_CPU"):
    # sitecustomize pins the axon TPU platform before env vars are read;
    # only jax.config reliably redirects to CPU (NOTES.md r3)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp

from bench_kernels import time_fn
from fms_fsdp_tpu.ops.ssd import causal_conv1d, ssd_scan

# mamba_9.8b shapes (ref:config_utils.py:162-185): d_model 4096,
# d_inner 8192 -> 128 heads x 64, d_state 128, ngroups 1, conv width 4,
# MLP 14336, vocab cut to 32k exactly as the bench row does
B, S, D = 2, 4096, 4096
H, P, G, N = 128, 64, 1, 128
D_INNER = H * P
CONV_C, CONV_W = D_INNER + 2 * G * N, 4
IN_PROJ = 2 * D_INNER + 2 * G * N + H
MLP_HID = 14336
VOCAB = 32000


def _gemm_flops(*dims):
    out = 2
    for d in dims:
        out *= d
    return out


def main():
    ks = jax.random.split(jax.random.PRNGKey(0), 8)
    x = jax.random.normal(ks[0], (B, S, D), jnp.bfloat16)
    w_in = jax.random.normal(ks[1], (D, IN_PROJ), jnp.bfloat16) * 0.02
    w_out = jax.random.normal(ks[2], (D_INNER, D), jnp.bfloat16) * 0.02
    w1 = jax.random.normal(ks[3], (D, MLP_HID), jnp.bfloat16) * 0.02
    w2 = jax.random.normal(ks[4], (MLP_HID, D), jnp.bfloat16) * 0.02
    w_head = jax.random.normal(ks[5], (D, VOCAB), jnp.bfloat16) * 0.02

    xs = jax.random.normal(ks[6], (B, S, H, P), jnp.bfloat16)
    dt = jax.nn.softplus(jax.random.normal(ks[7], (B, S, H), jnp.float32))
    A = -jnp.exp(jax.random.normal(ks[0], (H,), jnp.float32))
    Bm = jax.random.normal(ks[1], (B, S, G, N), jnp.bfloat16)
    Cm = jax.random.normal(ks[2], (B, S, G, N), jnp.bfloat16)
    Dm = jnp.ones((H,), jnp.float32)
    cx = jax.random.normal(ks[3], (B, S, CONV_C), jnp.bfloat16)
    cw = jax.random.normal(ks[4], (CONV_C, CONV_W), jnp.float32) * 0.1
    cb = jnp.zeros((CONV_C,), jnp.float32)

    tok = B * S
    components = []

    def add(name, fn, args, flops_fwd):
        print(f"# profiling {name}", file=sys.stderr)

        def loss(*a):
            return jnp.sum(fn(*a).astype(jnp.float32))

        t_f = time_fn(jax.jit(fn), *args, iters=20)
        # differentiate w.r.t. EVERY operand (activations AND weights):
        # a training step computes both dx and dw, so the timed backward
        # must too or the 3x amortization overstates the rate (ADVICE r4)
        t_g = time_fn(
            jax.jit(jax.grad(loss, argnums=tuple(range(len(args))))),
            *args,
            iters=10,
        )
        components.append(
            {
                "component": name,
                "fwd_ms": round(t_f * 1e3, 3),
                "fwd_bwd_ms": round(t_g * 1e3, 3),
                "fwd_tflops_per_s": round(flops_fwd / t_f / 1e12, 2),
                # bwd of a GEMM chain (dx + dw) is ~2x fwd FLOPs;
                # grad-of-loss runs fwd+bwd so the amortized rate uses 3x
                "fwd_bwd_tflops_per_s": round(3 * flops_fwd / t_g / 1e12, 2),
            }
        )

    add(
        "in_proj GEMM",
        lambda x, w: x @ w,
        (x, w_in),
        _gemm_flops(tok, D, IN_PROJ),
    )
    add(
        "conv1d (shifted-FMA)",
        lambda c, w, b: causal_conv1d(c, w, b),
        (cx, cw, cb),
        2 * tok * CONV_C * CONV_W,
    )
    add(
        "ssd_scan (auto kernel)",
        lambda xs, dt, A, Bm, Cm, Dm: ssd_scan(xs, dt, A, Bm, Cm, Dm),
        (xs, dt, A, Bm, Cm, Dm),
        # dominant SSD terms: intra-chunk (S*chunk per head) + state IO;
        # count the matmul terms only (B*S*chunk*(N+P) per head family)
        2 * tok * H * (N * P * 2 + N * 256),
    )
    add(
        "out_proj GEMM",
        lambda h, w: h.reshape(B, S, D_INNER) @ w,
        (xs, w_out),
        _gemm_flops(tok, D_INNER, D),
    )
    add(
        "MLP (SwiGLU 2-GEMM core)",
        lambda x, w1, w2: jax.nn.silu(x @ w1) @ w2,
        (x, w1, w2),
        _gemm_flops(tok, D, MLP_HID) * 2,
    )
    add(
        "lm_head GEMM",
        lambda x, w: x @ w,
        (x, w_head),
        _gemm_flops(tok, D, VOCAB),
    )

    # full train step at the bench-row config, same protocol as bench.py
    print("# profiling full step (bench row protocol)", file=sys.stderr)
    step_row = None
    try:
        from bench import run_config

        step_row = run_config(
            "mamba_9.8b",
            batch_size=B,
            sel_ac=0.5,
            model_overrides={
                "n_layer": 2,
                "attn_layer_idx": (),
                "vocab_size": VOCAB,
            },
        )
    except Exception as e:  # noqa: BLE001
        step_row = {"error": f"{type(e).__name__}: {e}"[:200]}

    out = {
        "shapes": {"B": B, "S": S, "d_model": D, "d_inner": D_INNER,
                   "heads": H, "d_state": N, "mlp": MLP_HID, "vocab": VOCAB},
        "components": components,
        "full_step_L2": step_row,
    }
    if step_row and "step_time_s" in (step_row or {}):
        step_ms = step_row["step_time_s"] * 1e3
        for c in out["components"]:
            # 2 layers in the step; per-layer components count twice
            mult = 1 if c["component"] == "lm_head GEMM" else 2
            c["share_of_step_pct"] = round(
                100 * mult * c["fwd_bwd_ms"] / step_ms, 1
            )
    with open("PROFILE_MAMBA.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
