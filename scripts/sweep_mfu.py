"""Sweep single-chip bench configs; print MFU per config.

Thin CLI over bench.run_config (same methodology as the headline bench).

Usage: python scripts/sweep_mfu.py <bs> <selAC> <fused> <chunk> [variant]
selAC: 0 for off, else fraction (e.g. 0.5); fused: 0/1.
variant may carry int overrides: "llama2_7b:nlayers=3".
Env: SWEEP_QUANT=none|int8|int8_dgrad.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import run_config  # noqa: E402


def main():
    bs = int(sys.argv[1])
    sel = float(sys.argv[2])
    fused = bool(int(sys.argv[3]))
    chunk = int(sys.argv[4])
    variant = sys.argv[5] if len(sys.argv) > 5 else "llama3_194m_4k"
    overrides = {}
    if ":" in variant:
        variant, ov = variant.split(":", 1)
        for kv in ov.split(","):
            key, val = kv.split("=")
            overrides[key] = int(val)
    quant = os.environ.get("SWEEP_QUANT", "none")

    r = run_config(
        variant,
        batch_size=bs,
        sel_ac=sel,
        quant=quant,
        model_overrides=overrides or None,
        fused_loss=fused,
        loss_chunk=chunk or 4096,
    )
    print(
        f"RESULT bs={bs} selAC={sel} fused={fused} chunk={chunk} quant={quant}: "
        f"MFU={r['mfu']:.4f} HFU={r['hfu']:.4f} "
        f"tok/s/chip={r['tokens_per_sec_per_chip']} step={r['step_time_s']*1e3:.1f}ms"
    )


if __name__ == "__main__":
    main()
