"""Chaos-soak harness: seeded fault schedules under the self-healing
supervisor, proved bit-identical to a fault-free run.

The soak drives the production llama stack (tests/_elastic_child.py — the
same child the elastic-resume gloo e2e uses) on a 2-slice x 1-host gloo
CPU world through a SEEDED schedule of kill-class faults sampled from the
``resilience/faults.py`` registry, one fault per incarnation, all
restarts performed by ``resilience/supervisor.py`` with no operator in
the loop. It then runs the identical config fault-free and asserts:

- **same step count**: both runs reach ``--budget-steps``;
- **bit-identical end state**: the final committed checkpoint's
  topology-independent STATE_HASH matches the fault-free run's;
- **zero replayed documents**: the *effective* trainer-consumed stream —
  each incarnation's per-rank walk truncated to its committed prefix
  (work past the last commit is redone by design; the ``B`` batch
  separators in the walk files mark step boundaries) — contains every
  document marker at most once, and equals the fault-free stream as a
  multiset;
- **downtime charged to goodput**: the final metrics.jsonl record
  carries schema-v6 ``restarts``/``restart_downtime_s`` from the restart
  ledger (pre-charged into the incarnation's ``goodput_overall``), and
  the faulted run's RUN-LEVEL goodput — committed steps per wall second
  from first launch to completion, downtime included — is strictly
  below the fault-free run's. (Per-incarnation window goodput counts
  every incarnation's recompile as compute, so it cannot fairly compare
  a restarted run against a straight one at CPU-test scale.)

The soak drives a WEIGHTED 3-CORPUS mix (datasets 2:1:1,
``min_live_corpora=2`` — the data-layer twin of the slice fault domain):
per-corpus markers live in disjoint ranges so the replay and share
checks hold corpus by corpus, and the realized per-corpus document
shares of the effective stream must sit within tolerance of the
configured weights.

Fault pool (kill-class — the run dies and the supervisor relaunches it
through elastic resume, so every redone step is bit-identical):

- ``slice_kill``          whole-slice loss (always scheduled — the
                          acceptance criterion's fault domain kill)
- ``corpus_kill``         whole-corpus loss (always scheduled): every
                          corpus matching the spec dies at its next
                          document boundary — the first loss degrades
                          the mix (quarantine + weights renormalized
                          over survivors, asserted from the logs), the
                          second breaches ``min_live_corpora`` and exits
                          via the classified ``corpus_loss`` registry
                          code before anything commits; the relaunch
                          finds the corpus healed (the fault arms per
                          incarnation) so end-state bit-identity holds
- ``ckpt_shard_corrupt``  (always scheduled, paired with a slice_kill
                          two steps later) silent bit-rot: bytes flipped
                          mid-shard in a COMMITTED checkpoint, size
                          unchanged — a size-only check restores it
                          blind. The next resume's full-content verify
                          (manifest v2) must detect it, quarantine the
                          step dir with one actionable line naming the
                          bad shard, and fall back to the previous
                          commit — which replays bit-identically, so
                          end-state identity still holds
- ``sdc_grad_flip``       (always scheduled) silent data corruption:
                          one process's gradient scaled on a chosen
                          step, diverging its slice's replicated state.
                          Placed at commit+1 so the report-cadence
                          divergence compare (divergence_check_interval
                          = report cadence here) catches it at commit+2
                          — BEFORE the poisoned update can ever commit
                          — and exits classified ``state_divergence``;
                          the supervisor relaunches under the
                          verified-resume rule and the redone steps are
                          bit-identical
- ``ckpt_precommit_kill`` death between snapshot and commit marker
- ``dcn_reduce_stall``    a parked rank; the step watchdog converts the
                          hang into a classified exit
- ``loader_worker``       (action=exit) loader death: in the workerless
                          zero-skew mode the trainer IS the worker, so
                          the injected kill surfaces as the classified
                          loader_death exit

``nan_loss`` bursts are deliberately NOT in the identity pool: a
non-finite burst makes the guard *skip* updates the fault-free run
applies, so the end states legitimately diverge — recovery from them is
covered by tests/test_resilience.py instead. The supervisor restarts
with ``on_slice_loss="same"`` (the lost slice "comes back"): end-state
bit-identity versus a fixed-topology reference requires every
incarnation to train on the same topology. The shrink policy
(``num_slices - 1``) is exercised by tests/test_supervisor.py, where
identity is asserted at the restore boundary exactly as the elastic
e2e does.

CI smoke: ``python scripts/chaos_soak.py --seed 0 --budget-steps 32``
(docs/resilience.md "Self-healing supervisor").
"""

import argparse
import json
import os
import random
import shutil
import socket
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
CHILD = os.path.join(REPO, "tests", "_elastic_child.py")

SEQ_LEN = 64
REPORT_INTERVAL = 2
SLICE_TIMEOUT_S = 8
STEP_TIMEOUT_S = 45
STALL_SECONDS = 900  # >> STEP_TIMEOUT_S: the watchdog ends it


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


MARKER_BASE = 1024
CORPORA = ["dataset_1", "dataset_2", "dataset_3"]
MIX_WEIGHTS = "2,1,1"
DOCS_PER_CORPUS = 300
MIN_LIVE_CORPORA = 2


def _marked_corpus(root, docs_per_corpus=DOCS_PER_CORPUS, doc_len=80):
    """Weighted-mix arrow corpora (same construction as
    tests/test_elastic.py::_marked_mixed_corpus): corpus c's documents
    open with unique markers in the disjoint range
    [MARKER_BASE + c*docs_per_corpus, MARKER_BASE + (c+1)*docs_per_corpus),
    so a marker appearing twice in the effective consumed stream is a
    replayed document — checkable corpus by corpus."""
    import pyarrow as pa

    root = str(root)
    assert MARKER_BASE + len(CORPORA) * docs_per_corpus <= 2048
    schema = pa.schema([pa.field("tokens", pa.uint32())])
    rows = []
    for c, name in enumerate(CORPORA):
        os.makedirs(os.path.join(root, name), exist_ok=True)
        base = MARKER_BASE + c * docs_per_corpus
        d = 0
        for s in range(2):
            path = os.path.join(root, name, f"shard_{s}.arrow")
            with pa.ipc.new_file(path, schema) as w:
                for _ in range(docs_per_corpus // 2):
                    body = [
                        ((base + d) * 31 + j) % 997 + 1
                        for j in range(doc_len - 1)
                    ]
                    w.write(pa.record_batch([[base + d] + body], schema))
                    d += 1
            rows.append(
                (f"/{name}/shard_{s}.arrow", docs_per_corpus // 2,
                 (docs_per_corpus // 2) * doc_len)
            )
    os.makedirs(os.path.join(root, "meta"), exist_ok=True)
    with open(os.path.join(root, "meta", "combined_counts.csv"), "w") as f:
        f.write("dataset/filename,documents,tokens\n")
        for name, docs, toks in rows:
            f.write(f"{name},{docs},{toks}\n")
    return root


def _corpus_of(marker):
    return (marker - MARKER_BASE) // DOCS_PER_CORPUS


def sample_schedule(seed: int, budget: int, ckpt_interval: int, n_sites: int):
    """The seeded fault schedule: one fault spec per incarnation.
    ``slice_kill`` is always first (the world is still 2-slice and the
    whole-domain loss is the acceptance criterion), ``corpus_kill``
    second (the data-layer fault domain), ``ckpt_shard_corrupt`` and
    ``sdc_grad_flip`` always join (the silent-corruption classes the
    state-integrity layer exists for), and the rest are drawn from the
    registry pool — all at ascending steps so each fault fires after the
    previous incarnation's resume point."""
    rng = random.Random(seed)
    pool = ["ckpt_precommit_kill", "dcn_reduce_stall", "loader_worker"]
    rng.shuffle(pool)
    always = ["slice_kill", "corpus_kill", "ckpt_shard_corrupt",
              "sdc_grad_flip"]
    sites = always + pool[: max(0, n_sites - len(always))]
    # ascending fire positions, >= one commit apart so every resume
    # point (a committed multiple of ckpt_interval) precedes the next
    # fault; jitter keeps the schedule seed-dependent. (corpus_kill
    # ignores its position: it fires at its incarnation's first
    # document boundaries, cascades to the min_live_corpora breach and
    # exits corpus_loss before anything commits.)
    positions, pos = [], ckpt_interval + 2
    for _ in sites:
        positions.append(min(pos + rng.randrange(0, 2), budget - 2))
        pos = positions[-1] + ckpt_interval + 2
    # shared headroom cap for the commit-aligned corruption sites,
    # rounded DOWN to the commit cadence: they only fire at save steps,
    # so an unaligned cap (budget not a multiple of the interval) would
    # name a step that never saves and the fault would never fire. Two
    # intervals of headroom: the poisoned/poison-free redo needs a
    # commit after the fire step, before the budget.
    corrupt_cap = (
        (budget - 2 * ckpt_interval) // ckpt_interval
    ) * ckpt_interval
    schedule = []
    for site, p in zip(sites, positions):
        if site == "slice_kill":
            spec = f"slice_kill:slice=1:step={p}"
        elif site == "corpus_kill":
            # substring filter: every corpus matches, so the cascade
            # (degrade -> renormalize -> floor breach) is deterministic
            spec = "corpus_kill:corpus=dataset_"
        elif site == "ckpt_shard_corrupt":
            # flip bytes in the commit at the next cadence point, then
            # kill a slice two steps later: the relaunch's resume finds
            # the poisoned checkpoint newest, must detect + quarantine
            # it, and fall back one commit (bit-identical redo)
            at = min(
                ((p + ckpt_interval - 1) // ckpt_interval) * ckpt_interval,
                corrupt_cap,
            )
            spec = (
                f"ckpt_shard_corrupt:step={at};"
                f"slice_kill:slice=1:step={at + 2}"
            )
        elif site == "sdc_grad_flip":
            # perturb proc 1's gradient at commit+1: the divergence
            # compare at the next report boundary (commit+2) fires
            # BEFORE the next commit (commit+interval), so the poisoned
            # update never lands in a checkpoint and bit-identity holds
            base = min(
                ((p + ckpt_interval - 1) // ckpt_interval) * ckpt_interval,
                # base must be a commit step for the commit+1 placement
                # to hold — same shared cap as ckpt_shard_corrupt
                corrupt_cap,
            )
            spec = f"sdc_grad_flip:step={base + 1}:proc=1"
        elif site == "ckpt_precommit_kill":
            # must land on the commit cadence to fire
            at = min(((p + ckpt_interval - 1) // ckpt_interval)
                     * ckpt_interval, budget - ckpt_interval)
            spec = f"ckpt_precommit_kill:step={at}"
        elif site == "dcn_reduce_stall":
            spec = f"dcn_reduce_stall:slice=1:step={p}:seconds={STALL_SECONDS}"
        else:  # loader_worker: produced-batch clock restarts per
            # incarnation, so a small count fires early in its attempt
            spec = "loader_worker:worker=0:batch=3:action=exit"
        schedule.append((site, spec))
    return schedule


def child_specs(ckpt, data, walk, obs_dir, hb_dir, phase, num_steps,
                ckpt_interval, faults=""):
    """Per-rank child specs for one 2-proc (2 slices x 1 host, 4 virtual
    devices each) incarnation, in the supervisor's spec format."""
    port = _free_port()
    overrides = [
        "num_slices=2",
        f"slice_heartbeat_dir={hb_dir}",
        f"slice_timeout_s={SLICE_TIMEOUT_S}",
        f"step_timeout_s={STEP_TIMEOUT_S}",
        # zero-skew data path (see module docstring): num_workers=1 is
        # the loader's workerless inline mode and feed_prefetch=0 makes
        # device staging synchronous, so every checkpoint's loader state
        # equals the consumed position exactly — restarts replay nothing
        # AND skip nothing, which is what makes end-state bit-identity
        # vs the fault-free run a provable property
        "feed_prefetch=0",
        f"obs_dir={obs_dir}",
        # the weighted 3-corpus mix (module docstring): disjoint marker
        # ranges per corpus; min_live_corpora=2 makes the second corpus
        # loss of a corpus_kill cascade a classified corpus_loss exit
        f"datasets={','.join(CORPORA)}",
        f"weights={MIX_WEIGHTS}",
        f"min_live_corpora={MIN_LIVE_CORPORA}",
        # state-integrity layer armed (docs/checkpointing.md "State
        # integrity"): cross-replica fingerprint compare at every
        # report boundary (catches sdc_grad_flip before the next
        # commit) and the background scrubber on the commit cadence
        # (re-verifies committed checkpoints; verdicts cached by
        # manifest digest)
        "divergence_check_interval=2",
        "scrub_interval_steps=4",
    ]
    specs = []
    for pid in range(2):
        specs.append(
            {
                "argv": [
                    sys.executable, "-u", CHILD, ckpt, data, walk, phase,
                    str(num_steps), str(ckpt_interval), faults, *overrides,
                ],
                "env": {
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                    "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                    "NUM_PROCESSES": "2",
                    "PROCESS_ID": str(pid),
                },
                "cwd": REPO,
            }
        )
    return specs


def _grab(path, key, default=None):
    try:
        with open(path) as f:
            for line in f:
                if line.startswith(key + " "):
                    return line.split(" ", 1)[1].strip()
    except OSError:
        pass
    return default


def _walk_batches(walk_dir, phase, rank):
    """The per-rank walk as a list of batches (marker lists), split on
    the ``B`` separator lines."""
    path = os.path.join(walk_dir, f"walk_{phase}_rank{rank}.txt")
    batches, cur = [], None
    try:
        with open(path) as f:
            for tok in f.read().split():
                if tok == "B":
                    cur = []
                    batches.append(cur)
                elif cur is not None:
                    cur.append(int(tok))
    except OSError:
        pass
    return batches


def effective_markers(walk_dir, phases_with_windows):
    """Reconstruct the effective (committed) consumed stream: for each
    (phase, start_step, committed_end) take the first committed_end -
    start_step batches of every rank's walk — work past the last commit
    was redone by the next incarnation and is excluded by design."""
    markers = []
    for phase, start, end in phases_with_windows:
        take = max(0, end - start)
        for rank in range(16):  # ranks present on disk only
            batches = _walk_batches(walk_dir, phase, rank)
            if not batches and rank > 0:
                break
            for b in batches[:take]:
                markers.extend(b)
    return markers


def _fired_faults(entries):
    """How many ledger entries ended in an INJECTED fault: at least one
    child exited with a registry code (the os._exit / classified-exit
    paths), which environment failures (SIGABRT, generic tracebacks)
    never produce."""
    registry = {2, 3, 4, 5, 7, 8, 9}
    return sum(
        1
        for e in entries
        if any(code in registry for code in (e.get("exit_codes") or []))
    )


def last_metrics_record(obs_dir):
    try:
        with open(os.path.join(obs_dir, "metrics.jsonl")) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        return json.loads(lines[-1]) if lines else None
    except (OSError, ValueError):
        return None


def run_soak(args, workdir):
    from fms_fsdp_tpu.resilience.supervisor import RunSupervisor

    data = _marked_corpus(os.path.join(workdir, "data"))
    budget, interval = args.budget_steps, args.ckpt_interval
    schedule = sample_schedule(args.seed, budget, interval, args.sites)
    print(f"chaos schedule (seed {args.seed}):")
    for site, spec in schedule:
        print(f"  {site}: {spec}")

    results = {}
    for kind in ("faulted", "clean"):
        root = os.path.join(workdir, kind)
        ckpt = os.path.join(root, "ckpt")
        walk = os.path.join(root, "walk")
        obs = os.path.join(root, "obs")
        hb = os.path.join(root, "slice_hb")
        logs = os.path.join(root, "logs")
        os.makedirs(walk, exist_ok=True)
        plan = schedule if kind == "faulted" else []

        def build(ctx, _plan=plan, _dirs=(ckpt, walk, obs, hb)):
            c, w, o, h = _dirs
            k = ctx["attempt"]
            # arm fault i only after i faults have FIRED: an injected
            # kill always leaves at least one child on a registry exit
            # code (os._exit paths: 2/3/5/7 — or 4 through the wrapper),
            # while an environment failure the supervisor healed (gloo
            # startup race, SIGABRT) never does. Without this, a healed
            # env flake would silently consume a schedule slot.
            fired = _fired_faults(ctx["ledger"]["entries"])
            faults = _plan[fired][1] if fired < len(_plan) else ""
            return child_specs(
                c, data, w, o, h, f"a{k}", budget, interval, faults
            )

        sup = RunSupervisor(
            build,
            ledger_path=os.path.join(root, "restart_ledger.json"),
            heartbeat_path=os.path.join(obs, "heartbeat.json"),
            target_step=budget,
            # headroom beyond the schedule: the supervisor also heals
            # ENVIRONMENT failures (e.g. the gloo startup race CPU CI
            # machines occasionally hit) — that is its job, and the
            # assertions below are written restart-count-tolerant
            max_restarts=len(plan) + 5,
            restart_backoff_s=args.backoff_s,
            crash_loop_threshold=5,
            on_slice_loss="same",  # see module docstring: identity
            num_slices=2,
            reset_paths=(hb,),
            log_dir=logs,
        )
        t0 = time.time()
        res = sup.run()
        print(
            f"{kind}: supervisor {res.status} after {res.restarts} "
            f"restart(s) in {time.time() - t0:.0f}s"
        )
        assert res.status == "completed", (
            f"{kind} soak did not complete: {res.status}\n{res.post_mortem}"
        )
        if kind == "faulted":
            fired = _fired_faults(res.ledger["entries"])
            assert fired >= len(plan), (
                f"only {fired} fault(s) fired of {len(plan)} scheduled; "
                f"ledger: {res.ledger}"
            )
            # corpus_kill contract: the first corpus loss DEGRADED the
            # mix (quarantine + weights renormalized over survivors —
            # the one actionable line, asserted from the child logs)
            # before the second breached min_live_corpora into the
            # classified corpus_loss exit the supervisor relaunched
            logs_text = ""
            for fn in sorted(os.listdir(logs)):
                if fn.startswith("attempt"):
                    with open(
                        os.path.join(logs, fn), errors="replace"
                    ) as fh:
                        logs_text += fh.read()
            assert "renormalized over survivors" in logs_text, (
                "corpus_kill never degraded the mix: no renormalize "
                "line in any attempt log"
            )
            assert any(
                e.get("classification") == "corpus_loss"
                for e in res.ledger["entries"]
            ), f"no corpus_loss classification in {res.ledger}"
            # ckpt_shard_corrupt contract: the size-preserving flip in a
            # COMMITTED shard was detected by the full-content verify
            # (counter + one actionable quarantine line naming the bad
            # shard) and the resume routed around the poisoned step dir
            assert "ckpt_shard_corrupt fault: flipped" in logs_text, (
                "ckpt_shard_corrupt never fired"
            )
            assert "quarantined:" in logs_text and (
                "checksum mismatch" in logs_text
            ), (
                "injected shard corruption was never detected/"
                "quarantined: no integrity line in any attempt log"
            )
            # sdc_grad_flip contract: the cross-replica fingerprint
            # compare detected the diverged replica (counter + line),
            # the exit classified state_divergence, and every later
            # incarnation resumed under the verified-resume rule
            assert "state divergence detected" in logs_text, (
                "sdc_grad_flip never tripped the divergence compare"
            )
            assert any(
                e.get("classification") == "state_divergence"
                for e in res.ledger["entries"]
            ), f"no state_divergence classification in {res.ledger}"
            assert "Verified-resume policy active" in logs_text, (
                "the state_divergence relaunch never applied the "
                "verified-resume rule"
            )

        # committed windows per incarnation: attempt k resumed at the
        # START_STEP its log printed; its committed prefix ends where
        # attempt k+1 resumed (the final attempt ends at the budget)
        starts = []
        for k in range(len(sup.entries)):
            s = _grab(
                os.path.join(logs, f"attempt{k}_child0.log"), "START_STEP"
            )
            starts.append(int(s) if s is not None else None)
        windows = []
        for k, s in enumerate(starts):
            if s is None:
                continue  # died before restore (no committed work)
            end = budget
            for nxt in starts[k + 1 :]:
                if nxt is not None:
                    end = nxt
                    break
            windows.append((f"a{k}", s, end))
        markers = effective_markers(walk, windows)
        assert markers, f"{kind}: empty effective walk ({windows})"
        dupes = sorted(
            {m for m in markers if markers.count(m) > 1}
        ) if len(markers) != len(set(markers)) else []
        assert not dupes, (
            f"{kind}: replayed documents in the effective stream: "
            f"{dupes[:10]} (windows {windows})"
        )

        # hash incarnation: num_steps == budget -> restore-only, prints
        # the topology-independent STATE_HASH of the final checkpoint
        specs = child_specs(
            ckpt, data, walk, obs, hb, "hash", budget, interval
        )
        codes = sup._launch_subprocesses(specs, len(sup.entries), "hash")
        assert codes == [0, 0], f"{kind} hash phase failed: {codes}"
        hash_log = os.path.join(logs, f"attempt{len(sup.entries)}_child0.log")
        final_step = _grab(hash_log, "START_STEP")
        state_hash = _grab(hash_log, "STATE_HASH")
        assert final_step == str(budget), (
            f"{kind}: final committed step {final_step} != budget {budget}"
        )
        rec = last_metrics_record(obs)
        assert rec is not None, f"{kind}: no metrics.jsonl record"
        # obs schema v8: the state-integrity layer was armed and worked
        # — checkpoints scrub-verified, divergence compares performed
        assert (rec.get("scrub_verified") or 0) >= 1, (
            f"{kind}: scrubber never verified a checkpoint ({rec})"
        )
        assert (rec.get("divergence_checks") or 0) >= 1, (
            f"{kind}: no divergence checks recorded ({rec})"
        )
        # run-level goodput: committed work over the run's wall clock,
        # restart downtime included. (Per-incarnation window goodput
        # counts each incarnation's recompile as compute, so at CPU-test
        # scale it cannot compare a restarted run against a straight
        # one; useful output per run-wall-second can.) The FAULTED run
        # is charged its whole run (every incarnation + downtime); the
        # CLEAN reference rate comes from its final incarnation — a
        # straight, uninterrupted pass — so an environment flake the
        # supervisor healed in the clean run cannot mask the injected
        # faults' cost.
        entries = res.ledger["entries"]
        run_wall = entries[-1]["ended_unix"] - entries[0]["started_unix"]
        final_start = next(
            (s for s in reversed(starts) if s is not None), 0
        )
        final_wall = entries[-1]["ended_unix"] - entries[-1]["started_unix"]
        results[kind] = {
            "state_hash": state_hash,
            "restarts_metric": rec.get("restarts"),
            "restart_downtime_s": rec.get("restart_downtime_s"),
            "run_wall_s": run_wall,
            "run_goodput_steps_per_s": budget / max(1e-9, run_wall),
            "straight_steps_per_s": (budget - final_start)
            / max(1e-9, final_wall),
            "supervisor_restarts": res.restarts,
            "markers": sorted(markers),
            "ledger": res.ledger,
        }

    f, c = results["faulted"], results["clean"]
    assert f["state_hash"] == c["state_hash"], (
        f"end-state hash diverged: faulted {f['state_hash']} != clean "
        f"{c['state_hash']}"
    )
    assert f["markers"] == c["markers"], (
        "effective consumed stream diverged from the fault-free run "
        f"({len(f['markers'])} vs {len(c['markers'])} markers)"
    )
    assert f["restarts_metric"] and f["restarts_metric"] >= len(schedule), (
        f"metrics restarts field {f['restarts_metric']} does not reflect "
        f"the {len(schedule)} scheduled faults"
    )
    assert (f["restart_downtime_s"] or 0) > 0, f
    if c["supervisor_restarts"]:
        # the supervisor healed a NON-injected environment failure in
        # the reference run (e.g. a gloo startup race) — its job, and
        # exactly why the clean goodput reference below uses the final
        # straight incarnation rather than the whole clean run
        print(
            f"note: clean run needed {c['supervisor_restarts']} "
            f"environment restart(s) (no faults were injected); "
            f"supervisor healed them"
        )
    assert (
        f["run_goodput_steps_per_s"] < c["straight_steps_per_s"]
    ), (
        f"faulted run goodput {f['run_goodput_steps_per_s']:.4f} steps/s "
        f"not below the straight-run rate {c['straight_steps_per_s']:.4f} "
        f"despite {f['restart_downtime_s']}s downtime and "
        f"{f['supervisor_restarts']} restart(s)"
    )
    # per-corpus document shares of the effective committed stream sit
    # within tolerance of the configured weights (equal doc lengths, so
    # document share ~= token share); generous bound — the run is only
    # budget_steps long and the walk includes reservoir lookahead
    mix_w = [float(w) for w in MIX_WEIGHTS.split(",")]
    targets = [w / sum(mix_w) for w in mix_w]
    counts = [0] * len(CORPORA)
    for m in f["markers"]:
        counts[_corpus_of(m)] += 1
    shares = [n / max(1, len(f["markers"])) for n in counts]
    for name, share, target in zip(CORPORA, shares, targets):
        assert share > 0 and abs(share - target) < 0.2, (
            f"corpus {name} realized share {share:.3f} vs target "
            f"{target:.3f} (doc counts {counts})"
        )
    summary = {
        "seed": args.seed,
        "budget_steps": args.budget_steps,
        "schedule": [s for s, _ in schedule],
        "state_hash": f["state_hash"],
        "restarts": f["supervisor_restarts"],
        "restart_downtime_s": f["restart_downtime_s"],
        "run_goodput_faulted_steps_per_s": f["run_goodput_steps_per_s"],
        "straight_run_steps_per_s": c["straight_steps_per_s"],
        "clean_env_restarts": c["supervisor_restarts"],
        "effective_documents": len(f["markers"]),
        "corpus_shares": {
            name: round(share, 3) for name, share in zip(CORPORA, shares)
        },
        "corpus_share_targets": {
            name: round(t, 3) for name, t in zip(CORPORA, targets)
        },
        "ok": True,
    }
    print(json.dumps(summary, indent=1))
    return summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--budget-steps", type=int, default=32)
    ap.add_argument("--ckpt-interval", type=int, default=4)
    ap.add_argument("--sites", type=int, default=5,
                    help="distinct fault sites to schedule (>=4; "
                    "slice_kill, corpus_kill, ckpt_shard_corrupt and "
                    "sdc_grad_flip always included)")
    ap.add_argument("--backoff-s", type=float, default=0.2)
    ap.add_argument("--workdir", default=None,
                    help="working directory (kept); default: a temp dir, "
                    "removed on success")
    args = ap.parse_args(argv)
    # fail fast on budgets the schedule cannot place: simulate it and
    # require the two commit-aligned corruption sites to land on
    # DISTINCT commit steps with a prior commit to fall back to. A
    # shared headroom cap squashes both onto the same step for small
    # budgets (flip, sdc perturbation, and the paired slice_kill then
    # stack into one incarnation), and a cap <= 0 names a step that
    # never saves — either way the soak would die minutes later on a
    # misleading "never fired"/identity assertion instead of here.
    fires = {}
    for site, spec in sample_schedule(
        args.seed, args.budget_steps, args.ckpt_interval, args.sites
    ):
        if site == "ckpt_shard_corrupt":
            fires[site] = int(spec.split("step=", 1)[1].split(";", 1)[0])
        elif site == "sdc_grad_flip":
            # fires at commit+1: the commit step is what must be distinct
            fires[site] = (
                int(spec.split("step=", 1)[1].split(":", 1)[0]) - 1
            )
    if (
        any(at < args.ckpt_interval for at in fires.values())
        or len(set(fires.values())) < len(fires)
    ):
        ap.error(
            f"--budget-steps {args.budget_steps} is too small for the "
            f"corruption sites at --ckpt-interval {args.ckpt_interval}: "
            f"their commit-aligned fire steps resolve to {fires} — they "
            "need distinct commit steps, each with an earlier commit to "
            "fall back to (CI runs 32)"
        )

    keep = args.workdir is not None
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_soak_")
    os.makedirs(workdir, exist_ok=True)
    print(f"chaos soak workdir: {workdir}")
    try:
        run_soak(args, workdir)
    except AssertionError as e:
        print(f"CHAOS SOAK FAILED: {e}", file=sys.stderr)
        print(f"(workdir kept for post-mortem: {workdir})", file=sys.stderr)
        return 1
    if not keep:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
