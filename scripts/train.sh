#!/bin/bash
# Multi-host TPU launch (ref:scripts/train.sh torchrun analog).
# Run this same script on every host of the pod slice (e.g. via
# `gcloud compute tpus tpu-vm ssh --worker=all --command="bash train.sh"`);
# JAX picks up host topology from the TPU pod environment and
# jax.distributed initializes one process per host.

set -euo pipefail

MODEL_ARGS="\
--model_variant=llama2_7b
--ckpt_load_path=/ckpts
--ckpt_save_path=/ckpts
--data_path=/data
--file_type=arrow
--datasets=dataset=commoncrawl,dataset=webhose
--weights=7725,500
--seq_length=4096
--vocab_size=32000
--logical_shards=1024
--sharding_strategy=hsdp
--fsdp_activation_checkpointing=False
--batch_size=2
--learning_rate=3e-4
--num_steps=1000000
--report_interval=100
--checkpoint_interval=10000
"

python main_training_llama.py ${MODEL_ARGS} "$@"
