"""Deviceless Mosaic lowering of every shipped Pallas kernel variant and
jitted train step against a TPU v5e topology — no chip required.

``jax.experimental.topologies.get_topology_desc`` builds a v5e
TopologyDescription on a chipless host, and ``jit(...).lower(...).
compile()`` against it runs the FULL XLA:TPU + Mosaic pipeline (verified:
an invalid kernel fails here exactly as it would on device). This
catches the "kernel never lowered on real TPU" failure class (this
repo's round-2 SSD kernel) while the TPU tunnel is down, and answers
compile-side questions like the int8 E-major Mixtral hang attribution.

What it cannot do: execute. Numerics, runtime hangs, and performance
still need silicon (scripts/chip_evidence.sh).

Robustness contract mirrors bench.py: the parent never imports jax;
every target runs as ``--target N`` in its own subprocess under a
watchdog, so one Mosaic crash or hang yields a JSON error/timeout entry
instead of killing the sweep. Results land in AOT_LOWER.json.

Run: python scripts/aot_lower_kernels.py            # full sweep
     python scripts/aot_lower_kernels.py --target 0 # one target (child)
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TOPOLOGY = os.environ.get("AOT_TOPOLOGY", "v5e:2x2")
TARGET_TIMEOUT_S = int(os.environ.get("AOT_TARGET_TIMEOUT_S", "1500"))


# -- child-side builders ----------------------------------------------------


def _env_setup():
    # trace REAL Mosaic kernels on this chipless host (pallas_mode.py),
    # and keep jax itself on the CPU client — the TPU side exists only
    # as the AOT compile target
    os.environ["FMS_FORCE_COMPILED_PALLAS"] = "1"
    import jax

    jax.config.update("jax_platforms", "cpu")


_USED_TOPOLOGY = None  # recorded per target into AOT_LOWER.json


def _topology_mesh(shape=(1, 1, 1, 1, 1), topology=None):
    """Full-axis Mesh over the deviceless v5e topology's devices
    (legacy 5-axis shapes get a leading dcn=1 prepended — AOT targets
    are single-slice programs; the dcn axis only matters on multislice
    hardware the deviceless topologies cannot describe). The
    default is a SINGLE-device mesh: an un-shard_mapped Mosaic kernel
    cannot be partitioned by GSPMD, so standalone-kernel targets compile
    single-chip (the bench-row configuration) while multi-device shapes
    are for shard_map'd compositions and full train steps. When the
    requested mesh outgrows the configured topology, it scales up to the
    2-host v5e:2x4, so 8-device programs compile with a REAL host
    boundary in the device assignment; the topology actually used is
    recorded in each result entry."""
    global _USED_TOPOLOGY
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh

    from fms_fsdp_tpu.parallel.mesh import MESH_AXES

    if len(shape) == len(MESH_AXES) - 1:
        shape = (1,) + tuple(shape)
    n = int(np.prod(shape))
    name = topology or TOPOLOGY
    td = topologies.get_topology_desc(platform="tpu", topology_name=name)
    if n > len(td.devices):
        name = "v5e:2x4"
        td = topologies.get_topology_desc(platform="tpu", topology_name=name)
    assert n <= len(td.devices), (shape, len(td.devices))
    _USED_TOPOLOGY = name
    return Mesh(np.asarray(td.devices[:n]).reshape(shape), MESH_AXES), td


def _sds(shape, dtype, sharding=None):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)


def _repl(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def _compile_flash(variant, b, s, nq, nkv, h):
    import jax
    import jax.numpy as jnp

    from fms_fsdp_tpu.ops import flash_attention as fa

    fa.set_kernel_variant(variant)
    mesh, _ = _topology_mesh()
    r = _repl(mesh)

    def loss(q, k, v):
        return jnp.sum(
            fa.flash_attention(q, k, v, causal=True).astype(jnp.float32)
        )

    f = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    q = _sds((b, s, nq, h), jnp.bfloat16, r)
    kv = _sds((b, s, nkv, h), jnp.bfloat16, r)
    f.lower(q, kv, kv).compile()


def _compile_ssd_fused():
    import jax
    import jax.numpy as jnp

    from fms_fsdp_tpu.ops.ssd import ssd_scan

    mesh, _ = _topology_mesh()
    r = _repl(mesh)
    # mamba_9.8b head geometry: 128 heads x P=64, d_state 128, 1 group
    b, s, hh, p, g, n = 1, 4096, 128, 64, 1, 128

    def loss(x, dt, A, Bm, Cm, D):
        return jnp.sum(
            ssd_scan(x, dt, A, Bm, Cm, D, kernel="pallas").astype(jnp.float32)
        )

    f = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2, 3, 4, 5)))
    f.lower(
        _sds((b, s, hh, p), jnp.bfloat16, r),
        _sds((b, s, hh), jnp.float32, r),
        _sds((hh,), jnp.float32, r),
        _sds((b, s, g, n), jnp.bfloat16, r),
        _sds((b, s, g, n), jnp.bfloat16, r),
        _sds((hh,), jnp.float32, r),
    ).compile()


def _compile_ring(cp):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fms_fsdp_tpu.ops.ring_attention import ring_attention
    from fms_fsdp_tpu.parallel.mesh import AXIS_CONTEXT

    mesh, _ = _topology_mesh((1, 1, 1, cp, 1))
    shard = NamedSharding(mesh, P(None, AXIS_CONTEXT, None, None))

    def loss(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh, causal=True).astype(jnp.float32)
        )

    f = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    q = _sds((1, 4096 * cp, 8, 128), jnp.bfloat16, shard)
    kv = _sds((1, 4096 * cp, 8, 128), jnp.bfloat16, shard)
    f.lower(q, kv, kv).compile()


def _compile_cp_ssd(cp):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fms_fsdp_tpu.ops.ssd import ssd_scan_cp
    from fms_fsdp_tpu.parallel.mesh import AXIS_CONTEXT

    mesh, _ = _topology_mesh((1, 1, 1, cp, 1))
    seq_shard = NamedSharding(mesh, P(None, AXIS_CONTEXT, None, None))
    seq_shard3 = NamedSharding(mesh, P(None, AXIS_CONTEXT, None))
    r = _repl(mesh)
    b, s, hh, p, g, n = 1, 1024 * cp, 128, 64, 1, 128

    def loss(x, dt, A, Bm, Cm, D):
        return jnp.sum(
            ssd_scan_cp(x, dt, A, Bm, Cm, D, mesh=mesh).astype(jnp.float32)
        )

    f = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2, 3, 4, 5)))
    f.lower(
        _sds((b, s, hh, p), jnp.bfloat16, seq_shard),
        _sds((b, s, hh), jnp.float32, seq_shard3),
        _sds((hh,), jnp.float32, r),
        _sds((b, s, g, n), jnp.bfloat16, seq_shard),
        _sds((b, s, g, n), jnp.bfloat16, seq_shard),
        _sds((hh,), jnp.float32, r),
    ).compile()


def _compile_train_step(
    variant, model_overrides, mesh_shape=(1, 4, 1, 1, 1), **cfg_overrides
):
    """AOT-compile the FULL donated jitted train step over a mesh of
    topology devices (default: 4-way fsdp; the _2host targets pass
    hsdp/cp/ep/tp shapes): Pallas kernels + GSPMD partitioning + int8
    GEMMs, compiled exactly as a v5e pod slice would compile them."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from fms_fsdp_tpu.config import TrainConfig
    from fms_fsdp_tpu.parallel.mixed_precision import get_dtype_policy
    from fms_fsdp_tpu.parallel.sharding import (
        batch_pspec,
        infer_state_specs,
        resolve_spec,
        tree_shardings,
    )
    from fms_fsdp_tpu.models import get_model_api
    from fms_fsdp_tpu.train.step import make_optimizer, make_train_step
    from fms_fsdp_tpu.utils.config_utils import get_model_config
    from jax.sharding import NamedSharding

    cfg_kw = dict(
        model_variant=variant,
        sharding_strategy="fsdp",
        batch_size=2,
        seq_length=4096,
        attention_kernel="pallas",
    )
    cfg_kw.update(cfg_overrides)
    cfg = TrainConfig(**cfg_kw)
    model_cfg = get_model_config(variant)
    if model_overrides:
        model_cfg = dataclasses.replace(model_cfg, **model_overrides)

    mesh, _ = _topology_mesh(mesh_shape)
    opt = make_optimizer(cfg)
    policy = get_dtype_policy(cfg)
    init_params, _, specs_fn, _ = get_model_api(model_cfg)

    def init_fn(rng):
        params = init_params(rng, model_cfg, dtype=policy.param_dtype)
        return {
            "params": params,
            "opt_state": opt.init(params),
            "step": jnp.zeros((), jnp.int32),
        }

    shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    specs = infer_state_specs(shapes, specs_fn())
    shardings = tree_shardings(
        mesh, specs, jax.tree.map(lambda s: s.shape, shapes)
    )
    state = jax.tree.map(
        lambda s, sh: _sds(s.shape, s.dtype, sh), shapes, shardings
    )

    from fms_fsdp_tpu.parallel.mesh import data_parallel_extent

    step_fn = make_train_step(model_cfg, cfg, mesh, opt)
    gb = cfg.batch_size * data_parallel_extent(mesh)
    bshape = (gb, cfg.seq_length)
    bsh = NamedSharding(mesh, resolve_spec(batch_pspec(), bshape, mesh))
    batch = (_sds(bshape, jnp.int32, bsh), _sds(bshape, jnp.int32, bsh))
    step_fn.lower(state, batch).compile()


# (name, thunk) — every shipped Pallas kernel variant + the flagship
# jitted train steps at their bench-row configs
TARGETS = [
    # resident (base-2) flash family, fwd+bwd, MHA and GQA
    ("flash_resident_mha_4k", lambda: _compile_flash("resident", 1, 4096, 32, 32, 128)),
    ("flash_resident_gqa_4k", lambda: _compile_flash("resident", 1, 4096, 8, 2, 128)),
    # kv-streamed family at the long-context bench rows
    ("flash_kvgrid_16k", lambda: _compile_flash("kvgrid", 1, 16384, 8, 2, 128)),
    ("flash_kvgrid_32k", lambda: _compile_flash("kvgrid", 1, 32768, 8, 2, 128)),
    # fused whole-sequence SSD kernel (the win-or-delete candidate)
    ("ssd_fused_fwd_bwd", _compile_ssd_fused),
    # kernel + collective compositions a pod actually runs
    ("ring_attention_cp4", lambda: _compile_ring(4)),
    ("cp_ssd_cp4", lambda: _compile_cp_ssd(4)),
    # full train steps: Pallas + GSPMD + int8, bench-row shapes
    (
        "train_llama7b_int8_pallas",
        lambda: _compile_train_step(
            "llama2_7b",
            {"nlayers": 3},
            quantized_matmuls="int8_dgrad",
            fsdp_activation_checkpointing=True,
            selective_checkpointing=0.25,
        ),
    ),
    (
        "train_mamba9.8b_pallas_int8",
        lambda: _compile_train_step(
            "mamba_9.8b",
            {"n_layer": 2, "attn_layer_idx": (), "vocab_size": 32000},
            quantized_matmuls="int8_dgrad",
            fsdp_activation_checkpointing=True,
            selective_checkpointing=0.5,
            mamba_kernel="pallas",
        ),
    ),
    # the open E-major question: does the int8 Mixtral row COMPILE for
    # v5e? (XLA:CPU already exonerated — NOTES.md r3)
    (
        "train_mixtral_int8_emajor",
        lambda: _compile_train_step(
            "mixtral_8x7b",
            {"nlayers": 1, "num_experts": 4, "capacity_factor": 1.25},
            quantized_matmuls="int8_dgrad",
            fsdp_activation_checkpointing=True,
            selective_checkpointing=1,
        ),
    ),
    # multi-axis mesh plans on an 8-device 2-HOST v5e:2x4 topology: the
    # dryrun_multichip compositions, compiled by the real TPU compiler
    # with a host boundary in the device assignment (the CPU dryrun can
    # only prove these shard; it cannot prove Mosaic+GSPMD compile them)
    (
        "train_llama_hsdp_tp_pallas_int8_2host",
        lambda: _compile_train_step(
            "llama2_7b",
            {"nlayers": 2},
            mesh_shape=(2, 2, 1, 1, 2),
            sharding_strategy="hsdp",
            sharding_group_size=2,
            quantized_matmuls="int8_dgrad",
            fsdp_activation_checkpointing=True,
            selective_checkpointing=0.25,
        ),
    ),
    (
        "train_mamba_hybrid_cp_ring_2host",
        lambda: _compile_train_step(
            "mamba_9.8b",
            {"n_layer": 2, "attn_layer_idx": (1,), "vocab_size": 32000},
            mesh_shape=(1, 4, 1, 2, 1),
            fsdp_activation_checkpointing=True,
            selective_checkpointing=0.5,
        ),
    ),
    (
        "train_mixtral_ep_tp_int8_2host",
        lambda: _compile_train_step(
            "mixtral_8x7b",
            {"nlayers": 1, "num_experts": 4, "capacity_factor": 1.25},
            mesh_shape=(1, 2, 2, 1, 2),
            quantized_matmuls="int8_dgrad",
            fsdp_activation_checkpointing=True,
            selective_checkpointing=1,
        ),
    ),
    # the 32k single-chip long-context bench row exactly as bench.py
    # runs it: kv-streamed flash + full AC + chunked fused CE
    (
        "train_llama194m_32k_kvgrid_fusedce",
        lambda: _compile_train_step(
            "llama3_194m_4k",
            {},
            mesh_shape=(1, 1, 1, 1, 1),
            batch_size=1,
            seq_length=32768,
            fused_loss=True,
            flash_kernel_variant="kvgrid",
            fsdp_activation_checkpointing=True,
            selective_checkpointing=1,
        ),
    ),
]


def _child(idx):
    _env_setup()
    name, thunk = TARGETS[idx]
    t0 = time.time()
    try:
        thunk()
        r = {"target": name, "status": "compiled", "seconds": round(time.time() - t0, 1)}
    except Exception as e:  # noqa: BLE001
        r = {
            "target": name,
            "status": "error",
            "seconds": round(time.time() - t0, 1),
            "error": f"{type(e).__name__}: {e}"[:400],
        }
    if _USED_TOPOLOGY:
        r["topology"] = _USED_TOPOLOGY
    print("AOT_TARGET_JSON:" + json.dumps(r))


def main():
    results = []
    for idx, (name, _t) in enumerate(TARGETS):
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--target", str(idx)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                timeout=TARGET_TIMEOUT_S,
                text=True,
            )
            r = None
            for line in (proc.stdout or "").splitlines():
                if line.startswith("AOT_TARGET_JSON:"):
                    r = json.loads(line[len("AOT_TARGET_JSON:") :])
            if r is None:
                tail = (proc.stdout or "").strip().splitlines()[-3:]
                r = {
                    "target": name,
                    "status": "error",
                    "error": f"child rc={proc.returncode}: {' | '.join(tail)}"[:400],
                }
        except subprocess.TimeoutExpired:
            r = {
                "target": name,
                "status": "timeout",
                "seconds": round(time.time() - t0, 1),
                "error": f"no result within {TARGET_TIMEOUT_S}s",
            }
        print(f"[aot] {r['target']}: {r['status']} ({r.get('seconds', '?')}s)", flush=True)
        results.append(r)

    out = {
        "topology": (
            f"default {TOPOLOGY}; multi-device targets may scale up — "
            "see each entry's topology field"
        ),
        "note": (
            "AOT lowering+compilation through the full XLA:TPU/Mosaic "
            "pipeline against a deviceless v5e TopologyDescription; "
            "validates kernels COMPILE for the chip (the r2 'never "
            "lowered' failure class), not that they are fast or "
            "numerically correct there"
        ),
        "targets": results,
        "compiled": sum(1 for r in results if r["status"] == "compiled"),
        "total": len(results),
    }
    with open("AOT_LOWER.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"compiled": out["compiled"], "total": out["total"]}))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--target":
        _child(int(sys.argv[2]))
    else:
        main()
