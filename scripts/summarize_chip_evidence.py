"""Turn the chip_evidence.sh artifacts into a recorded decision summary.

Reads CHIP_BENCH.json / BENCH_KERNELS.json / BENCH_SSD.json /
PROFILE_MAMBA.json / EVAL.json (whichever exist) and writes
DECISIONS_r04.md: the headline-vs-baseline verdict, the flash
resident-vs-kvgrid-vs-bundled race winner with the best swept blocks,
the ring-partial rate, and the SSD fused-vs-XLA call (VERDICT r3 items
1-4, 9-10). Runs automatically at the end of scripts/probe_loop.sh so
the recommendation exists even if the capture lands unattended.
"""

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load(name):
    path = os.path.join(ROOT, name)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except Exception as e:  # corrupt is different news than missing
        print(f"WARNING: {name} exists but failed to parse: {e}", file=sys.stderr)
        return {"_parse_error": f"{name}: {e}"}


def main():
    lines = ["# Chip-evidence decision summary (auto-generated)", ""]

    bench = load("CHIP_BENCH.json")
    if isinstance(bench, dict) and "_parse_error" in bench:
        lines.append(f"## Headline: CORRUPT artifact — {bench['_parse_error']}")
        lines.append("")
        bench = None
    if bench and bench.get("rows"):
        v = bench.get("vs_baseline")
        lines.append(
            f"## Headline: {bench.get('metric', '?')} = "
            f"{bench.get('value')} ({v}x baseline) — "
            + ("MEETS the >=1.0 bar" if (v or 0) >= 1.0 else "BELOW the 1.0 bar")
        )
        lines.append("")
        for r in bench["rows"]:
            if "error" in r:
                lines.append(f"- ROW FAILED: {r.get('config')}: {r['error']}")
        lines.append("")
    else:
        lines.append("## Headline: CHIP_BENCH.json missing or empty")
        lines.append("")

    kernels = load("BENCH_KERNELS.json")
    if kernels:
        rows = kernels if isinstance(kernels, list) else kernels.get("rows", [])
        fwd = [
            r
            for r in rows
            if r.get("pass") == "fwd"
            and "tf_s" in r
            and "ceiling" not in r.get("kernel", "")
        ]
        if fwd:
            best = max(fwd, key=lambda r: r["tf_s"])
            ours = [
                r
                for r in fwd
                if "fms_fsdp_tpu" in r.get("kernel", "")
                or "resident fwd" in r.get("kernel", "")
                or "kvgrid" in r.get("kernel", "")
            ]
            best_ours = max(ours, key=lambda r: r["tf_s"]) if ours else None
            lines.append(
                f"## Flash fwd race: best overall = {best['kernel']} "
                f"({best['tf_s']} TF/s)"
            )
            if best_ours:
                lines.append(
                    f"- best of ours: {best_ours['kernel']} "
                    f"({best_ours['tf_s']} TF/s) -> if a swept block combo "
                    f"beats 512/512, change the flash_attention defaults to "
                    f"it; if the bundled kernel still leads, record the gap"
                )
            lines.append("")

    ssd = load("BENCH_SSD.json")
    if ssd:
        rows = ssd if isinstance(ssd, list) else ssd.get("rows", [])
        try:
            tbl = {
                r.get("kernel", r.get("name", "?")): r
                for r in rows
                if isinstance(r, dict)
            }
            lines.append("## SSD fused-vs-XLA (win-or-delete, VERDICT r3 #3):")
            for name, r in tbl.items():
                ms = r.get("fwd_ms", r.get("ms"))
                lines.append(f"- {name}: fwd {ms} ms")
            lines.append(
                "- DECISION RULE: if the fused Pallas kernel beats the XLA "
                "einsums at these shapes, flip ops/ssd.py kernel='auto' to "
                "it; otherwise DELETE the kernel and record the measured "
                "negative in NOTES.md."
            )
            lines.append("")
        except Exception:
            pass

    prof = load("PROFILE_MAMBA.json")
    if prof and prof.get("components"):
        worst = sorted(
            (c for c in prof["components"] if "share_of_step_pct" in c),
            key=lambda c: -c.get("share_of_step_pct", 0),
        )[:3]
        lines.append("## Mamba step attribution (top shares):")
        for c in worst:
            lines.append(
                f"- {c['component']}: {c.get('share_of_step_pct')}% of step, "
                f"{c.get('fwd_bwd_tflops_per_s')} TF/s fwd+bwd"
            )
        lines.append("")

    ev = load("EVAL.json")
    if ev:
        lines.append(f"## EVAL.json: {json.dumps(ev)[:300]}")
        lines.append("")

    out = os.path.join(ROOT, "DECISIONS_r04.md")
    with open(out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))


if __name__ == "__main__":
    main()
