"""Microbench: flash attention TF/s at 7B head shapes on the real chip.

Compares this repo's Pallas kernel against jax's bundled reference
implementation (jax.experimental.pallas.ops.tpu.flash_attention) to know
the achievable ceiling. Timing syncs via host transfer (float()) — see
.claude/skills/verify: block_until_ready does not drain the tunneled queue.
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

B, N, NKV, S, H = 1, 32, 32, 4096, 128
CAUSAL = True


def flops_fwd():
    f = 2 * 2 * B * N * S * S * H  # qk + pv
    return f // 2 if CAUSAL else f


def time_fn(fn, *args, iters=20):
    out = fn(*args)
    _ = float(jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32)))  # sync
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _ = float(jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32)))
    return (time.perf_counter() - t0) / iters


def main():
    kq, kk, kv_ = jax.random.split(jax.random.PRNGKey(0), 3)
    # repo layout (B, S, N, H)
    q = jax.random.normal(kq, (B, S, N, H), jnp.bfloat16)
    k = jax.random.normal(kk, (B, S, NKV, H), jnp.bfloat16)
    v = jax.random.normal(kv_, (B, S, NKV, H), jnp.bfloat16)

    from fms_fsdp_tpu.ops.flash_attention import flash_attention

    ours_fwd = jax.jit(functools.partial(flash_attention, causal=CAUSAL))

    def ours_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=CAUSAL).astype(jnp.float32))

    ours_bwd = jax.jit(jax.grad(ours_loss, argnums=(0, 1, 2)))

    t = time_fn(ours_fwd, q, k, v)
    print(f"ours fwd: {t*1e3:.2f} ms  {flops_fwd()/t/1e12:.1f} TF/s")
    t = time_fn(ours_bwd, q, k, v)
    # fwd (recompute not included: custom vjp saves o, lse) + dq + dkv
    bwd_flops = flops_fwd() * 3.5 / 1.0  # dq: 3 matmuls? approx: fwd=2mm, bwd=5mm
    print(f"ours fwd+bwd(grad): {t*1e3:.2f} ms  {flops_fwd()*3.5/t/1e12:.1f} TF/s (counting 3.5x fwd)")

    # jax bundled impl wants (B, N, S, H)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention as jax_fa,
    )

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    bs = BlockSizes(
        block_q=512, block_k_major=512, block_k=512, block_b=1,
        block_q_major_dkv=512, block_k_major_dkv=512, block_k_dkv=512,
        block_q_dkv=512, block_k_major_dq=512, block_k_dq=512, block_q_dq=512,
    )
    ref_fwd = jax.jit(
        functools.partial(jax_fa, causal=CAUSAL, sm_scale=H**-0.5, block_sizes=bs)
    )

    def ref_loss(q, k, v):
        return jnp.sum(
            jax_fa(q, k, v, causal=CAUSAL, sm_scale=H**-0.5, block_sizes=bs).astype(
                jnp.float32
            )
        )

    ref_bwd = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))

    t = time_fn(ref_fwd, qt, kt, vt)
    print(f"jax  fwd: {t*1e3:.2f} ms  {flops_fwd()/t/1e12:.1f} TF/s")
    t = time_fn(ref_bwd, qt, kt, vt)
    print(f"jax  fwd+bwd(grad): {t*1e3:.2f} ms  {flops_fwd()*3.5/t/1e12:.1f} TF/s (counting 3.5x fwd)")


if __name__ == "__main__":
    main()
