"""Serving-fleet chaos soak: kill AND stall replicas mid-stream, prove
zero dropped requests, exactly-once token parity, and measured
availability < 1.0.

Three fleet runs over the SAME seeded request wave against the same
deterministically-initialized tiny model (greedy, reference attention,
float32 — the bit-parity mode PR 11's anchor proved batch-composition-
independent, which is what makes cross-run token comparison exact).
``--family mamba`` swaps the fleet model for a hybrid mamba (conv+SSD
slab decode, one attn layer on pages — serve/families/): a requeued
request's recompute-on-resume must then rebuild the recurrent slab
from scratch, so the token-parity assertion doubles as the fleet-level
proof of that family's eviction contract. The runs:

1. **reference**: no faults — the parity baseline;
2. **kill**: ``replica_kill`` hard-exits replica 1 mid-stream (engine
   iteration 10 of its first incarnation, ``times=1``; the relaunched
   incarnation gets the fault spec stripped) — exit code 10 classifies
   ``replica_loss``, the keep-N supervisor relaunches, the router
   requeues the dead incarnation's in-flight requests;
3. **stall**: ``replica_stall`` parks replica 0 in a long sleep without
   dying — heartbeats stop, the router's stall watchdog SIGKILLs it
   with the classification pinned to ``replica_loss``, then the same
   relaunch + requeue path runs.

Asserted per faulted run: every submitted request COMPLETED (zero
drops, zero stuck journal records), every completed response
token-identical to the reference run (exactly-once: no duplicate, no
divergent recompute), the restart ledger shows >= 1 relaunch with
``replica_loss`` classification, and the ledger-folded availability is
MEASURED < 1.0 (the churn happened) while per-request completion stays
1.0 (nothing was dropped). The stall run must additionally detect >= 1
stall via the watchdog. The fleet stats map is validated against the
obs schema ``serving_fleet`` field (v13).

``--speculative`` reruns the kill/stall schedule on a SPECULATIVE
llama fleet (every replica drafts through a random-init MLPSpeculator
checkpoint written into the workdir) and asserts its tokens against
the PLAIN fleet's reference run: greedy speculative decode must be
token-identical to non-speculative greedy — including requeued
requests whose recompute-on-resume re-prefills and re-drafts from
scratch on the surviving replica. A random head keeps the accept rate
near zero, which is the point: every draft still flows through the
verify/accept path, so parity is pinned on the mechanism, not on a
lucky always-accept stream.

``--disagg`` swaps the schedule for a disaggregated fleet (1 prefill +
2 decode replicas, ``FleetConfig.prefill_replicas``): the same wave
runs against the unified reference, then twice faulted — the prefill
worker killed mid-handoff (un-journaled rids requeue as fresh prompts
and re-prefill) and a decode worker killed post-handoff (journaled
KV-page bytes replay as ``resume`` on the sibling). Both must complete
every request with tokens identical to the unified fleet's — the
end-to-end proof that handoff pages ship bit-exact.

Writes ``fleet_soak.json`` (summary) plus per-incarnation replica
stderr logs and the request journal / restart ledger under ``--out``.

Budget: tiny model (2 layers, 64-dim), CPU, ~2-4 min wall. CI runs it
as a dedicated step (.github/workflows/pytest.yml) outside the main
test sweep.
"""

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from fms_fsdp_tpu.serve.fleet import (  # noqa: E402
    FleetConfig,
    FleetRouter,
    make_subprocess_spawn,
)

# per-family fleet model (--family): the replica resolves it through
# serve/families.load_model_config, so the same soak drives a llama
# fleet (paged KV only) or a hybrid-mamba fleet (conv+SSD slab decode
# with one attn layer on pages) — eviction/requeue recompute must
# rebuild the slab from scratch, so token parity here is the fleet-level
# proof of the family's recompute-on-resume contract.
MODEL_CFGS = {
    "llama": {
        "src_vocab_size": 128,
        "emb_dim": 64,
        "nheads": 4,
        "kvheads": 2,
        "nlayers": 2,
        "max_expected_seq_len": 128,
    },
    "mamba": {
        "family": "mamba",
        "d_model": 64,
        "n_layer": 3,
        "vocab_size": 128,
        "d_state": 16,
        "headdim": 16,
        "chunk_size": 8,
        "d_intermediate": 128,
        "attn_layer_idx": [1],
        "attn_cfg": {
            "head_dim": 16,
            "num_heads": 4,
            "num_heads_kv": 2,
            "rotary_emb_dim": 8,
        },
    },
}
MODEL_CFG = MODEL_CFGS["llama"]  # --family rebinds
FAMILY = "llama"
SERVE_CFG = {
    "max_batch": 4,
    "max_seq_len": 128,
    "page_size": 16,
    "attn_impl": "reference",
    "compute_dtype": "float32",  # the exact-parity numerics
    # bucketed prefill bounds jit-compile diversity: mid-run compiles
    # longer than the stall timeout would read as wedged replicas.
    # Parity here is fleet-vs-fleet under identical configs, so
    # bucketing does not loosen the token-identity assertion.
    "prefill_bucket": 8,
    "max_prefill_per_step": 1,
}
N_REQUESTS = int(os.environ.get("FLEET_SOAK_REQUESTS", "10"))
MAX_NEW = 8
SEED = 0
# the mamba prefill scan compiles slower than llama's on CPU; keep the
# watchdog above a residual mid-run compile for that family
STALL_TIMEOUT_S = {"llama": 10.0, "mamba": 30.0}


def make_wave(n, seed):
    """Seeded prompt wave (lengths 6..16) — identical across runs."""
    import numpy as np

    rng = np.random.default_rng(seed)
    vocab = MODEL_CFG.get("src_vocab_size") or MODEL_CFG["vocab_size"]
    wave = []
    for _ in range(n):
        plen = int(rng.integers(6, 17))
        wave.append(rng.integers(0, vocab, size=plen).tolist())
    return wave


def run_fleet(tag, workdir, faults="", n_replicas=2, prefill=0,
              serve_cfg=None, stall_timeout=None):
    """One fleet run over the wave. Returns (tokens_by_rid, stats,
    ledger, wall_s). ``prefill`` > 0 turns the fleet disaggregated:
    replicas [0, prefill) run role=prefill, the rest role=decode, and
    the router journals each KV-page handoff before forwarding.
    ``serve_cfg`` overrides the shared SERVE_CFG (the --speculative
    schedule's speculator_path); ``stall_timeout`` overrides the
    per-family watchdog (the speculative verify step adds a jit
    compile the 10s llama default would misread as a stall)."""
    scfg = serve_cfg or SERVE_CFG
    wdir = os.path.join(workdir, tag)
    spawn = make_subprocess_spawn(
        wdir,
        MODEL_CFG,
        scfg,
        init_seed=SEED,
        faults=faults,
        env_extra={"JAX_PLATFORMS": "cpu"},
        prefill_replicas=prefill,
    )
    cfg = FleetConfig(
        n_replicas=n_replicas,
        prefill_replicas=prefill,
        max_seq_len=scfg["max_seq_len"],
        max_inflight_per_replica=4,
        # above the worst single-step wall on CPU (a residual jit
        # compile), far below the injected 600s stall
        stall_timeout_s=stall_timeout or STALL_TIMEOUT_S[FAMILY],
        startup_timeout_s=180.0,
        restart_backoff_s=0.2,
        journal_path=os.path.join(wdir, "journal.jsonl"),
        ledger_path=os.path.join(wdir, "ledger.json"),
    )
    router = FleetRouter(spawn, cfg)
    router.start()
    t0 = time.monotonic()
    rids = [router.submit(p, MAX_NEW) for p in make_wave(N_REQUESTS, SEED)]
    router.run_until_idle(timeout_s=300.0)
    wall = time.monotonic() - t0
    stats = router.stats()
    router.drain()
    router.shutdown()
    with open(os.path.join(wdir, "ledger.json")) as f:
        ledger = json.load(f)
    tokens = {
        rid: router.journal.records[rid].tokens for rid in rids
    }
    counts = router.journal.counts()
    print(
        f"[{tag}] wall {wall:.1f}s counts={counts} "
        f"availability={stats['availability']:.4f} "
        f"restarts={stats['restarts']:.0f} "
        f"requeued={stats['requests_requeued']:.0f} "
        f"stalls={stats['stalls_detected']:.0f} "
        f"duplicates_dropped={stats['duplicates_dropped']:.0f}"
    )
    assert counts["completed"] == N_REQUESTS, (
        f"[{tag}] dropped requests: {counts}"
    )
    return tokens, stats, ledger, wall


def assert_faulted(tag, ref_tokens, tokens, stats, ledger):
    # zero drops + exactly-once parity: every response token-identical
    # to the unfaulted run's (recompute-on-resume is greedy and
    # batch-composition-independent, so a requeued request's re-decode
    # matches bit for bit)
    for rid, toks in ref_tokens.items():
        assert tokens[rid] == toks, (
            f"[{tag}] rid {rid} tokens diverged:\n"
            f"  ref: {toks}\n  got: {tokens[rid]}"
        )
    assert stats["restarts"] >= 1, f"[{tag}] no relaunch recorded"
    assert stats["requests_requeued"] >= 1, (
        f"[{tag}] fault landed with nothing in flight — not mid-stream"
    )
    # the churn is MEASURED: ledger-folded replica availability < 1.0
    # even though per-request completion is 1.0 (nothing dropped)
    assert 0.0 < stats["availability"] < 1.0, stats["availability"]
    assert stats["completion_rate"] == 1.0, stats["completion_rate"]
    classes = [e["classification"] for e in ledger["entries"]]
    assert "replica_loss" in classes, (tag, classes)


def validate_obs_map(stats):
    """The fleet stats map must satisfy the obs serving_fleet field on
    a schema-valid record (v13)."""
    from fms_fsdp_tpu.obs.schema import (
        SCHEMA_FIELDS,
        SCHEMA_VERSION,
        validate_record,
    )

    rec = {}
    for name, (tag, required) in SCHEMA_FIELDS.items():
        if not required:
            continue
        rec[name] = {"int": 0, "float": 0.0, "str": "", "map": {}}[tag]
    rec["schema_version"] = SCHEMA_VERSION
    rec["serving_fleet"] = stats
    errs = validate_record(rec)
    assert not errs, errs


def _journal_handoffs(workdir, tag):
    """Count journaled ``handoff`` events in a run's journal JSONL."""
    n = 0
    with open(os.path.join(workdir, tag, "journal.jsonl")) as f:
        for line in f:
            if json.loads(line).get("event") == "handoff":
                n += 1
    return n


def assert_disagg(tag, out, ref_tokens, tokens, stats, ledger):
    """Disagg-run assertions on top of the shared faulted-run set: the
    fleet really ran split (every request crossed the prefill->decode
    wire, journaled first) and the faulted side's loss was absorbed."""
    assert_faulted(tag, ref_tokens, tokens, stats, ledger)
    assert stats["prefill_replicas"] == 1.0, stats
    assert stats["requests_handed_off"] >= N_REQUESTS, (
        f"[{tag}] only {stats['requests_handed_off']:.0f} handoffs for "
        f"{N_REQUESTS} requests — the fleet did not run disaggregated"
    )
    journaled = _journal_handoffs(out, tag)
    assert journaled >= N_REQUESTS, (tag, journaled)
    print(f"[{tag}] handoffs journaled={journaled} "
          f"bytes={stats['handoff_bytes']:.0f}")


def run_disagg_soak(out):
    """--disagg: a 1-prefill + 2-decode fleet vs the unified reference.

    Token parity of BOTH faulted disagg runs against the unified
    2-replica fleet is the end-to-end proof that handoff pages are
    bit-exact (greedy float32/reference decode re-reads the shipped
    pages verbatim). The two kills land on either side of the wire:

    - **prefill_kill** (replica 0, the only prefill worker, iteration 5
      of its first incarnation): rids whose handoff bytes never reached
      the router's journal requeue as FRESH prompts and re-prefill on
      the relaunched incarnation — mid-handoff loss, zero drops;
    - **decode_kill** (replica 1, iteration 10): rids already past the
      journal requeue WITH their handoff bytes and replay as ``resume``
      on the surviving decode sibling — the prefill worker is never
      re-consulted post-handoff.
    """
    ref_tokens, ref_stats, _, _ = run_fleet("reference", out)
    assert ref_stats["restarts"] == 0, "reference run must be unfaulted"
    assert ref_stats["requests_handed_off"] == 0.0, ref_stats

    pk_tokens, pk_stats, pk_ledger, _ = run_fleet(
        "prefill_kill", out,
        faults="replica_kill:replica=0:step=5:times=1",
        n_replicas=3, prefill=1,
    )
    assert_disagg("prefill_kill", out, ref_tokens, pk_tokens, pk_stats,
                  pk_ledger)

    dk_tokens, dk_stats, dk_ledger, _ = run_fleet(
        "decode_kill", out,
        faults="replica_kill:replica=1:step=10:times=1",
        n_replicas=3, prefill=1,
    )
    assert_disagg("decode_kill", out, ref_tokens, dk_tokens, dk_stats,
                  dk_ledger)

    validate_obs_map(pk_stats)

    summary = {
        "family": FAMILY,
        "mode": "disagg",
        "requests": N_REQUESTS,
        "reference": ref_stats,
        "prefill_kill": pk_stats,
        "decode_kill": dk_stats,
        "zero_drops": True,
        "token_parity": True,
    }
    with open(os.path.join(out, "fleet_soak_disagg.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print("disagg chaos soak PASSED: zero drops, token parity vs "
          "unified, prefill-kill availability "
          f"{pk_stats['availability']:.4f}, decode-kill availability "
          f"{dk_stats['availability']:.4f}")


def _write_speculator(out):
    """Random-init serving speculator checkpoint for the --speculative
    schedule. The soak pins PARITY (speculative greedy == plain greedy
    under churn), never speed — a random head keeps the accept rate
    near zero while every draft still flows through the verify/accept
    path, which is exactly the mechanism under test."""
    import jax

    from fms_fsdp_tpu.models.speculator import (
        SpeculatorConfig,
        init_speculator_params,
        save_speculator,
    )

    scfg = SpeculatorConfig(
        emb_dim=MODEL_CFG["emb_dim"],
        inner_dim=32,
        vocab_size=MODEL_CFG["src_vocab_size"],
        n_predict=3,
    )
    path = os.path.join(out, "speculator.pkl")
    save_speculator(
        path, init_speculator_params(jax.random.PRNGKey(7), scfg), scfg
    )
    return path


def run_speculative_soak(out):
    """--speculative: the kill/stall schedule on a speculative llama
    fleet, token-parity-checked against the PLAIN fleet's reference
    run. Three runs:

    1. **reference**: the unfaulted NON-speculative fleet — the greedy
       baseline every later run must reproduce;
    2. **spec_reference**: the unfaulted speculative fleet — isolates
       the draft/verify/accept parity claim from churn;
    3. **kill** / **stall**: the faulted speculative fleet — a requeued
       request's recompute-on-resume re-prefills (re-stashing the draft
       embedding) and re-drafts on the survivor, and must still emit
       the plain fleet's exact tokens.
    """
    ref_tokens, ref_stats, _, _ = run_fleet("reference", out)
    assert ref_stats["restarts"] == 0, "reference run must be unfaulted"

    spec_cfg = dict(SERVE_CFG, speculator_path=_write_speculator(out))
    spec_tokens, spec_stats, _, _ = run_fleet(
        "spec_reference", out, serve_cfg=spec_cfg, stall_timeout=30.0
    )
    assert spec_stats["restarts"] == 0, "spec reference must be unfaulted"
    for rid, toks in ref_tokens.items():
        assert spec_tokens[rid] == toks, (
            f"[spec_reference] rid {rid} speculative greedy diverged "
            f"from plain greedy:\n  ref: {toks}\n  got: {spec_tokens[rid]}"
        )

    kill_tokens, kill_stats, kill_ledger, _ = run_fleet(
        "spec_kill", out,
        faults="replica_kill:replica=1:step=10:times=1",
        serve_cfg=spec_cfg, stall_timeout=30.0,
    )
    assert_faulted("spec_kill", ref_tokens, kill_tokens, kill_stats,
                   kill_ledger)

    stall_tokens, stall_stats, stall_ledger, _ = run_fleet(
        "spec_stall", out,
        faults="replica_stall:replica=0:step=10:seconds=600:times=1",
        serve_cfg=spec_cfg, stall_timeout=30.0,
    )
    assert_faulted("spec_stall", ref_tokens, stall_tokens, stall_stats,
                   stall_ledger)
    assert stall_stats["stalls_detected"] >= 1, (
        "watchdog never fired on the stalled replica"
    )

    validate_obs_map(kill_stats)

    summary = {
        "family": FAMILY,
        "mode": "speculative",
        "requests": N_REQUESTS,
        "reference": ref_stats,
        "spec_reference": spec_stats,
        "kill": kill_stats,
        "stall": stall_stats,
        "zero_drops": True,
        "token_parity": True,
    }
    with open(os.path.join(out, "fleet_soak_speculative.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print("speculative chaos soak PASSED: zero drops, speculative "
          "greedy token parity vs plain fleet, kill availability "
          f"{kill_stats['availability']:.4f}, stall availability "
          f"{stall_stats['availability']:.4f}")


def main():
    global MODEL_CFG, FAMILY
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="",
                    help="artifact dir (default: a temp dir)")
    ap.add_argument("--family", default="llama",
                    choices=sorted(MODEL_CFGS),
                    help="fleet model family: llama (paged KV) or "
                         "hybrid mamba (slab + one attn layer)")
    ap.add_argument("--disagg", action="store_true",
                    help="soak a disaggregated fleet (1 prefill + 2 "
                         "decode replicas, journaled KV-page handoff) "
                         "with kills on either side of the wire, "
                         "instead of the unified kill/stall schedule")
    ap.add_argument("--speculative", action="store_true",
                    help="soak a speculative llama fleet (random-init "
                         "MLPSpeculator draft/verify on every replica) "
                         "and assert greedy token parity against the "
                         "plain fleet's reference run")
    args = ap.parse_args()
    MODEL_CFG = MODEL_CFGS[args.family]
    FAMILY = args.family
    if args.disagg and args.family != "llama":
        ap.error("--disagg requires --family llama (mamba's slab state "
                 "has no page handoff; its adapter is unified-only)")
    if args.speculative and args.family != "llama":
        ap.error("--speculative requires --family llama (the "
                 "MLPSpeculator draft/verify loop is llama-only)")
    if args.speculative and args.disagg:
        ap.error("--speculative and --disagg are mutually exclusive: a "
                 "speculative engine rejects handoff resumes (the draft "
                 "embedding is not part of the page handoff)")
    out = args.out or tempfile.mkdtemp(prefix=f"fleet_soak_{FAMILY}_")
    os.makedirs(out, exist_ok=True)
    if args.disagg:
        print(f"disagg serving chaos soak ({FAMILY} fleet) -> {out}")
        run_disagg_soak(out)
        return
    if args.speculative:
        print(f"speculative serving chaos soak ({FAMILY} fleet) -> {out}")
        run_speculative_soak(out)
        return
    print(f"serving chaos soak ({FAMILY} fleet) -> {out}")

    ref_tokens, ref_stats, _, ref_wall = run_fleet("reference", out)
    assert ref_stats["restarts"] == 0, "reference run must be unfaulted"

    kill_tokens, kill_stats, kill_ledger, _ = run_fleet(
        "kill", out, faults="replica_kill:replica=1:step=10:times=1"
    )
    assert_faulted("kill", ref_tokens, kill_tokens, kill_stats,
                   kill_ledger)
    # the injected death must classify through the registry code (10),
    # not as a generic error
    kill_classes = [
        (e["exit_code"], e["classification"])
        for e in kill_ledger["entries"]
    ]
    assert (10, "replica_loss") in kill_classes, kill_classes

    stall_tokens, stall_stats, stall_ledger, _ = run_fleet(
        "stall", out,
        faults="replica_stall:replica=0:step=10:seconds=600:times=1",
    )
    assert_faulted("stall", ref_tokens, stall_tokens, stall_stats,
                   stall_ledger)
    assert stall_stats["stalls_detected"] >= 1, (
        "watchdog never fired on the stalled replica"
    )

    validate_obs_map(kill_stats)

    summary = {
        "family": FAMILY,
        "requests": N_REQUESTS,
        "reference": {"wall_s": round(ref_wall, 2), **ref_stats},
        "kill": kill_stats,
        "stall": stall_stats,
        "zero_drops": True,
        "token_parity": True,
    }
    with open(os.path.join(out, "fleet_soak.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print("serving chaos soak PASSED: zero drops, token parity, "
          f"kill availability {kill_stats['availability']:.4f}, "
          f"stall availability {stall_stats['availability']:.4f}")


if __name__ == "__main__":
    main()
