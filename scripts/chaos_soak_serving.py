"""Serving-fleet chaos soak: kill AND stall replicas mid-stream, prove
zero dropped requests, exactly-once token parity, and measured
availability < 1.0.

Three fleet runs over the SAME seeded request wave against the same
deterministically-initialized tiny model (greedy, reference attention,
float32 — the bit-parity mode PR 11's anchor proved batch-composition-
independent, which is what makes cross-run token comparison exact).
``--family mamba`` swaps the fleet model for a hybrid mamba (conv+SSD
slab decode, one attn layer on pages — serve/families/): a requeued
request's recompute-on-resume must then rebuild the recurrent slab
from scratch, so the token-parity assertion doubles as the fleet-level
proof of that family's eviction contract. The runs:

1. **reference**: no faults — the parity baseline;
2. **kill**: ``replica_kill`` hard-exits replica 1 mid-stream (engine
   iteration 10 of its first incarnation, ``times=1``; the relaunched
   incarnation gets the fault spec stripped) — exit code 10 classifies
   ``replica_loss``, the keep-N supervisor relaunches, the router
   requeues the dead incarnation's in-flight requests;
3. **stall**: ``replica_stall`` parks replica 0 in a long sleep without
   dying — heartbeats stop, the router's stall watchdog SIGKILLs it
   with the classification pinned to ``replica_loss``, then the same
   relaunch + requeue path runs.

Asserted per faulted run: every submitted request COMPLETED (zero
drops, zero stuck journal records), every completed response
token-identical to the reference run (exactly-once: no duplicate, no
divergent recompute), the restart ledger shows >= 1 relaunch with
``replica_loss`` classification, and the ledger-folded availability is
MEASURED < 1.0 (the churn happened) while per-request completion stays
1.0 (nothing was dropped). The stall run must additionally detect >= 1
stall via the watchdog. The fleet stats map is validated against the
obs schema ``serving_fleet`` field (v13).

``--speculative`` reruns the kill/stall schedule on a SPECULATIVE
llama fleet (every replica drafts through a random-init MLPSpeculator
checkpoint written into the workdir) and asserts its tokens against
the PLAIN fleet's reference run: greedy speculative decode must be
token-identical to non-speculative greedy — including requeued
requests whose recompute-on-resume re-prefills and re-drafts from
scratch on the surviving replica. A random head keeps the accept rate
near zero, which is the point: every draft still flows through the
verify/accept path, so parity is pinned on the mechanism, not on a
lucky always-accept stream.

``--disagg`` swaps the schedule for a disaggregated fleet (1 prefill +
2 decode replicas, ``FleetConfig.prefill_replicas``): the same wave
runs against the unified reference, then twice faulted — the prefill
worker killed mid-handoff (un-journaled rids requeue as fresh prompts
and re-prefill) and a decode worker killed post-handoff (journaled
KV-page bytes replay as ``resume`` on the sibling). Both must complete
every request with tokens identical to the unified fleet's — the
end-to-end proof that handoff pages ship bit-exact.

``--transport`` soaks the chunked state-transfer wire itself
(serve/disagg/transport.py) end to end:

- a disagg fleet with BOTH wire directions corrupted and lossy
  (``handoff_chunk_corrupt`` + ``handoff_chunk_drop`` against every
  ``.tx`` sender label, router process included) must complete the
  wave token-identical — CRC drops heal by retransmit, and the
  router's counters prove it (``handoff_retries``, ``chunks_resent``,
  ``transfers_resumed`` all > 0);
- mid-transfer kills on either wire side (the prefill worker shipping
  chunks, the decode worker receiving a resume) requeue exactly-once —
  the journaled transfer to the dead incarnation is aborted and the
  bytes replay in full to the sibling;
- a SIGTERM ``preempt`` of a hybrid-mamba replica drains-and-migrates:
  every live stream packs (conv window + fp32 SSD slab + hybrid pages)
  and arrives at a sibling as a ``migrate`` transfer —
  ``drain_migrations`` > 0, the journal shows the migrated rids
  resumed WITHOUT a recompute requeue, the exit classifies
  ``preempted``, and tokens still match the unfaulted mamba fleet.

Writes ``fleet_soak.json`` (summary) plus per-incarnation replica
stderr logs and the request journal / restart ledger under ``--out``.

Budget: tiny model (2 layers, 64-dim), CPU, ~2-4 min wall. CI runs it
as a dedicated step (.github/workflows/pytest.yml) outside the main
test sweep.
"""

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from fms_fsdp_tpu.serve.fleet import (  # noqa: E402
    FleetConfig,
    FleetRouter,
    make_subprocess_spawn,
)

# per-family fleet model (--family): the replica resolves it through
# serve/families.load_model_config, so the same soak drives a llama
# fleet (paged KV only) or a hybrid-mamba fleet (conv+SSD slab decode
# with one attn layer on pages) — eviction/requeue recompute must
# rebuild the slab from scratch, so token parity here is the fleet-level
# proof of the family's recompute-on-resume contract.
MODEL_CFGS = {
    "llama": {
        "src_vocab_size": 128,
        "emb_dim": 64,
        "nheads": 4,
        "kvheads": 2,
        "nlayers": 2,
        "max_expected_seq_len": 128,
    },
    "mamba": {
        "family": "mamba",
        "d_model": 64,
        "n_layer": 3,
        "vocab_size": 128,
        "d_state": 16,
        "headdim": 16,
        "chunk_size": 8,
        "d_intermediate": 128,
        "attn_layer_idx": [1],
        "attn_cfg": {
            "head_dim": 16,
            "num_heads": 4,
            "num_heads_kv": 2,
            "rotary_emb_dim": 8,
        },
    },
}
MODEL_CFG = MODEL_CFGS["llama"]  # --family rebinds
FAMILY = "llama"
SERVE_CFG = {
    "max_batch": 4,
    "max_seq_len": 128,
    "page_size": 16,
    "attn_impl": "reference",
    "compute_dtype": "float32",  # the exact-parity numerics
    # bucketed prefill bounds jit-compile diversity: mid-run compiles
    # longer than the stall timeout would read as wedged replicas.
    # Parity here is fleet-vs-fleet under identical configs, so
    # bucketing does not loosen the token-identity assertion.
    "prefill_bucket": 8,
    "max_prefill_per_step": 1,
}
N_REQUESTS = int(os.environ.get("FLEET_SOAK_REQUESTS", "10"))
MAX_NEW = 8
SEED = 0
# the mamba prefill scan compiles slower than llama's on CPU; keep the
# watchdog above a residual mid-run compile for that family
STALL_TIMEOUT_S = {"llama": 10.0, "mamba": 30.0}


def make_wave(n, seed):
    """Seeded prompt wave (lengths 6..16) — identical across runs."""
    import numpy as np

    rng = np.random.default_rng(seed)
    vocab = MODEL_CFG.get("src_vocab_size") or MODEL_CFG["vocab_size"]
    wave = []
    for _ in range(n):
        plen = int(rng.integers(6, 17))
        wave.append(rng.integers(0, vocab, size=plen).tolist())
    return wave


def run_fleet(tag, workdir, faults="", n_replicas=2, prefill=0,
              serve_cfg=None, stall_timeout=None, router_faults="",
              fleet_kw=None, on_poll=None):
    """One fleet run over the wave. Returns (tokens_by_rid, stats,
    ledger, wall_s). ``prefill`` > 0 turns the fleet disaggregated:
    replicas [0, prefill) run role=prefill, the rest role=decode, and
    the router journals each KV-page handoff before forwarding.
    ``serve_cfg`` overrides the shared SERVE_CFG (the --speculative
    schedule's speculator_path); ``stall_timeout`` overrides the
    per-family watchdog (the speculative verify step adds a jit
    compile the 10s llama default would misread as a stall).
    ``router_faults`` configures fault sites in THIS process too (the
    router hosts the resume-direction chunk senders); ``fleet_kw``
    passes extra FleetConfig knobs (transport chunk sizes);
    ``on_poll(router)`` runs every poll tick — the --transport drain
    schedule uses it to preempt a replica mid-wave."""
    from fms_fsdp_tpu.resilience.faults import configure_faults

    scfg = serve_cfg or SERVE_CFG
    wdir = os.path.join(workdir, tag)
    spawn = make_subprocess_spawn(
        wdir,
        MODEL_CFG,
        scfg,
        init_seed=SEED,
        faults=faults,
        env_extra={"JAX_PLATFORMS": "cpu"},
        prefill_replicas=prefill,
    )
    cfg = FleetConfig(
        n_replicas=n_replicas,
        prefill_replicas=prefill,
        max_seq_len=scfg["max_seq_len"],
        max_inflight_per_replica=4,
        # above the worst single-step wall on CPU (a residual jit
        # compile), far below the injected 600s stall
        stall_timeout_s=stall_timeout or STALL_TIMEOUT_S[FAMILY],
        startup_timeout_s=180.0,
        restart_backoff_s=0.2,
        journal_path=os.path.join(wdir, "journal.jsonl"),
        ledger_path=os.path.join(wdir, "ledger.json"),
        **(fleet_kw or {}),
    )
    router = FleetRouter(spawn, cfg)
    configure_faults(router_faults)
    router.start()
    t0 = time.monotonic()
    rids = [router.submit(p, MAX_NEW) for p in make_wave(N_REQUESTS, SEED)]
    try:
        if on_poll is None:
            router.run_until_idle(timeout_s=300.0)
        else:
            deadline = time.monotonic() + 300.0
            while router.journal.outstanding() > 0:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"[{tag}] fleet not idle: {router.journal.counts()}"
                    )
                router.poll()
                on_poll(router)
                time.sleep(0.01)
    finally:
        configure_faults("")
    wall = time.monotonic() - t0
    stats = router.stats()
    router.drain()
    router.shutdown()
    with open(os.path.join(wdir, "ledger.json")) as f:
        ledger = json.load(f)
    tokens = {
        rid: router.journal.records[rid].tokens for rid in rids
    }
    counts = router.journal.counts()
    print(
        f"[{tag}] wall {wall:.1f}s counts={counts} "
        f"availability={stats['availability']:.4f} "
        f"restarts={stats['restarts']:.0f} "
        f"requeued={stats['requests_requeued']:.0f} "
        f"stalls={stats['stalls_detected']:.0f} "
        f"duplicates_dropped={stats['duplicates_dropped']:.0f}"
    )
    assert counts["completed"] == N_REQUESTS, (
        f"[{tag}] dropped requests: {counts}"
    )
    return tokens, stats, ledger, wall


def assert_faulted(tag, ref_tokens, tokens, stats, ledger):
    # zero drops + exactly-once parity: every response token-identical
    # to the unfaulted run's (recompute-on-resume is greedy and
    # batch-composition-independent, so a requeued request's re-decode
    # matches bit for bit)
    for rid, toks in ref_tokens.items():
        assert tokens[rid] == toks, (
            f"[{tag}] rid {rid} tokens diverged:\n"
            f"  ref: {toks}\n  got: {tokens[rid]}"
        )
    assert stats["restarts"] >= 1, f"[{tag}] no relaunch recorded"
    assert stats["requests_requeued"] >= 1, (
        f"[{tag}] fault landed with nothing in flight — not mid-stream"
    )
    # the churn is MEASURED: ledger-folded replica availability < 1.0
    # even though per-request completion is 1.0 (nothing dropped)
    assert 0.0 < stats["availability"] < 1.0, stats["availability"]
    assert stats["completion_rate"] == 1.0, stats["completion_rate"]
    classes = [e["classification"] for e in ledger["entries"]]
    assert "replica_loss" in classes, (tag, classes)


def validate_obs_map(stats):
    """The fleet stats map must satisfy the obs serving_fleet field on
    a schema-valid record (v13)."""
    from fms_fsdp_tpu.obs.schema import (
        SCHEMA_FIELDS,
        SCHEMA_VERSION,
        validate_record,
    )

    rec = {}
    for name, (tag, required) in SCHEMA_FIELDS.items():
        if not required:
            continue
        rec[name] = {"int": 0, "float": 0.0, "str": "", "map": {}}[tag]
    rec["schema_version"] = SCHEMA_VERSION
    rec["serving_fleet"] = stats
    errs = validate_record(rec)
    assert not errs, errs


def _journal_handoffs(workdir, tag):
    """Count journaled ``handoff`` events in a run's journal JSONL."""
    n = 0
    with open(os.path.join(workdir, tag, "journal.jsonl")) as f:
        for line in f:
            if json.loads(line).get("event") == "handoff":
                n += 1
    return n


def assert_disagg(tag, out, ref_tokens, tokens, stats, ledger):
    """Disagg-run assertions on top of the shared faulted-run set: the
    fleet really ran split (every request crossed the prefill->decode
    wire, journaled first) and the faulted side's loss was absorbed."""
    assert_faulted(tag, ref_tokens, tokens, stats, ledger)
    assert stats["prefill_replicas"] == 1.0, stats
    assert stats["requests_handed_off"] >= N_REQUESTS, (
        f"[{tag}] only {stats['requests_handed_off']:.0f} handoffs for "
        f"{N_REQUESTS} requests — the fleet did not run disaggregated"
    )
    journaled = _journal_handoffs(out, tag)
    assert journaled >= N_REQUESTS, (tag, journaled)
    print(f"[{tag}] handoffs journaled={journaled} "
          f"bytes={stats['handoff_bytes']:.0f}")


def run_disagg_soak(out):
    """--disagg: a 1-prefill + 2-decode fleet vs the unified reference.

    Token parity of BOTH faulted disagg runs against the unified
    2-replica fleet is the end-to-end proof that handoff pages are
    bit-exact (greedy float32/reference decode re-reads the shipped
    pages verbatim). The two kills land on either side of the wire:

    - **prefill_kill** (replica 0, the only prefill worker, iteration 5
      of its first incarnation): rids whose handoff bytes never reached
      the router's journal requeue as FRESH prompts and re-prefill on
      the relaunched incarnation — mid-handoff loss, zero drops;
    - **decode_kill** (replica 1, iteration 10): rids already past the
      journal requeue WITH their handoff bytes and replay as ``resume``
      on the surviving decode sibling — the prefill worker is never
      re-consulted post-handoff.
    """
    ref_tokens, ref_stats, _, _ = run_fleet("reference", out)
    assert ref_stats["restarts"] == 0, "reference run must be unfaulted"
    assert ref_stats["requests_handed_off"] == 0.0, ref_stats

    pk_tokens, pk_stats, pk_ledger, _ = run_fleet(
        "prefill_kill", out,
        faults="replica_kill:replica=0:step=5:times=1",
        n_replicas=3, prefill=1,
    )
    assert_disagg("prefill_kill", out, ref_tokens, pk_tokens, pk_stats,
                  pk_ledger)

    dk_tokens, dk_stats, dk_ledger, _ = run_fleet(
        "decode_kill", out,
        faults="replica_kill:replica=1:step=10:times=1",
        n_replicas=3, prefill=1,
    )
    assert_disagg("decode_kill", out, ref_tokens, dk_tokens, dk_stats,
                  dk_ledger)

    validate_obs_map(pk_stats)

    summary = {
        "family": FAMILY,
        "mode": "disagg",
        "requests": N_REQUESTS,
        "reference": ref_stats,
        "prefill_kill": pk_stats,
        "decode_kill": dk_stats,
        "zero_drops": True,
        "token_parity": True,
    }
    with open(os.path.join(out, "fleet_soak_disagg.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print("disagg chaos soak PASSED: zero drops, token parity vs "
          "unified, prefill-kill availability "
          f"{pk_stats['availability']:.4f}, decode-kill availability "
          f"{dk_stats['availability']:.4f}")


def _assert_migrated_not_recomputed(out, tag):
    """The drain proof lives in the journal: every rid with a
    ``migrate`` event must resume through its re-journaled bytes —
    assign + complete afterwards, with NO recompute-path event
    (returned/requeue/reprefill) in between."""
    events_by_rid = {}
    with open(os.path.join(out, tag, "journal.jsonl")) as f:
        for line in f:
            ev = json.loads(line)
            events_by_rid.setdefault(ev.get("rid"), []).append(ev)
    migrated = [
        rid for rid, evs in events_by_rid.items()
        if any(e["event"] == "migrate" for e in evs)
    ]
    assert migrated, f"[{tag}] no stream migrated — drain landed idle"
    recompute_kinds = {"returned", "requeue", "reprefill"}
    for rid in migrated:
        evs = events_by_rid[rid]
        after = evs[
            max(i for i, e in enumerate(evs) if e["event"] == "migrate")
            + 1:
        ]
        kinds = [e["event"] for e in after]
        assert "complete" in kinds, (tag, rid, kinds)
        bad = recompute_kinds.intersection(kinds)
        assert not bad, (
            f"[{tag}] rid {rid} fell back to recompute ({sorted(bad)}) "
            f"after its migrate frame — zero-recompute drain violated"
        )
    return migrated


def run_transport_soak(out):
    """--transport: the chunked wire under corruption/loss, mid-transfer
    kills on both wire sides, and a SIGTERM drain-and-migrate of a
    hybrid-mamba replica. Five runs (see module docstring)."""
    global MODEL_CFG, FAMILY
    # small chunks + a tight in-flight cap force every handoff across
    # multiple pump cycles, so faults land MID-transfer, not between
    # transfers; chunk counters are per-sender, so each transfer must
    # span more chunks than the largest every= below for a fault to be
    # guaranteed to land on it (tiny-model handoffs are ~8 KiB)
    tkw = {
        "transport_chunk_bytes": 1024,
        "transport_inflight_bytes": 4 * 1024,
    }
    ref_tokens, ref_stats, _, _ = run_fleet("reference", out)
    assert ref_stats["restarts"] == 0, "reference run must be unfaulted"

    # 1. both wire directions corrupted AND lossy: the ".tx" label
    # substring matches the replica-side (repN.tx) and router-side
    # (rtrN.tx) chunk senders; the router process needs the spec
    # configured in-process (router_faults), the replicas get it by env
    wire_spec = ("handoff_chunk_corrupt:transport=.tx:every=5;"
                 "handoff_chunk_drop:transport=.tx:every=7")
    cr_tokens, cr_stats, _, _ = run_fleet(
        "chunk_chaos", out, faults=wire_spec, router_faults=wire_spec,
        n_replicas=3, prefill=1, fleet_kw=tkw,
    )
    for rid, toks in ref_tokens.items():
        assert cr_tokens[rid] == toks, (
            f"[chunk_chaos] rid {rid} tokens diverged under chunk "
            f"corruption/loss:\n  ref: {toks}\n  got: {cr_tokens[rid]}"
        )
    assert cr_stats["requests_handed_off"] >= N_REQUESTS, cr_stats
    # the healing is measured, not incidental: resume-direction
    # transfers retried, resent chunks, and completed as resumed
    assert cr_stats["chunks_resent"] > 0, cr_stats
    assert cr_stats["handoff_retries"] > 0, cr_stats
    assert cr_stats["transfers_resumed"] > 0, cr_stats
    print(f"[chunk_chaos] retries={cr_stats['handoff_retries']:.0f} "
          f"chunks_resent={cr_stats['chunks_resent']:.0f} "
          f"transfers_resumed={cr_stats['transfers_resumed']:.0f}")

    # 2./3. mid-transfer kill on both wire sides: the prefill worker
    # dies while SHIPPING chunked handoffs, a decode worker dies while
    # RECEIVING chunked resumes — both requeue exactly-once
    pk_tokens, pk_stats, pk_ledger, _ = run_fleet(
        "prefill_kill", out,
        faults="replica_kill:replica=0:step=5:times=1",
        n_replicas=3, prefill=1, fleet_kw=tkw,
    )
    assert_disagg("prefill_kill", out, ref_tokens, pk_tokens, pk_stats,
                  pk_ledger)
    dk_tokens, dk_stats, dk_ledger, _ = run_fleet(
        "decode_kill", out,
        faults="replica_kill:replica=1:step=10:times=1",
        n_replicas=3, prefill=1, fleet_kw=tkw,
    )
    assert_disagg("decode_kill", out, ref_tokens, dk_tokens, dk_stats,
                  dk_ledger)

    # 4./5. drain-and-migrate on a hybrid-mamba fleet: the preempted
    # replica packs each live stream through the SLAB codec (conv
    # window + fp32 SSD state + hybrid pages) and ships it to the
    # sibling — planned eviction, zero recompute
    llama_cfg, llama_family = MODEL_CFG, FAMILY
    MODEL_CFG, FAMILY = MODEL_CFGS["mamba"], "mamba"
    try:
        mref_tokens, mref_stats, _, _ = run_fleet("mamba_reference", out)
        assert mref_stats["restarts"] == 0, mref_stats

        preempted = []

        def preempt_once(router):
            if preempted:
                return
            counts = router.journal.counts()
            live = router.supervisor.live_indices()
            if 1 not in live or counts["completed"] < 1:
                return  # let the fleet warm up past the first compile
            if router.journal.inflight(router.supervisor.run_id(1)) >= 2:
                router.preempt(1)
                preempted.append(True)

        dr_tokens, dr_stats, dr_ledger, _ = run_fleet(
            "mamba_drain", out, fleet_kw=tkw, on_poll=preempt_once,
        )
        assert preempted, "[mamba_drain] the wave finished before the " \
                          "preempt trigger armed — raise FLEET_SOAK_REQUESTS"
        for rid, toks in mref_tokens.items():
            assert dr_tokens[rid] == toks, (
                f"[mamba_drain] rid {rid} tokens diverged after "
                f"drain-and-migrate:\n  ref: {toks}\n  got: {dr_tokens[rid]}"
            )
        assert dr_stats["drain_migrations"] >= 1, dr_stats
        classes = [e["classification"] for e in dr_ledger["entries"]]
        assert "preempted" in classes, (classes, "SIGTERM did not "
                                        "classify as a planned eviction")
        migrated = _assert_migrated_not_recomputed(out, "mamba_drain")
        print(f"[mamba_drain] migrated={len(migrated)} rids "
              f"{sorted(migrated)} drain_migrations="
              f"{dr_stats['drain_migrations']:.0f}")
    finally:
        MODEL_CFG, FAMILY = llama_cfg, llama_family

    validate_obs_map(cr_stats)
    validate_obs_map(dr_stats)

    summary = {
        "mode": "transport",
        "requests": N_REQUESTS,
        "reference": ref_stats,
        "chunk_chaos": cr_stats,
        "prefill_kill": pk_stats,
        "decode_kill": dk_stats,
        "mamba_reference": mref_stats,
        "mamba_drain": dr_stats,
        "zero_drops": True,
        "token_parity": True,
    }
    with open(os.path.join(out, "fleet_soak_transport.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print("transport chaos soak PASSED: zero drops + token parity under "
          "chunk corruption/loss "
          f"(retries={cr_stats['handoff_retries']:.0f}), mid-transfer "
          "kills on both wire sides, and mamba drain-and-migrate "
          f"(migrations={dr_stats['drain_migrations']:.0f})")


def _write_speculator(out):
    """Random-init serving speculator checkpoint for the --speculative
    schedule. The soak pins PARITY (speculative greedy == plain greedy
    under churn), never speed — a random head keeps the accept rate
    near zero while every draft still flows through the verify/accept
    path, which is exactly the mechanism under test."""
    import jax

    from fms_fsdp_tpu.models.speculator import (
        SpeculatorConfig,
        init_speculator_params,
        save_speculator,
    )

    scfg = SpeculatorConfig(
        emb_dim=MODEL_CFG["emb_dim"],
        inner_dim=32,
        vocab_size=MODEL_CFG["src_vocab_size"],
        n_predict=3,
    )
    path = os.path.join(out, "speculator.pkl")
    save_speculator(
        path, init_speculator_params(jax.random.PRNGKey(7), scfg), scfg
    )
    return path


def run_speculative_soak(out):
    """--speculative: the kill/stall schedule on a speculative llama
    fleet, token-parity-checked against the PLAIN fleet's reference
    run. Three runs:

    1. **reference**: the unfaulted NON-speculative fleet — the greedy
       baseline every later run must reproduce;
    2. **spec_reference**: the unfaulted speculative fleet — isolates
       the draft/verify/accept parity claim from churn;
    3. **kill** / **stall**: the faulted speculative fleet — a requeued
       request's recompute-on-resume re-prefills (re-stashing the draft
       embedding) and re-drafts on the survivor, and must still emit
       the plain fleet's exact tokens.
    """
    ref_tokens, ref_stats, _, _ = run_fleet("reference", out)
    assert ref_stats["restarts"] == 0, "reference run must be unfaulted"

    spec_cfg = dict(SERVE_CFG, speculator_path=_write_speculator(out))
    spec_tokens, spec_stats, _, _ = run_fleet(
        "spec_reference", out, serve_cfg=spec_cfg, stall_timeout=30.0
    )
    assert spec_stats["restarts"] == 0, "spec reference must be unfaulted"
    for rid, toks in ref_tokens.items():
        assert spec_tokens[rid] == toks, (
            f"[spec_reference] rid {rid} speculative greedy diverged "
            f"from plain greedy:\n  ref: {toks}\n  got: {spec_tokens[rid]}"
        )

    kill_tokens, kill_stats, kill_ledger, _ = run_fleet(
        "spec_kill", out,
        faults="replica_kill:replica=1:step=10:times=1",
        serve_cfg=spec_cfg, stall_timeout=30.0,
    )
    assert_faulted("spec_kill", ref_tokens, kill_tokens, kill_stats,
                   kill_ledger)

    stall_tokens, stall_stats, stall_ledger, _ = run_fleet(
        "spec_stall", out,
        faults="replica_stall:replica=0:step=10:seconds=600:times=1",
        serve_cfg=spec_cfg, stall_timeout=30.0,
    )
    assert_faulted("spec_stall", ref_tokens, stall_tokens, stall_stats,
                   stall_ledger)
    assert stall_stats["stalls_detected"] >= 1, (
        "watchdog never fired on the stalled replica"
    )

    validate_obs_map(kill_stats)

    summary = {
        "family": FAMILY,
        "mode": "speculative",
        "requests": N_REQUESTS,
        "reference": ref_stats,
        "spec_reference": spec_stats,
        "kill": kill_stats,
        "stall": stall_stats,
        "zero_drops": True,
        "token_parity": True,
    }
    with open(os.path.join(out, "fleet_soak_speculative.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print("speculative chaos soak PASSED: zero drops, speculative "
          "greedy token parity vs plain fleet, kill availability "
          f"{kill_stats['availability']:.4f}, stall availability "
          f"{stall_stats['availability']:.4f}")


def main():
    global MODEL_CFG, FAMILY
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="",
                    help="artifact dir (default: a temp dir)")
    ap.add_argument("--family", default="llama",
                    choices=sorted(MODEL_CFGS),
                    help="fleet model family: llama (paged KV) or "
                         "hybrid mamba (slab + one attn layer)")
    ap.add_argument("--disagg", action="store_true",
                    help="soak a disaggregated fleet (1 prefill + 2 "
                         "decode replicas, journaled KV-page handoff) "
                         "with kills on either side of the wire, "
                         "instead of the unified kill/stall schedule")
    ap.add_argument("--speculative", action="store_true",
                    help="soak a speculative llama fleet (random-init "
                         "MLPSpeculator draft/verify on every replica) "
                         "and assert greedy token parity against the "
                         "plain fleet's reference run")
    ap.add_argument("--transport", action="store_true",
                    help="soak the chunked state-transfer wire: chunk "
                         "corruption/loss on both directions, "
                         "mid-transfer kills on both wire sides, and a "
                         "SIGTERM drain-and-migrate of a hybrid-mamba "
                         "replica (slab codec, zero recompute)")
    args = ap.parse_args()
    MODEL_CFG = MODEL_CFGS[args.family]
    FAMILY = args.family
    if args.disagg and args.family != "llama":
        ap.error("--disagg requires --family llama (the mamba slab "
                 "codec is exercised by the --transport schedule's "
                 "drain-and-migrate leg instead)")
    if args.speculative and args.family != "llama":
        ap.error("--speculative requires --family llama (the "
                 "MLPSpeculator draft/verify loop is llama-only)")
    if args.speculative and args.disagg:
        ap.error("--speculative and --disagg are mutually exclusive: a "
                 "speculative engine rejects handoff resumes (the draft "
                 "embedding is not part of the page handoff)")
    if args.transport and (args.disagg or args.speculative
                           or args.family != "llama"):
        ap.error("--transport is its own schedule (it runs disagg-llama "
                 "wire legs AND a mamba drain leg internally); pass it "
                 "alone")
    out = args.out or tempfile.mkdtemp(prefix=f"fleet_soak_{FAMILY}_")
    os.makedirs(out, exist_ok=True)
    if args.transport:
        print(f"transport chaos soak -> {out}")
        run_transport_soak(out)
        return
    if args.disagg:
        print(f"disagg serving chaos soak ({FAMILY} fleet) -> {out}")
        run_disagg_soak(out)
        return
    if args.speculative:
        print(f"speculative serving chaos soak ({FAMILY} fleet) -> {out}")
        run_speculative_soak(out)
        return
    print(f"serving chaos soak ({FAMILY} fleet) -> {out}")

    ref_tokens, ref_stats, _, ref_wall = run_fleet("reference", out)
    assert ref_stats["restarts"] == 0, "reference run must be unfaulted"

    kill_tokens, kill_stats, kill_ledger, _ = run_fleet(
        "kill", out, faults="replica_kill:replica=1:step=10:times=1"
    )
    assert_faulted("kill", ref_tokens, kill_tokens, kill_stats,
                   kill_ledger)
    # the injected death must classify through the registry code (10),
    # not as a generic error
    kill_classes = [
        (e["exit_code"], e["classification"])
        for e in kill_ledger["entries"]
    ]
    assert (10, "replica_loss") in kill_classes, kill_classes

    stall_tokens, stall_stats, stall_ledger, _ = run_fleet(
        "stall", out,
        faults="replica_stall:replica=0:step=10:seconds=600:times=1",
    )
    assert_faulted("stall", ref_tokens, stall_tokens, stall_stats,
                   stall_ledger)
    assert stall_stats["stalls_detected"] >= 1, (
        "watchdog never fired on the stalled replica"
    )

    validate_obs_map(kill_stats)

    summary = {
        "family": FAMILY,
        "requests": N_REQUESTS,
        "reference": {"wall_s": round(ref_wall, 2), **ref_stats},
        "kill": kill_stats,
        "stall": stall_stats,
        "zero_drops": True,
        "token_parity": True,
    }
    with open(os.path.join(out, "fleet_soak.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print("serving chaos soak PASSED: zero drops, token parity, "
          f"kill availability {kill_stats['availability']:.4f}, "
          f"stall availability {stall_stats['availability']:.4f}")


if __name__ == "__main__":
    main()
