"""Fleet checkpoint scrubber CLI: full-content re-verification of every
committed checkpoint under one or more checkpoint roots, with quarantine
of corrupt step dirs and digest-cached verdicts.

The in-run scrubber (``scrub_interval_steps``, resilience/scrub.py)
covers live training; this CLI is the fleet/cron form of the same pass —
point it at the checkpoints/ folders of the runs you care about (both
tiers) and it:

- verifies every committed ``step_N_ckp`` against its manifest,
  including the version-2 chunked content checksums for large shards;
- **quarantines** a failing dir (``integrity_quarantine.json`` sidecar
  + one actionable line naming the bad shard/chunk) so every resume and
  fallback walk skips it before a crash needs it;
- **caches** passing verdicts by manifest digest
  (``integrity_scrub.json``), so the next sweep — or the next restore —
  re-hashes nothing that hasn't changed;
- exits nonzero when anything is (or already was) quarantined, so a
  cron wrapper can page.

Examples::

    python scripts/scrub_checkpoints.py /data/run1/ckpt/checkpoints \\
        /local/run1/ckpt/checkpoints
    python scripts/scrub_checkpoints.py --release /data/.../step_80_ckp
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "roots",
        nargs="*",
        help="checkpoint roots (the checkpoints/ folders; every "
        "committed step_N_ckp under each is scrubbed)",
    )
    ap.add_argument(
        "--release",
        action="append",
        default=[],
        metavar="STEP_DIR",
        help="remove the quarantine marker from a step dir (after "
        "repair, or to deliberately accept it); may repeat",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="emit a machine-readable summary line on stdout",
    )
    args = ap.parse_args(argv)
    if not args.roots and not args.release:
        ap.error("nothing to do: pass checkpoint roots and/or --release")

    from fms_fsdp_tpu.resilience.scrub import (
        committed_step_dirs,
        is_quarantined,
        quarantine_info,
        release_quarantine,
        scrub_checkpoint,
    )

    release_failed = False
    for path in args.release:
        if release_quarantine(path):
            print(f"released quarantine on {path}")
        elif is_quarantined(path):
            # False + still quarantined = the marker removal itself
            # failed (storage flake / read-only): the dir is NOT
            # released and the operator must not read this as a typo
            release_failed = True
            print(
                f"FAILED to remove the quarantine marker on {path} "
                "(storage error?); the dir is still quarantined",
                file=sys.stderr,
            )
        else:
            print(f"no quarantine marker on {path} (nothing to release)")

    summary = {"verified": 0, "quarantined": 0, "legacy": 0, "dirs": []}
    for root in args.roots:
        dirs = committed_step_dirs(root)
        if not dirs:
            print(f"{root}: no committed checkpoints")
            continue
        for ckpt_dir in dirs:
            status, problems = scrub_checkpoint(ckpt_dir)
            summary[status] += 1
            summary["dirs"].append({"dir": ckpt_dir, "status": status})
            if status == "quarantined":
                info = quarantine_info(ckpt_dir) or {}
                first = (problems or info.get("problems") or ["?"])[0]
                print(f"QUARANTINED {ckpt_dir}: {first}")
            else:
                print(f"{status:10s} {ckpt_dir}")
    if args.json:
        print(json.dumps(summary))
    return 1 if (summary["quarantined"] or release_failed) else 0


if __name__ == "__main__":
    sys.exit(main())
