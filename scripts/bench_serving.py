"""Serving-engine benchmark -> BENCH_SERVING.json.

Drives the continuous-batching engine (fms_fsdp_tpu/serve/) end to end
— submit a request wave, run admission / prefill-decode interleave /
completion — and reports the three serving headline numbers:

- ``tokens_per_sec``: decode throughput (generated tokens / decode wall);
- ``ttft_s``: time-to-first-token (mean / p50 / p99 over requests —
  queue wait included: a request admitted behind a full batch pays it,
  which is exactly what the metric is for);
- ``p99_latency_s``: p99 end-to-end request latency.

The default run emits one steady-state row per model family
(llama / mamba / mixtral — serve/families/), each carrying ``family``
and ``state_bytes_per_stream`` (mamba's constant decode slab; 0 for
paged-KV-only families); ``--family X`` benches one family alone.
Every row additionally reports per-request ``availability``
(completed / submitted), so the steady-state rows and the
``fleet-under-churn`` row (a 2-replica fleet with one replica
hard-killed mid-stream — serve/fleet.py) share one schema; the churn
row also carries ``replica_availability`` (the restart-ledger capacity
metric, < 1.0 under churn) and the relaunch/requeue counts.

PR 18 rows (every row now carries ``serve_layout``, "" = single-chip):

- ``sharded``: the llama steady-state wave on a ``serve_layout=tp=2``
  replica (parallel/sharding.py serving mesh; on a TPU-less host the
  mesh comes from 8 forced host-platform CPU devices, so the number is
  a CPU-relative but measured sharded-step cost);
- ``fleet-unified`` / ``fleet-disagg``: the SAME mixed wave — short
  prompts with long-prompt interferers — on a 3-replica unified fleet
  vs a disaggregated one (1 prefill + 2 decode,
  ``FleetConfig.prefill_replicas``; serve/disagg/). Their ``ttft_s``
  is computed over the SHORT requests only: the pair quantifies what
  moving interferer prefill off the decode path buys p99 TTFT. The
  disagg row carries the handoff ledger (``requests_handed_off``,
  ``handoff_bytes``, ``prefill_replicas``).

PR 19 rows (every row now carries ``spec_accept_rate`` /
``spec_draft_tokens`` / ``prefill_chunks`` / ``paged_kernel_impl``):

- ``speculative``: the llama steady-state wave with a bench-distilled
  MLPSpeculator (train_bench_speculator — fit on the base model's own
  greedy continuations so the row measures real acceptance, not a
  random head's ~0). Greedy accept keeps the emitted stream
  token-identical to the plain llama row; ``spec_accept_rate`` is the
  fraction of drafted tokens the base kept;
- ``kernel-v2-int8``: the int8-paged wave decoded through the v2
  paged-attention kernel (multi-page DMA, native quantized page reads
  with in-kernel dequantize — ``paged_kernel_impl: 2``); interpret-mode
  on a TPU-less host;
- ``long-prompt-whole`` / ``long-prompt-chunked``: the same mixed wave
  (long interferer prompts ahead of short requests) on one engine,
  whole-prompt prefill vs ``prefill_chunk_tokens=16``. TTFT covers the
  shorts only — the pair quantifies what decode-interleaved chunked
  prefill buys p99 TTFT on a single replica.

PR 20 rows (the chunked state-transfer wire, serve/disagg/transport.py):

- ``fleet-disagg-clean`` / ``fleet-disagg-chunkloss``: the disagg
  mixed wave with resume handoffs forced through 1 KiB chunks, clean
  vs ~1% router-side chunk corruption (every corrupted chunk fails its
  receiver CRC and is retransmitted after backoff). The pair measures
  what wire-level healing costs tokens/s and short-request p99 TTFT;
  the chunkloss row carries the measured retry ledger
  (``handoff_retries`` / ``chunks_resent`` / ``bytes_resent``).

Fallback-tier contract (bench.py's): the engine measures on whatever
backend answers — on a TPU-less host the numbers are CPU-relative but
MEASURED, so the record carries ``degraded: false`` with
``fallback_backend`` naming the backend (never a dark vs_baseline:null
row). ``--dry-run`` validates the output schema with no device and no
jax import (the CI smoke): it emits a zeroed, schema-valid document and
exits nonzero if validation fails.

Env knobs: BENCH_SERVING_REQUESTS / _PROMPT / _NEW / _BATCH / _SEQ.
"""

import argparse
import dataclasses
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

REQUESTS = int(os.environ.get("BENCH_SERVING_REQUESTS", "8"))
PROMPT = int(os.environ.get("BENCH_SERVING_PROMPT", "32"))
NEW = int(os.environ.get("BENCH_SERVING_NEW", "16"))
BATCH = int(os.environ.get("BENCH_SERVING_BATCH", "4"))
SEQ = int(os.environ.get("BENCH_SERVING_SEQ", "256"))

_REQUIRED = {
    "metric": str,
    "backend": str,
    "degraded": bool,
    "rows": list,
    "tokens_per_sec": (int, float),
    "ttft_s": dict,
    "p99_latency_s": (int, float),
}
_ROW_REQUIRED = {
    "family": str,
    "max_batch": int,
    "requests": int,
    "prompt_len": int,
    "max_new_tokens": int,
    "page_size": int,
    "kv_quant": str,
    "tokens_per_sec": (int, float),
    "ttft_s": dict,
    "p50_latency_s": (int, float),
    "p99_latency_s": (int, float),
    "requests_completed": int,
    "requests_evicted": int,
    "kv_pages_peak": int,
    # decode-state slab bytes one stream holds (mamba's constant-memory
    # number; 0 for families whose whole state is paged KV)
    "state_bytes_per_stream": (int, float),
    # per-request availability = completed / submitted, on EVERY row —
    # steady-state rows and the fleet-under-churn row share one
    # schema. Under churn the fleet's zero-drop contract keeps this at
    # 1.0 while the row's replica_availability records the capacity
    # actually lost to the injected death (< 1.0).
    "availability": (int, float),
    # replica parallel layout ("" = single-chip, "tp=2" = 2-way tensor
    # sharding — ServeConfig.serve_layout); fleet rows report the
    # layout their replicas ran
    "serve_layout": str,
    # PR 19 raw-speed fields, on EVERY row so the speculative /
    # chunked / kernel-v2 rows and the steady-state rows share one
    # schema: accepted-draft fraction (0.0 = speculation off or
    # nothing accepted), draft tokens per verify step (0 =
    # non-speculative), chunked-prefill slices advanced (0 =
    # whole-prompt), and the paged-attention kernel generation engaged
    # (0 = reference gather, 1 = kernel v1 single-page, 2 = kernel v2
    # multi-page / quantized-native)
    "spec_accept_rate": (int, float),
    "spec_draft_tokens": int,
    "prefill_chunks": int,
    "paged_kernel_impl": int,
}


def validate_result(doc) -> list:
    """Schema violations of one BENCH_SERVING document (empty = valid).
    The acceptance contract: tokens/s, TTFT, and p99 fields present and
    typed, on every row and the headline."""
    errs = []
    if not isinstance(doc, dict):
        return ["document is not an object"]
    for k, t in _REQUIRED.items():
        if k not in doc:
            errs.append(f"missing {k!r}")
        elif not isinstance(doc[k], t):
            errs.append(f"{k!r} is not {t}")
    if doc.get("backend") != "tpu" and "fallback_backend" not in doc:
        errs.append("non-TPU record must name fallback_backend")
    for f in ("mean", "p50", "p99"):
        if not isinstance(doc.get("ttft_s", {}).get(f), (int, float)):
            errs.append(f"ttft_s.{f} missing or not a number")
    for i, row in enumerate(doc.get("rows") or [{}]):
        for k, t in _ROW_REQUIRED.items():
            if not isinstance(row.get(k), t):
                errs.append(f"rows[{i}].{k} missing or not {t}")
    return errs


def _pct(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def _zero_doc():
    """A schema-shaped all-zero document (the --dry-run artifact)."""
    row = {k: (0 if t is int else 0.0) for k, t in _ROW_REQUIRED.items()}
    row.update(
        family="llama",
        kv_quant="none",
        serve_layout="",
        ttft_s={"mean": 0.0, "p50": 0.0, "p99": 0.0},
    )
    return {
        "metric": "serving engine throughput/latency",
        "mode": "dry_run",
        "backend": "none",
        "degraded": True,
        "fallback_backend": "none",
        "rows": [row],
        "tokens_per_sec": 0.0,
        "ttft_s": {"mean": 0.0, "p50": 0.0, "p99": 0.0},
        "p99_latency_s": 0.0,
    }


def run_row(params, cfg, max_batch, n_requests, prompt_len, max_new,
            kv_quant="none", serve_layout="", mode="", attn_impl="auto",
            speculator_path="", prefill_chunk_tokens=0, wave=None,
            ttft_idx=None, seq=0):
    """One engine row. ``wave`` overrides the uniform random wave
    ([(prompt, max_new), ...] — the long-prompt pair's mixed shape);
    ``ttft_idx`` narrows the TTFT percentiles to a sub-wave; ``seq``
    overrides the engine's max_seq_len (the long-prompt pair's larger
    context)."""
    import numpy as np

    from fms_fsdp_tpu.serve import ServeConfig, ServingEngine

    scfg = ServeConfig(
        max_batch=max_batch,
        max_seq_len=seq or SEQ,
        kv_quant=kv_quant,
        serve_layout=serve_layout,
        attn_impl=attn_impl,
        speculator_path=speculator_path,
        prefill_chunk_tokens=prefill_chunk_tokens,
    )
    eng = ServingEngine(params, cfg, scfg)
    if wave is None:
        rng = np.random.default_rng(0)
        vocab = getattr(cfg, "src_vocab_size", None) or cfg.vocab_size
        wave = [
            (p.tolist(), max_new)
            for p in rng.integers(0, vocab, size=(n_requests, prompt_len))
        ]
    # warmup wave: compiles prefill + decode; the wall/token accounting
    # is reset after so compile time never pollutes the measured rate
    for p, n in wave:
        eng.submit(p, n)
    eng.run()
    eng._decode_tokens = 0
    eng._decode_wall = 0.0
    eng._spec_draft_total = 0
    eng._spec_accept_total = 0
    eng._prefill_chunks = 0
    reqs = [eng.submit(p, n) for p, n in wave]
    pages_peak = 0
    while eng.has_work():
        eng.step()
        pages_peak = max(pages_peak, eng.adapter.pages_in_use)
    ttfts = [
        r.ttft
        for r in ([reqs[i] for i in ttft_idx] if ttft_idx else reqs)
        if r.ttft is not None
    ]
    lats = [r.latency for r in reqs if r.latency is not None]
    tok_s = (
        eng._decode_tokens / eng._decode_wall if eng._decode_wall else 0.0
    )
    st = eng.serving_stats()
    row = {
        "family": eng.family,
        "max_batch": max_batch,
        "requests": len(wave),
        "prompt_len": prompt_len,
        "max_new_tokens": max_new,
        "page_size": eng.page_size,
        "kv_quant": kv_quant,
        "serve_layout": serve_layout,
        "tokens_per_sec": round(tok_s, 1),
        "ttft_s": {
            "mean": round(sum(ttfts) / max(1, len(ttfts)), 4),
            "p50": round(_pct(ttfts, 0.5), 4),
            "p99": round(_pct(ttfts, 0.99), 4),
        },
        "p50_latency_s": round(_pct(lats, 0.5), 4),
        "p99_latency_s": round(_pct(lats, 0.99), 4),
        # measured wave only (the scheduler's counters also hold the
        # warmup wave); evicted counts REQUESTS that were evicted at
        # least once, not eviction events
        "requests_completed": sum(r.state == "finished" for r in reqs),
        "requests_evicted": sum(r.evictions > 0 for r in reqs),
        "kv_pages_peak": int(pages_peak),
        "state_bytes_per_stream": int(eng.adapter.state_bytes_per_stream),
        "availability": round(
            sum(r.state == "finished" for r in reqs) / max(1, len(reqs)),
            4,
        ),
        # PR 19 raw-speed fields (measured wave; serving_stats v14)
        "spec_accept_rate": round(float(st["spec_accept_rate"]), 4),
        "spec_draft_tokens": int(st["spec_draft_tokens"]),
        "prefill_chunks": int(st["prefill_chunks"]),
        "paged_kernel_impl": int(st["paged_kernel_impl"]),
    }
    if mode:
        row["mode"] = mode
    return row


def train_bench_speculator(params, cfg, path, n_predict=3, steps=400):
    """Mini-distill an MLPSpeculator onto the base model's own greedy
    continuations of the bench wave (seconds on CPU), so the speculative
    row measures a real acceptance rate — a random-init head accepts
    ~0 drafts and would bench the overhead, not the feature. The
    serving engine only guarantees parity, never quality, so the bench
    must bring a speculator that actually speculates."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fms_fsdp_tpu.models.generation import decode_step, prefill
    from fms_fsdp_tpu.models.speculator import (
        SpeculatorConfig,
        init_speculator_params,
        save_speculator,
        speculator_forward,
    )

    rng = np.random.default_rng(0)
    vocab = cfg.src_vocab_size
    toks = jnp.asarray(
        rng.integers(0, vocab, size=(REQUESTS, PROMPT)), jnp.int32
    )
    # teacher trace: greedy-decode the exact bench wave, keeping every
    # position's hidden state (bfloat16 — the serving compute dtype, so
    # the distilled chain sees the embeddings it will see in the engine)
    logits, embeds, cache = jax.jit(
        functools.partial(prefill, cfg=cfg, max_seq_len=SEQ,
                          full_logits=True)
    )(params, toks)
    step = jax.jit(functools.partial(decode_step, cfg=cfg))
    all_toks, all_embeds = [toks], [embeds]
    tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
    for pos in range(PROMPT, PROMPT + NEW):
        all_toks.append(tok[:, None])
        lg, em, cache = step(params, cache, tok[:, None], pos)
        all_embeds.append(em[:, None])
        tok = jnp.argmax(lg, -1).astype(jnp.int32)
    T = jnp.concatenate(all_toks, 1)  # (B, P+NEW)
    E = jnp.concatenate(all_embeds, 1).astype(jnp.float32)

    # teacher-forced chain loss: window t's state is the embed that
    # predicted token t+1, head i feeds token t+1+i and targets t+2+i —
    # exactly speculator_propose's inference alignment
    n = n_predict
    n_win = T.shape[1] - n - 1
    state, inds = E[:, :n_win], T[:, 1 : n + n_win]
    targets = jnp.stack(
        [T[:, 2 + i : 2 + i + n_win] for i in range(n)], 0
    )  # (n, B, N)

    scfg = SpeculatorConfig(
        emb_dim=cfg.emb_dim, inner_dim=cfg.emb_dim, vocab_size=vocab,
        n_predict=n,
    )
    sp = init_speculator_params(jax.random.PRNGKey(1), scfg)

    def loss_fn(p):
        lp = jax.nn.log_softmax(
            speculator_forward(p, state, inds, scfg).astype(jnp.float32)
        )
        return -jnp.take_along_axis(lp, targets[..., None], -1).mean()

    # inline Adam (the container ships no optimizer lib; 20 lines beats
    # a dependency for a 400-step fit)
    m = jax.tree.map(jnp.zeros_like, sp)
    v = jax.tree.map(jnp.zeros_like, sp)

    @jax.jit
    def update(p, m, v, t):
        g = jax.grad(loss_fn)(p)
        m = jax.tree.map(lambda a, b: 0.9 * a + 0.1 * b, m, g)
        v = jax.tree.map(lambda a, b: 0.999 * a + 0.001 * b * b, v, g)
        p = jax.tree.map(
            lambda a, mm, vv: a - 2e-3
            * (mm / (1 - 0.9**t))
            / (jnp.sqrt(vv / (1 - 0.999**t)) + 1e-8),
            p, m, v,
        )
        return p, m, v

    for t in range(1, steps + 1):
        sp, m, v = update(sp, m, v, t)
    save_speculator(path, sp, scfg)
    return path


def run_longprompt_rows(params, cfg):
    """``long-prompt-whole`` vs ``long-prompt-chunked``: the same mixed
    wave — long-prompt interferers submitted ahead of short requests —
    on ONE engine, whole-prompt prefill vs ``prefill_chunk_tokens``.
    Both rows' ``ttft_s`` covers the SHORT requests only: the pair is
    the measured answer to "what does slicing interferer prefill into
    decode-interleaved chunks buy p99 TTFT" (the single-replica twin of
    the fleet-unified/fleet-disagg pair).

    The pair runs a 4x-SEQ context (head-of-line blocking only shows up
    when one whole-prompt prefill costs many decode steps of wall, and
    the prefill's attention term is quadratic in prompt length — at the
    steady-state rows' scale the effect drowns in per-step dispatch
    overhead) and a batch wide enough to seat the whole wave: with
    starved slots, chunking's longer slot-hold on the interferers
    delays the LAST shorts' admission and muddies the p99 it exists to
    cut — the pair isolates prefill head-of-line blocking, not slot
    capacity (the oversubscribed row owns that axis)."""
    import dataclasses as _dc

    import numpy as np

    rng = np.random.default_rng(0)
    vocab = cfg.src_vocab_size
    seq = 4 * SEQ
    cfg = _dc.replace(cfg, max_expected_seq_len=seq)  # params are
    # shape-independent of the rope horizon, so the steady-state
    # weights serve the longer context unchanged
    long_len = min(3 * SEQ, seq - NEW - 1)
    n_long = max(2, REQUESTS // 2)
    wave, short_idx = [], []
    for _ in range(n_long):
        wave.append((rng.integers(0, vocab, size=long_len).tolist(), NEW))
    for _ in range(REQUESTS):
        short_idx.append(len(wave))
        wave.append((rng.integers(0, vocab, size=8).tolist(), NEW))

    rows = []
    for mode, chunk in (
        ("long-prompt-whole", 0),
        ("long-prompt-chunked", 64),
    ):
        row = run_row(
            params, cfg, len(wave), len(wave), 8, NEW, mode=mode,
            prefill_chunk_tokens=chunk, wave=wave, ttft_idx=short_idx,
            seq=seq,
        )
        row["interferer_prompt_len"] = long_len
        row["interferers"] = len(wave) - len(short_idx)
        rows.append(row)
    return rows


def _run_fleet(model_cfg_dict, wave, faults="", n_replicas=2, prefill=0,
               prefix="bench_fleet_", router_faults="", fleet_kw=None):
    """Drive one fleet over ``wave`` ([(prompt, max_new), ...]).
    Returns (records_in_submit_order, stats, wall_s).

    ``router_faults`` configures fault sites in THIS process (the
    router-side chunk senders live here; ``faults`` only reaches the
    replica subprocesses by env). ``fleet_kw`` is folded into
    FleetConfig — the transport chunk/inflight knobs."""
    import tempfile
    import time as _time

    from fms_fsdp_tpu.resilience.faults import configure_faults
    from fms_fsdp_tpu.serve.fleet import (
        FleetConfig,
        FleetRouter,
        make_subprocess_spawn,
    )

    serve_cfg = {
        "max_batch": BATCH,
        "max_seq_len": SEQ,
        "page_size": 16,
        "prefill_bucket": 8,
        "max_prefill_per_step": 1,
    }
    wdir = tempfile.mkdtemp(prefix=prefix)
    spawn = make_subprocess_spawn(
        wdir,
        model_cfg_dict,
        serve_cfg,
        init_seed=0,
        faults=faults,
        prefill_replicas=prefill,
    )
    cfg = FleetConfig(
        n_replicas=n_replicas,
        prefill_replicas=prefill,
        max_seq_len=SEQ,
        max_inflight_per_replica=BATCH,
        stall_timeout_s=30.0,
        startup_timeout_s=300.0,
        restart_backoff_s=0.2,
        ledger_path=os.path.join(wdir, "ledger.json"),
        **(fleet_kw or {}),
    )
    router = FleetRouter(spawn, cfg)
    configure_faults(router_faults)
    try:
        router.start()
        t0 = _time.monotonic()
        rids = [router.submit(p, n) for p, n in wave]
        router.run_until_idle(timeout_s=600.0)
        wall = _time.monotonic() - t0
        stats = router.stats()
        router.drain()
        router.shutdown()
    finally:
        configure_faults("")
    return [router.journal.records[r] for r in rids], stats, wall


def _fleet_row(mode, recs, stats, wall, ttft_recs=None):
    """Shared row shape for fleet benches. ``ttft_recs`` narrows the
    TTFT percentiles to a sub-wave (the short requests of the mixed
    wave); latency and throughput always cover the whole wave."""
    lats = [r.latency for r in recs if r.latency is not None]
    ttfts = [
        r.engine_ttft for r in (ttft_recs or recs)
        if r.engine_ttft is not None
    ]
    gen = sum(len(r.tokens) for r in recs if r.tokens)
    completed = sum(r.state == "completed" for r in recs)
    return {
        "mode": mode,
        "family": "llama",
        "max_batch": BATCH,
        "requests": len(recs),
        "prompt_len": PROMPT,
        "max_new_tokens": NEW,
        "page_size": 16,
        "kv_quant": "none",
        "serve_layout": "",
        "tokens_per_sec": round(gen / wall, 1) if wall else 0.0,
        "ttft_s": {
            "mean": round(sum(ttfts) / max(1, len(ttfts)), 4),
            "p50": round(_pct(ttfts, 0.5), 4),
            "p99": round(_pct(ttfts, 0.99), 4),
        },
        "p50_latency_s": round(_pct(lats, 0.5), 4),
        "p99_latency_s": round(_pct(lats, 0.99), 4),
        "requests_completed": completed,
        "requests_evicted": 0,
        "kv_pages_peak": 0,
        "state_bytes_per_stream": 0,
        "availability": round(completed / max(1, len(recs)), 4),
        # fleet replicas run non-speculative whole-prompt reference
        # decode in this bench; zeros keep the one-schema contract
        "spec_accept_rate": 0.0,
        "spec_draft_tokens": 0,
        "prefill_chunks": 0,
        "paged_kernel_impl": 0,
        "replica_availability": round(stats["availability"], 6),
        "replicas": int(stats["replicas"]),
        "restarts": int(stats["restarts"]),
        "requests_requeued": int(stats["requests_requeued"]),
    }


def run_fleet_row(model_cfg_dict):
    """The ``fleet-under-churn`` row: a 2-replica fleet over the same
    model, with one replica hard-killed mid-stream (the chaos-soak kill
    schedule). Throughput and p99 here are END-TO-END under churn —
    relaunch downtime and requeue recompute included — and the row
    carries both availabilities: per-request (completed/submitted,
    1.0 by the zero-drop contract) and replica (ledger-folded
    capacity, measured < 1.0)."""
    import numpy as np

    rng = np.random.default_rng(0)
    wave = [
        (p.tolist(), NEW)
        for p in rng.integers(
            0, model_cfg_dict["src_vocab_size"], size=(REQUESTS, PROMPT)
        )
    ]
    recs, stats, wall = _run_fleet(
        model_cfg_dict, wave,
        faults="replica_kill:replica=1:step=12:times=1",
    )
    return _fleet_row("fleet-under-churn", recs, stats, wall)


def run_disagg_rows(model_cfg_dict):
    """``fleet-unified`` vs ``fleet-disagg``: the same mixed wave —
    short prompts with long-prompt interferers submitted up front — on
    3 unified replicas vs 1 prefill + 2 decode. Both rows' ``ttft_s``
    covers the SHORT requests only: the pair is the measured answer to
    "what does moving interferer prefill off the decode path buy p99
    TTFT". The disagg row adds the handoff ledger."""
    import numpy as np

    rng = np.random.default_rng(0)
    vocab = model_cfg_dict["src_vocab_size"]
    long_len = min(4 * PROMPT, SEQ - NEW - 1)
    wave, short_idx = [], []
    # interferers first: they own the prefill path when the shorts land
    for _ in range(max(2, REQUESTS // 4)):
        wave.append(
            (rng.integers(0, vocab, size=long_len).tolist(), NEW)
        )
    for _ in range(REQUESTS):
        short_idx.append(len(wave))
        wave.append((rng.integers(0, vocab, size=8).tolist(), NEW))

    rows = []
    for mode, prefill in (("fleet-unified", 0), ("fleet-disagg", 1)):
        recs, stats, wall = _run_fleet(
            model_cfg_dict, wave, n_replicas=3, prefill=prefill,
            prefix=f"bench_{mode.replace('-', '_')}_",
        )
        row = _fleet_row(
            mode, recs, stats, wall,
            ttft_recs=[recs[i] for i in short_idx],
        )
        row["prompt_len"] = 8  # the TTFT-bearing sub-wave
        row["interferer_prompt_len"] = long_len
        row["interferers"] = len(wave) - len(short_idx)
        row["prefill_replicas"] = int(stats["prefill_replicas"])
        row["requests_handed_off"] = int(stats["requests_handed_off"])
        row["handoff_bytes"] = int(stats["handoff_bytes"])
        rows.append(row)
    return rows


def run_transport_rows(model_cfg_dict):
    """``fleet-disagg-clean`` vs ``fleet-disagg-chunkloss``: the disagg
    mixed wave with the resume direction forced through small (1 KiB)
    chunks, clean vs ~1% chunk corruption on the router-side senders
    (``handoff_chunk_corrupt:transport=rtr:every=77`` — a disagg
    handoff averages ~77 KiB, so roughly one corrupted chunk per
    transfer). A corrupted chunk fails its CRC at the receiver, is
    never acked, and is retransmitted after backoff: the pair measures
    what wire-level healing costs tokens/s and p99 TTFT, and the
    chunkloss row carries the measured retry ledger
    (``handoff_retries`` / ``chunks_resent`` / ``bytes_resent``)."""
    import numpy as np

    chunk_bytes = 1024
    tkw = {
        "transport_chunk_bytes": chunk_bytes,
        "transport_inflight_bytes": 8 * 1024,
        # a generous ack deadline: on a CPU host the decode replica's
        # first transfer lands during jit warmup, and the default 50 ms
        # backoff would count warmup stalls as resends — with 2 s only
        # genuinely lost (corrupted) chunks retransmit, so the
        # chunkloss row's ledger measures the injected fault
        "transport_backoff_s": 2.0,
    }
    rng = np.random.default_rng(0)
    vocab = model_cfg_dict["src_vocab_size"]
    long_len = min(4 * PROMPT, SEQ - NEW - 1)
    wave, short_idx = [], []
    for _ in range(max(2, REQUESTS // 4)):
        wave.append(
            (rng.integers(0, vocab, size=long_len).tolist(), NEW)
        )
    for _ in range(REQUESTS):
        short_idx.append(len(wave))
        wave.append((rng.integers(0, vocab, size=8).tolist(), NEW))

    rows = []
    for mode, spec in (
        ("fleet-disagg-clean", ""),
        ("fleet-disagg-chunkloss",
         "handoff_chunk_corrupt:transport=rtr:every=77"),
    ):
        recs, stats, wall = _run_fleet(
            model_cfg_dict, wave, n_replicas=3, prefill=1,
            prefix=f"bench_{mode.replace('-', '_')}_",
            router_faults=spec, fleet_kw=tkw,
        )
        row = _fleet_row(
            mode, recs, stats, wall,
            ttft_recs=[recs[i] for i in short_idx],
        )
        row["prompt_len"] = 8
        row["interferer_prompt_len"] = long_len
        row["interferers"] = len(wave) - len(short_idx)
        row["prefill_replicas"] = int(stats["prefill_replicas"])
        row["requests_handed_off"] = int(stats["requests_handed_off"])
        row["handoff_bytes"] = int(stats["handoff_bytes"])
        row["transport_chunk_bytes"] = chunk_bytes
        row["handoff_retries"] = int(stats["handoff_retries"])
        row["chunks_resent"] = int(stats["chunks_resent"])
        # retransmits carry full chunks; the last chunk of a transfer
        # is the only shorter one, so this over-counts by < 1 chunk
        row["bytes_resent"] = int(stats["chunks_resent"]) * chunk_bytes
        rows.append(row)
    return rows


def bench_model_cfg(family):
    """The benchmark model for one family — comparable scale across
    families (256-dim trunk, 4 layers, 512 vocab)."""
    from fms_fsdp_tpu.models.configs import (
        LlamaConfig,
        MambaConfig,
        MixtralConfig,
    )

    if family == "llama":
        return LlamaConfig(
            src_vocab_size=512, emb_dim=256, nheads=4, kvheads=2,
            nlayers=4, max_expected_seq_len=SEQ,
        )
    if family == "mamba":
        return MambaConfig(
            d_model=256, n_layer=4, vocab_size=512, d_state=16,
            headdim=64, chunk_size=16, attn_layer_idx=(),
            d_intermediate=512,
        )
    assert family == "mixtral", family
    return MixtralConfig(
        src_vocab_size=512, emb_dim=256, nheads=4, kvheads=2, nlayers=4,
        hidden_dim=512, num_experts=4, top_k=2, max_expected_seq_len=SEQ,
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dry-run", action="store_true",
                    help="emit + validate a zeroed schema document "
                         "without importing jax (CI smoke)")
    ap.add_argument("--ckpt", default="",
                    help="serve params from this checkpoint instead of "
                         "a random tiny init (llama only)")
    ap.add_argument("--family", default="all",
                    choices=["all", "llama", "mamba", "mixtral"],
                    help="bench one family's steady-state row only; "
                         "'all' (the BENCH_SERVING.json shape) runs one "
                         "row per family plus the llama int8 / "
                         "oversubscribed / fleet-under-churn rows")
    args = ap.parse_args()

    if args.dry_run:
        doc = _zero_doc()
        errs = validate_result(doc)
        print(json.dumps(doc, indent=1))
        if errs:
            print(f"BENCH_SERVING schema invalid: {errs}", file=sys.stderr)
            raise SystemExit(1)
        return

    # the sharded row needs a multi-device mesh: on a TPU-less host,
    # force 8 host-platform CPU devices (must precede the jax import;
    # a no-op for non-CPU backends, which ignore the host platform)
    if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )

    import jax

    from fms_fsdp_tpu.serve.families import init_params_for

    families = (
        ["llama", "mamba", "mixtral"]
        if args.family == "all" else [args.family]
    )
    cfgs, params = {}, {}
    for fam in families:
        cfgs[fam] = bench_model_cfg(fam)
        if fam == "llama" and args.ckpt:
            from fms_fsdp_tpu.utils.checkpointing import load_params_only

            params[fam] = load_params_only(
                args.ckpt, init_params_for(cfgs[fam])
            )
        else:
            params[fam] = init_params_for(cfgs[fam])(jax.random.PRNGKey(0))

    # one steady-state row per family: the cross-family headline
    # (llama/mixtral pay paged KV per token; mamba's decode state is
    # the constant slab the row's state_bytes_per_stream reports)
    rows = [
        run_row(params[f], cfgs[f], BATCH, REQUESTS, PROMPT, NEW)
        for f in families
    ]
    if args.family == "all":
        import tempfile

        cfg, p = cfgs["llama"], params["llama"]
        spec_path = os.path.join(
            tempfile.mkdtemp(prefix="bench_spec_"), "speculator.pkl"
        )
        train_bench_speculator(p, cfg, spec_path)
        rows += [
            # quantized page storage: the resident-KV-bytes lever
            run_row(p, cfg, BATCH, REQUESTS, PROMPT, NEW,
                    kv_quant="int8"),
            # speculative serving: the bench-distilled MLPSpeculator
            # drafts 3 tokens per verify step; the row's
            # spec_accept_rate explains its tokens_per_sec (greedy
            # accept — the emitted stream is token-identical to the
            # non-speculative llama row above)
            run_row(p, cfg, BATCH, REQUESTS, PROMPT, NEW,
                    mode="speculative", speculator_path=spec_path),
            # paged-attention kernel v2 on natively-quantized pages
            # (paged_kernel_impl=2: multi-page DMA + in-kernel
            # dequantize; interpret-mode on a TPU-less host, so the
            # CPU number measures the path, not the silicon)
            run_row(p, cfg, BATCH, REQUESTS, PROMPT, NEW,
                    mode="kernel-v2-int8", kv_quant="int8",
                    attn_impl="kernel"),
            # whole vs chunked prefill under long-prompt interferers:
            # the single-replica p99-TTFT pair
            *run_longprompt_rows(p, cfg),
            # oversubscribed: 2x the requests on the same batch — queue
            # wait lands in TTFT, the continuous-batching stress shape
            run_row(p, cfg, BATCH, 2 * REQUESTS, PROMPT, NEW),
            # tp=2-sharded replica: the same steady-state wave with
            # params + KV pools split over a 2-device serving mesh
            # (docs/serving.md "Sharded replicas & disaggregation")
            run_row(p, cfg, BATCH, REQUESTS, PROMPT, NEW,
                    serve_layout="tp=2"),
            # 2-replica fleet with one replica killed mid-stream: the
            # serving numbers under churn (docs/serving.md "Fleet
            # resilience"; the same schedule
            # scripts/chaos_soak_serving.py asserts zero-drop token
            # parity on)
            run_fleet_row(dataclasses.asdict(cfg)),
            # unified vs disaggregated fleets on the mixed wave: the
            # short-request p99-TTFT pair
            *run_disagg_rows(dataclasses.asdict(cfg)),
            # the disagg wave again over the chunked resume wire,
            # clean vs ~1% chunk corruption: what transport healing
            # costs (docs/serving.md "Streaming transport & drain")
            *run_transport_rows(dataclasses.asdict(cfg)),
        ]
    backend = jax.default_backend()
    result = {
        "metric": "serving engine throughput/latency",
        "backend": backend,
        # measured on the answering backend: degraded would mean an
        # UNmeasured record (bench.py fallback-tier contract) — a
        # CPU-host run is a real relative measurement, labeled by
        # fallback_backend
        "degraded": False,
        "rows": rows,
        "tokens_per_sec": rows[0]["tokens_per_sec"],
        "ttft_s": rows[0]["ttft_s"],
        "p99_latency_s": rows[0]["p99_latency_s"],
    }
    if backend != "tpu":
        result["fallback_backend"] = backend
    errs = validate_result(result)
    if errs:
        print(f"BENCH_SERVING schema invalid: {errs}", file=sys.stderr)
        raise SystemExit(1)
    out = os.path.join(REPO, "BENCH_SERVING.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
