"""Export a framework Mamba hybrid checkpoint to the mamba_ssm /
MambaLMHeadModel ``save_pretrained`` layout (ref:fms_to_hf_mamba.py:9-33):
a directory holding ``config.json`` (the MambaConfig dict) and
``pytorch_model.bin`` with mamba_ssm's parameter naming —

    backbone.embedding.weight
    backbone.layers.N.norm.weight / .norm2.weight
    backbone.layers.N.mixer.{in_proj,conv1d,dt_bias,A_log,D,norm,out_proj}
    backbone.layers.N.mixer.{in_proj (qkv fused),out_proj}  (attn layers)
    backbone.layers.N.mlp.{fc1 (up|gate fused),fc2}
    backbone.norm_f.weight, lm_head.weight

Usage:
    python fms_to_hf_mamba.py --load_path=... --save_path=...
"""

import json
import os
import sys
from dataclasses import asdict

import numpy as np

from fms_fsdp_tpu.models.configs import MambaConfig
from fms_fsdp_tpu.utils.cli import parse_cli_args
from fms_fsdp_tpu.utils.config_utils import get_model_config, update_config


def params_to_mamba_ssm_state_dict(params, cfg: MambaConfig):
    """Our pytree -> mamba_ssm-style state dict (numpy fp32)."""

    def a(x):
        return np.asarray(x, dtype=np.float32)

    def t(x):
        return a(x).T

    sd = {
        "backbone.embedding.weight": a(params["embedding"]),
        "backbone.norm_f.weight": a(params["norm_f"]),
        "lm_head.weight": t(params["lm_head"]),
    }
    for i, layer in enumerate(params["layers"]):
        lp = f"backbone.layers.{i}"
        sd[f"{lp}.norm.weight"] = a(layer["norm"])
        m = layer["mixer"]
        if i in cfg.attn_layer_idx:
            # mamba_ssm MHA: fused in_proj (out_features = (nq + 2*nkv) * hd)
            wqkv = np.concatenate([t(m["wq"]), t(m["wk"]), t(m["wv"])], axis=0)
            sd[f"{lp}.mixer.in_proj.weight"] = wqkv
            sd[f"{lp}.mixer.out_proj.weight"] = t(m["wo"])
        else:
            sd[f"{lp}.mixer.in_proj.weight"] = t(m["in_proj"])
            # torch conv1d weight layout: (channels, 1, width)
            sd[f"{lp}.mixer.conv1d.weight"] = a(m["conv_w"])[:, None, :]
            sd[f"{lp}.mixer.conv1d.bias"] = a(m["conv_b"])
            sd[f"{lp}.mixer.dt_bias"] = a(m["dt_bias"])
            sd[f"{lp}.mixer.A_log"] = a(m["A_log"])
            sd[f"{lp}.mixer.D"] = a(m["D"])
            sd[f"{lp}.mixer.norm.weight"] = a(m["norm"])
            sd[f"{lp}.mixer.out_proj.weight"] = t(m["out_proj"])
        if "mlp" in layer:
            sd[f"{lp}.norm2.weight"] = a(layer["norm2"])
            # mamba_ssm GatedMLP: fc1 output chunks as (y, gate) with the
            # activation on the SECOND chunk -> rows are [up (w3); gate (w1)]
            fc1 = np.concatenate([t(layer["mlp"]["w3"]), t(layer["mlp"]["w1"])], axis=0)
            sd[f"{lp}.mlp.fc1.weight"] = fc1
            sd[f"{lp}.mlp.fc2.weight"] = t(layer["mlp"]["w2"])
    return sd


def mamba_ssm_config_dict(cfg: MambaConfig) -> dict:
    """The MambaConfig dict format mamba_ssm consumes
    (ref:config_utils.py:162-185)."""
    return {
        "d_model": cfg.d_model,
        "d_intermediate": cfg.d_intermediate,
        "n_layer": cfg.n_layer,
        "vocab_size": cfg.vocab_size,
        "ssm_cfg": {"layer": cfg.ssm_layer},
        "attn_layer_idx": list(cfg.attn_layer_idx),
        "attn_cfg": asdict(cfg.attn_cfg),
        "rms_norm": cfg.rms_norm,
        "residual_in_fp32": cfg.residual_in_fp32,
        "fused_add_norm": cfg.fused_add_norm,
        "pad_vocab_size_multiple": cfg.pad_vocab_size_multiple,
        "tie_embeddings": cfg.tie_embeddings,
    }


def save_pretrained(params, cfg: MambaConfig, save_path: str):
    import torch

    os.makedirs(save_path, exist_ok=True)
    sd = {
        k: torch.from_numpy(np.ascontiguousarray(v))
        for k, v in params_to_mamba_ssm_state_dict(params, cfg).items()
    }
    torch.save(sd, os.path.join(save_path, "pytorch_model.bin"))
    with open(os.path.join(save_path, "config.json"), "w") as f:
        json.dump(mamba_ssm_config_dict(cfg), f, indent=2)


def main(**kwargs):
    cfg = get_model_config(kwargs.get("model_variant", "mamba_9.8b"))
    update_config(cfg, **kwargs)
    load_path = kwargs["load_path"]
    save_path = kwargs["save_path"]

    from fms_fsdp_tpu.models.mamba import init_mamba_params
    from fms_fsdp_tpu.utils.checkpointing import load_params_only

    params = load_params_only(load_path, lambda k: init_mamba_params(k, cfg))
    save_pretrained(params, cfg, save_path)
    print(f"mamba_ssm-format model saved to {save_path}")


if __name__ == "__main__":
    main(**parse_cli_args(sys.argv[1:]))
