"""Mamba hybrid pretraining entry point (ref:main_training_mamba.py:28-171).

The reference's mamba entry is the llama entry with the model swapped
(mamba_ssm MambaLMHeadModel + Block, per-rank Triton cache dirs); here the
whole orchestration is shared — ``get_model_config("mamba_9.8b")`` returns
a MambaConfig and the train-step factory dispatches to the Mamba2 hybrid
forward (models/mamba.py). No kernel cache management is needed: XLA/Mosaic
compile caching is process-global.

Observability (docs/observability.md) rides the shared orchestration:
``--obs_dir=...`` emits the schema-versioned metrics.jsonl/heartbeat
with Mamba-family MFU/HFU (utils/flops.py dispatches on MambaConfig).
So does async multi-tier checkpointing (docs/checkpointing.md):
``--ckpt_local_dir=... --ckpt_local_interval=N`` adds the fast local
tier beside the durable ``--ckpt_save_path``.

Run:  python main_training_mamba.py --use_dummy_dataset=True --num_steps=100
"""

import sys

from fms_fsdp_tpu.utils.cli import parse_cli_args

from main_training_llama import main as _shared_main


def main(**kwargs):
    kwargs.setdefault("model_variant", "mamba_9.8b")
    kwargs.setdefault("vocab_size", 128256)
    return _shared_main(**kwargs)


if __name__ == "__main__":
    # classified-exit mapping for the self-healing supervisor, same as
    # the llama entry (resilience/exits.py)
    from fms_fsdp_tpu.resilience.exits import classified_exit

    with classified_exit():
        main(**parse_cli_args(sys.argv[1:]))
