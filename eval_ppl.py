"""Native perplexity evaluation over a trained checkpoint.

The reference's evaluation path is: convert the sharded checkpoint to HF
format and run EleutherAI lm-evaluation-harness externally
(ref:docs/evaluation.md:1-5) — that path exists here too (fms_to_hf_llama
/ fms_to_hf_mamba + the HF importers). This entry point additionally
evaluates *natively* (no conversion, any mesh, any model family):
token-mean negative log-likelihood and perplexity over a held-out stream
from the same data pipeline used for training.

Run:  python eval_ppl.py --ckpt_load_path=/path/to/run --model_variant=llama3_194m_4k \
          --data_path=/data --eval_batches=50
Dummy smoke:  python eval_ppl.py --use_dummy_dataset=True --eval_batches=8

Prints one JSON line: {"nll": ..., "ppl": ..., "tokens": ...}.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp

from fms_fsdp_tpu.config import TrainConfig
from fms_fsdp_tpu.data.device_feed import DeviceFeed
from fms_fsdp_tpu.data.loader import (
    get_data_loader,
    get_dummy_loader,
    rebatch,
)
from fms_fsdp_tpu.models import get_model_api
from fms_fsdp_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    data_parallel_extent,
)
from fms_fsdp_tpu.parallel.mixed_precision import get_dtype_policy
from fms_fsdp_tpu.parallel.sharding import shard_params, tree_shardings
from fms_fsdp_tpu.utils.checkpointing import load_params_only
from fms_fsdp_tpu.utils.cli import parse_cli_args
from fms_fsdp_tpu.utils.config_utils import get_model_config, update_config
from fms_fsdp_tpu.ops.fused_ce import IGNORE_INDEX
from fms_fsdp_tpu.utils.train_utils import setup, setup_environ_flags


def make_eval_step(model_cfg, cfg, mesh):
    """(params, (input, label)) -> (summed token NLL, token count).

    Sums rather than means so perplexity can be aggregated exactly over
    batches of unequal valid-token counts.
    """
    policy = get_dtype_policy(cfg)
    _, forward_fn, _, _ = get_model_api(model_cfg)

    from fms_fsdp_tpu.models import MixtralConfig

    extra = (
        # eval uses the exact dense-mix MoE path (no capacity drops)
        {"moe_impl": "dense", "return_aux": True}
        if isinstance(model_cfg, MixtralConfig)
        else {}
    )

    @jax.jit
    def eval_step(params, batch):
        inputs, labels = batch
        out = forward_fn(
            params,
            inputs,
            model_cfg,
            compute_dtype=policy.compute_dtype,
            attn_impl=cfg.attention_kernel,
            mesh=mesh,
            **extra,
        )
        logits = out[0] if isinstance(out, tuple) else out
        mask = labels != IGNORE_INDEX
        safe = jnp.where(mask, labels, 0)
        m = jnp.max(logits, axis=-1, keepdims=True)
        shifted = (logits - m).astype(jnp.float32)
        logz = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0].astype(
            jnp.float32
        )
        gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[
            ..., 0
        ].astype(jnp.float32)
        nll = jnp.sum((logz - gold) * mask)
        return nll, jnp.sum(mask)

    return eval_step


def main(**kwargs):
    eval_batches = int(kwargs.pop("eval_batches", 50))
    cfg = TrainConfig()
    update_config(cfg, **kwargs)

    setup()
    setup_environ_flags()
    rank = jax.process_index()
    world_size = jax.process_count()

    mesh = build_mesh(MeshConfig.from_train_config(cfg))
    model_cfg = get_model_config(cfg.model_variant)
    update_config(model_cfg, **kwargs)

    data_extent = data_parallel_extent(mesh)
    local_batch = cfg.batch_size * max(1, data_extent // world_size)
    if not cfg.use_dummy_dataset:
        loader = get_data_loader(
            cfg, rank, world_size, batch_multiplier=max(1, data_extent // world_size)
        )
    else:
        loader = get_dummy_loader(cfg, rank, world_size)

    # Params only — no optimizer state is materialized or read (the Adam
    # moments would triple eval memory; load_params_only skips them at the
    # IO layer). A given --ckpt_load_path must resolve to a real
    # checkpoint: unlike training, eval hard-fails rather than falling
    # back to fresh weights.
    init_params, _, specs_fn, _ = get_model_api(model_cfg)
    policy = get_dtype_policy(cfg)
    if cfg.ckpt_load_path:
        path = (
            os.path.join(cfg.ckpt_load_path, "checkpoints/")
            if not os.path.isfile(cfg.ckpt_load_path)
            and not os.path.isdir(os.path.join(cfg.ckpt_load_path, "state"))
            else cfg.ckpt_load_path
        )
        params = load_params_only(
            path, lambda k: init_params(k, model_cfg, dtype=policy.param_dtype)
        )
        params = shard_params(params, specs_fn(), mesh)
    else:
        # fresh-init smoke mode (sanity-checking the pipeline only)
        def init_fn(k):
            return init_params(k, model_cfg, dtype=policy.param_dtype)

        shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(cfg.seed))
        shardings = tree_shardings(
            mesh, specs_fn(), jax.tree.map(lambda s: s.shape, shapes)
        )
        params = jax.jit(init_fn, out_shardings=shardings)(
            jax.random.PRNGKey(cfg.seed)
        )

    eval_step = make_eval_step(model_cfg, cfg, mesh)
    feed = DeviceFeed(
        rebatch(loader, local_batch, cfg.batch_size), mesh, prefetch=2
    )
    it = iter(feed)

    total_nll, total_tokens = 0.0, 0
    for _ in range(eval_batches):
        nll, count = eval_step(params, next(it))
        total_nll += float(nll)
        total_tokens += int(count)

    nll = total_nll / max(1, total_tokens)
    result = {
        "nll": round(nll, 6),
        "ppl": round(float(jnp.exp(nll)), 4),
        "tokens": total_tokens,
        "model_variant": cfg.model_variant,
    }
    if rank == 0:
        print(json.dumps(result))
    return result


if __name__ == "__main__":
    main(**parse_cli_args(sys.argv[1:]))
