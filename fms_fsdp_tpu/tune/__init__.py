"""Kernel autotuning: measured block/tile selection with a persistent
per-chip tuning table.

Three layers (docs/performance.md "Autotuning"):

- :mod:`candidates` — enumerate legal tile configs per kernel and prune
  statically against a per-chip VMEM budget (the same residency math the
  kernels document; no device, no timing);
- :mod:`table` — the schema-versioned JSON tuning table committed
  in-repo (KERNEL_TUNING.json, like AOT_LOWER.json), keyed by
  (kernel, shape signature, dtype, chip kind);
- :mod:`lookup` — trace-time resolution wired into
  ops/{flash_attention,ssd,fused_ce} and the serving engine's paged
  decode (resolve_paged_decode, answered once at engine build): exact
  table match first, nearest signature next, today's static defaults
  last. Pure table + cost model — the lookup path never times anything,
  so tier-1 CPU runs are fully deterministic.

The on-device sweep that fills the table is scripts/autotune_kernels.py.
"""

from fms_fsdp_tpu.tune.lookup import (  # noqa: F401
    attach_registry,
    choices,
    configure_kernel_tuning,
    resolve_ce_chunk,
    resolve_flash,
    resolve_paged_decode,
    resolve_ssd_chunk,
)
from fms_fsdp_tpu.tune.table import (  # noqa: F401
    TUNING_SCHEMA_VERSION,
    TuningTable,
    default_table_path,
    validate_table,
)
