"""Candidate tile configs per kernel + the static VMEM cost model.

Everything here is pure host arithmetic over python ints — no jax, no
device, no clock — so candidate generation and pruning run identically
on a chipless CI host and inside the trace-time lookup path.

The cost model reuses the residency math the kernels themselves
document:

- flash resident family (ops/flash_attention.py): the whole per-head kv
  stream lives in VMEM (k+v forward, k+v for dq) — ~``2 * S * H *
  dtype_bytes`` per operand, double-buffered by Mosaic because the
  block index map changes across grid cells; q/o/do/stat blocks ride
  alongside. This is the "~8 * S * H bytes" note above MAX_KERNEL_SEQ,
  and the model reproduces that 8k bf16 cap exactly
  (tests/test_tune.py::test_cost_model_matches_resident_cap).
- flash kvgrid family: O(block) residency — q/k/v/o blocks plus the
  fp32 (block_q, head) online-softmax scratch; independent of S.
- dk/dv kernel (shared by both families): kv blocks resident across the
  (group, q-block) sweep plus two fp32 (block_k, head) scratch
  accumulators.
- SSD fused kernel (ops/ssd.py): (L, L) fp32 C@B^T scratch, the
  per-group-member (R, N, P) fp32 carried state, and the L-row operand
  blocks.
- fused CE (ops/fused_ce.py): an XLA scan, not a Pallas kernel — the
  constraint is the fp32 (chunk, V) logits tile (one live in fwd, two in
  bwd: p and d_logits), budgeted against HBM headroom rather than VMEM.
- paged decode (ops/paged_attention.py): per grid cell one (block_kv, H)
  k and v page block (double-buffered), the (group, H) q/o blocks, and
  the fp32 online-softmax scratch — O(block) residency like the kvgrid
  family, plus the scalar-prefetched page table in SMEM.
"""

from typing import Dict, List, Optional

# Per-core VMEM budget by chip kind. ~16 MiB/core is the working figure
# the shipped kernels were sized against (the resident flash family's 8k
# bf16 sequence cap lands exactly at this budget); chips we have not
# measured inherit the conservative default.
CHIP_VMEM_BYTES: Dict[str, int] = {
    "v4": 16 << 20,
    "v5e": 16 << 20,
    "v5p": 16 << 20,
    "v6e": 16 << 20,
    "cpu": 16 << 20,  # interpret mode runs the same block algebra
}
DEFAULT_VMEM_BYTES = 16 << 20

# HBM headroom budget for the fused-CE logits tile (the tile competes
# with params/activations for the 16 GB chip). 8 GiB is calibrated
# against measured reality: the 128k-vocab long-context bench rows run
# chunk=4096 (a ~4.2 GiB fp32 tile pair) on a 16 GB v5e, so the budget
# must admit it; 8192 at 128k vocab (~8.4 GiB) is where a full train
# step stops fitting.
CE_HBM_BUDGET_BYTES = 8 << 30

DTYPE_BYTES = {
    "bfloat16": 2,
    "float16": 2,
    "float32": 4,
    "int8": 1,
}

# Mosaic double-buffers grid-streamed blocks (the next cell's DMA runs
# behind the current cell's compute).
_DB = 2

# Today's static defaults — the last link of the fallback chain, and the
# values `kernel_tuning="off"` must reproduce bit-identically.
FLASH_DEFAULT_BLOCK_Q = 512
FLASH_DEFAULT_BLOCK_K = 512
SSD_DEFAULT_CHUNK = 256
CE_DEFAULT_CHUNK = 4096

_BLOCK_CHOICES = (128, 256, 512, 1024, 2048)
_SSD_CHUNK_CHOICES = (128, 256, 512)
_CE_CHUNK_CHOICES = (1024, 2048, 4096, 8192, 16384)

# Quantized flash family: the k stream (with q, the operands of the
# score GEMM — v is never quantized) rides in a 1-byte wire format,
# cutting the resident family's k+v residency 1.5x vs bf16 and lifting
# its sequence cap past 16k. None = today's unquantized kernels.
_FLASH_QUANT_CHOICES = (None, "int8", "fp8")


def dtype_bytes(dtype: str) -> int:
    return DTYPE_BYTES.get(str(dtype), 4)


def vmem_budget(chip: str) -> int:
    return CHIP_VMEM_BYTES.get(chip, DEFAULT_VMEM_BYTES)


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------


def flash_sig(q_shape, k_shape) -> Dict[str, int]:
    """Shape signature of one attention call, (B, S, N, H) layout."""
    b, sq, nq, h = q_shape
    _, sk, nkv, _ = k_shape
    return {
        "batch": int(b),
        "nq": int(nq),
        "nkv": int(nkv),
        "seq_q": int(sq),
        "seq_k": int(sk),
        "head": int(h),
    }


def _flash_fwd_resident_bytes(sig, db, bq, kv_db):
    h, sk = sig["head"], sig["seq_k"]
    kv = sk * h * (kv_db + db) * _DB  # k (wire width) + v, whole stream
    q_o = 2 * bq * h * db * _DB  # q in + o out blocks
    lse = bq * 4 * _DB
    acc = bq * h * 4 + 2 * bq * 4  # fp32 acc + running max/denominator
    return kv + q_o + lse + acc


def _flash_fwd_kvgrid_bytes(sig, db, bq, bk, kv_db):
    h = sig["head"]
    kv = bk * h * (kv_db + db) * _DB
    q_o = 2 * bq * h * db * _DB
    lse = bq * 4 * _DB
    scratch = bq * h * 4 + 2 * bq * 4  # VMEM scratch: acc, m, l
    return kv + q_o + lse + scratch


def _flash_dq_resident_bytes(sig, db, bq, kv_db):
    h, sk = sig["head"], sig["seq_k"]
    kv = sk * h * (kv_db + db) * _DB
    blocks = 3 * bq * h * db * _DB  # q, do in + dq out
    stats = 2 * bq * 4 * _DB  # lse, delta
    acc = bq * h * 4  # fori-loop fp32 dq accumulator
    return kv + blocks + stats + acc


def _flash_dq_kvgrid_bytes(sig, db, bq, bk, kv_db):
    h = sig["head"]
    kv = bk * h * (kv_db + db) * _DB
    blocks = 3 * bq * h * db * _DB
    stats = 2 * bq * 4 * _DB
    scratch = bq * h * 4
    return kv + blocks + stats + scratch


def _flash_dkv_bytes(sig, db, bq, bk, kv_db):
    # shared by both families: kv blocks resident across the (g, qi)
    # sweep, q/do streamed, two fp32 scratch accumulators
    h = sig["head"]
    kv_blocks = bk * h * (kv_db + db) * _DB
    dkv_out = 2 * bk * h * 4 * _DB  # fp32 outputs
    q_do = 2 * bq * h * db * _DB
    stats = 2 * bq * 4 * _DB
    scratch = 2 * bk * h * 4
    return kv_blocks + dkv_out + q_do + stats + scratch


def flash_vmem_bytes(family: str, sig: Dict[str, int], dtype: str,
                     block_q: int, block_k: int,
                     quant: Optional[str] = None) -> int:
    """Worst-case per-core VMEM over the kernels a training step runs
    (fwd + dq + dkv) for one family/tile choice. ``quant`` ("int8" /
    "fp8") prices the k stream at its 1-byte wire width — v stays
    full-width (only q/k ride the wire, ops/flash_attention.py). The
    per-block scale vectors are O(block) fp32, noise against the
    O(block*head) operands."""
    db = dtype_bytes(dtype)
    kv_db = 1 if quant else db
    if family == "resident":
        fwd = _flash_fwd_resident_bytes(sig, db, block_q, kv_db)
        dq = _flash_dq_resident_bytes(sig, db, block_q, kv_db)
    else:
        fwd = _flash_fwd_kvgrid_bytes(sig, db, block_q, block_k, kv_db)
        dq = _flash_dq_kvgrid_bytes(sig, db, block_q, block_k, kv_db)
    dkv = _flash_dkv_bytes(sig, db, block_q, block_k, kv_db)
    return max(fwd, dq, dkv)


def _legal_block(seq: int, b: int) -> bool:
    return b <= seq and seq % b == 0


def flash_candidates(sig: Dict[str, int], dtype: str, chip: str) -> List[Dict]:
    """Legal (family, block_q, block_k) configs under the VMEM budget,
    smallest-footprint last so the sweep can time cheap ones first."""
    budget = vmem_budget(chip)
    out = []
    for family in ("resident", "kvgrid"):
        for quant in _FLASH_QUANT_CHOICES:
            for bq in _BLOCK_CHOICES:
                if not _legal_block(sig["seq_q"], bq):
                    continue
                for bk in _BLOCK_CHOICES:
                    if not _legal_block(sig["seq_k"], bk):
                        continue
                    vmem = flash_vmem_bytes(family, sig, dtype, bq, bk, quant)
                    if vmem > budget:
                        continue
                    c = {
                        "family": family,
                        "block_q": bq,
                        "block_k": bk,
                        "vmem_bytes": vmem,
                    }
                    if quant:
                        c["quant"] = quant
                    out.append(c)
    return out


def flash_config_legal(config: Dict, sig: Dict[str, int], dtype: str,
                       chip: str) -> bool:
    """Is a table entry's config runnable for this exact shape on this
    chip? (Nearest-signature fallbacks must re-check: a block that
    divided the neighbor's sequence may not divide ours, and a resident
    pick near the cap may not fit a longer sequence.)"""
    family = config.get("family")
    bq = config.get("block_q", FLASH_DEFAULT_BLOCK_Q)
    bk = config.get("block_k", FLASH_DEFAULT_BLOCK_K)
    quant = config.get("quant")
    if family not in (None, "resident", "kvgrid"):
        return False
    if quant not in _FLASH_QUANT_CHOICES:
        return False
    if not isinstance(bq, int) or not isinstance(bk, int):
        return False
    if not (_legal_block(sig["seq_q"], bq) and _legal_block(sig["seq_k"], bk)):
        return False
    fam = family or "resident"
    return flash_vmem_bytes(fam, sig, dtype, bq, bk, quant) <= vmem_budget(chip)


def resident_max_seq(head: int, dtype: str, chip: str,
                     block_q: int = FLASH_DEFAULT_BLOCK_Q) -> int:
    """Largest power-of-two seq_k the resident family fits under the
    chip's VMEM budget — the cost-model restatement of MAX_KERNEL_SEQ."""
    s = 256
    while True:
        sig = {"batch": 1, "nq": 1, "nkv": 1, "seq_q": s * 2,
               "seq_k": s * 2, "head": head}
        if flash_vmem_bytes("resident", sig, dtype, block_q,
                            FLASH_DEFAULT_BLOCK_K) > vmem_budget(chip):
            return s
        s *= 2


# ---------------------------------------------------------------------------
# SSD (Mamba2 chunked scan)
# ---------------------------------------------------------------------------


def ssd_sig(x_shape, groups: int, dstate: int) -> Dict[str, int]:
    """x (B, S, H, P); groups/dstate from the B/C projections."""
    b, s, h, p = x_shape
    return {
        "batch": int(b),
        "seq": int(s),
        "heads": int(h),
        "headdim": int(p),
        "groups": int(groups),
        "dstate": int(dstate),
    }


def ssd_vmem_bytes(sig: Dict[str, int], dtype: str, chunk: int) -> int:
    """Fused-kernel residency for chunk length L: the (L, L) fp32
    C@B^T scratch, the (R, N, P) fp32 carried state, and the L-row
    operand/output blocks (ops/ssd.py::_fused_kernel)."""
    db = dtype_bytes(dtype)
    L = chunk
    p, n = sig["headdim"], sig["dstate"]
    r = max(1, sig["heads"] // max(1, sig["groups"]))
    cb = L * L * 4
    state = r * n * p * 4
    x_blk = L * p * db * _DB
    bc_blk = 2 * L * n * db * _DB
    rows = 2 * L * 4 * _DB  # cum + dt (1, L) fp32 rows
    y_out = L * p * 4 * _DB  # fp32 output block
    return cb + state + x_blk + bc_blk + rows + y_out


def ssd_candidates(sig: Dict[str, int], dtype: str, chip: str) -> List[Dict]:
    budget = vmem_budget(chip)
    out = []
    for L in _SSD_CHUNK_CHOICES:
        if L > sig["seq"] or sig["seq"] % L != 0:
            continue
        vmem = ssd_vmem_bytes(sig, dtype, L)
        if vmem > budget:
            continue
        out.append({"chunk": L, "vmem_bytes": vmem})
    return out


def ssd_config_legal(config: Dict, sig: Dict[str, int], dtype: str,
                     chip: str) -> bool:
    L = config.get("chunk")
    if not isinstance(L, int) or L <= 0:
        return False
    if L > sig["seq"] or sig["seq"] % L != 0:
        return False
    return ssd_vmem_bytes(sig, dtype, L) <= vmem_budget(chip)


# ---------------------------------------------------------------------------
# fused CE (chunked lm-head + cross-entropy)
# ---------------------------------------------------------------------------


def ce_sig(d_model: int, vocab: int) -> Dict[str, int]:
    return {"d_model": int(d_model), "vocab": int(vocab)}


def ce_working_set_bytes(sig: Dict[str, int], dtype: str, chunk: int) -> int:
    """Live-tile bytes of one bwd scan step: the fp32 (chunk, V) p and
    d_logits tiles plus the (chunk, D) x tile (ops/fused_ce.py)."""
    db = dtype_bytes(dtype)
    return 2 * chunk * sig["vocab"] * 4 + chunk * sig["d_model"] * db


def ce_candidates(sig: Dict[str, int], dtype: str, chip: str) -> List[Dict]:
    del chip  # the CE tile is HBM-budgeted, not VMEM-budgeted
    out = []
    for c in _CE_CHUNK_CHOICES:
        ws = ce_working_set_bytes(sig, dtype, c)
        if ws > CE_HBM_BUDGET_BYTES:
            continue
        out.append({"chunk": c, "working_set_bytes": ws})
    return out


def ce_config_legal(config: Dict, sig: Dict[str, int], dtype: str,
                    chip: str) -> bool:
    del chip
    c = config.get("chunk")
    if not isinstance(c, int) or c <= 0:
        return False
    return ce_working_set_bytes(sig, dtype, c) <= CE_HBM_BUDGET_BYTES


# ---------------------------------------------------------------------------
# paged decode (serving: ragged paged-attention, ops/paged_attention.py)
# ---------------------------------------------------------------------------

PAGED_DEFAULT_PAGE_SIZE = 64
PAGED_DEFAULT_BLOCK_KV = 64

_PAGE_SIZE_CHOICES = (16, 32, 64, 128, 256)
_BLOCK_KV_MULTIPLES = (1, 2, 4)


def paged_decode_sig(batch: int, nq: int, nkv: int, head: int,
                     max_seq: int) -> Dict[str, int]:
    """Shape signature of one serving decode step: the ragged batch
    width, head geometry, and the per-sequence cache capacity the page
    table spans (max_pages * page_size)."""
    return {
        "batch": int(batch),
        "nq": int(nq),
        "nkv": int(nkv),
        "head": int(head),
        "max_seq": int(max_seq),
    }


def paged_decode_vmem_bytes(sig: Dict[str, int], dtype: str,
                            page_size: int, block_kv: int) -> int:
    """Per-core residency of one (batch, kv-head) cell of the decode
    kernel: k+v blocks of ``block_kv`` positions (double-buffered — the
    next page's DMA runs behind the current page's compute), the
    (group, H) q/o blocks, the fp32 online-softmax scratch, and the
    row's page-table slice in SMEM (4 bytes per page, counted for
    honesty though it never threatens the budget)."""
    db = dtype_bytes(dtype)
    h = sig["head"]
    group = max(1, sig["nq"] // max(1, sig["nkv"]))
    kv = 2 * block_kv * h * db * _DB
    q_o = 2 * group * h * db * _DB
    scratch = group * h * 4 + 2 * group * 4  # fp32 acc + m/l
    table = 4 * (sig["max_seq"] // max(1, page_size))
    return kv + q_o + scratch + table


def paged_decode_candidates(sig: Dict[str, int], dtype: str,
                            chip: str) -> List[Dict]:
    """Legal (page_size, block_kv) tiles under the VMEM budget. The v2
    kernel walks ``block_kv // page_size`` pool pages per grid step
    (manual-DMA fetch, the RPA paper's layout), so enumeration covers
    block_kv multiples of page_size — more positions per cell amortize
    the per-step grid overhead at the price of a wider VMEM block."""
    budget = vmem_budget(chip)
    out = []
    for ps in _PAGE_SIZE_CHOICES:
        if ps > sig["max_seq"] or sig["max_seq"] % ps != 0:
            continue
        for mult in _BLOCK_KV_MULTIPLES:
            bkv = ps * mult
            if bkv > sig["max_seq"]:
                continue
            vmem = paged_decode_vmem_bytes(sig, dtype, ps, bkv)
            if vmem > budget:
                continue
            out.append(
                {"page_size": ps, "block_kv": bkv, "vmem_bytes": vmem}
            )
    return out


def paged_decode_config_legal(config: Dict, sig: Dict[str, int], dtype: str,
                              chip: str) -> bool:
    ps = config.get("page_size")
    bkv = config.get("block_kv", ps)
    if not isinstance(ps, int) or ps <= 0:
        return False
    if not isinstance(bkv, int) or bkv <= 0 or bkv % ps != 0:
        return False
    if ps > sig["max_seq"] or sig["max_seq"] % ps != 0:
        return False
    return paged_decode_vmem_bytes(sig, dtype, ps, bkv) <= vmem_budget(chip)


# ---------------------------------------------------------------------------
# dcn_bucket (bucketed cross-slice gradient reduction, parallel/overlap.py)
# ---------------------------------------------------------------------------

DCN_BUCKET_DEFAULT_MB = 32

_DCN_BUCKET_MB_CHOICES = (4, 8, 16, 32, 64, 128)

# Per-chip-pair DCN characteristics for the bytes-on-wire cost model:
# effective per-chip cross-slice bandwidth (bytes/s) and the per-collective
# launch/rendezvous latency. Working figures from the multi-slice scaling
# guidance the dcn axis was sized against; chips we have not measured
# inherit the conservative default.
CHIP_DCN_BANDWIDTH: Dict[str, float] = {
    "v4": 25e9,
    "v5e": 12.5e9,
    "v5p": 50e9,
    "v6e": 25e9,
}
DEFAULT_DCN_BANDWIDTH = 12.5e9
DCN_COLLECTIVE_LATENCY_S = 50e-6


def dcn_bucket_sig(grad_mb: int, leaves: int, slices: int,
                   wire_bytes: int) -> Dict[str, int]:
    """Signature of one gradient-reduction schedule: total wire MB of
    the grad tree (rounded up), its leaf count, the slice count, and the
    wire width (1 for the fp8/int8 reduce formats, 2 for bf16)."""
    return {
        "grad_mb": max(1, int(grad_mb)),
        "leaves": int(leaves),
        "slices": int(slices),
        "wire_bytes": int(wire_bytes),
    }


def dcn_bucket_cost_s(sig: Dict[str, int], bucket_mb: int,
                      chip: str) -> float:
    """Exposed-latency estimate for one bucket size: K buckets pay K
    collective launches, and the LAST bucket's wire time cannot hide
    under any remaining backward compute (2x for the ring all-reduce's
    reduce+broadcast halves across slices). Minimizing trades launch
    count (favors big buckets) against the exposed tail (favors small
    ones)."""
    bw = CHIP_DCN_BANDWIDTH.get(chip, DEFAULT_DCN_BANDWIDTH)
    total = sig["grad_mb"] << 20
    bucket = max(1, int(bucket_mb)) << 20
    k = max(1, -(-total // bucket))  # ceil
    tail_bytes = min(bucket, total)
    hops = 2 * (sig["slices"] - 1) / max(1, sig["slices"])
    return k * DCN_COLLECTIVE_LATENCY_S + tail_bytes * hops / bw


def dcn_bucket_candidates(sig: Dict[str, int], dtype: str,
                          chip: str) -> List[Dict]:
    """Legal bucket sizes with their modeled exposed cost. A candidate
    larger than the grad tree collapses to one bucket — legal (it is
    exactly the unsplit schedule) but only the smallest such size is
    kept, so the sweep never times duplicates."""
    del dtype  # the wire width is part of the signature
    out = []
    seen_single = False
    for mb in _DCN_BUCKET_MB_CHOICES:
        if mb >= sig["grad_mb"]:
            if seen_single:
                continue
            seen_single = True
        out.append({
            "bucket_mb": mb,
            "cost_us": round(dcn_bucket_cost_s(sig, mb, chip) * 1e6, 3),
        })
    return out


def dcn_bucket_config_legal(config: Dict, sig: Dict[str, int], dtype: str,
                            chip: str) -> bool:
    del sig, dtype, chip  # any positive size buckets any tree
    mb = config.get("bucket_mb")
    return isinstance(mb, int) and not isinstance(mb, bool) and mb > 0


LEGALITY = {
    "flash_attention": flash_config_legal,
    "ssd": ssd_config_legal,
    "fused_ce": ce_config_legal,
    "paged_decode": paged_decode_config_legal,
    "dcn_bucket": dcn_bucket_config_legal,
}

CANDIDATES = {
    "flash_attention": flash_candidates,
    "ssd": ssd_candidates,
    "fused_ce": ce_candidates,
    "paged_decode": paged_decode_candidates,
    "dcn_bucket": dcn_bucket_candidates,
}


def config_legal(kernel: str, config: Dict, sig: Dict[str, int], dtype: str,
                 chip: str) -> bool:
    fn = LEGALITY.get(kernel)
    return bool(fn and fn(config, sig, dtype, chip))
