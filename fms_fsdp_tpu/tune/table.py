"""The schema-versioned JSON tuning table.

One document, committed in-repo at KERNEL_TUNING.json (like
AOT_LOWER.json), holds every tuned entry:

    {
      "schema_version": 1,
      "generated_by": "scripts/autotune_kernels.py",
      "entries": [
        {"kernel": "flash_attention", "chip": "v5e",
         "dtype": "bfloat16",
         "signature": {"batch": 1, "nq": 32, ...},
         "config": {"family": "resident", "block_q": 512, "block_k": 512},
         "source": "measured" | "cost_model",
         "measured_ms": 1.23 | null},
        ...
      ]
    }

Keys are (kernel, chip, dtype, canonical signature). ``source`` keeps
the table honest: cost-model-seeded entries (committed before a chip
was available) are distinguishable from measured winners, and the sweep
only ever *upgrades* cost_model -> measured, never the reverse.

Everything here is pure dict/JSON work — no jax, no clock — so loading
and lookup are deterministic on any host.
"""

import json
import os
import tempfile
from typing import Dict, List, Optional, Tuple

TUNING_SCHEMA_VERSION = 1

KNOWN_KERNELS = (
    "flash_attention", "ssd", "fused_ce", "paged_decode", "dcn_bucket"
)

_REQUIRED_ENTRY_FIELDS = ("kernel", "chip", "dtype", "signature", "config")


def default_table_path() -> str:
    """The committed table at the repo root."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "KERNEL_TUNING.json")


def canonical_sig(sig: Dict[str, int]) -> str:
    return ",".join(f"{k}={int(v)}" for k, v in sorted(sig.items()))


def entry_key(kernel: str, chip: str, dtype: str,
              sig: Dict[str, int]) -> str:
    return "|".join((kernel, chip, str(dtype), canonical_sig(sig)))


def validate_table(doc) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(doc, dict):
        return ["table document is not an object"]
    v = doc.get("schema_version")
    if v != TUNING_SCHEMA_VERSION:
        errs.append(
            f"schema_version {v!r} != {TUNING_SCHEMA_VERSION}"
        )
    entries = doc.get("entries")
    if not isinstance(entries, list):
        return errs + ["'entries' missing or not a list"]
    seen = set()
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            errs.append(f"entries[{i}] is not an object")
            continue
        for f in _REQUIRED_ENTRY_FIELDS:
            if f not in e:
                errs.append(f"entries[{i}] missing {f!r}")
        if e.get("kernel") not in KNOWN_KERNELS:
            errs.append(f"entries[{i}] unknown kernel {e.get('kernel')!r}")
        sig = e.get("signature")
        if not isinstance(sig, dict) or not all(
            isinstance(x, int) and not isinstance(x, bool)
            for x in sig.values()
        ):
            errs.append(f"entries[{i}] signature must be a str->int map")
            continue
        cfg = e.get("config")
        if not isinstance(cfg, dict):
            errs.append(f"entries[{i}] config must be an object")
            continue
        if e.get("source") not in ("measured", "cost_model"):
            errs.append(
                f"entries[{i}] source must be 'measured' or 'cost_model'"
            )
        k = entry_key(
            str(e.get("kernel")), str(e.get("chip")), str(e.get("dtype")), sig
        )
        if k in seen:
            errs.append(f"entries[{i}] duplicates key {k}")
        seen.add(k)
    return errs


def _sig_distance(a: Dict[str, int], b: Dict[str, int]) -> Optional[float]:
    """Log-space distance between two signatures; None when they are not
    comparable (different key sets)."""
    if set(a) != set(b):
        return None
    d = 0.0
    for k in a:
        x, y = max(1, int(a[k])), max(1, int(b[k]))
        hi, lo = (x, y) if x >= y else (y, x)
        # |log2(x/y)| without importing math: exact for the power-of-two
        # shapes we key on, monotone for everything else
        ratio = hi / lo
        while ratio >= 2.0:
            d += 1.0
            ratio /= 2.0
        d += ratio - 1.0
    return d


class TuningTable:
    """In-memory view of one table document with exact + nearest lookup."""

    def __init__(self, doc: Optional[Dict] = None, path: Optional[str] = None):
        self.doc = doc or {
            "schema_version": TUNING_SCHEMA_VERSION,
            "generated_by": "scripts/autotune_kernels.py",
            "entries": [],
        }
        self.path = path
        self._index: Dict[str, Dict] = {}
        for e in self.doc.get("entries", []):
            try:
                self._index[
                    entry_key(e["kernel"], e["chip"], e["dtype"],
                              e["signature"])
                ] = e
            except (KeyError, TypeError, ValueError):
                continue  # validate_table reports these; lookup skips them

    @classmethod
    def load(cls, path: str) -> "TuningTable":
        with open(path) as f:
            doc = json.load(f)
        errs = validate_table(doc)
        if errs:
            raise ValueError(
                f"invalid tuning table {path}: {errs[:5]}"
            )
        return cls(doc, path=path)

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path
        assert path, "no path to save the tuning table to"
        self.doc["entries"] = sorted(
            self.doc["entries"],
            key=lambda e: entry_key(
                e["kernel"], e["chip"], e["dtype"], e["signature"]
            ),
        )
        d = os.path.dirname(os.path.abspath(path))
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self.doc, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return path

    def add(self, kernel: str, chip: str, dtype: str, sig: Dict[str, int],
            config: Dict, source: str, measured_ms: Optional[float] = None,
            keep_measured: bool = True) -> None:
        """Insert or replace one entry. With ``keep_measured`` a
        cost_model write never clobbers an existing measured entry."""
        key = entry_key(kernel, chip, dtype, sig)
        old = self._index.get(key)
        if (
            old is not None
            and keep_measured
            and old.get("source") == "measured"
            and source != "measured"
        ):
            return
        entry = {
            "kernel": kernel,
            "chip": chip,
            "dtype": str(dtype),
            "signature": {k: int(v) for k, v in sig.items()},
            "config": config,
            "source": source,
            "measured_ms": measured_ms,
        }
        if old is not None:
            self.doc["entries"].remove(old)
        self.doc["entries"].append(entry)
        self._index[key] = entry

    def lookup(self, kernel: str, chip: str, dtype: str,
               sig: Dict[str, int]) -> Tuple[Optional[Dict], Optional[str]]:
        """(config, how) where how is "exact" | "nearest" | None.

        Nearest: the minimum log-space signature distance among entries
        for the same (kernel, chip, dtype) with a comparable signature;
        ties break on the canonical key so the answer never depends on
        file order. The caller re-validates legality for its shape."""
        e = self._index.get(entry_key(kernel, chip, str(dtype), sig))
        if e is not None:
            return dict(e["config"]), "exact"
        best = None
        for key, cand in sorted(self._index.items()):
            if not key.startswith(f"{kernel}|{chip}|{dtype}|"):
                continue
            d = _sig_distance(sig, cand["signature"])
            if d is None:
                continue
            if best is None or d < best[0]:
                best = (d, cand)
        if best is not None:
            return dict(best[1]["config"]), "nearest"
        return None, None
