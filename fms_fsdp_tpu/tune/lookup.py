"""Trace-time tile resolution: table -> nearest -> static defaults.

Mirrors the ``set_kernel_variant`` discipline (ops/flash_attention.py):
the mode/table/chip are module state resolved ONCE per step build via
:func:`configure_kernel_tuning` — never re-read from the environment at
trace time — so already-cached jits can never disagree with the config
that built them. The env defaults (read once at import):

- ``FMS_KERNEL_TUNING``   — "auto" | "off" | /path/to/table.json
- ``FMS_KERNEL_TUNING_TABLE`` — table path override (mode stays auto)
- ``FMS_TUNE_CHIP``       — chip-kind override ("v5e", ...) for lookup

Resolution is pure table + cost model — no device sweep, no clock — so
tier-1 CPU runs are deterministic. On a CPU backend the chip kind
resolves to "cpu"; the committed table carries only TPU chip entries,
so CPU runs fall through to the static defaults unless a test or
operator pins ``chip=`` explicitly.

Chosen configs are recorded as ``kernel.tune.*`` gauges/counters once a
MetricRegistry is attached (main_training wires the Observer's registry
in), and :func:`choices` exposes them to bench.py for the
tuned-vs-default column.
"""

import logging
import os
from typing import Dict, Optional, Tuple

from fms_fsdp_tpu.tune import candidates as cand
from fms_fsdp_tpu.tune.table import TuningTable, default_table_path

logger = logging.getLogger(__name__)

_VALID_MODES = ("auto", "off")


def _env_default() -> Tuple[str, Optional[str]]:
    mode = os.environ.get("FMS_KERNEL_TUNING", "auto")
    path = os.environ.get("FMS_KERNEL_TUNING_TABLE") or None
    if mode not in _VALID_MODES:
        if os.sep in mode or mode.endswith(".json"):
            # a path value means "auto, against this table"
            return "auto", mode
        # fail loud: a typo'd value silently resolving to defaults would
        # mislabel every benchmark run under it (same contract as
        # FLASH_KERNEL_VARIANT)
        raise ValueError(
            f"FMS_KERNEL_TUNING={mode!r}: expected 'auto' | 'off' | "
            f"/path/to/table.json"
        )
    return mode, path

_ENV_MODE, _ENV_TABLE = _env_default()
_ENV_CHIP = os.environ.get("FMS_TUNE_CHIP") or None

_MODE = _ENV_MODE
_TABLE_PATH = _ENV_TABLE
_CHIP = _ENV_CHIP
# True when the active table path was named by the operator (config/env)
# rather than the committed default — an unusable explicit table FAILS
# LOUD (same contract as a typo'd FMS_KERNEL_TUNING), while a missing
# committed default just falls back to the static tiles
_TABLE_EXPLICIT = _ENV_TABLE is not None

_TABLE_CACHE: Dict[str, Optional[TuningTable]] = {}
_CHOICES: Dict[str, Dict] = {}
_REGISTRY = None
_DEGRADED_WARNED = set()


def configure_kernel_tuning(mode: Optional[str] = None,
                            table_path: Optional[str] = None,
                            chip: Optional[str] = None) -> None:
    """Apply TrainConfig.kernel_tuning before the step is traced.

    ``mode``: "auto" | "off" | a table path (implies auto); None
    restores the import-time env default — so every step build resolves
    tuning deterministically from its own config, never inheriting a
    forcing left by an earlier build in the same process. Also clears
    the per-build choice record (bench reads it per row) and the table
    cache (a table regenerated at the same path is re-read by the next
    build). An explicitly named table that fails to load raises here —
    a run labeled as tuned against a table it never read would mislabel
    every benchmark under it."""
    global _MODE, _TABLE_PATH, _CHIP, _TABLE_EXPLICIT
    if mode is None:
        _MODE, _TABLE_PATH = _ENV_MODE, _ENV_TABLE
        _TABLE_EXPLICIT = _ENV_TABLE is not None
    elif mode in _VALID_MODES:
        _MODE, _TABLE_PATH = mode, (table_path or _ENV_TABLE)
        _TABLE_EXPLICIT = table_path is not None or _ENV_TABLE is not None
    elif os.sep in mode or mode.endswith(".json"):
        _MODE, _TABLE_PATH = "auto", mode
        _TABLE_EXPLICIT = True
    else:
        raise ValueError(
            f"kernel_tuning={mode!r}: expected 'auto' | 'off' | "
            f"/path/to/table.json"
        )
    if table_path:
        _TABLE_PATH = table_path
        _TABLE_EXPLICIT = True
    _CHIP = chip if chip is not None else _ENV_CHIP
    _CHOICES.clear()
    _TABLE_CACHE.clear()
    if _MODE != "off" and _TABLE_EXPLICIT:
        _table()  # fail loud NOW if the named table is unusable


def tuning_mode() -> str:
    return _MODE


def attach_registry(registry) -> None:
    """Wire a MetricRegistry (the Observer's) in; choices recorded
    before the attach are replayed so trace-before-attach ordering does
    not lose gauges."""
    global _REGISTRY
    _REGISTRY = registry
    if registry is not None:
        for name, rec in _CHOICES.items():
            for k, v in rec.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    registry.gauge(f"kernel.tune.{name}.{k}").set(v)


def choices() -> Dict[str, Dict]:
    """Configs resolved since the last configure (for bench rows/tests)."""
    return {k: dict(v) for k, v in _CHOICES.items()}


def _record(name: str, rec: Dict) -> None:
    _CHOICES[name] = rec
    if _REGISTRY is not None:
        for k, v in rec.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                _REGISTRY.gauge(f"kernel.tune.{name}.{k}").set(v)
        _REGISTRY.counter(f"kernel.tune.{rec.get('how', 'default')}").add()


def chip_kind() -> str:
    """Chip key for table lookup: the FMS_TUNE_CHIP/configure override,
    else the default backend's device kind mapped to the table
    vocabulary, else the backend name ("cpu")."""
    if _CHIP:
        return _CHIP
    try:
        import jax

        if jax.default_backend() != "tpu":
            return jax.default_backend()
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # chipless AOT hosts: no addressable devices
        return "unknown"
    if "v5 lite" in kind or "v5e" in kind:
        return "v5e"
    if "v5p" in kind or "v5" in kind:
        return "v5p"
    if "v6 lite" in kind or "v6e" in kind:
        return "v6e"
    if "v4" in kind:
        return "v4"
    return kind.replace(" ", "_")


def _table() -> Optional[TuningTable]:
    path = _TABLE_PATH or default_table_path()
    if path not in _TABLE_CACHE:
        try:
            _TABLE_CACHE[path] = TuningTable.load(path)
        except (OSError, ValueError) as e:
            if _TABLE_EXPLICIT:
                # operator named this table: defaults-with-a-warning
                # would silently mislabel the run as tuned
                raise ValueError(
                    f"kernel tuning table {path} unusable: {e}"
                ) from e
            logger.warning("kernel tuning table %s unusable: %s", path, e)
            _TABLE_CACHE[path] = None
    return _TABLE_CACHE[path]


def _lookup(kernel: str, sig: Dict[str, int], dtype: str,
            chip: Optional[str]) -> Tuple[Optional[Dict], str]:
    """(config, how) with legality re-checked against THIS shape; an
    illegal table config (stale entry, nearest mismatch) falls through
    to the defaults rather than producing an unlowerable kernel."""
    chip = chip or chip_kind()
    tab = _table()
    if tab is None:
        return None, "default"
    config, how = tab.lookup(kernel, chip, str(dtype), sig)
    if config is None:
        return None, "default"
    if not cand.config_legal(kernel, config, sig, str(dtype), chip):
        logger.warning(
            "tuning table %s entry for %s %s is illegal for this shape; "
            "using defaults", how, kernel, sig,
        )
        return None, "default"
    return config, how


# ---------------------------------------------------------------------------
# per-kernel resolvers (called at trace time from the ops)
# ---------------------------------------------------------------------------


_FLASH_QUANT_CODE = {"none": 0, "int8": 1, "fp8": 2}


def resolve_flash(q_shape, k_shape, dtype: str,
                  requested_q: Optional[int] = None,
                  requested_k: Optional[int] = None,
                  requested_variant: Optional[str] = None,
                  requested_quant: Optional[str] = None,
                  chip: Optional[str] = None,
                  ) -> Tuple[int, int, Optional[str], Optional[str], str]:
    """(block_q, block_k, family, quant, how) for one attention call,
    public (B, S, N, H) layout.

    Explicitly requested pieces are always honored (callers passing
    block sizes — ring attention's bwd partials, tests — pin them); only
    unset pieces consult the table. With tuning off the static defaults
    fill the gaps, bit-identical to the pre-tuner behavior.

    ``quant`` is the quantized-family selection (None | "int8" | "fp8"):
    a table entry carrying a ``quant`` field turns the kv wire format on
    for this call; the committed default table carries none, so stock
    runs stay bit-identical. The resolved mode is exported as the
    ``kernel.tune.flash.quant_code`` gauge (0/1/2) alongside the string
    in :func:`choices`."""
    sig = cand.flash_sig(q_shape, k_shape)
    pinned = requested_q is not None and requested_k is not None
    bq = requested_q or cand.FLASH_DEFAULT_BLOCK_Q
    bk = requested_k or cand.FLASH_DEFAULT_BLOCK_K
    fam = requested_variant
    qnt = requested_quant
    # "off" = tuning disabled; "pinned" = the caller named the tiles
    # (tuning may be on) — the record must never claim tuning was off
    # when the mode was auto
    how = "pinned" if (_MODE != "off" and pinned) else "off"
    if _MODE != "off" and not pinned:
        config, how = _lookup("flash_attention", sig, dtype, chip)
        if config is not None:
            if requested_q is None:
                bq = int(config.get("block_q", bq))
            if requested_k is None:
                bk = int(config.get("block_k", bk))
            if fam is None:
                fam = config.get("family")
            if qnt is None:
                qnt = config.get("quant")
    _record(
        "flash",
        {
            "block_q": bq,
            "block_k": bk,
            "kvgrid": 1 if fam == "kvgrid" else 0,
            "quant": qnt or "none",
            "quant_code": _FLASH_QUANT_CODE.get(qnt or "none", 0),
            "how": how,
            "seq_k": sig["seq_k"],
        },
    )
    return bq, bk, fam, qnt, how


def record_final_flash_blocks(block_q: int, block_k: int,
                              kvgrid: Optional[bool] = None) -> None:
    """Patch the last flash record with what actually runs —
    _pick_block's divisibility halving can shrink the resolved request,
    and the kernel family may come from the sequence-length rule rather
    than the table (fam=None out of resolve_flash), so flash_attention
    calls this after both decisions land. The perf record's contract is
    to state the tiles AND family that produced it."""
    rec = _CHOICES.get("flash")
    if rec is None:
        return
    kv = rec["kvgrid"] if kvgrid is None else int(kvgrid)
    if (rec["block_q"], rec["block_k"], rec["kvgrid"]) == (
        block_q, block_k, kv
    ):
        return
    rec = dict(rec, block_q=block_q, block_k=block_k, kvgrid=kv)
    _CHOICES["flash"] = rec
    if _REGISTRY is not None:
        _REGISTRY.gauge("kernel.tune.flash.block_q").set(block_q)
        _REGISTRY.gauge("kernel.tune.flash.block_k").set(block_k)
        _REGISTRY.gauge("kernel.tune.flash.kvgrid").set(kv)


def resolve_ssd_chunk(x_shape, groups: int, dstate: int, dtype: str,
                      requested: int, chip: Optional[str] = None) -> int:
    """Chunk length L for one SSD scan. ``requested`` is the config's
    value (MambaConfig.chunk_size): when it still holds the static
    default the table may override it; a NON-default value is an
    explicit operator choice and pins — same contract as resolve_flash's
    requested blocks (turning tuning fully off is not required to force
    one knob)."""
    sig = cand.ssd_sig(x_shape, groups, dstate)
    default = min(cand.SSD_DEFAULT_CHUNK, sig["seq"])
    pinned = int(requested) != default
    L, how = int(requested), "off"
    if _MODE != "off":
        if pinned:
            how = "pinned"
        else:
            config, how = _lookup("ssd", sig, dtype, chip)
            if config is not None:
                L = int(config["chunk"])
    L = min(L, sig["seq"])
    _record("ssd", {"chunk": L, "how": how, "seq": sig["seq"]})
    return L


def resolve_paged_decode(batch: int, nq: int, nkv: int, head: int,
                         max_seq: int, dtype: str,
                         requested_page_size: Optional[int] = None,
                         requested_block_kv: Optional[int] = None,
                         chip: Optional[str] = None,
                         ) -> Tuple[int, int, str]:
    """(page_size, block_kv, how) for the serving engine's paged decode
    (ops/paged_attention.py). Resolved ONCE at engine build — page size
    shapes the allocator's pool, so unlike the per-call flash blocks it
    can never change under a live cache. Same pinning contract as
    resolve_flash: explicitly requested values are honored (ServeConfig
    .page_size != 0 pins), only unset pieces consult the table, and the
    static defaults fill the gaps with tuning off — pure table +
    cost-model work, no timing."""
    sig = cand.paged_decode_sig(batch, nq, nkv, head, max_seq)
    pinned = requested_page_size is not None
    ps = requested_page_size or cand.PAGED_DEFAULT_PAGE_SIZE
    bkv = requested_block_kv or ps
    if pinned and max_seq % ps != 0:
        # fail loud: silently halving an OPERATOR-pinned page size would
        # build a different allocator than the one the config names
        # (same contract as an unusable explicit tuning table)
        raise ValueError(
            f"ServeConfig.page_size={ps} does not divide "
            f"max_seq_len={max_seq}; pick a dividing page size or leave "
            f"it 0 for table resolution"
        )
    how = "pinned" if (_MODE != "off" and pinned) else "off"
    if _MODE != "off" and not pinned:
        config, how = _lookup("paged_decode", sig, dtype, chip)
        if config is not None:
            ps = int(config.get("page_size", ps))
            bkv = int(config.get("block_kv", ps))
    # the per-sequence capacity must stay page-aligned whatever the
    # table or static default said (a nearest-signature hit, re-checked
    # as it is, can still differ from this max_seq's divisors)
    while max_seq % ps != 0 and ps > 1:
        ps //= 2
        bkv = ps
    _record(
        "paged",
        {"page_size": ps, "block_kv": bkv, "how": how, "max_seq": max_seq},
    )
    return ps, bkv, how


def resolve_ce_chunk(d_model: int, vocab: int, dtype: str,
                     requested: int, chip: Optional[str] = None) -> int:
    """Logits-chunk size for the fused lm-head+CE; ``requested`` is
    TrainConfig.loss_chunk_size. Same pinning contract as
    resolve_ssd_chunk: the table only overrides the static default — an
    operator-set value (e.g. a smaller tile to fit HBM) wins."""
    sig = cand.ce_sig(d_model, vocab)
    pinned = int(requested) != cand.CE_DEFAULT_CHUNK
    c, how = int(requested), "off"
    if _MODE != "off":
        if pinned:
            how = "pinned"
        else:
            config, how = _lookup("fused_ce", sig, dtype, chip)
            if config is not None:
                c = int(config["chunk"])
    _record("ce", {"chunk": c, "how": how, "vocab": sig["vocab"]})
    return c


def resolve_dcn_bucket(grad_mb: int, leaves: int, slices: int,
                       wire_bytes: int, requested: int = 0,
                       chip: Optional[str] = None) -> int:
    """Bucket size (MB of wire bytes) for the overlapped DCN gradient
    reduction (parallel/overlap.py), resolved once per step build.

    Same pinning contract as resolve_ce_chunk: ``requested`` is
    TrainConfig.dcn_bucket_mb — nonzero is an explicit operator choice
    and wins over the table; 0 consults the table (exact -> nearest ->
    the cost model's pick over the candidate sizes, so even a tableless
    host gets a bytes-on-wire/DCN-bandwidth-reasoned size rather than a
    blind constant)."""
    sig = cand.dcn_bucket_sig(grad_mb, leaves, slices, wire_bytes)
    pinned = int(requested) != 0
    mb, how = int(requested) or cand.DCN_BUCKET_DEFAULT_MB, "off"
    chip_key = chip or chip_kind()
    if _MODE != "off":
        if pinned:
            how = "pinned"
        else:
            config, how = _lookup("dcn_bucket", sig, "bfloat16", chip)
            if config is not None:
                mb = int(config["bucket_mb"])
            else:
                # cost-model fallback: cheapest modeled exposed latency
                # among the candidate sizes (pure host arithmetic)
                cands = cand.dcn_bucket_candidates(sig, "bfloat16", chip_key)
                if cands:
                    mb = min(cands, key=lambda c: c["cost_us"])["bucket_mb"]
    _record(
        "dcn_bucket",
        {"bucket_mb": mb, "how": how, "grad_mb": sig["grad_mb"],
         "slices": sig["slices"], "wire_bytes": sig["wire_bytes"]},
    )
    return mb


# ---------------------------------------------------------------------------
# degradation signal for _pick_block (ops/flash_attention.py)
# ---------------------------------------------------------------------------


def note_block_degradation(kind: str, seq: int, requested: int,
                           resolved: int) -> None:
    """Called when divisibility halving degraded a block below half the
    requested size (e.g. seq 2944 @ 512 -> 128): count it in the obs
    registry and warn once per (kind, seq, requested) — a silent 4x tile
    shrink is an MFU cliff nobody sees otherwise."""
    if _REGISTRY is not None:
        _REGISTRY.counter("kernel.tune.block_degraded").add()
        _REGISTRY.gauge(f"kernel.tune.block_degraded_{kind}").set(resolved)
    key = (kind, seq, requested)
    if key not in _DEGRADED_WARNED:
        _DEGRADED_WARNED.add(key)
        logger.warning(
            "flash block_%s degraded %d -> %d for seq %d (divisibility "
            "halving); consider a tuned table entry or an aligned "
            "sequence length", kind, requested, resolved, seq,
        )
