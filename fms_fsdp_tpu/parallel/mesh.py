"""Device mesh construction.

The reference's sharding-strategy trichotomy (ddp / fsdp / hsdp mapping to
NO_SHARD / FULL_SHARD / HYBRID_SHARD, ref:fms_fsdp/utils/train_utils.py:227-234)
collapses into the *shape* of one 6-axis ``jax.sharding.Mesh``:

    ("dcn", "replica", "fsdp", "expert", "context", "tensor")

- dcn   -> data-parallel axis ACROSS slices (the slowest transport: the
           data-center network joining TPU slices on a multislice pod).
           Size = the number of slices; params are replicated over it
           (no spec ever names it) and gradients all-reduce across it.
           Collapses to size 1 on single-slice — the mesh is then
           bit-identical to the historical 5-axis construction (the
           device array is built exactly as before and reshaped).
- ddp   -> fsdp axis size 1, replica = per-slice world: params replicated,
           gradients psum'ed over "replica" by GSPMD (NCCL all-reduce analog).
- fsdp  -> replica 1, fsdp = per-slice world: params/opt state sharded over
           "fsdp"; XLA inserts all-gather (fwd/bwd) + reduce-scatter (grads)
           over ICI.
- hsdp  -> replica = per-slice world // group, fsdp = group: shard within an
           ICI-local group, replicate across groups — HYBRID_SHARD analog.
- expert  -> expert-parallel axis (beyond-reference MoE training): MoE
           expert weights shard their E dim here, while the axis doubles as
           a data axis for dense layers (DATA_AXES) — the dispatch/combine
           einsums reshard tokens batch->expert, which GSPMD lowers to the
           all-to-all pair of classic EP.
- tensor  -> megatron-style TP axis (speculator parity + headroom).
- context -> sequence/ring-attention axis (beyond-reference long-context).

Axis order places "dcn" outermost (slowest-varying: whole slices), then
"replica" (DCN-or-ICI replica groups within a slice), down to "tensor"
innermost (fastest ICI neighborhood) — so GSPMD's collectives decompose
hierarchically: reduce-scatter/all-gather over ICI within a slice, one
all-reduce across slices over DCN (the pjit/TPUv4 scaling pattern,
PAPERS.md "Scalable Training of Language Models using JAX pjit and
TPUv4"). The slice is also the FAULT DOMAIN: elastic resume treats
"lost a slice" as a legal rescale (ckpt/elastic.py), and
resilience/slices.py detects a dead slice instead of letting the DCN
all-reduce hang.

Slice discovery (``slice_assignments`` / ``process_slice_context``):
real TPU multislice exposes ``device.slice_index``; MEGASCALE_* env vars
carry the same facts on older stacks; the ``FMS_SIM_SLICES`` env knob
(or an explicit ``num_slices``) partitions a gloo/CPU world into
simulated slices for tests — processes are split into ``S`` contiguous
equal blocks.
"""

import os
import re
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_DCN = "dcn"
AXIS_REPLICA = "replica"
AXIS_FSDP = "fsdp"
AXIS_EXPERT = "expert"
AXIS_CONTEXT = "context"
AXIS_TENSOR = "tensor"
MESH_AXES = (
    AXIS_DCN,
    AXIS_REPLICA,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_CONTEXT,
    AXIS_TENSOR,
)

# Axes a batch is sharded over (all data-parallel dimensions). The expert
# axis is data-parallel for every dense computation; only MoE dispatch
# reshards from it (see module docstring). "dcn" leads: every slice holds
# its own batch rows, so the only cross-slice traffic is the gradient
# all-reduce.
DATA_AXES = (AXIS_DCN, AXIS_REPLICA, AXIS_FSDP, AXIS_EXPERT)

# Gloo/CPU simulation knob (tests, docs/train_details.md "Multi-slice"):
# the process world is split into this many contiguous equal slices.
SIM_SLICES_ENV = "FMS_SIM_SLICES"


@dataclass(frozen=True)
class MeshConfig:
    sharding_strategy: str = "hsdp"  # ddp | fsdp | hsdp | tp
    sharding_group_size: Optional[int] = None  # fsdp-axis size under hsdp
    tensor_parallel_size: int = 1
    context_parallel_size: int = 1
    expert_parallel_size: int = 1
    # 0 = auto-detect (device slice metadata / MEGASCALE env /
    # FMS_SIM_SLICES); explicit values override detection.
    num_slices: int = 0

    @classmethod
    def from_train_config(cls, cfg):
        return cls(
            sharding_strategy=cfg.sharding_strategy,
            sharding_group_size=getattr(cfg, "sharding_group_size", None),
            tensor_parallel_size=getattr(cfg, "tensor_parallel_size", 1),
            context_parallel_size=getattr(cfg, "context_parallel_size", 1),
            expert_parallel_size=getattr(cfg, "expert_parallel_size", 1),
            num_slices=int(getattr(cfg, "num_slices", 0) or 0),
        )


# ---------------------------------------------------------------------------
# slice discovery
# ---------------------------------------------------------------------------


def _env_num_slices() -> int:
    """Slice count from the environment: the gloo simulation knob first
    (tests drive it explicitly), then the megascale launcher's count."""
    for var in (SIM_SLICES_ENV, "MEGASCALE_NUM_SLICES"):
        raw = os.environ.get(var, "")
        if raw:
            try:
                n = int(raw)
            except ValueError:
                continue
            if n > 0:
                return n
    return 0


def _process_to_slice(process_index: int, process_count: int, n_slices: int) -> int:
    """Contiguous-block mapping for simulated slices: processes
    [k*P/S, (k+1)*P/S) form slice k."""
    return process_index * n_slices // max(1, process_count)


def slice_assignments(
    devices: Sequence, num_slices: int = 0
) -> Tuple[List[int], int]:
    """Per-device slice ids for ``devices`` (aligned to the sequence)
    plus the slice count.

    Precedence: real device metadata (``device.slice_index``, present on
    TPU multislice) -> an explicit/env slice count partitioning by the
    devices' ``process_index`` (gloo simulation; contiguous equal blocks
    of processes) -> in-process fallback (single process exposing every
    device: contiguous equal blocks of the device list itself) -> one
    slice."""
    devices = list(devices)
    n = len(devices)
    ids = [getattr(d, "slice_index", None) for d in devices]
    if devices and all(i is not None for i in ids):
        uniq = sorted(set(ids))
        if len(uniq) > 1:
            remap = {s: i for i, s in enumerate(uniq)}
            return [remap[i] for i in ids], len(uniq)
    n_slices = int(num_slices or 0) or _env_num_slices()
    if n_slices <= 1:
        return [0] * n, 1
    if n % n_slices != 0:
        raise ValueError(
            f"{n} devices cannot split into {n_slices} equal slices"
        )
    procs = sorted({getattr(d, "process_index", 0) for d in devices})
    if len(procs) > 1:
        if len(procs) % n_slices != 0:
            raise ValueError(
                f"{len(procs)} processes cannot split into {n_slices} "
                f"equal slices"
            )
        rank_of = {p: i for i, p in enumerate(procs)}
        return [
            _process_to_slice(
                rank_of[getattr(d, "process_index", 0)], len(procs), n_slices
            )
            for d in devices
        ], n_slices
    per = n // n_slices
    return [i // per for i in range(n)], n_slices


def process_slice_context(cfg=None) -> Tuple[int, int]:
    """(num_slices, this process's slice index) for the live world —
    the host-side mirror of ``slice_assignments`` (guards tagging, the
    SliceHealthMonitor, and the topology fingerprint all consume it
    without holding a mesh). Single-slice worlds return (1, 0)."""
    explicit = int(getattr(cfg, "num_slices", 0) or 0) if cfg is not None else 0
    try:
        local = jax.local_devices()
    except RuntimeError:
        local = []
    sidx = next(
        (
            getattr(d, "slice_index", None)
            for d in local
            if getattr(d, "slice_index", None) is not None
        ),
        None,
    )
    if sidx is not None:
        all_ids = {
            getattr(d, "slice_index", None) for d in jax.devices()
        }
        all_ids.discard(None)
        if len(all_ids) > 1:
            uniq = sorted(all_ids)
            return len(uniq), uniq.index(sidx)
    n_slices = explicit or _env_num_slices()
    if n_slices <= 1:
        return 1, 0
    raw = os.environ.get("MEGASCALE_SLICE_ID", "")
    if raw and not os.environ.get(SIM_SLICES_ENV):
        try:
            return n_slices, int(raw)
        except ValueError:
            pass
    return n_slices, _process_to_slice(
        jax.process_index(), jax.process_count(), n_slices
    )


def num_mesh_slices(mesh: Mesh) -> int:
    """Slice count a mesh was built with (its dcn-axis extent)."""
    return int(mesh.shape.get(AXIS_DCN, 1))


# ---------------------------------------------------------------------------
# mesh construction
# ---------------------------------------------------------------------------


def _default_group_size(n_dp: int, devices: Sequence) -> int:
    """HSDP group size when unspecified: devices per host if the
    data-parallel extent spans multiple hosts (the reference shards
    within the 8-GPU node, ref:README), else the full extent.

    Derived from the PASSED devices, never ``jax.local_device_count()``:
    a caller handing in a device subset (simulated/partial worlds,
    ``dryrun_multichip``) must get group inference for THAT world, and
    on multi-slice meshes the caller passes one slice's devices so the
    group never straddles a DCN boundary."""
    counts: dict = {}
    for d in devices:
        p = getattr(d, "process_index", 0)
        counts[p] = counts.get(p, 0) + 1
    local = max(counts.values()) if counts else 1
    if n_dp % local == 0 and n_dp > local:
        return local
    return n_dp


def build_mesh(
    mesh_config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence] = None,
    **overrides,
) -> Mesh:
    """Build the 6-axis mesh from a MeshConfig (or kwargs).

    Multi-slice worlds (slice metadata on the devices, MEGASCALE env,
    the FMS_SIM_SLICES simulation knob, or an explicit ``num_slices``)
    get the dcn axis = slice count, with each slice's devices filling
    one dcn index — via ``mesh_utils.create_hybrid_device_mesh`` when
    the devices carry real slice/coord metadata, else by stacking
    per-slice ``create_device_mesh`` blocks. Single-slice worlds build
    the device array exactly as the historical 5-axis mesh did and
    reshape a leading dcn=1 axis on — device placement is bit-identical
    (pinned by tests/test_sharding.py)."""
    if mesh_config is None:
        mesh_config = MeshConfig(**overrides)
    devices = list(devices if devices is not None else jax.devices())
    world = len(devices)

    tp = mesh_config.tensor_parallel_size or 1
    cp = mesh_config.context_parallel_size or 1
    ep = mesh_config.expert_parallel_size or 1
    if world % (tp * cp * ep) != 0:
        raise ValueError(
            f"world size {world} not divisible by "
            f"tensor*context*expert = {tp * cp * ep}"
        )
    n_dp = world // (tp * cp * ep)

    slice_ids, n_slices = slice_assignments(
        devices, int(mesh_config.num_slices or 0)
    )
    if n_dp % n_slices != 0:
        raise ValueError(
            f"data-parallel extent {n_dp} not divisible by the slice "
            f"count {n_slices}; tensor/context/expert axes may not span "
            f"slices"
        )
    slice_dp = n_dp // n_slices
    per_slice = [
        [d for d, s in zip(devices, slice_ids) if s == k]
        for k in range(n_slices)
    ]
    if len({len(g) for g in per_slice}) > 1:
        raise ValueError(
            f"slices are unevenly sized "
            f"({[len(g) for g in per_slice]} devices): the dcn mesh axis "
            f"needs equal slices"
        )

    strategy = mesh_config.sharding_strategy
    if strategy == "ddp":
        replica, fsdp = slice_dp, 1
    elif strategy in ("fsdp", "tp"):
        # "tp" (speculator path) shards the base model over the remaining
        # devices FSDP-style alongside the tensor axis
        # (ref:speculator/train_speculator.py:133-160).
        replica, fsdp = 1, slice_dp
    elif strategy == "hsdp":
        group = mesh_config.sharding_group_size or _default_group_size(
            slice_dp, per_slice[0]
        )
        if slice_dp % group != 0:
            raise ValueError(
                f"per-slice data-parallel extent {slice_dp} not divisible "
                f"by sharding group {group}"
            )
        replica, fsdp = slice_dp // group, group
    else:
        raise ValueError(f"unknown sharding strategy: {strategy}")

    shape5 = (replica, fsdp, ep, cp, tp)
    if n_slices == 1:
        # bit-identical to the historical 5-axis construction: same
        # create_device_mesh call, a leading size-1 dcn axis reshaped on
        device_array = mesh_utils.create_device_mesh(shape5, devices=devices)
        device_array = device_array.reshape((1,) + device_array.shape)
        return Mesh(device_array, MESH_AXES)

    if all(getattr(d, "slice_index", None) is not None for d in devices):
        # real multislice hardware: let jax place the per-slice mesh by
        # ICI topology and replicate the layout across slices
        try:
            device_array = mesh_utils.create_hybrid_device_mesh(
                (1,) + shape5,
                (n_slices, 1, 1, 1, 1, 1),
                devices=devices,
            )
            return Mesh(device_array, MESH_AXES)
        except (ValueError, NotImplementedError, AssertionError):
            pass  # fall through to the generic per-slice stacking
    device_array = np.stack(
        [
            mesh_utils.create_device_mesh(shape5, devices=group)
            for group in per_slice
        ]
    )
    return Mesh(device_array, MESH_AXES)


def data_parallel_extent(mesh: Mesh) -> int:
    """Number of ways the global batch is split (product of DATA_AXES)."""
    return int(np.prod([mesh.shape[a] for a in DATA_AXES]))


# ---------------------------------------------------------------------------
# HLO collective attribution (bench + tests)
# ---------------------------------------------------------------------------

_COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

_GROUPS_LIST_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?"
)


def _parse_replica_groups(attr_text: str):
    """Decode the two HLO replica_groups encodings into device-id lists:
    the explicit ``{{0,1},{2,3}}`` form and the iota-v2
    ``[g,s]<=[dims]T(perm)`` form."""
    m = _GROUPS_LIST_RE.search(attr_text)
    if m:
        return [
            [int(x) for x in grp.split(",") if x.strip()]
            for grp in re.findall(r"\{([^{}]*)\}", m.group(1))
        ]
    m = _GROUPS_IOTA_RE.search(attr_text)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        arr = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            arr = arr.transpose([int(x) for x in m.group(4).split(",")])
        return arr.reshape(n_groups, group_size).tolist()
    return None


def hlo_collective_split(hlo_text: str, mesh: Mesh) -> dict:
    """Classify every collective in compiled-HLO text as ICI
    (within one slice) or DCN (replica groups spanning slices).

    The attribution behind the MULTICHIP bench rows and the
    "dcn=1 adds no cross-slice collectives" test pin: replica_groups in
    compiled SPMD HLO hold LOGICAL partition ordinals — positions in the
    computation's device assignment, i.e. the mesh's flattened device
    order — NOT hardware device ids (they coincide on CPU test backends
    but not on real multislice hardware, where create_hybrid_device_mesh
    orders devices by topology). The dcn axis is the mesh's leading
    axis, so flattened order is slice-major: ordinal // per_slice is the
    slice. A collective whose any replica group contains two slices'
    ordinals is DCN traffic."""
    n_slices = int(mesh.shape.get(AXIS_DCN, 1))
    per_slice = max(1, mesh.size // max(1, n_slices))
    slice_of = {i: i // per_slice for i in range(mesh.size)}
    counts = {"ici": 0, "dcn": 0, "unattributed": 0}
    op_re = re.compile(
        r"\b(" + "|".join(_COLLECTIVE_OPS) + r")(-start)?(\.\d+)?\("
    )
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if not op_re.search(stripped):
            continue
        if "-done" in stripped:
            continue  # count each async collective once (its -start)
        groups = _parse_replica_groups(stripped)
        if groups is None:
            counts["unattributed"] += 1
            continue
        crosses = any(
            len({slice_of.get(i, -1) for i in g}) > 1 for g in groups
        )
        counts["dcn" if crosses else "ici"] += 1
    return counts


_BACKWARD_MARKERS = ("transpose(", "fwd_bwd")


def hlo_collective_schedule(hlo_text: str, mesh: Mesh) -> dict:
    """Structural view of WHERE the collectives sit in the compiled
    program, not just how many there are (hlo_collective_split).

    Walks the HLO text in emission order and classifies each line as a
    collective (ici/dcn, same replica-group attribution as the split) or
    a backward-compute op (op_name metadata under the ``fwd_bwd`` scope
    or a ``transpose(...)`` autodiff region). Returns::

        {"dcn": K, "ici": N, "backward_lines": B,
         "interleaved_pairs": P}

    ``interleaved_pairs`` counts consecutive pairs of dcn collectives
    with at least one backward-compute op strictly between them — the
    property the DCN-overlap schedule exists to create (a program whose
    cross-slice reduces all sit in one tail blob scores 0; one whose
    reduces are threaded through the backward scores K-1). Collective
    lines themselves never count as backward markers even when their
    op_name carries a transpose scope, so a blob of back-to-back grad
    reduces cannot self-certify as interleaved."""
    n_slices = int(mesh.shape.get(AXIS_DCN, 1))
    per_slice = max(1, mesh.size // max(1, n_slices))
    slice_of = {i: i // per_slice for i in range(mesh.size)}
    op_re = re.compile(
        r"\b(" + "|".join(_COLLECTIVE_OPS) + r")(-start)?(\.\d+)?\("
    )
    events = []  # ("dcn" | "ici" | "bwd") in program order
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if op_re.search(stripped) and "-done" not in stripped:
            groups = _parse_replica_groups(stripped)
            if groups is None:
                continue
            crosses = any(
                len({slice_of.get(i, -1) for i in g}) > 1 for g in groups
            )
            events.append("dcn" if crosses else "ici")
            continue
        if "op_name=" in stripped and any(
            m in stripped for m in _BACKWARD_MARKERS
        ):
            events.append("bwd")
    out = {
        "dcn": events.count("dcn"),
        "ici": events.count("ici"),
        "backward_lines": events.count("bwd"),
        "interleaved_pairs": 0,
    }
    saw_bwd_since_dcn = False
    saw_dcn = False
    for ev in events:
        if ev == "dcn":
            if saw_dcn and saw_bwd_since_dcn:
                out["interleaved_pairs"] += 1
            saw_dcn = True
            saw_bwd_since_dcn = False
        elif ev == "bwd":
            saw_bwd_since_dcn = True
    return out
