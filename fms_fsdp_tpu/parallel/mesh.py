"""Device mesh construction.

The reference's sharding-strategy trichotomy (ddp / fsdp / hsdp mapping to
NO_SHARD / FULL_SHARD / HYBRID_SHARD, ref:fms_fsdp/utils/train_utils.py:227-234)
collapses into the *shape* of one 5-axis ``jax.sharding.Mesh``:

    ("replica", "fsdp", "expert", "context", "tensor")

- ddp   -> fsdp axis size 1, replica = world: params replicated, gradients
           psum'ed over "replica" by GSPMD (NCCL all-reduce analog).
- fsdp  -> replica 1, fsdp = world: params/opt state sharded over "fsdp";
           XLA inserts all-gather (fwd/bwd) + reduce-scatter (grads) over ICI.
- hsdp  -> replica = world // group, fsdp = group: shard within an ICI-local
           group, replicate across groups (DCN on multi-slice pods) —
           HYBRID_SHARD analog.
- expert  -> expert-parallel axis (beyond-reference MoE training): MoE
           expert weights shard their E dim here, while the axis doubles as
           a data axis for dense layers (DATA_AXES) — the dispatch/combine
           einsums reshard tokens batch->expert, which GSPMD lowers to the
           all-to-all pair of classic EP.
- tensor  -> megatron-style TP axis (speculator parity + headroom).
- context -> sequence/ring-attention axis (beyond-reference long-context).

Axis order places "replica" outermost (slowest-varying = DCN on multi-slice)
and "tensor" innermost (fastest ICI neighborhood).
"""

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_REPLICA = "replica"
AXIS_FSDP = "fsdp"
AXIS_EXPERT = "expert"
AXIS_CONTEXT = "context"
AXIS_TENSOR = "tensor"
MESH_AXES = (AXIS_REPLICA, AXIS_FSDP, AXIS_EXPERT, AXIS_CONTEXT, AXIS_TENSOR)

# Axes a batch is sharded over (all data-parallel dimensions). The expert
# axis is data-parallel for every dense computation; only MoE dispatch
# reshards from it (see module docstring).
DATA_AXES = (AXIS_REPLICA, AXIS_FSDP, AXIS_EXPERT)


@dataclass(frozen=True)
class MeshConfig:
    sharding_strategy: str = "hsdp"  # ddp | fsdp | hsdp | tp
    sharding_group_size: Optional[int] = None  # fsdp-axis size under hsdp
    tensor_parallel_size: int = 1
    context_parallel_size: int = 1
    expert_parallel_size: int = 1

    @classmethod
    def from_train_config(cls, cfg):
        return cls(
            sharding_strategy=cfg.sharding_strategy,
            sharding_group_size=getattr(cfg, "sharding_group_size", None),
            tensor_parallel_size=getattr(cfg, "tensor_parallel_size", 1),
            context_parallel_size=getattr(cfg, "context_parallel_size", 1),
            expert_parallel_size=getattr(cfg, "expert_parallel_size", 1),
        )


def _default_group_size(n_dp: int) -> int:
    """HSDP group size when unspecified: devices per host if the world spans
    multiple hosts (the reference shards within the 8-GPU node,
    ref:README), else the full data-parallel extent."""
    local = jax.local_device_count()
    if n_dp % local == 0 and n_dp > local:
        return local
    return n_dp


def build_mesh(
    mesh_config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence] = None,
    **overrides,
) -> Mesh:
    """Build the 4-axis mesh from a MeshConfig (or kwargs)."""
    if mesh_config is None:
        mesh_config = MeshConfig(**overrides)
    devices = list(devices if devices is not None else jax.devices())
    world = len(devices)

    tp = mesh_config.tensor_parallel_size or 1
    cp = mesh_config.context_parallel_size or 1
    ep = mesh_config.expert_parallel_size or 1
    if world % (tp * cp * ep) != 0:
        raise ValueError(
            f"world size {world} not divisible by "
            f"tensor*context*expert = {tp * cp * ep}"
        )
    n_dp = world // (tp * cp * ep)

    strategy = mesh_config.sharding_strategy
    if strategy == "ddp":
        replica, fsdp = n_dp, 1
    elif strategy in ("fsdp", "tp"):
        # "tp" (speculator path) shards the base model over the remaining
        # devices FSDP-style alongside the tensor axis
        # (ref:speculator/train_speculator.py:133-160).
        replica, fsdp = 1, n_dp
    elif strategy == "hsdp":
        group = mesh_config.sharding_group_size or _default_group_size(n_dp)
        if n_dp % group != 0:
            raise ValueError(
                f"data-parallel extent {n_dp} not divisible by sharding group {group}"
            )
        replica, fsdp = n_dp // group, group
    else:
        raise ValueError(f"unknown sharding strategy: {strategy}")

    shape = (replica, fsdp, ep, cp, tp)
    device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(device_array, MESH_AXES)


def data_parallel_extent(mesh: Mesh) -> int:
    """Number of ways the global batch is split (product of DATA_AXES)."""
    return int(np.prod([mesh.shape[a] for a in DATA_AXES]))
