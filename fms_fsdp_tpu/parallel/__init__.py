from fms_fsdp_tpu.parallel.ac import parse_ac_fraction, selective_ac_mask
from fms_fsdp_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    num_mesh_slices,
    process_slice_context,
)
from fms_fsdp_tpu.parallel.mixed_precision import (
    DtypePolicy,
    bfSixteen,
    bfSixteen_working,
    fp32_policy,
    get_dtype_policy,
)
from fms_fsdp_tpu.parallel.sharding import (
    batch_pspec,
    hierarchical_reduce_info,
    llama_param_specs,
    shard_params,
)

__all__ = [
    "MeshConfig",
    "build_mesh",
    "num_mesh_slices",
    "process_slice_context",
    "hierarchical_reduce_info",
    "DtypePolicy",
    "bfSixteen",
    "bfSixteen_working",
    "fp32_policy",
    "get_dtype_policy",
    "selective_ac_mask",
    "parse_ac_fraction",
    "llama_param_specs",
    "batch_pspec",
    "shard_params",
]
