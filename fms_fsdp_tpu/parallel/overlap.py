"""Bucketed cross-slice (DCN) gradient reduction, scheduled for overlap.

Param specs never name the ``dcn`` axis (parallel/sharding.py), so on a
multi-slice mesh GSPMD owns the placement of every cross-slice gradient
all-reduce. Left alone, the latency-hiding scheduler is free to sink
those reduces toward the step tail, where the narrow DCN link is fully
exposed latency (ROADMAP item 3; *SimpleFSDP* and *Memory and Bandwidth
are All You Need for FSDP* both put FSDP throughput exactly here).

This module makes the reduction *explicit and scheduled* without
touching numerics:

- ``assign_buckets`` partitions the gradient tree into size-targeted
  buckets — a deterministic greedy pack over ``quant_leaf_key``-ordered
  leaves, pure host arithmetic over shapes, so every process (and every
  restart) computes the identical schedule;
- ``apply_bucket_anchors`` wraps each bucket's param leaves in a
  ``jax.custom_vjp`` identity whose backward pins each cotangent to its
  resolved (dcn-replicated) sharding with ``with_sharding_constraint``
  and fuses the bucket's cotangents with ``optimization_barrier`` under
  a ``dcn_bucket_reduce_<i>`` scope. The forward is the identity and the
  backward constrains to the sharding the gradient already must have, so
  the traced math is value-identical — the 2-slice e2e pins the final
  STATE_HASH bit-for-bit against the unbucketed path — but GSPMD now has
  K anchored reduce points threaded through the backward instead of one
  schedulable-anywhere blob, and XLA's latency-hiding scheduler can run
  bucket N's DCN hop under bucket N+1's backward compute;
- ``bucketed_quantized_grad_reduce`` composes the schedule with the
  quantized reduce wire (sharding.py::quantized_grad_reduce): the same
  per-leaf round-trip and per-leaf amax keying/rolling, iterated
  bucket-by-bucket so each bucket's wire work is graph-adjacent to its
  reduce. The single-draw numerics contract is unchanged.

The bucket size comes from the ``dcn_bucket`` tuning entry
(tune/candidates.py cost model, KERNEL_TUNING.json, resolve_dcn_bucket)
unless pinned via ``TrainConfig.dcn_bucket_mb``. The resolved schedule
is published module-level (``plan_summary``) the way tune/lookup.py
publishes kernel choices, so entry points (dryrun rows, the obs
collective probe, the observer's ``dcn_overlap_frac``) can read what the
step was actually built with.
"""

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fms_fsdp_tpu.parallel.mesh import num_mesh_slices
from fms_fsdp_tpu.parallel.sharding import (
    quant_leaf_key,
    resolve_spec,
)

MB = 1024 * 1024


def wire_bytes_per_element(reduce_quant: str) -> int:
    """Bytes per gradient element on the reduce wire: 1 for the fp8/int8
    wire formats, 2 (bf16) otherwise."""
    return 1 if reduce_quant in ("int8", "fp8", "fp8_delayed") else 2


@dataclass(frozen=True)
class BucketPlan:
    """One resolved bucket schedule: ``buckets[i]`` is the tuple of
    ``quant_leaf_key`` leaf names reduced together, ``bucket_bytes[i]``
    their summed wire bytes."""

    buckets: Tuple[Tuple[str, ...], ...]
    bucket_bytes: Tuple[int, ...]
    target_mb: int
    wire_bytes: int
    total_bytes: int

    def summary(self) -> dict:
        return {
            "buckets": len(self.buckets),
            "bytes_per_bucket": list(self.bucket_bytes),
            "target_mb": self.target_mb,
            "wire_bytes": self.wire_bytes,
            "total_bytes": self.total_bytes,
        }


def assign_buckets(params, target_mb: int, wire_bytes: int) -> BucketPlan:
    """Deterministic size-targeted bucket assignment over the param(-
    shaped) tree.

    Leaves are ordered by ``quant_leaf_key`` (the same flat names the
    amax state is keyed by), then greedily packed: a bucket closes when
    adding the next leaf would push it past ``target_mb`` of wire bytes.
    Only leaf names and sizes are consumed — arrays and
    ``ShapeDtypeStruct``s both work, and the assignment is identical on
    every process and independent of any ``quant`` state riding in the
    train state (it is computed from the params tree alone).
    """
    target_bytes = max(1, int(target_mb)) * MB
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    keyed = sorted(
        (quant_leaf_key(path), int(leaf.size) * wire_bytes)
        for path, leaf in flat
    )
    buckets, sizes = [], []
    cur, cur_bytes = [], 0
    for key, nbytes in keyed:
        if cur and cur_bytes + nbytes > target_bytes:
            buckets.append(tuple(cur))
            sizes.append(cur_bytes)
            cur, cur_bytes = [], 0
        cur.append(key)
        cur_bytes += nbytes
    if cur:
        buckets.append(tuple(cur))
        sizes.append(cur_bytes)
    return BucketPlan(
        buckets=tuple(buckets),
        bucket_bytes=tuple(sizes),
        target_mb=int(target_mb),
        wire_bytes=int(wire_bytes),
        total_bytes=sum(sizes),
    )


def overlap_enabled(dcn_overlap: str, mesh: Mesh) -> bool:
    """Resolve the TrainConfig knob against the mesh. ``"off"`` never,
    ``"on"`` always, ``"auto"`` only when the mesh actually has a dcn
    extent > 1 — a single-slice mesh has no cross-slice reduce to
    schedule, and skipping keeps its traced program bit-identical to
    the pre-overlap step (pinned by tests/test_overlap.py)."""
    mode = (dcn_overlap or "auto").lower()
    if mode == "off":
        return False
    if mode == "on":
        return True
    if mode != "auto":
        raise ValueError(
            f"dcn_overlap must be off|auto|on, got {dcn_overlap!r}"
        )
    return num_mesh_slices(mesh) > 1


def apply_bucket_anchors(params, plan: BucketPlan, specs, mesh: Mesh):
    """Return ``params`` with each bucket routed through a custom_vjp
    identity that anchors the bucket's gradient reduce.

    ``specs`` is the param PartitionSpec tree (the model family's
    ``specs_fn()``); each cotangent is constrained to its
    divisibility-resolved spec — the sharding the gradient must hold
    anyway (dcn-replicated, i.e. fully reduced across slices), which is
    what forces GSPMD to materialize the cross-slice all-reduce at the
    anchor instead of wherever the scheduler drifts it. The
    ``optimization_barrier`` keeps one bucket's cotangents fused as a
    scheduling unit. Value-wise both ops are identities: the traced math
    is unchanged bit-for-bit.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    leaf_by_key = {quant_leaf_key(path): leaf for path, leaf in flat}
    spec_by_key = {
        quant_leaf_key(path): spec
        for path, spec in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
    }
    anchored_by_key = {}
    for bi, bucket in enumerate(plan.buckets):
        leaves = tuple(leaf_by_key[k] for k in bucket)
        shardings = tuple(
            NamedSharding(
                mesh,
                resolve_spec(
                    spec_by_key.get(k, P()), leaf_by_key[k].shape, mesh
                ),
            )
            for k in bucket
        )

        @jax.custom_vjp
        def _anchor(*ls):
            return tuple(ls)

        def _fwd(*ls):
            return tuple(ls), None

        def _bwd(_, cts, _shardings=shardings, _bi=bi):
            with jax.named_scope(f"dcn_bucket_reduce_{_bi}"):
                out = tuple(
                    jax.lax.with_sharding_constraint(g, s)
                    for g, s in zip(cts, _shardings)
                )
                return jax.lax.optimization_barrier(out)

        _anchor.defvjp(_fwd, _bwd)
        for k, leaf in zip(bucket, _anchor(*leaves)):
            anchored_by_key[k] = leaf
    new_leaves = [anchored_by_key[quant_leaf_key(p)] for p, _ in flat]
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def bucketed_quantized_grad_reduce(
    grads, mode: str, quant_state=None, plan: Optional[BucketPlan] = None
):
    """``quantized_grad_reduce`` iterated bucket-by-bucket.

    Identical numerics and amax keying to the per-leaf loop in
    parallel/sharding.py (ONE quantization draw on the globally-summed
    gradient; per-leaf delayed scale from the same ``quant_leaf_key``
    rows, rolled per leaf): the only difference is graph adjacency —
    each bucket's wire round-trip traces under its own
    ``quant_reduce_bucket_<i>`` scope so it schedules next to that
    bucket's anchored reduce rather than as one monolithic tail region.
    """
    from fms_fsdp_tpu.ops.quant import (
        delayed_scale,
        leaf_amax,
        roll_amax_history,
        wire_roundtrip,
    )

    if plan is None:
        from fms_fsdp_tpu.parallel.sharding import quantized_grad_reduce

        return quantized_grad_reduce(grads, mode, quant_state)
    if mode not in ("int8", "fp8", "fp8_delayed"):
        raise ValueError(f"unknown quantized_reduce mode: {mode!r}")
    bucket_of = {
        k: bi for bi, bucket in enumerate(plan.buckets) for k in bucket
    }
    flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
    out_by_key = {}
    new_hist = {}
    history = quant_state["amax_history"] if mode == "fp8_delayed" else None
    for bi in range(len(plan.buckets)):
        group = [
            (quant_leaf_key(path), g)
            for path, g in flat
            if bucket_of.get(quant_leaf_key(path)) == bi
        ]
        with jax.named_scope(f"quant_reduce_bucket_{bi}"):
            for key, g in group:
                if mode == "fp8_delayed":
                    amax = leaf_amax(g)
                    scale = delayed_scale(history[key], amax)
                    out_by_key[key] = wire_roundtrip(
                        g, "fp8_delayed", scale=scale
                    )
                    new_hist[key] = roll_amax_history(history[key], amax)
                else:
                    out_by_key[key] = wire_roundtrip(g, mode)
    # leaves the plan does not cover (never the case for plans built
    # from the same param tree, but keep the round-trip total) go
    # through the same per-leaf path unscoped
    for path, g in flat:
        key = quant_leaf_key(path)
        if key in out_by_key:
            continue
        if mode == "fp8_delayed":
            amax = leaf_amax(g)
            scale = delayed_scale(history[key], amax)
            out_by_key[key] = wire_roundtrip(g, "fp8_delayed", scale=scale)
            new_hist[key] = roll_amax_history(history[key], amax)
        else:
            out_by_key[key] = wire_roundtrip(g, mode)
    out = jax.tree_util.tree_unflatten(
        treedef, [out_by_key[quant_leaf_key(p)] for p, _ in flat]
    )
    if mode == "fp8_delayed":
        return out, {"amax_history": new_hist}
    return out, quant_state


# ---------------------------------------------------------------------------
# resolved-schedule registry (mirrors tune/lookup.py's choices()): set once
# per step build, read by dryrun rows, the obs collective probe, and the
# observer's dcn_overlap_frac estimate
# ---------------------------------------------------------------------------

_PLAN_SUMMARY: Optional[dict] = None


def set_plan_summary(summary: Optional[dict]) -> None:
    global _PLAN_SUMMARY
    _PLAN_SUMMARY = dict(summary) if summary else None


def plan_summary() -> Optional[dict]:
    """The schedule the most recent ``make_train_step`` resolved, or None
    when overlap was off/disabled at the last step build."""
    return dict(_PLAN_SUMMARY) if _PLAN_SUMMARY else None
