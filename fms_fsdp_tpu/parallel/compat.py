"""JAX API compatibility shims.

The kernels and MoE dispatch target the jax >= 0.8 surface
(``jax.shard_map`` with ``check_vma`` / ``axis_names``); older
environments (< 0.5) only ship ``jax.experimental.shard_map.shard_map``
with ``check_rep`` / ``auto``. This module presents the new-style
signature on either, so a version mismatch degrades to a shim instead of
an ImportError that takes out every sharded kernel path (robustness:
version skew between the pinned dev env and a site's jax install is a
deployment fault, not a crash).
"""

try:  # jax >= 0.8: top-level export, check_vma kwarg
    from jax import shard_map as _new_shard_map
except ImportError:
    _new_shard_map = None
    from jax.experimental.shard_map import shard_map as _old_shard_map


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, axis_names=None):
    """``jax.shard_map`` facade with the >= 0.8 keyword surface.

    ``axis_names`` (new API: the axes the body is manual over; None =
    all) maps onto the legacy ``auto`` complement; ``check_vma`` maps
    onto legacy ``check_rep``.
    """
    if _new_shard_map is not None:
        kwargs = dict(
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return _new_shard_map(f, **kwargs)
    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _old_shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
        auto=auto,
    )


def has_new_shard_map() -> bool:
    return _new_shard_map is not None


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across the jax >= 0.7 rename (older
    releases call it ``TPUCompilerParams``; same dataclass fields)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:  # pre-rename jax
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
