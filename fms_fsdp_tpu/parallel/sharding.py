"""PartitionSpec rulebook — the TPU replacement for FSDP wrapping policies.

The reference wraps every transformer block as an FSDP unit and lets the
FlatParameter runtime all-gather / reduce-scatter it
(ref:fms_fsdp/policies/wrapping.py:6-14, main_training_llama.py:82-91).
Here the same intent is a *declarative map* from every parameter to a
``PartitionSpec`` over the mesh axes; GSPMD inserts the collectives.

Conventions (see mesh.py for axis meaning):
- every weight matrix shards its model-dim over "fsdp" and its head/ffn
  output dim over "tensor" (megatron layout: column-parallel in, row-parallel
  out), so fsdp-only meshes get pure ZeRO-3 sharding and tensor meshes get
  TP with no code change;
- norms are replicated (bytes are trivial; avoids all-gather latency);
- a spec dim is silently dropped (replicated) when the dim size is not
  divisible by the mesh axis extent, so tiny debug models run on any mesh.
"""

from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fms_fsdp_tpu.parallel.mesh import (
    AXIS_CONTEXT,
    AXIS_DCN,
    AXIS_FSDP,
    AXIS_REPLICA,
    AXIS_TENSOR,
    DATA_AXES,
)


def batch_pspec() -> P:
    """Spec for (B, S) token batches: batch over all data axes (dcn
    included — each slice holds its own rows), sequence over the context
    axis (ring attention); replicated over tensor."""
    return P(DATA_AXES, AXIS_CONTEXT)


def hierarchical_reduce_info(mesh: Mesh) -> Dict[str, tuple]:
    """Name the two transport tiers the gradient reduce decomposes over
    on this mesh (docs/train_details.md "Multi-slice").

    Param specs never mention the ``dcn`` axis, so params replicate
    across slices; the batch is sharded over DATA_AXES (dcn included),
    so GSPMD lowers the backward's gradient reduction hierarchically —
    reduce-scatter/all-gather over the within-slice ICI axes, plus ONE
    all-reduce across slices over the dcn axis. ``dcn_axes`` is empty on
    single-slice meshes: a size-1 axis generates no collective, keeping
    the traced step bit-identical to the pre-dcn program (pinned by
    tests/test_sharding.py). The quantized-reduce wire
    (``quantized_grad_reduce``) sits at exactly this boundary — on
    multi-slice meshes the round-trip models the DCN hop, which is where
    the bandwidth lever pays most (PAPERS.md "Memory and Bandwidth are
    All You Need for Fully Sharded Data Parallel")."""
    ici = tuple(
        a for a in DATA_AXES if a != AXIS_DCN and mesh.shape[a] > 1
    )
    dcn = (AXIS_DCN,) if mesh.shape[AXIS_DCN] > 1 else ()
    return {"ici_axes": ici, "dcn_axes": dcn}


def embed_lookup(table, tokens, mesh: Optional[Mesh]):
    """Token-embedding gather that partitions cleanly.

    The table is stored P(tensor, fsdp). A direct ``table[tokens]`` makes
    the gather output inherit the table's feature-dim (fsdp) sharding,
    and the subsequent reshard to batch sharding is one GSPMD cannot do
    efficiently — it falls back to "involuntary full rematerialization"
    (replicate the whole (B, S, D) activation, then re-partition).

    Constraining the table to P(tensor, None) *before* the gather moves
    the all-gather to the table weight (the same bytes FSDP all-gathers
    for every other layer's weights) and keeps the vocab dim sharded
    over tensor, which GSPMD partitions with the standard clamp + select
    + psum trick; the output is then born batch-sharded with the feature
    dim replicated — exactly the layout the model constrains `x` to.
    """
    if mesh is None:
        return table[tokens]
    table = constrain(table, P(AXIS_TENSOR, None), mesh)
    x = table[tokens]
    return constrain(x, P(DATA_AXES, AXIS_CONTEXT, None), mesh)


def llama_param_specs(scan: bool = True) -> Dict[str, Any]:
    """Spec tree matching the Llama param tree (models/llama.py).

    Layer params are stacked on a leading L axis (for lax.scan), which is
    never sharded — sharding happens within each layer's weight, mirroring
    the reference's per-block FSDP units.
    """
    l = (None,) if scan else ()
    layers = {
        "attn_norm": P(*l, None),
        "wq": P(*l, AXIS_FSDP, AXIS_TENSOR),
        "wk": P(*l, AXIS_FSDP, AXIS_TENSOR),
        "wv": P(*l, AXIS_FSDP, AXIS_TENSOR),
        "wo": P(*l, AXIS_TENSOR, AXIS_FSDP),
        "ffn_norm": P(*l, None),
        "w1": P(*l, AXIS_FSDP, AXIS_TENSOR),
        "w3": P(*l, AXIS_FSDP, AXIS_TENSOR),
        "w2": P(*l, AXIS_TENSOR, AXIS_FSDP),
    }
    return {
        "embedding": P(AXIS_TENSOR, AXIS_FSDP),
        "layers": layers,
        "norm": P(None),
        "lm_head": P(AXIS_FSDP, AXIS_TENSOR),
    }


def resolve_spec(spec: P, shape, mesh: Mesh) -> P:
    """Drop spec entries whose mesh extent does not divide the dim size.

    Axes the mesh does not carry are dropped from the entry first (a
    5-axis legacy mesh — or any future submesh — consumes the shared
    dcn-bearing specs without a KeyError; a dropped axis is exactly a
    size-1 axis sharding-wise)."""
    out = []
    for i, entry in enumerate(spec):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        present = tuple(a for a in axes if a in mesh.shape)
        if not present:
            out.append(None)
            continue
        entry = present if isinstance(entry, tuple) else present[0]
        extent = int(np.prod([mesh.shape[a] for a in present]))
        if i < len(shape) and shape[i] % extent == 0:
            out.append(entry)
        else:
            out.append(None)
    return P(*out)


def constrain(x, spec: Optional[P], mesh: Optional[Mesh]):
    """``with_sharding_constraint`` with divisibility-resolved spec;
    no-op without a mesh. The one constraint helper shared by every model
    family (llama/mamba/mixtral)."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, resolve_spec(spec, x.shape, mesh))
    )


def named_sharding(mesh: Mesh, spec: P, shape=None) -> NamedSharding:
    if shape is not None:
        spec = resolve_spec(spec, shape, mesh)
    return NamedSharding(mesh, spec)


def tree_shardings(mesh: Mesh, specs, shapes=None):
    """Map a spec pytree (+ optional matching shape pytree) to NamedShardings."""
    if shapes is None:
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )
    return jax.tree.map(
        lambda s, shp: named_sharding(mesh, s, tuple(shp)),
        specs,
        shapes,
        is_leaf=lambda x: isinstance(x, P),
    )


def _path_key(entry) -> str:
    """Normalize a tree_util key entry (DictKey/GetAttrKey/SequenceKey/...)."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def infer_state_specs(state_shapes, param_specs, params_subtree: str = "params"):
    """Spec tree for a full train state {params, opt_state, step, ...}.

    The optimizer state (optax adamw mu/nu) mirrors the param tree
    structurally, so each state leaf is matched to the param spec whose
    key-path is a suffix of the leaf's key-path; unmatched leaves (step
    counters, schedule counts) are replicated. This is the TPU analog of
    FSDP's sharded optimizer state (ZeRO: opt shards follow param shards,
    ref:checkpointing_utils.py:259-271 relies on the same correspondence).
    """
    flat_specs = {}
    for path, spec in jax.tree_util.tree_flatten_with_path(
        param_specs, is_leaf=lambda x: isinstance(x, P)
    )[0]:
        flat_specs[tuple(_path_key(e) for e in path)] = spec

    def spec_for(path, leaf):
        keys = tuple(_path_key(e) for e in path)
        if keys and keys[0] == params_subtree and keys[1:] in flat_specs:
            return flat_specs[keys[1:]]
        for i in range(len(keys)):
            if keys[i:] in flat_specs:
                return flat_specs[keys[i:]]
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, state_shapes)


# ---------------------------------------------------------------------------
# quantized gradient reduction (scale-carrying wire format)
# ---------------------------------------------------------------------------


def quant_leaf_key(path) -> str:
    """Stable dotted name for one gradient leaf. The flat "g."-prefixed
    string (not a nested tree) is load-bearing twice: the amax-state
    keys must NOT suffix-match the param spec paths in
    ``infer_state_specs`` (a (H,) history row sharded like its (D, F)
    weight would be nonsense — the prefix guarantees no key, top-level
    leaves included, ever matches), and flat string keys checkpoint as
    ordinary pytree dict entries."""
    return "g." + ".".join(_path_key(e) for e in path)


def init_amax_state(params_shapes, history_len: int):
    """Fresh delayed-scaling state for a param(-shaped) tree: one (H,)
    fp32 amax-history row per gradient leaf, newest at index 0, all
    zeros (the first step bootstraps from its own dynamic amax — see
    ops/quant.py::delayed_scale). Lives in the train state under
    ``state["quant"]`` so it checkpoints, donates, and elastic-reshards
    (replicated — unmatched by infer_state_specs) like optimizer state."""
    import jax.numpy as jnp

    flat = jax.tree_util.tree_flatten_with_path(params_shapes)[0]
    return {
        "amax_history": {
            quant_leaf_key(path): jnp.zeros((history_len,), jnp.float32)
            for path, _ in flat
        }
    }


def quantized_grad_reduce(grads, mode: str, quant_state=None):
    """Scale-carrying quantized gradient reduction: round-trip every
    gradient leaf through the reduce wire format (int8 / e5m2 fp8 with
    per-row scales, or a per-leaf delayed scale from the amax history).

    Returns ``(grads, new_quant_state)`` — the round-tripped gradients,
    and (fp8_delayed only) the rolled amax history.

    Numerics contract (what the loss-parity tests pin): ONE
    quantization draw on the globally-summed gradient — the tree
    surfacing from the backward is already reduced under GSPMD, so this
    models the wire's resolution, not a true per-rank reduce-scatter
    (which would deliver sum(roundtrip(g_i)): N independent noise draws
    on the partials, strictly noisier than the single draw here). A
    future in-collective implementation (custom reduce-scatter over the
    wire dtype, the actual bandwidth win) must re-pin the parity
    tolerances against that per-shard formulation; docs/performance.md
    "Quantized training" states the contract and this limit.

    Multi-slice composition (``hierarchical_reduce_info``): on a mesh
    with a dcn axis > 1 the reduce boundary this round-trip models is
    the cross-slice DCN all-reduce — the narrowest link in the
    hierarchy, so the wire format's byte savings land where they pay
    most. The single-draw contract above is unchanged: per-slice
    partials over ICI stay full-precision in this model.
    """
    from fms_fsdp_tpu.ops.quant import (
        delayed_scale,
        leaf_amax,
        roll_amax_history,
        wire_roundtrip,
    )

    if mode in ("int8", "fp8"):
        return jax.tree.map(lambda g: wire_roundtrip(g, mode), grads), quant_state
    if mode != "fp8_delayed":
        raise ValueError(f"unknown quantized_reduce mode: {mode!r}")
    history = quant_state["amax_history"]
    flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
    out_leaves = []
    new_hist = {}
    for path, g in flat:
        key = quant_leaf_key(path)
        amax = leaf_amax(g)
        scale = delayed_scale(history[key], amax)
        out_leaves.append(wire_roundtrip(g, "fp8_delayed", scale=scale))
        new_hist[key] = roll_amax_history(history[key], amax)
    grads = jax.tree_util.tree_unflatten(treedef, out_leaves)
    return grads, {"amax_history": new_hist}


# ---------------------------------------------------------------------------
# serving layout (ServeConfig.serve_layout — docs/serving.md "Sharded
# replicas & disaggregation")
# ---------------------------------------------------------------------------

# a serving replica's mesh carries only the two axes serving shards
# over: fsdp (ZeRO-style weight sharding) and tensor (megatron TP over
# heads/ffn). The train-side spec rulebooks (llama_param_specs,
# mixtral_param_specs) never name any other axis on a weight, so
# resolve_spec consumes them on this submesh unchanged — one rulebook,
# train and serve.
SERVE_MESH_AXES = (AXIS_FSDP, AXIS_TENSOR)


def parse_serve_layout(layout: str) -> Dict[str, int]:
    """``"tp=2"`` / ``"tp=2,fsdp=2"`` -> {"tensor": 2, "fsdp": 2}.

    Empty string means single-chip (the caller skips mesh construction
    entirely — every existing parity anchor runs that path untouched).
    Unknown keys and non-positive extents are typed config errors."""
    out = {AXIS_TENSOR: 1, AXIS_FSDP: 1}
    if not layout:
        return out
    names = {"tp": AXIS_TENSOR, "tensor": AXIS_TENSOR, "fsdp": AXIS_FSDP}
    for part in layout.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        axis = names.get(key.strip())
        if axis is None:
            raise ValueError(
                f"unknown serve_layout axis {key.strip()!r} in "
                f"{layout!r}: expected 'tp' and/or 'fsdp' "
                f"(e.g. \"tp=2\" or \"tp=2,fsdp=2\")"
            )
        try:
            extent = int(val)
        except ValueError:
            extent = 0
        if extent <= 0:
            raise ValueError(
                f"serve_layout axis {key.strip()!r} needs a positive "
                f"integer extent, got {val!r} in {layout!r}"
            )
        out[axis] = extent
    return out


def serve_layout_code(layout: str) -> int:
    """Numeric shard-layout code for flat str->number obs maps (schema
    v13 ``serving.serve_layout``): ``100 * tp + fsdp``, 0 for the
    single-chip layout (no mesh)."""
    if not layout:
        return 0
    ext = parse_serve_layout(layout)
    return 100 * ext[AXIS_TENSOR] + ext[AXIS_FSDP]


def build_serve_mesh(layout: str, devices=None) -> Optional[Mesh]:
    """``serve_layout`` string -> the replica's 2-axis serving mesh
    (None for the single-chip layout). Uses the first tp*fsdp visible
    devices; fewer than that is a hard config error — a sharded replica
    that silently ran single-chip would misreport its capacity to the
    fleet router."""
    ext = parse_serve_layout(layout)
    n = ext[AXIS_FSDP] * ext[AXIS_TENSOR]
    if n <= 1:
        return None
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < n:
        raise ValueError(
            f"serve_layout {layout!r} needs {n} devices "
            f"(fsdp={ext[AXIS_FSDP]} x tensor={ext[AXIS_TENSOR]}) but "
            f"only {len(devices)} are visible"
        )
    arr = np.asarray(devices[:n]).reshape(
        ext[AXIS_FSDP], ext[AXIS_TENSOR]
    )
    return Mesh(arr, SERVE_MESH_AXES)


def serve_kv_pool_specs(quant: str = "none") -> Dict[str, P]:
    """PartitionSpecs for the PagedKVCache pools on a serving mesh:
    (L, P, page_size, Nkv, H) pools shard the kv-head dim over the
    tensor axis — the same placement the train-side cache uses, and the
    layout *Ragged Paged Attention* (PAPERS.md) serves from. Scale
    pools (quantized storage) are (L, P, page_size, Nkv, 1) and shard
    identically. resolve_spec drops the entry when Nkv does not divide
    tp, so tiny debug models stay replicated instead of failing."""
    spec = P(None, None, None, AXIS_TENSOR, None)
    out = {"k": spec, "v": spec}
    if quant != "none":
        out["k_scale"] = spec
        out["v_scale"] = spec
    return out


def serve_param_specs(family: str):
    """Family -> the param spec rulebook a sharded serving replica
    places weights with (None = replicate every leaf). Mamba has no
    rulebook yet — its adapter rejects serve_layout with the fix
    spelled out, so this never resolves for it."""
    if family == "llama":
        return llama_param_specs(scan=True)
    if family == "mixtral":
        from fms_fsdp_tpu.models.mixtral import mixtral_param_specs

        return mixtral_param_specs(scan=True)
    return None


def shard_params(params, specs, mesh: Mesh):
    """Place a param pytree on the mesh per the spec tree (host -> device).

    ``specs=None`` replicates every leaf — the frozen-base fallback for
    model families without a dedicated spec rulebook."""
    if specs is None:
        sharding = jax.sharding.NamedSharding(mesh, P())
        return jax.device_put(params, sharding)
    shardings = jax.tree.map(
        lambda p, s: named_sharding(mesh, s, np.shape(p)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(params, shardings)
