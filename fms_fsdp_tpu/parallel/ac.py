"""Selective activation checkpointing (remat) policy.

The reference checkpoints a fraction ``p`` of transformer blocks, evenly
spaced, via a stateful counter walk over blocks
(ref:fms_fsdp/policies/ac_handler.py:16-64):

    block_idx += 1
    if block_idx * p >= cut_off: cut_off += 1 -> checkpoint this block

On TPU the same selection becomes a static boolean mask over layers that
chooses where ``jax.checkpoint`` (remat) is applied in the layer stack —
XLA then recomputes those blocks' activations in the backward pass instead
of saving them, trading MXU FLOPs for HBM exactly like the reference trades
CUDA FLOPs for GPU memory.
"""

from fractions import Fraction
from typing import List, Union


def parse_ac_fraction(p: Union[float, int, str]) -> float:
    """Fraction strings like "1/3" arrive via CLI argv; the reference
    ``eval``s them (ref:ac_handler.py:45-47). We parse safely instead."""
    if isinstance(p, str):
        return float(Fraction(p))
    return float(p)


def selective_ac_mask(nlayers: int, p: Union[float, int, str]) -> List[bool]:
    """Per-layer remat mask replicating the reference's counter walk exactly
    (ref:ac_handler.py:43-58). p=0 -> no remat, p=1 -> full remat, p=1/2 ->
    [T,F,T,F,...], p=1/3 -> [F,T,F, F,T,F, ...], p=2/3 -> [T,F,T, T,F,T, ...].
    """
    p = parse_ac_fraction(p)
    mask = []
    block_idx = 0
    cut_off = 1 / 2
    for _ in range(nlayers):
        block_idx += 1
        if block_idx * p >= cut_off:
            cut_off += 1
            mask.append(True)
        else:
            mask.append(False)
    return mask
