"""Mixed precision (dtype) policies.

The reference's FSDP ``MixedPrecision`` presets
(ref:fms_fsdp/policies/mixed_precision.py:5-27):

- ``bfSixteen``          param bf16 / reduce bf16 / buffer bf16 — but FSDP
  keeps the fp32 sharded master copy for the optimizer. TPU equivalent:
  params + optimizer state fp32, cast to bf16 on entry to the forward,
  gradients reduce in bf16 and are accumulated to fp32 for the update.
- ``bfSixteen_working``  params genuinely bf16, reduce fp32.
- ``fpSixteen``          fp16 variant (CUDA fallback; on TPU bf16 is always
  available so this exists only for completeness).
- ``fp32_policy``        everything fp32.

On TPU this is a pure dtype policy — there is no wrapper machinery; casts
happen inside the jitted step and XLA fuses them into adjacent ops.

``reduce_dtype`` note: with GSPMD the cross-device gradient reduction runs
in the dtype the gradient has at the point XLA inserts the collective —
for the bfSixteen policy that is bf16 (the reduce-scatter mirrors the
forward's bf16 all-gather), matching the reference preset. It is recorded
here for parity/reporting; the train step additionally casts gradients to
``param_dtype`` before the optimizer so Adam math always runs in the
storage precision.

``reduce_quant`` extends the policy below bf16: *Memory and Bandwidth
are All You Need for FSDP* (PAPERS.md) argues FSDP throughput is
bandwidth-bound, which makes the gradient reduce-scatter bytes the
direct lever — the 1-byte int8/fp8 wire formats halve them against
bf16 (4x against an fp32 reduce).
The scale-carrying reduce itself lives in
parallel/sharding.py::quantized_grad_reduce; "none" is bit-identical to
today's step (the reduce path is not even traced).
"""

from dataclasses import dataclass

import jax.numpy as jnp

# legal TrainConfig.quantized_reduce / DtypePolicy.reduce_quant values
REDUCE_QUANT_MODES = ("none", "int8", "fp8", "fp8_delayed")


@dataclass(frozen=True)
class DtypePolicy:
    param_dtype: jnp.dtype = jnp.float32  # storage (and optimizer) dtype
    compute_dtype: jnp.dtype = jnp.bfloat16  # matmul / activation dtype
    reduce_dtype: jnp.dtype = jnp.bfloat16  # gradient cross-device reduction
    # gradient-reduction wire format below reduce_dtype: "none" (exact),
    # "int8" / "fp8" (dynamic per-row scales), "fp8_delayed" (per-leaf
    # scale from the amax history threaded through the train state)
    reduce_quant: str = "none"


bfSixteen = DtypePolicy(
    param_dtype=jnp.float32,
    compute_dtype=jnp.bfloat16,
    reduce_dtype=jnp.bfloat16,
)

bfSixteen_working = DtypePolicy(
    param_dtype=jnp.bfloat16,
    compute_dtype=jnp.bfloat16,
    reduce_dtype=jnp.float32,
)

fpSixteen = DtypePolicy(
    param_dtype=jnp.float32,
    compute_dtype=jnp.float16,
    reduce_dtype=jnp.float16,
)

fp32_policy = DtypePolicy(
    param_dtype=jnp.float32,
    compute_dtype=jnp.float32,
    reduce_dtype=jnp.float32,
)


def get_dtype_policy(cfg) -> DtypePolicy:
    """Map train config -> policy (ref:train_utils.py:192-214 chooses
    bfSixteen whenever bf16 is supported; on TPU it always is).
    ``cfg.quantized_reduce`` rides on whichever preset is selected."""
    rq = getattr(cfg, "quantized_reduce", "none") or "none"
    if rq not in REDUCE_QUANT_MODES:
        raise ValueError(
            f"quantized_reduce={rq!r}: expected one of {REDUCE_QUANT_MODES}"
        )
    if not getattr(cfg, "mixed_precision", True):
        policy = fp32_policy
    elif getattr(cfg, "pure_bf16", False):
        policy = bfSixteen_working
    else:
        policy = bfSixteen
    if rq == "none":
        return policy
    from dataclasses import replace

    return replace(policy, reduce_quant=rq)
