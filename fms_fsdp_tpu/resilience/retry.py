"""Bounded retry-with-exponential-backoff, and the retrying shard-file
handler that applies it to every storage touch the streaming pipeline
makes.

Transient shard-read errors (GCS/NFS flaking under pod-scale fan-in)
dominate long-job data-path failures; a bounded retry absorbs them, and
exhaustion surfaces the final error to the caller —
``StreamingDocDataset`` then quarantines the shard instead of killing
the run (see data/streaming.py).
"""

import logging
import time
from typing import Callable, Set

from fms_fsdp_tpu.data.handlers import ShardFileHandler
from fms_fsdp_tpu.resilience.faults import maybe_raise_fault

logger = logging.getLogger(__name__)

# errors worth retrying: transient storage/io flakes. Anything else
# (KeyError, schema mismatch, ...) is a real bug and propagates raw.
TRANSIENT_EXCEPTIONS = (OSError,)


def backoff_delay(
    attempt: int, backoff_s: float = 0.5, max_backoff_s: float = 30.0
) -> float:
    """The repo's one backoff schedule: ``backoff_s * 2^attempt``,
    capped at ``max_backoff_s``. Shared by the blocking ``retry_call``
    loop below and the non-blocking chunk retransmit timers in
    serve/disagg/transport.py (which cannot sleep — the router's
    dispatch loop runs between retries)."""
    return min(backoff_s * (2**attempt), max_backoff_s)


def retry_call(
    fn: Callable,
    *,
    retries: int = 3,
    backoff_s: float = 0.5,
    max_backoff_s: float = 30.0,
    exceptions=TRANSIENT_EXCEPTIONS,
    describe: str = "",
):
    """Call ``fn()``; on a transient exception retry up to ``retries``
    times with exponential backoff (backoff_s * 2^attempt, capped).
    Re-raises the final exception after exhaustion."""
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions as e:
            if attempt >= retries:
                raise
            delay = backoff_delay(attempt, backoff_s, max_backoff_s)
            attempt += 1
            logger.warning(
                "transient error in %s (attempt %d/%d, retrying in %.2fs): %s",
                describe or getattr(fn, "__name__", "call"),
                attempt,
                retries,
                delay,
                e,
            )
            time.sleep(delay)


class RetryingShardHandler(ShardFileHandler):
    """Wrap a ShardFileHandler so every open/length/get/slice retries
    transient errors with bounded exponential backoff.

    Also hosts the ``shard_read`` fault-injection site: the fault check
    runs inside the retried attempt, so a ``times=K`` transient fault is
    absorbed by the retry loop while a permanent one exhausts it —
    exercising both halves of the recovery path.

    ``get``/``slice`` receive no path, so the wrapper remembers the last
    opened one for error context (per-clone state: pipeline deepcopies
    clone the wrapper along with its reader).
    """

    def __init__(
        self,
        inner: ShardFileHandler,
        retries: int = 3,
        backoff_s: float = 0.5,
        max_backoff_s: float = 30.0,
    ):
        self.inner = inner
        self.retries = retries
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self._last_path = ""

    def _retry(self, op: str, path: str, fn: Callable):
        def attempt():
            maybe_raise_fault("shard_read", path=path, op=op)
            return fn()

        return retry_call(
            attempt,
            retries=self.retries,
            backoff_s=self.backoff_s,
            max_backoff_s=self.max_backoff_s,
            describe=f"shard {op} [{path}]",
        )

    def is_legal(self, filepath: str) -> bool:
        return self.inner.is_legal(filepath)

    def open(self, path: str):
        self._last_path = path
        return self._retry("open", path, lambda: self.inner.open(path))

    def length(self, path: str) -> int:
        return self._retry("length", path, lambda: self.inner.length(path))

    def get(self, reader, index: int, drop_tokens: Set):
        return self._retry(
            "get",
            self._last_path,
            lambda: self.inner.get(reader, index, drop_tokens),
        )

    def slice(self, doc, index: int, n_pull: int):
        return self._retry(
            "slice",
            self._last_path,
            lambda: self.inner.slice(doc, index, n_pull),
        )
