"""Cross-replica divergence detection: prove the dcn-replicated train
states still agree.

On a multi-slice mesh (parallel/mesh.py) every param/optimizer leaf is
REPLICATED across slices — GSPMD assumes the replicas are bit-identical
and no collective ever checks it. Silent data corruption (a defective
chip, a broken reduce, a flipped DMA) can diverge one slice's replica
and the run keeps training, healthy-looking, on two different models:
the post-reduce loss mixes both contributions and reads the same
everywhere, so the scalars the operator watches cannot catch it.

This module catches it at report cadence, for the cost of one pass of
on-device integer arithmetic and one tiny allgather:

- each process computes a **fingerprint**: the window's loss and
  grad-norm scalars (bit-patterns, not approximate compares) plus a
  jitted **whole-state checksum** — every leaf of the train state
  (params AND optimizer moments: opt-moment SDC reaches params only a
  step later, and by then a commit may have persisted the poison)
  bitcast to uint32 and wrap-summed on device, reduced within the
  slice, REPLICATED (i.e. redundantly recomputed, never communicated)
  across slices. One scalar crosses to the host per check. A
  single-leaf digest would not do: the gradient all-reduce hands every
  replica the SAME update, so corruption stays confined to exactly the
  leaves it hit and never spreads to a sentinel leaf — the checksum
  must cover the whole tree;
- fingerprints cross the wire via ``multihost_utils.process_allgather``
  (the same collective helper the checkpoint gate uses), packed into a
  fixed-shape int64 row — no variable-size payloads on the hot path;
- **every value must agree across every process**: the scalars are
  post-reduce replicated values, and the checksum is a per-replica
  recomputation of a nominally replicated quantity — any disagreement
  means a replica's state (or the reduce itself) is broken.

Disagreement means a replica silently diverged. That is not retryable
— every later step compounds it — so the check raises
:class:`StateDivergenceError`, which the entries' ``classified_exit``
maps to the ``state_divergence`` registry exit code; the run
supervisor's policy relaunches through elastic resume under the
VERIFIED-resume rule (restore only a scrub-verified checkpoint — the
newest one may already hold the diverged replica's poison;
resilience/scrub.py).

Fault site ``sdc_grad_flip`` injects exactly this failure — HOST-side,
at the ``_train_loop`` step boundary (utils/train_utils.py; the NOTE in
train/step.py explains why the in-trace site was abandoned): one
process's gradient is perturbed on a chosen step, its slice's replica
walks away, and the next fingerprint compare must catch it
(scripts/chaos_soak.py proves detection + verified-resume recovery end
to end).
"""

import hashlib
import struct
from typing import List, Optional, Tuple

import numpy as np

_TOTAL_CHECKS = 0


class StateDivergenceError(RuntimeError):
    """Raised when cross-replica fingerprints disagree: a replica's
    train state has silently diverged (SDC or a broken reduce). Mapped
    to the ``state_divergence`` exit code by ``classified_exit``."""


def total_checks() -> int:
    """Divergence checks performed by this process (obs schema v8
    ``divergence_checks``)."""
    return _TOTAL_CHECKS


def reset_checks() -> None:
    global _TOTAL_CHECKS
    _TOTAL_CHECKS = 0


def _digest64(payload: bytes) -> int:
    """First 8 bytes of sha256 as a signed int64 (allgather-friendly)."""
    return int.from_bytes(
        hashlib.sha256(payload).digest()[:8], "big", signed=True
    )


def _leaf_by_size(state, largest: bool) -> Tuple[str, object]:
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(state["params"])[0]
    assert leaves, "empty param tree"
    keyed = sorted(
        leaves,
        key=lambda kv: (
            int(np.prod(kv[1].shape)) * np.dtype(kv[1].dtype).itemsize,
            jax.tree_util.keystr(kv[0]),
        ),
        reverse=largest,
    )
    path, leaf = keyed[0]
    return jax.tree_util.keystr(path), leaf


_CHECKSUM_JIT = None


def state_checksum(state) -> int:
    """Per-replica whole-state checksum: EVERY leaf of the train state
    — params, optimizer moments, step, amax histories — bitcast to
    uint32 and wrap-summed (mod 2^32) on device. Optimizer state is
    covered deliberately: SDC in a replicated Adam moment reaches
    params only one step later, and a commit boundary in between
    persists the poison into a checkpoint every replica then restores
    uniformly — the compare must see it while it still disagrees. The
    sum reduces over the SHARDED axes (an in-slice collective); across
    the replicated dcn axis each replica redundantly recomputes it from
    its own bytes — which is the point: a diverged replica computes a
    different number, and the fetched scalar is this process's
    replica's answer.

    Exact integer arithmetic (no float rounding to hide a bit-flip),
    order-independent (safe under any reduction tiling), one device
    pass, one scalar to the host."""
    import jax
    import jax.numpy as jnp

    global _CHECKSUM_JIT
    if _CHECKSUM_JIT is None:

        def _bits32(leaf):
            leaf = jnp.asarray(leaf)
            if leaf.dtype == jnp.bool_:
                leaf = leaf.astype(jnp.uint8)
            dt = jnp.dtype(leaf.dtype)
            if dt.itemsize == 4:
                return jax.lax.bitcast_convert_type(leaf, jnp.uint32)
            if dt.itemsize == 2:
                return jax.lax.bitcast_convert_type(
                    leaf, jnp.uint16
                ).astype(jnp.uint32)
            if dt.itemsize == 1:
                return jax.lax.bitcast_convert_type(
                    leaf, jnp.uint8
                ).astype(jnp.uint32)
            # 8-byte leaves (x64-enabled runs): fold halves
            halves = jax.lax.bitcast_convert_type(
                leaf.reshape(-1), jnp.uint32
            )
            return halves

        @jax.jit
        def _ck(tree):
            total = jnp.uint32(0)
            for leaf in jax.tree.leaves(tree):
                total = total + jnp.sum(
                    _bits32(leaf), dtype=jnp.uint32
                )
            return total

        _CHECKSUM_JIT = _ck
    return int(jax.device_get(_CHECKSUM_JIT(state)))


# back-compat name (the checksum has always taken the full state dict;
# it now also COVERS the full state, optimizer moments included)
params_checksum = state_checksum


def inject_sdc(state, scale: float = 1.5):
    """The ``sdc_grad_flip`` fault-site payload (train loop, step
    boundary): scale THIS process's addressable shards of the largest
    param leaf, leaving every other process's replica untouched — the
    observable effect of an update computed from a corrupted gradient
    on one replica. Deliberately host-side: any in-trace injection,
    even an exact multiply-by-1.0, shifts XLA's fusion/precision
    decisions and diverges the compiled program's rounding on EVERY
    step — the injection must corrupt exactly one replica at exactly
    one step and nothing else. Returns the new state (old leaf buffers
    are dropped; the next donated step consumes the rebuilt array)."""
    import jax

    key, leaf = _leaf_by_size(state, largest=True)
    shards = sorted(leaf.addressable_shards, key=lambda s: str(s.index))
    new_shards = [
        jax.device_put(
            (np.asarray(s.data) * scale).astype(leaf.dtype), s.device
        )
        for s in shards
    ]
    new_leaf = jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, new_shards
    )

    def replace(path, old):
        return (
            new_leaf if jax.tree_util.keystr(path) == key else old
        )

    params = jax.tree_util.tree_map_with_path(replace, state["params"])
    return dict(state, params=params), key


def scalar_digest(loss: float, grad_norm: float) -> int:
    """Bit-pattern digest of the window's post-reduce scalars. These are
    replicated values: any healthy world fetches the same bits on every
    process, so equality (not tolerance) is the correct compare."""
    return _digest64(struct.pack("<dd", float(loss), float(grad_norm)))


def _minority(labels, values):
    """Attribute a fingerprint disagreement: the MINORITY value's label
    set is the suspect (with >=3 participants the corrupted replica is
    outvoted; blaming "whoever differs from row 0" would name the
    healthy peers whenever process 0 is the corrupt one). Returns
    (sorted minority labels, None) — or (None, {value: labels}) on an
    exact tie, where no side can be blamed and the report must show the
    split symmetrically."""
    groups: dict = {}
    for lab, val in zip(labels, values):
        groups.setdefault(int(val), set()).add(int(lab))
    sizes = sorted(len(m) for m in groups.values())
    if len(groups) > 1 and sizes.count(sizes[-1]) == 1:
        majority_val = max(groups, key=lambda v: len(groups[v]))
        odd = sorted(
            lab
            for val, mem in groups.items()
            if val != majority_val
            for lab in mem
        )
        return odd, None
    return None, {v: sorted(m) for v, m in sorted(groups.items())}


def check_divergence(
    state,
    loss: float,
    grad_norm: float,
    step: int,
    cfg=None,
    registry=None,
    report=print,
) -> bool:
    """One divergence check (call at report cadence, every rank, same
    step — the allgather is collective). Returns True when all
    fingerprints agree; raises :class:`StateDivergenceError` (after one
    actionable line and the ``integrity.divergence_detected`` counter)
    when a replica disagrees. Single-process worlds are a no-op."""
    global _TOTAL_CHECKS
    import jax

    if jax.process_count() == 1:
        return True
    from jax.experimental import multihost_utils

    from fms_fsdp_tpu.parallel.mesh import process_slice_context

    _, slice_idx = process_slice_context(cfg)
    row = np.array(
        [
            int(jax.process_index()),
            int(slice_idx),
            scalar_digest(loss, grad_norm),
            state_checksum(state) & 0xFFFFFFFF,
        ],
        np.int64,
    )
    gathered = np.asarray(multihost_utils.process_allgather(row)).reshape(
        -1, 4
    )
    _TOTAL_CHECKS += 1

    problems: List[str] = []
    scal = gathered[:, 2]
    if not np.all(scal == scal[0]):
        odd, tied = _minority(gathered[:, 0], scal)
        problems.append(
            (
                f"loss/grad-norm fingerprints disagree across processes "
                f"(split {tied} — no majority)"
                if odd is None
                else f"loss/grad-norm fingerprints disagree across "
                f"processes (minority processes {odd} differ from the "
                f"majority)"
            )
            + " — the post-reduce scalars are replicated values and "
            "must be bit-identical"
        )
    cks = gathered[:, 3]
    if not np.all(cks == cks[0]):
        odd, tied = _minority(gathered[:, 1], cks)
        problems.append(
            (
                f"whole-state checksums disagree (slices split {tied} "
                f"— no majority)"
                if odd is None
                else f"whole-state checksums disagree (minority "
                f"slices {odd} differ from the majority)"
            )
            + " — a replicated train state has silently diverged"
        )
    if not problems:
        return True
    if registry is not None:
        registry.counter("integrity.divergence_detected").add()
    report(
        f"INTEGRITY: cross-replica state divergence detected at step "
        f"{step}: {problems[0]} (integrity.divergence_detected; "
        f"relaunch will resume from the last scrub-verified checkpoint)"
    )
    raise StateDivergenceError(
        f"cross-replica state divergence at step {step}: "
        + "; ".join(problems)
    )


def divergence_due(
    step: int, last_checked: Optional[int], interval: int
) -> bool:
    """Cadence gate the train loop consults at report boundaries:
    ``interval`` steps (the ``divergence_check_interval`` knob) must
    have passed since the last check. 0 disables."""
    if interval <= 0:
        return False
    return last_checked is None or (step - last_checked) >= interval
