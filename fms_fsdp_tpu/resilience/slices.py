"""Slice fault domains: per-slice liveness aggregation and the
DCN-collective timeout classifier.

On a multi-slice mesh (parallel/mesh.py: the ``dcn`` axis) the slice is
the unit capacity dies in — a preempted or crashed slice takes its whole
ICI domain with it. To every *surviving* host the failure looks like a
cross-slice collective (the DCN gradient all-reduce, or the report-time
metric fetch that drains it) which simply never completes: without this
module the run either hangs until the scheduler's job timeout or dies in
an opaque transport error, and the operator cannot tell a dead slice
from a wedged step (the StepWatchdog's generic stall).

``SliceHealthMonitor`` closes that gap with out-of-band liveness:

- every process writes a tiny heartbeat file
  (``slice<k>_proc<r>.hb``) into a SHARED directory from a daemon
  thread, so the file keeps updating while the main thread is parked
  inside a blocked collective — the heartbeat tracks *process
  liveness* (the fault-domain signal), not step progress;
- the same thread scans every peer's file. Staleness is judged by
  "mtime unchanged across local polls for > timeout_s" (the same
  skew-immune discipline as the checkpoint GC quiesce window — shared
  -storage server clocks can lead or lag this host's);
- when every process of some OTHER slice has gone silent, the slice is
  declared LOST: the monitor prints one actionable line on every
  healthy host — naming the dead slice, its last observed step, and
  the restart policy ("restart at world minus one fault domain"; the
  elastic-resume path preserves the global batch and reshards the
  loader walk, docs/checkpointing.md) — and fail-fasts the process
  (``os._exit``) so the scheduler restarts the world instead of
  burning the reservation on a DCN hang.

The *classifier* half (``wait_classify``): gloo/TCP simulations (and
some real transports) surface a dead peer as an exception in the
collective rather than a hang. The train loop routes such exceptions
through ``wait_classify``, which waits up to the timeout for the
liveness verdict and lets the loop re-raise a classified
"slice K lost" error instead of the raw transport traceback — the same
message whichever way the failure surfaced.

Fault sites (resilience/faults.py): ``slice_kill`` hard-exits every
process of one slice at a chosen step and ``dcn_reduce_stall`` parks a
rank at the reduce boundary, so the whole detect-classify-resume path is
CPU-testable (tests/test_resilience.py, tests/test_elastic.py).
"""

import json
import os
import sys
import threading
import time
from typing import Callable, Dict, Optional

from fms_fsdp_tpu.resilience.exits import EXIT_CODES, current_run_id

EXIT_CODE = EXIT_CODES["slice_loss"]

_HB_SUFFIX = ".hb"


class SliceLostError(RuntimeError):
    """A failure the liveness verdict classified as a lost fault domain
    ("slice K lost ... restart at world minus one fault domain"). Typed
    so the entry points' classified-exit wrapper
    (resilience/exits.py) maps it onto the ``slice_loss`` registry exit
    code — the same code the monitor thread's direct ``os._exit`` uses —
    whichever way the failure surfaced (hang vs dead-peer transport
    error)."""


def _hb_name(slice_index: int, process_index: int) -> str:
    return f"slice{slice_index}_proc{process_index}{_HB_SUFFIX}"


def _parse_hb_name(name: str):
    if not name.endswith(_HB_SUFFIX) or not name.startswith("slice"):
        return None
    try:
        s, p = name[len("slice") : -len(_HB_SUFFIX)].split("_proc")
        return int(s), int(p)
    except ValueError:
        return None


class SliceHealthMonitor:
    """Per-slice liveness over a shared heartbeat directory.

    ``beat(step)`` is called once per loop iteration (stores the step
    for the post-mortem message; the liveness file itself is written by
    the monitor thread, so a blocked main thread keeps beating liveness
    but not progress). ``on_dead`` (tests) replaces the default
    report-and-``os._exit`` action.

    ``run_id`` (defaults to the supervisor-exported ``FMS_RUN_ID``,
    identical on every host of one incarnation) stamps this process's
    liveness file and filters the scan: liveness files left behind by a
    PREVIOUS incarnation are ignored entirely — a freshly restarted
    world must not declare a slice lost off the dead world's stale
    files. Unsupervised runs (no run id) scan every file, as before;
    the supervisor additionally clears the directory between
    incarnations.
    """

    EXIT_CODE = EXIT_CODE

    def __init__(
        self,
        heartbeat_dir: str,
        num_slices: int,
        slice_index: int,
        process_index: int,
        timeout_s: float,
        poll_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        on_dead: Optional[Callable[[str], None]] = None,
        run_id: Optional[str] = None,
    ):
        assert timeout_s > 0 and num_slices > 1
        self.dir = heartbeat_dir
        self.num_slices = int(num_slices)
        self.slice_index = int(slice_index)
        self.process_index = int(process_index)
        self.timeout_s = float(timeout_s)
        self.poll_s = (
            min(1.0, self.timeout_s / 4) if poll_s is None else float(poll_s)
        )
        self._clock = clock
        self._on_dead = on_dead
        self.run_id = current_run_id() if run_id is None else (run_id or None)
        self._tag = (
            f"slice-health [proc {self.process_index} "
            f"slice {self.slice_index}]"
        )
        self._step = 0
        self._last_progress = clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # path -> (mtime fingerprint, local clock when first seen at it,
        # the file's run_id stamp): staleness is "unchanged across local
        # polls", never a wall-clock age comparison against a possibly-
        # skewed storage server; the run_id filters out a previous
        # incarnation's leftovers
        self._marks: Dict[str, tuple] = {}
        self._dead: Optional[dict] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SliceHealthMonitor":
        os.makedirs(self.dir, exist_ok=True)
        self._write_own()
        self._thread = threading.Thread(
            target=self._run, name="slice-health", daemon=True
        )
        self._thread.start()
        return self

    def beat(self, step: int) -> None:
        self._step = int(step)
        self._last_progress = self._clock()

    def stop(self) -> None:
        self._stop.set()

    # -- liveness file -----------------------------------------------------

    def _write_own(self) -> None:
        path = os.path.join(
            self.dir, _hb_name(self.slice_index, self.process_index)
        )
        tmp = path + ".tmp"
        try:
            payload = {
                "slice": self.slice_index,
                "proc": self.process_index,
                "step": self._step,
                "time_unix": time.time(),
            }
            if self.run_id:
                payload["run_id"] = self.run_id
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            pass  # a transient shared-fs hiccup must not kill the writer

    # -- scanning ----------------------------------------------------------

    def _scan(self) -> Optional[dict]:
        """One liveness pass. Returns {"slice", "procs", "last_step",
        "silent_s"} for a lost slice, else None. Pure over the
        injectable clock (fake-clock testable)."""
        now = self._clock()
        try:
            names = os.listdir(self.dir)
        except OSError:
            return None
        by_slice: Dict[int, list] = {}
        for name in names:
            parsed = _parse_hb_name(name)
            if parsed is None:
                continue
            s, p = parsed
            path = os.path.join(self.dir, name)
            try:
                m = os.path.getmtime(path)
            except OSError:
                continue
            marked = self._marks.get(path)
            if marked is None or marked[0] != m:
                # (re)marking: read the file's incarnation stamp once
                # per mtime change (atomic replace — never torn)
                file_run = None
                try:
                    with open(path) as f:
                        file_run = json.load(f).get("run_id")
                except (OSError, ValueError):
                    pass
                marked = self._marks[path] = (m, now, file_run)
            age = now - marked[1]
            if (
                self.run_id
                and marked[2] is not None
                and marked[2] != self.run_id
            ):
                # a previous incarnation's file: its processes are dead
                # by definition (the world restarted) — not evidence of
                # a lost slice in THIS incarnation
                continue
            by_slice.setdefault(s, []).append((p, path, age))
        for s, entries in sorted(by_slice.items()):
            if s == self.slice_index or not entries:
                continue
            if all(age > self.timeout_s for _, _, age in entries):
                last_step = -1
                for _, path, _ in entries:
                    try:
                        with open(path) as f:
                            last_step = max(
                                last_step, int(json.load(f).get("step", -1))
                            )
                    except (OSError, ValueError):
                        pass
                return {
                    "slice": s,
                    "procs": sorted(p for p, _, _ in entries),
                    "last_step": last_step,
                    "silent_s": min(age for _, _, age in entries),
                }
        return None

    def describe_loss(self, dead: dict) -> str:
        """The one actionable line every healthy host prints."""
        blocked = self._clock() - self._last_progress
        stall = (
            f"; the local step has been blocked in a cross-slice "
            f"collective for {blocked:.0f}s — classified as slice loss, "
            f"not a local stall"
            if blocked > self.poll_s * 2
            else ""
        )
        step = dead.get("last_step", -1)
        at = f" (last progress at step {step})" if step >= 0 else ""
        return (
            f"{self._tag}: slice {dead['slice']} lost — all "
            f"{len(dead['procs'])} of its process(es) "
            f"{dead['procs']} silent for {dead['silent_s']:.0f}s{at}{stall}. "
            f"Restart at world minus one fault domain "
            f"({self.num_slices} -> {self.num_slices - 1} slice(s), same "
            f"per-slice shape): elastic resume restores the last committed "
            f"checkpoint, preserves the global batch, and reshards the "
            f"loader walk (docs/resilience.md, docs/checkpointing.md)."
        )

    # -- classifier --------------------------------------------------------

    def wait_classify(self, extra_wait_s: Optional[float] = None) -> Optional[dict]:
        """Block up to ``timeout_s + extra_wait_s`` waiting for a
        lost-slice verdict — the classifier for a cross-slice collective
        that ERRORED (dead-peer transport reset) rather than hung: the
        peer's files need a full timeout window to go stale, so the
        caller holding a transport exception waits here before deciding
        whether it is a slice loss or an unrelated failure."""
        deadline = self._clock() + self.timeout_s + (
            self.poll_s * 2 if extra_wait_s is None else extra_wait_s
        )
        while True:
            dead = self._dead or self._scan()
            if dead is not None or self._clock() >= deadline:
                return dead
            if self._stop.wait(self.poll_s):
                return self._dead

    # -- thread ------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self._write_own()
            dead = self._scan()
            if dead is None:
                continue
            self._dead = dead
            msg = self.describe_loss(dead)
            if self._on_dead is not None:
                self._on_dead(msg)
                return
            sys.stderr.write(msg + "\n")
            sys.stderr.flush()
            # fail-fast on every healthy host: parking the world in the
            # dead slice's DCN collective burns the reservation and
            # yields no post-mortem
            os._exit(self.EXIT_CODE)
