"""Checkpoint manifests: file list + sizes + content checksums, written
at commit time and verified on load and by the background scrubber.

A torn or bit-flipped checkpoint usually fails loudly only deep inside
Orbax/TensorStore, after minutes of restore work — or worse, not at all.
The manifest makes corruption detectable before the restore: sizes catch
truncation (the dominant torn-write mode), checksums catch content
corruption where a size coincidentally matches.

Manifest versions:

- **version 1** (pre-state-integrity): sizes for every file, sha256 for
  files at/below ``CHECKSUM_MAX_BYTES`` only. A bit-flip inside a LARGE
  array shard passed silently — the size never changed. Version-1
  manifests keep verifying (size-only for large files, with a note).
- **version 2**: additionally records **chunked sha256 digests** for
  every large file (``chunks[rel] = {chunk_bytes, digests[]}``), so a
  same-size corruption anywhere in a multi-GB TensorStore shard is
  caught — and the failing CHUNK is named, not just the file, which is
  what an operator needs to tell a torn storage stripe from random SDC.
  Chunk digests are computed on the checkpoint manager's BACKGROUND
  writer path (ckpt/manager.py ``_commit_tier_io``), where the bytes are
  already being waited on — blocking snapshot time does not grow.
  ``write_manifest(full_checksums=False)`` (the ``ckpt_full_checksums``
  knob) drops the chunk records and degrades large files back to
  size-only verification.

Write ordering matters: the manifest lands BEFORE the ``metadata.json``
commit marker, so a save torn between the two leaves no marker and the
candidate is skipped by the existing scanners; a committed checkpoint
always has a verifiable manifest. Checkpoints from before this layer
(no manifest) verify as legacy-ok with a warning.

Verification also flags **unrecorded files**: a file present in the
checkpoint dir that the manifest never recorded (a foreign stray, a
partial copy from a botched migration) is a problem — only
``loader_state*`` files (written per-rank after commit), the commit
marker, the manifest itself, and the scrubber's ``integrity_*``
sidecars (resilience/scrub.py) are exempt. A torn ``manifest.json``
(invalid or structurally wrong JSON) is returned as a verification
problem, never raised — the restore fallback chain must walk past it.

Verification work is accounted: every verify adds its wall seconds and
any content-checksum detections to a buffered event window
(:func:`drain_integrity_events`) the train loop drains into the obs
registry at report cadence (schema v8 ``integrity_verify_s``,
``integrity.shard_corrupt_detected``).
"""

import hashlib
import json
import logging
import os
import threading
import time
from typing import Dict, List, Tuple

logger = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 2
# checksum files at/below this size whole (metadata, index structures);
# above it, files are "large": chunked digests under version 2, size
# only under version 1 / full_checksums=False
CHECKSUM_MAX_BYTES = 1 << 20
# chunk granularity for large-file digests: big enough that the digest
# list stays tiny next to the data (64 MiB -> 16 digests per GiB), small
# enough that a mismatch localizes the corruption usefully
CHUNK_BYTES = 1 << 26

# files outside the manifest's scope: the commit marker is written after
# the manifest, loader state files are per-rank (another host may still
# be writing its own), the manifest itself, and the scrubber's verdict/
# quarantine sidecars (resilience/scrub.py) which land post-commit by
# design
_EXCLUDE_PREFIXES = (
    "metadata.json",
    MANIFEST_NAME,
    "loader_state",
    "integrity_",
)

# buffered verification events, drained into the obs registry at report
# cadence by the train loop (the scrubber thread and the load path both
# record here; the MetricRegistry itself is main-thread-only by
# contract)
_EVENTS_LOCK = threading.Lock()
_EVENTS = {"verify_s": 0.0, "shard_corrupt_detected": 0}


def record_integrity_event(verify_s: float = 0.0, corrupt: int = 0) -> None:
    with _EVENTS_LOCK:
        _EVENTS["verify_s"] += float(verify_s)
        _EVENTS["shard_corrupt_detected"] += int(corrupt)


def drain_integrity_events() -> Dict[str, float]:
    """Return-and-reset the buffered verification window."""
    global _EVENTS
    with _EVENTS_LOCK:
        out, _EVENTS = _EVENTS, {
            "verify_s": 0.0,
            "shard_corrupt_detected": 0,
        }
    return out


def _excluded(rel: str) -> bool:
    # exclusions match the file NAME anywhere in the tree (loader_state
    # and sidecars land at the top level today, but a rename-safe check
    # costs nothing): a path is exempt when its basename starts with an
    # excluded prefix
    return any(os.path.basename(rel).startswith(p) for p in _EXCLUDE_PREFIXES)


def _manifest_files(ckpt_dir: str) -> List[str]:
    out = []
    for root, _, files in os.walk(ckpt_dir):
        for name in files:
            rel = os.path.relpath(os.path.join(root, name), ckpt_dir)
            if _excluded(rel):
                continue
            out.append(rel)
    out.sort()
    return out


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 16), b""):
            h.update(block)
    return h.hexdigest()


def _chunk_digests(path: str, chunk_bytes: int) -> List[str]:
    """Per-chunk sha256 hexdigests of ``path`` in ``chunk_bytes`` strides
    (last chunk short). Streaming: one chunk of memory, one pass."""
    out = []
    with open(path, "rb") as f:
        while True:
            h = hashlib.sha256()
            got = 0
            while got < chunk_bytes:
                block = f.read(min(1 << 20, chunk_bytes - got))
                if not block:
                    break
                h.update(block)
                got += len(block)
            if got == 0:
                break
            out.append(h.hexdigest())
            if got < chunk_bytes:
                break
    return out


def write_manifest(
    ckpt_dir: str,
    full_checksums: bool = True,
    chunk_bytes: int = CHUNK_BYTES,
) -> str:
    """Write a version-2 ``manifest.json`` covering every file under
    ``ckpt_dir`` (except the exclusions above): sizes for all, whole-file
    sha256 for small files, chunked sha256 for large files (omitted when
    ``full_checksums`` is off — the ``ckpt_full_checksums`` knob).
    Atomic via rename: a torn manifest write can never masquerade as a
    valid one.

    Called from the async manager's BACKGROUND writer (the blocking
    snapshot never pays the hashing) and from the synchronous save path
    (where the whole save is on the critical path anyway)."""
    files = {}
    checksums = {}
    chunks = {}
    for rel in _manifest_files(ckpt_dir):
        full = os.path.join(ckpt_dir, rel)
        try:
            size = os.path.getsize(full)
        except OSError:
            continue  # concurrently pruned; verification scopes what exists
        files[rel] = size
        if size <= CHECKSUM_MAX_BYTES:
            checksums[rel] = _sha256(full)
        elif full_checksums:
            chunks[rel] = {
                "chunk_bytes": int(chunk_bytes),
                "digests": _chunk_digests(full, int(chunk_bytes)),
            }
    manifest = {
        "version": MANIFEST_VERSION,
        "files": files,
        "checksums": checksums,
        "chunks": chunks,
    }
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def verify_manifest(
    ckpt_dir: str, content: bool = True
) -> Tuple[bool, List[str]]:
    """Check ``ckpt_dir`` against its manifest.

    Returns ``(ok, problems)``. A checkpoint with no manifest (written
    before this layer) is legacy-ok: ``(True, ["no manifest ..."])`` —
    the caller may log the note but must accept the checkpoint. A
    version-1 manifest (or a v2 written with full checksums off)
    verifies large files by size only, with a note appended when such
    files exist, so the caller can state exactly how much was checked.

    ``content=False`` runs the CHEAP half only — presence, sizes, and
    the unrecorded-file sweep, no hashing. This is the re-check behind a
    cached scrub verdict (resilience/scrub.py): the expensive content
    hashing is trusted from the verdict, but metadata reads cost nothing
    and still catch truncation/deletion that happened after the scrub.

    Any torn/invalid manifest — unreadable, non-JSON, or structurally
    wrong (a list where a dict belongs) — is returned as a verification
    PROBLEM, never raised: the restore fallback chain walks past it to
    the next-newest committed checkpoint instead of crashing the
    restore."""
    t0 = time.monotonic()
    try:
        return _verify_manifest(ckpt_dir, content)
    finally:
        record_integrity_event(verify_s=time.monotonic() - t0)


def _verify_manifest(
    ckpt_dir: str, content: bool = True
) -> Tuple[bool, List[str]]:
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    if not os.path.isfile(path):
        return True, [f"no manifest in {ckpt_dir} (pre-manifest checkpoint)"]
    try:
        with open(path) as f:
            manifest = json.load(f)
        version = int(manifest["version"])
        files = dict(manifest["files"])
        checksums = dict(manifest.get("checksums") or {})
        chunks = dict(manifest.get("chunks") or {})
        sizes = {rel: int(size) for rel, size in files.items()}
    except (OSError, ValueError, KeyError, TypeError, AttributeError) as e:
        # a torn manifest truncates to invalid JSON — or to VALID JSON of
        # the wrong shape (a bare list, files-as-list), which indexes or
        # int() above throw on. Either way it is a corrupt checkpoint,
        # reported as such so the fallback chain keeps walking.
        return False, [f"unreadable or malformed manifest {path}: {e!r}"]

    problems = []
    corrupt = 0
    size_only_large = 0
    for rel, size in sizes.items():
        full = os.path.join(ckpt_dir, rel)
        if not os.path.isfile(full):
            problems.append(f"missing file {rel}")
            continue
        actual = os.path.getsize(full)
        if actual != size:
            problems.append(f"size mismatch {rel}: {actual} != {size}")
            continue
        if not content:
            continue
        want = checksums.get(rel)
        if want is not None:
            if _sha256(full) != want:
                problems.append(f"checksum mismatch {rel}")
                corrupt += 1
            continue
        chunk_rec = chunks.get(rel)
        if chunk_rec is not None:
            try:
                chunk_bytes = int(chunk_rec["chunk_bytes"])
                want_digests = list(chunk_rec["digests"])
            except (KeyError, TypeError, ValueError):
                problems.append(f"malformed chunk record for {rel}")
                continue
            got = _chunk_digests(full, chunk_bytes)
            if got != want_digests:
                bad = next(
                    (
                        i
                        for i, (g, w) in enumerate(zip(got, want_digests))
                        if g != w
                    ),
                    min(len(got), len(want_digests)),
                )
                problems.append(
                    f"checksum mismatch {rel} (chunk {bad + 1}/"
                    f"{len(want_digests)}, offset {bad * chunk_bytes})"
                )
                corrupt += 1
        elif size > CHECKSUM_MAX_BYTES:
            size_only_large += 1

    # files on disk the manifest never recorded: a foreign/partial stray
    # in a committed dir must be visible, not silently restored around
    recorded = set(sizes)
    for rel in _manifest_files(ckpt_dir):
        if rel not in recorded:
            try:
                size = os.path.getsize(os.path.join(ckpt_dir, rel))
            except OSError:
                continue
            problems.append(
                f"unrecorded file {rel} ({size} bytes) not in manifest"
            )

    if corrupt:
        record_integrity_event(corrupt=corrupt)
    if problems:
        logger.warning(
            "checkpoint %s failed integrity verification: %s",
            ckpt_dir,
            "; ".join(problems[:5]),
        )
        return False, problems
    if size_only_large:
        # informational note on a PASSING verify (the legacy-ok
        # contract: ok=True with notes the caller may log)
        problems.append(
            f"manifest version {version} without full checksums: "
            f"{size_only_large} large file(s) verified by size only "
            f"(re-save with ckpt_full_checksums for content coverage)"
        )
    return True, problems
