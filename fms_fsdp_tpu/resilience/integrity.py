"""Checkpoint manifests: file list + sizes + checksums of small metadata
files, written at commit time and verified on load.

A torn or bit-flipped checkpoint usually fails loudly only deep inside
Orbax/TensorStore, after minutes of restore work — or worse, not at all.
The manifest makes corruption detectable in milliseconds: sizes catch
truncation (the dominant torn-write mode), checksums catch metadata
corruption where a size can coincidentally match. Large array-data files
get size checks only — checksumming terabytes on the save path would
erase the async-checkpoint win.

Write ordering matters: the manifest lands BEFORE the ``metadata.json``
commit marker, so a save torn between the two leaves no marker and the
candidate is skipped by the existing scanners; a committed checkpoint
always has a verifiable manifest. Checkpoints from before this layer
(no manifest) verify as legacy-ok with a warning.
"""

import hashlib
import json
import logging
import os
from typing import List, Tuple

logger = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"
# checksum files at/below this size (metadata, index structures);
# above it, record size only
CHECKSUM_MAX_BYTES = 1 << 20

# files outside the manifest's scope: the commit marker is written after
# the manifest, loader state files are per-rank (another host may still
# be writing its own), and the manifest itself
_EXCLUDE_PREFIXES = ("metadata.json", MANIFEST_NAME, "loader_state")


def _manifest_files(ckpt_dir: str) -> List[str]:
    out = []
    for root, _, files in os.walk(ckpt_dir):
        for name in files:
            rel = os.path.relpath(os.path.join(root, name), ckpt_dir)
            if any(rel.startswith(p) for p in _EXCLUDE_PREFIXES):
                continue
            out.append(rel)
    out.sort()
    return out


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 16), b""):
            h.update(block)
    return h.hexdigest()


def write_manifest(ckpt_dir: str) -> str:
    """Write ``manifest.json`` covering every file under ``ckpt_dir``
    (except the exclusions above). Atomic via rename: a torn manifest
    write can never masquerade as a valid one."""
    files = {}
    checksums = {}
    for rel in _manifest_files(ckpt_dir):
        full = os.path.join(ckpt_dir, rel)
        try:
            size = os.path.getsize(full)
        except OSError:
            continue  # concurrently pruned; verification scopes what exists
        files[rel] = size
        if size <= CHECKSUM_MAX_BYTES:
            checksums[rel] = _sha256(full)
    manifest = {"version": 1, "files": files, "checksums": checksums}
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def verify_manifest(ckpt_dir: str) -> Tuple[bool, List[str]]:
    """Check ``ckpt_dir`` against its manifest.

    Returns ``(ok, problems)``. A checkpoint with no manifest (written
    before this layer) is legacy-ok: ``(True, ["no manifest ..."])`` —
    the caller may log the note but must accept the checkpoint.
    """
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    if not os.path.isfile(path):
        return True, [f"no manifest in {ckpt_dir} (pre-manifest checkpoint)"]
    try:
        with open(path) as f:
            manifest = json.load(f)
        files = manifest["files"]
        checksums = manifest.get("checksums", {})
    except (OSError, ValueError, KeyError) as e:
        return False, [f"unreadable manifest {path}: {e}"]

    problems = []
    for rel, size in files.items():
        full = os.path.join(ckpt_dir, rel)
        if not os.path.isfile(full):
            problems.append(f"missing file {rel}")
            continue
        actual = os.path.getsize(full)
        if actual != size:
            problems.append(f"size mismatch {rel}: {actual} != {size}")
            continue
        want = checksums.get(rel)
        if want is not None and _sha256(full) != want:
            problems.append(f"checksum mismatch {rel}")
    if problems:
        logger.warning(
            "checkpoint %s failed integrity verification: %s",
            ckpt_dir,
            "; ".join(problems[:5]),
        )
    return not problems, problems
