"""Checkpoint scrubber: background re-verification of committed
checkpoints, quarantine of corrupt ones, and a verdict cache keyed by
manifest digest.

The manifest (resilience/integrity.py) makes corruption *detectable*,
but until a restore walks the dir nothing ever *looks* — a bit-flipped
shard sits silently poisoned until the crash that needs it. The
scrubber closes that gap:

- :class:`CheckpointScrubber` re-verifies every committed checkpoint
  across all tiers at a step cadence (``scrub_interval_steps``), on a
  background daemon thread — the train loop only pays a cadence check;
- a checkpoint that fails verification is **quarantined**: a sidecar
  marker (``integrity_quarantine.json``) plus ONE actionable line
  naming the bad shard. ``Checkpointer._candidate_ckp_paths`` skips
  quarantined dirs, so ``load(candidates=)`` and ``resume_topology``
  route around the poison *before* a crash needs it;
- verdicts are **cached by manifest digest** (sidecar
  ``integrity_scrub.json`` + an in-process memo), so the restore-time
  fallback walk — which verifies the same dirs the topology scan just
  verified — never re-hashes terabytes twice, and a scrub-verified
  checkpoint restores with zero re-hashing;
- ``scripts/scrub_checkpoints.py`` drives the same pass as a fleet CLI.

Verified-resume policy: when the run supervisor relaunches after a
``state_divergence`` classification (resilience/divergence.py) it
exports ``FMS_VERIFIED_RESUME=1`` — the restored state is suspect, so
``Checkpointer.load`` must restore only from a checkpoint whose content
has actually been verified (cached scrub verdict, or a fresh full
verify during the walk), never trust-on-size the newest one.
"""

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from fms_fsdp_tpu.resilience.integrity import (
    MANIFEST_NAME,
    record_integrity_event,
    verify_manifest,
)

VERDICT_NAME = "integrity_scrub.json"
QUARANTINE_NAME = "integrity_quarantine.json"
ENV_VERIFIED_RESUME = "FMS_VERIFIED_RESUME"
ENV_VERDICT_TTL = "FMS_SCRUB_VERDICT_TTL_S"
# Positive verdicts EXPIRE: the manifest digest keys the cache, but the
# digest only changes when the dir is re-written — bit-rot that lands
# AFTER a dir's first successful scrub leaves the manifest bytes (and
# the digest) untouched, so without a TTL the rot would hide behind the
# verdict forever, including under the verified-resume policy. A week
# default re-hashes each retained checkpoint once per TTL window —
# noise at fleet scale. 0 disables expiry.
VERDICT_TTL_S = 7 * 24 * 3600.0

# in-process verdict memo:
# (ckpt_dir) -> (manifest_digest, ok, problems, verified_unix).
# The topology scan and the restore walk both verify the same candidate
# list within one process — the second pass must be a dict lookup, not a
# terabyte re-hash. Keyed by the manifest digest so a re-written dir
# re-verifies; positive entries expire with the verdict TTL.
_MEMO_LOCK = threading.Lock()
_MEMO: Dict[str, Tuple[Optional[str], bool, List[str], float]] = {}
# checkpoints confirmed content-verified by this process (scrubber pass
# or restore-walk verify). _VERIFIED_TOTAL is the obs v8
# ``scrub_verified`` field and is MONOTONE: a re-committed dir leaves
# the set (its new bytes are unverified) but the confirmations already
# made are history — the cumulative count never decreases.
_VERIFIED_DIRS: set = set()
_VERIFIED_TOTAL = 0


def _mark_verified(ckpt_dir: str) -> None:
    """Caller holds _MEMO_LOCK."""
    global _VERIFIED_TOTAL
    if ckpt_dir not in _VERIFIED_DIRS:
        _VERIFIED_DIRS.add(ckpt_dir)
        _VERIFIED_TOTAL += 1


def verified_resume_active() -> bool:
    """True when the supervisor demanded a verified resume (the
    ``state_divergence`` relaunch policy). Parsed as a boolean flag:
    ``FMS_VERIFIED_RESUME=0`` (an operator opting OUT during an
    incident, e.g. to force-restore the newest checkpoint) must
    disable the policy, not enable it."""
    val = os.environ.get(ENV_VERIFIED_RESUME, "")
    return val.strip().lower() not in ("", "0", "false", "no", "off")


def _verdict_ttl_s() -> float:
    try:
        raw = os.environ.get(ENV_VERDICT_TTL, "").strip()
        return float(raw) if raw else VERDICT_TTL_S
    except ValueError:
        return VERDICT_TTL_S


def _verdict_expired(verified_unix) -> bool:
    """True when a POSITIVE verdict is older than the TTL and must be
    re-earned by a full re-hash (failures never expire — they are
    routed around via the quarantine sidecar, not trusted)."""
    ttl = _verdict_ttl_s()
    if ttl <= 0:
        return False
    try:
        return (time.time() - float(verified_unix)) > ttl
    except (TypeError, ValueError):
        return True  # unreadable stamp: treat as expired, re-verify


def total_verified() -> int:
    with _MEMO_LOCK:
        return _VERIFIED_TOTAL


def reset_cache() -> None:
    """Testing hook: drop the in-process memo and verified set (sidecar
    files on disk are untouched)."""
    global _VERIFIED_TOTAL
    with _MEMO_LOCK:
        _MEMO.clear()
        _VERIFIED_DIRS.clear()
        _VERIFIED_TOTAL = 0


def manifest_digest(ckpt_dir: str) -> Optional[str]:
    """sha256 of the manifest bytes, or None (legacy/no manifest). The
    cache key: any change to what the manifest records invalidates every
    cached verdict for the dir."""
    import hashlib

    try:
        with open(os.path.join(ckpt_dir, MANIFEST_NAME), "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return None


def is_quarantined(ckpt_dir: str) -> bool:
    return os.path.isfile(os.path.join(ckpt_dir, QUARANTINE_NAME))


def quarantine_info(ckpt_dir: str) -> Optional[dict]:
    try:
        with open(os.path.join(ckpt_dir, QUARANTINE_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def quarantine_checkpoint(ckpt_dir: str, problems: List[str], report=print):
    """Write the quarantine sidecar and print the ONE actionable line
    naming the bad shard. Idempotent; the sidecar is excluded from the
    manifest's unrecorded-file check."""
    info = {
        "problems": list(problems)[:20],
        "manifest_digest": manifest_digest(ckpt_dir),
        "quarantined_unix": time.time(),
    }
    path = os.path.join(ckpt_dir, QUARANTINE_NAME)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(info, f, indent=1)
        os.replace(tmp, path)
    except OSError:
        pass  # read-only storage: the verdict memo still routes around it
    report(
        f"INTEGRITY: checkpoint {ckpt_dir} quarantined: "
        f"{problems[0] if problems else 'verification failed'} "
        f"(sidecar {QUARANTINE_NAME}; resume and the fallback chain "
        f"will skip this step dir)"
    )
    return path


def clear_integrity_sidecars(ckpt_dir: str) -> None:
    """Drop any verdict/quarantine sidecar (and the memo entry) for a
    step dir being (re)committed: a fallback resume that routed around a
    quarantined step N re-commits step N with FRESH content when it
    trains back past it, and the stale verdicts must not outlive the
    bytes they judged. Called by both save paths before the manifest is
    written."""
    with _MEMO_LOCK:
        _MEMO.pop(ckpt_dir, None)
        _VERIFIED_DIRS.discard(ckpt_dir)
    for name in (VERDICT_NAME, QUARANTINE_NAME):
        try:
            os.remove(os.path.join(ckpt_dir, name))
        except OSError:
            pass


def release_quarantine(ckpt_dir: str) -> bool:
    """Remove a quarantine marker (fleet CLI ``--release`` after the
    operator repaired or deliberately accepts the dir). BOTH sidecars
    and the memo entry are dropped so the next walk re-verifies from
    scratch: a verdict stamped before the dir went bad still matches
    the manifest digest (the manifest bytes never changed), and leaving
    it behind would read the released dir as content-verified without
    anyone re-hashing the repaired bytes."""
    path = os.path.join(ckpt_dir, QUARANTINE_NAME)
    if not os.path.isfile(path):
        # nothing to release: an accidental --release against a
        # healthy, scrub-verified dir must not discard its cached
        # verification (a multi-GB re-hash on the next walk)
        return False
    # the marker goes FIRST: if its removal fails (storage flake) the
    # dir is still quarantined and must keep its memo/verdict state
    # untouched — False then always means "not released, not touched",
    # and the caller can tell the two cases apart via is_quarantined
    try:
        os.remove(path)
    except OSError:
        return False
    with _MEMO_LOCK:
        _MEMO.pop(ckpt_dir, None)
        _VERIFIED_DIRS.discard(ckpt_dir)
    try:
        os.remove(os.path.join(ckpt_dir, VERDICT_NAME))
    except OSError:
        pass
    return True


def _read_verdict(ckpt_dir: str) -> Optional[dict]:
    try:
        with open(os.path.join(ckpt_dir, VERDICT_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_verdict(
    ckpt_dir: str,
    digest: Optional[str],
    verify_s: float,
    verified_at: Optional[float] = None,
):
    if digest is None:
        return  # legacy checkpoint: nothing content-verified to cache
    info = {
        "manifest_digest": digest,
        # the moment the content was ACTUALLY hashed — a memo-hit
        # persist (scan verified, sidecar write deferred to the walk)
        # must stamp the ORIGINAL hash time, not now, or the TTL clock
        # restarts without a byte having been re-read
        "verified_unix": time.time() if verified_at is None else verified_at,
        "verify_s": round(float(verify_s), 6),
    }
    path = os.path.join(ckpt_dir, VERDICT_NAME)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, path)
    except OSError:
        pass  # read-only storage: the in-process memo still has it


def scrub_verdict(ckpt_dir: str) -> str:
    """Cached verdict for ``ckpt_dir``: ``"quarantined"`` | ``"verified"``
    (sidecar digest matches the CURRENT manifest) | ``"unknown"``."""
    if is_quarantined(ckpt_dir):
        return "quarantined"
    verdict = _read_verdict(ckpt_dir)
    if verdict is not None:
        digest = manifest_digest(ckpt_dir)
        if (
            digest is not None
            and verdict.get("manifest_digest") == digest
            and not _verdict_expired(verdict.get("verified_unix"))
        ):
            return "verified"
    return "unknown"


def cached_verify(
    ckpt_dir: str,
    write_sidecars: bool = False,
    report=print,
) -> Tuple[bool, List[str]]:
    """``verify_manifest`` behind the verdict cache.

    Order: quarantine sidecar -> verdict sidecar (digest match) ->
    in-process memo -> full verification. ``write_sidecars`` (rank 0
    only — sidecars live on shared storage) persists the outcome so no
    later walk, in this process or the next incarnation, re-hashes the
    same bytes: a fresh pass writes the verified marker, a failed pass
    quarantines the dir with the one actionable line."""
    if is_quarantined(ckpt_dir):
        info = quarantine_info(ckpt_dir) or {}
        first = (info.get("problems") or ["verification failed"])[0]
        return False, [f"quarantined checkpoint ({first})"]
    digest = manifest_digest(ckpt_dir)
    cached_ok = None
    have_sidecar = False
    # the moment the content was ACTUALLY hashed — carried forward on
    # every cache hit, NEVER refreshed by one: a hit that re-stamped
    # "now" would let a sweep cadence shorter than the TTL keep a
    # positive verdict alive forever, defeating the rot-detection
    # guarantee the TTL exists for
    verified_at = time.time()
    if digest is not None:
        verdict = _read_verdict(ckpt_dir)
        if (
            verdict is not None
            and verdict.get("manifest_digest") == digest
            and not _verdict_expired(verdict.get("verified_unix"))
        ):
            cached_ok = (True, [])
            have_sidecar = True
            try:
                verified_at = float(verdict.get("verified_unix"))
            except (TypeError, ValueError):
                pass  # unreadable stamp: _verdict_expired rejected it
        else:
            with _MEMO_LOCK:
                memo = _MEMO.get(ckpt_dir)
            # a POSITIVE memo entry expires exactly like the sidecar —
            # on a multi-week run rank 0's memo would otherwise mask
            # the TTL for the whole incarnation; negatives never expire
            # (they are dropped when their quarantine sidecar lands)
            if (
                memo is not None
                and memo[0] == digest
                and not (memo[1] and _verdict_expired(memo[3]))
            ):
                cached_ok = (memo[1], list(memo[2]))
                verified_at = memo[3]
    verify_s = 0.0
    if cached_ok is not None and cached_ok[0]:
        # the content hashing is trusted from the verdict/memo, but the
        # CHEAP half (presence/sizes/unrecorded sweep) is metadata reads
        # and re-runs every time: truncation or deletion AFTER the
        # verification must not hide behind the cache — only same-size
        # bit-rot relies on it, which is the documented cache contract
        # (a re-written manifest, i.e. a re-saved dir, invalidates it)
        ok, problems = verify_manifest(ckpt_dir, content=False)
        if ok:
            # keep the cached coverage notes (size-only large files):
            # a memo hit must report exactly what the original pass did
            problems = list(cached_ok[1])
    elif cached_ok is not None:
        ok, problems = cached_ok
    else:
        t0 = time.monotonic()
        ok, problems = verify_manifest(ckpt_dir)
        verify_s = time.monotonic() - t0
    # "verified" means CONTENT-verified: a pass that carries coverage
    # notes (v1 manifest / ckpt_full_checksums=False — large files
    # checked by size only) is accepted for loading but must not count
    # toward the obs scrub_verified field nor persist a verified
    # verdict sidecar, or the verified-resume policy would silently
    # degrade to exactly the trust-on-size restore it rules out.
    content_verified = ok and digest is not None and not problems
    # persistence runs for FRESH results and for memo hits alike: the
    # production entry verifies every candidate in the topology scan
    # (write_sidecars=False) before load's walk (write_sidecars=True)
    # re-asks — an early memo-hit return here would leave a corrupt
    # newest checkpoint detected-but-never-quarantined (every later
    # incarnation re-hashing it) and a verified one without its verdict
    # sidecar. Only a verdict-sidecar hit skips the rewrite.
    with _MEMO_LOCK:
        _MEMO[ckpt_dir] = (digest, ok, list(problems), verified_at)
        if content_verified:
            _mark_verified(ckpt_dir)
    if write_sidecars:
        if content_verified and not have_sidecar:
            _write_verdict(ckpt_dir, digest, verify_s, verified_at)
        elif not ok and os.path.isfile(
            os.path.join(ckpt_dir, "metadata.json")
        ):
            # metadata.json gone means the retention GC is deleting the
            # dir under the sweep — a failure over vanishing files is
            # not corruption, and stamping a sidecar into a dir rmtree
            # is walking would make its final rmdir fail
            qpath = quarantine_checkpoint(ckpt_dir, problems, report=report)
            if os.path.isfile(qpath):
                # the sidecar is now the single source of truth for this
                # failure; dropping the memo lets an operator repair +
                # CLI --release (which removes the sidecar but cannot
                # reach this process's memo, and does not change the
                # manifest digest the memo is keyed on) trigger a TRUE
                # re-verify here instead of a stale-memo re-quarantine.
                # A stamp that failed (read-only storage) keeps the memo
                # — then it is the only record routing around the dir.
                with _MEMO_LOCK:
                    _MEMO.pop(ckpt_dir, None)
    return ok, problems


def scrub_checkpoint(ckpt_dir: str, report=print) -> Tuple[str, List[str]]:
    """One committed checkpoint: returns (status, problems) with status
    ``"verified"`` (content confirmed — freshly or from a matching
    cached verdict), ``"quarantined"`` (newly failed or already marked),
    or ``"legacy"`` (content NOT fully confirmable: no manifest, or a
    manifest whose large files carry only size records — v1 /
    ``ckpt_full_checksums=False``)."""
    if is_quarantined(ckpt_dir):
        info = quarantine_info(ckpt_dir) or {}
        return "quarantined", list(info.get("problems") or [])
    if manifest_digest(ckpt_dir) is None:
        return "legacy", [f"no manifest in {ckpt_dir}"]
    ok, problems = cached_verify(ckpt_dir, write_sidecars=True, report=report)
    if not ok:
        return "quarantined", problems
    # a passing verify that carries coverage notes was only partially
    # content-checked — honest counting keeps the obs scrub_verified
    # field meaning what the verified-resume policy assumes it means
    return ("verified" if not problems else "legacy"), problems


def committed_step_dirs(root: str) -> List[str]:
    """Committed step checkpoints under a ``checkpoints/`` root, newest
    first — the scrub population (torn dirs without a commit marker are
    invisible to resume and owned by the torn-dir GC, not the
    scrubber)."""
    from fms_fsdp_tpu.utils.ckpt_paths import (
        is_step_ckp,
        safe_listdir,
        step_number,
    )

    if not root or not os.path.isdir(root):
        return []
    out = [
        os.path.join(root, x)
        for x in safe_listdir(root)
        if is_step_ckp(os.path.join(root, x))
        and os.path.isdir(os.path.join(root, x))
        and "metadata.json" in safe_listdir(os.path.join(root, x))
    ]
    out.sort(key=step_number, reverse=True)
    return out


def scrub_roots(checkpointer) -> List[str]:
    """The checkpoint roots a live run should scrub: every tier of an
    ``AsyncCheckpointManager``, or a bare ``Checkpointer``'s own dir."""
    tiers = getattr(checkpointer, "tiers", None)
    if tiers:
        return [t.ckp.ckp_path for t in tiers]
    path = getattr(checkpointer, "ckp_path", None)
    return [path] if path else []


def scrub_pass(roots: List[str], report=print) -> Dict[str, int]:
    """One scrub sweep over every committed checkpoint in ``roots``.
    Returns counts per status. Cached verdicts make repeat passes
    near-free: only new commits (or changed manifests) hash bytes."""
    counts = {"verified": 0, "quarantined": 0, "legacy": 0}
    for root in roots:
        for ckpt_dir in committed_step_dirs(root):
            status, _ = scrub_checkpoint(ckpt_dir, report=report)
            counts[status] = counts.get(status, 0) + 1
    return counts


class CheckpointScrubber:
    """Step-cadence background scrubber the train loop drives.

    ``maybe_scrub(step)`` costs a comparison; when ``interval_steps``
    have passed since the last sweep it launches one on a daemon thread
    (at most one in flight — a slow storage sweep self-throttles to its
    own duration). Rank 0 only: sidecars live on shared storage and must
    have a single writer, exactly like the commit markers."""

    def __init__(self, roots: List[str], interval_steps: int, report=print):
        self.roots = [r for r in roots if r]
        self.interval_steps = max(0, int(interval_steps))
        self.report = report
        self.last_counts: Dict[str, int] = {}
        self._last_step: Optional[int] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return self.interval_steps > 0 and bool(self.roots)

    def maybe_scrub(self, step: int) -> bool:
        if not self.enabled:
            return False
        if self._last_step is not None and (
            step - self._last_step < self.interval_steps
        ):
            return False
        if self._thread is not None and self._thread.is_alive():
            return False  # previous sweep still running: self-throttle
        self._last_step = step
        self._thread = threading.Thread(
            target=self._sweep, name="ckpt-scrubber", daemon=True
        )
        self._thread.start()
        return True

    def scrub_now(self) -> Dict[str, int]:
        """Synchronous sweep (tests, CLI, loop-exit drain)."""
        self._sweep()
        return dict(self.last_counts)

    def _sweep(self) -> None:
        try:
            self.last_counts = scrub_pass(self.roots, report=self.report)
        except Exception as e:  # noqa: BLE001 — the scrubber must never
            # kill training; a sweep that died (storage flake) just
            # reports and retries at the next cadence
            record_integrity_event()  # keep the drain path warm
            self.report(f"WARNING: checkpoint scrub sweep failed: {e!r}")

    def stop(self, timeout_s: float = 5.0) -> None:
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout_s)
