"""Hot-loop anomaly guards: non-finite batch accounting and a wall-clock
step watchdog.

Detection happens INSIDE the jitted step (train/step.py computes a
``metrics["nonfinite"]`` flag and skips the poisoned update on device),
so the guard costs no extra host sync: the host only sees the flags at
report time, when the metric window is fetched anyway. This module owns
the host-side policy over those flags — count and report skipped
batches, abort cleanly (with a final checkpoint) after K consecutive bad
steps instead of silently diverging.

The watchdog covers the opposite failure: a step that never finishes
(stuck collective, wedged host). The trainer heartbeats it once per loop
iteration; if no beat lands within the timeout it dumps all thread
stacks and hard-exits nonzero, so the scheduler restarts the job instead
of burning the reservation on a hang.
"""

import contextlib
import faulthandler
import json
import logging
import os
import sys
import threading
import time
from typing import Iterable

from fms_fsdp_tpu.resilience.exits import EXIT_CODES, current_run_id

logger = logging.getLogger(__name__)


class AnomalyGuard:
    """Accumulates per-step non-finite flags fetched at report time.

    ``observe`` consumes the flags in step order; ``should_abort``
    becomes True once ``max_consecutive`` bad steps run back-to-back
    (a poisoned data region or true divergence — skipping forever would
    silently train on nothing). Isolated bad batches are just counted:
    the update was already skipped on device.
    """

    def __init__(self, max_consecutive: int = 8):
        assert max_consecutive > 0
        self.max_consecutive = max_consecutive
        self.skipped_batches = 0
        self.consecutive = 0
        self.worst_streak = 0

    def observe(self, flags: Iterable[float]) -> int:
        """Feed one report window's flags; returns the window's skip
        count."""
        window_skips = 0
        for f in flags:
            if f:
                window_skips += 1
                self.consecutive += 1
                self.worst_streak = max(self.worst_streak, self.consecutive)
            else:
                self.consecutive = 0
        self.skipped_batches += window_skips
        return window_skips

    def should_abort(self) -> bool:
        return self.consecutive >= self.max_consecutive


class StepWatchdog:
    """Wall-clock watchdog over training progress.

    ``beat()`` is called once per loop iteration (cheap: one monotonic
    read + store). A daemon thread polls; if the gap since the last beat
    exceeds ``timeout_s`` it dumps every thread's stack via faulthandler
    (the post-mortem for "which collective wedged") and ``os._exit``\\ s
    with :data:`EXIT_CODE` — a stuck collective must not hang forever.

    ``heartbeat_path`` (optional) points at the observability layer's
    heartbeat file (obs/sinks.py::Heartbeat — {step, time_unix,
    goodput}); the stall report quotes its last contents so the
    post-mortem states exactly how far the run got and how healthy it
    was when it wedged. External orchestrators poll the same file.

    ``process_index`` (optional) is the host's ``jax.process_index()``,
    passed in by the trainer at construction — the stall path must not
    import or call into jax from the watchdog thread of a wedged
    process — so merged multi-host logs attribute WHICH host's stacks
    are being read. ``slice_index`` (optional, multi-slice meshes)
    additionally names the host's fault domain, so a multi-slice stall
    triage reads "[proc N slice K]" and goes straight to the slice
    (docs/resilience.md "Slice fault domains").

    ``run_id`` (optional; defaults to the supervisor-exported
    ``FMS_RUN_ID``) guards the heartbeat quote against incarnations: a
    freshly restarted run inherits the DEAD run's heartbeat.json on
    shared storage, and quoting it unlabeled would make the stall report
    claim progress this incarnation never made.
    """

    EXIT_CODE = EXIT_CODES["watchdog_stall"]

    def __init__(
        self,
        timeout_s: float,
        poll_s: float = None,
        heartbeat_path=None,
        process_index=None,
        slice_index=None,
        run_id=None,
    ):
        assert timeout_s > 0
        self.timeout_s = timeout_s
        self.poll_s = min(1.0, timeout_s / 4) if poll_s is None else poll_s
        self.heartbeat_path = heartbeat_path
        self.process_index = process_index
        self.slice_index = slice_index
        self.run_id = current_run_id() if run_id is None else run_id
        if process_index is None:
            self._tag = "step watchdog"
        elif slice_index is None:
            self._tag = f"step watchdog [proc {process_index}]"
        else:
            self._tag = (
                f"step watchdog [proc {process_index} slice {slice_index}]"
            )
        self._last_beat = time.monotonic()
        self._paused = 0
        self._stop = threading.Event()
        self._thread = None

    def start(self) -> "StepWatchdog":
        self._last_beat = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def beat(self) -> None:
        self._last_beat = time.monotonic()

    @contextlib.contextmanager
    def paused(self):
        """Suspend the deadline around a known-long healthy host
        operation (a multi-minute Orbax save must not be judged by a
        timeout sized for step windows). Re-arms with a fresh beat."""
        self._paused += 1
        try:
            yield
        finally:
            # beat BEFORE unpausing: the poller must never observe
            # paused==0 while _last_beat is still pre-pause stale
            self.beat()
            self._paused -= 1

    def stop(self) -> None:
        self._stop.set()

    def _stall_report(self, stalled: float) -> str:
        """The stall message (separate from the exit so tests can pin
        it without dying). A heartbeat stamped by a DIFFERENT
        incarnation (run_id mismatch) is quoted but labeled stale — a
        restarted run must not read the dead run's heartbeat as its own
        progress."""
        lines = [
            f"{self._tag}: no training progress for "
            f"{stalled:.1f}s (timeout {self.timeout_s}s); dumping "
            f"stacks and exiting {self.EXIT_CODE}"
        ]
        if self.heartbeat_path:
            # read inline (no project imports): the process is
            # wedged — the stall path must not risk an import
            # lock held by the stuck main thread
            try:
                with open(self.heartbeat_path) as f:
                    hb = json.load(f)
            except (OSError, ValueError):
                hb = None
            stale = ""
            if (
                isinstance(hb, dict)
                and self.run_id
                and hb.get("run_id") not in (None, self.run_id)
            ):
                stale = (
                    " [STALE: written by a previous incarnation "
                    f"(run_id {hb.get('run_id')!r}, ours "
                    f"{self.run_id!r}) — this run made no reported "
                    "progress]"
                )
            lines.append(
                f"{self._tag}: last heartbeat "
                f"({self.heartbeat_path}): {hb}{stale}"
            )
        return "\n".join(lines) + "\n"

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self._paused:
                continue
            stalled = time.monotonic() - self._last_beat
            if stalled > self.timeout_s:
                sys.stderr.write(self._stall_report(stalled))
                sys.stderr.flush()
                try:
                    faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
                except Exception:  # noqa: BLE001 — already dying, exit anyway
                    pass
                sys.stderr.flush()
                os._exit(self.EXIT_CODE)
