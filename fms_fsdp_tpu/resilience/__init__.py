"""Resilience layer: fault injection, anomaly guards, retrying shard IO,
and checkpoint integrity.

At pod scale the harness's job is mostly surviving: preempted hosts,
flaky shard reads off GCS/NFS, torn checkpoints, and the occasional
non-finite batch. The preemption half lives in
``utils/train_utils.PreemptionGuard``; this package owns the rest:

- ``faults``    — deterministic, env/config-driven fault injection at
  named sites, so every guard below is testable on CPU
  (``tests/test_resilience.py``);
- ``exits``     — the central exit-code registry every fail-fast site
  adopts (collision-free by test), the per-incarnation run-id plumbing,
  and the classified-exit entry wrapper;
- ``supervisor`` — the self-healing run supervisor: launches the
  training entry as child processes, maps each incarnation's exit
  classification to a restart policy, relaunches through elastic
  resume, detects crash loops, and writes the restart ledger that
  charges downtime against goodput (docs/resilience.md "Self-healing
  supervisor");
- ``guards``    — host-side anomaly accounting over the in-jit
  non-finite flag (skip/report/abort) and a wall-clock step watchdog;
- ``slices``    — multi-slice fault domains: per-slice liveness
  heartbeats + the DCN-collective timeout classifier, so a dead slice
  is reported as "slice K lost, restart at world minus one fault
  domain" instead of a hang (docs/resilience.md "Slice fault domains");
- ``retry``     — bounded retry-with-backoff helpers and the retrying
  shard-file handler wrapper;
- ``integrity`` — per-checkpoint manifests (file list + sizes +
  full-content checksums: whole-file for small files, chunked for large
  array shards — manifest v2) written at commit time and verified on
  load and by the scrubber;
- ``scrub``     — the checkpoint scrubber: background re-verification
  of committed checkpoints, quarantine sidecars the fallback chain
  skips, digest-cached verdicts, and the verified-resume policy
  (docs/checkpointing.md "State integrity");
- ``divergence`` — cross-replica divergence detection: report-cadence
  fingerprint compares proving the dcn-replicated train states still
  agree, raising ``StateDivergenceError`` (exit class
  ``state_divergence``) when a replica silently diverged.

Recovery semantics are documented in docs/resilience.md.
"""

from fms_fsdp_tpu.resilience.exits import (
    EXIT_CODES,
    classified_exit,
    classify_exit,
    classify_world,
    current_run_id,
    exit_code,
)
from fms_fsdp_tpu.resilience.faults import (
    configure_faults,
    fault_params,
    fire_fault,
    maybe_raise_fault,
)
from fms_fsdp_tpu.resilience.divergence import (
    StateDivergenceError,
    check_divergence,
)
from fms_fsdp_tpu.resilience.guards import AnomalyGuard, StepWatchdog
from fms_fsdp_tpu.resilience.integrity import (
    verify_manifest,
    write_manifest,
)
from fms_fsdp_tpu.resilience.retry import RetryingShardHandler, retry_call
from fms_fsdp_tpu.resilience.scrub import (
    CheckpointScrubber,
    cached_verify,
    is_quarantined,
    quarantine_checkpoint,
    scrub_checkpoint,
    scrub_verdict,
)
from fms_fsdp_tpu.resilience.slices import SliceHealthMonitor, SliceLostError

__all__ = [
    "AnomalyGuard",
    "CheckpointScrubber",
    "EXIT_CODES",
    "RetryingShardHandler",
    "SliceHealthMonitor",
    "SliceLostError",
    "StateDivergenceError",
    "StepWatchdog",
    "cached_verify",
    "check_divergence",
    "is_quarantined",
    "quarantine_checkpoint",
    "scrub_checkpoint",
    "scrub_verdict",
    "classified_exit",
    "classify_exit",
    "classify_world",
    "configure_faults",
    "current_run_id",
    "exit_code",
    "fault_params",
    "fire_fault",
    "maybe_raise_fault",
    "retry_call",
    "verify_manifest",
    "write_manifest",
]
