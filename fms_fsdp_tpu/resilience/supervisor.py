"""Self-healing run supervisor: classified-exit auto-restart through
elastic resume.

The guards end every unrecoverable failure in a fail-fast exit with a
registry code (resilience/exits.py), and elastic resume
(ckpt/elastic.py) makes restarting on the surviving topology a proven,
bit-identical operation — but until now an *external* scheduler had to
connect the two. ``RunSupervisor`` closes the loop with no operator in
it: it launches the training entry as child processes (one per host; in
gloo simulations, every rank of the world), reads the incarnation's exit
classification, and relaunches through the existing elastic-resume path
under a per-class restart policy:

==============  =============================================================
class           policy (DEFAULT_POLICIES)
==============  =============================================================
ok              heartbeat step >= target_step -> done; below it, the run
                exited clean early (a preemption save) -> immediate relaunch
slice_loss      relaunch quoting the SliceHealthMonitor verdict; with
                ``on_slice_loss="shrink"`` (the default) the next incarnation
                runs at ``num_slices - 1`` (world minus one fault domain);
                ``"same"`` relaunches the full world (capacity returns —
                required when end-state bit-identity vs a fixed-topology
                reference is asserted, scripts/chaos_soak.py)
anomaly_abort   relaunch from the last committed checkpoint after a cooldown
                (the abort already saved; an instant relaunch into the same
                poisoned data region would just re-abort)
watchdog_stall  relaunch with backoff
loader_death    relaunch with backoff
corpus_loss     relaunch with backoff: the data mix dropped below its
                ``min_live_corpora`` floor (data/streaming.py) — the
                relaunch expects the corpus storage restored; a corpus
                still dead re-exits corpus_loss and the crash-loop guard
                ends it with the quarantine list in the post-mortem
injected_kill   relaunch with backoff (fault-injection hard kills)
error           bounded generic retry with backoff (unknown exit codes)
==============  =============================================================

Safety rails — the supervisor never loops forever:

- ``max_restarts`` caps total relaunches;
- **crash-loop detection**: the heartbeat step (obs heartbeat.json,
  written at report cadence and on every loop-exit drain) must advance
  across restarts. ``crash_loop_threshold`` consecutive incarnations
  without progress end the run with a post-mortem that prints the full
  restart ledger (every restart's exit class, resumed step, downtime).

The **restart ledger** (JSON, written BEFORE each launch and at exit) is
the goodput bridge: the relaunched run reads it via ``FMS_RESTART_LEDGER``
(obs/observer.py::build_observer) and folds ``restarts`` /
``restart_downtime_s`` into every metrics record (schema v6) and into
``GoodputTracker`` — restart downtime is charged against goodput, so a
faulted run's goodput is strictly below the fault-free run's.

Incarnation hygiene: each launch exports ``FMS_RUN_ID`` (identical on
every host — derived from the attempt counter) so the heartbeat and
slice-liveness files are stamped per incarnation and a restarted run
ignores the dead run's records; ``reset_paths`` directories (e.g. the
slice heartbeat dir) are cleared between incarnations.

CLI (one supervisor per host in production)::

    python -m fms_fsdp_tpu.resilience.supervisor \\
        --ledger /tmp/run/ledger.json --heartbeat /tmp/run/obs/heartbeat.json \\
        --target-step 50000 --max-restarts 8 -- \\
        python main_training_llama.py --num_steps=50000 --obs_dir=/tmp/run/obs ...

Chaos proof: scripts/chaos_soak.py drives seeded fault schedules through
this supervisor and asserts end-state bit-identity vs a fault-free run
(docs/resilience.md "Self-healing supervisor").
"""

import json
import os
import shutil
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from fms_fsdp_tpu.resilience.exits import (
    ENV_LEDGER,
    ENV_RUN_ID,
    EXIT_CODES,
    classify_exit,
    classify_world,
)
from fms_fsdp_tpu.resilience.scrub import ENV_VERIFIED_RESUME

LEDGER_VERSION = 1


@dataclass
class RestartPolicy:
    """Per-exit-class restart decision: whether to relaunch, the backoff
    base (doubles per consecutive no-progress restart, like every other
    backoff in resilience/), an extra fixed cooldown, whether the next
    incarnation drops a fault domain, and whether it must resume under
    the VERIFIED-resume rule (restore only a scrub-verified checkpoint —
    the state-divergence policy, resilience/divergence.py)."""

    restart: bool = True
    backoff: bool = True
    cooldown_s: float = 0.0
    drop_slice: bool = False
    verified_resume: bool = False


def default_policies(
    anomaly_cooldown_s: float = 30.0, on_slice_loss: str = "shrink"
) -> Dict[str, RestartPolicy]:
    assert on_slice_loss in ("shrink", "same"), on_slice_loss
    return {
        "ok": RestartPolicy(restart=False),
        # a clean exit below the target step is a preemption save:
        # relaunch immediately (the grace window already cost time)
        "preempted": RestartPolicy(backoff=False),
        "slice_loss": RestartPolicy(drop_slice=(on_slice_loss == "shrink")),
        "anomaly_abort": RestartPolicy(cooldown_s=anomaly_cooldown_s),
        "watchdog_stall": RestartPolicy(),
        "loader_death": RestartPolicy(),
        # the data itself is gone (mix below min_live_corpora), not the
        # worker: relaunch with backoff expecting the corpus restored —
        # a still-dead corpus re-exits and the crash-loop guard ends it
        "corpus_loss": RestartPolicy(),
        # a replica's state silently diverged (SDC / broken reduce): the
        # newest checkpoint may hold the diverged replica's poison, so
        # every later incarnation resumes from the last SCRUB-VERIFIED
        # checkpoint (FMS_VERIFIED_RESUME exported to the children),
        # never trust-on-size the newest
        "state_divergence": RestartPolicy(verified_resume=True),
        "injected_kill": RestartPolicy(),
        "error": RestartPolicy(),
    }


@dataclass
class SupervisorResult:
    status: str  # "completed" | "crash_loop" | "max_restarts" | "gave_up"
    restarts: int
    final_step: int
    ledger: dict
    post_mortem: str = ""


@dataclass
class _Entry:
    attempt: int
    run_id: str
    exit_codes: List[Optional[int]] = field(default_factory=list)
    classification: str = ""
    started_unix: float = 0.0
    ended_unix: float = 0.0
    resumed_step: int = -1  # heartbeat step going INTO the incarnation
    step_at_exit: int = -1  # heartbeat step when it died
    downtime_s: float = 0.0  # death -> next launch (backoff + spawn)
    note: str = ""

    def as_dict(self) -> dict:
        return dict(self.__dict__)


class RunSupervisor:
    """Launch -> classify -> relaunch loop over one training run.

    ``build_command(ctx)`` returns the incarnation's child specs: a list
    with one entry per host process, each either an argv list or a dict
    ``{"argv": [...], "env": {...}, "cwd": ...}``. ``ctx`` carries
    ``attempt`` (0 = first launch), ``run_id``, ``num_slices`` (already
    decremented after a shrink restart), ``restarts`` and the ledger so
    the builder can reshape the world per incarnation.

    ``target_step`` tells completion apart from a clean preemption exit:
    both exit 0, but only one has heartbeat step >= target. Without it,
    any all-zero exit completes the run.

    Injectables (``launch``, ``clock``, ``sleep``, ``read_step``) keep
    the whole policy loop unit-testable without real processes.
    """

    def __init__(
        self,
        build_command: Callable[[dict], list],
        *,
        ledger_path: str,
        heartbeat_path: Optional[str] = None,
        target_step: Optional[int] = None,
        max_restarts: int = 8,
        restart_backoff_s: float = 5.0,
        crash_loop_threshold: int = 3,
        anomaly_cooldown_s: float = 30.0,
        on_slice_loss: str = "shrink",
        num_slices: int = 1,
        reset_paths: tuple = (),
        log_dir: Optional[str] = None,
        policies: Optional[Dict[str, RestartPolicy]] = None,
        launch=None,
        clock: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
        log: Callable[[str], None] = None,
    ):
        self.build_command = build_command
        self.ledger_path = ledger_path
        self.heartbeat_path = heartbeat_path
        if target_step is not None and not heartbeat_path:
            # completion vs clean-preemption is decided from the
            # heartbeat step; without one, every clean exit would read
            # as step -1 < target and a finished run would be
            # relaunched into the crash-loop guard
            raise ValueError(
                "target_step requires heartbeat_path (the obs "
                "heartbeat.json): the supervisor reads the reached "
                "step from it to tell completion from a clean "
                "preemption exit"
            )
        self.target_step = target_step
        self.max_restarts = int(max_restarts)
        self.restart_backoff_s = float(restart_backoff_s)
        self.crash_loop_threshold = max(1, int(crash_loop_threshold))
        self.num_slices = max(1, int(num_slices))
        self.reset_paths = tuple(reset_paths)
        self.log_dir = log_dir
        self.policies = policies or default_policies(
            anomaly_cooldown_s=anomaly_cooldown_s, on_slice_loss=on_slice_loss
        )
        self._launch = launch or self._launch_subprocesses
        # sticky once set (a state_divergence classification): every
        # later incarnation restores only scrub-verified checkpoints —
        # once a replica has silently diverged, "newest" is no longer a
        # trustworthy resume point for the rest of this run
        self._verified_resume = False
        self._clock = clock
        self._sleep = sleep
        self._log = log or (lambda msg: print(f"[supervisor] {msg}", flush=True))
        # resume a prior supervisor's ledger at the same path: attempt
        # numbering (and therefore run_ids) and downtime accounting
        # continue instead of restarting at i0 — a restarted supervisor
        # must never reuse a dead incarnation's run_id, or the dead
        # run's heartbeat/liveness records would pass the incarnation
        # filters they exist for
        self.entries: List[_Entry] = []
        prior = None
        try:
            with open(self.ledger_path) as f:
                prior = json.load(f)
        except (OSError, ValueError):
            prior = None
        if prior and isinstance(prior.get("entries"), list):
            for e in prior["entries"]:
                try:
                    self.entries.append(
                        _Entry(
                            **{
                                k: e[k]
                                for k in _Entry.__dataclass_fields__
                                if k in e
                            }
                        )
                    )
                except TypeError:
                    continue  # unknown ledger shape: start fresh past it
            if self.entries:
                self._log(
                    f"resuming restart ledger {self.ledger_path}: "
                    f"{len(self.entries)} prior incarnation(s)"
                )

    # -- ledger ------------------------------------------------------------

    def _ledger(self, run_id: str, final: bool = False) -> dict:
        # written BEFORE each launch, ``restarts`` is "relaunches that
        # preceded the incarnation about to start" == len(entries); in
        # the final ledger the last entry is the terminal incarnation
        # itself, not a restart
        restarts = len(self.entries) - (1 if final and self.entries else 0)
        return {
            "version": LEDGER_VERSION,
            "run_id": run_id,
            "restarts": max(0, restarts),
            "restart_downtime_s": round(
                sum(e.downtime_s for e in self.entries), 6
            ),
            "entries": [e.as_dict() for e in self.entries],
        }

    def _write_ledger(self, run_id: str, final: bool = False) -> dict:
        led = self._ledger(run_id, final=final)
        d = os.path.dirname(os.path.abspath(self.ledger_path))
        os.makedirs(d, exist_ok=True)
        tmp = self.ledger_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(led, f, indent=1)
        os.replace(tmp, self.ledger_path)
        return led

    # -- heartbeat ---------------------------------------------------------

    def _read_step(self, run_id: Optional[str] = None) -> int:
        """Last heartbeat step, or -1. When ``run_id`` is given, a
        heartbeat stamped by a DIFFERENT incarnation reads as -1 (no
        progress observed from THIS incarnation) — the dead run's file
        must not count as the live run's progress."""
        if not self.heartbeat_path:
            return -1
        try:
            with open(self.heartbeat_path) as f:
                hb = json.load(f)
        except (OSError, ValueError):
            return -1
        if run_id is not None and hb.get("run_id") not in (None, run_id):
            return -1
        try:
            return int(hb.get("step", -1))
        except (TypeError, ValueError):
            return -1

    # -- launching ---------------------------------------------------------

    def _launch_subprocesses(self, specs: list, attempt: int, run_id: str):
        """Default launcher: one subprocess per spec, stdout/stderr to
        per-child log files under ``log_dir`` (or inherited)."""
        procs = []
        try:
            for i, spec in enumerate(specs):
                if isinstance(spec, dict):
                    argv = list(spec["argv"])
                    env = dict(os.environ, **(spec.get("env") or {}))
                    cwd = spec.get("cwd")
                else:
                    argv, env, cwd = list(spec), dict(os.environ), None
                env[ENV_RUN_ID] = run_id
                env[ENV_LEDGER] = os.path.abspath(self.ledger_path)
                if self._verified_resume:
                    env[ENV_VERIFIED_RESUME] = "1"
                out = None
                if self.log_dir:
                    os.makedirs(self.log_dir, exist_ok=True)
                    out = open(
                        os.path.join(
                            self.log_dir, f"attempt{attempt}_child{i}.log"
                        ),
                        "w",
                    )
                try:
                    procs.append(
                        (
                            subprocess.Popen(
                                argv,
                                env=env,
                                cwd=cwd,
                                stdout=out,
                                stderr=subprocess.STDOUT if out else None,
                            ),
                            out,
                        )
                    )
                except BaseException:
                    if out:
                        out.close()
                    raise
        except BaseException:
            # a later spawn failed (bad argv, ENOMEM): the children
            # already started must not keep training unsupervised
            for p, out in procs:
                p.kill()
                p.wait()
                if out:
                    out.close()
            raise
        codes = []
        for p, out in procs:
            codes.append(p.wait())
            if out:
                out.close()
        return codes

    def _reset_incarnation_state(self):
        """Clear per-incarnation shared state (slice liveness dirs):
        the next world must not read the dead world's files."""
        for path in self.reset_paths:
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)

    # -- the loop ----------------------------------------------------------

    def run(self) -> SupervisorResult:
        no_progress = 0
        backoff_exp = 0
        # on a resumed ledger, the dead supervisor's final incarnation
        # already ended: the gap from its death to our first relaunch is
        # real downtime and must be charged like any other restart gap
        last_end: Optional[float] = (
            self.entries[-1].ended_unix if self.entries else None
        )
        while True:
            attempt = len(self.entries)
            stem = os.path.splitext(os.path.basename(self.ledger_path))[0]
            run_id = f"{stem}-i{attempt}"
            # before EVERY launch (including the first): a previous
            # world — this supervisor's, or a dead supervisor's whose
            # ledger we resumed — may have left per-incarnation shared
            # state (slice liveness files) behind
            self._reset_incarnation_state()
            led = self._write_ledger(run_id)  # the child folds this in
            ctx = {
                "attempt": attempt,
                "run_id": run_id,
                "num_slices": self.num_slices,
                "restarts": led["restarts"],
                "ledger": led,
                # custom launchers (tests, fleet builders) see the
                # verified-resume demand too; the default subprocess
                # launcher exports FMS_VERIFIED_RESUME itself
                "verified_resume": self._verified_resume,
            }
            specs = self.build_command(ctx)
            entry = _Entry(
                attempt=attempt,
                run_id=run_id,
                resumed_step=self._read_step(),
                started_unix=self._clock(),
            )
            if last_end is not None and self.entries:
                # downtime of the PREVIOUS incarnation's restart: death
                # -> this launch (backoff + cooldown + spawn overhead)
                self.entries[-1].downtime_s = max(
                    0.0, entry.started_unix - last_end
                )
                self._write_ledger(run_id)
            self._log(
                f"attempt {attempt} (run_id {run_id}, num_slices "
                f"{self.num_slices}, resumed step {entry.resumed_step}): "
                f"launching {len(specs)} child process(es)"
            )
            entry.exit_codes = list(self._launch(specs, attempt, run_id))
            entry.ended_unix = self._clock()
            last_end = entry.ended_unix
            entry.classification = classify_world(entry.exit_codes)
            entry.step_at_exit = self._read_step(run_id)
            self.entries.append(entry)

            cls = entry.classification
            if cls == "ok":
                step = entry.step_at_exit
                if self.target_step is not None and (
                    step < self.target_step
                ):
                    # a clean exit short of the target: the preemption
                    # save path ("exiting clean") — relaunch
                    cls = entry.classification = "preempted"
                    entry.note = (
                        f"clean exit at step {step} < target "
                        f"{self.target_step}: classified preempted"
                    )
                else:
                    self._log(
                        f"attempt {attempt} completed (step "
                        f"{entry.step_at_exit}); "
                        f"{len(self.entries) - 1} restart(s) total"
                    )
                    return self._finish("completed", run_id)
            policy = self.policies.get(cls) or self.policies["error"]
            self._log(
                f"attempt {attempt} exited {entry.exit_codes} -> "
                f"classified {cls!r} (heartbeat step {entry.step_at_exit})"
            )
            if policy.verified_resume and not self._verified_resume:
                self._verified_resume = True
                entry.note = (
                    entry.note + " " if entry.note else ""
                ) + (
                    "state divergence: all further incarnations resume "
                    "under the verified-resume rule (scrub-verified "
                    "checkpoints only)"
                )
                self._log(entry.note)
            if not policy.restart:
                return self._finish("gave_up", run_id)

            # crash-loop guard: heartbeat progress across incarnations.
            # A restart that failed before its first report (step -1) or
            # never got past the previous incarnation's step counts
            # toward the loop; any advance resets it.
            prev_best = max(
                (e.step_at_exit for e in self.entries[:-1]), default=-1
            )
            if entry.step_at_exit > prev_best:
                no_progress = 0
                backoff_exp = 0
            else:
                no_progress += 1
                if no_progress >= self.crash_loop_threshold:
                    return self._finish(
                        "crash_loop",
                        run_id,
                        reason=(
                            f"step did not advance across "
                            f"{no_progress} consecutive restart(s) "
                            f"(stuck at {max(prev_best, entry.step_at_exit)})"
                        ),
                    )
            if len(self.entries) - 1 >= self.max_restarts:
                return self._finish(
                    "max_restarts",
                    run_id,
                    reason=f"max_restarts={self.max_restarts} exhausted",
                )

            delay = policy.cooldown_s
            if policy.backoff:
                delay += self.restart_backoff_s * (2**backoff_exp)
                backoff_exp += 1
            if policy.drop_slice and self.num_slices > 1:
                self.num_slices -= 1
                entry.note = (
                    entry.note + " " if entry.note else ""
                ) + (
                    f"slice loss: relaunching at world minus one fault "
                    f"domain (num_slices -> {self.num_slices})"
                )
                self._log(entry.note)
            if delay > 0:
                self._log(
                    f"relaunching after {delay:.1f}s "
                    f"({'cooldown + ' if policy.cooldown_s else ''}backoff)"
                )
                self._sleep(delay)

    def _finish(self, status: str, run_id: str, reason: str = ""):
        led = self._write_ledger(run_id, final=True)
        final_step = max((e.step_at_exit for e in self.entries), default=-1)
        pm = ""
        if status != "completed":
            pm = self.post_mortem(reason)
            self._log(pm)
        return SupervisorResult(
            status=status,
            restarts=max(0, len(self.entries) - 1),
            final_step=final_step,
            ledger=led,
            post_mortem=pm,
        )

    def post_mortem(self, reason: str = "") -> str:
        """The give-up summary: one line per incarnation — exit class,
        resumed step, step at exit, downtime its restart cost — so the
        operator reads the whole restart history without grepping logs."""
        lines = [
            "supervisor giving up"
            + (f": {reason}" if reason else "")
            + f" (ledger: {self.ledger_path})"
        ]
        for e in self.entries:
            lines.append(
                f"  attempt {e.attempt}: exit {e.exit_codes} -> "
                f"{e.classification or '?'}, resumed step "
                f"{e.resumed_step}, step at exit {e.step_at_exit}, "
                f"restart downtime {e.downtime_s:.1f}s"
                + (f" ({e.note})" if e.note else "")
            )
        lines.append(
            f"  total: {max(0, len(self.entries) - 1)} restart(s), "
            f"{sum(e.downtime_s for e in self.entries):.1f}s downtime"
        )
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# keep-N replica supervision (the serving-fleet generalization)
# ---------------------------------------------------------------------------


def default_replica_policies() -> Dict[str, RestartPolicy]:
    """Per-exit-class relaunch policy for a serving replica set. Much
    simpler than the training table: a replica is stateless capacity
    (its KV cache is recomputable — the router's journal requeues its
    in-flight requests), so almost every death class relaunches with
    backoff. ``ok`` is the drain path: a replica that exited clean was
    ASKED to stop and must not be resurrected."""
    return {
        "ok": RestartPolicy(restart=False),
        # the dedicated replica death class (and the watchdog-killed
        # stall the router classifies the same way): relaunch without
        # backoff — lost capacity is paid for by every queued request,
        # and the crash-loop guard still ends a replica that dies
        # repeatedly without serving anything
        "replica_loss": RestartPolicy(backoff=False),
        # drain-and-migrate (serve/replica.py SIGTERM path): the replica
        # packed its live streams, shipped them to siblings through the
        # router, and exited clean with the ``preempted`` registry code.
        # Planned eviction is not a crash — relaunch immediately, no
        # backoff (the scheduler that preempted the host decides whether
        # the relaunch actually lands)
        "preempted": RestartPolicy(backoff=False),
        "injected_kill": RestartPolicy(),
        "watchdog_stall": RestartPolicy(),
        "anomaly_abort": RestartPolicy(),
        "error": RestartPolicy(),
    }


@dataclass
class _ReplicaEntry:
    """One replica incarnation's ledger row."""

    replica: int
    incarnation: int
    run_id: str
    started_unix: float = 0.0
    ended_unix: float = 0.0
    exit_code: Optional[int] = None
    classification: str = ""
    progress_at_exit: int = 0  # router-fed completions when it died
    downtime_s: float = 0.0  # death -> its successor's launch
    note: str = ""

    def as_dict(self) -> dict:
        return dict(self.__dict__)


class _ReplicaSlot:
    """Mutable per-replica-index state: the live handle plus the
    relaunch bookkeeping (state machine live -> down -> live, or
    -> failed when a rail fires)."""

    def __init__(self, index: int):
        self.index = index
        self.state = "idle"  # idle | live | down | failed
        self.handle = None
        self.incarnation = -1
        self.run_id = ""
        self.started = 0.0
        self.died_at = 0.0
        self.relaunch_at = 0.0
        self.backoff_exp = 0
        self.no_progress = 0
        self.progress = 0  # router-fed monotone completion count
        self.progress_at_launch = 0
        self.pending_class: Optional[str] = None
        self.pending_note = ""
        self.restarts = 0
        self.fail_reason = ""


class ReplicaSetSupervisor:
    """Keep N serving replicas alive: the RunSupervisor loop generalized
    from "relaunch the training world" (one blocking launch-classify-
    relaunch cycle) to "N concurrent children, each on its own
    classify/backoff/crash-loop track, polled without blocking" —
    the fleet router drives ``poll()`` from its dispatch loop.

    Shared with RunSupervisor: the exits-registry classification
    (``classify_exit``), :class:`RestartPolicy` semantics (backoff
    doubling on consecutive no-progress deaths, reset on progress),
    per-incarnation run ids (``replica<K>-i<N>`` — heartbeats and
    journal assignments are stamped with them so a dead incarnation's
    records never pass for the live one's), the crash-loop guard
    (``crash_loop_threshold`` consecutive deaths of one replica without
    a served request end THAT replica with a post-mortem — the fleet
    degrades to N-1 instead of burning the host on a relaunch loop),
    and a restart ledger. New here: the ledger folds into an
    **availability** metric — replica-seconds live over replica-seconds
    owed since ``start()`` — the serving twin of the training ledger's
    goodput charge (obs schema v11 ``serving_fleet`` map).

    ``spawn(ctx)`` returns a replica handle exposing ``poll() ->
    Optional[int]`` and ``kill()`` (the router's subprocess handles add
    send/recv on top; the supervisor only manages lifecycle). ``ctx``
    carries ``replica``, ``incarnation``, ``run_id``, ``restarts``.

    The router reports progress via ``note_progress(idx, completed)``
    (a monotone per-replica completion count from heartbeats) and asks
    for watchdog kills via ``kill(idx, classify_as=..., note=...)`` —
    a stalled replica's SIGKILL would otherwise classify as ``error``;
    the router knows the cause (no heartbeat with work in flight) and
    pins the classification before the exit code exists.
    """

    def __init__(
        self,
        spawn: Callable[[dict], object],
        n_replicas: int,
        *,
        ledger_path: Optional[str] = None,
        policies: Optional[Dict[str, RestartPolicy]] = None,
        max_restarts_per_replica: int = 8,
        restart_backoff_s: float = 1.0,
        crash_loop_threshold: int = 3,
        clock: Callable[[], float] = time.time,
        log: Callable[[str], None] = None,
    ):
        assert n_replicas >= 1, n_replicas
        self.spawn = spawn
        self.n_replicas = int(n_replicas)
        self.ledger_path = ledger_path
        self.policies = policies or default_replica_policies()
        self.max_restarts_per_replica = int(max_restarts_per_replica)
        self.restart_backoff_s = float(restart_backoff_s)
        self.crash_loop_threshold = max(1, int(crash_loop_threshold))
        self._clock = clock
        self._log = log or (
            lambda msg: print(f"[replica-supervisor] {msg}", flush=True)
        )
        self.slots = [_ReplicaSlot(i) for i in range(self.n_replicas)]
        self.entries: List[_ReplicaEntry] = []
        self.started_at: Optional[float] = None
        self.stalls_detected = 0

    # -- lifecycle ---------------------------------------------------------

    def _launch(self, slot: _ReplicaSlot) -> dict:
        slot.incarnation += 1
        slot.run_id = f"replica{slot.index}-i{slot.incarnation}"
        ctx = {
            "replica": slot.index,
            "incarnation": slot.incarnation,
            "run_id": slot.run_id,
            "restarts": slot.restarts,
        }
        slot.handle = self.spawn(ctx)
        slot.started = self._clock()
        slot.progress_at_launch = slot.progress
        slot.pending_class = None
        slot.pending_note = ""
        if slot.incarnation > 0 and slot.died_at:
            # close the downtime of the incarnation this launch replaces
            for e in reversed(self.entries):
                if e.replica == slot.index:
                    e.downtime_s = max(0.0, slot.started - slot.died_at)
                    break
        slot.state = "live"
        self._write_ledger()
        return ctx

    def start(self) -> None:
        """Launch all N replicas (incarnation 0 each)."""
        assert self.started_at is None, "start() is one-shot"
        self.started_at = self._clock()
        for slot in self.slots:
            self._launch(slot)
            self._log(
                f"replica {slot.index} launched (run_id {slot.run_id})"
            )

    def handle(self, idx: int):
        """The CURRENT incarnation's handle for replica ``idx`` (None
        while it is down/failed)."""
        slot = self.slots[idx]
        return slot.handle if slot.state == "live" else None

    def run_id(self, idx: int) -> str:
        return self.slots[idx].run_id

    def live_indices(self) -> List[int]:
        return [s.index for s in self.slots if s.state == "live"]

    def note_progress(self, idx: int, completed: int) -> None:
        """Router-fed monotone completion count for replica ``idx`` —
        the crash-loop guard's progress signal (a replica that keeps
        dying without ever completing a request is looping)."""
        self.slots[idx].progress = max(self.slots[idx].progress, completed)

    def kill(
        self, idx: int, classify_as: str = "replica_loss", note: str = ""
    ) -> None:
        """Router-initiated kill with a pinned classification: the
        watchdog path for a stalled replica. The SIGKILL's raw exit
        code (a signal death -> ``error``) must not pick the policy —
        the router knows WHY it killed."""
        slot = self.slots[idx]
        if slot.state != "live" or slot.handle is None:
            return
        if slot.pending_class is not None:
            return  # kill already in flight; don't double-count
        slot.pending_class = classify_as
        slot.pending_note = note
        self.stalls_detected += 1
        self._log(
            f"replica {idx} (run_id {slot.run_id}) killed by router: "
            f"{note or classify_as}"
        )
        slot.handle.kill()

    def stop_all(self) -> None:
        """Kill every live replica (fleet shutdown; no relaunch —
        callers stop polling after this)."""
        for slot in self.slots:
            if slot.state == "live" and slot.handle is not None:
                slot.pending_class = "ok"
                slot.pending_note = "fleet shutdown"
                slot.handle.kill()
                slot.state = "idle"
        self._write_ledger(final=True)

    # -- the poll loop -----------------------------------------------------

    def poll(self) -> List[dict]:
        """One non-blocking sweep: reap deaths, classify, schedule and
        perform due relaunches. Returns events the router acts on:
        ``{"event": "died", "replica": i, "run_id": ...,
        "classification": ...}`` (requeue that incarnation's in-flight
        work), ``{"event": "relaunched", "replica": i, "run_id": ...}``
        (a fresh handle is installed), and ``{"event": "gave_up",
        "replica": i, "reason": ..., "post_mortem": ...}`` (the fleet
        is permanently down a replica)."""
        now = self._clock()
        events: List[dict] = []
        for slot in self.slots:
            if slot.state == "live" and slot.handle is not None:
                code = slot.handle.poll()
                if code is None:
                    continue
                events.extend(self._reap(slot, code, now))
            elif slot.state == "down" and now >= slot.relaunch_at:
                ctx = self._launch(slot)
                self._log(
                    f"replica {slot.index} relaunched (run_id "
                    f"{slot.run_id}, restart {slot.restarts})"
                )
                events.append(
                    {
                        "event": "relaunched",
                        "replica": slot.index,
                        "run_id": slot.run_id,
                        "ctx": ctx,
                    }
                )
        return events

    def _reap(self, slot: _ReplicaSlot, code: int, now: float) -> List[dict]:
        cls = slot.pending_class or classify_exit(code)
        entry = _ReplicaEntry(
            replica=slot.index,
            incarnation=slot.incarnation,
            run_id=slot.run_id,
            started_unix=slot.started,
            ended_unix=now,
            exit_code=code,
            classification=cls,
            progress_at_exit=slot.progress,
            note=slot.pending_note,
        )
        self.entries.append(entry)
        slot.died_at = now
        dead_run_id = slot.run_id
        self._log(
            f"replica {slot.index} (run_id {dead_run_id}) exited "
            f"{code} -> classified {cls!r}"
        )
        events = [
            {
                "event": "died",
                "replica": slot.index,
                "run_id": dead_run_id,
                "classification": cls,
                # the dead incarnation's handle: the router drains its
                # remaining output (exactly-once delivery) before the
                # journal requeues its in-flight work
                "handle": slot.handle,
            }
        ]
        policy = self.policies.get(cls) or self.policies["error"]
        if not policy.restart:
            slot.state = "idle"
            slot.handle = None
            self._write_ledger()
            return events

        # crash-loop guard: progress (router-fed completions) must
        # advance across THIS replica's consecutive incarnations
        if slot.progress > slot.progress_at_launch:
            slot.no_progress = 0
            slot.backoff_exp = 0
        else:
            slot.no_progress += 1
        slot.handle = None
        if slot.no_progress >= self.crash_loop_threshold:
            return events + [self._give_up(
                slot,
                f"no completed request across {slot.no_progress} "
                f"consecutive incarnation(s)",
            )]
        if slot.restarts >= self.max_restarts_per_replica:
            return events + [self._give_up(
                slot,
                f"max_restarts_per_replica="
                f"{self.max_restarts_per_replica} exhausted",
            )]
        delay = policy.cooldown_s
        if policy.backoff:
            delay += self.restart_backoff_s * (2**slot.backoff_exp)
            slot.backoff_exp += 1
        slot.restarts += 1
        slot.state = "down"
        slot.relaunch_at = now + delay
        self._write_ledger()
        return events

    def _give_up(self, slot: _ReplicaSlot, reason: str) -> dict:
        slot.state = "failed"
        slot.fail_reason = reason
        pm_lines = [
            f"replica {slot.index} given up: {reason}"
            + (f" (ledger: {self.ledger_path})" if self.ledger_path else "")
        ]
        for e in self.entries:
            if e.replica != slot.index:
                continue
            pm_lines.append(
                f"  incarnation {e.incarnation}: exit {e.exit_code} -> "
                f"{e.classification}, completions at exit "
                f"{e.progress_at_exit}, restart downtime "
                f"{e.downtime_s:.1f}s"
                + (f" ({e.note})" if e.note else "")
            )
        pm = "\n".join(pm_lines)
        self._log(pm)
        self._write_ledger()
        return {
            "event": "gave_up",
            "replica": slot.index,
            "reason": reason,
            "post_mortem": pm,
        }

    # -- ledger / availability ---------------------------------------------

    def restarts(self) -> int:
        return sum(s.restarts for s in self.slots)

    def availability(self, now: Optional[float] = None) -> float:
        """Replica-seconds live / replica-seconds owed since start():
        the restart ledger folded into one number. 1.0 = no replica was
        ever down; every death subtracts its death-to-relaunch gap
        (open gaps of currently-down/failed replicas count up to
        ``now``). The serving acceptance records this measured < 1.0
        under churn (scripts/chaos_soak_serving.py)."""
        if self.started_at is None:
            return 1.0
        now = self._clock() if now is None else now
        owed = (now - self.started_at) * self.n_replicas
        if owed <= 0:
            return 1.0
        down = sum(e.downtime_s for e in self.entries)
        for slot in self.slots:
            if slot.state in ("down", "failed") and slot.died_at:
                closed = any(
                    e.replica == slot.index and e.downtime_s > 0
                    for e in reversed(self.entries)
                    if e.incarnation == slot.incarnation
                )
                if not closed:
                    down += max(0.0, now - slot.died_at)
        return max(0.0, min(1.0, 1.0 - down / owed))

    def ledger(self, final: bool = False) -> dict:
        return {
            "version": LEDGER_VERSION,
            "kind": "replica_set",
            "n_replicas": self.n_replicas,
            "restarts": self.restarts(),
            "stalls_detected": self.stalls_detected,
            "availability": round(self.availability(), 6),
            "replica_downtime_s": round(
                sum(e.downtime_s for e in self.entries), 6
            ),
            "entries": [e.as_dict() for e in self.entries],
        }

    def _write_ledger(self, final: bool = False) -> None:
        if not self.ledger_path:
            return
        led = self.ledger(final=final)
        d = os.path.dirname(os.path.abspath(self.ledger_path))
        os.makedirs(d, exist_ok=True)
        tmp = self.ledger_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(led, f, indent=1)
        os.replace(tmp, self.ledger_path)


def supervise_from_config(cfg, build_command, **kwargs) -> RunSupervisor:
    """RunSupervisor with the policy knobs read from TrainConfig
    (``max_restarts`` / ``restart_backoff_s`` / ``crash_loop_threshold``,
    docs/configurations.md)."""
    kwargs.setdefault("max_restarts", int(getattr(cfg, "max_restarts", 8)))
    kwargs.setdefault(
        "restart_backoff_s", float(getattr(cfg, "restart_backoff_s", 5.0))
    )
    kwargs.setdefault(
        "crash_loop_threshold",
        int(getattr(cfg, "crash_loop_threshold", 3)),
    )
    kwargs.setdefault("num_slices", max(1, int(getattr(cfg, "num_slices", 0) or 1)))
    return RunSupervisor(build_command, **kwargs)


def main(argv=None) -> int:
    """One-host CLI: everything after ``--`` is the training command,
    relaunched verbatim each incarnation (an ``{num_slices}`` placeholder
    in any arg is substituted per incarnation for shrink restarts)."""
    import argparse

    argv = sys.argv[1:] if argv is None else list(argv)
    if "--" in argv:
        split = argv.index("--")
        argv, cmd = argv[:split], argv[split + 1 :]
    else:
        cmd = []
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ledger", required=True)
    ap.add_argument("--heartbeat", default=None)
    ap.add_argument("--target-step", type=int, default=None)
    ap.add_argument("--max-restarts", type=int, default=8)
    ap.add_argument("--restart-backoff-s", type=float, default=5.0)
    ap.add_argument("--crash-loop-threshold", type=int, default=3)
    ap.add_argument("--anomaly-cooldown-s", type=float, default=30.0)
    ap.add_argument("--num-slices", type=int, default=1)
    ap.add_argument(
        "--on-slice-loss", choices=("shrink", "same"), default="shrink"
    )
    ap.add_argument("--log-dir", default=None)
    args = ap.parse_args(argv)
    if not cmd:
        ap.error("no training command after '--'")
    if args.target_step is not None and not args.heartbeat:
        ap.error(
            "--target-step requires --heartbeat (the run's obs "
            "heartbeat.json): completion is read from the heartbeat step"
        )

    def build(ctx):
        return [[a.replace("{num_slices}", str(ctx["num_slices"])) for a in cmd]]

    result = RunSupervisor(
        build,
        ledger_path=args.ledger,
        heartbeat_path=args.heartbeat,
        target_step=args.target_step,
        max_restarts=args.max_restarts,
        restart_backoff_s=args.restart_backoff_s,
        crash_loop_threshold=args.crash_loop_threshold,
        anomaly_cooldown_s=args.anomaly_cooldown_s,
        on_slice_loss=args.on_slice_loss,
        num_slices=args.num_slices,
        log_dir=args.log_dir,
    ).run()
    print(
        f"[supervisor] {result.status}: {result.restarts} restart(s), "
        f"final step {result.final_step}"
    )
    return 0 if result.status == "completed" else 1


if __name__ == "__main__":
    sys.exit(main())
