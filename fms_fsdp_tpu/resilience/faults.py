"""Deterministic fault injection at named sites.

Every recovery path in the harness (shard-read retry/quarantine, loader
worker restart, non-finite-batch skip/abort, checkpoint-corruption
fallback) must be testable on CPU without real flaky storage or a real
diverging model. This registry injects faults deterministically at the
named sites the production code consults:

==============  =======================================================
site            fires where
==============  =======================================================
shard_read      RetryingShardHandler, before each delegated
                open/length/get/slice call (raises OSError)
loader_worker   StatefulDataLoader worker loops (thread + process),
                after each produced batch (raises RuntimeError, or
                hard-exits with ``action=exit``)
nan_loss        inside the jitted train step (multiplies loss and grads
                by NaN for the matching step window) — consulted once
                at trace time via :func:`fault_params`
ckpt_corrupt    Checkpointer.save / the async writer thread, after the
                commit marker is written (truncates one file inside the
                committed checkpoint)
ckpt_writer_crash
                AsyncCheckpointManager's background writer thread,
                after the storage write and before commit (raises
                RuntimeError in the writer; the error must surface in
                the next ``save``/``finalize``)
ckpt_precommit_kill
                AsyncCheckpointManager's writer, between the snapshot
                (fully written dir) and the metadata.json commit marker
                (hard-exits the process with ``code``, default the
                ``injected_kill`` registry code) — the mid-save kill
                whose torn dir resume must skip
ckpt_durable_write
                AsyncCheckpointManager's per-tier commit IO, before the
                manifest write (raises OSError — injected ENOSPC/EIO).
                ``times=K`` within the retry budget is absorbed by the
                bounded commit retry; an unbounded fault on the durable
                tier exhausts it and triggers the degrade-to-local path
                (checkpoint.durable_degraded counter)
ckpt_shard_corrupt
                Checkpointer.save / the async writer, after the commit
                marker: flips ``bytes=N`` (default 4) at the midpoint of
                a manifest-recorded file (``file=<substring>`` selects;
                largest match first, so the default hits an array
                shard) WITHOUT changing its size — the silent bit-rot /
                SDC-storage class that passes every size check and only
                the manifest-v2 content checksums or the scrubber catch
                (the committed dir must quarantine and resume must
                route around it)
sdc_grad_flip   the train loop's step boundary, host-side (the
                observable effect of an update computed from a
                corrupted gradient): scales ONE process's addressable
                shards of the largest param leaf by ``scale`` (default
                1.5) on loop step ``step``, ``proc=P`` selecting the
                victim (resilience/divergence.py::inject_sdc — kept
                OUT of the trace: any per-process program difference
                shifts XLA rounding on every step). That process's
                slice silently diverges from its replicas; the
                report-cadence cross-replica fingerprint compare must
                detect it and exit classified ``state_divergence``
slice_kill      the train loop's step boundary, before the step is
                dispatched (hard-exits the process with ``code``,
                default the ``injected_kill`` registry code,
                resilience/exits.py). Filtered by ``slice``/``step``, it kills
                every process of one fault domain at once — the
                whole-slice preemption the SliceHealthMonitor must
                detect and the surviving slices must classify
                (resilience/slices.py)
dcn_reduce_stall
                the same step boundary (parks the rank in a
                ``seconds``-long sleep, default 3600) — the wedged
                cross-slice reduce whose hang the slice/step watchdogs
                must convert into an actionable report instead of a
                burned reservation
replica_kill    the serving replica loop's engine-iteration boundary
                (serve/replica.py): hard-exits the replica process with
                ``code`` (default the ``replica_loss`` registry code) —
                the mid-stream replica death whose in-flight requests
                the fleet router must requeue with zero drops
                (serve/fleet.py). Filtered by ``replica`` (index) and
                ``step`` (engine iteration)
replica_stall   the same replica-loop boundary: parks the replica in a
                ``seconds``-long sleep (default 3600) WITHOUT dying —
                heartbeats stop while the process lives, the hang class
                the router's stall watchdog must detect, kill, classify
                ``replica_loss``, and relaunch (a wedged replica is
                dead capacity; waiting on it drops every stream it
                holds)
corpus_kill     SamplingDataset document boundaries and re-probe
                attempts (data/streaming.py): a match simulates every
                owned shard of the named corpus dying at once — the
                corpus quarantines and the mix degrades (weights
                renormalized over survivors) or, below the
                ``min_live_corpora`` floor, exits classified as
                ``corpus_loss``. Filtered by ``corpus`` (substring, so
                one clause can kill a corpus family); ``times=N`` lets
                the survivor-epoch re-probe heal it after N matches
handoff_chunk_corrupt
                ChunkSender.pump (serve/disagg/transport.py), per chunk
                send: flips a payload byte AFTER the CRC was computed,
                so the receiver's check fails, the chunk is dropped
                unacked, and the sender's retransmit timer must heal
                it. ``every=N`` payload acts on every Nth matched send
                (the bench's deterministic 1% corruption); filtered by
                ``transport`` (channel label substring) and ``step``
                (chunk seq)
handoff_chunk_drop
                same site: the send is skipped entirely (wire loss) —
                consumed a retry attempt, nothing reaches the receiver.
                Same ``every=`` / ``transport=`` / ``step=`` handling
transport_stall DataChannel._stalled (serve/disagg/transport.py): parks
                the channel — no reads, no writes, frames queue — for
                ``seconds=S`` (default 5) WITHOUT blocking the caller;
                the router's heartbeat/dispatch loop keeps beating
                while the transfer watchdog / chunk retry budget
                decides the transfer's fate. Filtered by ``transport``
==============  =======================================================

Spec strings configure the registry, via the ``FMS_FAULTS`` environment
variable or ``TrainConfig.faults``::

    site[:key=value]*  joined by ';'
    e.g.  "shard_read:path=quartershard:times=2;nan_loss:step=5:count=3"

Filter params are matched against the call-site context before firing:
``path`` / ``op`` / ``tier`` / ``corpus`` / ``transport`` (substring),
``worker`` / ``batch`` / ``step`` / ``slice`` / ``proc`` / ``replica``
(equality). A configured filter the call site does not supply in its
context is a non-match (the fault does not fire) — a typo'd filter must
never degrade into firing everywhere.
``times=N`` caps the number of fires (per process; counters are
inherited across fork but not shared back). Everything else
(``count``, ``action``, ``code``, ``file``) is payload the call site
interprets. Production runs leave the registry empty: every hook is a
dict lookup returning None.
"""

import os
import threading
from typing import Any, Dict, Optional

_LOCK = threading.Lock()
# site -> params; None until first configure (lazy env read)
_SPECS: Optional[Dict[str, Dict[str, Any]]] = None
_FIRED: Dict[str, int] = {}

ENV_VAR = "FMS_FAULTS"

# params that filter whether a call-site context matches (vs payload)
_FILTER_KEYS = (
    "path", "op", "worker", "batch", "step", "tier", "slice", "corpus",
    "proc", "replica", "transport",
)


def _parse_value(v: str):
    try:
        return int(v)
    except ValueError:
        try:
            return float(v)
        except ValueError:
            return v


def parse_spec(spec: str) -> Dict[str, Dict[str, Any]]:
    """Parse ``site:key=val:key=val;site2:...`` into {site: params}."""
    out: Dict[str, Dict[str, Any]] = {}
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        site, params = parts[0].strip(), {}
        for kv in parts[1:]:
            if not kv.strip():
                continue
            if "=" not in kv:
                raise ValueError(
                    f"fault clause {clause!r}: expected key=value, got {kv!r}"
                )
            k, v = kv.split("=", 1)
            params[k.strip()] = _parse_value(v.strip())
        out[site] = params
    return out


def configure_faults(spec: Optional[str]) -> None:
    """(Re)configure the registry from a spec string; None or "" clears
    it (and suppresses the lazy env read)."""
    global _SPECS
    with _LOCK:
        _SPECS = parse_spec(spec) if spec else {}
        _FIRED.clear()


def _specs() -> Dict[str, Dict[str, Any]]:
    global _SPECS
    if _SPECS is None:
        with _LOCK:
            if _SPECS is None:
                _SPECS = parse_spec(os.environ.get(ENV_VAR, ""))
    return _SPECS


def fault_params(site: str) -> Optional[Dict[str, Any]]:
    """The raw configured params for ``site`` (no firing, no counters) —
    for sites consulted once at build/trace time (``nan_loss``)."""
    return _specs().get(site)


def fire_fault(site: str, **ctx) -> Optional[Dict[str, Any]]:
    """Fire ``site`` if configured and the context matches its filters.
    Returns the params dict on fire (the call site interprets payload
    keys), else None."""
    params = _specs().get(site)
    if params is None:
        return None
    for key in _FILTER_KEYS:
        if key in params:
            if key not in ctx:
                # a configured filter the call site can't evaluate is a
                # NON-match: firing everywhere because a filter didn't
                # apply would be maximal injection from a typo
                return None
            want, got = params[key], ctx[key]
            if isinstance(want, str):
                if want not in str(got):
                    return None
            elif want != got:
                return None
    with _LOCK:
        times = params.get("times")
        if times is not None and _FIRED.get(site, 0) >= times:
            return None
        _FIRED[site] = _FIRED.get(site, 0) + 1
    return params


def maybe_raise_fault(site: str, exc_cls=OSError, **ctx) -> None:
    """Fire ``site`` and raise ``exc_cls`` when it matches."""
    params = fire_fault(site, **ctx)
    if params is not None:
        raise exc_cls(
            f"injected fault at site {site!r} (ctx={ctx}, params={params})"
        )
