"""Central exit-code registry: every fail-fast site exits with a code
the run supervisor can map to a restart policy.

The resilience stack deliberately ends every unrecoverable failure in a
fail-fast exit (``os._exit`` from a watchdog thread, ``SystemExit`` from
a classified entry-point wrapper) so the process never burns a
reservation hanging in a dead collective. Before this registry each site
picked its own code ad hoc — the loader's injected-kill default (3)
collided with the slice-loss code, so a dead loader classified as a lost
slice. Now there is ONE table; a uniqueness test
(tests/test_supervisor.py) keeps it collision-free, and
``resilience/supervisor.py`` maps each class to a restart policy
(docs/resilience.md "Self-healing supervisor").

==================  ====  ===================================================
class               code  exited by
==================  ====  ===================================================
ok                  0     a run that reached num_steps (or a clean
                          preemption exit — the supervisor tells the two
                          apart by the heartbeat step vs its target)
error               1     any unclassified Python exception (the
                          interpreter default; never exited explicitly)
watchdog_stall      2     StepWatchdog (resilience/guards.py): no training
                          progress inside step_timeout_s
slice_loss          3     SliceHealthMonitor (resilience/slices.py): every
                          process of a peer fault domain went silent; also
                          the classified re-raise path (SliceLostError
                          through the entry wrapper)
anomaly_abort       4     the anomaly guard's DeliberateAbort through the
                          entry wrapper: K consecutive non-finite steps,
                          checkpoint saved, aborting on purpose
loader_death        5     LoaderWorkerError through the entry wrapper: a
                          loader worker died and the restart budget is
                          exhausted (also the loader_worker fault site's
                          ``action=exit`` default for the worker process
                          itself)
preempted           6     reserved for schedulers that need preemption
                          nonzero; the in-repo loop exits 0 after the
                          preemption save ("exiting clean") and the
                          supervisor classifies it from the heartbeat step
injected_kill       7     fault-injection hard-kills (slice_kill,
                          ckpt_precommit_kill) when the spec carries no
                          explicit ``code=``
corpus_loss         8     CorpusLossError through the entry wrapper: the
                          weighted data mix lost a corpus and fewer than
                          ``min_live_corpora`` corpora remain live (losing
                          the LAST corpus always breaches the floor) — the
                          data is gone, not the worker, so the supervisor
                          relaunches expecting the corpus restored
state_divergence    9     StateDivergenceError through the entry wrapper
                          (resilience/divergence.py): the report-cadence
                          cross-replica fingerprint compare found a
                          replicated train state disagreeing across
                          processes — SDC or a broken reduce. The state in
                          memory (and possibly the newest checkpoint) is
                          suspect, so the supervisor's policy relaunches
                          under the VERIFIED-resume rule: restore only from
                          a scrub-verified checkpoint (FMS_VERIFIED_RESUME)
replica_loss        10    a serving replica died: ReplicaLostError through
                          the entry wrapper, the replica child's engine
                          failure path (serve/replica.py), or the fleet
                          router's watchdog kill of a stalled replica
                          (serve/fleet.py — a replica that stops
                          heartbeating mid-stream is dead capacity even if
                          the process is technically alive). The
                          ReplicaSetSupervisor's keep-N policy relaunches
                          it and the router requeues its in-flight
                          requests (recompute-on-resume, zero drops)
==================  ====  ===================================================

``classify_world`` merges one incarnation's per-host exit codes into the
single most-causal class: a loader death on one host surfaces on its
peers as a slice loss or watchdog stall (the collective died under
them), and the restart policy must key on the cause, not the echo.

Run incarnations: the supervisor exports ``FMS_RUN_ID`` (identical on
every host of one incarnation) and ``FMS_RESTART_LEDGER`` (the restart
ledger path). ``current_run_id``/``read_restart_ledger`` are the child-
side readers — the heartbeat and slice-liveness files stamp the run id
so a freshly restarted run never mistakes the dead incarnation's records
for live progress, and the observer folds the ledger's restart downtime
into goodput (obs schema v6 ``restarts``/``restart_downtime_s``).
"""

import contextlib
import json
import os
import sys
import traceback
from typing import Dict, Iterable, Optional

ENV_RUN_ID = "FMS_RUN_ID"
ENV_LEDGER = "FMS_RESTART_LEDGER"

EXIT_CODES: Dict[str, int] = {
    "ok": 0,
    "error": 1,
    "watchdog_stall": 2,
    "slice_loss": 3,
    "anomaly_abort": 4,
    "loader_death": 5,
    "preempted": 6,
    "injected_kill": 7,
    "corpus_loss": 8,
    "state_divergence": 9,
    "replica_loss": 10,
}

# most-causal-first: when one incarnation's hosts exit with different
# codes (the cause on one host, its echoes on the peers), the world
# classifies as the first class present in this order. loader_death and
# anomaly_abort outrank slice_loss/watchdog_stall because a single dead
# process IS a dead fault domain to a 1-host slice's peers — the echo
# must not pick the restart policy.
CLASSIFY_PRIORITY = (
    "loader_death",
    "corpus_loss",
    # every process detects divergence at the same collective compare
    # and exits 9 together, but a rank that was wedged inside the
    # allgather when its peers bailed can echo as a watchdog stall or
    # slice loss — the divergence is the cause and must pick the
    # (verified-resume) restart policy
    "state_divergence",
    "anomaly_abort",
    # a serving replica's death is the cause; its peers (if a future
    # sharded replica spans processes) echo as slice/watchdog exits
    "replica_loss",
    "slice_loss",
    "watchdog_stall",
    "preempted",
    "injected_kill",
    "error",
    "ok",
)


def exit_code(name: str) -> int:
    return EXIT_CODES[name]


def classify_exit(code: Optional[int]) -> str:
    """Exit code -> class name. Unknown nonzero codes (including signal
    deaths, surfaced by subprocess as negative codes) classify as
    ``error`` — the supervisor's bounded generic retry."""
    if code is None:
        return "error"
    for name, c in EXIT_CODES.items():
        if c == code:
            return name
    return "error"


def classify_world(codes: Iterable[Optional[int]]) -> str:
    """Merge one incarnation's per-host exit codes into the single
    most-causal class (see CLASSIFY_PRIORITY)."""
    classes = {classify_exit(c) for c in codes}
    for name in CLASSIFY_PRIORITY:
        if name in classes:
            return name
    return "ok"


def current_run_id() -> Optional[str]:
    """The incarnation id the supervisor exported for this process, or
    None when running unsupervised. Identical on every host of one
    incarnation (the supervisor derives it from its attempt counter), so
    it is safe to compare across a shared filesystem."""
    return os.environ.get(ENV_RUN_ID) or None


def read_restart_ledger(path: Optional[str] = None) -> Optional[dict]:
    """The supervisor's restart ledger (written BEFORE each launch so
    the child can fold prior downtime into goodput), or None when absent
    or unreadable — a torn ledger must never block a restart."""
    path = path or os.environ.get(ENV_LEDGER) or ""
    if not path:
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def classify_exception(e: BaseException) -> Optional[str]:
    """Exit class for a classified failure type, or None (unclassified —
    let the interpreter exit 1). Types are imported lazily: this runs on
    the crash path and must not create import cycles; a failing import
    just skips that classification."""
    checks = []
    try:
        from fms_fsdp_tpu.utils.train_utils import DeliberateAbort

        checks.append((DeliberateAbort, "anomaly_abort"))
    except Exception:  # noqa: BLE001 — crash path: classify what we can
        pass
    try:
        from fms_fsdp_tpu.resilience.slices import SliceLostError

        checks.append((SliceLostError, "slice_loss"))
    except Exception:  # noqa: BLE001
        pass
    try:
        from fms_fsdp_tpu.data.loader import LoaderWorkerError

        checks.append((LoaderWorkerError, "loader_death"))
    except Exception:  # noqa: BLE001
        pass
    try:
        from fms_fsdp_tpu.data.streaming import CorpusLossError

        # BEFORE the isinstance sweep order matters only across types
        # that nest; CorpusLossError and LoaderWorkerError are disjoint
        checks.append((CorpusLossError, "corpus_loss"))
    except Exception:  # noqa: BLE001
        pass
    try:
        from fms_fsdp_tpu.resilience.divergence import StateDivergenceError

        checks.append((StateDivergenceError, "state_divergence"))
    except Exception:  # noqa: BLE001
        pass
    try:
        from fms_fsdp_tpu.serve.fleet import ReplicaLostError

        checks.append((ReplicaLostError, "replica_loss"))
    except Exception:  # noqa: BLE001
        pass
    for typ, name in checks:
        if isinstance(e, typ):
            return name
    return None


@contextlib.contextmanager
def classified_exit():
    """Entry-point wrapper: map classified failure types onto registry
    exit codes so the supervisor reads the cause from the exit status.

    Wraps the ``__main__`` body of every training entry (the three
    pretraining mains, the speculator loop, and the test child). The
    traceback still prints — classification changes the exit code, not
    the post-mortem. Unclassified exceptions propagate untouched
    (interpreter exit 1 == the registry's ``error``).

    Classified failures exit via ``os._exit`` (like every other
    fail-fast site): normal interpreter teardown runs the jax
    distributed service's atexit shutdown barrier, which — with a dead
    peer, exactly the classified case — aborts the process (SIGABRT)
    and would clobber the classified code the supervisor reads."""
    try:
        yield
    except (SystemExit, KeyboardInterrupt):
        raise
    except BaseException as e:  # noqa: BLE001 — classification boundary
        name = classify_exception(e)
        if name is None:
            raise
        traceback.print_exc()
        sys.stderr.write(
            f"exit classified: {name} (exit {EXIT_CODES[name]})\n"
        )
        sys.stderr.flush()
        sys.stdout.flush()
        os._exit(EXIT_CODES[name])
