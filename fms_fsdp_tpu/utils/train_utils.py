"""Host-side training loop, distributed setup, profiler, and trackers.

The loop keeps the reference's observable behavior
(ref:fms_fsdp/utils/train_utils.py:21-180): report cadence and metric
names/semantics (loss, LR, gradient norm, tokens seen, memory,
current/overall tokens-per-chip-per-sec, tokens-per-day), checkpoint
cadence, resume semantics. TPU differences:

- fwd/loss/bwd/clip/update is ONE jitted ``step_fn``; metric scalars stay
  on device and are fetched only at report time, so the host never forces a
  sync inside the hot window (XLA dispatch stays ahead of the device);
- no explicit all_reduce of stats: loss/gnorm come out of the step already
  globally reduced (jit over global arrays);
- memory stats come from ``device.memory_stats()`` instead of CUDA.
"""

import os
import signal
import time
from contextlib import nullcontext as _nullctx
from dataclasses import asdict

import jax


def setup():
    """Join the multi-host JAX world (NCCL-process-group analog,
    ref:train_utils.py:183-184). Initializes on any multi-host signal:
    an explicit coordinator, a multi-worker TPU pod env, or NUM_PROCESSES.
    No-op on single-host runs (Orbax's multi-process commit protocol is
    only needed — and only engaged — when process_count > 1).

    Also honors ``--xla_force_host_platform_device_count`` from XLA_FLAGS
    via jax.config when running on CPU: site customizations that import
    jax early (TPU plugin registration) can otherwise swallow the flag,
    silently collapsing the virtual test mesh to one device."""
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"xla_force_host_platform_device_count=(\d+)", flags)
    if m and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        try:
            # both updates are required: the env var alone loses to
            # early-imported platform plugins, and the device count only
            # applies to a CPU client created after the config round-trips
            jax.config.update("jax_platforms", "cpu")
            jax.config.update("jax_num_cpu_devices", int(m.group(1)))
        except Exception:
            pass  # backend already initialized; flag may still have applied
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    multihost = (
        os.environ.get("COORDINATOR_ADDRESS")
        or int(os.environ.get("NUM_PROCESSES", "1")) > 1
        or len([h for h in hostnames.split(",") if h.strip()]) > 1
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
        # Slurm launch (scripts/train.slurm): jax.distributed auto-detects
        # the coordinator/process-index from the Slurm env. SLURM_PROCID
        # gates on actually being inside an srun step — a bare `python`
        # inside a multi-task allocation inherits SLURM_NTASKS but is a
        # single process and must stay single-host.
        or (
            "SLURM_PROCID" in os.environ
            and int(os.environ.get("SLURM_NTASKS", "1")) > 1
        )
    )
    if multihost:
        coord = os.environ.get("COORDINATOR_ADDRESS")
        if coord and "NUM_PROCESSES" in os.environ:
            # explicit env-driven init (torch env:// analog: MASTER_ADDR/
            # WORLD_SIZE/RANK -> COORDINATOR_ADDRESS/NUM_PROCESSES/
            # PROCESS_ID). jax's argless auto-detect only covers managed
            # launchers (Slurm/OMPI/TPU pods/K8s) — a hand-launched or
            # custom-orchestrated world must pass the triple explicitly.
            if os.environ.get("JAX_PLATFORMS", "") == "cpu":
                # cross-process collectives on CPU need a real backend;
                # gloo is the XLA:CPU implementation (tested by
                # tests/test_multiprocess.py on a 2-process world)
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(os.environ["NUM_PROCESSES"]),
                process_id=int(os.environ["PROCESS_ID"]),
            )
        else:
            jax.distributed.initialize()
    # slice-aware init (docs/train_details.md "Multi-slice"): surface
    # the detected fault domain once the world is up — slice index/count
    # come from device attributes on real multislice hardware, the
    # MEGASCALE env on older stacks, or the FMS_SIM_SLICES gloo
    # simulation knob in tests (parallel/mesh.py). Purely informational
    # here; the mesh builder and train loop re-derive the same facts.
    try:
        from fms_fsdp_tpu.parallel.mesh import process_slice_context

        n_slices, slice_idx = process_slice_context()
        if n_slices > 1:
            print(
                f"--> multi-slice world: slice {slice_idx} of {n_slices} "
                f"(process {jax.process_index()} of {jax.process_count()})"
            )
    except Exception:  # noqa: BLE001 — a detection hiccup must not block init
        pass


def setup_environ_flags():
    """Fail-loudly flags (ref:train_utils.py:187-189 analog)."""
    os.environ.setdefault("JAX_TRACEBACK_FILTERING", "off")


class DeliberateAbort(RuntimeError):
    """An abort the train loop raised ON PURPOSE (anomaly guard).

    The multi-slice exception classifier must not hold these for a
    liveness verdict: a whole-world deliberate abort would otherwise
    wait out slice_timeout_s on every rank and — with the other slice's
    processes already gone — be re-reported as a lost slice, sending the
    operator to a fault-domain restart for what is really a data/NaN
    problem. (Transport errors from a genuinely dead slice arrive as
    XlaRuntimeError/etc., never as this type.)"""


def get_tracker(cfg, rank: int):
    """Optional wandb/aim tracker (ref:train_utils.py:34-73). Returns a
    log_fn(dict, step) or None."""
    if not cfg.tracker:
        return None
    if cfg.tracker not in ["wandb", "aim"]:
        raise ValueError(f"tracker {cfg.tracker} not supported.")
    if rank != 0:
        return None
    if cfg.tracker == "wandb":
        try:
            import wandb
        except ImportError:
            raise ImportError("tracker is set to wandb but wandb is not installed.")
        print("--> wandb is enabled!")
        wandb.init(
            project=cfg.tracker_project_name,
            dir=cfg.tracker_dir,
            resume="allow",
            id=cfg.tracker_run_id,
        )
        wandb.config = asdict(cfg)
        return wandb.log
    try:
        from aim import Run
    except ImportError:
        raise ImportError("tracker is set to aim but aim is not installed.")
    print("--> aim is enabled!")
    run = Run(
        experiment=cfg.tracker_project_name,
        repo=cfg.tracker_dir,
        run_hash=cfg.tracker_run_id,
    )
    run["hparams"] = asdict(cfg)
    return run.track


class WindowedProfiler:
    """jax.profiler trace with the reference's windowing — skip ``wait``
    steps, ``warmup`` more, capture ``active`` steps, once
    (ref:train_utils.py:256-271: wait=1, warmup=2, active=3, repeat=1),
    writing a TensorBoard-compatible XPlane trace to ``logdir``."""

    def __init__(self, logdir="profile_traces", wait=1, warmup=2, active=3):
        self.logdir = logdir
        self.start_at = wait + warmup
        self.stop_at = wait + warmup + active
        self.count = 0
        self._running = False

    def step(self):
        self.count += 1
        if self.count == self.start_at and not self._running:
            jax.profiler.start_trace(self.logdir)
            self._running = True
        elif self.count == self.stop_at and self._running:
            jax.profiler.stop_trace()
            self._running = False

    def close(self):
        """Finalize a trace left open by an early loop exit — an unflushed
        XPlane buffer writes no usable profile."""
        if self._running:
            jax.profiler.stop_trace()
            self._running = False


def get_profiler(cfg, rank: int):
    if not cfg.use_profiler:
        return None
    if cfg.profiler_rank0_only and rank != 0:
        return None
    return WindowedProfiler()


def _memory_stats():
    stats = jax.local_devices()[0].memory_stats() or {}
    return stats.get("peak_bytes_in_use", 0), stats.get("bytes_in_use", 0)


class PreemptionGuard:
    """SIGTERM -> checkpoint at the next step boundary, then exit clean.

    TPU capacity is commonly preemptible (spot/queued resources send
    SIGTERM with a grace window before teardown); the reference's story
    is restart-based resume from the last *interval* checkpoint, which
    loses up to checkpoint_interval steps. The guard converts the grace
    window into an up-to-date checkpoint.

    Multi-host note: the Orbax save is collective, so every process must
    enter it at the same step. ``poll()`` makes the trigger itself
    collective: each boundary, every rank contributes its local flag to a
    tiny jitted global max over all devices, and the boundary's decision
    reads the collective result dispatched one boundary earlier — so a
    rank that never received SIGTERM (delivery straddling a boundary, or
    a scheduler that signals only one rank) still saves at the same step
    as the rank that did. The one-boundary pipeline delay keeps the fetch
    non-blocking in steady state (the collective finished during the
    step) at the cost of saving one step after the signal — well inside
    any real grace window. Single-process worlds skip the collective
    entirely and see the flag at the boundary it arrived.
    """

    def __init__(self):
        self.triggered = False
        self._prev = None
        self._dispatch = None
        self._inflight = None

    def install(self):
        def handler(signum, frame):
            self.triggered = True
            if self._prev not in (None, signal.SIG_DFL, signal.SIG_IGN):
                self._prev(signum, frame)

        try:
            self._prev = signal.signal(signal.SIGTERM, handler)
        except ValueError:
            pass  # not the main thread (tests, embedded use): no-op
        return self

    def _make_dispatch(self):
        import numpy as np
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(np.array(jax.devices()), ("all",))
        sharding = NamedSharding(mesh, PartitionSpec("all"))
        n_local = len(jax.local_devices())
        _max = jax.jit(jnp.max)

        def dispatch(flag: bool):
            local = np.full((n_local,), 1 if flag else 0, dtype=np.int32)
            garr = jax.make_array_from_process_local_data(sharding, local)
            return _max(garr)

        return dispatch

    def poll(self) -> bool:
        """Call exactly once per step boundary on every rank. Returns the
        globally-agreed flag (identical on all ranks at the same step)."""
        if jax.process_count() == 1:
            return self.triggered
        if self._dispatch is None:
            self._dispatch = self._make_dispatch()
        agreed = bool(self._inflight) if self._inflight is not None else False
        self._inflight = self._dispatch(self.triggered)
        return agreed


def _mix_record(observer, dataloader):
    """Per-corpus data-mix accounting for the report record (obs schema
    v7 ``data_mix``): drains the SamplingDataset's buffered lifecycle
    events into the registry (data.corpus_quarantined / corpus_rearmed
    counters) and reads realized-vs-target token shares from the live
    loader. None when the run carries no mixing layer (dummy data,
    process-mode workers)."""
    from fms_fsdp_tpu.data.loader import loader_mix_stats
    from fms_fsdp_tpu.data.streaming import drain_mix_events

    for name, n in drain_mix_events().items():
        if n:
            observer.registry.counter(f"data.{name}").add(n)
    mix = loader_mix_stats(dataloader) if dataloader is not None else None
    if mix is None:
        return None
    total = sum(mix["tokens"].values())
    record = {}
    for corpus, tokens in mix["tokens"].items():
        observer.registry.gauge(f"data.mix.{corpus}.tokens_seen").set(tokens)
        record[f"{corpus}.tokens_seen"] = tokens
        record[f"{corpus}.target_share"] = round(
            mix["weights"].get(corpus, 0.0), 6
        )
        record[f"{corpus}.realized_share"] = (
            round(tokens / total, 6) if total else 0.0
        )
        record[f"{corpus}.quarantined"] = (
            1 if corpus in mix["quarantined"] else 0
        )
    return record


def train(
    cfg,
    state,
    step_fn,
    rank,
    train_loader,
    profiler,
    checkpointer,
    start_step,
    tokens_seen,
    dataloader=None,
    model_cfg=None,
    observer=None,
):
    """Run the hot loop to cfg.num_steps. Returns the final reported loss.

    ``dataloader`` is the stateful loader behind ``train_loader`` (which
    is typically a rebatch/DeviceFeed iterator over it): when provided,
    interval/final/preemption checkpoints persist the live loader state
    into the same ``step_N_ckp`` dir as the model, so a resume continues
    the data stream instead of relying on the loader's own auto-save
    clock (which can drift from trainer steps).

    ``observer`` (obs/) carries the metrics registry, phase timing, and
    sinks; built here from ``cfg`` (and ``model_cfg``, for the MFU FLOPs
    model) when the entry point didn't pass one. The legacy wandb/aim
    tracker attaches to it as one sink among several."""
    tracker_fn = get_tracker(cfg, rank)
    from fms_fsdp_tpu.obs import build_observer
    from fms_fsdp_tpu.obs.sinks import TrackerSink

    if observer is None:
        observer = build_observer(
            cfg, rank, model_cfg=model_cfg, tracker_fn=tracker_fn
        )
    elif tracker_fn is not None:
        observer.sinks.append(TrackerSink(tracker_fn))

    world_size = (
        jax.device_count()
        // max(1, getattr(cfg, "tensor_parallel_size", 1))
        // max(1, getattr(cfg, "context_parallel_size", 1))
    )

    try:
        train_loss = _train_loop(
            cfg,
            state,
            step_fn,
            rank,
            train_loader,
            profiler,
            checkpointer,
            start_step,
            tokens_seen,
            observer,
            world_size,
            dataloader,
        )
    finally:
        if profiler:
            profiler.close()
        try:
            # mandatory on loop exit/preemption (ckpt/manager.py):
            # joins the in-flight background writer so the final save
            # is never torn by process exit, and surfaces any writer
            # error the loop hadn't hit yet (no-op on the synchronous
            # Checkpointer)
            checkpointer.finalize()
        finally:
            observer.close()
    return train_loss


def _train_loop(
    cfg,
    state,
    step_fn,
    rank,
    train_loader,
    profiler,
    checkpointer,
    start_step,
    tokens_seen,
    observer,
    world_size,
    dataloader=None,
):
    from fms_fsdp_tpu.parallel.mesh import process_slice_context
    from fms_fsdp_tpu.resilience import divergence as _divergence
    from fms_fsdp_tpu.resilience import scrub as _scrub
    from fms_fsdp_tpu.resilience.divergence import StateDivergenceError
    from fms_fsdp_tpu.resilience.faults import fire_fault
    from fms_fsdp_tpu.resilience.guards import AnomalyGuard, StepWatchdog
    from fms_fsdp_tpu.resilience.integrity import drain_integrity_events
    from fms_fsdp_tpu.resilience.slices import (
        SliceHealthMonitor,
        SliceLostError,
    )
    from fms_fsdp_tpu.train.step import wrap_step_fn

    window = []
    train_loss = -1.0
    g_norm = -1.0
    start = time.time()
    loop_start = time.time()
    batch_idx = start_step
    preemption = PreemptionGuard().install()
    guard = AnomalyGuard(
        max_consecutive=max(1, getattr(cfg, "anomaly_max_consecutive", 8))
    )
    # multi-slice fault domains (docs/resilience.md): slice context for
    # guard tagging + the slice health monitor; (1, 0) on single-slice
    # worlds, where every slice-aware path below is inert
    n_slices, slice_idx = process_slice_context(cfg)
    slice_tag = f"[proc {rank} slice {slice_idx}] " if n_slices > 1 else ""
    watchdog = None
    timeout_s = float(getattr(cfg, "step_timeout_s", 0.0) or 0.0)
    if timeout_s > 0:
        hb = observer.heartbeat.path if observer.heartbeat else None
        # rank (== jax.process_index() in the entries) is passed in so a
        # multi-host stall report names its host without the wedged
        # process having to touch jax from the watchdog thread; the
        # slice index rides along on multi-slice worlds so stall triage
        # names the fault domain directly
        watchdog = StepWatchdog(
            timeout_s,
            heartbeat_path=hb,
            process_index=rank,
            slice_index=slice_idx if n_slices > 1 else None,
        ).start()
    monitor = None
    if n_slices > 1:
        hb_dir = str(getattr(cfg, "slice_heartbeat_dir", "") or "")
        if not hb_dir and getattr(cfg, "obs_dir", ""):
            hb_dir = os.path.join(cfg.obs_dir, "slice_health")
        slice_timeout = float(getattr(cfg, "slice_timeout_s", 0.0) or 0.0)
        if hb_dir and slice_timeout > 0:
            monitor = SliceHealthMonitor(
                hb_dir, n_slices, slice_idx, rank, slice_timeout
            ).start()

    # phase instrumentation: data_wait at the loop's next(), compute at
    # step dispatch + the report-time fetch, checkpoint inside save()
    train_loader = observer.wrap_data_iter(train_loader)
    step_fn = wrap_step_fn(step_fn, observer.timer)
    checkpointer.observer = observer

    # state-integrity layer (docs/checkpointing.md "State integrity"):
    # the background scrubber re-verifies committed checkpoints across
    # all tiers at scrub_interval_steps cadence (rank 0 — sidecars on
    # shared storage need a single writer), and the cross-replica
    # divergence compare runs at report boundaries every
    # divergence_check_interval steps on multi-process worlds
    scrubber = None
    scrub_interval = int(getattr(cfg, "scrub_interval_steps", 0) or 0)
    if scrub_interval > 0 and rank == 0:
        roots = _scrub.scrub_roots(checkpointer)
        if roots:
            scrubber = _scrub.CheckpointScrubber(roots, scrub_interval)
    divergence_interval = int(
        getattr(cfg, "divergence_check_interval", 0) or 0
    )
    if jax.process_count() == 1:
        divergence_interval = 0  # nothing to compare against
    last_divergence_check = start_step

    def _integrity_stats():
        # drained at report cadence on the main thread: the scrubber
        # thread and every verify buffered into integrity's event
        # window; detections become registry counters so they land in
        # this record's extras (obs schema v8)
        ev = drain_integrity_events()
        if ev.get("shard_corrupt_detected"):
            observer.registry.counter(
                "integrity.shard_corrupt_detected"
            ).add(int(ev["shard_corrupt_detected"]))
        return {
            "verify_s": float(ev.get("verify_s", 0.0)),
            "scrub_verified": _scrub.total_verified(),
            "divergence_checks": _divergence.total_checks(),
        }

    observer.attach_integrity_stats(_integrity_stats)

    def global_tokens(step):
        """Tokens seen through ``step``, exact at any step — checkpoint
        metadata must not reuse the last report's stale figure when a
        preemption/final save lands mid-report-window."""
        return tokens_seen + (
            (step - start_step) * world_size * cfg.batch_size * cfg.seq_length
        )

    def flush_window(step, drain=False):
        """Fetch + report the pending metric window (no-op when empty).

        Called at every report boundary AND (``drain=True``) when the
        loop exits mid-window (preemption, final step, exhausted
        loader): the tail steps' non-finite flags must reach
        ``guard.observe`` — otherwise the final record under-counts
        skipped_steps_total and a bad streak spanning the exit is
        invisible — and the tail's metrics must land in one last record
        before the final save stamps the guard's totals into checkpoint
        metadata. Boundary prints keep the reference's fixed
        report_interval divisor (ref parity, even for a resume's partial
        first window); drain windows are new output with no reference
        counterpart, so their printed rates use the true step count —
        the exit lines an operator reads must not inflate throughput by
        report_interval/len(window)."""
        nonlocal window, start, train_loss, g_norm
        if not window:
            return
        # one host sync per report interval. This device_get is where a
        # stuck collective actually manifests (the loop only
        # dispatches), so the watchdog timeout must cover a FULL report
        # window of steps — see the step_timeout_s sizing note in
        # config/training.py.
        with observer.phase("compute"):
            fetched = jax.device_get(window)
        if watchdog:
            watchdog.beat()
        window = []
        # anomaly accounting: per-step non-finite flags in step order
        # (updates for flagged steps were already skipped on device);
        # report means over the clean steps only so one NaN doesn't
        # poison the whole window's loss
        flags = [float(m.pop("nonfinite", 0.0)) for m in fetched]
        window_skips = guard.observe(flags)
        good = [m for m, f in zip(fetched, flags) if not f]
        # a fully-poisoned window (every step non-finite) has no finite
        # loss to state: carry the last clean loss/gnorm instead of
        # averaging NaN into the print stream, and mark the record
        # (loss=null in sinks, window_poisoned in extra) — skipped_
        # steps_window == steps tells the story
        poisoned = not good
        if not poisoned:
            train_loss = float(sum(m["loss"] for m in good) / len(good))
            g_norm = float(sum(m["gnorm"] for m in good) / len(good))
        current_lr = float(fetched[-1]["lr"])
        # any extra model-family metrics (e.g. MoE moe_drop_frac)
        extra_metrics = (
            {}
            if poisoned
            else {
                k: float(sum(m[k] for m in good) / len(good))
                for k in good[-1]
                if k not in ("loss", "gnorm", "lr")
            }
        )
        elapsed_time = time.time() - loop_start
        new_tokens_seen = (
            (step - start_step) * world_size * cfg.batch_size * cfg.seq_length
        )
        total_tokens_seen = tokens_seen + new_tokens_seen
        window_wall = time.time() - start
        current_step_time = window_wall / (
            len(fetched) if drain else cfg.report_interval
        )
        overall_step_time = elapsed_time / max(1, step - start_step)
        current_throughput = int(
            cfg.batch_size * cfg.seq_length / current_step_time
        )
        overall_throughput = int(
            cfg.batch_size * cfg.seq_length / overall_step_time
        )
        reserved_mem, allocated_mem = _memory_stats()
        if rank == 0:
            if poisoned:
                print(
                    f"report window poisoned: all {len(fetched)} step(s) "
                    f"non-finite; carrying last clean loss"
                )
            print("step:", step)
            print("loss:", train_loss)
            print("LR:", current_lr)
            print("tokens seen:", total_tokens_seen)
            print("gradient norm:", g_norm)
            print("reserved memory:", reserved_mem)
            print("allocated memory:", allocated_mem)
            print("current step time:", current_step_time)
            print("overall step time:", overall_step_time)
            print("current token per chip per sec:", current_throughput)
            print("overall token per chip per sec:", overall_throughput)
            print(
                "overall token per day:",
                int(new_tokens_seen / elapsed_time * 3600 * 24),
            )
            if guard.skipped_batches:
                print("skipped batches:", guard.skipped_batches)
            for k, v in extra_metrics.items():
                print(f"{k}:", v)
        # structured record: every sink (JSONL/CSV file sinks, the
        # legacy wandb/aim tracker adapter), goodput/MFU derivation, and
        # the heartbeat hang off this one call; non-zero ranks run it
        # too (no sinks — it closes their phase window so timing stays
        # rank-consistent). Rates are derived from the window's TRUE
        # step count (a resume's first window and an exit-drain window
        # are partial — len(fetched) < report_interval — and the printed
        # per-interval numbers inherit the reference's fixed divisor) so
        # the persistent record never inflates throughput/MFU.
        window_steps = max(1, len(fetched))
        obs_step_time = max(1e-9, window_wall) / window_steps
        record_extra = dict(extra_metrics)
        if poisoned:
            record_extra["window_poisoned"] = 1
        data_mix = _mix_record(observer, dataloader)
        observer.report(
            step,
            len(fetched),
            loss=float("nan") if poisoned else train_loss,
            grad_norm=float("nan") if poisoned else g_norm,
            learning_rate=current_lr,
            tokens_seen=total_tokens_seen,
            tokens_per_sec_per_chip=(
                cfg.batch_size * cfg.seq_length / obs_step_time
            ),
            tokens_per_sec_per_chip_overall=overall_throughput,
            step_time_s=obs_step_time,
            skipped_steps_total=guard.skipped_batches,
            skipped_steps_window=window_skips,
            memory_reserved_bytes=reserved_mem,
            memory_allocated_bytes=allocated_mem,
            data_mix=data_mix,
            extra=record_extra,
        )
        start = time.time()

    try:
        for batch_idx, batch in enumerate(train_loader, start=start_step + 1):
            if batch_idx > cfg.num_steps:
                batch_idx -= 1  # this batch was never trained on
                break
            if watchdog:
                watchdog.beat()
            if monitor:
                monitor.beat(batch_idx)
            # slice-scoped fault sites (resilience/faults.py): kill every
            # process of one fault domain at the step boundary, or park a
            # rank in a wedged cross-slice reduce — the failures the
            # SliceHealthMonitor must detect/classify
            kill = fire_fault("slice_kill", step=batch_idx, slice=slice_idx)
            if kill is not None:
                from fms_fsdp_tpu.resilience.exits import EXIT_CODES

                os._exit(int(kill.get("code", EXIT_CODES["injected_kill"])))
            stall = fire_fault(
                "dcn_reduce_stall", step=batch_idx, slice=slice_idx
            )
            if stall is not None:
                time.sleep(float(stall.get("seconds", 3600)))
            sdc = fire_fault("sdc_grad_flip", step=batch_idx, proc=rank)
            if sdc is not None:
                # injected silent data corruption: perturb THIS
                # process's replica of one param leaf, host-side (zero
                # compiled-program changes — see divergence.inject_sdc).
                # Nothing here reports it: the cross-replica fingerprint
                # compare at the next report boundary must DISCOVER it.
                state, leaf_key = _divergence.inject_sdc(
                    state, float(sdc.get("scale", 1.5))
                )
                print(
                    f"sdc_grad_flip fault: scaled local shards of "
                    f"{leaf_key} by {float(sdc.get('scale', 1.5))} on "
                    f"proc {rank} at step {batch_idx}"
                )
            state, metrics = step_fn(state, batch)
            window.append(metrics)

            if profiler:
                profiler.step()

            if batch_idx % cfg.report_interval == 0:
                if _divergence.divergence_due(
                    batch_idx, last_divergence_check, divergence_interval
                ):
                    # cross-replica fingerprint compare (one tiny
                    # allgather, every rank at the same boundary),
                    # BEFORE the window flush: loss/gnorm are the LAST
                    # flushed window's post-reduce scalars — replicated
                    # values that must be bit-identical on every
                    # process — and the whole-state checksum proves the
                    # dcn-replicated LIVE state still agrees.
                    # Disagreement raises StateDivergenceError ->
                    # classified state_divergence exit; the supervisor
                    # relaunches under the verified-resume rule. No
                    # checkpoint is saved on this path: the live state
                    # is suspect.
                    last_divergence_check = batch_idx
                    try:
                        _divergence.check_divergence(
                            state,
                            train_loss,
                            g_norm,
                            batch_idx,
                            cfg,
                            observer.registry,
                        )
                    except StateDivergenceError:
                        # the pending window (and with it the
                        # integrity.divergence_detected counter the
                        # check just bumped) must reach one final
                        # record before the classified abort — the
                        # exit path never reports again
                        flush_window(batch_idx, drain=True)
                        raise
                flush_window(batch_idx)

                if scrubber is not None:
                    # cadence check only; the sweep itself runs on a
                    # daemon thread and self-throttles to one in flight
                    scrubber.maybe_scrub(batch_idx)

                if guard.should_abort():
                    # a poisoned data region or true divergence: skipping
                    # forever would silently train on nothing. Save a
                    # final checkpoint (params are the last good ones —
                    # flagged updates never landed) and abort loudly.
                    with watchdog.paused() if watchdog else _nullctx():
                        checkpointer.save(
                            batch_idx,
                            state,
                            dataloader,
                            reason="abort",
                            tokens_seen=global_tokens(batch_idx),
                            skipped_steps=guard.skipped_batches,
                        )
                    raise DeliberateAbort(
                        f"{slice_tag}anomaly guard: {guard.consecutive} "
                        f"consecutive non-finite steps (threshold "
                        f"{guard.max_consecutive}); checkpoint saved at "
                        f"step {batch_idx}, aborting"
                    )

            preempt_now = preemption.poll()
            # tier-aware cadence when the checkpointer is the async
            # manager (a fast local tier can be due between durable
            # intervals); plain Checkpointer keeps the single interval
            interval_due = (
                checkpointer.save_due(batch_idx)
                if hasattr(checkpointer, "save_due")
                else batch_idx % cfg.checkpoint_interval == 0
            )
            if interval_due or batch_idx == cfg.num_steps or preempt_now:
                reason = (
                    "preempt"
                    if preempt_now
                    else ("final" if batch_idx == cfg.num_steps else "interval")
                )
                if reason != "interval":
                    # the loop is about to exit: drain the pending
                    # window first so the guard's totals (stamped into
                    # the save's metadata below) and the final record
                    # cover the tail steps
                    flush_window(batch_idx, drain=True)
                # the watchdog deadline is sized for step windows; a
                # healthy multi-minute Orbax save must not trip it, so
                # the watchdog is suspended (and re-armed) around it.
                # (Async saves only block for the snapshot here; the
                # storage write runs on the background writer.)
                with watchdog.paused() if watchdog else _nullctx():
                    checkpointer.save(
                        batch_idx,
                        state,
                        dataloader,
                        reason=reason,
                        tokens_seen=global_tokens(batch_idx),
                        skipped_steps=guard.skipped_batches,
                    )
            if preempt_now:
                if rank == 0:
                    print(
                        f"preemption signal received: checkpoint saved at "
                        f"step {batch_idx}, exiting clean"
                    )
                break

        # exhausted loader (finite stream) or num_steps overrun: drain
        # whatever the last report window left pending (no-op when the
        # exit landed on a report/save boundary)
        flush_window(batch_idx, drain=True)
        if guard.should_abort() and rank == 0:
            print(
                f"WARNING: {slice_tag}run exited with {guard.consecutive} "
                f"consecutive non-finite steps still streaking"
            )
    except Exception as e:
        # DCN-collective timeout classifier (resilience/slices.py): a
        # dead slice can surface on the survivors as a transport ERROR
        # from the cross-slice collective rather than a hang. Hold the
        # exception until the liveness verdict is in, and re-raise it
        # classified — "slice K lost, restart at world minus one fault
        # domain" — instead of the raw transport traceback. Unrelated
        # failures (no slice went silent) re-raise untouched, and the
        # loop's own deliberate aborts skip the wait entirely (a
        # whole-world abort must not be re-badged as a slice loss) —
        # as does a divergence detection, which every rank raises from
        # the same collective compare (a whole-world classified abort,
        # not a dead fault domain).
        if monitor is not None and not isinstance(
            e, (DeliberateAbort, StateDivergenceError)
        ):
            dead = monitor.wait_classify()
            if dead is not None:
                # typed (resilience/slices.py) so the entry points'
                # classified-exit wrapper exits with the slice_loss
                # registry code — the same code the monitor thread's
                # direct os._exit path uses
                raise SliceLostError(monitor.describe_loss(dead)) from e
        raise
    finally:
        if watchdog:
            watchdog.stop()
        if monitor:
            monitor.stop()
        if scrubber is not None:
            scrubber.stop()

    return train_loss
