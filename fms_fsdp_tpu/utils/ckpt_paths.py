"""Checkpoint-directory path helpers (ref:fms_fsdp/utils/checkpointing_utils.py:23-64).

Shared by the model Checkpointer and the dataloader's auto-checkpoint layer.
"""

import os


def safe_listdir(path) -> list:
    """listdir that treats a concurrently-deleted (or not-a-dir) entry as
    empty. Checkpoint-folder scanners enumerate candidate step dirs and
    then inspect each; rank-0 retention pruning can rmtree a candidate
    between those two steps, and the scanner must skip it, not crash."""
    try:
        return os.listdir(path)
    except (FileNotFoundError, NotADirectoryError):
        return []


def is_step_ckp(path) -> bool:
    """True for the step_<N>_ckp names Checkpointer.save writes. The
    middle must be numeric: a parked 'step_best_ckp' must be ignored by
    every scanner, not crash its step_number sort."""
    name = os.path.basename(str(path))
    return (
        name.startswith("step_")
        and name.endswith("_ckp")
        and name.split("_")[1].isdigit()
    )


def step_number(path) -> int:
    """Parse N out of .../step_<N>_ckp."""
    return int(os.path.basename(str(path)).split("_")[1])


def get_latest(targdir, qualifier=lambda x: True, key=os.path.getctime):
    """Full path of the newest qualifying entry in targdir, or None."""
    if os.path.exists(targdir) and len(os.listdir(targdir)) > 0:
        candidates = [
            os.path.join(targdir, x)
            for x in os.listdir(targdir)
            if qualifier(os.path.join(targdir, x))
        ]
        if candidates:
            return max(candidates, key=key)
    return None


def get_oldest(targdir, qualifier=lambda x: True, key=os.path.getctime):
    """Full path of the oldest qualifying entry in targdir, or None."""
    if os.path.exists(targdir) and len(os.listdir(targdir)) > 0:
        candidates = [
            os.path.join(targdir, x)
            for x in os.listdir(targdir)
            if qualifier(os.path.join(targdir, x))
        ]
        if candidates:
            return min(candidates, key=key)
    return None
