"""Model FLOPs accounting for MFU/HFU reporting.

The reference publishes MFU/HFU per the PaLM appendix-B convention
(ref:README.md:22-30). Same convention here:

- matmul params contribute 2 FLOPs/param/token forward (embedding gather
  contributes none; the lm_head matmul counts);
- causal attention contributes 2 * S * d_attn FLOPs/token/layer forward
  (QK^T and PV, halved for causality);
- backward = 2x forward; train = 3x forward;
- HFU additionally counts recomputed forward FLOPs for remat'ed blocks.
"""

from fms_fsdp_tpu.models.configs import LlamaConfig, MixtralConfig


def llama_matmul_params(cfg: LlamaConfig) -> int:
    """Params participating in matmuls (everything but the embedding table)."""
    return cfg.n_params(include_embeddings=False) + cfg.src_vocab_size * cfg.emb_dim


def llama_fwd_flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    mm = 2 * llama_matmul_params(cfg)
    attn_dim = cfg.nheads * cfg.head_dim
    attn = cfg.nlayers * 2 * seq_len * attn_dim  # causal: S/2 keys avg, x4
    return mm + attn


def llama_train_flops_per_token(
    cfg: LlamaConfig, seq_len: int, ac_fraction: float = 0.0
) -> float:
    """Model FLOPs (MFU numerator) per token for fwd+bwd.

    ``ac_fraction`` > 0 gives the HFU numerator: remat'ed blocks replay
    their forward in the backward pass.
    """
    fwd = llama_fwd_flops_per_token(cfg, seq_len)
    return fwd * (3 + ac_fraction)


def mamba_matmul_params(cfg) -> int:
    """Matmul-participating params of the hybrid Mamba2 stack (everything
    but the embedding gather; lm_head counts). Mirrors
    models/mamba.py:init_mamba_params layer shapes."""
    d = cfg.d_model
    ipd = 2 * cfg.d_inner + 2 * cfg.ngroups * cfg.d_state + cfg.nheads
    a = cfg.attn_cfg
    total = d * cfg.padded_vocab_size  # lm_head
    for i in range(cfg.n_layer):
        if i in cfg.attn_layer_idx:
            total += d * (a.num_heads + 2 * a.num_heads_kv) * a.head_dim
            total += a.num_heads * a.head_dim * d
        else:
            total += d * ipd + cfg.d_inner * d
        if cfg.d_intermediate > 0:
            total += 3 * d * cfg.d_intermediate
    return total


def mamba_fwd_flops_per_token(cfg, seq_len: int) -> float:
    """Forward FLOPs/token: matmuls + the chunked SSD scan + conv1d +
    the hybrid attention layers (causal convention as in the Llama
    accounting)."""
    mm = 2 * mamba_matmul_params(cfg)
    L = min(cfg.chunk_size, seq_len)  # ssd_scan clamps the chunk the same way
    G, N = cfg.ngroups, cfg.d_state
    H, P = cfg.nheads, cfg.headdim
    n_mamba = cfg.n_layer - len(cfg.attn_layer_idx)
    # per token per mamba layer: CB (2*L*G*N), intra y (2*L*H*P),
    # states + inter-chunk output (4*N*H*P each pair)
    scan = n_mamba * (2 * L * G * N + 2 * L * H * P + 4 * N * H * P)
    conv = n_mamba * 2 * (cfg.d_inner + 2 * G * N) * cfg.d_conv
    a = cfg.attn_cfg
    attn = len(cfg.attn_layer_idx) * 2 * seq_len * a.num_heads * a.head_dim
    return mm + scan + conv + attn


def mamba_train_flops_per_token(cfg, seq_len: int, ac_fraction: float = 0.0):
    return mamba_fwd_flops_per_token(cfg, seq_len) * (3 + ac_fraction)


def mixtral_matmul_params_active(cfg) -> int:
    """Matmul params a token actually touches: dense attention + router +
    the ``top_k`` activated expert FFNs + lm_head. The standard MoE MFU
    convention counts activated FLOPs only — capacity slack
    (capacity_factor > top_k buffer fill) and dispatch movement are real
    work that does NOT count toward the numerator."""
    d, h = cfg.emb_dim, cfg.hidden_dim
    attn_dim = cfg.nheads * cfg.head_dim
    kv_dim = cfg.n_kv_heads * cfg.head_dim
    per_layer = (
        d * attn_dim  # wq
        + 2 * d * kv_dim  # wk, wv
        + attn_dim * d  # wo
        + d * cfg.num_experts  # router gate
        + cfg.top_k * 3 * d * h  # activated expert SwiGLU
    )
    return cfg.nlayers * per_layer + cfg.src_vocab_size * d  # + lm_head


def mixtral_fwd_flops_per_token(cfg, seq_len: int) -> float:
    mm = 2 * mixtral_matmul_params_active(cfg)
    attn = cfg.nlayers * 2 * seq_len * cfg.nheads * cfg.head_dim
    return mm + attn


def mixtral_train_flops_per_token(cfg, seq_len: int, ac_fraction: float = 0.0):
    return mixtral_fwd_flops_per_token(cfg, seq_len) * (3 + ac_fraction)


def train_flops_per_token(model_cfg, seq_len: int, ac_fraction: float = 0.0):
    """Family dispatch for MFU/HFU accounting."""
    if isinstance(model_cfg, LlamaConfig):
        return llama_train_flops_per_token(model_cfg, seq_len, ac_fraction)
    if isinstance(model_cfg, MixtralConfig):
        return mixtral_train_flops_per_token(model_cfg, seq_len, ac_fraction)
    return mamba_train_flops_per_token(model_cfg, seq_len, ac_fraction)


# Peak dense bf16 TFLOP/s per chip.
TPU_PEAK_FLOPS = {
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
}


def peak_flops_per_chip(kind_hint: str = "") -> float:
    import os

    hint = (kind_hint or os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")).lower()
    for k, v in TPU_PEAK_FLOPS.items():
        if k in hint:
            return v
    return TPU_PEAK_FLOPS["v5e"]
