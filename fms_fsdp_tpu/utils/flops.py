"""Model FLOPs accounting for MFU/HFU reporting.

The reference publishes MFU/HFU per the PaLM appendix-B convention
(ref:README.md:22-30). Same convention here:

- matmul params contribute 2 FLOPs/param/token forward (embedding gather
  contributes none; the lm_head matmul counts);
- causal attention contributes 2 * S * d_attn FLOPs/token/layer forward
  (QK^T and PV, halved for causality);
- backward = 2x forward; train = 3x forward;
- HFU additionally counts recomputed forward FLOPs for remat'ed blocks.
"""

from fms_fsdp_tpu.models.configs import LlamaConfig


def llama_matmul_params(cfg: LlamaConfig) -> int:
    """Params participating in matmuls (everything but the embedding table)."""
    return cfg.n_params(include_embeddings=False) + cfg.src_vocab_size * cfg.emb_dim


def llama_fwd_flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    mm = 2 * llama_matmul_params(cfg)
    attn_dim = cfg.nheads * cfg.head_dim
    attn = cfg.nlayers * 2 * seq_len * attn_dim  # causal: S/2 keys avg, x4
    return mm + attn


def llama_train_flops_per_token(
    cfg: LlamaConfig, seq_len: int, ac_fraction: float = 0.0
) -> float:
    """Model FLOPs (MFU numerator) per token for fwd+bwd.

    ``ac_fraction`` > 0 gives the HFU numerator: remat'ed blocks replay
    their forward in the backward pass.
    """
    fwd = llama_fwd_flops_per_token(cfg, seq_len)
    return fwd * (3 + ac_fraction)


# Peak dense bf16 TFLOP/s per chip.
TPU_PEAK_FLOPS = {
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
}


def peak_flops_per_chip(kind_hint: str = "") -> float:
    import os

    hint = (kind_hint or os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")).lower()
    for k, v in TPU_PEAK_FLOPS.items():
        if k in hint:
            return v
    return TPU_PEAK_FLOPS["v5e"]
