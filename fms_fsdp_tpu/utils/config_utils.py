"""Config override + model-variant registry.

``update_config`` reproduces the reference's kwarg-override semantics
(ref:fms_fsdp/utils/config_utils.py:6-22): set matching attributes, support
dotted ``ClassName.param`` addressing, warn on unknown keys.

``get_model_config`` reproduces the variant table
(ref:fms_fsdp/utils/config_utils.py:25-189) — llama2 {1.4b,7b,13b,34b,70b},
llama3 {194m,1.8b,3.2b,8b,70b} (±4k variants), mamba_9.8b — with identical
architectural hyperparameters, expressed as our native config dataclasses.
"""

import dataclasses

from fms_fsdp_tpu.config import TrainConfig
from fms_fsdp_tpu.models.configs import (
    LlamaConfig,
    MambaAttnConfig,
    MambaConfig,
    MixtralConfig,
)


def _set(config, name, value):
    # Model configs are frozen dataclasses (immutability guards the jit
    # closures); the CLI override path is the one sanctioned mutation site.
    if dataclasses.is_dataclass(config) and config.__dataclass_params__.frozen:
        object.__setattr__(config, name, value)
    else:
        setattr(config, name, value)


def update_config(config, **kwargs):
    if isinstance(config, (tuple, list)):
        for c in config:
            update_config(c, **kwargs)
        return
    for k, v in kwargs.items():
        if hasattr(config, k):
            _set(config, k, v)
        elif "." in k:
            config_name, param_name = k.split(".")
            if type(config).__name__ == config_name:
                if hasattr(config, param_name):
                    _set(config, param_name, v)
                else:
                    print(f"Warning: {config_name} does not accept parameter: {k}")
        elif isinstance(config, TrainConfig):
            print(f"Warning: unknown parameter {k}")


_LLAMA_VARIANTS = {
    "llama2_70b": dict(
        emb_dim=8192,
        multiple_of=4096,
        nheads=64,
        kvheads=8,
        nlayers=80,
        hidden_grow_factor=28672 / 8192,
    ),
    "llama2_34b": dict(
        emb_dim=8192,
        nheads=64,
        kvheads=8,
        nlayers=48,
        hidden_grow_factor=22016 / 8192,
        max_expected_seq_len=16384,
        rope_theta=1000000.0,
    ),
    "llama2_13b": dict(
        emb_dim=5120,
        nheads=40,
        nlayers=40,
        hidden_grow_factor=13824 / 5120,
    ),
    "llama2_7b": dict(
        hidden_grow_factor=11008 / 4096,
        kvheads=32,
    ),
    "llama2_1.4b": dict(
        emb_dim=2048,
        nheads=16,
        nlayers=24,
        hidden_grow_factor=3,
        kvheads=4,
    ),
    "llama3_8b": dict(
        src_vocab_size=128256,
        emb_dim=4096,
        nheads=32,
        kvheads=8,
        nlayers=32,
        hidden_grow_factor=3.5,
        max_expected_seq_len=8192,
        rope_theta=500000.0,
    ),
    "llama3_1.8b": dict(
        src_vocab_size=128256,
        emb_dim=2048,
        nheads=16,
        kvheads=8,
        nlayers=24,
        hidden_grow_factor=3.5,
        max_expected_seq_len=8192,
        rope_theta=500000.0,
    ),
    "llama3_3.2b": dict(
        src_vocab_size=128256,
        emb_dim=3072,
        nheads=24,
        kvheads=8,
        nlayers=24,
        hidden_grow_factor=8 / 3,
        max_expected_seq_len=8192,
        rope_theta=500000.0,
    ),
    "llama3_70b": dict(
        src_vocab_size=128256,
        emb_dim=8192,
        nheads=64,
        kvheads=8,
        nlayers=80,
        hidden_grow_factor=3.5,
        max_expected_seq_len=8192,
        rope_theta=500000.0,
    ),
    "llama3_194m_4k": dict(
        src_vocab_size=128256,
        emb_dim=1024,
        nheads=8,
        nlayers=10,
        max_expected_seq_len=4096,
        rope_theta=500000.0,
    ),
}

# llama3 *_4k variants: same architecture with a 4096 context window
# (ref:fms_fsdp/utils/config_utils.py:76-86,98-108,120-130,142-152).
for _name in ["llama3_8b", "llama3_1.8b", "llama3_3.2b", "llama3_70b"]:
    _LLAMA_VARIANTS[_name + "_4k"] = dict(
        _LLAMA_VARIANTS[_name], max_expected_seq_len=4096
    )


def get_model_config(model_variant):
    if model_variant in _LLAMA_VARIANTS:
        return LlamaConfig(**_LLAMA_VARIANTS[model_variant])
    if model_variant == "mamba_9.8b":
        # ref:fms_fsdp/utils/config_utils.py:162-185
        return MambaConfig(
            d_model=4096,
            d_intermediate=14336,
            n_layer=32,
            vocab_size=128256,
            ssm_layer="Mamba2",
            attn_layer_idx=(9, 18, 27),
            attn_cfg=MambaAttnConfig(
                causal=True,
                d_conv=0,
                head_dim=128,
                num_heads=32,
                num_heads_kv=8,
                out_proj_bias=False,
                qkv_proj_bias=False,
                rotary_emb_dim=64,
            ),
            rms_norm=True,
            residual_in_fp32=True,
            fused_add_norm=True,
            pad_vocab_size_multiple=16,
            tie_embeddings=False,
        )
    if model_variant == "mixtral_8x7b":
        # Mixtral-8x7B (46.7B total / 12.9B active params): beyond-reference
        # trainable MoE family; the reference uses this architecture only as
        # a frozen speculator base via fms
        # (ref:speculator/train_speculator_utils.py:500-569).
        return MixtralConfig(
            src_vocab_size=32000,
            emb_dim=4096,
            nheads=32,
            kvheads=8,
            nlayers=32,
            hidden_dim=14336,
            num_experts=8,
            top_k=2,
            max_expected_seq_len=4096,
            rope_theta=1e6,
        )
    raise ValueError(f"model variant {model_variant} not supported.")
