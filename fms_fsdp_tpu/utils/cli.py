"""Minimal fire-style CLI: ``main_training_llama.py --key=value ...``.

The reference exposes arbitrary config kwargs through ``fire.Fire(main)``
(ref:main_training_llama.py:174-175, scripts/train.sh:24-31). This parser
accepts the same surface — ``--key=value``, ``--key value``, dotted
``ClassName.param=value`` — with literal-eval typing, no dependency.
"""

import ast
from typing import Dict, List, Optional


def _coerce(value: str):
    low = value.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    try:
        return ast.literal_eval(value)
    except (ValueError, SyntaxError):
        return value


def parse_cli_args(argv: List[str]) -> Dict[str, object]:
    """argv (sans program name) -> kwargs dict."""
    kwargs = {}
    key: Optional[str] = None
    for token in argv:
        if token.startswith("--"):
            if key is not None:
                kwargs[key] = True  # bare flag
            body = token[2:]
            if "=" in body:
                k, v = body.split("=", 1)
                kwargs[k] = _coerce(v)
                key = None
            else:
                key = body
        elif key is not None:
            kwargs[key] = _coerce(token)
            key = None
        elif "=" in token:
            k, v = token.split("=", 1)
            kwargs[k] = _coerce(v)
        else:
            raise ValueError(f"Cannot parse CLI token: {token}")
    if key is not None:
        kwargs[key] = True
    return kwargs
