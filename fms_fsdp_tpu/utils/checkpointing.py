"""Model/optimizer checkpointing against XLA-sharded arrays.

TPU-native replacement for the reference ``Checkpointer``
(ref:fms_fsdp/utils/checkpointing_utils.py:65-316), keeping its observable
contract:

- directory layout ``<ckpdir>/checkpoints/step_N_ckp/`` with run metadata
  (step + tokens_seen) alongside, plus the dataloader's per-rank
  ``loader_state_*`` files;
- ``load`` prefers a checkpoint in the save directory (a restarted job
  resumes itself, ref:checkpointing_utils.py:203-206), falling back to the
  provided path (continued pretraining) with step/stat reset;
- single-file checkpoints (ddp/speculator path) hold a bare model param
  tree and reset optimizer/step;
- rolling retention of the newest ``n_to_save`` step checkpoints (ordered
  by the step number in the name).

Sharded tensor IO is Orbax/TensorStore: every process writes only its own
array shards in parallel (the FileSystemWriter single-file-per-rank
analog); on restore, arrays are materialized directly into the target
sharding, so optimizer "resharding" across world sizes — a hard problem
the reference solves with load_sharded_optimizer_state_dict
(ref:checkpointing_utils.py:259-271) — comes free. HSDP write dedup (only
one replica writes, ref:checkpointing_utils.py:137-141) is likewise
automatic: replicated shards have a single primary writer.
"""

import json
import os
import pickle
import shutil
import time
from pathlib import Path

import jax

from fms_fsdp_tpu.utils.ckpt_paths import (
    get_latest,
    get_oldest,
    is_step_ckp,
    safe_listdir,
    step_number,
)


def load_params_only(load_path: str, init_params_fn):
    """Load just the model params from a training checkpoint (converter
    path): a params pickle, a step_N_ckp dir, or a checkpoints/ folder.

    Optimizer moments and counters are skipped at the IO layer (orbax
    placeholder leaves), so conversion reads ~1/3 of the checkpoint bytes
    and never materializes Adam state. ``init_params_fn(key) -> params``
    supplies the target structure.
    """
    import pickle

    import jax.numpy as jnp
    import orbax.checkpoint as ocp

    from fms_fsdp_tpu.config import TrainConfig

    if os.path.isfile(load_path):
        with open(load_path, "rb") as f:
            payload = pickle.load(f)
        return payload.get("model_state", payload)

    # full saved-state structure, with non-param leaves as placeholders
    from fms_fsdp_tpu.train.step import make_optimizer

    optimizer = make_optimizer(TrainConfig())

    def init_fn(k):
        params = init_params_fn(k)
        return {
            "params": params,
            "opt_state": optimizer.init(params),
            "step": jnp.zeros((), jnp.int32),
        }

    shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    placeholder = getattr(ocp, "PLACEHOLDER", None)
    if placeholder is not None:
        target = {
            "params": shapes["params"],
            "opt_state": jax.tree.map(
                lambda _: placeholder, shapes["opt_state"]
            ),
            "step": placeholder,
        }
    else:
        # older orbax has no placeholder leaves: degrade to restoring the
        # full state (3x the IO, Adam moments materialized) rather than
        # failing the conversion outright
        import warnings

        warnings.warn(
            "this orbax version lacks ocp.PLACEHOLDER: load_params_only "
            "falls back to restoring the full train state (reads ~3x the "
            "bytes). Upgrade orbax-checkpoint for params-only IO.",
            stacklevel=2,
        )
        target = shapes
    state_dir = os.path.join(load_path, "state")
    if not os.path.isdir(state_dir):
        # newest step dir holding a COMMITTED model checkpoint
        # (metadata.json is written last, after wait_until_finished — the
        # commit marker _validate_ckp_path keys on): loader-only
        # auto-save dirs and torn mid-save dirs must both be skipped
        latest = get_latest(
            load_path,
            qualifier=lambda p: is_step_ckp(p)
            and os.path.isdir(p)
            and "metadata.json" in safe_listdir(p),
            key=step_number,
        )
        assert latest is not None, f"no checkpoint under {load_path}"
        state_dir = os.path.join(latest, "state")
    restored = ocp.PyTreeCheckpointer().restore(
        state_dir, args=ocp.args.PyTreeRestore(item=target)
    )
    return restored["params"]


def scan_topology(candidates, verify=True):
    """Topology fingerprint stamped into the newest loadable checkpoint
    in ``candidates`` (a newest-first ``_candidate_ckp_paths`` list), or
    None. Single-file checkpoints carry no metadata; a torn
    ``metadata.json`` or (with ``verify``) a manifest-verification
    failure falls through to the next candidate — the same fallback
    chain ``load`` walks, so the batch policy decided from this scan
    matches the checkpoint a restore will actually read (a corrupt
    newest checkpoint with intact metadata must not set a policy the
    restore's fallback then contradicts)."""
    from fms_fsdp_tpu.resilience.scrub import (
        cached_verify,
        verified_resume_active,
    )

    for cand in candidates:
        if os.path.isfile(cand):
            break  # single-file checkpoints carry no metadata
        # verdict-cached verification (resilience/scrub.py): a
        # quarantined dir is skipped outright, a scrub-verified one
        # costs a digest read, and a fresh verify here is memoized so
        # load()'s walk over the same candidates never re-hashes it
        if (verify or verified_resume_active()) and not cached_verify(cand)[0]:
            continue  # load() will reject it and fall back too
        try:
            with open(os.path.join(cand, "metadata.json")) as f:
                return json.load(f).get("topology")
        except (OSError, ValueError):
            continue  # torn metadata: the next candidate may do
    return None


def _merge_trees(target, loaded, strict: bool):
    """Overlay ``loaded`` onto ``target``. strict=True requires identical
    structure; strict=False takes matching keys and keeps target leaves for
    anything missing (torch load_state_dict(strict=False) analog)."""
    if strict:
        return jax.tree.map(lambda _, l: l, target, loaded)
    if isinstance(target, dict) and isinstance(loaded, dict):
        return {
            k: _merge_trees(v, loaded[k], strict) if k in loaded else v
            for k, v in target.items()
        }
    return loaded if loaded is not None else target


class Checkpointer:
    """Manages the checkpoint directory: rolling saves, resume detection,
    sharded (fsdp/hsdp) directory checkpoints or single-file (ddp) loads."""

    # minimum local seconds a stale loader auto-save dir must hold an
    # unchanged mtime across cleanup passes before it is pruned
    PRUNE_QUIESCE_S = 60.0

    # observability hook (obs/observer.py): when the train loop attaches
    # its Observer here, save() wall time lands in the "checkpoint"
    # phase of the step-time decomposition and the save counters
    observer = None

    def __init__(
        self,
        ckpdir: str,
        n_to_save: int,
        parallel_mode: str,
        rank: int = None,
        local_rank: int = 0,
        report_fn=None,
        verify: bool = True,
        full_checksums: bool = True,
    ):
        self.max_ckps = n_to_save
        self.rank = jax.process_index() if rank is None else rank
        self.local_rank = local_rank
        # verify per-checkpoint manifests on load and fall back to the
        # next-newest committed checkpoint on corruption (resilience layer)
        self.verify = verify
        # manifest v2 full-content coverage: chunked checksums for large
        # array files (the ckpt_full_checksums knob); off degrades large
        # files to size-only verification like a version-1 manifest
        self.full_checksums = bool(full_checksums)
        self.ckp_path = os.path.join(ckpdir, "checkpoints/")
        os.makedirs(self.ckp_path, exist_ok=True)
        assert parallel_mode in ["fsdp", "hsdp", "ddp", "tp"]
        self.p_mode = parallel_mode
        self.report = self._selective_print if report_fn is None else report_fn
        # loader-only prune candidates awaiting quiescence: path ->
        # (newest mtime when marked, local time when marked)
        self._prune_marks: dict = {}
        # elastic resume (ckpt/elastic.py): the live world's topology
        # fingerprint, stamped into every metadata.json by save() and
        # checked against the checkpoint's stamp by load(). None (the
        # default for direct constructions) stamps nothing and skips the
        # gate — the entry points always set one via set_fingerprint.
        self.fingerprint: dict = None
        self.allow_batch_change = False
        self.allow_corpus_change = False

        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()

    def _selective_print(self, *args, **kwargs):
        if self.rank == 0:
            print(*args)
            for k, v in kwargs.items():
                print(k, "=", v)

    def set_fingerprint(
        self,
        fingerprint,
        allow_batch_change: bool = False,
        allow_corpus_change: bool = False,
    ):
        """Arm the elastic-resume contract: ``fingerprint`` (a
        ``ckpt/elastic.py`` topology dict for the LIVE world) is stamped
        into every save's metadata.json and compared against the
        checkpoint's stamp on load — a mismatch is validated for rescale
        legality before any collective restore."""
        self.fingerprint = dict(fingerprint) if fingerprint else None
        self.allow_batch_change = bool(allow_batch_change)
        self.allow_corpus_change = bool(allow_corpus_change)

    def resume_topology(self, candidates=None):
        """Topology fingerprint stamped into the checkpoint a resume
        from the save dir would restore, or None (fresh start, legacy
        checkpoint, or single-file checkpoint). Multi-host runs
        broadcast rank 0's read so every host resolves the same elastic
        batch policy before building its loader. ``candidates`` lets
        the multi-tier manager pass its cross-tier merged newest-first
        list instead of this Checkpointer's own save dir."""
        if candidates is None:
            candidates = self._candidate_ckp_paths(self.ckp_path)
        topo = scan_topology(candidates, verify=self.verify)
        if jax.process_count() > 1:
            topo = self._broadcast_obj({"topo": topo})["topo"]
        return topo

    def _elastic_gate(self, load_path, meta):
        """Validate the checkpoint's topology stamp against the live
        fingerprint BEFORE the collective restore: an illegal rescale
        must fail fast with the same actionable error on every host —
        never deadlock half the pod inside Orbax, and never walk a
        silently shifted document stream. No-op (bit-identical to the
        pre-elastic behavior) when topologies match, when either side
        carries no fingerprint, or on single-file checkpoints."""
        from fms_fsdp_tpu.ckpt.elastic import (
            check_rescale,
            describe_change,
            describe_mixing_change,
        )

        if self.fingerprint is None:
            return
        topo = (meta or {}).get("topology")
        if topo is None:
            self.report(
                f"Note: checkpoint {load_path} predates topology "
                f"fingerprints; skipping the elastic-resume "
                f"compatibility check."
            )
            return
        if "num_slices" not in topo and "num_slices" in self.fingerprint:
            # v1 fingerprint from pre-multi-slice code: the slice
            # fault-domain checks have nothing to compare against
            self.report(
                f"Note: checkpoint {load_path} predates slice-aware "
                f"topology fingerprints (no slice fields); slice "
                f"fault-domain checks are skipped for this resume."
            )
        problems, changed = check_rescale(
            topo,
            self.fingerprint,
            ckp_dir=load_path,
            allow_batch_change=self.allow_batch_change,
            allow_corpus_change=self.allow_corpus_change,
        )
        # collective verdict: the loader-file count is a local listdir
        # that eventually-consistent storage could split across hosts,
        # and every host must either proceed into the collective
        # restore or raise — never a mixture
        if not self._all_agree(not problems):
            raise RuntimeError(
                f"elastic resume from {load_path} is not legal for this "
                f"world ({describe_change(topo, self.fingerprint) or 'peer report'}):\n- "
                + "\n- ".join(problems or ["a peer process rejected the rescale"])
            )
        if changed:
            self.report(
                f"Elastic resume: restart topology differs from the "
                f"save topology ({describe_change(topo, self.fingerprint)}); "
                f"model/optimizer reshard onto the live mesh and loader "
                f"state reshards across the new ranks."
            )
            # legal data-mix changes (weight change, corpus reorder) are
            # worth a line of their own: the realized mix shifts even
            # though nothing is lost
            mix_note = describe_mixing_change(topo, self.fingerprint)
            if mix_note:
                self.report(f"Elastic resume mixing note: {mix_note}")

    # -- path resolution ----------------------------------------------------

    def _candidate_ckp_paths(self, path):
        """All loadable checkpoints under ``path``, newest first: a file
        or committed step dir resolves to itself; a checkpoint folder
        resolves to its committed step entries ordered by step number.
        The fallback chain for corrupt-restore recovery walks this list."""
        if not path or not os.path.exists(path):
            return []
        if os.path.isfile(path):
            return [path]
        entries = os.listdir(path)
        if "metadata.json" in entries:
            return [path]
        # only step_<N>_ckp entries qualify (by step number, not
        # ctime): foreign files parked in the folder must not shadow
        # real checkpoints. Keep entries that actually hold MODEL
        # state — the folder interleaves loader auto-save dirs
        # (loader_state only, no metadata.json) with model checkpoints.
        # Quarantined dirs (the scrubber's integrity_quarantine.json
        # sidecar, resilience/scrub.py) are dropped here, at the single
        # choke point every walk shares — load's fallback chain,
        # resume_topology, and the multi-tier merge all route around a
        # known-corrupt step dir without re-reading a byte of it.
        from fms_fsdp_tpu.resilience.scrub import is_quarantined

        candidates = sorted(
            (
                os.path.join(path, x)
                for x in entries
                if is_step_ckp(os.path.join(path, x))
            ),
            key=step_number,
            reverse=True,
        )
        return [
            cand
            for cand in candidates
            if os.path.isfile(cand)
            or (
                "metadata.json" in safe_listdir(cand)
                and not is_quarantined(cand)
            )
        ]

    def _validate_ckp_path(self, path):
        """Resolve to the newest loadable checkpoint (file, step dir, or
        newest step dir inside a checkpoint folder), else None."""
        candidates = self._candidate_ckp_paths(path)
        return candidates[0] if candidates else None

    def _broadcast_obj(self, obj):
        """Broadcast a small JSON-able object from process 0 to all.
        Two collectives: the byte length (fixed shape), then the padded
        payload buffer (now same shape everywhere)."""
        import numpy as np
        from jax.experimental import multihost_utils

        source = jax.process_index() == 0
        # non-source processes contribute explicit zeros: some
        # implementations of the broadcast reduce contributions, and
        # only the source's bytes may survive the reduction
        data = (
            np.frombuffer(json.dumps(obj).encode("utf-8"), np.uint8)
            if source
            else np.zeros(0, np.uint8)
        )
        n = int(
            multihost_utils.broadcast_one_to_all(
                np.asarray(len(data), np.int32)
            )
        )
        buf = np.zeros(n, np.uint8)
        if source:
            buf[:] = data
        out = multihost_utils.broadcast_one_to_all(buf)
        # some jax versions return the buffer upcast (uint8 -> int32):
        # cast back before reassembling the bytes
        out = np.asarray(out).astype(np.uint8)
        return json.loads(out.tobytes().decode("utf-8"))

    def _all_agree(self, ok: bool) -> bool:
        """Collective AND of a per-process verdict. Fallback decisions
        must be identical on every process — the Orbax restore is
        collective, so two hosts restoring different candidates would
        deadlock the pod (or assemble a mixed-step state). Single-process
        worlds return the local verdict untouched."""
        if jax.process_count() == 1:
            return ok
        import numpy as np
        from jax.experimental import multihost_utils

        votes = multihost_utils.process_allgather(
            np.array([1 if ok else 0], np.int32)
        )
        return bool(np.asarray(votes).min() == 1)

    # -- cleanup ------------------------------------------------------------

    def _cleanup(self):
        """Rolling retention: delete the oldest saved step checkpoints
        beyond max_ckps. The reference's equivalent filters on a 'tmp'
        qualifier its own save path never produces
        (ref:checkpointing_utils.py:120-135 vs :299), so its advertised
        n_to_save retention silently never fires — here the filter matches
        the names ``save`` actually writes (step_<N>_ckp)."""
        if self.rank != 0:
            return None

        def is_model_ckp(p):
            return is_step_ckp(p) and (
                os.path.isfile(p) or "metadata.json" in safe_listdir(p)
            )

        # the quota counts MODEL checkpoints only: loader auto-save dirs
        # (loader_state files, no metadata.json) share the folder and
        # must not evict real checkpoints from the retention window
        while (
            len(
                [
                    x
                    for x in os.listdir(self.ckp_path)
                    if is_model_ckp(os.path.join(self.ckp_path, x))
                ]
            )
            > self.max_ckps
        ):
            # order by the step number in the name, not ctime: copied or
            # restored checkpoint trees don't preserve ctime, and deleting
            # by ctime could claim the newest step instead of the oldest
            oldest = get_oldest(
                self.ckp_path, qualifier=is_model_ckp, key=step_number
            )
            if oldest is None:
                break
            ckp_to_remove = Path(oldest)
            if os.path.isfile(ckp_to_remove):
                ckp_to_remove.unlink()
            else:
                try:
                    shutil.rmtree(ckp_to_remove)
                except OSError:
                    # the rank-0 scrubber thread can stamp a verdict/
                    # quarantine sidecar into this dir between rmtree's
                    # directory scan and its final rmdir (ENOTEMPTY):
                    # drop the sidecars and retry once; a second failure
                    # must not kill the save path over retention
                    # housekeeping — leave the dir for the next pass
                    from fms_fsdp_tpu.resilience.scrub import (
                        clear_integrity_sidecars,
                    )

                    clear_integrity_sidecars(str(ckp_to_remove))
                    try:
                        shutil.rmtree(ckp_to_remove)
                    except OSError as e:
                        self.report(
                            f"WARNING: retention cleanup of "
                            f"{ckp_to_remove} failed ({e}); retrying at "
                            f"the next save"
                        )
                        break
        # non-model step dirs split two ways:
        # - loader-only auto-save dirs (loader_state files, no model
        #   state payload): CheckpointDataset resumes from the newest of
        #   them only, so keep the newest two (margin for a partially-
        #   written newest) and drop the rest. Ranked strictly among
        #   loader-only dirs — their step numbers are on the worker
        #   clock, which can lag or lead the trainer clock, so comparing
        #   them against model-checkpoint numbers would be meaningless
        #   (and at worst delete the only loader state).
        # - torn (uncommitted) model saves: state payload or manifest
        #   but no metadata.json commit marker — a save killed before
        #   commit. Invisible to every scanner and to the retention
        #   quota, so without GC they accumulate forever; ALL of them
        #   are prune candidates (after the same quiesce window, which
        #   spares a save still being written).
        def has_loader_state(p):
            return any(
                f.startswith("loader_state") for f in safe_listdir(p)
            )

        def has_state_payload(p):
            # a committed/in-flight orbax write ("state", or its tmp
            # name mid-write) or the manifest written just before commit
            return any(
                f == "state" or "orbax-checkpoint" in f or f == "manifest.json"
                for f in safe_listdir(p)
            )

        non_model = [
            os.path.join(self.ckp_path, x)
            for x in os.listdir(self.ckp_path)
            if is_step_ckp(x)
            and not is_model_ckp(os.path.join(self.ckp_path, x))
        ]
        loader_only = sorted(
            (
                p
                for p in non_model
                if has_loader_state(p) and not has_state_payload(p)
            ),
            key=step_number,
            reverse=True,
        )
        torn = [p for p in non_model if p not in loader_only]
        def newest_mtime(p):
            # mtime fingerprint across the dir tree: a growing
            # loader_state file (or a TensorStore shard deep inside a
            # torn dir's state payload) bumps its own mtime, not the
            # directory's. A full fingerprint (not max): a skewed writer
            # can stamp a file BELOW the directory mtime, which a max
            # would never see
            try:
                entries = [("", os.path.getmtime(p))]
                for root, _, files in os.walk(p):
                    for f in files:
                        full = os.path.join(root, f)
                        entries.append(
                            (os.path.relpath(full, p), os.path.getmtime(full))
                        )
                return tuple(sorted(entries))
            except OSError:
                return None

        # a straggler worker can still be writing its shard into an old
        # step dir (its auto-save clock lags the fast workers'), and an
        # async save's storage write may still be landing in a dir that
        # looks torn until its commit marker appears: prune a candidate
        # only after its newest mtime holds STILL across two cleanup
        # passes at least PRUNE_QUIESCE_S of local time apart.
        # Progress is detected by mtime CHANGE, never by comparing an
        # mtime against the local clock — shared-storage server clocks
        # can lead or lag rank 0's by more than the window, which would
        # make a wall-clock age test prune under an active writer (or
        # never prune at all).
        now = time.time()
        marks = self._prune_marks
        candidates = {p: newest_mtime(p) for p in loader_only[2:] + torn}
        for p, m in candidates.items():
            if m is None:
                marks.pop(p, None)
                continue
            marked = marks.get(p)
            if marked is None or marked[0] != m:
                marks[p] = (m, now)  # (re)arm: new candidate or still writing
                continue
            if now - marked[1] >= self.PRUNE_QUIESCE_S:
                shutil.rmtree(p, ignore_errors=True)
                marks.pop(p, None)
        # drop marks for paths no longer candidates (pruned, promoted
        # back inside the newest-two window, or externally removed)
        for p in list(marks):
            if p not in candidates:
                marks.pop(p)
        return None

    # -- save ---------------------------------------------------------------

    def save(self, step, state, dataloader=None, reason="interval", **metadata):
        """Write the sharded train state + loader state + metadata to
        ``step_<step>_ckp``. ``metadata`` kwargs (e.g. tokens_seen) land in
        metadata.json with the step count. ``reason`` is accepted for
        call-compatibility with the tiered AsyncCheckpointManager (the
        loop passes it unconditionally); the synchronous path has no
        tier routing, so it is ignored.

        Commit ordering: state shards -> loader state -> manifest ->
        metadata.json (the commit marker, atomic rename). A save torn
        before the marker leaves an uncommitted dir every scanner skips;
        a committed checkpoint always has a verifiable manifest."""
        from contextlib import nullcontext

        # function-level: ckpt/__init__ -> manager -> this module
        from fms_fsdp_tpu.ckpt.elastic import stamp_topology
        from fms_fsdp_tpu.resilience.integrity import write_manifest

        obs = self.observer
        save_time = time.time()
        with obs.phase("checkpoint") if obs is not None else nullcontext():
            save_name = os.path.join(self.ckp_path, f"step_{step}_ckp")
            os.makedirs(save_name, exist_ok=True)

            self._ckptr.save(
                os.path.join(save_name, "state"), state, force=True
            )
            self._ckptr.wait_until_finished()
            if dataloader is not None:
                dataloader.save_to_path(save_name)
            if self.rank == 0:
                from fms_fsdp_tpu.resilience.scrub import (
                    clear_integrity_sidecars,
                )

                # a re-commit into a previously-quarantined step dir
                # (fallback resume trained back past it) carries fresh
                # content: stale verdicts must not outlive the bytes
                clear_integrity_sidecars(save_name)
                write_manifest(save_name, full_checksums=self.full_checksums)
                metadata["step"] = step
                stamp_topology(metadata, self.fingerprint, dataloader)
                meta_path = os.path.join(save_name, "metadata.json")
                with open(meta_path + ".tmp", "w") as f:
                    json.dump(metadata, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(meta_path + ".tmp", meta_path)
                # re-clear after the commit marker: a scrubber sweep
                # racing the manifest hash above sees old manifest +
                # old metadata.json + new payload on a RE-commit and
                # quarantines the dir (see _commit_tier_io)
                clear_integrity_sidecars(save_name)
                self._maybe_corrupt(save_name, step)
                self._maybe_flip(save_name, step)
        if obs is not None:
            obs.registry.counter("checkpoint.saves").add()
            obs.registry.hist("checkpoint.save_s").record(
                time.time() - save_time
            )
        self.report(
            f"Checkpoint saved in {save_name}",
            model_save_time=time.time() - save_time,
        )
        return self._cleanup()

    def finalize(self):
        """No-op: the synchronous save has nothing in flight when it
        returns. Lets callers invoke ``finalize()`` unconditionally at
        loop exit (the async manager's is mandatory)."""

    @staticmethod
    def _maybe_corrupt(save_name, step, **ctx):
        """``ckpt_corrupt`` fault site: truncate one file inside the
        just-committed checkpoint (``file=<substring>`` selects it) —
        the torn/bit-rotted storage failure the load-time manifest
        verification and fallback chain must absorb. Extra ``ctx``
        (e.g. ``tier`` from the async writer) feeds the fault filters."""
        from fms_fsdp_tpu.resilience.faults import fire_fault

        params = fire_fault("ckpt_corrupt", step=step, **ctx)
        if params is None:
            return
        want = str(params.get("file", ""))
        victims = []
        for root, _, files in os.walk(save_name):
            for name in files:
                full = os.path.join(root, name)
                if want in full and os.path.getsize(full) > 0:
                    victims.append(full)
        victims.sort()
        assert victims, f"ckpt_corrupt: no file matching {want!r} in {save_name}"
        victim = victims[0]
        size = os.path.getsize(victim)
        with open(victim, "rb+") as f:
            f.truncate(size // 2)
        print(f"ckpt_corrupt fault: truncated {victim} ({size} -> {size // 2})")

    @staticmethod
    def _maybe_flip(save_name, step, **ctx):
        """``ckpt_shard_corrupt`` fault site: flip bytes mid-file inside
        a manifest-recorded shard of the just-committed checkpoint
        WITHOUT changing its size — the silent bit-rot/SDC storage class
        that passes every size check and only full-content checksums
        (manifest v2) or the scrubber catch. ``file=<substring>``
        selects the victim among the manifest's recorded files (largest
        match first, so the default hits an array shard, not an index
        blob); ``bytes=N`` flips N bytes (default 4) at the file's
        midpoint."""
        from fms_fsdp_tpu.resilience.faults import fire_fault
        from fms_fsdp_tpu.resilience.integrity import MANIFEST_NAME

        params = fire_fault("ckpt_shard_corrupt", step=step, **ctx)
        if params is None:
            return
        want = str(params.get("file", ""))
        try:
            with open(os.path.join(save_name, MANIFEST_NAME)) as f:
                recorded = json.load(f).get("files", {})
        except (OSError, ValueError):
            recorded = {}
        victims = sorted(
            (
                (int(size), rel)
                for rel, size in recorded.items()
                if want in rel and int(size) > 0
            ),
            key=lambda t: (-t[0], t[1]),
        )
        assert victims, (
            f"ckpt_shard_corrupt: no recorded file matching {want!r} in "
            f"{save_name}"
        )
        size, rel = victims[0]
        victim = os.path.join(save_name, rel)
        n = max(1, int(params.get("bytes", 4)))
        off = size // 2
        with open(victim, "rb+") as f:
            f.seek(off)
            data = f.read(min(n, size - off))
            f.seek(off)
            f.write(bytes(b ^ 0xFF for b in data))
        # injection hygiene: a scrubber sweep racing the commit could
        # have stamped a verified verdict in the instant before the
        # flip — real bit-rot cannot consult the scrubber's clock, but
        # the INJECTED corruption must be deterministic for the chaos
        # soak, so the verdict for THIS dir (sidecars + memo entry) is
        # invalidated with it. Scoped, not reset_cache(): the global
        # reset would zero the monotone scrub_verified counter mid-run
        # and force every other dir to re-hash.
        from fms_fsdp_tpu.resilience.scrub import clear_integrity_sidecars

        clear_integrity_sidecars(save_name)
        print(
            f"ckpt_shard_corrupt fault: flipped {len(data)} byte(s) at "
            f"offset {off} of {victim} (size {size} unchanged)"
        )

    # -- load ---------------------------------------------------------------

    def load(
        self,
        state,
        dataloader=None,
        path="",
        reset_stepcount=False,
        strict=True,
        candidates=None,
        is_resuming=None,
    ):
        """Restore (state, dataloader) from the save dir if it holds a
        checkpoint (job restart), else from ``path``.

        ``state`` is the freshly initialized sharded train state — it
        provides the target structure/sharding for restoration. Returns
        (state, dataloader, step, tokens_seen, is_resuming).

        ``candidates`` (with ``is_resuming``) lets a caller that already
        scanned — the tiered AsyncCheckpointManager merging several
        checkpoint roots — inject its own newest-first candidate list;
        the caller is then responsible for the multi-host agreement on
        that list (the broadcast below is skipped).

        Integrity: each candidate checkpoint is manifest-verified (when
        ``self.verify``) and its restore wrapped — a corrupt or torn
        newest checkpoint falls back to the next-newest committed one
        with a warning instead of killing the restart. Only when every
        candidate fails does load raise (restarting a long run from
        scratch silently would be worse than crashing)."""
        from fms_fsdp_tpu.resilience.scrub import (
            cached_verify,
            verified_resume_active,
        )

        # verified-resume policy (resilience/scrub.py): after a
        # state-divergence relaunch the supervisor exports
        # FMS_VERIFIED_RESUME — the newest checkpoint may hold the
        # diverged replica's poison, so the restore must come from a
        # checkpoint whose CONTENT has been verified (cached scrub
        # verdict or a fresh full verify in this walk), even when
        # checkpoint_verify was turned off
        verified_resume = verified_resume_active()
        verify = self.verify or verified_resume
        if verified_resume and self.rank == 0:
            self.report(
                "Verified-resume policy active (FMS_VERIFIED_RESUME): "
                "restoring only from scrub-verified checkpoints; the "
                "newest unverified candidate is verified in place "
                "before it may be restored."
            )

        if candidates is None:
            is_resuming = False
            candidates = self._candidate_ckp_paths(self.ckp_path)
            if candidates:
                path = self.ckp_path
                is_resuming = True
            else:
                candidates = self._candidate_ckp_paths(path)
            if jax.process_count() > 1:
                # process 0's directory scan is authoritative: eventually-
                # consistent shared storage can show hosts different
                # listings, and every host must walk the SAME candidate
                # list in the same order — the per-candidate votes and
                # collective restores below are counted in lockstep
                decision = self._broadcast_obj(
                    {"resume": is_resuming, "cands": candidates}
                )
                is_resuming = bool(decision["resume"])
                candidates = [str(c) for c in decision["cands"]]
        else:
            is_resuming = bool(is_resuming)
        if not candidates:
            self.report(
                f"No valid checkpoint detected at {path}, starting from scratch."
            )
            if dataloader is not None and getattr(
                dataloader, "supports_fresh_start", False
            ):
                # from-scratch is a RESOLVED verdict, not an absence of
                # one: tell the dataset (empty-path marker) so its
                # setup() auto-load cannot resume the walk from a stale
                # loader auto-save left by a torn or quarantined
                # checkpoint this scan just rejected (model@0 +
                # loader@N splits the stream; chaos_soak pins this).
                # Gated on the advertised contract: a bare loader
                # without the flag treats load_from_path("") as a real
                # (missing) checkpoint path and must stay untouched.
                dataloader.load_from_path("")
            return state, dataloader, 0, 0, False

        last_err = None
        for load_path in candidates:
            self.report(f"Prior checkpoint {load_path} detected.")
            t0 = time.time()
            if os.path.isfile(load_path):
                # single-file checkpoint: bare model params (ddp/speculator
                # path, ref:checkpointing_utils.py:215-233); optimizer and
                # dataloader start fresh
                err = None
                payload = None
                try:
                    with open(load_path, "rb") as f:
                        payload = pickle.load(f)
                except (OSError, pickle.UnpicklingError, EOFError) as e:
                    err = e
                # every process must take the same branch: a host whose
                # local read failed while a peer's succeeded would leave
                # the pod on different checkpoints
                if not self._all_agree(err is None):
                    self.report(
                        f"WARNING: single-file checkpoint {load_path} is "
                        f"unreadable on at least one process ({err}); "
                        f"falling back to the next-newest checkpoint."
                    )
                    last_err = err or RuntimeError(
                        f"peer process failed to read {load_path}"
                    )
                    continue
                params = payload.get("model_state", payload)
                target = state["params"]
                merged = _merge_trees(target, params, strict)
                shardings = jax.tree.map(lambda a: a.sharding, target)
                loaded = jax.tree.map(
                    lambda arr, s: jax.device_put(arr, s), merged, shardings
                )
                state = dict(state, params=loaded)
                self.report(
                    f"Checkpoint {load_path} is a single-file checkpoint "
                    "containing only a model. Optimizer and dataloader are "
                    "from scratch.",
                    model_load_time=time.time() - t0,
                )
                if dataloader is not None and getattr(
                    dataloader, "supports_fresh_start", False
                ):
                    # same fresh-start marker as the no-candidates path:
                    # "dataloader from scratch" must also suppress the
                    # dataset's own stale-auto-save detection
                    dataloader.load_from_path("")
                return state, dataloader, 0, 0, is_resuming

            if verify:
                # verdict-cached (resilience/scrub.py): a scrub-verified
                # dir costs a digest read, a fresh verify is memoized
                # (the topology scan already paid for this candidate),
                # and rank 0 persists the outcome — success as a verdict
                # sidecar, failure as a quarantine marker with the one
                # actionable line, so no later walk re-hashes this dir
                ok, problems = cached_verify(
                    load_path,
                    write_sidecars=(self.rank == 0),
                    report=self.report,
                )
                # collective verdict: the restore below is a collective
                # op, so a candidate one process rejects must be rejected
                # by ALL of them (shared storage normally agrees; a
                # host-local read error must not split the decision)
                if not self._all_agree(ok):
                    self.report(
                        f"WARNING: checkpoint {load_path} failed integrity "
                        f"verification on at least one process "
                        f"({'; '.join(problems[:3]) or 'peer report'}); "
                        f"falling back to the next-newest committed "
                        f"checkpoint."
                    )
                    last_err = RuntimeError(
                        f"integrity verification failed: {problems}"
                    )
                    continue
                if problems:  # coverage note: legacy / size-only large files
                    if verified_resume:
                        # the policy demanded content verification; this
                        # candidate can only offer partial coverage.
                        # Restore it anyway (refusing every size-only
                        # candidate would turn a divergence relaunch
                        # into a crash loop on runs that disabled full
                        # checksums) but say so loudly — it does NOT
                        # count as scrub-verified (resilience/scrub.py)
                        self.report(
                            f"WARNING: verified-resume policy active but "
                            f"{load_path} is only partially "
                            f"content-verifiable ({problems[0]}); "
                            f"restoring it anyway — enable "
                            f"ckpt_full_checksums to close this gap."
                        )
                    else:
                        self.report(f"Note: {problems[0]}")

            # metadata is read BEFORE the collective restore: a torn
            # metadata.json is a corrupt checkpoint (fall back while
            # falling back is still collective-safe), and the elastic
            # topology gate below must be able to fail fast on every
            # host rather than deadlock half the pod inside Orbax
            meta = None
            if is_resuming and not reset_stepcount:
                meta_err = None
                try:
                    with open(os.path.join(load_path, "metadata.json")) as f:
                        meta = json.load(f)
                except (OSError, ValueError) as e:
                    meta_err = e
                if not self._all_agree(meta_err is None):
                    self.report(
                        f"WARNING: checkpoint {load_path} has an "
                        f"unreadable metadata.json on at least one "
                        f"process ({meta_err}); falling back to the "
                        f"next-newest committed checkpoint."
                    )
                    last_err = meta_err or RuntimeError(
                        f"peer process failed to read metadata of {load_path}"
                    )
                    continue
                # elastic gate: same-topology resumes pass through
                # untouched; a topology change is validated for rescale
                # legality (illegal -> actionable raise on every host)
                self._elastic_gate(load_path, meta)

            # sharded directory checkpoint: restore into the target sharding
            abstract = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(
                    a.shape, a.dtype, sharding=a.sharding
                ),
                state,
            )
            try:
                restored = self._ckptr.restore(
                    os.path.join(load_path, "state"), abstract
                )
                if dataloader is not None:
                    # loader state is per-rank and excluded from the
                    # manifest (another host may still be writing its
                    # own), so a torn loader file surfaces HERE — it must
                    # fall back with the rest of the checkpoint, not kill
                    # the restart after a successful model restore
                    t1 = time.time()
                    dataloader.load_from_path(load_path)
                    self.report(dataset_load_time=time.time() - t1)
                else:
                    self.report("Skipping dataset load, no dataloader provided.")
            except Exception as e:  # noqa: BLE001 — any restore failure
                # falls back to the next-newest committed checkpoint
                if jax.process_count() > 1:
                    # a failure thrown on THIS process mid-collective
                    # cannot be recovered unilaterally: peers may be
                    # parked inside the collective restore, and quietly
                    # moving to an older candidate would deadlock or
                    # mix steps across hosts. Fail loudly; the restart
                    # supervisor retries the whole job.
                    raise RuntimeError(
                        f"restore from {load_path} failed on process "
                        f"{self.rank}; multi-host fallback cannot proceed "
                        f"safely from inside a failed collective restore"
                    ) from e
                self.report(
                    f"WARNING: restore from {load_path} failed ({e!r}); "
                    f"falling back to the next-newest committed checkpoint."
                )
                last_err = e
                continue
            state = restored
            self.report(model_load_time=time.time() - t0)

            step, ntok = 0, 0
            if meta is not None:
                step = meta.get("step", 0)
                ntok = meta.get("tokens_seen", 0)
                self.report(
                    "Metadata loaded", start_step=step, n_tokens_seen=ntok
                )
            else:
                # Continued pretraining from an external checkpoint: keep the
                # optimizer moments but restart the schedule clock — the step
                # counter drives the injected LR (ref:main_training_llama.py:
                # 130-134 resets initial_lr + scheduler on non-resume loads).
                if "step" in state:
                    state = dict(
                        state, step=jax.tree.map(lambda s: s * 0, state["step"])
                    )

            return state, dataloader, step, ntok, is_resuming

        raise RuntimeError(
            f"all {len(candidates)} checkpoint(s) under {path} failed to "
            f"load; refusing to silently restart from scratch"
        ) from last_err
