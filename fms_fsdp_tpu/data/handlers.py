"""Shard-file readers (ref:fms_fsdp/utils/dataset_utils.py:286-457).

- ArrowHandler: pre-tokenized pyarrow IPC files, one document per
  RecordBatch; mmap'd so document chunks slice zero-copy without reading
  whole shards (pyarrow is host-side C++ — TPU-agnostic, reused as-is).
- ParquetHandler: HF-style parquet of raw text, tokenized on the fly.
- AutoHandler: dispatch by file extension.

All strip configured bos/eos tokens found at document edges so delimiter
placement is fully owned by the pipeline.
"""

import os
from typing import Any, List, Set

import numpy as np


class ShardFileHandler:
    """Interface: open / length / get / slice over one shard file."""

    def is_legal(self, filepath: str) -> bool:
        return os.path.isfile(filepath)

    def open(self, path: str):
        raise NotImplementedError

    def length(self, path: str) -> int:
        """Number of documents in the file (without reading it whole)."""
        raise NotImplementedError

    def get(self, reader, index: int, drop_tokens: Set):
        """Fetch document ``index``; strip leading/trailing drop_tokens.
        Result must support len()."""
        raise NotImplementedError

    def slice(self, doc, index: int, n_pull: int) -> "np.ndarray":
        """Return doc[index : index + n_pull] as a 1-D int numpy array.

        Token chunks travel the whole host pipeline as numpy arrays —
        per-token python-object conversion (arrow ``to_pylist``) was the
        single hottest call of the loader at ~2/3 of iterator time.
        """
        raise NotImplementedError


class ArrowHandler(ShardFileHandler):
    """Indexable pre-tokenized pyarrow shard files: each RecordBatch holds
    one document as a token list under ``col_name``."""

    def __init__(self, col_name: str = "tokens"):
        self.col_name = col_name

    def is_legal(self, filepath: str) -> bool:
        return "arrow" in os.path.splitext(filepath)[1]

    def open(self, path: str):
        import pyarrow as pa

        return pa.ipc.open_file(pa.memory_map(path))

    def length(self, path: str) -> int:
        return self.open(path).num_record_batches

    def get(self, reader, index: int, drop_tokens: Set):
        doc = reader.get_batch(index)[self.col_name]
        if len(doc) > 0 and doc[0].as_py() in drop_tokens:
            doc = doc.slice(1, len(doc) - 1)
        # re-check: doc may have been exactly [eos]
        if len(doc) > 0 and doc[-1].as_py() in drop_tokens:
            doc = doc.slice(0, len(doc) - 1)
        return doc

    def slice(self, doc, index: int, n_pull: int) -> np.ndarray:
        return doc.slice(index, n_pull).to_numpy(zero_copy_only=False)


class ParquetHandler(ShardFileHandler):
    """Parquet shards of raw text, tokenized on access with an HF tokenizer
    (assumes modest shard/document sizes)."""

    def __init__(self, tokenizer_path: str, col_name: str = "text"):
        from transformers import AutoTokenizer

        self.tokenizer = AutoTokenizer.from_pretrained(tokenizer_path)
        self.col_name = col_name

    def is_legal(self, filepath: str) -> bool:
        return "parquet" in os.path.splitext(filepath)[1]

    def open(self, path: str):
        import pyarrow.parquet as pq

        return pq.read_pandas(path, columns=[self.col_name], partitioning=None)[
            self.col_name
        ]

    def length(self, path: str) -> int:
        import pyarrow.parquet as pq

        return pq.read_metadata(path).num_rows

    def get(self, reader, index: int, drop_tokens: Set):
        doc = self.tokenizer(str(reader[index]))["input_ids"]
        if len(doc) > 0 and doc[0] in drop_tokens:
            doc = doc[1:]
        if len(doc) > 0 and doc[-1] in drop_tokens:
            doc = doc[:-1]
        return doc

    def slice(self, doc: List, index: int, n_pull: int) -> np.ndarray:
        return np.asarray(doc[index : index + n_pull], dtype=np.int64)


class AutoHandler(ShardFileHandler):
    """Extension-dispatching handler over Arrow + Parquet."""

    def __init__(self, tokenizer_path: str, col_name: str = "text"):
        self.PHandler = ParquetHandler(tokenizer_path, col_name)
        self.AHandler = ArrowHandler()
        self.current: ShardFileHandler = ShardFileHandler()

    def _pick(self, path: str) -> ShardFileHandler:
        if "arrow" in os.path.splitext(path)[1]:
            return self.AHandler
        return self.PHandler

    def is_legal(self, filepath: str) -> bool:
        ext = os.path.splitext(filepath)[1]
        return "parquet" in ext or "arrow" in ext

    def open(self, path: str):
        self.current = self._pick(path)
        return self.current.open(path)

    def length(self, path: str) -> int:
        return self._pick(path).length(path)

    def get(self, reader, index: int, drop_tokens: Set):
        return self.current.get(reader, index, drop_tokens)

    def slice(self, doc, index: int, n_pull: int) -> List:
        return self.current.slice(doc, index, n_pull)
