"""Streaming document readers: the distribution-aware base of the pipeline.

Three layers (ref:fms_fsdp/utils/dataset_utils.py:797-1417):

- ``StreamingDocDataset`` — walks one dataset directory, partitions shard
  files into worldsize fragments per worker (contiguous spans to limit
  file churn), pulls documents via an LCG bijection shuffle (no doc-list
  materialization), yields documents in chunks <= max_chunksize with
  delimiter/bos placement, and tracks epoch/token/doc progress with
  mid-document resume.
- ``ScalableShardDataset`` — rescalability: clones the reader into
  ``n_logical_shards`` logical workers; each physical rank owns
  n/worldsize of them and samples among its logicals proportional to
  docs remaining, so checkpoints reshard onto any world size dividing
  the logical count.
- ``SamplingDataset`` — multi-dataset weighted mixing by *tokens seen*:
  always draws from the most under-target subdataset, holding it to a
  document boundary.
"""

import csv
import logging
import math
import os
import random
from copy import deepcopy
from typing import Any, List, Optional, Set, Union

import numpy as np

from fms_fsdp_tpu.data.handlers import ShardFileHandler
from fms_fsdp_tpu.data.stateful import (
    StatefulDataset,
    WrapperDataset,
    shard_partition,
)

logger = logging.getLogger(__name__)


class CorpusUnreadableError(RuntimeError):
    """One corpus's document stream died: every owned shard of the
    corpus is quarantined (or the corpus held no readable documents to
    begin with). Raised by the per-corpus reader stack and caught by
    ``SamplingDataset``, which quarantines the corpus and degrades the
    mix over the survivors instead of killing the run."""


class CorpusLossError(RuntimeError):
    """The weighted mix dropped below its survivable floor: losing a
    corpus left fewer than ``min_live_corpora`` live corpora (losing the
    LAST corpus always breaches the implicit floor of 1). Typed so the
    entry points' classified-exit wrapper (resilience/exits.py) exits
    with the ``corpus_loss`` registry code and the run supervisor
    applies the corpus-loss restart policy rather than the generic
    crash policy."""


# Mix lifecycle events buffered for the observer (obs/): the
# SamplingDataset lives deep inside the loader pipeline — possibly in a
# worker thread — with no registry handle, so it bumps these module
# counters (GIL-atomic int +=) and the train loop drains them into the
# metric registry at report cadence (``data.corpus_quarantined`` /
# ``data.corpus_rearmed``). Forked process-mode workers keep their own
# copy; their events are visible in logs but not in the parent's
# metrics (docs/dataloader.md "Multi-corpus mixing").
_MIX_EVENTS = {"corpus_quarantined": 0, "corpus_rearmed": 0}


def drain_mix_events() -> dict:
    """Return and consume the buffered mix lifecycle events. Decrements
    by the drained amount rather than resetting to zero: a worker-thread
    increment landing between the copy and the reset must not be
    silently discarded (it stays buffered for the next drain)."""
    out = dict(_MIX_EVENTS)
    for k, n in out.items():
        _MIX_EVENTS[k] -= n
    return out


class StreamingDocDataset(StatefulDataset):
    """Base reader for one dataset directory (need not be flat).

    Document order: shard files are deterministically shuffled per worker;
    within each owned shard fragment, documents are visited via an LCG
    random bijection (a=5, c=(rank+seed)*2+1, power-of-2 modulus — Knuth
    3.2.1.3) so shuffled traversal needs O(1) state and resumes exactly.
    Documents stream out as chunks of at most ``max_chunksize`` tokens with
    the delimiter appended at document end (and optional bos prepended),
    so downstream layers can detect document boundaries.

    Shard-file lengths come from a ``meta/*counts*.csv`` in the parent
    directory when present, else each owned file is touched once.
    """

    def __init__(
        self,
        datapath: str,
        rank: int,
        worldsize: int,
        filehandler: ShardFileHandler,
        delimiter_token: Any,
        bos_token: Optional[Any] = None,
        strip_tokens: Optional[Set[Any]] = set(),
        seed: int = 42,
        min_length: int = 1,
        max_chunksize: int = 1024,
        verbose: bool = False,
    ):
        super().__init__(datapath, rank, worldsize)
        self.seed = seed
        self.datapath = datapath
        self.filehandler = filehandler
        self.min_length = min_length
        assert max_chunksize > 0, "Max chunksize must be a nonzero positive integer"
        self.chunksize = max_chunksize
        self.eos = delimiter_token
        self.bos = bos_token
        self.drop = strip_tokens
        self.verbose = verbose

        # docset: list of (shard-relpath, min docid, max docid) owned spans
        self.docset: List[Any] = []
        self.docset_index = 0
        self.chunk_index = -1

        # progress stats
        self.epochs_seen = -1
        self.tokens_seen = 0
        self.docs_seen = 0
        self.percent_seen = 0

        # shards whose reads kept failing after bounded retries: skipped
        # (not fatal) and carried in the state_dict so a resume doesn't
        # rediscover the same bad file the hard way. Shards unreadable at
        # SETUP (length probe failed; zero-doc span for the whole run)
        # are tracked separately so the epoch-boundary re-probe doesn't
        # pointlessly clear them — AND persisted in the state_dict: the
        # docset is built around their zero-doc spans, so a resume on a
        # healed shard must re-apply the set before rebuilding the
        # docset, or the restored docset_index/lcg_state would walk a
        # silently shifted document order (replays/skips for the rest of
        # the epoch).
        self.quarantined_shards: List[str] = []
        self.setup_quarantined: List[str] = []

        self.state_params = [
            "dataset",
            "docset_index",
            "chunk_index",
            "epochs_seen",
            "tokens_seen",
            "docs_seen",
            "percent_seen",
            "lcg_state",
            "quarantined_shards",
            "setup_quarantined",
        ]

        self.is_setup = False
        self._len = 0
        self.dataset = ""
        self.lcg_state = 0

    # -- setup ------------------------------------------------------------

    def _walk_shards(self) -> List[str]:
        shards = [
            os.path.join(root, name)[len(self.datapath) + 1 :]
            for root, dirs, files in os.walk(self.datapath, topdown=False)
            for name in files
            if self.filehandler.is_legal(os.path.join(root, name))
        ]
        shards.sort()  # identical ordering on every worker
        return shards

    def _load_doc_counts(self, pardir: str, dataset: str, shardfrags) -> dict:
        """Document count per shard file: from the meta csv when present,
        else by touching each owned file once."""
        countfiles = []
        metadir = os.path.join(pardir, "meta")
        if os.path.exists(metadir):
            countfiles = [
                x for x in os.listdir(metadir) if "counts" in x and "csv" in x
            ]
        if countfiles:
            doc_counts = {}
            with open(os.path.join(metadir, countfiles[0]), "r") as csvfile:
                for row in csv.DictReader(csvfile):
                    fullpath = row["dataset/filename"]
                    prefix = fullpath.find("/" + dataset) + 1
                    if prefix > 0:
                        key = fullpath[prefix + len(dataset) + 1 :]
                        doc_counts[key] = int(row["documents"])
            return doc_counts
        doc_counts = {}
        for shard in set(shard for shard, frag in shardfrags):
            try:
                doc_counts[shard] = self.filehandler.length(
                    os.path.join(self.datapath, shard)
                )
            except OSError as e:
                # unreadable at setup (after the retry layer gave up):
                # quarantine and contribute zero docs — the run starts on
                # the readable shards instead of dying in setup
                self._quarantine(shard, e)
                if shard not in self.setup_quarantined:
                    self.setup_quarantined.append(shard)
                doc_counts[shard] = 0
        return doc_counts

    def setup(self):
        if self.is_setup:
            return
        super().setup()
        self._build_docset()
        self.lcg_state = self.seed + self.rank

    def _build_docset(self):
        """(Re)build the owned docset spans. Shards listed in
        ``setup_quarantined`` are forced to zero docs even when their
        length probe succeeds now — called once at setup, and again on
        resume when the checkpoint carries setup-quarantined shards that
        have healed since (the restored walk position is only valid over
        the docset it was saved against)."""
        # dataset name = final path component (robust to trailing slashes)
        pathsplit = (self.datapath, "")
        while len(pathsplit[1]) == 0:
            pathsplit = os.path.split(pathsplit[0])
        pardir, dataset = pathsplit
        self.dataset = dataset

        # Fragment ownership: every shard file splits into worldsize
        # fragments; the global fragment list (ordered by shard, then
        # fragment) is cut into worldsize contiguous spans.
        shards = self._walk_shards()
        n = len(shards)
        shardfrags = [
            (shards[i // self.worldsize], i % self.worldsize)
            for i in range(self.rank * n, (self.rank + 1) * n)
        ]

        doc_counts = self._load_doc_counts(pardir, dataset, shardfrags)
        # setup-time quarantine (this run's probe failures plus any
        # persisted from the checkpoint): zero-doc spans, always
        for shard in self.setup_quarantined:
            if shard in doc_counts:
                doc_counts[shard] = 0

        # Aggregate owned fragments into per-shard [min, max] doc spans.
        spans = {}
        for shard, frag in shardfrags:
            ndocs = doc_counts[shard]
            doc_start = (ndocs * frag) // self.worldsize
            doc_end = (ndocs * frag + ndocs) // self.worldsize - 1  # inclusive
            if shard not in spans:
                spans[shard] = [doc_start, doc_end]
            else:
                spans[shard][0] = min(spans[shard][0], doc_start)
                spans[shard][1] = max(spans[shard][1], doc_end)

        self.docset = []
        doccount = 0
        for shardid, (min_d, max_d) in spans.items():
            self.docset.append((shardid, min_d, max_d))
            doccount += max_d - min_d + 1
        self._len = doccount

        if self.verbose:
            logger.info(
                f"    Worker {self.rank} ingested {len(shardfrags)} shard "
                f"fragments from {dataset}"
            )

        # Shard-file order shuffle, distinct per worker.
        random.Random(self.seed + self.rank).shuffle(self.docset)

    # -- doc addressing ---------------------------------------------------

    def _get_docid(self, i):
        """Map a worker-global doc index to (shard, span length, span min)."""
        cur = 0
        assert i <= self._len, (
            f"You have requested an illegal doc index {i}, "
            f"docset length is {self._len}"
        )
        for shardid, min_d, max_d in self.docset:
            cur += max_d - min_d + 1
            if cur > i:
                return shardid, max_d - min_d + 1, min_d

    def _random_map_docid(self, size):
        """Next within-span shuffled index from the LCG walk; states >= size
        are skipped, giving a bijection over [0, size)."""
        m = 2 ** math.ceil(math.log2(size))  # power-of-2 modulus
        a = 5
        c = (self.rank + self.seed) * 2 + 1
        state = self.lcg_state
        while True:
            state = (a * state + c) % m
            if state < size:
                return state

    # -- iteration --------------------------------------------------------

    def _open_if_new(self, path, newpath, reader):
        if newpath != path:
            del reader
            if self.verbose:
                logger.info(f"Worker {self.rank} opening new file {newpath}")
            return newpath, self.filehandler.open(newpath)
        return path, reader

    def _emit_chunk(self, j, doc, n_chunks):
        """Chunk j of the doc, with bos on the first chunk and the delimiter
        closing the last; accounts for the bos offset in slicing. Chunks are
        int64 numpy arrays end-to-end (see ShardFileHandler.slice)."""
        start_index = j * self.chunksize
        n_pull = self.chunksize
        if self.bos is not None:
            if j == 0:
                n_pull -= 1
            else:
                start_index -= 1
        chunk = self.filehandler.slice(doc, start_index, n_pull)
        self.tokens_seen += len(chunk)
        parts = [np.asarray(chunk, dtype=np.int64)]
        if self.bos is not None and j == 0:
            parts.insert(0, np.array([self.bos], dtype=np.int64))
        if j == n_chunks - 1:
            parts.append(np.array([self.eos], dtype=np.int64))
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def _quarantine(self, shardid, err) -> None:
        """Mark ``shardid`` unreadable: its reads kept failing after the
        retry layer gave up. The shard's remaining docs are skipped (the
        run survives); the set rides in the state_dict. If EVERY owned
        shard is quarantined the stream would go silent — that is fatal."""
        if shardid not in self.quarantined_shards:
            self.quarantined_shards.append(shardid)
            logger.error(
                "Worker %d quarantining shard %s after exhausted retries "
                "(%s); its remaining documents will be skipped",
                self.rank,
                shardid,
                err,
            )
        owned = set(s for s, _, _ in self.docset)
        if owned and owned.issubset(set(self.quarantined_shards)):
            # typed: under a SamplingDataset this degrades the MIX
            # (corpus quarantined, weights renormalized over survivors)
            # instead of killing the run; a single-corpus pipeline still
            # surfaces it fatally
            raise CorpusUnreadableError(
                f"worker {self.rank}: all {len(owned)} owned shards are "
                f"quarantined; no readable data remains"
            ) from err

    def __iter__(self):
        if not self.is_setup:
            self.setup()
        docset_offset = self.docset_index
        lcg_offset = self.lcg_state
        # chunks of the offset doc already emitted before checkpoint; they
        # are replayed at the END of the epoch so the epoch stays exact
        residual_chunks = self.chunk_index + 1
        ndocs = self._len
        if ndocs == 0:
            raise CorpusUnreadableError(
                f"worker {self.rank}: no readable documents in "
                f"{self.datapath}"
                + (
                    f" ({len(self.quarantined_shards)} shard(s) "
                    f"quarantined: {self.quarantined_shards})"
                    if self.quarantined_shards
                    else ""
                )
            )
        path = ""
        reader = None
        first_pass = True
        while True:
            # Epoch boundary (and resume start): re-probe quarantined
            # shards. A transient storage outage outlasting the retry
            # budget must not exclude data for the rest of a multi-week
            # run — each new pass retries the shard once (one bounded
            # retry cycle per epoch if it is still dead, after which it
            # re-quarantines). Shards unreadable at SETUP contribute zero
            # docs for the whole run (their docset spans are fixed); only
            # iteration-time quarantine heals here.
            if self.quarantined_shards and not first_pass:
                logger.info(
                    "Worker %d re-probing %d quarantined shard(s) at the "
                    "epoch boundary: %s",
                    self.rank,
                    len(self.quarantined_shards),
                    self.quarantined_shards,
                )
                self.quarantined_shards = [
                    s
                    for s in self.quarantined_shards
                    if s in self.setup_quarantined
                ]
            first_pass = False
            for i in range(ndocs):
                doc_index = (docset_offset + i) % ndocs
                if doc_index == 0:
                    self.epochs_seen += 1
                self.docset_index = doc_index
                shardid, docrange, mindoc = self._get_docid(doc_index)

                doclcg = self._random_map_docid(docrange)
                if shardid in self.quarantined_shards:
                    self.lcg_state = doclcg  # keep the walk deterministic
                    continue
                docid = doclcg + mindoc
                try:
                    newpath = os.path.join(self.datapath, shardid)
                    path, reader = self._open_if_new(path, newpath, reader)
                    doc = self.filehandler.get(reader, docid, self.drop)
                except OSError as e:
                    # retries exhausted inside the handler: quarantine the
                    # shard and move on instead of killing the run
                    path, reader = "", None
                    self._quarantine(shardid, e)
                    self.lcg_state = doclcg
                    continue
                if len(doc) == 0:
                    continue
                doclen = len(doc) + 1 if self.bos is None else len(doc) + 2
                if doclen >= self.min_length:
                    n_chunks = math.ceil(doclen / self.chunksize)
                    for j in range(n_chunks):
                        if i == 0 and j < residual_chunks:
                            continue  # skipped now, replayed at epoch end
                        self.chunk_index = j
                        if j == n_chunks - 1:
                            self.docs_seen += 1
                            self.percent_seen = (
                                self.docs_seen * 100 / (self._len + 1e-9)
                            )
                        yield self._emit_chunk(j, doc, n_chunks)

                self.lcg_state = doclcg

            # Epoch complete except the skipped residual chunks: rewind to
            # the offset doc and emit them now.
            self.docset_index = docset_offset
            self.lcg_state = lcg_offset
            shardid, docrange, mindoc = self._get_docid(docset_offset)
            docid = self._random_map_docid(docrange) + mindoc
            if shardid in self.quarantined_shards:
                continue
            try:
                newpath = os.path.join(self.datapath, shardid)
                path, reader = self._open_if_new(path, newpath, reader)
                doc = self.filehandler.get(reader, docid, self.drop)
            except OSError as e:
                path, reader = "", None
                self._quarantine(shardid, e)
                continue
            if len(doc) == 0:
                continue
            doclen = len(doc) + 1 if self.bos is None else len(doc) + 2
            if doclen >= self.min_length:
                n_chunks = math.ceil(doclen / self.chunksize)
                for j in range(residual_chunks):
                    self.chunk_index = j
                    yield self._emit_chunk(j, doc, n_chunks)

    def load_state_dict(self, state_dicts, sharded_input=False):
        self.setup()
        if self.load_worldsize != self.worldsize:
            # a real diagnostic, not a bare assert: this is where an
            # illegal elastic resume lands when the checkpoint-side
            # topology gate was bypassed (direct pipeline construction,
            # hand-copied loader state)
            raise RuntimeError(
                f"StreamingDocDataset does not support rescaling: the "
                f"checkpoint holds {self.load_worldsize} reader state(s) "
                f"but this world expects {self.worldsize}. A bare reader "
                f"resumes only at its save world size — wrap it in "
                f"ScalableShardDataset (n_logical_shards divisible by "
                f"every process x worker product you may restart on, "
                f"the production get_data_loader layout), or restart "
                f"with the original world size."
            )
        d = self.dataset
        # this run's own setup-time probe failures, before the restored
        # state overwrites the attribute
        own_setup_q = set(self.setup_quarantined)
        out = super().load_state_dict(state_dicts, sharded_input)
        assert d == self.dataset, (
            f"Dataset mismatch: checkpoint contains {self.dataset}, expected {d}"
        )
        # the restored state replaced both quarantine lists wholesale;
        # THIS run's own setup-probe failures must merge back in (the
        # live docset already zeroes them, and dropping them here would
        # persist a checkpoint without them — re-creating the shifted-
        # walk bug one save later, when that checkpoint is resumed on a
        # healed shard)
        ckpt_setup_q = set(self.setup_quarantined)
        merged = own_setup_q | ckpt_setup_q
        ckpt_added = merged - own_setup_q
        newly_broken = own_setup_q - ckpt_setup_q
        self.setup_quarantined = sorted(merged)
        for s in self.setup_quarantined:
            if s not in self.quarantined_shards:
                self.quarantined_shards.append(s)
        if newly_broken:
            # the reverse direction is NOT fixable: these shards held
            # readable docs when the checkpoint was written, and this
            # run cannot serve them — the restored docset_index/
            # lcg_state index a shrunk docset, so the walk position is
            # approximate (documents near the boundary may replay or
            # skip for the rest of the epoch). Say so loudly instead of
            # resuming as if nothing changed.
            logger.warning(
                "Worker %d: %d shard(s) readable at checkpoint time "
                "failed this run's setup probe (%s); their documents "
                "are unavailable and the restored stream position is "
                "approximate for the rest of the epoch",
                self.rank,
                len(newly_broken),
                sorted(newly_broken),
            )
        if ckpt_added:
            # the checkpoint carries setup-quarantined shards this run's
            # probe succeeded on (healed since the save): the saved
            # docset_index/lcg_state walk a docset where those shards
            # had zero docs, so rebuild ours to match — a heal must wait
            # for the natural epoch boundary, not shift the walk under a
            # restored position. (Own-only shards need no rebuild: the
            # docset built at setup already zeroes them.)
            logger.info(
                "Worker %d re-applying %d setup-quarantined shard(s) from "
                "the checkpoint before the docset rebuild: %s",
                self.rank,
                len(ckpt_added),
                sorted(ckpt_added),
            )
            self._build_docset()
        return out


class ScalableShardDataset(WrapperDataset):
    """Rescaling layer: the wrapped reader is cloned into ``n_logical_shards``
    logical workers (rank i of n_logicals); this physical rank owns
    n/worldsize of them and draws one document at a time from a logical
    chosen ∝ docs-remaining, so data seen this epoch stays un-revisited
    under any future world size dividing n_logicals."""

    def __init__(
        self,
        dataset: StreamingDocDataset,
        delimiter_token: Any,
        n_logical_shards: int = 2048,
        verbose=False,
    ):
        super().__init__(dataset)
        assert n_logical_shards % self.worldsize == 0, (
            f"World size {self.worldsize} must divide n_logical_shards "
            f"{n_logical_shards} evenly"
        )
        assert (
            n_logical_shards > 0
        ), f"n_logical_shards {n_logical_shards} must be a positive integer"
        self.total_shards = n_logical_shards
        self.delimiter = delimiter_token
        self.verbose = verbose

        self.data: List[StreamingDocDataset] = []
        self.logicals_owned: List[int] = []
        self.n_logicals = 0
        self.n_docs_remaining: List[int] = []
        self.generator: Optional[np.random.Generator] = None

        # Position state is meaningful only at unchanged world size; on
        # rescale it is dropped with the other state_params.
        self.current_reader = None
        self.logical_shard_states = None
        self.g_state = None

        self.state_params = ["current_reader", "g_state"]
        self.reshard_params = ["n_docs_remaining", "logical_shard_states"]

    def setup(self):
        if self.is_setup:
            return
        StatefulDataset.setup(self)
        if self.total_shards % self.worldsize != 0:
            # checked at setup (not just __init__) because the loader's
            # worker inflation multiplies worldsize after construction
            raise RuntimeError(
                f"n_logical_shards {self.total_shards} is not divisible "
                f"by the loader world size {self.worldsize} (= process "
                f"count x num_workers): logical shards cannot be "
                f"partitioned evenly. Adjust --logical_shards or "
                f"--num_workers (or the host count) so the product "
                f"divides {self.total_shards}."
            )
        logicals = list(range(self.total_shards))
        self.logicals_owned = shard_partition(logicals, self.rank, self.worldsize)
        self.n_logicals = self.total_shards // self.worldsize
        assert (
            len(self.logicals_owned) == self.n_logicals
        ), "(world size * num workers) does not divide logical shards evenly"

        for i in range(self.n_logicals):
            shard = deepcopy(self.dataset)
            shard.worldsize = self.total_shards
            shard.load_worldsize = self.total_shards
            shard.rank = self.logicals_owned[i]
            shard.local_worldsize = 1
            shard.datapath = self.datapath
            shard.verbose = self.rank == 0
            self.data.append(shard)
            if self.verbose:
                logger.info(
                    f"Worker {self.rank} assembled logical shard "
                    f"{self.logicals_owned[i]}, {i + 1} of {self.n_logicals}"
                )
        for d in self.data:
            d.setup()
        self.n_docs_remaining = [d._len for d in self.data]
        self.generator = np.random.default_rng(self.rank)

    def _sample_logical(self) -> int:
        weights = np.asarray(self.n_docs_remaining, dtype=np.float64)
        total = weights.sum()
        assert total > 0, f"No documents detected in {self.datapath}"
        return int(self.generator.choice(len(weights), p=weights / total))

    def __iter__(self):
        self.setup()
        data = [iter(d) for d in self.data]
        while True:
            if self.current_reader is not None:
                ind = self.current_reader
            else:
                ind = self._sample_logical()
            self.current_reader = ind
            # stream one full document from the chosen logical
            out = next(data[ind])
            while out[-1] != self.delimiter:
                yield out
                out = next(data[ind])
            self.current_reader = None
            self.n_docs_remaining[ind] -= 1
            if sum(self.n_docs_remaining) == 0:
                # epoch boundary: reset counts and the sampling stream
                self.n_docs_remaining = [d._len for d in self.data]
                self.generator = np.random.default_rng(self.rank)
            yield out

    def state_dict(self):
        self.setup()
        self.g_state = self.generator.bit_generator.state
        self.logical_shard_states = [d.state_dict() for d in self.data]
        return StatefulDataset.state_dict(self)

    def load_state_dict(self, state_dicts, sharded_input=False):
        self.setup()
        sharded_dicts = StatefulDataset.load_state_dict(
            self, state_dicts, sharded_input
        )
        if self.g_state is not None:
            self.generator = np.random.default_rng()
            self.generator.bit_generator.state = self.g_state
        for i in range(self.n_logicals):
            self.data[i].load_state_dict([self.logical_shard_states[i]], True)
        return sharded_dicts


class SamplingDataset(WrapperDataset):
    """Multi-dataset weighted mixing by tokens seen: each draw picks the
    subdataset furthest below its target share and holds it through a full
    document (delimiter detection).

    Production hardening (docs/dataloader.md "Multi-corpus mixing"):

    - resume state pairs subdatasets by corpus NAME, not list index —
      adding/reordering a corpus cannot silently misassign another
      corpus's walk position; a changed corpus SET is an actionable
      error unless ``allow_corpus_change`` accepts it;
    - corpus-granular fault isolation: when a corpus's whole reader
      stack dies (``CorpusUnreadableError`` — every owned shard
      quarantined), the corpus is quarantined and the mix degrades
      gracefully (weights renormalized over survivors) instead of
      killing the run; survivor epoch boundaries re-arm a quarantined
      corpus. Dropping below ``min_live_corpora`` live corpora (or
      losing the last corpus) raises ``CorpusLossError``, which the
      entry points classify as the ``corpus_loss`` supervisor exit;
    - a max-held-chunks guard releases the document hold if a
      subdataset emits chunks whose last token never equals the
      delimiter (zero-length/undelimited tail documents previously
      pinned ``current_iterator`` forever, starving every other corpus).
    """

    def __init__(
        self,
        datapath: str,
        dataset: Union[ScalableShardDataset, StreamingDocDataset],
        delimiter_token: Any,
        datasets=None,
        weights=None,
        min_live_corpora: int = 1,
        allow_corpus_change: bool = False,
        max_held_chunks: int = 4096,
        verbose=False,
    ):
        super().__init__(dataset)
        self.datapath = datapath
        self.delimiter = delimiter_token
        self.verbose = verbose
        # auto-discovery is SORTED: os.listdir order is filesystem-
        # dependent, and different ranks/hosts disagreeing on corpus
        # order would diverge the mix (and misassign per-index state)
        self.datasets = (
            list(datasets)
            if datasets is not None
            else sorted(
                f
                for f in os.listdir(datapath)
                if not os.path.isfile(os.path.join(datapath, f)) and "meta" not in f
            )
        )
        assert len(self.datasets) > 0, "You must specify at least one dataset"
        assert len(set(self.datasets)) == len(self.datasets), (
            f"Duplicate corpus names in {self.datasets}: resume state "
            f"pairs by name and requires unique names"
        )

        if weights is not None:
            assert len(weights) == len(self.datasets), (
                f"Number of oversample weights {len(weights)} must match "
                f"number of datasets {len(self.datasets)}"
            )
            for w in weights:
                assert w > 0, f"Sampling rate {w} must be positive"
        self.weights = [1] * len(self.datasets) if weights is None else weights
        self.weights = [w / sum(self.weights) for w in self.weights]

        self.min_live_corpora = max(1, int(min_live_corpora))
        self.allow_corpus_change = bool(allow_corpus_change)
        self.max_held_chunks = max(1, int(max_held_chunks))

        self.tokens_seen = [0] * len(self.datasets)
        self.current_iterator = -1
        # corpora whose reader stack died (by NAME); persisted so a
        # resume knows the mix was degraded — the iterator re-probes
        # them at start and at survivor epoch boundaries
        self.quarantined_corpora: List[str] = []
        self.state_params = [
            "tokens_seen",
            "current_iterator",
            "quarantined_corpora",
        ]
        # survivor epoch clock at quarantine time (name -> clock); None
        # = eligible for an immediate re-probe (fresh iterator /
        # resume). Not persisted: a restart is a natural re-probe point.
        self._rearm_snapshot: dict = {}
        self._held_chunks = 0
        self._starve_warned: Set[str] = set()
        self._pending = None  # (corpus index, first chunk) from a re-arm

    def setup(self):
        if self.is_setup:
            return
        StatefulDataset.setup(self)
        self.data = []
        for i, d in enumerate(self.datasets):
            clone = deepcopy(self.dataset)
            clone.datapath = os.path.join(self.datapath, d)
            clone.rank = self.rank
            clone.worldsize = self.worldsize
            clone.local_worldsize = self.local_worldsize
            self.data.append(clone)
            if self.verbose:
                logger.info(
                    f"Worker {self.rank} assembled subdataset iterator for "
                    f"{d}, {i + 1} of {len(self.datasets)}"
                )
        for d in self.data:
            d.setup()

    # -- fault isolation ---------------------------------------------------

    def _live_indices(self) -> List[int]:
        return [
            i
            for i, n in enumerate(self.datasets)
            if n not in self.quarantined_corpora
        ]

    def _survivor_epochs(self) -> int:
        """Monotonic epoch clock over the LIVE corpora: advances as their
        readers wrap epochs (per logical shard under
        ScalableShardDataset). Quarantined corpora re-probe when this
        clock has advanced past their quarantine snapshot — the corpus-
        level analog of the shard-level epoch-boundary re-probe."""
        total = 0
        for i in self._live_indices():
            sub = self.data[i]
            readers = getattr(sub, "data", None)
            if isinstance(readers, list) and readers:
                total += sum(getattr(r, "epochs_seen", 0) for r in readers)
            else:
                total += getattr(sub, "epochs_seen", 0)
        return total

    def _injected_kill(self, i: int) -> bool:
        """``corpus_kill`` fault site (resilience/faults.py): simulates
        every owned shard of one corpus dying at once. Filter:
        ``corpus=`` (substring). Consulted at document boundaries and
        re-probe attempts; production runs never fire it."""
        from fms_fsdp_tpu.resilience.faults import fire_fault

        return fire_fault("corpus_kill", corpus=self.datasets[i]) is not None

    def _quarantine_corpus(self, i: int, err) -> None:
        """Quarantine corpus ``i``: the mix degrades to the survivors
        with weights renormalized, or — below the ``min_live_corpora``
        floor — raises the classified ``CorpusLossError``."""
        name = self.datasets[i]
        if name not in self.quarantined_corpora:
            self.quarantined_corpora.append(name)
            self._rearm_snapshot[name] = self._survivor_epochs()
            _MIX_EVENTS["corpus_quarantined"] += 1
        live = self._live_indices()
        if len(live) < self.min_live_corpora:
            raise CorpusLossError(
                f"worker {self.rank}: corpus {name!r} is unreadable and "
                f"only {len(live)} of {len(self.datasets)} corpora remain "
                f"live — below min_live_corpora={self.min_live_corpora} "
                f"(quarantined: {self.quarantined_corpora}). Restore the "
                f"corpus data and restart (the supervisor classifies "
                f"this exit as corpus_loss), or lower --min_live_corpora "
                f"to accept training on the surviving mix."
            ) from err
        wsum = sum(self.weights[j] for j in live)
        renorm = {
            self.datasets[j]: round(self.weights[j] / wsum, 4) for j in live
        }
        logger.error(
            "worker %d: corpus %r quarantined (%s); mix degrades to %d "
            "live corpora with weights renormalized over survivors: %s "
            "— survivor epoch boundaries re-probe and re-arm it if it "
            "heals",
            self.rank,
            name,
            err,
            len(live),
            renorm,
        )

    def _maybe_rearm(self, data) -> None:
        """Re-probe quarantined corpora whose snapshot the survivor
        epoch clock has passed (at most one re-arm per document
        boundary). A successful probe pulls the corpus's next chunk —
        stashed in ``_pending`` and served immediately, so the probe
        never skips data."""
        if not self.quarantined_corpora:
            return
        clock = self._survivor_epochs()
        for name in list(self.quarantined_corpora):
            snap = self._rearm_snapshot.get(name)
            if snap is not None and clock <= snap:
                continue
            i = self.datasets.index(name)
            if self._injected_kill(i):
                self._rearm_snapshot[name] = clock
                continue
            it = iter(self.data[i])
            try:
                out = next(it)
            except CorpusUnreadableError:
                self._rearm_snapshot[name] = clock
                continue
            data[i] = it
            self.quarantined_corpora.remove(name)
            self._rearm_snapshot.pop(name, None)
            _MIX_EVENTS["corpus_rearmed"] += 1
            logger.info(
                "worker %d: corpus %r healed; re-armed into the mix "
                "(weights restored to their configured shares)",
                self.rank,
                name,
            )
            self._pending = (i, out)
            return

    def _select_corpus(self) -> int:
        """Most-undertarget LIVE subdataset next (ties -> higher index),
        with weights renormalized over the live set."""
        while True:
            live = self._live_indices()
            total = sum(self.tokens_seen[j] for j in live) + 1e-9
            wsum = sum(self.weights[j] for j in live)
            choice = max(
                (self.weights[j] / wsum - self.tokens_seen[j] / total, j)
                for j in live
            )[1]
            if self._injected_kill(choice):
                self._quarantine_corpus(
                    choice,
                    CorpusUnreadableError(
                        f"injected corpus_kill: {self.datasets[choice]}"
                    ),
                )
                continue
            return choice

    def __iter__(self):
        self.setup()
        data = [iter(d) for d in self.data]
        self._held_chunks = 0
        self._pending = None
        # restored quarantine: eligible for an immediate re-probe (a
        # restart is a natural heal point)
        for name in self.quarantined_corpora:
            self._rearm_snapshot.setdefault(name, None)
        while True:
            out = None
            if self.current_iterator == -1:
                # document boundary: re-probe quarantined corpora, then
                # pick the most-undertarget live subdataset
                self._maybe_rearm(data)
                if self._pending is not None:
                    i, out = self._pending
                    self._pending = None
                else:
                    i = self._select_corpus()
                self.current_iterator = i
            else:
                i = self.current_iterator
            if out is None:
                try:
                    out = next(data[i])
                except CorpusUnreadableError as e:
                    # the corpus's reader stack is dead: quarantine it
                    # (or raise CorpusLossError below the floor) and
                    # release any mid-document hold — the partial
                    # document is lost with its corpus
                    self._quarantine_corpus(i, e)
                    self.current_iterator = -1
                    self._held_chunks = 0
                    continue
            self.tokens_seen[i] += len(out)
            self._held_chunks += 1
            if out[-1] == self.delimiter:
                self.current_iterator = -1
                self._held_chunks = 0
            elif self._held_chunks >= self.max_held_chunks:
                # starvation guard: a chunk stream that never closes
                # with the delimiter (zero-length/undelimited tail
                # document, or a delimiter mismatch between pipeline
                # layers) would otherwise pin current_iterator forever
                # and starve every other corpus
                name = self.datasets[i]
                if name not in self._starve_warned:
                    self._starve_warned.add(name)
                    logger.warning(
                        "worker %d: corpus %r emitted %d chunks without "
                        "a document delimiter (%r); releasing the "
                        "document hold so other corpora keep serving — "
                        "check the corpus's delimiter/eos configuration",
                        self.rank,
                        name,
                        self._held_chunks,
                        self.delimiter,
                    )
                self.current_iterator = -1
                self._held_chunks = 0
            yield out

    # -- state (keyed by corpus name) --------------------------------------

    def state_dict(self):
        self.setup()
        out = {
            self.statename("sample_iterator_states"): [
                d.state_dict() for d in self.data
            ],
            # the pairing key for resume: state follows the corpus NAME,
            # never the config-list index
            self.statename("corpus_names"): list(self.datasets),
            self.statename("mix_weights"): list(self.weights),
        }
        out.update(StatefulDataset.state_dict(self))
        return out

    def _pair_by_name(self, saved_names: List[str]) -> dict:
        """live index -> saved index for corpora present in both; gate
        corpus-set changes behind ``allow_corpus_change``."""
        added = [n for n in self.datasets if n not in saved_names]
        removed = [n for n in saved_names if n not in self.datasets]
        if (added or removed) and not self.allow_corpus_change:
            raise RuntimeError(
                f"worker {self.rank}: the corpus set changed across the "
                f"resume — checkpoint has {saved_names}, this run mixes "
                f"{self.datasets} (added: {added or 'none'}, removed: "
                f"{removed or 'none'}). Per-corpus mix state pairs by "
                f"name and cannot follow a changed set. Restart with "
                f"--datasets={','.join(saved_names)}, or pass "
                f"--allow_corpus_change=True to accept it (removed "
                f"corpora drop their stream position; new corpora start "
                f"cold at zero tokens_seen)."
            )
        if added or removed:
            logger.warning(
                "worker %d: resuming across a corpus-set change "
                "(allow_corpus_change=True): added %s start cold, "
                "removed %s drop their stream position",
                self.rank,
                added or "none",
                removed or "none",
            )
        return {
            li: saved_names.index(n)
            for li, n in enumerate(self.datasets)
            if n in saved_names
        }

    def load_state_dict(self, state_dicts, sharded_input=False):
        self.setup()
        sharded_dicts = StatefulDataset.load_state_dict(
            self, state_dicts, sharded_input
        )
        states_key = self.statename("sample_iterator_states")
        names_key = self.statename("corpus_names")
        saved_names = sharded_dicts[0].get(names_key)
        legacy = saved_names is None
        if legacy:
            # pre-name-keyed checkpoint: index pairing is all there is,
            # and it is only sound when the corpus COUNT matches
            if any(
                len(sd.get(states_key, [])) != len(self.data)
                for sd in sharded_dicts
            ):
                raise RuntimeError(
                    f"worker {self.rank}: legacy (un-named) mix state "
                    f"holds a different corpus count than this run's "
                    f"{len(self.data)} — index pairing would misassign "
                    f"corpus state. Restart with the save-time "
                    f"--datasets list."
                )
            logger.warning(
                "worker %d: mix state predates name-keyed resume; "
                "pairing %d corpora by index — verify the --datasets "
                "order matches the save",
                self.rank,
                len(self.data),
            )
            saved_names = list(self.datasets)
        pair = self._pair_by_name(list(saved_names))

        saved_weights = sharded_dicts[0].get(self.statename("mix_weights"))
        if saved_weights is not None and any(
            si < len(saved_weights)
            and abs(float(saved_weights[si]) - float(self.weights[li])) > 1e-9
            for li, si in pair.items()
        ):
            # a weight change is LEGAL (docs/dataloader.md): the token-
            # share controller simply steers toward the new targets —
            # but say so, because the realized mix shifts from here
            logger.info(
                "worker %d: mixing weights changed across the resume "
                "(saved %s -> live %s); the token-share controller "
                "steers toward the new targets from here, no stream "
                "position is lost",
                self.rank,
                [round(float(w), 4) for w in saved_weights],
                [round(float(w), 4) for w in self.weights],
            )

        same_size = self.load_worldsize == self.worldsize
        if same_size:
            # the base class restored the scalar state in SAVED order;
            # remap it onto the live corpus order by name
            saved_tokens = list(self.tokens_seen)
            saved_current = self.current_iterator
            saved_quarantined = list(self.quarantined_corpora or [])
            self.tokens_seen = [
                (
                    saved_tokens[pair[li]]
                    if li in pair and pair[li] < len(saved_tokens)
                    else 0
                )
                for li in range(len(self.datasets))
            ]
            self.current_iterator = -1
            if saved_current is not None and 0 <= saved_current < len(
                saved_names
            ):
                held = saved_names[saved_current]
                if held in self.datasets:
                    self.current_iterator = self.datasets.index(held)
                else:
                    logger.warning(
                        "worker %d: the checkpoint held corpus %r "
                        "mid-document but it is not in this run's mix; "
                        "releasing the hold",
                        self.rank,
                        held,
                    )
            self.quarantined_corpora = [
                n for n in saved_quarantined if n in self.datasets
            ]
        else:
            # rescale: scalar mix state was dropped by the base class —
            # the token-share controller re-converges to the target mix
            # from zero while every corpus's document walk reshards
            # exactly (zero replays) through its own sub-state below
            self.tokens_seen = [0] * len(self.datasets)
            self.current_iterator = -1
            self.quarantined_corpora = []
            logger.info(
                "worker %d: elastic rescale (%d -> %d loader ranks) "
                "resets per-corpus tokens_seen; the mix re-converges to "
                "its target shares (document walks reshard exactly)",
                self.rank,
                self.load_worldsize,
                self.worldsize,
            )
        self._rearm_snapshot = {n: -1 for n in self.quarantined_corpora}

        for li, si in pair.items():
            subdata = self.data[li]
            subdata.load_worldsize = self.load_worldsize
            subdata.load_state_dict(
                [sd[states_key][si] for sd in sharded_dicts],
                True,
            )
        return sharded_dicts
