from fms_fsdp_tpu.data.buffering import (
    BufferDataset,
    CheckpointDataset,
    PreloadBufferDataset,
    PreprocessDataset,
)
from fms_fsdp_tpu.data.handlers import ArrowHandler, AutoHandler, ParquetHandler
from fms_fsdp_tpu.data.loader import (
    StatefulDataLoader,
    causal_lm,
    get_data_loader,
    get_dummy_loader,
    loader_mix_stats,
    parse_data_args,
)
from fms_fsdp_tpu.data.stateful import StatefulDataset, WrapperDataset
from fms_fsdp_tpu.data.streaming import (
    CorpusLossError,
    CorpusUnreadableError,
    SamplingDataset,
    ScalableShardDataset,
    StreamingDocDataset,
)

__all__ = [
    "ArrowHandler",
    "AutoHandler",
    "ParquetHandler",
    "BufferDataset",
    "CheckpointDataset",
    "CorpusLossError",
    "CorpusUnreadableError",
    "PreloadBufferDataset",
    "PreprocessDataset",
    "SamplingDataset",
    "ScalableShardDataset",
    "StatefulDataLoader",
    "StatefulDataset",
    "StreamingDocDataset",
    "WrapperDataset",
    "causal_lm",
    "get_data_loader",
    "get_dummy_loader",
    "loader_mix_stats",
    "parse_data_args",
]
