"""Synthetic arrow-corpus generation for tests and evidence capture.

Writes real .arrow shard files plus the ``meta/combined_counts.csv`` the
streaming pipeline's sampling layer reads — the same on-disk layout the
reference's dataset tooling produces (ref:fms_fsdp/utils/dataset_utils.py
Streaming_Doc_Dataset file discovery + counts csv) — so everything from
file handlers through shard rescaling runs exactly as it would on a real
corpus.

Documents are noisy counter sequences: from a random start, each next
token is previous+1 (mod the vocab band) with probability ``1 - noise``,
else uniform. The +1 transition is learnable by any LM in a few hundred
steps, so perplexity measurably falls after training — which is what the
arrow-streaming -> training -> eval evidence leg needs to show. Token
values stay inside [1, vocab) so the pipeline's eos/bos specials (0 by
default) never collide with corpus tokens.
"""

import os

import numpy as np


def build_arrow_corpus(
    root,
    *,
    n_shards: int = 3,
    docs_per_shard: int = 60,
    doc_len: int = 90,
    vocab: int = 256,
    noise: float = 0.1,
    seed: int = 11,
    dataset_name: str = "dataset_1",
):
    """Write ``n_shards`` arrow files of counter-structured docs under
    ``root/<dataset_name>/`` with the counts csv; returns ``str(root)``."""
    import pyarrow as pa

    root = str(root)
    schema = pa.schema([pa.field("tokens", pa.uint32())])
    os.makedirs(os.path.join(root, dataset_name), exist_ok=True)
    rng = np.random.default_rng(seed)
    rows = []
    for s in range(n_shards):
        path = os.path.join(root, dataset_name, f"shard_{s}.arrow")
        with pa.ipc.new_file(path, schema) as w:
            for _ in range(docs_per_shard):
                start = rng.integers(1, vocab)
                steps = np.arange(doc_len, dtype=np.uint32)
                counter = (start - 1 + steps) % (vocab - 1) + 1
                flip = rng.random(doc_len) < noise
                noise_tok = rng.integers(1, vocab, size=doc_len)
                doc = np.where(flip, noise_tok, counter).astype(np.uint32)
                w.write(pa.record_batch([pa.array(doc)], schema))
        rows.append(
            (
                f"/{dataset_name}/shard_{s}.arrow",
                docs_per_shard,
                docs_per_shard * doc_len,
            )
        )
    os.makedirs(os.path.join(root, "meta"), exist_ok=True)
    with open(os.path.join(root, "meta", "combined_counts.csv"), "w") as f:
        f.write("dataset/filename,documents,tokens\n")
        for name, d, t in rows:
            f.write(f"{name},{d},{t}\n")
    return root
