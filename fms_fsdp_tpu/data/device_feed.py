"""Host -> device feed: sharded global arrays with background prefetch.

The reference hides data-pipeline latency behind torch DataLoader worker
processes; on TPU the equivalent is (a) a background host thread running
the (pure-python) pipeline, and (b) forming each batch directly into a
``jax.Array`` sharded over the mesh's data axes so the jitted step consumes
it with zero reshuffling. Double-buffering (prefetch >= 1) overlaps the
next batch's host work and H2D copy with the current device step
(SURVEY.md §7 hard part 5).
"""

import queue
import threading
import time
from typing import Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from fms_fsdp_tpu.parallel.sharding import batch_pspec, resolve_spec


def to_global_batch(batch, mesh: Mesh):
    """Assemble a (tuple of) process-local numpy batch into global sharded
    jax.Arrays laid out per batch_pspec over the mesh."""

    def convert(arr):
        arr = np.asarray(arr)
        # global shape: concatenation of per-process batches on axis 0
        gshape = (arr.shape[0] * jax.process_count(),) + arr.shape[1:]
        sharding = NamedSharding(mesh, resolve_spec(batch_pspec(), gshape, mesh))
        return jax.make_array_from_process_local_data(sharding, arr, gshape)

    if isinstance(batch, tuple):
        return tuple(convert(a) for a in batch)
    return convert(batch)


class DeviceFeed:
    """Iterator over device-resident sharded batches with prefetch.

    The host thread pulls from ``loader`` (the stateful pipeline) and stages
    arrays onto devices; the consumer gets batches that are already placed.
    ``prefetch=0`` degrades to synchronous operation (useful in tests).
    """

    def __init__(self, loader, mesh: Mesh, prefetch: int = 2, registry=None):
        self.loader = loader
        self.mesh = mesh
        self.prefetch = prefetch
        # optional obs MetricRegistry: the feed thread attributes its own
        # time (pipeline pull vs device staging) so a data-bound window
        # is diagnosable — was the host pipeline slow, or the H2D copy?
        # The consumer-visible data_wait phase is timed by the train
        # loop's iterator wrapper, NOT here (no double counting).
        self.registry = registry

    def _rec(self, name: str, seconds: float) -> None:
        if self.registry is not None:
            self.registry.counter(name).add(seconds)

    def __iter__(self) -> Iterator:
        if self.prefetch <= 0:
            for batch in self.loader:
                t0 = time.monotonic()
                staged = to_global_batch(batch, self.mesh)
                self._rec("feed.stage_s", time.monotonic() - t0)
                yield staged
            return

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        err = []

        def worker():
            try:
                it = iter(self.loader)
                while True:
                    t0 = time.monotonic()
                    try:
                        batch = next(it)
                    except StopIteration:
                        # clean exhaustion: sentinel the consumer awake
                        # (it treats None with no recorded error as end
                        # of stream); without this a finite loader left
                        # the consumer blocked in q.get() forever. The
                        # stop.is_set() return below deliberately does
                        # NOT put a sentinel — its consumer has already
                        # left, and a put on a full queue would block
                        # this thread for the process lifetime.
                        q.put(None)
                        return
                    t1 = time.monotonic()
                    if stop.is_set():
                        return
                    staged = to_global_batch(batch, self.mesh)
                    t2 = time.monotonic()
                    self._rec("feed.pipeline_s", t1 - t0)
                    self._rec("feed.stage_s", t2 - t1)
                    if self.registry is not None:
                        self.registry.counter("feed.batches").add()
                    q.put(staged)
            except BaseException as e:  # surface pipeline errors to consumer
                err.append(e)
                q.put(None)

        t = threading.Thread(target=worker, daemon=True, name="device-feed")
        t.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()
