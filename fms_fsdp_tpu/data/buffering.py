"""Pipeline post-processing layers: packing, shuffling, mapping, and
auto-checkpointing (ref:fms_fsdp/utils/dataset_utils.py:463-794).
"""

import logging
import os
import time
from typing import Any, Callable, List

import numpy as np

from fms_fsdp_tpu.data.stateful import StatefulDataset, WrapperDataset
from fms_fsdp_tpu.utils.ckpt_paths import (
    get_latest,
    is_step_ckp,
    safe_listdir,
    step_number,
)

_EMPTY = np.empty(0, dtype=np.int64)

logger = logging.getLogger(__name__)


class PreprocessDataset(WrapperDataset):
    """Apply a map function to every item of the wrapped stream."""

    def __init__(self, dataset: StatefulDataset, aug_fn: Callable):
        super().__init__(dataset)
        self.aug_fn = aug_fn

    def __iter__(self):
        dataset = iter(self.dataset)
        while True:
            yield self.aug_fn(next(dataset))


class BufferDataset(WrapperDataset):
    """Pack variable-length sequences into fixed ``seq_len`` lines.

    Greedy packing: pull until the line would overrun, split hard
    (``pack_hard``) or pad out. Optionally injects bos at line start and eos
    at line end, avoiding duplicates; a split token displaced by an injected
    eos is pushed back onto the buffer. Rescales by dropping buffer state.
    """

    def __init__(
        self,
        dataset: StatefulDataset,
        seq_len: int,
        pack_hard: bool,
        bos_token=None,
        eos_token=None,
        pad_token=None,
    ):
        super().__init__(dataset)
        self.len = seq_len
        self.buffer: List = []
        self.bos = bos_token
        self.eos = eos_token
        self.pad = pad_token
        self.pack_hard = pack_hard
        if not pack_hard:
            assert (
                pad_token is not None
            ), "Error: if using pads, you must supply a pad_token"
        self.state_params = ["buffer"]

    def _assemble_line(self, iterable, length, buffer):
        """Return (line, leftover_buffer). All segments are int64 numpy
        arrays — per-token list surgery was a top loader hotspot; the
        concatenation count per line is the same as the old list version
        but each is one vectorized copy."""
        cat = np.concatenate
        new = _EMPTY
        while len(buffer) + len(new) < length:
            buffer = cat([buffer, new]) if len(new) else buffer
            new = np.asarray(next(iterable), dtype=np.int64)

        if self.bos is not None and (len(buffer) == 0 or buffer[0] != self.bos):
            buffer = cat([[self.bos], buffer])

        if len(buffer) >= length:
            # split the overfull buffer at the line boundary
            out = buffer[:length].copy()
            buffer = buffer[length:]
            if self.eos is not None and out[-1] != self.eos:
                buffer = cat([out[-1:], buffer])  # displaced token survives
                out[-1] = self.eos
            buffer = cat([buffer, new])
        elif self.pack_hard:
            # pack in as much of the new sequence as fits
            buffer = cat([buffer, new])
            out = buffer[:length].copy()
            buffer = buffer[length:]
            if self.eos is not None and out[-1] != self.eos:
                buffer = cat([out[-1:], buffer])
                out[-1] = self.eos
        else:
            # pad out the line
            if self.eos is not None and buffer[-1] != self.eos:
                buffer = cat([buffer, [self.eos]])
            if self.pad is not None:
                out = cat([buffer, np.full(length - len(buffer), self.pad)])
            else:
                out = buffer
            buffer = new
        return out, buffer

    def __iter__(self):
        dataset = iter(self.dataset)
        while True:
            # tolerate list-typed buffer state from older checkpoints
            buffer = np.asarray(self.buffer, dtype=np.int64)
            out, buffer = self._assemble_line(dataset, self.len, buffer)
            self.buffer = buffer
            yield out


class PreloadBufferDataset(WrapperDataset):
    """Shuffle via a ``window_size`` reservoir: fill the buffer, then emit a
    uniformly random slot and refill it from the stream. Consecutive inputs
    emerge ~window_size steps apart in expectation. Buffers reshard; an
    oversized buffer (after down-scaling) drains back to window_size by
    popping the tail into emitted slots."""

    def __init__(self, dataset: StatefulDataset, window_size: int):
        super().__init__(dataset)
        assert window_size > 1, (
            f"Window size {window_size} must be greater than 1 for shuffling"
            " to occur"
        )
        self.window_size = window_size
        self.g_state = None
        self.generator = np.random.default_rng(self.rank)
        self.buffer: List[List[Any]] = []
        self.buffer_size = 0
        self.state_params = ["g_state"]
        self.reshard_params = ["buffer"]

    def _pad_buffer(self):
        if self.buffer_size < self.window_size:
            self.buffer += [[]] * (self.window_size - self.buffer_size)

    def __iter__(self):
        dataset = iter(self.dataset)
        while True:
            self._pad_buffer()
            # grow an undersized buffer
            if self.buffer_size < self.window_size:
                self.buffer[self.buffer_size] = next(dataset)
                self.buffer_size += 1

            i = int(self.generator.integers(self.buffer_size))
            out = self.buffer[i]
            if self.buffer_size > self.window_size:
                # shrink an oversized (post-rescale) buffer
                self.buffer[i] = self.buffer[self.buffer_size - 1]
                self.buffer_size -= 1
            else:
                self.buffer[i] = next(dataset)
            yield out

    def state_dict(self):
        self.g_state = self.generator.bit_generator.state
        self.buffer = self.buffer[: self.buffer_size]
        return super().state_dict()

    def load_state_dict(self, state_dicts, sharded_input=False):
        sharded_dicts = super().load_state_dict(state_dicts, sharded_input)
        if self.g_state is not None:
            self.generator = np.random.default_rng()
            self.generator.bit_generator.state = self.g_state
        self.buffer_size = len(self.buffer)
        return sharded_dicts


class CheckpointDataset(WrapperDataset):
    """Auto-save the full pipeline state every ``interval`` complete batches
    to ``<save_path>/checkpoints/step_N_ckp/loader_state_<rank>.pkl``, and
    auto-load the newest valid checkpoint at setup (preferring the save
    directory — a restarted job resumes itself; an external load path
    resets the step count)."""

    # advertises the empty-path fresh-start marker contract to
    # Checkpointer.load (load_from_path("") = "the trainer resolved a
    # from-scratch start"); loaders without this flag are left untouched
    # exactly as before the marker existed
    supports_fresh_start = True

    def __init__(
        self,
        dataset: StatefulDataset,
        load_path: str,
        interval: int,
        steps_per_batch: int = 1,
        save_path: str = "",
        extra_roots=(),
    ):
        super().__init__(dataset)
        self.interval = interval
        self.spb = steps_per_batch
        load_path = os.path.join(load_path, "checkpoints")
        if len(save_path) == 0:
            save_path = load_path
        else:
            save_path = os.path.join(save_path, "checkpoints")
        self.load_path = load_path
        self.path = save_path
        # additional checkpoint roots the trainer may resolve a restart
        # from (the async manager's fast-local tier): a step dir under
        # any of these is a trainer-resolved restore, same as the
        # primary roots (see load_from_path)
        self.extra_roots = tuple(extra_roots)
        self.step = 0
        self.ministep = 0

    def setup(self):
        if not self.is_setup:
            super().setup()
            if not getattr(self, "_explicit_restore", False):
                self.load_from_path(self.load_path)

    def __iter__(self):
        self.setup()
        dataset = iter(self.dataset)
        while True:
            out = next(dataset)
            # count (and save) eagerly before yielding: without worker
            # prefetch running ahead, a lazy post-yield count would delay
            # the interval-N save until batch N+1 is pulled
            self.ministep += 1
            if self.ministep == self.spb:
                self.ministep = 0
                self.step += 1
                if self.step % self.interval == 0:
                    newpath = os.path.join(self.path, f"step_{self.step}_ckp")
                    self.save_to_path(newpath)
            yield out

    def report(self, msg):
        if self.rank == 0:
            print(msg)

    def _validate_ckp_path(self, path: str, verbose: bool = False):
        """Resolve path to the newest checkpoint dir CONTAINING loader
        state, or ''. Scans step dirs newest-first rather than inspecting
        only the single newest: the checkpoints folder interleaves model
        checkpoints (Checkpointer.save) with loader auto-saves, and when
        their step numbering drifts (see get_data_loader's
        batch_multiplier note) the newest dir may be model-only."""
        if not os.path.exists(path) or len(os.listdir(path)) == 0:
            if verbose:
                self.report(
                    f"  Dataset: No valid checkpoint detected at {path}, "
                    "dataset starting from scratch."
                )
            return ""
        candidates = sorted(
            (
                os.path.join(path, x)
                for x in os.listdir(path)
                if is_step_ckp(x)
            ),
            key=step_number,
            reverse=True,
        )
        for cand in candidates:
            if os.path.isdir(cand) and any(
                "loader" in x for x in safe_listdir(cand)
            ):
                if verbose:
                    self.report(f"Checkpoint detected at {cand}")
                self.step = step_number(cand)
                return cand
        if verbose:
            self.report(
                f"  Dataset: Checkpoints exist under {path} but none "
                "contain dataset state. Dataset starting from scratch."
            )
        return ""

    def save_to_path(self, path: str):
        self.report(f"Saving dataset to {path}")
        start = time.time()
        super().save_to_path(path)
        self.report(
            f"Dataset successfully saved to {path}! "
            f"Save time: {time.time() - start}"
        )

    def load_from_path(self, path: str):
        # The trainer's RESOLVED restart checkpoint — a step dir inside
        # this run's own checkpoints folder, holding loader state — is
        # authoritative: the model restored exactly from it, and the
        # auto-detect below would instead pick the NEWEST loader state
        # on disk, which after a fallback resume (torn newest
        # checkpoint skipped, supervisor relaunch after a mid-commit
        # kill) can be a loader auto-save AHEAD of the model — silently
        # skipping every batch between the two positions (model@N +
        # loader@M>N). Restoring the committed pair keeps the resumed
        # stream exactly the committed stream (scripts/chaos_soak.py
        # pins bit-identity on this). The flag suppresses setup()'s
        # auto-load, which would clobber the explicit restore.
        #
        # An EMPTY path is the same contract's other verdict: the
        # trainer resolved NO restorable checkpoint (every candidate
        # torn, quarantined, or absent) and the model starts from
        # scratch — so must the walk THROUGH THIS RUN'S OWN SAVE DIR.
        # Loader auto-saves land there on the dataset's own interval
        # cadence whether or not the model commit ever completed, so
        # without this marker setup()'s auto-load would resume the walk
        # from a stale auto-save under fresh model state (model@0 +
        # loader@N), shifting the consumed stream of the entire
        # restarted run. An EXTERNAL load root (resuming_dataset=True,
        # continued pretraining) is still honored below: that loader
        # state belongs to a different run and cannot outrun this run's
        # model state.
        if path == "":
            self._explicit_restore = True
            self.setup()
            self.report(
                "  Dataset: trainer resolved a from-scratch start; "
                "ignoring loader auto-saves in the save directory."
            )
            if os.path.abspath(self.load_path) != os.path.abspath(self.path):
                self._load_external()
            return
        resolved = os.path.abspath(path)
        own_roots = {
            os.path.abspath(p)
            for p in (self.path, self.load_path, *self.extra_roots)
        }
        if (
            os.path.dirname(resolved) in own_roots
            and os.path.isdir(resolved)
            and any("loader" in x for x in safe_listdir(resolved))
        ):
            # flag BEFORE setup(): it suppresses setup()'s auto-load, and
            # setup() must run first — it propagates the (possibly
            # worker-inflated) rank/worldsize down the wrapper stack,
            # which the restore's shard partitioning depends on (the
            # auto-load path gets this ordering from __iter__)
            self._explicit_restore = True
            self.setup()
            self.step = step_number(resolved)
            start = time.time()
            self.dataset.load_from_path(resolved)
            self.report(
                f"Dataset checkpoint loaded (trainer-resolved "
                f"{resolved})! Load time: {time.time() - start}"
            )
            return
        # a checkpoint in the save dir means this job restarted: prefer it
        save_path = self._validate_ckp_path(self.path, False)
        if len(save_path) > 0:
            self.report(
                f"  Dataset: Detected a checkpoint in the save directory "
                f"{save_path}. Restoring from this checkpoint."
            )
            start = time.time()
            self.dataset.load_from_path(save_path)
            self.report(
                f"Dataset checkpoint loaded! Load time: {time.time() - start}"
            )
            return
        self._load_external()

    def _load_external(self):
        """Restore from the EXTERNAL load root (``resuming_dataset=True``
        continued pretraining): that loader state belongs to a different
        run, so the step count restarts. Shared by the auto-detect path
        and the fresh-start marker (which only rules out this run's own
        save dir)."""
        load_path = self._validate_ckp_path(self.load_path, True)
        if len(load_path) == 0:
            return
        self.step = 0  # external checkpoint: step restarts
        start = time.time()
        self.dataset.load_from_path(load_path)
        self.report(f"Dataset checkpoint loaded! Load time: {time.time() - start}")
