"""Pipeline assembly + batching loader
(ref:fms_fsdp/utils/dataloader_utils.py:17-163).

``StatefulDataLoader`` replaces torch's DataLoader: it stacks pipeline
outputs into numpy batches and realizes ``num_workers`` as in-process
logical sub-ranks — each worker is a full pipeline clone whose
(rank, worldsize) is inflated exactly the way the reference inflates them
inside torch worker processes (worldsize *= num_workers,
rank = rank * num_workers + worker_id, ref:dataset_utils.py:108-119), with
batches drawn round-robin across workers (torch IterableDataset semantics).

With ``num_workers > 1`` each worker pipeline runs in its own thread
feeding a bounded queue, and batches are popped round-robin — real host
parallelism for the compute-bound tokenizing (ParquetHandler) path,
since HF tokenizers' rust encode releases the GIL (the reference gets
the same from torch DataLoader worker *processes*,
ref:dataloader_utils.py:144-146). Round-robin popping preserves the
exact single-threaded batch order, and loader checkpointing keeps the
reference's worker semantics: CheckpointDataset auto-saves inside each
worker at its own batch boundaries (which, as with torch's prefetching
workers, may run slightly ahead of consumption).
Async device prefetch happens at the device-feed layer (device_feed.py),
which is where TPU step-time overlap actually comes from.
"""

import queue
import threading
from copy import deepcopy
from typing import Callable, List

import numpy as np

from fms_fsdp_tpu.data.buffering import (
    BufferDataset,
    CheckpointDataset,
    PreloadBufferDataset,
    PreprocessDataset,
)
from fms_fsdp_tpu.data.handlers import ArrowHandler, AutoHandler, ParquetHandler
from fms_fsdp_tpu.data.streaming import (
    SamplingDataset,
    ScalableShardDataset,
    StreamingDocDataset,
)

_HANDLER_BUILDERS = {
    "arrow": lambda cfg: ArrowHandler(cfg.col_name),
    "hf_parquet": lambda cfg: ParquetHandler(cfg.tokenizer_path, cfg.col_name),
    "auto": lambda cfg: AutoHandler(cfg.tokenizer_path, cfg.col_name),
}


def causal_lm(data_seq, prompt_len: int = 1):
    """Shift for next-token prediction: input = seq[:-1], label = seq[1:]
    with the first ``prompt_len`` labels masked to -100
    (ref:dataloader_utils.py:24-33)."""
    data_seq = np.asarray(data_seq, dtype=np.int32)
    t = data_seq[1:].copy()
    data_seq = data_seq[:-1]
    t[:prompt_len] = -100
    return data_seq, t


def _stack(items):
    """Stack a list of items (arrays or tuples of arrays) into a batch."""
    if isinstance(items[0], tuple):
        return tuple(np.stack(field) for field in zip(*items))
    return np.stack(items)


class StatefulDataLoader:
    """Batching iterator over one or more pipeline clones ("workers").

    Exposes the wrapped pipeline as ``.dataset`` (parity with
    ``torch_loader.dataset`` access in the reference checkpoint path,
    ref:checkpointing_utils.py:275-278); with num_workers > 1 each worker
    owns an inflated rank and saves its own ``loader_state_<rank>`` file.
    """

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        num_workers: int = 1,
        prefetch_batches: int = 2,
    ):
        self.batch_size = batch_size
        self.num_workers = max(1, num_workers)
        self.prefetch_batches = max(1, prefetch_batches)
        self._threads: List[threading.Thread] = []
        # per-iterator-generation stop event: set-and-abandoned on
        # shutdown, REPLACED (never cleared) when a new iterator spawns
        # workers — a straggler thread that outlives a 5s join timeout
        # still sees ITS generation's event set and can never race a
        # successor over the same pipeline object
        self._stop = threading.Event()
        # one lock per worker, held while that worker advances its
        # pipeline: external state reads (state_dict/save_to_path — the
        # speculator path checkpoints a live loader) grab all locks and
        # observe every pipeline at a batch boundary
        self._locks = [threading.Lock() for _ in range(self.num_workers)]
        if self.num_workers == 1:
            self.pipelines = [dataset]
        else:
            self.pipelines = []
            for worker_id in range(self.num_workers):
                clone = dataset if worker_id == self.num_workers - 1 else deepcopy(
                    dataset
                )
                clone.local_worldsize = self.num_workers
                clone.worldsize = clone.worldsize * self.num_workers
                clone.rank = self.num_workers * clone.rank + worker_id
                self.pipelines.append(clone)

    @property
    def dataset(self):
        return self.pipelines[0]

    @staticmethod
    def _worker_loop(pipeline, out_q, lock, stop, batch_size):
        """Produce stacked batches from one worker pipeline into its queue.
        Exceptions are forwarded so the consumer re-raises them. The lock
        is held only while advancing the pipeline (never across the
        blocking put — a full queue must not deadlock a state reader).

        Static on purpose: a bound-method target would keep the loader
        strongly referenced from the thread registry, so an abandoned
        iterator's loader could never be garbage collected and __del__
        could never signal its threads to exit."""
        try:
            it = iter(pipeline)
            while not stop.is_set():
                with lock:
                    items = [next(it) for _ in range(batch_size)]
                batch = _stack(items)
                while not stop.is_set():
                    try:
                        out_q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 — forwarded to consumer
            # bounded, stop-aware put: the consumer may already be gone
            # (peer worker's error triggered shutdown, or the generator
            # was abandoned) — never hang a dying worker on a full queue
            while not stop.is_set():
                try:
                    out_q.put(e, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def shutdown(self):
        """Stop worker threads (idempotent). Call before inspecting
        pipeline state externally while an iterator is live."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []

    def __del__(self):
        self._stop.set()  # reachable: worker threads don't reference self

    def __iter__(self):
        # Top-level setup propagates the (possibly worker-inflated)
        # rank/worldsize down the wrapper stack before any layer iterates.
        for p in self.pipelines:
            p.setup()
        if self.num_workers == 1:
            it = iter(self.pipelines[0])
            while True:
                yield _stack([next(it) for _ in range(self.batch_size)])

        self.shutdown()
        self._stop = threading.Event()  # fresh generation (see __init__)
        queues = [
            queue.Queue(maxsize=self.prefetch_batches) for _ in self.pipelines
        ]
        self._threads = [
            threading.Thread(
                target=self._worker_loop,
                args=(p, q, lk, self._stop, self.batch_size),
                daemon=True,
            )
            for p, q, lk in zip(self.pipelines, queues, self._locks)
        ]
        for t in self._threads:
            t.start()
        w = 0
        while True:
            batch = queues[w].get()
            if isinstance(batch, BaseException):
                self.shutdown()
                raise batch
            yield batch
            w = (w + 1) % self.num_workers

    # -- state (delegates to every worker pipeline) -----------------------

    class _AllLocks:
        def __init__(self, locks):
            self.locks = locks

        def __enter__(self):
            for lk in self.locks:
                lk.acquire()

        def __exit__(self, *exc):
            for lk in reversed(self.locks):
                lk.release()

    def state_dict(self) -> List[dict]:
        with self._AllLocks(self._locks):
            return [p.state_dict() for p in self.pipelines]

    def load_state_dict(self, state_dicts, sharded_input=False):
        with self._AllLocks(self._locks):
            for p in self.pipelines:
                p.load_state_dict(state_dicts, sharded_input)

    def save_to_path(self, path: str):
        with self._AllLocks(self._locks):
            for p in self.pipelines:
                p.save_to_path(path)

    def load_from_path(self, path: str):
        with self._AllLocks(self._locks):
            for p in self.pipelines:
                p.load_from_path(path)


class SteadyCounter:
    """Dummy stream: incrementing counts of constant length l mod vocab v
    (ref:dataloader_utils.py:41-54). Used for benchmarking / dummy runs."""

    def __init__(self, l: int, v: int):
        self.i = 0
        self.l = l
        self.v = v

    def __iter__(self):
        while True:
            out = np.arange(self.i, self.i + self.l, dtype=np.int32) % self.v
            yield out, out
            self.i += self.l


class _SimpleLoader:
    """Minimal batching loader for non-stateful iterables (dummy data)."""

    def __init__(self, dataset, batch_size: int):
        self.dataset = dataset
        self.batch_size = batch_size

    def __iter__(self):
        it = iter(self.dataset)
        while True:
            yield _stack([next(it) for _ in range(self.batch_size)])


def get_dummy_loader(cfg, rank, world_size):
    return _SimpleLoader(SteadyCounter(cfg.seq_length, cfg.vocab_size), cfg.batch_size)


def get_data_loader(cfg, rank, world_size, postprocess=None):
    """Build the full 7-layer pipeline
    (ref:dataloader_utils.py:60-146): streaming docs -> logical-shard
    rescaling -> weighted multi-dataset sampling -> fixed-length packing ->
    reservoir shuffle -> tensorize -> task postprocess -> auto-checkpoint,
    wrapped in the batching loader.
    """
    if postprocess is None:
        postprocess = [causal_lm]

    datasets, weights = parse_data_args(cfg.datasets, cfg.weights)

    droplist = [
        int(x.strip()) for x in cfg.strip_tokens.split(",") if len(x.strip()) > 0
    ]
    droplist = droplist + [cfg.bos_token, cfg.eos_token, cfg.bol_token, cfg.eol_token]
    assert cfg.file_type in _HANDLER_BUILDERS, (
        f"File type {cfg.file_type} is not recognized "
        f"({list(_HANDLER_BUILDERS.keys())})"
    )
    filehandler = _HANDLER_BUILDERS[cfg.file_type](cfg)

    data = StreamingDocDataset(
        cfg.data_path,
        rank,
        world_size,
        filehandler,
        cfg.eos_token,
        bos_token=cfg.bos_token,
        strip_tokens=set(droplist),
        min_length=3,
        seed=cfg.seed,
    )
    data = ScalableShardDataset(
        data,
        cfg.eos_token,
        n_logical_shards=cfg.logical_shards,
    )
    data = SamplingDataset(
        cfg.data_path,
        data,
        cfg.eos_token,
        datasets=datasets,
        weights=weights,
        verbose=(rank == 0),
    )
    # +1 token so the causal shift still yields seq_length-long examples
    data = BufferDataset(
        data,
        cfg.seq_length if causal_lm not in postprocess else cfg.seq_length + 1,
        bos_token=cfg.bol_token,
        eos_token=cfg.eol_token,
        pack_hard=True,
    )
    data = PreloadBufferDataset(data, 10000)

    data = PreprocessDataset(data, lambda x: np.asarray(x, dtype=np.int32))
    for p in postprocess:
        data = PreprocessDataset(data, p)

    data = CheckpointDataset(
        data,
        cfg.ckpt_load_path if cfg.resuming_dataset else cfg.ckpt_save_path,
        cfg.checkpoint_interval,
        cfg.batch_size,
        cfg.ckpt_save_path,
    )
    return StatefulDataLoader(
        data, batch_size=cfg.batch_size, num_workers=cfg.num_workers
    )


def rebatch(loader, local_batch: int, batch_size: int):
    """Concatenate per-rank batches (of ``batch_size`` rows) into
    process-local device batches of ``local_batch`` rows — the bridge from
    the reference's per-GPU batch_size to a per-process multi-chip batch."""
    if local_batch == batch_size:
        return loader

    def gen():
        it = iter(loader)
        n = local_batch // batch_size
        while True:
            parts = [next(it) for _ in range(n)]
            if isinstance(parts[0], tuple):
                yield tuple(np.concatenate(f) for f in zip(*parts))
            else:
                yield np.concatenate(parts)

    return gen()


def parse_data_args(datas, weights):
    """csv strings -> lists (ref:dataloader_utils.py:149-163)."""

    def splitstrip(x):
        if isinstance(x, str):
            return [item.strip() for item in x.split(",")]
        elif isinstance(x, (list, tuple)):
            return list(x)
        elif isinstance(x, (int, float, complex)):
            return [x]
        else:
            raise ValueError(f"arg input {x} cannot be parsed.")

    datas = splitstrip(datas)
    weights = [float(x) for x in splitstrip(weights)]
    return datas, weights
