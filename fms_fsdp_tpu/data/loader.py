"""Pipeline assembly + batching loader
(ref:fms_fsdp/utils/dataloader_utils.py:17-163).

``StatefulDataLoader`` replaces torch's DataLoader: it stacks pipeline
outputs into numpy batches and realizes ``num_workers`` as in-process
logical sub-ranks — each worker is a full pipeline clone whose
(rank, worldsize) is inflated exactly the way the reference inflates them
inside torch worker processes (worldsize *= num_workers,
rank = rank * num_workers + worker_id, ref:dataset_utils.py:108-119), with
batches drawn round-robin across workers (torch IterableDataset semantics).

With ``num_workers > 1`` each worker pipeline runs in its own thread
(``worker_mode="thread"``, default) or its own forked process
(``worker_mode="process"``) feeding a bounded queue, with batches popped
round-robin — real host parallelism for the compute-bound tokenizing
(ParquetHandler) path. Threads rely on HF tokenizers' rust encode
releasing the GIL; the process mode matches the reference's
unconditional process-level parallelism (torch DataLoader worker
processes, ref:dataloader_utils.py:144-146) and is immune to GIL
contention from pure-Python pipeline stages. Round-robin popping
preserves the exact single-threaded batch order, and loader
checkpointing keeps the reference's worker semantics: CheckpointDataset
auto-saves inside each worker at its own batch boundaries (which, as
with torch's prefetching workers, may run ahead of consumption by up to
``num_workers * (prefetch_batches + 1)`` batches; explicit state
captures log the skew — see ``_log_skew``).
Async device prefetch happens at the device-feed layer (device_feed.py),
which is where TPU step-time overlap actually comes from.
"""

import multiprocessing
import os
import pickle
import queue
import threading
import time
import traceback
from copy import deepcopy
from typing import Callable, List

import numpy as np

from fms_fsdp_tpu.data.buffering import (
    BufferDataset,
    CheckpointDataset,
    PreloadBufferDataset,
    PreprocessDataset,
)
from fms_fsdp_tpu.data.handlers import ArrowHandler, AutoHandler, ParquetHandler
from fms_fsdp_tpu.data.streaming import (
    CorpusLossError,
    SamplingDataset,
    ScalableShardDataset,
    StreamingDocDataset,
)

_HANDLER_BUILDERS = {
    "arrow": lambda cfg: ArrowHandler(cfg.col_name),
    "hf_parquet": lambda cfg: ParquetHandler(cfg.tokenizer_path, cfg.col_name),
    "auto": lambda cfg: AutoHandler(cfg.tokenizer_path, cfg.col_name),
}


def causal_lm(data_seq, prompt_len: int = 1):
    """Shift for next-token prediction: input = seq[:-1], label = seq[1:]
    with the first ``prompt_len`` labels masked to -100
    (ref:dataloader_utils.py:24-33)."""
    data_seq = np.asarray(data_seq, dtype=np.int32)
    t = data_seq[1:].copy()
    data_seq = data_seq[:-1]
    t[:prompt_len] = -100
    return data_seq, t


def _stack(items):
    """Stack a list of items (arrays or tuples of arrays) into a batch."""
    if isinstance(items[0], tuple):
        return tuple(np.stack(field) for field in zip(*items))
    return np.stack(items)


def _pickle_safe(e: BaseException) -> BaseException:
    """An exception that survives the mp pickle boundary: the original if
    it round-trips, else a RuntimeError carrying its formatted traceback."""
    try:
        pickle.loads(pickle.dumps(e))
        return e
    except Exception:
        return RuntimeError(
            "".join(traceback.format_exception(type(e), e, e.__traceback__))
        )


def _service_commands(pipeline, cmd) -> bool:
    """Drain pending parent commands at a worker-process batch boundary.
    Returns True on a stop command (the worker must exit). Every non-stop
    command gets exactly one reply — a state-op failure replies with the
    exception instead of leaving the parent blocked on recv()."""
    while cmd.poll():
        op, arg = cmd.recv()
        if op == "stop":
            return True
        try:
            if op == "state_dict":
                reply = pipeline.state_dict()
            elif op == "save_to_path":
                pipeline.save_to_path(arg)
                reply = "ok"
            elif op == "load_state_dict":
                pipeline.load_state_dict(*arg)
                reply = "ok"
            elif op == "load_from_path":
                pipeline.load_from_path(arg)
                reply = "ok"
            else:
                reply = RuntimeError(f"unknown loader command {op!r}")
        except BaseException as e:  # noqa: BLE001 — forwarded to parent
            reply = _pickle_safe(e)
        cmd.send(reply)
    return False


class LoaderWorkerError(RuntimeError):
    """A loader worker died and the restart budget could not absorb it.
    Typed so the entry points' classified-exit wrapper
    (resilience/exits.py) exits with the ``loader_death`` registry code
    instead of the generic 1 — the run supervisor restarts a dead data
    path differently from an anomaly abort or a lost slice."""


def _worker_fault(widx: int, produced_count: int):
    """``loader_worker`` fault site, shared by both worker modes: fired
    after each produced batch (filters: worker=, batch=). ``action=exit``
    hard-kills the process (the OOM/preemption analog, process mode);
    the default raises, exercising the forwarded-exception path."""
    from fms_fsdp_tpu.resilience.faults import fire_fault

    params = fire_fault("loader_worker", worker=widx, batch=produced_count)
    if params is None:
        return
    if params.get("action") == "exit":
        from fms_fsdp_tpu.resilience.exits import EXIT_CODES

        # the registry's loader_death code, NOT the old hardcoded 3:
        # that collided with the slice-loss code, so a dead loader
        # worker classified as a lost slice (resilience/exits.py)
        os._exit(int(params.get("code", EXIT_CODES["loader_death"])))
    raise RuntimeError(
        f"injected loader worker crash (worker {widx}, "
        f"batch {produced_count})"
    )


def _process_worker_loop(pipeline, out_q, cmd, batch_size, produced, widx=0):
    """One worker pipeline in a forked process: produce stacked batches
    into ``out_q``, service state commands from the parent at batch
    boundaries (the process-mode analog of thread mode's per-worker
    lock), and forward exceptions to the consumer. ``produced`` is a
    shared counter of batches built, read by the parent for save-skew
    accounting (and continued across worker restarts)."""
    import signal

    try:
        # the trainer's PreemptionGuard SIGTERM handler (which only sets
        # a flag) is inherited across fork — restore the default so
        # shutdown()'s terminate() actually terminates a stuck worker
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
    except (ValueError, OSError):
        pass
    try:
        pipeline.setup()
        it = iter(pipeline)
        while True:
            if _service_commands(pipeline, cmd):
                out_q.cancel_join_thread()
                return
            items = [next(it) for _ in range(batch_size)]
            batch = _stack(items)
            with produced.get_lock():
                produced.value += 1
            _worker_fault(widx, produced.value)
            while True:
                if _service_commands(pipeline, cmd):
                    out_q.cancel_join_thread()
                    return
                try:
                    out_q.put(batch, timeout=0.1)
                    break
                except queue.Full:
                    continue
    except BaseException as e:  # noqa: BLE001 — forwarded to consumer
        payload = _pickle_safe(e)
        sent = False
        while True:  # keep servicing state commands until told to stop
            try:
                if _service_commands(pipeline, cmd):
                    out_q.cancel_join_thread()
                    return
            except (EOFError, OSError, BrokenPipeError):
                return  # parent is gone
            if not sent:
                try:
                    out_q.put(payload, timeout=0.1)
                    sent = True
                except queue.Full:
                    continue
            time.sleep(0.05)


class StatefulDataLoader:
    """Batching iterator over one or more pipeline clones ("workers").

    Exposes the wrapped pipeline as ``.dataset`` (parity with
    ``torch_loader.dataset`` access in the reference checkpoint path,
    ref:checkpointing_utils.py:275-278); with num_workers > 1 each worker
    owns an inflated rank and saves its own ``loader_state_<rank>`` file.
    """

    # forwards the empty-path fresh-start marker to its pipelines
    # (get_data_loader always builds CheckpointDataset outermost, which
    # implements it; see data/buffering.py)
    supports_fresh_start = True

    # shutdown escalation budget (seconds): cooperative stop -> join ->
    # SIGTERM -> join -> SIGKILL -> reap. Class attrs so tests (and
    # latency-sensitive callers) can tighten the bounds.
    STOP_JOIN_S = 5.0
    TERM_JOIN_S = 2.0
    KILL_JOIN_S = 2.0

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        num_workers: int = 1,
        prefetch_batches: int = 2,
        worker_mode: str = "thread",
        max_worker_restarts: int = 2,
        restart_backoff_s: float = 1.0,
    ):
        assert worker_mode in ("thread", "process"), worker_mode
        self.batch_size = batch_size
        self.num_workers = max(1, num_workers)
        self.prefetch_batches = max(1, prefetch_batches)
        self.worker_mode = worker_mode
        # a worker that dies from a transient error is restarted with
        # exponential backoff up to this many times (per worker, per
        # iterator generation) before the error reaches the consumer
        self.max_worker_restarts = max(0, max_worker_restarts)
        self.restart_backoff_s = restart_backoff_s
        self._threads: List[threading.Thread] = []
        self._procs: list = []
        self._cmds: list = []
        self._procs_started = False
        # save-skew accounting: batches built per worker vs consumed by
        # the trainer (explicit state captures log the difference)
        self._produced: list = [[0] for _ in range(self.num_workers)]
        self._consumed = [0] * self.num_workers
        # per-iterator-generation stop event: set-and-abandoned on
        # shutdown, REPLACED (never cleared) when a new iterator spawns
        # workers — a straggler thread that outlives a 5s join timeout
        # still sees ITS generation's event set and can never race a
        # successor over the same pipeline object
        self._stop = threading.Event()
        # one lock per worker, held while that worker advances its
        # pipeline: external state reads (state_dict/save_to_path — the
        # speculator path checkpoints a live loader) grab all locks and
        # observe every pipeline at a batch boundary
        self._locks = [threading.Lock() for _ in range(self.num_workers)]
        if self.num_workers == 1:
            self.pipelines = [dataset]
        else:
            self.pipelines = []
            for worker_id in range(self.num_workers):
                clone = dataset if worker_id == self.num_workers - 1 else deepcopy(
                    dataset
                )
                clone.local_worldsize = self.num_workers
                clone.worldsize = clone.worldsize * self.num_workers
                clone.rank = self.num_workers * clone.rank + worker_id
                self.pipelines.append(clone)

    @property
    def dataset(self):
        return self.pipelines[0]

    @staticmethod
    def _worker_loop(pipeline, out_q, lock, stop, batch_size, produced, widx=0):
        """Produce stacked batches from one worker pipeline into its queue.
        Exceptions are forwarded so the consumer re-raises them. The lock
        is held only while advancing the pipeline (never across the
        blocking put — a full queue must not deadlock a state reader).

        Static on purpose: a bound-method target would keep the loader
        strongly referenced from the thread registry, so an abandoned
        iterator's loader could never be garbage collected and __del__
        could never signal its threads to exit."""
        try:
            it = iter(pipeline)
            while not stop.is_set():
                with lock:
                    items = [next(it) for _ in range(batch_size)]
                    produced[0] += 1
                _worker_fault(widx, produced[0])
                batch = _stack(items)
                while not stop.is_set():
                    try:
                        out_q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 — forwarded to consumer
            # bounded, stop-aware put: the consumer may already be gone
            # (peer worker's error triggered shutdown, or the generator
            # was abandoned) — never hang a dying worker on a full queue
            while not stop.is_set():
                try:
                    out_q.put(e, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def shutdown(self):
        """Stop worker threads/processes (idempotent), within bounded
        time. Escalation for a process worker that ignores the stop
        command (wedged mid-batch, never reaches its command-servicing
        boundary): cooperative stop -> join -> SIGTERM -> join -> SIGKILL
        -> reap — the parent never hangs on a stuck worker. Call before
        inspecting pipeline state externally while an iterator is live."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self.STOP_JOIN_S)
        self._threads = []
        for c in self._cmds:
            if c is None:
                continue
            try:
                c.send(("stop", None))
            except (OSError, BrokenPipeError, ValueError):
                pass
        for p in self._procs:
            if p is None:  # spawn loop interrupted mid-way
                continue
            p.join(timeout=self.STOP_JOIN_S)
            if p.is_alive():
                p.terminate()
                p.join(timeout=self.TERM_JOIN_S)
                if p.is_alive():
                    p.kill()
                    # reap: SIGKILL is not ignorable, so this join only
                    # waits out the kernel's teardown (bounded as a
                    # belt-and-braces measure; a daemon zombie would
                    # otherwise linger until interpreter exit)
                    p.join(timeout=self.KILL_JOIN_S)
        self._procs, self._cmds = [], []

    def __del__(self):
        self._stop.set()  # reachable: worker threads don't reference self
        for c in getattr(self, "_cmds", []):
            if c is None:
                continue
            try:
                c.send(("stop", None))
            except (OSError, BrokenPipeError, ValueError):
                pass

    def _workers_alive(self) -> bool:
        return bool(self._procs) and any(
            p is not None and p.is_alive() for p in self._procs
        )

    def _log_skew(self, op: str):
        """ADVICE r3: prefetching workers run ahead of consumption, so a
        state capture includes up to num_workers*(prefetch_batches+1)
        batches the trainer never saw — a resume skips them. Surface the
        actual skew whenever state is captured from live workers."""
        produced = [
            p.value if hasattr(p, "value") else p[0] for p in self._produced
        ]
        skew = [p - c for p, c in zip(produced, self._consumed)]
        if any(s > 0 for s in skew):
            # the inflated worker rank // num_workers recovers the data
            # rank, so merged multi-host logs attribute each skew list
            rank = self.pipelines[0].rank // self.num_workers
            print(
                f"loader {op} [rank {rank}]: worker prefetch ran {skew} "
                f"batches ahead of consumption (per worker); resume will "
                f"skip those batches"
            )

    def __iter__(self):
        if self.worker_mode == "process":
            yield from self._iter_process()
            return
        # Top-level setup propagates the (possibly worker-inflated)
        # rank/worldsize down the wrapper stack before any layer iterates.
        for p in self.pipelines:
            p.setup()
        if self.num_workers == 1:
            # workerless path: same generation contract as the worker
            # paths — a later __iter__ (or shutdown) supersedes this
            # iterator, which must raise rather than keep drawing from
            # the shared pipeline interleaved with its successor.
            # Consumption advances the pipeline INLINE, so this path is
            # zero-skew by construction: a state capture at a step
            # boundary equals exactly the consumed position, and a
            # resume replays nothing and skips nothing — the property
            # chaos certification leans on (scripts/chaos_soak.py, with
            # feed_prefetch=0 ahead of it).
            self.shutdown()
            stop = self._stop = threading.Event()
            self._produced = [[0]]
            self._consumed = [0]
            it = iter(self.pipelines[0])
            while True:
                if stop.is_set():
                    raise RuntimeError(
                        "stale loader iterator: the loader was shut down "
                        "or re-iterated; this generation's stream has "
                        "ended"
                    )
                batch = _stack([next(it) for _ in range(self.batch_size)])
                self._produced[0][0] += 1
                self._consumed[0] += 1
                # same fault site as the worker modes (fires after each
                # produced batch): action=exit kills THIS process — in
                # workerless mode the trainer is the worker, so the
                # injected loader death surfaces as the classified
                # loader_death exit the supervisor restarts
                _worker_fault(0, self._produced[0][0])
                yield batch

        self.shutdown()
        # fresh generation (see __init__); the local binding lets THIS
        # generator detect it was superseded — shutdown() (including the
        # one a later __iter__ issues) sets the event, and a stale
        # iterator must raise, not block forever on queues nobody fills
        stop = self._stop = threading.Event()
        self._produced = [[0] for _ in range(self.num_workers)]
        self._consumed = [0] * self.num_workers
        queues = [
            queue.Queue(maxsize=self.prefetch_batches) for _ in self.pipelines
        ]
        self._threads = [
            threading.Thread(
                target=self._worker_loop,
                args=(p, q, lk, self._stop, self.batch_size, prod, i),
                daemon=True,
            )
            for i, (p, q, lk, prod) in enumerate(
                zip(self.pipelines, queues, self._locks, self._produced)
            )
        ]
        for t in self._threads:
            t.start()
        restarts = [0] * self.num_workers
        w = 0
        while True:
            while True:
                # checked BEFORE the get: a superseded iterator must not
                # serve leftover prefetched batches either — the stream
                # has moved to the new generation, and the skipped-
                # prefetch contract says those batches are dropped, not
                # delivered late interleaved with the successor's
                if stop.is_set():
                    raise RuntimeError(
                        "stale loader iterator: the loader was shut down "
                        "or re-iterated; this generation's stream has "
                        "ended"
                    )
                try:
                    batch = queues[w].get(timeout=1.0)
                    break
                except queue.Empty:
                    continue
            if isinstance(batch, BaseException):
                if self._can_restart(batch, restarts, w):
                    # the pipeline object (and its position) lives in this
                    # process: a restarted thread resumes the stream from
                    # where the crashed one left it (minus the partial
                    # batch in flight)
                    t = threading.Thread(
                        target=self._worker_loop,
                        args=(
                            self.pipelines[w],
                            queues[w],
                            self._locks[w],
                            stop,
                            self.batch_size,
                            self._produced[w],
                            w,
                        ),
                        daemon=True,
                    )
                    self._threads[w] = t
                    t.start()
                    continue
                self.shutdown()
                if isinstance(batch, (StopIteration, CorpusLossError)):
                    # CorpusLossError stays typed: the entry wrapper
                    # exits corpus_loss, not loader_death — the
                    # supervisor restarts dead DATA differently from a
                    # dead worker
                    raise batch
                # restart budget exhausted: surface typed so the entry's
                # classified-exit wrapper exits loader_death (the
                # supervisor's restart policy keys on the cause)
                raise LoaderWorkerError(
                    f"loader worker {w} failed and the restart budget "
                    f"({self.max_worker_restarts}) is exhausted: {batch}"
                ) from batch
            self._consumed[w] += 1
            yield batch
            w = (w + 1) % self.num_workers

    def _can_restart(self, err, restarts, w) -> bool:
        """Worker-restart budget check + backoff sleep. StopIteration
        (stream genuinely ended) and CorpusLossError (the data itself is
        gone below the survivable floor — a worker restart rereads the
        same dead corpora) are never restarted; anything else gets
        ``max_worker_restarts`` attempts per worker per generation with
        exponential backoff before the error surfaces to the consumer."""
        if isinstance(err, (StopIteration, CorpusLossError)):
            return False
        if restarts[w] >= self.max_worker_restarts:
            return False
        restarts[w] += 1
        delay = self.restart_backoff_s * (2 ** (restarts[w] - 1))
        print(
            f"loader worker {w} died ({type(err).__name__}: {err}); "
            f"restart {restarts[w]}/{self.max_worker_restarts} "
            f"in {delay:.2f}s"
        )
        time.sleep(delay)
        return True

    def _iter_process(self):
        """Process-mode consumer: forked worker processes (the reference's
        torch DataLoader worker-process model, ref:dataloader_utils.py:
        144-146) feed bounded mp queues; state commands are serviced at
        worker batch boundaries via per-worker pipes. Fork (not spawn)
        so resumed/rescaled pipeline state built in the parent — e.g.
        load_from_path before iteration — is inherited without pickling.

        Fork caveat (same one torch DataLoader accepts with its fork
        default): the parent is multithreaded by the time the loader
        iterates (JAX dispatch/gRPC threads), and fork() snapshots mutex
        state — a child could inherit a held allocator/gRPC lock and
        deadlock. The workers never touch JAX (pure numpy/pyarrow/
        tokenizers), which keeps the inherited-lock surface to the
        allocator; if a worker ever hangs before producing its first
        batch, the thread mode is the drop-in fallback."""
        if self._procs_started:
            if not self._workers_alive():
                raise RuntimeError(
                    "worker_mode='process': re-iteration after workers "
                    "exited — their pipeline state is gone. Build a fresh "
                    "loader (resume via load_from_path) instead."
                )
            # capture-then-refork: live workers hold the stream position,
            # so a second __iter__ (an eval loop re-iterating its loader,
            # torch DataLoader's normal contract) pulls each worker's
            # state through the command channel, restores it into the
            # parent's pipeline clones — the same same-size single-shard
            # load the file-resume path uses — and falls through to fork
            # a fresh generation that CONTINUES the stream. Batches the
            # workers prefetched but the consumer never took are skipped,
            # exactly like a checkpoint resume; _log_skew reports them.
            states = self._command_all("state_dict")
            self._log_skew("re-iteration")
            for p, sd in zip(self.pipelines, states):
                p.load_worldsize = p.worldsize
                p.load_state_dict([sd], sharded_input=True)
        self.shutdown()
        # same stale-iterator contract as thread mode: shutdown() (ours
        # above, or a later __iter__'s) sets the old generation's event,
        # and that generation's consumer raises instead of spinning on
        # queues whose producers are gone
        stop = self._stop = threading.Event()
        self._procs_started = True
        ctx = multiprocessing.get_context("fork")
        self._produced = [ctx.Value("q", 0) for _ in range(self.num_workers)]
        self._consumed = [0] * self.num_workers
        queues = [
            ctx.Queue(maxsize=self.prefetch_batches) for _ in self.pipelines
        ]
        self._cmds = [None] * self.num_workers
        self._procs = [None] * self.num_workers
        for i in range(self.num_workers):
            self._spawn_proc_worker(i, ctx, queues)
        procs = self._procs  # generation-local (shutdown() rebinds the attr)
        restarts = [0] * self.num_workers
        w = 0
        while True:
            while True:
                # pre-get staleness check, same contract as thread mode
                if stop.is_set():
                    raise RuntimeError(
                        "stale loader iterator: the loader was shut down "
                        "or re-iterated; this generation's stream has "
                        "ended"
                    )
                try:
                    batch = queues[w].get(timeout=1.0)
                    break
                except queue.Empty:
                    if stop.is_set():
                        # deliberate shutdown/re-iteration, not a worker
                        # crash: loop back so the top-of-loop check
                        # raises the stale-iterator error, not a
                        # misleading "worker died (exit -15)"
                        continue
                    if not procs[w].is_alive():
                        if stop.is_set():
                            # shutdown landed between the check above and
                            # the liveness probe: the dead worker is the
                            # OLD generation's (TERMed by shutdown), not
                            # a crash — loop back to the stale raise
                            continue
                        exitcode = procs[w].exitcode
                        batch = RuntimeError(
                            f"loader worker {w} died (exit {exitcode})"
                        )
                        break
            if isinstance(batch, BaseException):
                if stop.is_set():
                    # a superseded iterator must NEVER call shutdown():
                    # that would kill the NEW generation's workers. The
                    # stream has moved on — raise the stale error instead
                    raise RuntimeError(
                        "stale loader iterator: the loader was shut down "
                        "or re-iterated; this generation's stream has "
                        "ended"
                    )
                if self._can_restart(batch, restarts, w):
                    # refork from the parent's pipeline clone. The dead
                    # worker's stream position died with it, so the
                    # restarted worker resumes from the parent's last
                    # captured state (construction or the last
                    # load_from_path/re-iteration capture) — batches
                    # consumed since then are REPLAYED; flag it.
                    print(
                        f"loader worker {w} restarting from the parent's "
                        f"last captured pipeline state; batches consumed "
                        f"since that capture will repeat"
                    )
                    # FRESH queue: a worker killed mid-put (SIGKILL/OOM)
                    # can die holding the mp.Queue's shared write lock,
                    # which would wedge the replacement worker's first
                    # put forever. Prefetched batches in the old queue
                    # are dropped — already covered by replay semantics.
                    queues[w] = ctx.Queue(maxsize=self.prefetch_batches)
                    self._spawn_proc_worker(w, ctx, queues)
                    continue
                self.shutdown()
                if isinstance(batch, (StopIteration, CorpusLossError)):
                    raise batch
                raise LoaderWorkerError(
                    f"loader worker {w} failed and the restart budget "
                    f"({self.max_worker_restarts}) is exhausted: {batch}"
                ) from batch
            self._consumed[w] += 1
            yield batch
            w = (w + 1) % self.num_workers

    def _spawn_proc_worker(self, w, ctx, queues):
        """(Re)fork worker ``w``: fresh pipe, fresh process over the
        parent's pipeline clone, shared produced counter (so save-skew
        accounting and batch-numbered fault filters survive restarts)."""
        old = self._cmds[w]
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        parent_conn, child_conn = ctx.Pipe()
        proc = ctx.Process(
            target=_process_worker_loop,
            args=(
                self.pipelines[w],
                queues[w],
                child_conn,
                self.batch_size,
                self._produced[w],
                w,
            ),
            daemon=True,
        )
        proc.start()
        child_conn.close()
        self._cmds[w] = parent_conn
        self._procs[w] = proc

    # -- state (delegates to every worker pipeline) -----------------------

    class _AllLocks:
        def __init__(self, locks):
            self.locks = locks

        def __enter__(self):
            for lk in self.locks:
                lk.acquire()

        def __exit__(self, *exc):
            for lk in reversed(self.locks):
                lk.release()

    def _command_all(self, op: str, arg=None):
        """Send a state command to every live worker process and collect
        the replies (each worker answers at its next batch boundary — the
        process-mode analog of grabbing all thread locks). A worker that
        died or whose state op failed raises here instead of blocking the
        trainer's checkpoint path forever — but only after EVERY live
        worker's reply has been drained, so a partial failure can't leave
        a stale reply queued in a pipe to be mis-attributed to the next
        command."""
        out, errs, sent = [], [], []
        for c, p in zip(self._cmds, self._procs):
            try:
                c.send((op, arg))
                sent.append(True)
            except (OSError, BrokenPipeError, ValueError):
                errs.append(
                    RuntimeError(
                        f"loader worker (pid {p.pid}) unreachable for "
                        f"{op!r} (exit {p.exitcode})"
                    )
                )
                sent.append(False)
        for c, p, ok in zip(self._cmds, self._procs, sent):
            if not ok:
                out.append(None)
                continue
            reply = None
            try:
                while not c.poll(timeout=1.0):
                    if not p.is_alive():
                        raise RuntimeError(
                            f"loader worker (pid {p.pid}) died during "
                            f"{op!r} (exit {p.exitcode})"
                        )
                reply = c.recv()
            except (RuntimeError, EOFError, OSError) as e:
                errs.append(e)
            if isinstance(reply, BaseException):
                errs.append(reply)
                reply = None
            out.append(reply)
        if errs:
            raise errs[0]
        return out

    def _check_not_stale(self, op: str):
        """worker_mode='process': all data-position state lives in the
        forked workers — the parent's pipeline copies never advance.
        Refuse to serve state from them once workers have run (a silent
        batch-0 checkpoint would replay the whole consumed stream on
        resume); capture state while workers are live instead (the
        production paths do: CheckpointDataset auto-saves inside workers,
        explicit saves go through the command channel)."""
        if (
            self.worker_mode == "process"
            and self._procs_started
            and not self._workers_alive()
        ):
            raise RuntimeError(
                f"loader.{op} after process workers exited: their pipeline "
                f"state is gone; capture state while workers are live"
            )

    def state_dict(self) -> List[dict]:
        self._check_not_stale("state_dict")
        if self._workers_alive():
            out = self._command_all("state_dict")
            self._log_skew("state_dict")
            return out
        with self._AllLocks(self._locks):
            self._log_skew("state_dict")
            return [p.state_dict() for p in self.pipelines]

    def load_state_dict(self, state_dicts, sharded_input=False):
        self._check_not_stale("load_state_dict")
        if self._workers_alive():
            self._command_all("load_state_dict", (state_dicts, sharded_input))
            return
        with self._AllLocks(self._locks):
            for p in self.pipelines:
                p.load_state_dict(state_dicts, sharded_input)

    def save_to_path(self, path: str):
        self._check_not_stale("save_to_path")
        if self._workers_alive():
            self._command_all("save_to_path", path)
            self._log_skew("save_to_path")
            return
        with self._AllLocks(self._locks):
            self._log_skew("save_to_path")
            for p in self.pipelines:
                p.save_to_path(path)

    def load_from_path(self, path: str):
        self._check_not_stale("load_from_path")
        if self._workers_alive():
            self._command_all("load_from_path", path)
            return
        with self._AllLocks(self._locks):
            for p in self.pipelines:
                p.load_from_path(path)


class SteadyCounter:
    """Dummy stream: incrementing counts of constant length l mod vocab v
    (ref:dataloader_utils.py:41-54). Used for benchmarking / dummy runs."""

    def __init__(self, l: int, v: int):
        self.i = 0
        self.l = l
        self.v = v

    def __iter__(self):
        while True:
            out = np.arange(self.i, self.i + self.l, dtype=np.int32) % self.v
            yield out, out
            self.i += self.l


class _SimpleLoader:
    """Minimal batching loader for non-stateful iterables (dummy data)."""

    def __init__(self, dataset, batch_size: int):
        self.dataset = dataset
        self.batch_size = batch_size

    def __iter__(self):
        it = iter(self.dataset)
        while True:
            yield _stack([next(it) for _ in range(self.batch_size)])


def get_dummy_loader(cfg, rank, world_size):
    return _SimpleLoader(SteadyCounter(cfg.seq_length, cfg.vocab_size), cfg.batch_size)


def elastic_batch_size(cfg, resume_topology, data_extent, rank=0) -> int:
    """Per-rank rows for an elastic resume: preserve the checkpoint's
    *global* batch across a topology change (docs/checkpointing.md
    "Elastic resume").

    ``resume_topology`` is the fingerprint stamped into the checkpoint a
    restart will restore (``checkpointer.resume_topology()``); the
    global row count it records divided by the new data-parallel extent
    gives the per-rank batch size that keeps tokens-per-step — and with
    it tokens_seen, the LR schedule, and the loss trajectory —
    meaningful across the rescale. Recomputation only ever covers the
    launch-script case (same per-rank ``--batch_size``, different
    world): when the data-parallel extent is UNCHANGED, a differing
    global batch can only be a deliberate ``--batch_size`` edit, and
    that — like a global batch the new extent cannot divide — is a hard
    error; ``--allow_batch_change=True`` is the escape hatch (the
    configured batch_size is then used as-is, with a loud notice).
    Returns ``cfg.batch_size`` unchanged on a fresh start or a
    same-batch resume."""
    if not resume_topology:
        return cfg.batch_size
    old_rows = int(resume_topology.get("global_batch_rows") or 0)
    if old_rows <= 0:
        return cfg.batch_size
    if cfg.batch_size * data_extent == old_rows:
        return cfg.batch_size
    old_dc = int(resume_topology.get("device_count") or 0)
    old_extent = old_dc // max(
        1, int(resume_topology.get("tensor_parallel_size") or 1)
    ) // max(1, int(resume_topology.get("context_parallel_size") or 1))
    deliberate = old_dc > 0 and old_extent == data_extent
    if deliberate and not getattr(cfg, "allow_batch_change", False):
        raise ValueError(
            f"elastic resume: batch_size was changed on an unchanged "
            f"data-parallel extent ({data_extent}), moving the global "
            f"batch {old_rows} -> {cfg.batch_size * data_extent} rows "
            f"(tokens_seen and the LR schedule shift). Restore "
            f"--batch_size={old_rows // data_extent}, or pass "
            f"--allow_batch_change=True to accept the change."
        )
    if getattr(cfg, "allow_batch_change", False):
        if rank == 0:
            print(
                f"WARNING: elastic resume changes the global batch "
                f"({old_rows} -> {cfg.batch_size * data_extent} rows; "
                f"allow_batch_change=True): tokens-per-step, the LR "
                f"schedule, and the loss trajectory shift from here."
            )
        return cfg.batch_size
    if old_rows % data_extent != 0:
        raise ValueError(
            f"elastic resume: the checkpoint's global batch is "
            f"{old_rows} rows but the new data-parallel extent "
            f"{data_extent} does not divide it, so the global batch "
            f"cannot be preserved. Restart on a chip count whose "
            f"data-parallel extent divides {old_rows}, or pass "
            f"--allow_batch_change=True to accept a changed global "
            f"batch (tokens_seen / LR schedule shift)."
        )
    resolved = old_rows // data_extent
    if rank == 0:
        print(
            f"elastic resume: preserving the global batch of {old_rows} "
            f"rows across the rescale — per-rank batch_size "
            f"{cfg.batch_size} -> {resolved}"
        )
    return resolved


def get_data_loader(cfg, rank, world_size, postprocess=None, batch_multiplier=1):
    """Build the full 7-layer pipeline
    (ref:dataloader_utils.py:60-146): streaming docs -> logical-shard
    rescaling -> weighted multi-dataset sampling -> fixed-length packing ->
    reservoir shuffle -> tensorize -> task postprocess -> auto-checkpoint,
    wrapped in the batching loader.

    ``batch_multiplier``: loader batches consumed per trainer step by this
    process (the ``rebatch`` factor — data-parallel shards per process).
    It keeps CheckpointDataset's auto-save step numbering aligned with
    trainer steps, preserving the reference invariant that loader state
    lands in the same ``step_N_ckp`` dirs as model checkpoints
    (ref:dataloader_utils.py:137-143 counts its interval in trainer
    batches; one torch batch = one trainer step there, but here one
    trainer step consumes batch_multiplier loader batches spread
    round-robin over num_workers workers). When num_workers does not
    divide the per-step row count the worker step clock diverges from the
    trainer's (by up to num_workers/rows_per_step when workers outnumber
    per-step rows) — a warning is printed, and resume still works because
    both checkpoint validators scan for the newest directory of their own
    kind.
    """
    if postprocess is None:
        postprocess = [causal_lm]

    datasets, weights = parse_data_args(cfg.datasets, cfg.weights)

    droplist = [
        int(x.strip()) for x in cfg.strip_tokens.split(",") if len(x.strip()) > 0
    ]
    droplist = droplist + [cfg.bos_token, cfg.eos_token, cfg.bol_token, cfg.eol_token]
    assert cfg.file_type in _HANDLER_BUILDERS, (
        f"File type {cfg.file_type} is not recognized "
        f"({list(_HANDLER_BUILDERS.keys())})"
    )
    filehandler = _HANDLER_BUILDERS[cfg.file_type](cfg)
    # transient shard-read errors retry with bounded backoff; exhaustion
    # surfaces OSError to StreamingDocDataset, which quarantines the
    # shard instead of killing the run (resilience layer)
    from fms_fsdp_tpu.resilience.retry import RetryingShardHandler

    filehandler = RetryingShardHandler(
        filehandler,
        retries=max(0, getattr(cfg, "shard_read_retries", 3)),
        backoff_s=getattr(cfg, "shard_read_backoff_s", 0.5),
    )

    data = StreamingDocDataset(
        cfg.data_path,
        rank,
        world_size,
        filehandler,
        cfg.eos_token,
        bos_token=cfg.bos_token,
        strip_tokens=set(droplist),
        min_length=3,
        seed=cfg.seed,
    )
    data = ScalableShardDataset(
        data,
        cfg.eos_token,
        n_logical_shards=cfg.logical_shards,
    )
    data = SamplingDataset(
        cfg.data_path,
        data,
        cfg.eos_token,
        datasets=datasets,
        weights=weights,
        # fault-isolation floor: a run survives corpus loss (weights
        # renormalized over survivors) down to this many live corpora;
        # below it the classified corpus_loss exit fires
        min_live_corpora=int(getattr(cfg, "min_live_corpora", 1) or 1),
        allow_corpus_change=bool(getattr(cfg, "allow_corpus_change", False)),
        verbose=(rank == 0),
    )
    # +1 token so the causal shift still yields seq_length-long examples
    data = BufferDataset(
        data,
        cfg.seq_length if causal_lm not in postprocess else cfg.seq_length + 1,
        bos_token=cfg.bol_token,
        eos_token=cfg.eol_token,
        pack_hard=True,
    )
    # Reservoir-shuffle window. NOTE for tests/small corpora: while the
    # reservoir fills it pulls ~2 rows from the packer per emitted row,
    # so the underlying document walk runs up to (window + consumed)
    # rows ahead of consumption — on a corpus smaller than ~2x the
    # window's token footprint the walk wraps into its SECOND epoch
    # almost immediately, and a resume will (correctly) re-serve
    # epoch-1 documents. Size the window below the corpus for
    # deterministic walk tests (tests/_elastic_child.py does).
    data = PreloadBufferDataset(
        data, int(getattr(cfg, "loader_shuffle_window", 10000) or 10000)
    )

    data = PreprocessDataset(data, lambda x: np.asarray(x, dtype=np.int32))
    for p in postprocess:
        data = PreprocessDataset(data, p)

    # rows one worker emits per trainer step (see batch_multiplier above)
    rows_per_step = cfg.batch_size * max(1, batch_multiplier)
    steps_per_batch = max(1, rows_per_step // max(1, cfg.num_workers))
    if rank == 0 and rows_per_step % max(1, cfg.num_workers) != 0:
        # worst case (num_workers > rows_per_step) the worker step clock
        # runs num_workers/rows_per_step times SLOW, not "slightly off"
        print(
            f"WARNING: num_workers={cfg.num_workers} does not divide the "
            f"per-step row count {rows_per_step}; loader auto-save step "
            f"numbering will drift from trainer steps (resume still works "
            f"— both checkpoint scanners pick the newest dir of their own "
            f"kind — but on-disk step numbers won't correlate)"
        )
    # the fast-local checkpoint tier (docs/checkpointing.md) is another
    # root the trainer may resolve a restart from; the loader must
    # honor a trainer-resolved step dir under it exactly like one under
    # the durable root (model-loader consistency)
    local_dir = str(getattr(cfg, "ckpt_local_dir", "") or "")
    data = CheckpointDataset(
        data,
        cfg.ckpt_load_path if cfg.resuming_dataset else cfg.ckpt_save_path,
        cfg.checkpoint_interval,
        steps_per_batch,
        cfg.ckpt_save_path,
        extra_roots=(
            (os.path.join(local_dir, "checkpoints"),) if local_dir else ()
        ),
    )
    return StatefulDataLoader(
        data,
        batch_size=cfg.batch_size,
        num_workers=cfg.num_workers,
        worker_mode=getattr(cfg, "worker_mode", "thread"),
        max_worker_restarts=getattr(cfg, "loader_worker_restarts", 2),
        restart_backoff_s=getattr(cfg, "loader_restart_backoff_s", 1.0),
    )


def rebatch(loader, local_batch: int, batch_size: int):
    """Concatenate per-rank batches (of ``batch_size`` rows) into
    process-local device batches of ``local_batch`` rows — the bridge from
    the reference's per-GPU batch_size to a per-process multi-chip batch."""
    if local_batch == batch_size:
        return loader

    def gen():
        it = iter(loader)
        n = local_batch // batch_size
        while True:
            parts = [next(it) for _ in range(n)]
            if isinstance(parts[0], tuple):
                yield tuple(np.concatenate(f) for f in zip(*parts))
            else:
                yield np.concatenate(parts)

    return gen()


def _find_layer(pipeline, cls):
    """Walk a wrapper pipeline's ``.dataset`` chain for a layer type."""
    d = pipeline
    while d is not None:
        if isinstance(d, cls):
            return d
        d = getattr(d, "dataset", None)
    return None


def loader_mix_stats(loader):
    """Aggregate per-corpus mixing stats from a live loader, or None.

    Walks every worker pipeline's wrapper chain to the SamplingDataset
    and sums per-corpus ``tokens_seen`` (racy int reads — gauge
    accuracy, not exactness). Returns ``{"tokens": {corpus: int},
    "weights": {corpus: float}, "quarantined": [corpus, ...]}``.
    None when the loader carries no mixing layer (dummy loader), the
    pipeline is not set up yet (fresh un-iterated start), or
    worker_mode="process" has started its workers (the parent's
    pipeline copies never advance — their numbers would be frozen at
    the fork point)."""
    pipelines = getattr(loader, "pipelines", None)
    if not pipelines:
        return None
    if (
        getattr(loader, "worker_mode", "thread") == "process"
        and getattr(loader, "_procs_started", False)
    ):
        return None
    samplers = [
        s
        for s in (_find_layer(p, SamplingDataset) for p in pipelines)
        if s is not None and s.is_setup
    ]
    if not samplers:
        return None
    names = list(samplers[0].datasets)
    tokens = {n: 0 for n in names}
    quarantined = set()
    for s in samplers:
        for n, t in zip(s.datasets, s.tokens_seen):
            tokens[n] = tokens.get(n, 0) + int(t)
        quarantined.update(s.quarantined_corpora)
    return {
        "tokens": tokens,
        "weights": {n: float(w) for n, w in zip(names, samplers[0].weights)},
        "quarantined": sorted(quarantined),
    }


def parse_data_args(datas, weights):
    """csv strings -> lists (ref:dataloader_utils.py:149-163)."""

    def splitstrip(x):
        if isinstance(x, str):
            return [item.strip() for item in x.split(",")]
        elif isinstance(x, (list, tuple)):
            return list(x)
        elif isinstance(x, (int, float, complex)):
            return [x]
        else:
            raise ValueError(f"arg input {x} cannot be parsed.")

    datas = splitstrip(datas)
    weights = [float(x) for x in splitstrip(weights)]
    return datas, weights
