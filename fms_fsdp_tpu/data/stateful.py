"""Stateful, rescalable iterator pipeline — base layer.

Design principles carried over from the reference dataloader
(ref:fms_fsdp/utils/dataset_utils.py:19-42):

1. workers never communicate — distribution is parameterized by
   (rank, worldsize) integers only;
2. the pipeline is a stack of wrapped iterators;
3. every layer checkpoints itself via recursive state_dict/load_state_dict;
4. state splits into ``state_params`` (scalars, droppable on rescale) and
   ``reshard_params`` (lists, redistributed by fractional ownership when the
   world size changes) — the mechanism behind restart-on-different-chip-count
   (ref:dataset_utils.py:136-161).

This implementation is torch-free: rank comes from ``jax.process_index()``
at assembly time, values are python lists / numpy arrays, per-rank state
files are stdlib pickles. There is no torch-DataLoader worker-process
machinery — ``num_workers`` is realized as in-process logical sub-ranks
(see loader.py), so the worker-id rank inflation the reference performs
inside worker processes (ref:dataset_utils.py:108-119) happens at
construction instead.
"""

import logging
import math
import os
import pickle
from typing import Any, List

logger = logging.getLogger(__name__)


def shard_partition(itemlist: List[Any], rank: int, worldsize: int) -> List[Any]:
    """Contiguous 1/worldsize slice of itemlist owned by rank (exact
    partition; uneven remainders spread by integer flooring)."""
    n = len(itemlist)
    return itemlist[(rank * n) // worldsize : ((rank + 1) * n) // worldsize]


def shard_inclusive(itemlist: List[Any], rank: int, worldsize: int) -> List[Any]:
    """Like shard_partition but with fractional ownership: include any item
    partially owned by rank (floor/ceil bounds)."""
    n = len(itemlist)
    start = math.floor(n * rank / worldsize)
    end = math.ceil(n * (rank + 1) / worldsize)
    return itemlist[start:end]


class StatefulDataset:
    """Iterable with recursive checkpoint state and rescaling support.

    Subclasses declare ``state_params`` (per-worker scalars, dropped when the
    world size changes) and ``reshard_params`` (lists redistributed across
    the new world size).
    """

    def __init__(self, datapath, rank: int, worldsize: int):
        assert rank >= 0, f"Rank {rank} must be a non-negative integer"
        assert worldsize > rank, f"Worldsize {worldsize} must exceed rank {rank}"
        assert datapath is None or (
            os.path.isdir(datapath) and len(os.listdir(datapath)) > 0
        ), f"Data path {datapath} must be a non-empty folder or None"
        self.state_params: List[str] = []
        self.reshard_params: List[str] = []

        self.datapath = datapath
        self.rank = rank
        self.worldsize = worldsize
        self.local_worldsize = -1

        self.load_worldsize = worldsize
        self.is_setup = False

    # -- setup ------------------------------------------------------------

    def setup(self):
        """Rank/path-dependent setup, deferred so that wrapper layers can
        re-target rank/datapath after construction."""
        if not self.is_setup:
            self.is_setup = True
            if self.local_worldsize == -1:
                self.local_worldsize = 1

    def __iter__(self):
        raise NotImplementedError

    # -- state ------------------------------------------------------------

    def statename(self, x: str) -> str:
        # Class-qualified keys; implicitly disallows repeating a layer type
        # within one pipeline.
        return self.__class__.__name__ + "." + x

    def state_dict(self):
        self.setup()
        return {
            self.statename(flag): getattr(self, flag)
            for flag in self.state_params + self.reshard_params
        }

    def _reshard(self, sharded_list):
        """Flatten the (inclusively owned) per-checkpoint-shard lists and
        slice out exactly the fraction this worker owns.

        ``sharded_list`` is a list of equal-length shard sublists spanning
        this worker's inclusive ownership range.
        """
        shard_offset = math.floor(self.load_worldsize * self.rank / self.worldsize)
        shard_len = len(sharded_list[0])
        for i, shard in enumerate(sharded_list):
            assert (
                len(shard) == shard_len
            ), f"Shard {i} length {len(shard)} != expected {shard_len}"
        item_offset = shard_len * shard_offset
        n_items = self.load_worldsize * shard_len
        my_items = range(
            int(n_items * self.rank / self.worldsize) - item_offset,
            int(n_items * (self.rank + 1) / self.worldsize) - item_offset,
        )
        return [sharded_list[i // shard_len][i % shard_len] for i in my_items]

    def load_state_dict(self, state_dicts, sharded_input=False):
        """Load from a list of per-worker state dicts.

        Same-size world: adopt both state and reshard params from own shard.
        Different size: drop state params, reassemble reshard params by
        fractional ownership.
        """
        self.setup()
        if not sharded_input:
            self.load_worldsize = len(state_dicts)
            state_dicts = shard_inclusive(state_dicts, self.rank, self.worldsize)
        if self.load_worldsize == self.worldsize:
            for flag in self.state_params + self.reshard_params:
                # keys absent from the checkpoint (state params added in
                # a later version, e.g. quarantined_shards) keep their
                # constructed defaults instead of failing the resume —
                # but LOUDLY: a partial dict can also mean a torn loader
                # state file, and a silently-defaulted position key would
                # replay data with no trace
                key = self.statename(flag)
                if key in state_dicts[0]:
                    setattr(self, flag, state_dicts[0][key])
                else:
                    logger.warning(
                        "loader state for %s is missing key %r; keeping "
                        "the constructed default (new-version state "
                        "param, or a torn/partial checkpoint)",
                        type(self).__name__,
                        key,
                    )
        else:
            for flag in self.reshard_params:
                if self.statename(flag) not in state_dicts[0]:
                    logger.warning(
                        "loader state for %s is missing reshard key %r; "
                        "keeping the constructed default",
                        type(self).__name__,
                        self.statename(flag),
                    )
                    continue
                setattr(
                    self,
                    flag,
                    self._reshard([sd[self.statename(flag)] for sd in state_dicts]),
                )
        return state_dicts

    # -- disk -------------------------------------------------------------

    def load_from_path(self, path: str):
        """Find this worker's overlap among the checkpoint's per-rank state
        files and load only those."""
        assert os.path.exists(path), "Specified checkpoint does not exist"
        assert not os.path.isfile(path), "Checkpoint should be a folder of shard states"
        fileshards = [x for x in os.listdir(path) if "loader" in x]
        fileshards = sorted(fileshards, key=lambda x: int(x.split("_")[2][:-4]))
        if not fileshards:
            raise RuntimeError(
                f"checkpoint {path} contains no loader_state files: the "
                f"document-walk position cannot be restored. The "
                f"checkpoint is either model-only (saved without a "
                f"dataloader) or an incomplete copy — resume from a "
                f"checkpoint holding every per-rank loader_state_<N>.pkl "
                f"the save wrote."
            )
        # elastic resume: load_worldsize is the SAVE world (process
        # count x num_workers then); when it differs from this world,
        # each rank reads every old file that fractionally owns its
        # logical shards and load_state_dict reshards (docs/dataloader.md)
        self.load_worldsize = len(fileshards)
        my_fileshards = shard_inclusive(fileshards, self.rank, self.worldsize)
        states = []
        for x in my_fileshards:
            with open(os.path.join(path, x), "rb") as f:
                states.append(pickle.load(f))
        self.load_state_dict(states, True)

    def save_to_path(self, path: str):
        os.makedirs(path, exist_ok=True)
        state = self.state_dict()
        with open(os.path.join(path, f"loader_state_{self.rank}.pkl"), "wb") as f:
            pickle.dump(state, f)


class WrapperDataset(StatefulDataset):
    """A pipeline layer holding one wrapped StatefulDataset; state calls
    recurse through it, rank/path retargeting propagates down at setup."""

    def __init__(self, dataset: StatefulDataset):
        self.dataset = dataset
        super().__init__(dataset.datapath, dataset.rank, dataset.worldsize)

    def setup(self):
        if not self.is_setup:
            super().setup()
            self.dataset.datapath = self.datapath
            self.dataset.rank = self.rank
            self.dataset.worldsize = self.worldsize
            self.dataset.local_worldsize = self.local_worldsize
            self.dataset.setup()

    def state_dict(self):
        self.setup()
        out = self.dataset.state_dict()
        out.update(StatefulDataset.state_dict(self))
        return out

    def load_state_dict(self, state_dicts, sharded_input=False):
        self.setup()
        sharded_dicts = StatefulDataset.load_state_dict(
            self, state_dicts, sharded_input
        )
        self.dataset.load_worldsize = self.load_worldsize
        self.dataset.load_state_dict(sharded_dicts, True)
        return sharded_dicts
