"""Autoregressive generation with a kv-cache for the Llama family.

Replaces the reference's embeds-returning ``generate`` copy
(ref:speculator/train_speculator_utils.py:28-118): prefill + a
``lax.scan`` decode loop entirely under jit — no Python in the token loop
(SURVEY.md §7 hard part 4). Supports temperature / top-k sampling or
greedy decode, and optionally returns the final hidden state (embedding)
of every generated position for speculator stage-2 training.

The kv cache is a pytree {"k", "v"} of (L, B, S_max, Nkv, H) arrays
carried through the scan; each decode step runs the layer stack as an
inner ``lax.scan`` whose xs are the stacked layer params + cache slices.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from fms_fsdp_tpu.models.configs import LlamaConfig
from fms_fsdp_tpu.models.llama import llama_forward
from fms_fsdp_tpu.ops.norms import rms_norm
from fms_fsdp_tpu.ops.paged_attention import gqa_attend
from fms_fsdp_tpu.ops.rope import apply_rotary, rope_table


def prefill(
    params,
    tokens,
    cfg: LlamaConfig,
    max_seq_len: int,
    compute_dtype=jnp.bfloat16,
    full_logits: bool = False,
):
    """Run the prompt through the model, building the kv cache.

    Returns (logits, embeds (B, S, D), cache). ``logits`` covers only the
    final position (B, 1, V) unless ``full_logits`` — generation discards
    the rest, and at 128k vocab the full (B, S, V) matmul is pure waste.
    The cache holds max_seq_len positions; positions >= len(prompt) are
    zeros until decode writes them.
    """
    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    b, s = tokens.shape
    hd, nkv = cfg.head_dim, cfg.n_kv_heads
    nlayers = params["layers"]["wq"].shape[0]

    cos, sin = rope_table(max_seq_len, hd, cfg.rope_theta)
    x = params["embedding"][tokens]

    def body(x, layer):
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = (h @ layer["wq"]).reshape(b, s, cfg.nheads, hd)
        k = (h @ layer["wk"]).reshape(b, s, nkv, hd)
        v = (h @ layer["wv"]).reshape(b, s, nkv, hd)
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)
        from fms_fsdp_tpu.ops.attention import attention

        o = attention(q, k, v, causal=True, impl="xla")
        x = x + o.reshape(b, s, cfg.nheads * hd) @ layer["wo"]
        h2 = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
        ffn = (jax.nn.silu(h2 @ layer["w1"]) * (h2 @ layer["w3"])) @ layer["w2"]
        # cache entries padded out to max_seq_len
        pad = [(0, 0), (0, max_seq_len - s), (0, 0), (0, 0)]
        return x + ffn, (jnp.pad(k, pad), jnp.pad(v, pad))

    x, (k_cache, v_cache) = lax.scan(body, x, params["layers"])
    embeds = rms_norm(x, params["norm"], cfg.norm_eps)
    src = embeds if full_logits else embeds[:, -1:]
    logits = src @ params["lm_head"]
    return logits, embeds, {"k": k_cache, "v": v_cache}


def decode_layer_qkv(x, layer, cfg: LlamaConfig, cos, sin, positions):
    """Pre-attention half of one decode layer: norm -> q/k/v projections
    -> rotary at ``positions``. Shared by the dense decode path below and
    the paged decode path (fms_fsdp_tpu/serve/decode.py) so both run the
    exact same ops — the bit-parity contract between them."""
    b, m = x.shape[:2]
    hd, nkv = cfg.head_dim, cfg.n_kv_heads
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = (h @ layer["wq"]).reshape(b, m, cfg.nheads, hd)
    k = (h @ layer["wk"]).reshape(b, m, nkv, hd)
    v = (h @ layer["wv"]).reshape(b, m, nkv, hd)
    q = apply_rotary(q, cos, sin, positions)
    k = apply_rotary(k, cos, sin, positions)
    return q, k, v


def decode_layer_out(x, layer, cfg: LlamaConfig, o):
    """Post-attention half of one decode layer: residual + SwiGLU FFN.
    Shared with the paged decode path (see decode_layer_qkv)."""
    x = x + o @ layer["wo"]
    h2 = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
    ffn = (jax.nn.silu(h2 @ layer["w1"]) * (h2 @ layer["w3"])) @ layer["w2"]
    return x + ffn


def decode_chunk(params, cache, tokens, pos, cfg: LlamaConfig, compute_dtype=jnp.bfloat16):
    """Cached decode of m tokens at positions pos..pos+m-1 in one forward
    (the verification step of speculative decoding; decode_step is the
    m=1 case). Returns (logits (B, m, V), embeds (B, m, D), cache)."""
    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    b, m = tokens.shape
    hd = cfg.head_dim
    max_seq = cache["k"].shape[2]

    cos, sin = rope_table(max_seq, hd, cfg.rope_theta)
    positions = pos + jnp.arange(m, dtype=jnp.int32)[None, :]  # (1, m)
    positions = jnp.broadcast_to(positions, (b, m))
    x = params["embedding"][tokens]

    def body(x, inp):
        layer, k_cache, v_cache = inp
        q, k, v = decode_layer_qkv(x, layer, cfg, cos, sin, positions)
        k_cache = lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
        v_cache = lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))
        # q position pos+i sees cache entries <= pos+i
        o = gqa_attend(q, k_cache, v_cache, positions)
        return decode_layer_out(x, layer, cfg, o), (k_cache, v_cache)

    x, (k_cache, v_cache) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    embeds = rms_norm(x, params["norm"], cfg.norm_eps)
    logits = embeds @ params["lm_head"]
    return logits, embeds, {"k": k_cache, "v": v_cache}


def decode_step(params, cache, token, pos, cfg: LlamaConfig, compute_dtype=jnp.bfloat16):
    """One cached decode step. token (B, 1) int32 at position ``pos``.
    Returns (logits (B, V), embeds (B, D), updated cache) — the m=1 case
    of decode_chunk."""
    logits, embeds, cache = decode_chunk(
        params, cache, token, pos, cfg, compute_dtype
    )
    return logits[:, 0], embeds[:, 0], cache


def sample_token(logits, key, temperature, top_k, do_sample):
    """Greedy argmax or temperature / top-k sampling of one token per
    row. Public: the serving engine (fms_fsdp_tpu/serve/engine.py) uses
    the same sampler as ``generate`` so greedy serving is token-for-token
    the dense path."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / temperature
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


_sample = sample_token


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "max_seq_len",
        "max_new_tokens",
        "temperature",
        "top_k",
        "do_sample",
        "include_embeds",
    ),
)
def generate(
    params,
    input_ids,
    cfg: LlamaConfig,
    *,
    key,
    max_seq_len: int = 2048,
    max_new_tokens: int = 256,
    temperature: float = 1.0,
    top_k: int = 10,
    do_sample: bool = True,
    include_embeds: bool = True,
):
    """Autoregressive generation (ref:train_speculator_utils.py:28-118).

    input_ids (B, P) -> result (B, P + max_new_tokens); with
    ``include_embeds`` also returns embeds (B, max_new_tokens, D): the
    final hidden state at each *generated* position (the state that
    predicted the NEXT token), matching the reference's embeds capture.
    """
    b, prompt_len = input_ids.shape
    assert prompt_len + max_new_tokens <= max_seq_len, (
        f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) exceeds "
        f"max_seq_len ({max_seq_len}): the kv cache would overflow (dynamic "
        "slice writes clamp silently)"
    )
    logits, prefill_embeds, cache = prefill(params, input_ids, cfg, max_seq_len)
    last_logits = logits[:, -1]
    last_embed = prefill_embeds[:, -1]

    def step(carry, key_t):
        cache, last_logits, last_embed, pos = carry
        tok = _sample(last_logits, key_t, temperature, top_k, do_sample)
        logits, embeds, cache = decode_step(
            params, cache, tok[:, None], pos, cfg
        )
        return (cache, logits, embeds, pos + 1), (tok, last_embed)

    keys = jax.random.split(key, max_new_tokens)
    (_, _, _, _), (tokens, embeds) = lax.scan(
        step, (cache, last_logits, last_embed, prompt_len), keys
    )
    tokens = jnp.moveaxis(tokens, 0, 1)  # (B, T)
    result = jnp.concatenate([input_ids, tokens], axis=1)
    if include_embeds:
        return result, jnp.moveaxis(embeds, 0, 1)  # (B, T, D)
    return result
