"""GPTBigCode (StarCoder family) — speculator base model.

The reference registers an ``EmbedGPTBigCode`` base for speculator
training (ref:speculator/train_speculator_utils.py:430-500): forward
that also yields the final hidden states. This is a frozen-base,
forward-only implementation (no sharding rules / optimizer wiring):

- learned absolute position embeddings (wte + wpe);
- multi-query attention: one kv head shared by all q heads (the GQA
  nkv=1 case of ops/attention);
- fused c_attn projection (q | k | v), gelu MLP, full LayerNorm with
  bias, tied lm_head (logits = h @ wte^T).
"""

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from fms_fsdp_tpu.ops.attention import attention
from fms_fsdp_tpu.ops.norms import layer_norm

Params = Dict[str, Any]


@dataclass(frozen=True)
class GPTBigCodeConfig:
    src_vocab_size: int = 49152
    emb_dim: int = 2048
    nheads: int = 16
    nlayers: int = 24
    hidden_grow_factor: float = 4.0
    max_expected_seq_len: int = 2048
    ln_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.emb_dim // self.nheads

    @property
    def hidden_dim(self) -> int:
        return int(self.emb_dim * self.hidden_grow_factor)


def init_gpt_bigcode_params(key, cfg: GPTBigCodeConfig, dtype=jnp.float32) -> Params:
    d, hd, h = cfg.emb_dim, cfg.head_dim, cfg.hidden_dim
    std = 0.02
    keys = iter(jax.random.split(key, 4 * cfg.nlayers + 2))

    def tn(k, shape):
        return (
            jax.random.truncated_normal(k, -3, 3, shape, jnp.float32) * std
        ).astype(dtype)

    L = cfg.nlayers
    layers = {
        "ln1_w": jnp.ones((L, d), dtype),
        "ln1_b": jnp.zeros((L, d), dtype),
        # fused MQA projection: q (d) | k (hd) | v (hd)
        "c_attn": jnp.stack([tn(next(keys), (d, d + 2 * hd)) for _ in range(L)]),
        "attn_proj": jnp.stack([tn(next(keys), (d, d)) for _ in range(L)]),
        "ln2_w": jnp.ones((L, d), dtype),
        "ln2_b": jnp.zeros((L, d), dtype),
        "c_fc": jnp.stack([tn(next(keys), (d, h)) for _ in range(L)]),
        "mlp_proj": jnp.stack([tn(next(keys), (h, d)) for _ in range(L)]),
    }
    return {
        "wte": tn(next(keys), (cfg.src_vocab_size, d)),
        "wpe": tn(next(keys), (cfg.max_expected_seq_len, d)),
        "layers": layers,
        "ln_f_w": jnp.ones((d,), dtype),
        "ln_f_b": jnp.zeros((d,), dtype),
    }


def gpt_bigcode_param_specs() -> Params:
    """PartitionSpec tree for the GPTBigCode param tree (megatron layout,
    same conventions as the Llama rulebook in parallel/sharding.py). The
    reference shards every speculator base via fms TP/FSDP
    (ref:speculator/train_speculator.py:133-160); without this rulebook
    ``shard_params`` would silently replicate a 20B+ StarCoder base.

    Layer weights carry a leading stacked-L axis (never sharded). The
    fused MQA c_attn output dim (d + 2*head_dim) is usually not divisible
    by the tensor extent, in which case resolve_spec drops that entry —
    fsdp row sharding still applies.
    """
    from jax.sharding import PartitionSpec as P

    from fms_fsdp_tpu.parallel.mesh import AXIS_FSDP, AXIS_TENSOR

    layers = {
        "ln1_w": P(None, None),
        "ln1_b": P(None, None),
        "c_attn": P(None, AXIS_FSDP, AXIS_TENSOR),
        "attn_proj": P(None, AXIS_TENSOR, AXIS_FSDP),
        "ln2_w": P(None, None),
        "ln2_b": P(None, None),
        "c_fc": P(None, AXIS_FSDP, AXIS_TENSOR),
        "mlp_proj": P(None, AXIS_TENSOR, AXIS_FSDP),
    }
    return {
        "wte": P(AXIS_TENSOR, AXIS_FSDP),
        "wpe": P(None, AXIS_FSDP),
        "layers": layers,
        "ln_f_w": P(None),
        "ln_f_b": P(None),
    }


def gpt_bigcode_forward(
    params: Params,
    tokens,
    cfg: GPTBigCodeConfig,
    *,
    compute_dtype=jnp.bfloat16,
    positions=None,
    return_embeds: bool = False,
    mesh=None,
    **_unused,
):
    """tokens (B, S) -> logits (B, S, V); optionally also the final hidden
    states (the Embed* contract)."""
    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    b, s = tokens.shape
    assert s <= cfg.max_expected_seq_len, (
        f"sequence length {s} exceeds max_expected_seq_len "
        f"{cfg.max_expected_seq_len}: the wpe gather would clamp silently"
    )
    d, hd = cfg.emb_dim, cfg.head_dim
    if positions is None:
        positions = jnp.arange(s)[None, :]
    # wte is stored P(tensor, fsdp) (gpt_bigcode_param_specs): a direct
    # gather would hand the activation the table's feature-dim sharding —
    # the involuntary-full-remat pattern embed_lookup exists to avoid.
    # wpe is tiny; replicate it before the position slice.
    from fms_fsdp_tpu.parallel.sharding import constrain, embed_lookup

    wpe = params["wpe"]
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        wpe = constrain(wpe, P(None, None), mesh)
    x = embed_lookup(params["wte"], tokens, mesh) + wpe[positions]

    L = params["layers"]["c_attn"].shape[0]
    for i in range(L):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], cfg.ln_eps)
        qkv = h @ lp["c_attn"]
        q = qkv[..., :d].reshape(b, s, cfg.nheads, hd)
        k = qkv[..., d : d + hd].reshape(b, s, 1, hd)
        v = qkv[..., d + hd :].reshape(b, s, 1, hd)
        o = attention(q, k, v, causal=True, impl="xla")
        x = x + o.reshape(b, s, d) @ lp["attn_proj"]
        h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], cfg.ln_eps)
        x = x + jax.nn.gelu(h @ lp["c_fc"], approximate=True) @ lp["mlp_proj"]

    embeds = layer_norm(x, params["ln_f_w"], params["ln_f_b"], cfg.ln_eps)
    logits = embeds @ params["wte"].T  # tied lm head
    if return_embeds:
        return logits, embeds
    return logits


def generate_simple(
    params,
    input_ids,
    cfg,
    forward_fn,
    *,
    key,
    max_new_tokens: int = 8,
    do_sample: bool = False,
    temperature: float = 1.0,
    include_embeds: bool = False,
    **_unused,
):
    """Cache-less greedy/sampled generation by full re-forward — shared by
    the non-Llama speculator bases (correctness over speed; the Llama base
    keeps its kv-cached models/generation path).

    The sequence lives in a fixed (B, P+T) buffer written in place via
    dynamic_update_slice — causal attention makes the trailing padding
    invisible to earlier positions, so one compile covers every step."""
    from jax import lax

    b, plen = input_ids.shape
    total = plen + max_new_tokens
    toks = jnp.zeros((b, total), input_ids.dtype).at[:, :plen].set(input_ids)

    def step(i, carry):
        toks, key = carry
        out = forward_fn(params, toks, cfg)
        logits_all = out[0] if isinstance(out, tuple) else out
        logits = lax.dynamic_slice_in_dim(logits_all, i - 1, 1, axis=1)[:, 0]
        key, sub = jax.random.split(key)
        sampled = jax.random.categorical(
            sub, logits.astype(jnp.float32) / temperature, axis=-1
        )
        nxt = sampled if do_sample else jnp.argmax(logits, axis=-1)
        toks = lax.dynamic_update_slice_in_dim(
            toks, nxt[:, None].astype(toks.dtype), i, axis=1
        )
        return toks, key

    toks, _ = lax.fori_loop(plen, total, step, (toks, key))
    if include_embeds:
        _, embeds = forward_fn(params, toks, cfg, return_embeds=True)
        # llama generate contract (models/generation.py): embeds at each
        # *generated* position = hidden state that predicted that token,
        # i.e. positions plen-1 .. plen+T-2
        return toks, embeds[:, plen - 1 : plen - 1 + max_new_tokens]
    return toks
