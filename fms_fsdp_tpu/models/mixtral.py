"""Mixtral (sparse MoE Llama-family) — speculator base model.

The reference registers an ``EmbedMixtral`` base for speculator training
(ref:speculator/train_speculator_utils.py:500-569). Frozen-base,
forward-only implementation: Llama-style attention (GQA + RoPE +
RMSNorm) with the FFN replaced by a top-2-of-E SwiGLU mixture.

Routing computes every expert densely and mixes with the (renormalized)
top-2 softmax weights — for a frozen base this trades FLOPs (E/2 extra)
for exact, jit-friendly static shapes; a capacity-based gather/scatter
dispatch is the training-scale optimization, not needed for a frozen
teacher.
"""

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

from fms_fsdp_tpu.ops.attention import attention
from fms_fsdp_tpu.ops.norms import rms_norm
from fms_fsdp_tpu.ops.rope import apply_rotary, rope_table

Params = Dict[str, Any]


@dataclass(frozen=True)
class MixtralConfig:
    src_vocab_size: int = 32000
    emb_dim: int = 4096
    nheads: int = 32
    kvheads: int = 8
    nlayers: int = 32
    hidden_dim: int = 14336
    num_experts: int = 8
    top_k: int = 2
    max_expected_seq_len: int = 4096
    rope_theta: float = 1e6
    norm_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.emb_dim // self.nheads


def init_mixtral_params(key, cfg: MixtralConfig, dtype=jnp.float32) -> Params:
    d, hd, h, E = cfg.emb_dim, cfg.head_dim, cfg.hidden_dim, cfg.num_experts
    std = 0.02
    keys = iter(jax.random.split(key, 8 * cfg.nlayers + 3))

    def tn(k, shape):
        return (
            jax.random.truncated_normal(k, -3, 3, shape, jnp.float32) * std
        ).astype(dtype)

    L = cfg.nlayers
    layers = {
        "attn_norm": jnp.ones((L, d), dtype),
        "wq": jnp.stack([tn(next(keys), (d, cfg.nheads * hd)) for _ in range(L)]),
        "wk": jnp.stack([tn(next(keys), (d, cfg.kvheads * hd)) for _ in range(L)]),
        "wv": jnp.stack([tn(next(keys), (d, cfg.kvheads * hd)) for _ in range(L)]),
        "wo": jnp.stack([tn(next(keys), (cfg.nheads * hd, d)) for _ in range(L)]),
        "ffn_norm": jnp.ones((L, d), dtype),
        "gate": jnp.stack([tn(next(keys), (d, E)) for _ in range(L)]),
        "w1": jnp.stack([tn(next(keys), (E, d, h)) for _ in range(L)]),
        "w3": jnp.stack([tn(next(keys), (E, d, h)) for _ in range(L)]),
        "w2": jnp.stack([tn(next(keys), (E, h, d)) for _ in range(L)]),
    }
    return {
        "embedding": tn(next(keys), (cfg.src_vocab_size, d)),
        "layers": layers,
        "norm": jnp.ones((d,), dtype),
        "lm_head": tn(next(keys), (d, cfg.src_vocab_size)),
    }


def _moe_ffn(h, gate_w, w1, w3, w2, top_k):
    """Dense-mix top-k MoE SwiGLU. h (B, S, D); w1/w3 (E, D, H); w2 (E, H, D)."""
    router = (h @ gate_w).astype(jnp.float32)  # (B, S, E)
    top_vals, top_idx = jax.lax.top_k(router, top_k)
    weights = jax.nn.softmax(top_vals, axis=-1)  # renormalized over top-k
    E = gate_w.shape[-1]
    # scatter the top-k weights back to a dense (B, S, E) mixing matrix
    mix = jnp.sum(
        jax.nn.one_hot(top_idx, E, dtype=jnp.float32)
        * weights[..., None],
        axis=-2,
    )
    expert_out = jnp.einsum(
        "bseh,ehd->bsed",
        jax.nn.silu(jnp.einsum("bsd,edh->bseh", h, w1))
        * jnp.einsum("bsd,edh->bseh", h, w3),
        w2,
    )  # (B, S, E, D)
    return jnp.einsum("bse,bsed->bsd", mix.astype(h.dtype), expert_out)


def mixtral_forward(
    params: Params,
    tokens,
    cfg: MixtralConfig,
    *,
    compute_dtype=jnp.bfloat16,
    return_embeds: bool = False,
    **_unused,
):
    """tokens (B, S) -> logits (B, S, V); optionally the final hidden
    states (the Embed* contract)."""
    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    b, s = tokens.shape
    hd = cfg.head_dim
    x = params["embedding"][tokens]
    cos, sin = rope_table(s, hd, cfg.rope_theta)

    L = params["layers"]["wq"].shape[0]
    for i in range(L):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ lp["wq"]).reshape(b, s, cfg.nheads, hd)
        k = (h @ lp["wk"]).reshape(b, s, cfg.kvheads, hd)
        v = (h @ lp["wv"]).reshape(b, s, cfg.kvheads, hd)
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)
        o = attention(q, k, v, causal=True, impl="xla")
        x = x + o.reshape(b, s, -1) @ lp["wo"]
        h = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
        x = x + _moe_ffn(h, lp["gate"], lp["w1"], lp["w3"], lp["w2"], cfg.top_k)

    embeds = rms_norm(x, params["norm"], cfg.norm_eps)
    logits = embeds @ params["lm_head"]
    if return_embeds:
        return logits, embeds
    return logits
