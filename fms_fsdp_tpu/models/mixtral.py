"""Mixtral (sparse MoE Llama-family) — trainable model + speculator base.

The reference touches Mixtral only as a frozen speculator base
(``EmbedMixtral``, ref:speculator/train_speculator_utils.py:500-569,
with the model math imported from fms). Here it is both that frozen base
and a first-class trainable family: Llama-style attention (GQA + RoPE +
RMSNorm) with the FFN replaced by a top-k-of-E SwiGLU mixture, trained
with expert parallelism over the mesh's "expert" axis.

Two MoE implementations, selected by ``moe_impl``:

- ``"dense"`` (default; the frozen-base path): every expert computes every
  token, mixed by the renormalized top-k softmax weights. Exact and
  jit-trivial; costs E/top_k extra FFN FLOPs — fine for a frozen teacher.
- ``"dispatch"`` (the training path): capacity-based routing moved by one
  scatter-add and one gather. Each expert processes at most
  ``capacity = capacity_factor * top_k * S / E`` tokens per batch row;
  first choices fill buffers before second choices; overflow tokens drop
  that expert's contribution (their residual stream passes through).
  When the mesh has an expert axis > 1, the batch->expert reshard is an
  explicit ``lax.all_to_all`` in a shard_map manual over only that axis
  (``_moe_ffn_dispatch_a2a``); single-axis meshes use the plain GSPMD
  formulation.
- ``"dispatch_einsum"``: the same routing semantics expressed as
  GShard-style (B, S, E, C) one-hot einsums. Kept as the oracle the
  scatter path is tested against — the dispatch+combine einsum pair costs
  ``2 * B*S*E*C*D`` MACs with ``E*C = capacity_factor*top_k*S``
  (quadratic in S; ~25-50% of the expert FFN FLOPs at Mixtral shapes),
  where the scatter path is O(B*S*top_k*D) data movement.

The training path also returns the load-balancing auxiliary loss
(Switch-style f.p product, pre-scaled by cfg.aux_loss_weight).
"""

import functools
import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fms_fsdp_tpu.models.configs import MixtralConfig
from fms_fsdp_tpu.models.llama import attention_block
from fms_fsdp_tpu.obs.scopes import scoped
from fms_fsdp_tpu.ops.norms import rms_norm
from fms_fsdp_tpu.ops.quant import expert_matmul
from fms_fsdp_tpu.ops.rope import rope_table
from fms_fsdp_tpu.parallel.mesh import (
    AXIS_CONTEXT,
    AXIS_DCN,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_REPLICA,
    AXIS_TENSOR,
    DATA_AXES,
)
from fms_fsdp_tpu.parallel.sharding import constrain as _constrain

__all__ = [
    "MixtralConfig",
    "init_mixtral_params",
    "mixtral_forward",
    "mixtral_param_specs",
]

Params = Dict[str, Any]


def init_mixtral_params(key, cfg: MixtralConfig, dtype=jnp.float32) -> Params:
    d, hd, h, E = cfg.emb_dim, cfg.head_dim, cfg.hidden_dim, cfg.num_experts
    std = 0.02
    out_std = std / (2 * cfg.nlayers) ** 0.5
    keys = jax.random.split(key, 10)

    def tn(k, shape, s=std):
        return (
            jax.random.truncated_normal(k, -3, 3, shape, jnp.float32) * s
        ).astype(dtype)

    L = cfg.nlayers
    layers = {
        "attn_norm": jnp.ones((L, d), dtype),
        "wq": tn(keys[0], (L, d, cfg.nheads * hd)),
        "wk": tn(keys[1], (L, d, cfg.kvheads * hd)),
        "wv": tn(keys[2], (L, d, cfg.kvheads * hd)),
        "wo": tn(keys[3], (L, cfg.nheads * hd, d), out_std),
        "ffn_norm": jnp.ones((L, d), dtype),
        "gate": tn(keys[4], (L, d, E)),
        "w1": tn(keys[5], (L, E, d, h)),
        "w3": tn(keys[6], (L, E, d, h)),
        "w2": tn(keys[7], (L, E, h, d), out_std),
    }
    return {
        "embedding": tn(keys[8], (cfg.src_vocab_size, d)),
        "layers": layers,
        "norm": jnp.ones((d,), dtype),
        "lm_head": tn(keys[9], (d, cfg.src_vocab_size)),
    }


def mixtral_param_specs(scan: bool = True) -> Dict[str, Any]:
    """PartitionSpec tree: attention follows the Llama megatron layout;
    expert weights shard E over "expert" AND each expert's matrices over
    fsdp/tensor — EP composes with ZeRO-3 and TP instead of replacing
    them."""
    l = (None,) if scan else ()
    layers = {
        "attn_norm": P(*l, None),
        "wq": P(*l, AXIS_FSDP, AXIS_TENSOR),
        "wk": P(*l, AXIS_FSDP, AXIS_TENSOR),
        "wv": P(*l, AXIS_FSDP, AXIS_TENSOR),
        "wo": P(*l, AXIS_TENSOR, AXIS_FSDP),
        "ffn_norm": P(*l, None),
        # router weight replicated like the norms: it is trivially small
        # (D x E), and a D-over-fsdp-sharded router makes SPMD prefer the
        # (B, S, D) activation D-sharded too — the reshard back to batch
        # sharding is an involuntary-full-remat in the remat'd backward
        "gate": P(*l, None, None),
        "w1": P(*l, AXIS_EXPERT, AXIS_FSDP, AXIS_TENSOR),
        "w3": P(*l, AXIS_EXPERT, AXIS_FSDP, AXIS_TENSOR),
        "w2": P(*l, AXIS_EXPERT, AXIS_TENSOR, AXIS_FSDP),
    }
    return {
        "embedding": P(AXIS_TENSOR, AXIS_FSDP),
        "layers": layers,
        "norm": P(None),
        "lm_head": P(AXIS_FSDP, AXIS_TENSOR),
    }


def moe_capacity(cfg: MixtralConfig, seq_len: int) -> int:
    """Static per-expert buffer size per batch row."""
    return max(
        1,
        int(
            math.ceil(
                cfg.capacity_factor * cfg.top_k * seq_len / cfg.num_experts
            )
        ),
    )


@scoped("moe_router")
def _router(h, gate_w, cfg: MixtralConfig):
    """Shared routing math: renormalized top-k weights + aux loss.

    Returns (top_idx (B,S,K) int, top_w (B,S,K) fp32, aux scalar fp32).
    Router math is fp32 (softmax over logits from a bf16 matmul is
    routing-decision-critical; the matmul itself is tiny: D x E).
    """
    logits = (h @ gate_w).astype(jnp.float32)  # (B, S, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, cfg.top_k)
    top_w = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)

    # load-balancing aux loss (Switch eq. 4 generalized to top-k):
    # E * sum_e (fraction of choices routed to e) * (mean router prob of e);
    # minimized at 1.0 by a uniform router.
    E = cfg.num_experts
    choice = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)  # (B, S, K, E)
    f = jnp.mean(jnp.sum(choice, axis=2), axis=(0, 1)) / cfg.top_k
    p = jnp.mean(probs, axis=(0, 1))
    aux = cfg.aux_loss_weight * E * jnp.sum(f * p)
    return top_idx, top_w, aux


def _moe_stats(aux, keep=None):
    """Per-layer MoE stats: the load-balancing loss term plus the
    fraction of routing choices dropped by capacity overflow (0 for the
    dense path, which never drops)."""
    drop = (
        1.0 - jnp.mean(keep.astype(jnp.float32))
        if keep is not None
        else jnp.zeros((), jnp.float32)
    )
    return {"balance": aux, "drop_frac": drop}


@scoped("moe_dense")
def _moe_ffn_dense(h, lp, cfg: MixtralConfig):
    """Dense-mix top-k MoE SwiGLU (every expert computes every token).
    h (B, S, D); w1/w3 (E, D, H); w2 (E, H, D)."""
    top_idx, top_w, aux = _router(h, lp["gate"], cfg)
    E = cfg.num_experts
    mix = jnp.sum(
        jax.nn.one_hot(top_idx, E, dtype=jnp.float32) * top_w[..., None],
        axis=-2,
    )  # (B, S, E)
    expert_out = jnp.einsum(
        "bseh,ehd->bsed",
        jax.nn.silu(jnp.einsum("bsd,edh->bseh", h, lp["w1"]))
        * jnp.einsum("bsd,edh->bseh", h, lp["w3"]),
        lp["w2"],
    )  # (B, S, E, D)
    y = jnp.einsum("bse,bsed->bsd", mix.astype(h.dtype), expert_out)
    return y, _moe_stats(aux)


def _priority_slots(top_idx, E: int, C: int):
    """Per-choice expert-buffer slots under priority routing.

    Choice round k claims an expert's slots only after rounds < k have
    claimed theirs; within a round, tokens claim in sequence order.
    Returns ``(slot, keep)``, both (B, S, K): the buffer position within
    the chosen expert and whether it fit under capacity C.
    """
    counts = jnp.zeros((top_idx.shape[0], 1, E), jnp.int32)
    slots = []
    for k in range(top_idx.shape[-1]):
        mask_k = jax.nn.one_hot(top_idx[:, :, k], E, dtype=jnp.int32)
        pos_k = jnp.cumsum(mask_k, axis=1) - mask_k + counts  # (B, S, E)
        slots.append(
            jnp.take_along_axis(pos_k, top_idx[:, :, k, None], axis=-1)[..., 0]
        )
        counts = counts + jnp.sum(mask_k, axis=1, keepdims=True)
    slot = jnp.stack(slots, axis=-1)
    return slot, slot < C


def _expert_swiglu(xd, w1, w3, w2, quant, constrain_hidden=lambda t: t):
    """Per-expert SwiGLU chain over an E-major (E, B, C, D) tensor; the
    (E, B, C, H) hidden passes through ``constrain_hidden`` so each
    caller can apply its own layout (the manual-region caller must not
    mention the expert axis). Shared so the matmul/quant chain cannot
    drift between the GSPMD and all-to-all paths.

    E-major because E is the batch dim of the per-expert dot_generals and
    dot_general batch dims lead the output — B-major activations would
    pay a full relayout of every (E, B, C, H) product (int32-wide on the
    int8 path), measured as a net slowdown at Mixtral bench shapes."""
    hidden = jax.nn.silu(expert_matmul(xd, w1, quant=quant)) * expert_matmul(
        xd, w3, quant=quant
    )
    return expert_matmul(constrain_hidden(hidden), w2, quant=quant)


@scoped("expert_ffn")
def _expert_ffn(xd, lp, mesh, quant: str = "none"):
    """Expert SwiGLU with full GSPMD sharding: E over "expert", batch
    over dcn/replica/fsdp (tokens never leave their slice — the a2a pair
    stays on ICI), hidden width over "tensor"."""
    ep_spec = P(AXIS_EXPERT, (AXIS_DCN, AXIS_REPLICA, AXIS_FSDP), None, None)
    xd = _constrain(xd, ep_spec, mesh)
    out_e = _expert_swiglu(
        xd,
        lp["w1"],
        lp["w3"],
        lp["w2"],
        quant,
        lambda t: _constrain(
            t,
            P(
                AXIS_EXPERT,
                (AXIS_DCN, AXIS_REPLICA, AXIS_FSDP),
                None,
                AXIS_TENSOR,
            ),
            mesh,
        ),
    )
    return _constrain(out_e, ep_spec, mesh)


def _fill_expert_buffer(h, top_idx, slot, keep, C: int, E: int):
    """Scatter local batch rows into the flat E-major expert buffer.

    Returns (dest (B*S*K,) flat row indices — dropped choices point at
    the dump row — and the (E, B, C, D) buffer with the dump row sliced
    off). Shared by the single-program and all-to-all dispatch paths so
    the index arithmetic cannot drift between them.
    """
    B, S, D = h.shape
    K = top_idx.shape[-1]
    b_ix = jnp.arange(B, dtype=top_idx.dtype)[:, None, None]
    dest = jnp.where(keep, (top_idx * B + b_ix) * C + slot, E * B * C)
    dest = dest.reshape(B * S * K)
    src = jnp.broadcast_to(h[:, :, None, :], (B, S, K, D)).reshape(B * S * K, D)
    buf = jnp.zeros((E * B * C + 1, D), h.dtype).at[dest].add(src)
    return dest, buf[: E * B * C].reshape(E, B, C, D)


def _combine_from_buffer(out_e, dest, top_w, S: int):
    """Gather each choice's expert output back (dump row reads as the
    appended zero row) and mix with the renormalized router weights."""
    E, B, C, D = out_e.shape
    K = top_w.shape[-1]
    out_flat = jnp.concatenate(
        [out_e.reshape(E * B * C, D), jnp.zeros((1, D), out_e.dtype)], axis=0
    )
    gathered = jnp.take(out_flat, dest, axis=0).reshape(B, S, K, D)
    return jnp.einsum("bskd,bsk->bsd", gathered, top_w.astype(out_e.dtype))


@scoped("moe_dispatch")
def _moe_ffn_dispatch(
    h, lp, cfg: MixtralConfig, mesh: Optional[Mesh], quant: str = "none"
):
    """Capacity-based dispatch via scatter/gather — the training default.

    Routing semantics are identical to ``_moe_ffn_dispatch_einsum``
    (priority slot claiming, overflow drop), but token movement is one
    scatter-add into the flat (E*B*C)-row expert buffer and one gather
    back — O(B*S*K*D) HBM traffic, the same op class as an embedding
    update — instead of one-hot einsums whose MAC count is quadratic in
    S. Dropped choices target a trailing dump row that is sliced off
    before expert compute and gathered back as zeros. The buffer is laid
    out E-major (see ``_expert_ffn``).
    """
    B, S, D = h.shape
    E = cfg.num_experts
    C = moe_capacity(cfg, S)
    top_idx, top_w, aux = _router(h, lp["gate"], cfg)
    slot, keep = _priority_slots(top_idx, E, C)

    dest, xd = _fill_expert_buffer(h, top_idx, slot, keep, C, E)
    out_e = _expert_ffn(xd, lp, mesh, quant)
    y = _combine_from_buffer(out_e, dest, top_w, S)
    y = _constrain(y, P(DATA_AXES, AXIS_CONTEXT, None), mesh)
    return y, _moe_stats(aux, keep)


def _use_expert_a2a(
    cfg: MixtralConfig, mesh: Optional[Mesh], batch_size: int
) -> bool:
    """The explicit all-to-all path applies when the mesh actually has an
    expert axis to exchange over, it divides the expert count, and it
    divides the global batch (every shard_map input is batch-sharded on
    the expert axis, so a non-divisible batch fails at trace time)."""
    if mesh is None or AXIS_EXPERT not in mesh.shape:
        return False
    ep = int(mesh.shape[AXIS_EXPERT])
    if ep <= 1:
        return False
    from fms_fsdp_tpu.parallel.compat import has_new_shard_map

    if not has_new_shard_map():
        import warnings

        warnings.warn(
            "this jax version's legacy shard_map cannot express the"
            " partial-manual (expert-axis-only) a2a dispatch — its auto-"
            "subgroup partial manual mode hard-crashes the XLA SPMD"
            " partitioner. Falling back to the GSPMD dispatch (correct,"
            " ~E/top_k x the minimal expert-exchange traffic). Upgrade to"
            " jax >= 0.8 for the explicit EP all-to-all.",
            stacklevel=3,
        )
        return False
    if cfg.num_experts % ep != 0:
        import warnings

        warnings.warn(
            f"num_experts={cfg.num_experts} is not divisible by the expert"
            f" axis extent {ep}: falling back to the GSPMD dispatch, whose"
            " expert reshard replicates the token buffer across the expert"
            " axis (~E/top_k x the minimal all-to-all traffic). Pick"
            " expert_parallel_size dividing num_experts.",
            stacklevel=3,
        )
        return False
    if batch_size % ep != 0:
        import warnings

        warnings.warn(
            f"global batch {batch_size} is not divisible by the expert axis"
            f" extent {ep}: falling back to the GSPMD dispatch. Pick a batch"
            " size divisible by expert_parallel_size to enable the explicit"
            " all-to-all EP exchange.",
            stacklevel=3,
        )
        return False
    return True


@scoped("moe_dispatch_a2a")
def _moe_ffn_dispatch_a2a(
    h, lp, cfg: MixtralConfig, mesh: Mesh, quant: str = "none"
):
    """Scatter dispatch with an explicit expert-axis all-to-all (EP).

    Identical routing semantics to ``_moe_ffn_dispatch``, but the
    batch->expert reshard is written as ``lax.all_to_all`` inside a
    shard_map that is manual over ONLY the "expert" mesh axis — the
    fsdp/tensor sharding of the expert weights and the replica/fsdp
    sharding of the local batch stay with GSPMD. Left to GSPMD, the flat
    scatter/gather's expert reshard lowers to replicating the token
    buffer across the expert axis ("involuntary full rematerialization"
    SPMD warnings; ~E/top_k x the minimal traffic). The explicit a2a
    pair moves each token's top_k rows exactly once — the classic
    GShard/Switch EP exchange.

    Each shard scatters its local batch rows into a full (E, B_loc, C, D)
    buffer, the a2a splits the E dim across expert shards while
    concatenating the sender batches, experts compute on (E/ep,
    B_loc*ep, C, D), and the inverse a2a brings each token's rows home
    for the weighted combine.

    The router (and all stats) run OUTSIDE the manual region and the
    routing tensors enter the body batch-sharded: the body must have no
    expert-replicated differentiable inputs, because the shard_map
    transpose would psum their cotangents over the expert axis inside
    the manual region, and a bf16 all-reduce there crashes XLA:CPU's
    AllReducePromotion pass ("Invalid binary instruction opcode copy").
    """
    E = cfg.num_experts
    top_idx, top_w, aux = _router(h, lp["gate"], cfg)
    C = moe_capacity(cfg, h.shape[1])
    slot, keep = _priority_slots(top_idx, E, C)

    def body(h, top_idx, slot, keep, top_w, w1, w3, w2):
        S = h.shape[1]  # h here is this expert shard's batch rows
        dest, buf = _fill_expert_buffer(h, top_idx, slot, keep, C, E)
        xd = lax.all_to_all(
            buf, AXIS_EXPERT, split_axis=0, concat_axis=1, tiled=True
        )  # (E/ep, B*ep, C, D)
        # pin the token dim to the data axes and D to replicated: without
        # this, w1's (fsdp, tensor) sharding back-propagates a D-over-fsdp
        # preference through the buffer scatter into the residual stream,
        # which GSPMD can only satisfy by involuntary full remat. The
        # expert dim is manual here, so only auto axes may appear.
        token_spec = P(None, (AXIS_DCN, AXIS_REPLICA, AXIS_FSDP), None, None)
        xd = _constrain(xd, token_spec, mesh)
        out = _expert_swiglu(
            xd,
            w1,
            w3,
            w2,
            quant,
            # expert dim is manual here; only auto axes may appear
            lambda t: _constrain(t, P(None, None, None, AXIS_TENSOR), mesh),
        )
        out = _constrain(out, token_spec, mesh)
        out = lax.all_to_all(
            out, AXIS_EXPERT, split_axis=1, concat_axis=0, tiled=True
        )  # (E, B, C, D)
        return _combine_from_buffer(out, dest, top_w, S)

    from fms_fsdp_tpu.parallel.compat import shard_map as _shard_map

    y = _shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(AXIS_EXPERT),
            P(AXIS_EXPERT),
            P(AXIS_EXPERT),
            P(AXIS_EXPERT),
            P(AXIS_EXPERT),
            P(AXIS_EXPERT),
            P(AXIS_EXPERT),
            P(AXIS_EXPERT),
        ),
        out_specs=P(AXIS_EXPERT),
        axis_names=frozenset({AXIS_EXPERT}),
        check_vma=False,
    )(h, top_idx, slot, keep, top_w, lp["w1"], lp["w3"], lp["w2"])
    y = _constrain(y, P(DATA_AXES, AXIS_CONTEXT, None), mesh)
    return y, _moe_stats(aux, keep)


def _moe_ffn_dispatch_einsum(
    h, lp, cfg: MixtralConfig, mesh: Optional[Mesh], quant: str = "none"
):
    """Capacity-based einsum dispatch (GShard style) — oracle path.

    Builds (B, S, E, C) one-hot dispatch/combine tensors with first
    choices filling expert buffers before second choices, gathers tokens
    into an E-major (E, B, C, D) dispatched tensor, runs every expert's
    SwiGLU as batched matmuls, and scatters back weighted by the
    renormalized router weights.
    """
    B, S, D = h.shape
    E, K = cfg.num_experts, cfg.top_k
    C = moe_capacity(cfg, S)
    top_idx, top_w, aux = _router(h, lp["gate"], cfg)
    slot, keep = _priority_slots(top_idx, E, C)

    dispatch = jnp.zeros((B, S, E, C), h.dtype)
    combine = jnp.zeros((B, S, E, C), h.dtype)
    for k in range(K):
        d_k = (
            jax.nn.one_hot(top_idx[:, :, k], E, dtype=jnp.float32)[..., None]
            * jax.nn.one_hot(slot[:, :, k], C, dtype=jnp.float32)[:, :, None, :]
            * keep[:, :, k, None, None]
        ).astype(h.dtype)
        dispatch = dispatch + d_k
        combine = combine + d_k * top_w[:, :, k, None, None].astype(h.dtype)

    xd = jnp.einsum("bsec,bsd->ebcd", dispatch, h)
    out_e = _expert_ffn(xd, lp, mesh, quant)
    y = jnp.einsum("bsec,ebcd->bsd", combine, out_e)
    y = _constrain(y, P(DATA_AXES, AXIS_CONTEXT, None), mesh)
    return y, _moe_stats(aux, keep)


# ---------------------------------------------------------------------------
# cached decode (serving path — serve/families/mixtral.py)
# ---------------------------------------------------------------------------
#
# The attention half reuses the llama decode split (decode_layer_qkv /
# gqa_attend — the mixtral layer dict carries the same attn key names on
# purpose), so paged-vs-dense bit-parity rests on the exact zero-page
# argument serve/decode.py documents. The FFN half routes ONE token:
# ``moe_impl="dense"`` replays `_moe_ffn_dense` (every expert computes,
# mixed by the renormalized top-k weights — the parity mode, exact vs the
# dense forward); ``"routed"`` gathers only the top-k experts' weights per
# token — O(top_k/E) of the dense FLOPs, the serving default at scale.
# Both produce the same mixture (non-chosen experts carry exactly-zero
# mix weights), which tests/test_serving_families.py pins.


def _moe_token(h, lp, cfg: MixtralConfig, moe_impl: str = "dense"):
    """Single-position MoE FFN. h (B, m, D) post-ffn_norm."""
    if moe_impl == "dense":
        return _moe_ffn_dense(h, lp, cfg)[0]
    assert moe_impl == "routed", f"unknown decode moe_impl {moe_impl!r}"
    top_idx, top_w, _ = _router(h, lp["gate"], cfg)  # (B, m, K)
    w1 = lp["w1"][top_idx]  # (B, m, K, D, H)
    w3 = lp["w3"][top_idx]
    w2 = lp["w2"][top_idx]  # (B, m, K, H, D)
    hidden = jax.nn.silu(
        jnp.einsum("bmd,bmkdh->bmkh", h, w1)
    ) * jnp.einsum("bmd,bmkdh->bmkh", h, w3)
    out = jnp.einsum("bmkh,bmkhd->bmkd", hidden, w2)
    return jnp.einsum("bmkd,bmk->bmd", out, top_w.astype(h.dtype))


def _mixtral_decode_layer_out(x, layer, cfg: MixtralConfig, o, moe_impl: str):
    """Post-attention half of one decode layer: residual + routed MoE.
    Shared by the dense-cache reference walk and the paged decode step so
    the two cannot drift (the llama decode_layer_out analog)."""
    x = x + o @ layer["wo"]
    h2 = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
    return x + _moe_token(h2, layer, cfg, moe_impl)


def mixtral_prefill(
    params: Params,
    tokens,
    cfg: MixtralConfig,
    max_seq_len: int,
    compute_dtype=jnp.bfloat16,
    full_logits: bool = False,
):
    """Prompt prefill building the dense kv cache — the mixtral analog of
    models/generation.py::prefill (same cache layout (L, B, S_max, Nkv,
    H), zeros beyond the written prefix), with the FFN as the dense-mix
    MoE. Returns (logits, embeds, {"k", "v"} cache)."""
    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    b, s = tokens.shape
    hd, nkv = cfg.head_dim, cfg.n_kv_heads

    cos, sin = rope_table(max_seq_len, hd, cfg.rope_theta)
    x = params["embedding"][tokens]

    def body(x, layer):
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = (h @ layer["wq"]).reshape(b, s, cfg.nheads, hd)
        k = (h @ layer["wk"]).reshape(b, s, nkv, hd)
        v = (h @ layer["wv"]).reshape(b, s, nkv, hd)
        from fms_fsdp_tpu.ops.rope import apply_rotary

        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)
        from fms_fsdp_tpu.ops.attention import attention

        o = attention(q, k, v, causal=True, impl="xla")
        x = x + o.reshape(b, s, cfg.nheads * hd) @ layer["wo"]
        h2 = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
        x = x + _moe_ffn_dense(h2, layer, cfg)[0]
        pad = [(0, 0), (0, max_seq_len - s), (0, 0), (0, 0)]
        return x, (jnp.pad(k, pad), jnp.pad(v, pad))

    x, (k_cache, v_cache) = lax.scan(body, x, params["layers"])
    embeds = rms_norm(x, params["norm"], cfg.norm_eps)
    src = embeds if full_logits else embeds[:, -1:]
    logits = src @ params["lm_head"]
    return logits, embeds, {"k": k_cache, "v": v_cache}


def mixtral_decode_step(
    params: Params,
    cache,
    token,
    pos,
    cfg: MixtralConfig,
    compute_dtype=jnp.bfloat16,
    moe_impl: str = "dense",
):
    """One dense-cache decode step — the family's parity reference walk.
    token (B, 1) int32 at position ``pos``. Returns (logits (B, V),
    updated cache)."""
    from fms_fsdp_tpu.models.generation import decode_layer_qkv
    from fms_fsdp_tpu.ops.paged_attention import gqa_attend

    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    b, m = token.shape
    max_seq = cache["k"].shape[2]
    cos, sin = rope_table(max_seq, cfg.head_dim, cfg.rope_theta)
    positions = jnp.broadcast_to(
        pos + jnp.arange(m, dtype=jnp.int32)[None, :], (b, m)
    )
    x = params["embedding"][token]

    def body(x, inp):
        layer, k_cache, v_cache = inp
        q, k, v = decode_layer_qkv(x, layer, cfg, cos, sin, positions)
        k_cache = lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
        v_cache = lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))
        o = gqa_attend(q, k_cache, v_cache, positions)
        return (
            _mixtral_decode_layer_out(x, layer, cfg, o, moe_impl),
            (k_cache, v_cache),
        )

    x, (k_cache, v_cache) = lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"])
    )
    embeds = rms_norm(x, params["norm"], cfg.norm_eps)
    logits = embeds @ params["lm_head"]
    return logits[:, 0], {"k": k_cache, "v": v_cache}


def mixtral_paged_decode_step(
    params: Params,
    pools,
    page_table,
    seq_lens,
    tokens,
    cfg: MixtralConfig,
    *,
    page_size: int,
    compute_dtype=jnp.bfloat16,
    moe_impl: str = "dense",
):
    """One ragged paged decode step — serve/decode.py::paged_decode_step
    with the FFN swapped for the routed MoE. tokens (B,) int32 at
    positions ``seq_lens``; pools is the adapter's PagedKVCache.pools.
    Returns (logits (B, V), pools)."""
    from fms_fsdp_tpu.models.generation import decode_layer_qkv
    from fms_fsdp_tpu.ops.paged_attention import gather_pages, gqa_attend

    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    b = tokens.shape[0]
    max_seq = page_table.shape[1] * page_size
    cos, sin = rope_table(max_seq, cfg.head_dim, cfg.rope_theta)
    positions = seq_lens[:, None].astype(jnp.int32)
    x = params["embedding"][tokens[:, None]]

    rows = jnp.arange(b)
    page_ids = page_table[rows, seq_lens // page_size]
    slots = seq_lens % page_size

    def body(x, inp):
        layer, layer_pools = inp
        q, k, v = decode_layer_qkv(x, layer, cfg, cos, sin, positions)
        layer_pools = {
            "k": layer_pools["k"].at[page_ids, slots].set(k[:, 0]),
            "v": layer_pools["v"].at[page_ids, slots].set(v[:, 0]),
        }
        o = gqa_attend(
            q,
            gather_pages(layer_pools["k"], page_table),
            gather_pages(layer_pools["v"], page_table),
            positions,
        )
        return _mixtral_decode_layer_out(x, layer, cfg, o, moe_impl), layer_pools

    x, pools = lax.scan(body, x, (params["layers"], pools))
    embeds = rms_norm(x, params["norm"], cfg.norm_eps)
    logits = embeds @ params["lm_head"]
    return logits[:, 0], pools


def _mixtral_block(
    x,
    layer: Params,
    cfg: MixtralConfig,
    cos,
    sin,
    *,
    attn_impl: str,
    mesh: Optional[Mesh],
    quant: str,
    moe_impl: str,
):
    x = attention_block(
        x, layer, cfg, cos, sin, attn_impl=attn_impl, mesh=mesh, quant=quant
    )

    h = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
    if moe_impl == "dispatch":
        if _use_expert_a2a(cfg, mesh, h.shape[0]):
            y, aux = _moe_ffn_dispatch_a2a(h, layer, cfg, mesh, quant)
        else:
            y, aux = _moe_ffn_dispatch(h, layer, cfg, mesh, quant)
    elif moe_impl == "dispatch_einsum":
        y, aux = _moe_ffn_dispatch_einsum(h, layer, cfg, mesh, quant)
    else:
        y, aux = _moe_ffn_dense(h, layer, cfg)
    return x + y, aux


def mixtral_forward(
    params: Params,
    tokens,
    cfg: MixtralConfig,
    *,
    compute_dtype=jnp.bfloat16,
    attn_impl: str = "xla",
    ac_mask: Optional[List[bool]] = None,
    scan_layers: bool = True,
    mesh: Optional[Mesh] = None,
    moe_impl: str = "dense",
    return_embeds: bool = False,
    return_hidden: bool = False,
    return_aux: bool = False,
    quant: str = "none",
    **_unused,
):
    """tokens (B, S) -> logits (B, S, V) in the compute dtype.

    ``return_aux`` additionally returns a stats dict — ``"balance"``,
    the summed (pre-weighted) load-balancing loss the train step adds to
    the objective, and ``"drop_frac"``, the layer-mean fraction of
    routing choices dropped by capacity overflow (reported as a metric).
    ``return_embeds`` returns final hidden states (the frozen-base
    Embed* contract); ``return_hidden`` returns only them (fused-loss
    path).
    """
    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    b, s = tokens.shape
    nlayers = params["layers"]["wq"].shape[0]
    from fms_fsdp_tpu.parallel.sharding import embed_lookup

    x = embed_lookup(params["embedding"], tokens, mesh)
    cos, sin = rope_table(s, cfg.head_dim, cfg.rope_theta)

    block = functools.partial(
        _mixtral_block,
        cfg=cfg,
        cos=cos,
        sin=sin,
        attn_impl=attn_impl,
        mesh=mesh,
        quant=quant,
        moe_impl=moe_impl,
    )
    ac_mask = ac_mask if ac_mask is not None else [False] * nlayers
    uniform = all(ac_mask) or not any(ac_mask)

    if scan_layers and uniform:
        body = block
        if all(ac_mask):
            body = jax.checkpoint(block, prevent_cse=False)

        def scan_fn(carry, layer):
            y, stats = body(carry, layer)
            return y, stats

        x, stats_stack = lax.scan(scan_fn, x, params["layers"])
        aux_total = {
            "balance": jnp.sum(stats_stack["balance"]),
            "drop_frac": jnp.mean(stats_stack["drop_frac"]),
        }
    else:
        remat_block = jax.checkpoint(block, prevent_cse=False)
        per_layer = []
        for i in range(nlayers):
            layer = jax.tree.map(lambda a: a[i], params["layers"])
            x, stats = (remat_block if ac_mask[i] else block)(x, layer)
            per_layer.append(stats)
        aux_total = {
            "balance": sum(s["balance"] for s in per_layer),
            "drop_frac": sum(s["drop_frac"] for s in per_layer) / nlayers,
        }

    embeds = rms_norm(x, params["norm"], cfg.norm_eps)
    if return_hidden:
        return (embeds, aux_total) if return_aux else embeds
    logits = embeds @ params["lm_head"]
    logits = _constrain(logits, P(DATA_AXES, AXIS_CONTEXT, AXIS_TENSOR), mesh)
    if return_embeds:
        return logits, embeds
    if return_aux:
        return logits, aux_total
    return logits
