"""Llama-family decoder, TPU-native.

Replaces the reference's external model dependency (fms ``LLaMA`` /
``LLaMABlock``, imported at ref:main_training_llama.py:7) with a functional
JAX implementation:

- params are a plain pytree with all layers *stacked on a leading L axis*,
  so the layer stack runs as one ``lax.scan`` (one compiled block body —
  the XLA analog of wrapping every block as an identical FSDP unit);
- mixed precision is a cast at function entry (policies/mixed_precision);
- selective activation checkpointing is ``jax.checkpoint`` applied to the
  scan body (uniform masks) or to individual unrolled layers (fractional
  masks), selected by the reference-exact mask (parallel/ac.py);
- sharding is expressed only through constraints; GSPMD inserts the
  all-gathers/reduce-scatters the FSDP runtime does by hand.

Architecture degrees of freedom match the reference variant table
(ref:fms_fsdp/utils/config_utils.py:25-161): RMSNorm, RoPE with variant
theta, GQA, SwiGLU with multiple_of rounding, untied embeddings.
"""

import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fms_fsdp_tpu.models.configs import LlamaConfig
from fms_fsdp_tpu.ops.attention import attention
from fms_fsdp_tpu.ops.norms import rms_norm
from fms_fsdp_tpu.ops.quant import matmul as qmatmul
from fms_fsdp_tpu.ops.rope import apply_rotary, rope_table
from fms_fsdp_tpu.parallel.mesh import AXIS_CONTEXT, AXIS_TENSOR, DATA_AXES
from fms_fsdp_tpu.parallel.sharding import constrain

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_llama_params(
    key, cfg: LlamaConfig, dtype=jnp.float32, nlayers: Optional[int] = None
) -> Params:
    """Initialize the full param tree.

    Truncated-normal std 0.02 everywhere, with the residual-output
    projections (wo, w2) scaled by 1/sqrt(2*nlayers) (GPT-2-style depth
    scaling) so the residual stream variance is depth-independent.
    """
    nlayers = nlayers if nlayers is not None else cfg.nlayers
    d = cfg.emb_dim
    h = cfg.hidden_dim
    hd = cfg.head_dim
    nq, nkv = cfg.nheads, cfg.n_kv_heads
    v = cfg.src_vocab_size
    std = 0.02
    out_std = std / (2 * nlayers) ** 0.5

    keys = jax.random.split(key, 8)

    def tn(k, shape, s):
        return (jax.random.truncated_normal(k, -3, 3, shape, jnp.float32) * s).astype(
            dtype
        )

    L = nlayers
    layers = {
        "attn_norm": jnp.ones((L, d), dtype),
        "wq": tn(keys[0], (L, d, nq * hd), std),
        "wk": tn(keys[1], (L, d, nkv * hd), std),
        "wv": tn(keys[2], (L, d, nkv * hd), std),
        "wo": tn(keys[3], (L, nq * hd, d), out_std),
        "ffn_norm": jnp.ones((L, d), dtype),
        "w1": tn(keys[4], (L, d, h), std),
        "w3": tn(keys[5], (L, d, h), std),
        "w2": tn(keys[6], (L, h, d), out_std),
    }
    return {
        "embedding": tn(keys[7], (v, d), std),
        "layers": layers,
        "norm": jnp.ones((d,), dtype),
        "lm_head": tn(jax.random.fold_in(keys[7], 1), (d, v), std),
    }


def param_count(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


_constrain = constrain  # shared helper (parallel/sharding.py)


def attention_block(
    x,
    layer: Params,
    cfg,
    cos,
    sin,
    *,
    attn_impl: str,
    mesh: Optional[Mesh],
    quant: str = "none",
):
    """x + Attn(RMS(x)) — the attention residual half, shared by every
    Llama-family model (Llama, Mixtral). ``cfg`` needs head_dim / nheads /
    n_kv_heads / norm_eps; ``layer`` needs attn_norm / wq / wk / wv / wo.

    NOTE: params arrive pre-cast to the compute dtype (single cast site at
    the forward entry — that placement is what makes GSPMD all-gather
    bf16 bytes).
    """
    b, s, d = x.shape
    hd = cfg.head_dim
    nq, nkv = cfg.nheads, cfg.n_kv_heads

    # named scope: XPlane trace rows group under "attn" so profiler time
    # is attributable per block half (docs/observability.md)
    with jax.named_scope("attn"):
        head_spec = P(DATA_AXES, AXIS_CONTEXT, AXIS_TENSOR, None)
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = qmatmul(h, layer["wq"], quant=quant).reshape(b, s, nq, hd)
        k = qmatmul(h, layer["wk"], quant=quant).reshape(b, s, nkv, hd)
        v = qmatmul(h, layer["wv"], quant=quant).reshape(b, s, nkv, hd)
        q = _constrain(q, head_spec, mesh)
        k = _constrain(k, head_spec, mesh)
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)
        if mesh is not None and mesh.shape[AXIS_CONTEXT] > 1:
            # sequence sharded over the context axis: ring attention keeps
            # kv O(S/cp) per device instead of letting GSPMD all-gather it
            from fms_fsdp_tpu.ops.ring_attention import ring_attention

            o = ring_attention(q, k, v, mesh, causal=True)
        else:
            o = attention(q, k, v, causal=True, impl=attn_impl, mesh=mesh)
        o = qmatmul(o.reshape(b, s, nq * hd), layer["wo"], quant=quant)
        return x + _constrain(o, P(DATA_AXES, AXIS_CONTEXT, None), mesh)


def _llama_block(
    x,
    layer: Params,
    cfg: LlamaConfig,
    cos,
    sin,
    *,
    attn_impl: str,
    mesh: Optional[Mesh],
    quant: str = "none",
):
    """One decoder block: x + Attn(RMS(x)); then x + SwiGLU(RMS(x))."""
    b, s, d = x.shape
    x = attention_block(
        x, layer, cfg, cos, sin, attn_impl=attn_impl, mesh=mesh, quant=quant
    )

    with jax.named_scope("ffn"):
        h = rms_norm(x, layer["ffn_norm"], cfg.norm_eps)
        gate = jax.nn.silu(qmatmul(h, layer["w1"], quant=quant))
        up = qmatmul(h, layer["w3"], quant=quant)
        ffn = _constrain(
            gate * up, P(DATA_AXES, AXIS_CONTEXT, AXIS_TENSOR), mesh
        )
        ffn = qmatmul(ffn, layer["w2"], quant=quant)
        return x + _constrain(ffn, P(DATA_AXES, AXIS_CONTEXT, None), mesh)


def llama_forward(
    params: Params,
    tokens,
    cfg: LlamaConfig,
    *,
    compute_dtype=jnp.bfloat16,
    attn_impl: str = "auto",
    ac_mask: Optional[List[bool]] = None,
    scan_layers: bool = True,
    mesh: Optional[Mesh] = None,
    return_embeds: bool = False,
    return_hidden: bool = False,
    quant: str = "none",
):
    """tokens (B, S) int32 -> logits (B, S, V) in the compute dtype.

    Logits are NOT upcast here — at 128k vocab an fp32 copy would be the
    largest buffer in the step; the CE loss upcasts inside its reductions.
    """
    nlayers = params["layers"]["wq"].shape[0]
    # Cast the whole tree to compute dtype up front: with fp32 storage this
    # makes GSPMD's param all-gathers move bf16 bytes (the bfSixteen
    # comm-volume behavior, ref:policies/mixed_precision.py:11-15), not fp32.
    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    from fms_fsdp_tpu.parallel.sharding import embed_lookup

    with jax.named_scope("embed"):
        x = embed_lookup(params["embedding"], tokens, mesh)

    # RoPE positions are global; with a context axis the constraint above
    # keeps tokens sharded but positions stay absolute (table is replicated)
    seq_len = tokens.shape[1]
    cos, sin = rope_table(seq_len, cfg.head_dim, cfg.rope_theta)

    block = functools.partial(
        _llama_block,
        cfg=cfg,
        cos=cos,
        sin=sin,
        attn_impl=attn_impl,
        mesh=mesh,
        quant=quant,
    )
    ac_mask = ac_mask if ac_mask is not None else [False] * nlayers
    uniform = all(ac_mask) or not any(ac_mask)

    if scan_layers and uniform:
        body = block
        if all(ac_mask):
            body = jax.checkpoint(block, prevent_cse=False)

        def scan_fn(carry, layer):
            return body(carry, layer), None

        x, _ = lax.scan(scan_fn, x, params["layers"])
    else:
        remat_block = jax.checkpoint(block, prevent_cse=False)
        for i in range(nlayers):
            layer = jax.tree.map(lambda a: a[i], params["layers"])
            x = (remat_block if ac_mask[i] else block)(x, layer)

    x = rms_norm(x, params["norm"], cfg.norm_eps)
    if return_hidden:
        # final hidden states only — the fused lm-head+CE loss consumes
        # these and never materializes full logits
        return x
    with jax.named_scope("lm_head"):
        logits = x @ params["lm_head"]
    # Logits stay in compute dtype: at 128k vocab an fp32 copy is the
    # single largest buffer in the step. The loss upcasts inside its
    # reductions (fp32 logsumexp) without materializing an fp32 tensor.
    logits = _constrain(logits, P(DATA_AXES, AXIS_CONTEXT, AXIS_TENSOR), mesh)
    if return_embeds:
        # final-hidden-state capture for speculator training (the
        # reference's Embed* model variants + include_embeds flag,
        # ref:speculator/train_speculator_utils.py:430-569)
        return logits, x
    return logits
