"""Mamba2 hybrid LM, TPU-native.

Replaces the reference's external `mamba_ssm` dependency
(ref:main_training_mamba.py:8-13, MambaConfig dict at
ref:config_utils.py:162-185): a stack of pre-norm blocks where each block
is  residual + mixer(norm(residual)), then residual + mlp(norm2(residual))
(when d_intermediate > 0), with

- mixer = Mamba2 on most layers: fused in_proj -> (z | xBC | dt), depthwise
  causal conv1d with silu over xBC, softplus dt with learned bias,
  negative-exponential A per head, chunked SSD selective scan (ops/ssd.py),
  gated RMSNorm (norm(y * silu(z))), out_proj;
- mixer = causal MHA on `attn_layer_idx` layers (9/18/27 for mamba_9.8b)
  with GQA 32/8 heads, head_dim 128, partial rotary over the first 64 dims
  (ref attn_cfg, config_utils.py:170-179);
- swiglu MLP (d_intermediate) after every mixer;
- fp32 residual stream (`residual_in_fp32`), RMSNorm everywhere, untied
  embeddings with vocab padded to pad_vocab_size_multiple.

Layers are heterogeneous, so the stack runs as an unrolled loop (not
lax.scan); params live in a per-layer list pytree.
"""

import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fms_fsdp_tpu.models.configs import MambaConfig
from fms_fsdp_tpu.obs.scopes import scoped
from fms_fsdp_tpu.ops.attention import attention
from fms_fsdp_tpu.ops.norms import rms_norm
from fms_fsdp_tpu.ops.quant import matmul as qmatmul
from fms_fsdp_tpu.ops.rope import apply_rotary, rope_table
from fms_fsdp_tpu.ops.ssd import causal_conv1d, ssd_scan
from fms_fsdp_tpu.parallel.mesh import AXIS_CONTEXT, AXIS_FSDP, AXIS_TENSOR, DATA_AXES

Params = Dict[str, Any]


def _conv_dim(cfg: MambaConfig) -> int:
    return cfg.d_inner + 2 * cfg.ngroups * cfg.d_state


def _in_proj_dim(cfg: MambaConfig) -> int:
    return 2 * cfg.d_inner + 2 * cfg.ngroups * cfg.d_state + cfg.nheads


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_mamba_params(key, cfg: MambaConfig, dtype=jnp.float32) -> Params:
    d = cfg.d_model
    v = cfg.padded_vocab_size
    H = cfg.nheads
    std = 0.02
    out_std = std / (2 * cfg.n_layer) ** 0.5

    def tn(k, shape, s):
        return (jax.random.truncated_normal(k, -3, 3, shape, jnp.float32) * s).astype(
            dtype
        )

    keys = iter(jax.random.split(key, 8 * cfg.n_layer + 4))

    def mamba_mixer():
        # dt bias: softplus^-1 of dt ~ LogUniform[1e-3, 1e-1] (mamba2 init)
        u = jax.random.uniform(next(keys), (H,), jnp.float32)
        dt = jnp.exp(u * (jnp.log(0.1) - jnp.log(1e-3)) + jnp.log(1e-3))
        dt = jnp.clip(dt, 1e-4)
        dt_bias = dt + jnp.log(-jnp.expm1(-dt))
        # A ~ Uniform[1, 16]
        A = jax.random.uniform(next(keys), (H,), jnp.float32, 1.0, 16.0)
        return {
            "in_proj": tn(next(keys), (d, _in_proj_dim(cfg)), std),
            "conv_w": tn(next(keys), (_conv_dim(cfg), cfg.d_conv), std * 10),
            "conv_b": jnp.zeros((_conv_dim(cfg),), dtype),
            "dt_bias": dt_bias.astype(dtype),
            "A_log": jnp.log(A).astype(dtype),
            "D": jnp.ones((H,), dtype),
            "norm": jnp.ones((cfg.d_inner,), dtype),
            "out_proj": tn(next(keys), (cfg.d_inner, d), out_std),
        }

    def attn_mixer():
        a = cfg.attn_cfg
        hd = a.head_dim
        return {
            "wq": tn(next(keys), (d, a.num_heads * hd), std),
            "wk": tn(next(keys), (d, a.num_heads_kv * hd), std),
            "wv": tn(next(keys), (d, a.num_heads_kv * hd), std),
            "wo": tn(next(keys), (a.num_heads * hd, d), out_std),
        }

    layers: List[Params] = []
    for i in range(cfg.n_layer):
        layer = {
            "norm": jnp.ones((d,), dtype),
            "mixer": attn_mixer() if i in cfg.attn_layer_idx else mamba_mixer(),
        }
        if cfg.d_intermediate > 0:
            layer["norm2"] = jnp.ones((d,), dtype)
            layer["mlp"] = {
                "w1": tn(next(keys), (d, cfg.d_intermediate), std),
                "w3": tn(next(keys), (d, cfg.d_intermediate), std),
                "w2": tn(next(keys), (cfg.d_intermediate, d), out_std),
            }
        layers.append(layer)

    return {
        "embedding": tn(next(keys), (v, d), std),
        "layers": layers,
        "norm_f": jnp.ones((d,), dtype),
        "lm_head": tn(next(keys), (d, v), std),
    }


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


from fms_fsdp_tpu.parallel.sharding import constrain as _constrain  # noqa: E402


@scoped("mamba_mixer")
def _mamba_mixer(x, p: Params, cfg: MambaConfig, mesh, kernel="auto", quant="none"):
    """x (B, S, D) compute dtype -> (B, S, D)."""
    B, S, d = x.shape
    H, Pd, G, N = cfg.nheads, cfg.headdim, cfg.ngroups, cfg.d_state
    d_inner = cfg.d_inner

    zxbcdt = qmatmul(x, p["in_proj"], quant=quant)
    zxbcdt = _constrain(zxbcdt, P(DATA_AXES, AXIS_CONTEXT, AXIS_TENSOR), mesh)
    z = zxbcdt[..., :d_inner]
    xBC = zxbcdt[..., d_inner : d_inner + _conv_dim(cfg)]
    dt_raw = zxbcdt[..., d_inner + _conv_dim(cfg) :]  # (B, S, H)

    xBC = causal_conv1d(xBC, p["conv_w"], p["conv_b"], activation="silu")
    xs = xBC[..., :d_inner].reshape(B, S, H, Pd)
    Bm = xBC[..., d_inner : d_inner + G * N].reshape(B, S, G, N)
    Cm = xBC[..., d_inner + G * N :].reshape(B, S, G, N)

    dt = jax.nn.softplus(
        dt_raw.astype(jnp.float32) + p["dt_bias"].astype(jnp.float32)
    )
    A = -jnp.exp(p["A_log"].astype(jnp.float32))

    if mesh is not None and mesh.shape[AXIS_CONTEXT] > 1:
        # sequence sharded over the context axis: pass the inter-chunk
        # state across devices explicitly (ops/ssd.py::ssd_scan_cp) —
        # long context for the Mamba family, O(S/cp) per device, instead
        # of letting GSPMD gather the sequence around the chunk scan
        from fms_fsdp_tpu.ops.ssd import ssd_scan_cp

        y = ssd_scan_cp(
            xs, dt, A, Bm, Cm, p["D"], mesh=mesh, chunk_size=cfg.chunk_size,
            kernel=kernel,  # accepted for parity; the cp core is XLA
        )
    else:
        y = ssd_scan(
            xs, dt, A, Bm, Cm, p["D"], chunk_size=cfg.chunk_size,
            kernel=kernel, mesh=mesh,
        )
    y = y.reshape(B, S, d_inner)

    # gated RMSNorm: norm(y * silu(z)) (mamba2 norm_before_gate=False)
    y = rms_norm(y * jax.nn.silu(z), p["norm"], cfg.norm_eps)
    out = qmatmul(y, p["out_proj"], quant=quant)
    return _constrain(out, P(DATA_AXES, AXIS_CONTEXT, None), mesh)


@scoped("attn_mixer")
def _attn_mixer(x, p: Params, cfg: MambaConfig, cos, sin, attn_impl, mesh, quant="none"):
    B, S, d = x.shape
    a = cfg.attn_cfg
    hd = a.head_dim
    q = qmatmul(x, p["wq"], quant=quant).reshape(B, S, a.num_heads, hd)
    k = qmatmul(x, p["wk"], quant=quant).reshape(B, S, a.num_heads_kv, hd)
    v = qmatmul(x, p["wv"], quant=quant).reshape(B, S, a.num_heads_kv, hd)

    # partial rotary: first rotary_emb_dim dims of each head
    r = a.rotary_emb_dim
    if r and r < hd:
        q = jnp.concatenate(
            [apply_rotary(q[..., :r], cos, sin), q[..., r:]], axis=-1
        )
        k = jnp.concatenate(
            [apply_rotary(k[..., :r], cos, sin), k[..., r:]], axis=-1
        )
    elif r:
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)

    if mesh is not None and mesh.shape[AXIS_CONTEXT] > 1:
        from fms_fsdp_tpu.ops.ring_attention import ring_attention

        o = ring_attention(q, k, v, mesh, causal=a.causal)
    else:
        o = attention(q, k, v, causal=a.causal, impl=attn_impl, mesh=mesh)
    o = qmatmul(o.reshape(B, S, a.num_heads * hd), p["wo"], quant=quant)
    return _constrain(o, P(DATA_AXES, AXIS_CONTEXT, None), mesh)


@scoped("mlp")
def _mlp(x, p: Params, mesh, quant="none"):
    gate = jax.nn.silu(qmatmul(x, p["w1"], quant=quant))
    up = qmatmul(x, p["w3"], quant=quant)
    h = _constrain(gate * up, P(DATA_AXES, AXIS_CONTEXT, AXIS_TENSOR), mesh)
    return _constrain(
        qmatmul(h, p["w2"], quant=quant), P(DATA_AXES, AXIS_CONTEXT, None), mesh
    )


def mamba_forward(
    params: Params,
    tokens,
    cfg: MambaConfig,
    *,
    compute_dtype=jnp.bfloat16,
    attn_impl: str = "auto",
    ac_mask: Optional[List[bool]] = None,
    scan_layers: bool = False,  # heterogeneous layers: always unrolled
    mesh: Optional[Mesh] = None,
    return_hidden: bool = False,
    quant: str = "none",
    mamba_kernel: str = "auto",
):
    """tokens (B, S) int32 -> logits (B, S, padded_vocab) in compute dtype."""
    del scan_layers
    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    n_layer = len(params["layers"])
    ac_mask = ac_mask if ac_mask is not None else [False] * n_layer

    from fms_fsdp_tpu.parallel.sharding import embed_lookup

    x = embed_lookup(params["embedding"], tokens, mesh)
    residual = x.astype(jnp.float32)  # residual_in_fp32

    seq_len = tokens.shape[1]
    a = cfg.attn_cfg
    cos, sin = rope_table(seq_len, a.rotary_emb_dim or a.head_dim, 10000.0)

    def block(residual, layer, is_attn):
        h = rms_norm(residual.astype(compute_dtype), layer["norm"], cfg.norm_eps)
        if is_attn:
            out = _attn_mixer(
                h, layer["mixer"], cfg, cos, sin, attn_impl, mesh, quant=quant
            )
        else:
            out = _mamba_mixer(
                h, layer["mixer"], cfg, mesh, kernel=mamba_kernel, quant=quant
            )
        residual = residual + out.astype(jnp.float32)
        if "mlp" in layer:
            h = rms_norm(
                residual.astype(compute_dtype), layer["norm2"], cfg.norm_eps
            )
            residual = residual + _mlp(
                h, layer["mlp"], mesh, quant=quant
            ).astype(jnp.float32)
        return residual

    for i, layer in enumerate(params["layers"]):
        fn = functools.partial(block, is_attn=i in cfg.attn_layer_idx)
        if ac_mask[i]:
            fn = jax.checkpoint(fn, prevent_cse=False)
        residual = fn(residual, layer)

    x = rms_norm(residual.astype(compute_dtype), params["norm_f"], cfg.norm_eps)
    if return_hidden:
        return x
    logits = x @ params["lm_head"]
    return _constrain(logits, P(DATA_AXES, AXIS_CONTEXT, AXIS_TENSOR), mesh)


# ---------------------------------------------------------------------------
# sharding rulebook
# ---------------------------------------------------------------------------


def mamba_param_specs(cfg: MambaConfig) -> Params:
    """PartitionSpec tree matching init_mamba_params' structure."""

    def mamba_mixer():
        return {
            "in_proj": P(AXIS_FSDP, AXIS_TENSOR),
            "conv_w": P(AXIS_FSDP, None),
            "conv_b": P(AXIS_FSDP),
            "dt_bias": P(None),
            "A_log": P(None),
            "D": P(None),
            "norm": P(None),
            "out_proj": P(AXIS_TENSOR, AXIS_FSDP),
        }

    def attn_mixer():
        return {
            "wq": P(AXIS_FSDP, AXIS_TENSOR),
            "wk": P(AXIS_FSDP, AXIS_TENSOR),
            "wv": P(AXIS_FSDP, AXIS_TENSOR),
            "wo": P(AXIS_TENSOR, AXIS_FSDP),
        }

    layers = []
    for i in range(cfg.n_layer):
        layer = {
            "norm": P(None),
            "mixer": attn_mixer() if i in cfg.attn_layer_idx else mamba_mixer(),
        }
        if cfg.d_intermediate > 0:
            layer["norm2"] = P(None)
            layer["mlp"] = {
                "w1": P(AXIS_FSDP, AXIS_TENSOR),
                "w3": P(AXIS_FSDP, AXIS_TENSOR),
                "w2": P(AXIS_TENSOR, AXIS_FSDP),
            }
        layers.append(layer)

    return {
        "embedding": P(AXIS_TENSOR, AXIS_FSDP),
        "layers": layers,
        "norm_f": P(None),
        "lm_head": P(AXIS_FSDP, AXIS_TENSOR),
    }


