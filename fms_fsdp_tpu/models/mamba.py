"""Mamba2 hybrid LM, TPU-native.

Replaces the reference's external `mamba_ssm` dependency
(ref:main_training_mamba.py:8-13, MambaConfig dict at
ref:config_utils.py:162-185): a stack of pre-norm blocks where each block
is  residual + mixer(norm(residual)), then residual + mlp(norm2(residual))
(when d_intermediate > 0), with

- mixer = Mamba2 on most layers: fused in_proj -> (z | xBC | dt), depthwise
  causal conv1d with silu over xBC, softplus dt with learned bias,
  negative-exponential A per head, chunked SSD selective scan (ops/ssd.py),
  gated RMSNorm (norm(y * silu(z))), out_proj;
- mixer = causal MHA on `attn_layer_idx` layers (9/18/27 for mamba_9.8b)
  with GQA 32/8 heads, head_dim 128, partial rotary over the first 64 dims
  (ref attn_cfg, config_utils.py:170-179);
- swiglu MLP (d_intermediate) after every mixer;
- fp32 residual stream (`residual_in_fp32`), RMSNorm everywhere, untied
  embeddings with vocab padded to pad_vocab_size_multiple.

Layers are heterogeneous, so the stack runs as an unrolled loop (not
lax.scan); params live in a per-layer list pytree.
"""

import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from fms_fsdp_tpu.models.configs import MambaConfig
from fms_fsdp_tpu.obs.scopes import scoped
from fms_fsdp_tpu.ops.attention import attention
from fms_fsdp_tpu.ops.norms import rms_norm
from fms_fsdp_tpu.ops.quant import matmul as qmatmul
from fms_fsdp_tpu.ops.rope import apply_rotary, rope_table
from fms_fsdp_tpu.ops.ssd import causal_conv1d, ssd_scan
from fms_fsdp_tpu.parallel.mesh import AXIS_CONTEXT, AXIS_FSDP, AXIS_TENSOR, DATA_AXES

Params = Dict[str, Any]


def _conv_dim(cfg: MambaConfig) -> int:
    return cfg.d_inner + 2 * cfg.ngroups * cfg.d_state


def _in_proj_dim(cfg: MambaConfig) -> int:
    return 2 * cfg.d_inner + 2 * cfg.ngroups * cfg.d_state + cfg.nheads


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_mamba_params(key, cfg: MambaConfig, dtype=jnp.float32) -> Params:
    d = cfg.d_model
    v = cfg.padded_vocab_size
    H = cfg.nheads
    std = 0.02
    out_std = std / (2 * cfg.n_layer) ** 0.5

    def tn(k, shape, s):
        return (jax.random.truncated_normal(k, -3, 3, shape, jnp.float32) * s).astype(
            dtype
        )

    keys = iter(jax.random.split(key, 8 * cfg.n_layer + 4))

    def mamba_mixer():
        # dt bias: softplus^-1 of dt ~ LogUniform[1e-3, 1e-1] (mamba2 init)
        u = jax.random.uniform(next(keys), (H,), jnp.float32)
        dt = jnp.exp(u * (jnp.log(0.1) - jnp.log(1e-3)) + jnp.log(1e-3))
        dt = jnp.clip(dt, 1e-4)
        dt_bias = dt + jnp.log(-jnp.expm1(-dt))
        # A ~ Uniform[1, 16]
        A = jax.random.uniform(next(keys), (H,), jnp.float32, 1.0, 16.0)
        return {
            "in_proj": tn(next(keys), (d, _in_proj_dim(cfg)), std),
            "conv_w": tn(next(keys), (_conv_dim(cfg), cfg.d_conv), std * 10),
            "conv_b": jnp.zeros((_conv_dim(cfg),), dtype),
            "dt_bias": dt_bias.astype(dtype),
            "A_log": jnp.log(A).astype(dtype),
            "D": jnp.ones((H,), dtype),
            "norm": jnp.ones((cfg.d_inner,), dtype),
            "out_proj": tn(next(keys), (cfg.d_inner, d), out_std),
        }

    def attn_mixer():
        a = cfg.attn_cfg
        hd = a.head_dim
        return {
            "wq": tn(next(keys), (d, a.num_heads * hd), std),
            "wk": tn(next(keys), (d, a.num_heads_kv * hd), std),
            "wv": tn(next(keys), (d, a.num_heads_kv * hd), std),
            "wo": tn(next(keys), (a.num_heads * hd, d), out_std),
        }

    layers: List[Params] = []
    for i in range(cfg.n_layer):
        layer = {
            "norm": jnp.ones((d,), dtype),
            "mixer": attn_mixer() if i in cfg.attn_layer_idx else mamba_mixer(),
        }
        if cfg.d_intermediate > 0:
            layer["norm2"] = jnp.ones((d,), dtype)
            layer["mlp"] = {
                "w1": tn(next(keys), (d, cfg.d_intermediate), std),
                "w3": tn(next(keys), (d, cfg.d_intermediate), std),
                "w2": tn(next(keys), (cfg.d_intermediate, d), out_std),
            }
        layers.append(layer)

    return {
        "embedding": tn(next(keys), (v, d), std),
        "layers": layers,
        "norm_f": jnp.ones((d,), dtype),
        "lm_head": tn(next(keys), (d, v), std),
    }


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


from fms_fsdp_tpu.parallel.sharding import constrain as _constrain  # noqa: E402


@scoped("mamba_mixer")
def _mamba_mixer(x, p: Params, cfg: MambaConfig, mesh, kernel="auto", quant="none"):
    """x (B, S, D) compute dtype -> (B, S, D)."""
    B, S, d = x.shape
    H, Pd, G, N = cfg.nheads, cfg.headdim, cfg.ngroups, cfg.d_state
    d_inner = cfg.d_inner

    zxbcdt = qmatmul(x, p["in_proj"], quant=quant)
    zxbcdt = _constrain(zxbcdt, P(DATA_AXES, AXIS_CONTEXT, AXIS_TENSOR), mesh)
    z = zxbcdt[..., :d_inner]
    xBC = zxbcdt[..., d_inner : d_inner + _conv_dim(cfg)]
    dt_raw = zxbcdt[..., d_inner + _conv_dim(cfg) :]  # (B, S, H)

    xBC = causal_conv1d(xBC, p["conv_w"], p["conv_b"], activation="silu")
    xs = xBC[..., :d_inner].reshape(B, S, H, Pd)
    Bm = xBC[..., d_inner : d_inner + G * N].reshape(B, S, G, N)
    Cm = xBC[..., d_inner + G * N :].reshape(B, S, G, N)

    dt = jax.nn.softplus(
        dt_raw.astype(jnp.float32) + p["dt_bias"].astype(jnp.float32)
    )
    A = -jnp.exp(p["A_log"].astype(jnp.float32))

    if mesh is not None and mesh.shape[AXIS_CONTEXT] > 1:
        # sequence sharded over the context axis: pass the inter-chunk
        # state across devices explicitly (ops/ssd.py::ssd_scan_cp) —
        # long context for the Mamba family, O(S/cp) per device, instead
        # of letting GSPMD gather the sequence around the chunk scan
        from fms_fsdp_tpu.ops.ssd import ssd_scan_cp

        y = ssd_scan_cp(
            xs, dt, A, Bm, Cm, p["D"], mesh=mesh, chunk_size=cfg.chunk_size,
            kernel=kernel,  # accepted for parity; the cp core is XLA
        )
    else:
        y = ssd_scan(
            xs, dt, A, Bm, Cm, p["D"], chunk_size=cfg.chunk_size,
            kernel=kernel, mesh=mesh,
        )
    y = y.reshape(B, S, d_inner)

    # gated RMSNorm: norm(y * silu(z)) (mamba2 norm_before_gate=False)
    y = rms_norm(y * jax.nn.silu(z), p["norm"], cfg.norm_eps)
    out = qmatmul(y, p["out_proj"], quant=quant)
    return _constrain(out, P(DATA_AXES, AXIS_CONTEXT, None), mesh)


@scoped("attn_mixer")
def _attn_mixer(x, p: Params, cfg: MambaConfig, cos, sin, attn_impl, mesh, quant="none"):
    B, S, d = x.shape
    a = cfg.attn_cfg
    hd = a.head_dim
    q = qmatmul(x, p["wq"], quant=quant).reshape(B, S, a.num_heads, hd)
    k = qmatmul(x, p["wk"], quant=quant).reshape(B, S, a.num_heads_kv, hd)
    v = qmatmul(x, p["wv"], quant=quant).reshape(B, S, a.num_heads_kv, hd)

    # partial rotary: first rotary_emb_dim dims of each head
    r = a.rotary_emb_dim
    if r and r < hd:
        q = jnp.concatenate(
            [apply_rotary(q[..., :r], cos, sin), q[..., r:]], axis=-1
        )
        k = jnp.concatenate(
            [apply_rotary(k[..., :r], cos, sin), k[..., r:]], axis=-1
        )
    elif r:
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)

    if mesh is not None and mesh.shape[AXIS_CONTEXT] > 1:
        from fms_fsdp_tpu.ops.ring_attention import ring_attention

        o = ring_attention(q, k, v, mesh, causal=a.causal)
    else:
        o = attention(q, k, v, causal=a.causal, impl=attn_impl, mesh=mesh)
    o = qmatmul(o.reshape(B, S, a.num_heads * hd), p["wo"], quant=quant)
    return _constrain(o, P(DATA_AXES, AXIS_CONTEXT, None), mesh)


@scoped("mlp")
def _mlp(x, p: Params, mesh, quant="none"):
    gate = jax.nn.silu(qmatmul(x, p["w1"], quant=quant))
    up = qmatmul(x, p["w3"], quant=quant)
    h = _constrain(gate * up, P(DATA_AXES, AXIS_CONTEXT, AXIS_TENSOR), mesh)
    return _constrain(
        qmatmul(h, p["w2"], quant=quant), P(DATA_AXES, AXIS_CONTEXT, None), mesh
    )


def mamba_forward(
    params: Params,
    tokens,
    cfg: MambaConfig,
    *,
    compute_dtype=jnp.bfloat16,
    attn_impl: str = "auto",
    ac_mask: Optional[List[bool]] = None,
    scan_layers: bool = False,  # heterogeneous layers: always unrolled
    mesh: Optional[Mesh] = None,
    return_hidden: bool = False,
    quant: str = "none",
    mamba_kernel: str = "auto",
):
    """tokens (B, S) int32 -> logits (B, S, padded_vocab) in compute dtype."""
    del scan_layers
    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    n_layer = len(params["layers"])
    ac_mask = ac_mask if ac_mask is not None else [False] * n_layer

    from fms_fsdp_tpu.parallel.sharding import embed_lookup

    x = embed_lookup(params["embedding"], tokens, mesh)
    residual = x.astype(jnp.float32)  # residual_in_fp32

    seq_len = tokens.shape[1]
    a = cfg.attn_cfg
    cos, sin = rope_table(seq_len, a.rotary_emb_dim or a.head_dim, 10000.0)

    def block(residual, layer, is_attn):
        h = rms_norm(residual.astype(compute_dtype), layer["norm"], cfg.norm_eps)
        if is_attn:
            out = _attn_mixer(
                h, layer["mixer"], cfg, cos, sin, attn_impl, mesh, quant=quant
            )
        else:
            out = _mamba_mixer(
                h, layer["mixer"], cfg, mesh, kernel=mamba_kernel, quant=quant
            )
        residual = residual + out.astype(jnp.float32)
        if "mlp" in layer:
            h = rms_norm(
                residual.astype(compute_dtype), layer["norm2"], cfg.norm_eps
            )
            residual = residual + _mlp(
                h, layer["mlp"], mesh, quant=quant
            ).astype(jnp.float32)
        return residual

    for i, layer in enumerate(params["layers"]):
        fn = functools.partial(block, is_attn=i in cfg.attn_layer_idx)
        if ac_mask[i]:
            fn = jax.checkpoint(fn, prevent_cse=False)
        residual = fn(residual, layer)

    x = rms_norm(residual.astype(compute_dtype), params["norm_f"], cfg.norm_eps)
    if return_hidden:
        return x
    logits = x @ params["lm_head"]
    return _constrain(logits, P(DATA_AXES, AXIS_CONTEXT, AXIS_TENSOR), mesh)


# ---------------------------------------------------------------------------
# recurrent decode (serving path — serve/families/mamba.py)
# ---------------------------------------------------------------------------
#
# Serving decodes one token per step from O(1) recurrent state instead of a
# growing kv cache: per mamba layer a conv window (the last d_conv-1 xBC
# inputs) plus the fp32 SSD state h (H, headdim, d_state) — together a
# fixed-size slab whose bytes never grow with generated length. Every op
# below replays the exact per-token math of the sequence path
# (`causal_conv1d`'s shifted-FMA sum, `ssd_scan_reference`'s recurrence,
# the gated RMSNorm), which is what makes greedy recurrent decode bitwise
# equal to a dense full-forward walk under fp32 + mamba_kernel="reference"
# — the family's parity anchor (tests/test_serving_families.py). Hybrid
# configs' attn-mixer layers ride a kv cache supplied by the caller
# through ``attn_cb`` (dense buffers in prefill, the paged pools in
# serve-side decode).


def init_mamba_decode_state(
    cfg: MambaConfig, batch: int, compute_dtype=jnp.float32
) -> List[Params]:
    """Per-layer recurrent decode state for ``batch`` slots.

    Mamba layers: {"conv": (B, d_conv-1, conv_dim) compute dtype — the
    sliding window of pre-conv xBC inputs; "ssd": (B, H, headdim,
    d_state) fp32 — the carried SSD state}. Attention layers of hybrid
    configs hold no slab here ({}): their kv lives in the caller's
    paged pool."""
    state: List[Params] = []
    for i in range(cfg.n_layer):
        if i in cfg.attn_layer_idx:
            state.append({})
        else:
            state.append(
                {
                    "conv": jnp.zeros(
                        (batch, cfg.d_conv - 1, _conv_dim(cfg)), compute_dtype
                    ),
                    "ssd": jnp.zeros(
                        (batch, cfg.nheads, cfg.headdim, cfg.d_state),
                        jnp.float32,
                    ),
                }
            )
    return state


def mamba_state_bytes_per_stream(cfg: MambaConfig, compute_dtype=jnp.float32) -> int:
    """Slab bytes one decode stream holds — constant in generated length
    (the constant-memory claim a tier-1 test pins)."""
    itemsize = jnp.dtype(compute_dtype).itemsize
    n_mamba = cfg.n_layer - len(cfg.attn_layer_idx)
    conv = (cfg.d_conv - 1) * _conv_dim(cfg) * itemsize
    ssd = cfg.nheads * cfg.headdim * cfg.d_state * 4  # fp32
    return n_mamba * (conv + ssd)


def _mamba_mixer_step(x, st: Params, p: Params, cfg: MambaConfig):
    """One token through a Mamba2 mixer. x (B, D) post-norm hidden in the
    compute dtype; st the layer's {"conv", "ssd"} slab. Returns
    (out (B, D), new st). Op-for-op the single-position case of
    ``_mamba_mixer``: same split points, the conv as the same ascending-w
    fp32 FMA sum ``causal_conv1d`` unrolls, the state update as the same
    einsums ``ssd_scan_reference`` scans — the bit-parity contract."""
    B, d = x.shape
    H, Pd, G, N = cfg.nheads, cfg.headdim, cfg.ngroups, cfg.d_state
    d_inner = cfg.d_inner

    zxbcdt = x @ p["in_proj"]
    z = zxbcdt[..., :d_inner]
    xBC_in = zxbcdt[..., d_inner : d_inner + _conv_dim(cfg)]
    dt_raw = zxbcdt[..., d_inner + _conv_dim(cfg) :]  # (B, H)

    # causal conv over the window of the last d_conv inputs (current
    # token included) — the position-t row of causal_conv1d's output
    window = jnp.concatenate([st["conv"], xBC_in[:, None, :]], axis=1)
    wf = p["conv_w"].astype(jnp.float32)
    xBC = sum(
        window[:, w].astype(jnp.float32) * wf[None, :, w]
        for w in range(cfg.d_conv)
    )
    xBC = xBC + p["conv_b"].astype(jnp.float32)[None, :]
    xBC = jax.nn.silu(xBC).astype(x.dtype)

    xs = xBC[..., :d_inner].reshape(B, H, Pd)
    Bm = xBC[..., d_inner : d_inner + G * N].reshape(B, G, N)
    Cm = xBC[..., d_inner + G * N :].reshape(B, G, N)

    dt = jax.nn.softplus(
        dt_raw.astype(jnp.float32) + p["dt_bias"].astype(jnp.float32)
    )  # (B, H) fp32
    Af = -jnp.exp(p["A_log"].astype(jnp.float32))
    rep = H // G
    xf = xs.astype(jnp.float32)
    Bf = jnp.repeat(Bm.astype(jnp.float32), rep, axis=1)
    Cf = jnp.repeat(Cm.astype(jnp.float32), rep, axis=1)

    h_ssd = st["ssd"] * jnp.exp(dt * Af)[:, :, None, None] + jnp.einsum(
        "bh,bhn,bhp->bhpn", dt, Bf, xf
    )
    y = jnp.einsum("bhn,bhpn->bhp", Cf, h_ssd)
    y = y + p["D"].astype(jnp.float32)[None, :, None] * xf
    y = y.astype(x.dtype).reshape(B, d_inner)

    y = rms_norm(y * jax.nn.silu(z), p["norm"], cfg.norm_eps)
    out = y @ p["out_proj"]
    return out, {"conv": window[:, 1:], "ssd": h_ssd}


def _attn_qkv_step(h, p: Params, a, cos, sin, positions):
    """Projections + partial rotary for one decode position of a hybrid
    attn mixer. h (B, 1, D) post-norm; positions (B, 1) int32. Returns
    q (B, 1, nq, hd), k/v (B, 1, nkv, hd)."""
    B, m, _ = h.shape
    hd = a.head_dim
    q = (h @ p["wq"]).reshape(B, m, a.num_heads, hd)
    k = (h @ p["wk"]).reshape(B, m, a.num_heads_kv, hd)
    v = (h @ p["wv"]).reshape(B, m, a.num_heads_kv, hd)
    r = a.rotary_emb_dim
    if r and r < hd:
        q = jnp.concatenate(
            [apply_rotary(q[..., :r], cos, sin, positions), q[..., r:]], axis=-1
        )
        k = jnp.concatenate(
            [apply_rotary(k[..., :r], cos, sin, positions), k[..., r:]], axis=-1
        )
    elif r:
        q = apply_rotary(q, cos, sin, positions)
        k = apply_rotary(k, cos, sin, positions)
    return q, k, v


def _stack_step(params: Params, x_t, cfg: MambaConfig, states, attn_cb):
    """One token through the whole (heterogeneous) layer stack.

    x_t (B, D) embedding row in the compute dtype; ``attn_cb(j, h, mixer)
    -> (B, D)`` runs hybrid attn layer j (qkv + cache interaction + wo)
    against whatever cache the caller owns. Returns (residual (B, D)
    fp32, new per-layer states)."""
    compute_dtype = x_t.dtype
    residual = x_t.astype(jnp.float32)
    new_states = []
    attn_j = 0
    for i, layer in enumerate(params["layers"]):
        h = rms_norm(residual.astype(compute_dtype), layer["norm"], cfg.norm_eps)
        if i in cfg.attn_layer_idx:
            out = attn_cb(attn_j, h[:, None], layer["mixer"])
            attn_j += 1
            new_states.append(states[i])
        else:
            out, st = _mamba_mixer_step(h, states[i], layer["mixer"], cfg)
            new_states.append(st)
        residual = residual + out.astype(jnp.float32)
        if "mlp" in layer:
            h2 = rms_norm(
                residual.astype(compute_dtype), layer["norm2"], cfg.norm_eps
            )
            residual = residual + _mlp(h2, layer["mlp"], None).astype(
                jnp.float32
            )
    return residual, new_states


def mamba_prefill(
    params: Params,
    tokens,
    lengths,
    cfg: MambaConfig,
    *,
    compute_dtype=jnp.float32,
    kv_len: int = 0,
):
    """Prompt prefill by scanning the recurrent step over positions.

    tokens (B, S_pad) int32, lengths (B,) int32 actual prompt lengths
    (<= S_pad; state freezes per-row past its length, so bucketed
    padding never corrupts the slab). Returns (logits (B, V) of each
    row's last real position, per-layer state, kv) where kv is a dense
    {"k", "v"} cache (n_attn, B, kv_len, nkv, hd) for hybrid attn layers
    (None when the config has none) — page-multiple ``kv_len`` feeds
    PagedKVCache.write_prompt directly. Because every position runs the
    exact ops of the recurrent decode step, prefill state equals the
    state a token-by-token decode of the prompt would carry, bit for
    bit."""
    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    B, S_pad = tokens.shape
    a = cfg.attn_cfg
    n_attn = len(cfg.attn_layer_idx)
    states = init_mamba_decode_state(cfg, B, compute_dtype)

    if n_attn:
        kv_len = kv_len or S_pad
        assert kv_len >= S_pad, (kv_len, S_pad)
        kv = {
            "k": jnp.zeros(
                (n_attn, B, kv_len, a.num_heads_kv, a.head_dim), compute_dtype
            ),
            "v": jnp.zeros(
                (n_attn, B, kv_len, a.num_heads_kv, a.head_dim), compute_dtype
            ),
        }
        cos, sin = rope_table(kv_len, a.rotary_emb_dim or a.head_dim, 10000.0)
    else:
        kv = {}
        cos = sin = None

    last_res = jnp.zeros((B, cfg.d_model), jnp.float32)

    def body(carry, inp):
        states, kv, last_res = carry
        i, tok = inp
        live = i < lengths  # (B,) rows still inside their prompt
        x_t = params["embedding"][tok]

        def attn_cb(j, h, mixer):
            positions = jnp.full((B, 1), i, jnp.int32)
            q, k, v = _attn_qkv_step(h, mixer, a, cos, sin, positions)
            # zero padded rows' writes: the pages this buffer lands in
            # must match the zero-beyond-prompt discipline the llama
            # prefill keeps (kv_cache.py zero-page contract)
            k = jnp.where(live[:, None, None, None], k, 0)
            v = jnp.where(live[:, None, None, None], v, 0)
            kv["k"] = lax.dynamic_update_slice(
                kv["k"], k[None], (j, 0, i, 0, 0)
            )
            kv["v"] = lax.dynamic_update_slice(
                kv["v"], v[None], (j, 0, i, 0, 0)
            )
            from fms_fsdp_tpu.ops.paged_attention import gqa_attend

            o = gqa_attend(q, kv["k"][j], kv["v"][j], positions)
            return o[:, 0] @ mixer["wo"]

        residual, new_states = _stack_step(params, x_t, cfg, states, attn_cb)
        states = jax.tree.map(
            lambda n, o: jnp.where(
                live.reshape((B,) + (1,) * (n.ndim - 1)), n, o
            ),
            new_states,
            states,
        )
        last_res = jnp.where((i == lengths - 1)[:, None], residual, last_res)
        return (states, kv, last_res), None

    (states, kv, last_res), _ = lax.scan(
        body,
        (states, kv, last_res),
        (jnp.arange(S_pad, dtype=jnp.int32), jnp.moveaxis(tokens, 0, 1)),
    )
    x = rms_norm(last_res.astype(compute_dtype), params["norm_f"], cfg.norm_eps)
    logits = x @ params["lm_head"]
    return logits, states, (kv if n_attn else None)


def mamba_decode_step(
    params: Params,
    state,
    kv_pools,
    page_table,
    seq_lens,
    tokens,
    cfg: MambaConfig,
    *,
    page_size: int = 0,
    compute_dtype=jnp.float32,
):
    """One recurrent decode step for a ragged batch.

    tokens (B,) int32 — each row's current token at position
    ``seq_lens[b]``; ``state`` the per-layer slab (all B slots step
    together; an idle slot's slices update with garbage it alone reads —
    its next prefill overwrites them). Hybrid attn layers scatter k/v
    into ``kv_pools`` (n_attn-layer paged pools) exactly like
    serve/decode.py does for llama; pure-Mamba configs pass ``{}`` /
    ``None`` and touch no cache at all. Returns (logits (B, V), state,
    kv_pools)."""
    params = jax.tree.map(lambda a: a.astype(compute_dtype), params)
    B = tokens.shape[0]
    a = cfg.attn_cfg
    x_t = params["embedding"][tokens]

    if cfg.attn_layer_idx:
        from fms_fsdp_tpu.ops.paged_attention import gather_pages, gqa_attend

        max_seq = page_table.shape[1] * page_size
        cos, sin = rope_table(max_seq, a.rotary_emb_dim or a.head_dim, 10000.0)
        positions = seq_lens[:, None].astype(jnp.int32)
        rows = jnp.arange(B)
        page_ids = page_table[rows, seq_lens // page_size]
        slots = seq_lens % page_size
        new_pools = {"k": [], "v": []}

        def attn_cb(j, h, mixer):
            q, k, v = _attn_qkv_step(h, mixer, a, cos, sin, positions)
            k_pool = kv_pools["k"][j].at[page_ids, slots].set(k[:, 0])
            v_pool = kv_pools["v"][j].at[page_ids, slots].set(v[:, 0])
            new_pools["k"].append(k_pool)
            new_pools["v"].append(v_pool)
            o = gqa_attend(
                q,
                gather_pages(k_pool, page_table),
                gather_pages(v_pool, page_table),
                positions,
            )
            return o[:, 0] @ mixer["wo"]

    else:
        new_pools = None

        def attn_cb(j, h, mixer):  # pragma: no cover - unreachable
            raise AssertionError("attn layer in a config without attn_layer_idx")

    residual, state = _stack_step(params, x_t, cfg, state, attn_cb)
    x = rms_norm(residual.astype(compute_dtype), params["norm_f"], cfg.norm_eps)
    logits = x @ params["lm_head"]
    if cfg.attn_layer_idx:
        kv_pools = {
            "k": jnp.stack(new_pools["k"]),
            "v": jnp.stack(new_pools["v"]),
        }
    return logits, state, kv_pools


# ---------------------------------------------------------------------------
# sharding rulebook
# ---------------------------------------------------------------------------


def mamba_param_specs(cfg: MambaConfig) -> Params:
    """PartitionSpec tree matching init_mamba_params' structure."""

    def mamba_mixer():
        return {
            "in_proj": P(AXIS_FSDP, AXIS_TENSOR),
            "conv_w": P(AXIS_FSDP, None),
            "conv_b": P(AXIS_FSDP),
            "dt_bias": P(None),
            "A_log": P(None),
            "D": P(None),
            "norm": P(None),
            "out_proj": P(AXIS_TENSOR, AXIS_FSDP),
        }

    def attn_mixer():
        return {
            "wq": P(AXIS_FSDP, AXIS_TENSOR),
            "wk": P(AXIS_FSDP, AXIS_TENSOR),
            "wv": P(AXIS_FSDP, AXIS_TENSOR),
            "wo": P(AXIS_TENSOR, AXIS_FSDP),
        }

    layers = []
    for i in range(cfg.n_layer):
        layer = {
            "norm": P(None),
            "mixer": attn_mixer() if i in cfg.attn_layer_idx else mamba_mixer(),
        }
        if cfg.d_intermediate > 0:
            layer["norm2"] = P(None)
            layer["mlp"] = {
                "w1": P(AXIS_FSDP, AXIS_TENSOR),
                "w3": P(AXIS_FSDP, AXIS_TENSOR),
                "w2": P(AXIS_TENSOR, AXIS_FSDP),
            }
        layers.append(layer)

    return {
        "embedding": P(AXIS_TENSOR, AXIS_FSDP),
        "layers": layers,
        "norm_f": P(None),
        "lm_head": P(AXIS_FSDP, AXIS_TENSOR),
    }


