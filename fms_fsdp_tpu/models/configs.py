"""Model architecture configs.

``LlamaConfig`` carries the same architectural degrees of freedom the
reference exercises through fms's ``LLaMAConfig`` (variant table at
ref:fms_fsdp/utils/config_utils.py:25-161): emb_dim, nheads, kvheads (GQA),
nlayers, hidden_grow_factor + multiple_of (SwiGLU width rounding),
max_expected_seq_len, rope_theta, vocab size.

``MambaConfig`` mirrors the mamba_9.8b dict config
(ref:fms_fsdp/utils/config_utils.py:162-185): Mamba2 layers with a few
interleaved attention layers, RMSNorm, residual in fp32.

``MixtralConfig`` covers the sparse-MoE Llama family the reference touches
only as a frozen speculator base (ref:speculator/train_speculator_utils.py:
500-569); here it is additionally a first-class trainable family with
capacity-based routing and expert parallelism (models/mixtral.py).
"""

import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple


@dataclass(frozen=True)
class LlamaConfig:
    src_vocab_size: int = 32000
    emb_dim: int = 4096
    norm_eps: float = 1e-5
    nheads: int = 32
    kvheads: int = 0  # 0 -> MHA (kvheads = nheads), else GQA group count
    nlayers: int = 32
    hidden_grow_factor: float = 8 / 3
    multiple_of: int = 256
    max_expected_seq_len: int = 4096
    rope_theta: float = 10000.0
    p_dropout: float = 0.0

    @property
    def head_dim(self) -> int:
        return self.emb_dim // self.nheads

    @property
    def n_kv_heads(self) -> int:
        return self.kvheads if self.kvheads else self.nheads

    @property
    def hidden_dim(self) -> int:
        """SwiGLU inner width with multiple_of rounding (fms GatedLinearUnit)."""
        hidden = int(self.emb_dim * self.hidden_grow_factor)
        if self.multiple_of:
            hidden = self.multiple_of * (
                (hidden + self.multiple_of - 1) // self.multiple_of
            )
        return hidden

    def n_params(self, include_embeddings: bool = True) -> int:
        """Exact parameter count (untied input/output embeddings)."""
        d, h = self.emb_dim, self.hidden_dim
        kv_dim = self.n_kv_heads * self.head_dim
        per_layer = (
            d * d  # wq
            + 2 * d * kv_dim  # wk, wv
            + d * d  # wo
            + 3 * d * h  # w1 and w3 (d->h each), w2 (h->d)
            + 2 * d  # attn norm + ffn norm
        )
        total = self.nlayers * per_layer + d  # final norm
        if include_embeddings:
            total += 2 * self.src_vocab_size * d  # embed + lm head
        return int(total)


@dataclass(frozen=True)
class MambaAttnConfig:
    """Attention sub-config for hybrid Mamba (ref:config_utils.py:170-179)."""

    causal: bool = True
    d_conv: int = 0
    head_dim: int = 128
    num_heads: int = 32
    num_heads_kv: int = 8
    out_proj_bias: bool = False
    qkv_proj_bias: bool = False
    rotary_emb_dim: int = 64


@dataclass(frozen=True)
class MambaConfig:
    d_model: int = 4096
    d_intermediate: int = 14336  # MLP width; 0 -> no MLP block
    n_layer: int = 32
    vocab_size: int = 128256
    ssm_layer: str = "Mamba2"
    attn_layer_idx: Tuple[int, ...] = ()
    attn_cfg: MambaAttnConfig = field(default_factory=MambaAttnConfig)
    rms_norm: bool = True
    residual_in_fp32: bool = True
    fused_add_norm: bool = True
    pad_vocab_size_multiple: int = 16
    tie_embeddings: bool = False
    norm_eps: float = 1e-5
    # Mamba2 layer hyperparameters (mamba_ssm defaults)
    d_state: int = 128
    d_conv: int = 4
    expand: int = 2
    headdim: int = 64
    ngroups: int = 1
    chunk_size: int = 256

    @property
    def padded_vocab_size(self) -> int:
        m = self.pad_vocab_size_multiple
        return m * ((self.vocab_size + m - 1) // m)

    @property
    def d_inner(self) -> int:
        return self.expand * self.d_model

    @property
    def nheads(self) -> int:
        return self.d_inner // self.headdim

    def n_params(self) -> int:
        """Exact parameter count of the hybrid stack (see models/mamba.py)."""
        d = self.d_model
        conv_dim = self.d_inner + 2 * self.ngroups * self.d_state
        in_proj = 2 * self.d_inner + 2 * self.ngroups * self.d_state + self.nheads
        per_mamba = (
            d * in_proj
            + conv_dim * (self.d_conv + 1)  # conv weight + bias
            + 3 * self.nheads  # dt_bias, A_log, D
            + self.d_inner  # gated norm
            + self.d_inner * d  # out_proj
        )
        a = self.attn_cfg
        per_attn = d * a.head_dim * (a.num_heads * 2 + a.num_heads_kv * 2)
        per_mlp = 3 * d * self.d_intermediate + d if self.d_intermediate else 0
        n_attn = len(self.attn_layer_idx)
        total = (
            (self.n_layer - n_attn) * per_mamba
            + n_attn * per_attn
            + self.n_layer * (per_mlp + d)  # mlp (+norm2) and mixer norm
            + d  # final norm
            + 2 * self.padded_vocab_size * d
        )
        return int(total)


@dataclass(frozen=True)
class MixtralConfig:
    """Sparse-MoE Llama family (Mixtral). Frozen speculator base
    (the reference's EmbedMixtral) and trainable MoE model."""

    src_vocab_size: int = 32000
    emb_dim: int = 4096
    nheads: int = 32
    kvheads: int = 8
    nlayers: int = 32
    hidden_dim: int = 14336
    num_experts: int = 8
    top_k: int = 2
    max_expected_seq_len: int = 4096
    rope_theta: float = 1e6
    norm_eps: float = 1e-5
    # training-only knobs (ignored by the dense frozen-base path):
    # per-expert buffer size = capacity_factor * top_k * S / num_experts
    capacity_factor: float = 2.0
    # load-balancing auxiliary loss coefficient (HF router_aux_loss_coef)
    aux_loss_weight: float = 0.02

    @property
    def head_dim(self) -> int:
        return self.emb_dim // self.nheads

    @property
    def n_kv_heads(self) -> int:
        return self.kvheads if self.kvheads else self.nheads

    def n_params(self, include_embeddings: bool = True) -> int:
        d, h, E = self.emb_dim, self.hidden_dim, self.num_experts
        kv_dim = self.n_kv_heads * self.head_dim
        per_layer = (
            d * d  # wq
            + 2 * d * kv_dim  # wk, wv
            + d * d  # wo
            + d * E  # router gate
            + 3 * E * d * h  # per-expert w1, w3, w2
            + 2 * d  # norms
        )
        total = self.nlayers * per_layer + d
        if include_embeddings:
            total += 2 * self.src_vocab_size * d
        return int(total)
