"""HF -> native weight import for speculator base models.

The reference loads its speculator bases from HF-format checkpoints via
``fms.models.get_model(..., source="hf")``
(ref:speculator/train_speculator.py:115-131). Equivalent here: read a
local HF checkpoint directory with transformers and map the state dict
onto our native param trees. For Llama this is the exact inverse of
fms_to_hf_llama.params_to_hf_state_dict (transposes + naming).

Supported architectures (the reference's Embed* registry,
ref:speculator/train_speculator_utils.py:430-569):
  llama       -> models/llama.py tree
  gpt_bigcode -> models/gpt_bigcode.py tree
  mixtral     -> models/mixtral.py tree
"""

import numpy as np

import jax.numpy as jnp

from fms_fsdp_tpu.models.configs import LlamaConfig
from fms_fsdp_tpu.models.gpt_bigcode import GPTBigCodeConfig
from fms_fsdp_tpu.models.mixtral import MixtralConfig


def is_hf_checkpoint(path: str) -> bool:
    import os

    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, "config.json")
    )


def _sd(model):
    return {
        k: np.asarray(v.detach().to("cpu").float().numpy())
        for k, v in model.state_dict().items()
    }


def _to(x, dtype):
    return jnp.asarray(x, dtype=dtype)


def _stack(sd, fmt, nlayers, dtype, transpose=True):
    """Per-layer weights -> one stacked (L, ...) array; Linear weights
    (out, in) transpose to our (in, out)."""
    mats = [sd[fmt.format(i)] for i in range(nlayers)]
    if transpose:
        mats = [m.T for m in mats]
    return _to(np.stack(mats), dtype)


# ---------------------------------------------------------------------------
# llama
# ---------------------------------------------------------------------------


def llama_config_from_hf(hf_cfg) -> LlamaConfig:
    return LlamaConfig(
        src_vocab_size=hf_cfg.vocab_size,
        emb_dim=hf_cfg.hidden_size,
        nheads=hf_cfg.num_attention_heads,
        kvheads=(
            0
            if hf_cfg.num_key_value_heads == hf_cfg.num_attention_heads
            else hf_cfg.num_key_value_heads
        ),
        nlayers=hf_cfg.num_hidden_layers,
        # +0.5 then truncate: guarantees hidden_dim == intermediate_size
        # exactly regardless of float rounding in the ratio
        hidden_grow_factor=(hf_cfg.intermediate_size + 0.5)
        / hf_cfg.hidden_size,
        multiple_of=1,
        max_expected_seq_len=hf_cfg.max_position_embeddings,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        norm_eps=hf_cfg.rms_norm_eps,
    )


def hf_to_llama_params(model, cfg: LlamaConfig, dtype=jnp.bfloat16):
    """transformers LlamaForCausalLM -> native param tree (stacked layers)."""
    sd = _sd(model)

    def t(key):
        return sd[key].T

    def stack(fmt, transpose=True):
        return _stack(sd, fmt, cfg.nlayers, dtype, transpose)

    return {
        "embedding": _to(sd["model.embed_tokens.weight"], dtype),
        "layers": {
            "attn_norm": stack("model.layers.{}.input_layernorm.weight", False),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            "ffn_norm": stack(
                "model.layers.{}.post_attention_layernorm.weight", False
            ),
            "w1": stack("model.layers.{}.mlp.gate_proj.weight"),
            "w3": stack("model.layers.{}.mlp.up_proj.weight"),
            "w2": stack("model.layers.{}.mlp.down_proj.weight"),
        },
        "norm": _to(sd["model.norm.weight"], dtype),
        "lm_head": _to(t("lm_head.weight"), dtype),
    }


# ---------------------------------------------------------------------------
# gpt_bigcode
# ---------------------------------------------------------------------------


def gpt_bigcode_config_from_hf(hf_cfg) -> GPTBigCodeConfig:
    if not getattr(hf_cfg, "multi_query", True):
        raise ValueError(
            "GPTBigCode import supports the multi_query=True layout only "
            "(the StarCoder family); this checkpoint uses full MHA"
        )
    return GPTBigCodeConfig(
        src_vocab_size=hf_cfg.vocab_size,
        emb_dim=hf_cfg.n_embd,
        nheads=hf_cfg.n_head,
        nlayers=hf_cfg.n_layer,
        hidden_grow_factor=(hf_cfg.n_inner or 4 * hf_cfg.n_embd) / hf_cfg.n_embd,
        max_expected_seq_len=hf_cfg.n_positions,
        ln_eps=hf_cfg.layer_norm_epsilon,
    )


def hf_to_gpt_bigcode_params(model, cfg: GPTBigCodeConfig, dtype=jnp.bfloat16):
    sd = _sd(model)

    def stack(fmt, transpose=True):
        return _stack(sd, fmt, cfg.nlayers, dtype, transpose)

    return {
        "wte": _to(sd["transformer.wte.weight"], dtype),
        "wpe": _to(sd["transformer.wpe.weight"], dtype),
        "layers": {
            "ln1_w": stack("transformer.h.{}.ln_1.weight", False),
            "ln1_b": stack("transformer.h.{}.ln_1.bias", False),
            "c_attn": stack("transformer.h.{}.attn.c_attn.weight"),
            "attn_proj": stack("transformer.h.{}.attn.c_proj.weight"),
            "ln2_w": stack("transformer.h.{}.ln_2.weight", False),
            "ln2_b": stack("transformer.h.{}.ln_2.bias", False),
            "c_fc": stack("transformer.h.{}.mlp.c_fc.weight"),
            "mlp_proj": stack("transformer.h.{}.mlp.c_proj.weight"),
        },
        "ln_f_w": _to(sd["transformer.ln_f.weight"], dtype),
        "ln_f_b": _to(sd["transformer.ln_f.bias"], dtype),
    }


# ---------------------------------------------------------------------------
# mixtral
# ---------------------------------------------------------------------------


def mixtral_config_from_hf(hf_cfg) -> MixtralConfig:
    return MixtralConfig(
        src_vocab_size=hf_cfg.vocab_size,
        emb_dim=hf_cfg.hidden_size,
        nheads=hf_cfg.num_attention_heads,
        kvheads=hf_cfg.num_key_value_heads,
        nlayers=hf_cfg.num_hidden_layers,
        hidden_dim=hf_cfg.intermediate_size,
        num_experts=hf_cfg.num_local_experts,
        top_k=hf_cfg.num_experts_per_tok,
        max_expected_seq_len=hf_cfg.max_position_embeddings,
        rope_theta=hf_cfg.rope_theta,
        norm_eps=hf_cfg.rms_norm_eps,
        aux_loss_weight=getattr(hf_cfg, "router_aux_loss_coef", 0.02),
    )


def hf_to_mixtral_params(model, cfg: MixtralConfig, dtype=jnp.bfloat16):
    sd = _sd(model)

    def stack(fmt, transpose=True):
        return _stack(sd, fmt, cfg.nlayers, dtype, transpose)

    def stack_experts(fmt):
        # (L, E, in, out) from per-expert Linear weights (out, in)
        return _to(
            np.stack(
                [
                    np.stack(
                        [
                            sd[fmt.format(i, e)].T
                            for e in range(cfg.num_experts)
                        ]
                    )
                    for i in range(cfg.nlayers)
                ]
            ),
            dtype,
        )

    return {
        "embedding": _to(sd["model.embed_tokens.weight"], dtype),
        "layers": {
            "attn_norm": stack("model.layers.{}.input_layernorm.weight", False),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            "ffn_norm": stack(
                "model.layers.{}.post_attention_layernorm.weight", False
            ),
            "gate": stack("model.layers.{}.block_sparse_moe.gate.weight"),
            "w1": stack_experts("model.layers.{}.block_sparse_moe.experts.{}.w1.weight"),
            "w3": stack_experts("model.layers.{}.block_sparse_moe.experts.{}.w3.weight"),
            "w2": stack_experts("model.layers.{}.block_sparse_moe.experts.{}.w2.weight"),
        },
        "norm": _to(sd["model.norm.weight"], dtype),
        "lm_head": _to(sd["lm_head.weight"].T, dtype),
    }


# ---------------------------------------------------------------------------
# loader
# ---------------------------------------------------------------------------

_ARCHS = {
    "llama": (llama_config_from_hf, hf_to_llama_params),
    "gpt_bigcode": (gpt_bigcode_config_from_hf, hf_to_gpt_bigcode_params),
    "mixtral": (mixtral_config_from_hf, hf_to_mixtral_params),
}


def load_hf_base(path: str, dtype=jnp.bfloat16):
    """Load a local HF checkpoint; returns (arch, native_cfg, params)."""
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_cfg = AutoConfig.from_pretrained(path)
    arch = hf_cfg.model_type
    if arch not in _ARCHS:
        raise ValueError(
            f"unsupported HF base architecture {arch!r}; "
            f"supported: {sorted(_ARCHS)}"
        )
    model = AutoModelForCausalLM.from_pretrained(path, torch_dtype="float32")
    cfg_fn, map_fn = _ARCHS[arch]
    cfg = cfg_fn(hf_cfg)
    params = map_fn(model, cfg, dtype=dtype)
    del model
    return arch, cfg, params
