"""Speculative decoding: the inference-side consumer of the trained
MLPSpeculator.

The reference trains speculators for fms-extras' speculative_generate;
this module closes the loop natively (beyond fms-fsdp itself, which ships
only the training half): the speculator proposes ``n_predict`` tokens per
step, the frozen base verifies the whole candidate chain in ONE cached
forward over n_predict+1 positions, and the longest matching prefix is
accepted — greedy speculative decoding reproduces plain greedy decoding
token-for-token while running the base ~(accepted+1) tokens per forward.

Single-candidate chain (no tree), greedy acceptance, batch size 1 (the
accepted length is data-dependent per row; a batched variant needs
per-row bookkeeping).
"""

from typing import Dict

import jax
import jax.numpy as jnp

from fms_fsdp_tpu.models.configs import LlamaConfig
from fms_fsdp_tpu.models.generation import decode_chunk, prefill
from fms_fsdp_tpu.models.speculator import (
    SpeculatorConfig,
    head_step,
    scale_input,
)


def speculator_propose(spec_params, embed, last_tok, scfg: SpeculatorConfig):
    """Greedy n_predict-token proposal chain. embed (B, D): the base
    hidden state that predicted ``last_tok`` (B,). Returns (B, n_predict)
    int32 — each head's argmax feeds the next head's token input
    (at inference the teacher-forced inds of speculator_forward are the
    chain of the speculator's own picks)."""
    state = scale_input(embed[:, None, :], scfg)  # (B, 1, D)

    tok = last_tok[:, None]  # (B, 1)
    outs = []
    for i in range(scfg.n_predict):
        state, logits = head_step(spec_params, scfg, state, tok, i)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, 1)
        outs.append(tok)
    return jnp.concatenate(outs, axis=1)  # (B, n_predict)


def speculative_decode(
    base_params,
    spec_params,
    input_ids,
    cfg: LlamaConfig,
    scfg: SpeculatorConfig,
    *,
    max_seq_len: int = 2048,
    max_new_tokens: int = 64,
) -> Dict[str, jnp.ndarray]:
    """Greedy speculative decoding. Returns {"tokens": (1, P+T),
    "accept_rate": mean accepted proposals per verification}.

    Output is token-identical to plain greedy decoding: a proposal is
    accepted only when it equals the base's own greedy pick, and the
    first mismatch position emits the base's pick instead.
    """
    assert input_ids.shape[0] == 1, "speculative_decode is B=1 (see module doc)"
    n = scfg.n_predict
    b, plen = input_ids.shape
    assert plen + max_new_tokens + n + 1 <= max_seq_len

    logits, embeds, cache = prefill(base_params, input_ids, cfg, max_seq_len)
    last_tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)  # (B,)
    state_embed = embeds[:, -1]
    pos = plen

    chunk = jax.jit(decode_chunk, static_argnames=("cfg",))
    propose = jax.jit(speculator_propose, static_argnames=("scfg",))

    out = [int(last_tok[0])]
    accepted_counts = []
    while len(out) < max_new_tokens:
        props = propose(spec_params, state_embed, last_tok, scfg)  # (1, n)
        cand = jnp.concatenate([last_tok[:, None], props], axis=1)  # (1, n+1)
        logits, embeds, cache = chunk(base_params, cache, cand, pos, cfg)
        base_next = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (1, n+1)
        match = jnp.cumprod(
            (props == base_next[:, :-1]).astype(jnp.int32), axis=1
        )
        # ONE host sync per verification step — per-element int() pulls
        # would each pay a full device round trip through the tunnel
        props_h, next_h, match_h = jax.device_get((props, base_next, match))
        k = int(match_h[0].sum())  # accepted proposals (0..n)
        accepted_counts.append(k)
        out.extend([int(t) for t in props_h[0, :k]] + [int(next_h[0, k])])
        last_tok = base_next[:, k]
        state_embed = embeds[:, k]
        pos = pos + k + 1

    tokens = jnp.concatenate(
        [input_ids, jnp.asarray(out[:max_new_tokens], jnp.int32)[None, :]],
        axis=1,
    )
    rate = float(sum(accepted_counts)) / max(1, len(accepted_counts))
    return {"tokens": tokens, "accept_rate": rate}
