from fms_fsdp_tpu.models.configs import LlamaConfig, MambaConfig

__all__ = ["LlamaConfig", "MambaConfig", "get_model_api"]


def get_model_api(model_cfg):
    """Dispatch a model config to (init_fn, forward_fn, specs_fn, n_layers).

    init_fn(key, cfg, dtype) -> params; forward_fn(params, tokens, cfg, ...)
    -> logits; specs_fn() -> PartitionSpec tree mirroring params.
    """
    if isinstance(model_cfg, MambaConfig):
        from fms_fsdp_tpu.models.mamba import (
            init_mamba_params,
            mamba_forward,
            mamba_param_specs,
        )

        return (
            init_mamba_params,
            mamba_forward,
            lambda: mamba_param_specs(model_cfg),
            model_cfg.n_layer,
        )
    if isinstance(model_cfg, LlamaConfig):
        from fms_fsdp_tpu.models.llama import init_llama_params, llama_forward
        from fms_fsdp_tpu.parallel.sharding import llama_param_specs

        return init_llama_params, llama_forward, llama_param_specs, model_cfg.nlayers
    raise TypeError(f"unknown model config type: {type(model_cfg).__name__}")
