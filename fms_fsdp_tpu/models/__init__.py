from fms_fsdp_tpu.models.configs import LlamaConfig, MambaConfig, MixtralConfig

__all__ = [
    "LlamaConfig",
    "MambaConfig",
    "MixtralConfig",
    "get_model_api",
    "get_base_api",
]


def get_model_api(model_cfg):
    """Dispatch a model config to (init_fn, forward_fn, specs_fn, n_layers).

    init_fn(key, cfg, dtype) -> params; forward_fn(params, tokens, cfg, ...)
    -> logits; specs_fn() -> PartitionSpec tree mirroring params.
    """
    if isinstance(model_cfg, MixtralConfig):
        from fms_fsdp_tpu.models.mixtral import (
            init_mixtral_params,
            mixtral_forward,
            mixtral_param_specs,
        )

        return (
            init_mixtral_params,
            mixtral_forward,
            mixtral_param_specs,
            model_cfg.nlayers,
        )
    if isinstance(model_cfg, MambaConfig):
        from fms_fsdp_tpu.models.mamba import (
            init_mamba_params,
            mamba_forward,
            mamba_param_specs,
        )

        return (
            init_mamba_params,
            mamba_forward,
            lambda: mamba_param_specs(model_cfg),
            model_cfg.n_layer,
        )
    if isinstance(model_cfg, LlamaConfig):
        from fms_fsdp_tpu.models.llama import init_llama_params, llama_forward
        from fms_fsdp_tpu.parallel.sharding import llama_param_specs

        return init_llama_params, llama_forward, llama_param_specs, model_cfg.nlayers
    raise TypeError(f"unknown model config type: {type(model_cfg).__name__}")


class BaseModelAPI:
    """Frozen speculator-base contract (the reference's Embed* registry,
    ref:speculator/train_speculator_utils.py:430-569): a forward that also
    yields final hidden states, and a sampling generate that can return
    per-position embeds."""

    def __init__(self, arch, init_fn, forward_embeds, generate_fn, specs_fn):
        self.arch = arch
        self.init = init_fn
        self.forward_embeds = forward_embeds  # (params, tokens, cfg) -> (logits, embeds)
        self.generate = generate_fn  # (params, prompts, cfg, key=..., ...) -> toks[, embeds]
        self.param_specs = specs_fn  # () -> PartitionSpec tree for shard_params


def get_base_api(arch: str) -> "BaseModelAPI":
    """arch: the reference's model_arch values — embedllama /
    embedgptbigcode / embedmixtral (bare HF names accepted too)."""
    key = arch.lower().removeprefix("embed")
    if key == "llama":
        from fms_fsdp_tpu.models.generation import generate
        from fms_fsdp_tpu.models.llama import init_llama_params, llama_forward
        from fms_fsdp_tpu.parallel.sharding import llama_param_specs

        def fwd(params, tokens, cfg, **kw):
            return llama_forward(params, tokens, cfg, return_embeds=True, **kw)

        return BaseModelAPI(
            "llama", init_llama_params, fwd, generate, llama_param_specs
        )
    if key in ("gptbigcode", "gpt_bigcode"):
        from fms_fsdp_tpu.models.gpt_bigcode import (
            generate_simple,
            gpt_bigcode_forward,
            gpt_bigcode_param_specs,
            init_gpt_bigcode_params,
        )

        def fwd(params, tokens, cfg, **kw):
            return gpt_bigcode_forward(
                params, tokens, cfg, return_embeds=True, **kw
            )

        def gen(params, prompts, cfg, **kw):
            return generate_simple(
                params, prompts, cfg, gpt_bigcode_forward, **kw
            )

        return BaseModelAPI(
            "gpt_bigcode",
            init_gpt_bigcode_params,
            fwd,
            gen,
            gpt_bigcode_param_specs,
        )
    if key == "mixtral":
        from fms_fsdp_tpu.models.gpt_bigcode import generate_simple
        from fms_fsdp_tpu.models.mixtral import (
            init_mixtral_params,
            mixtral_forward,
            mixtral_param_specs,
        )

        def fwd(params, tokens, cfg, **kw):
            return mixtral_forward(params, tokens, cfg, return_embeds=True, **kw)

        def gen(params, prompts, cfg, **kw):
            return generate_simple(params, prompts, cfg, mixtral_forward, **kw)

        return BaseModelAPI(
            "mixtral", init_mixtral_params, fwd, gen, mixtral_param_specs
        )
    raise ValueError(f"unknown speculator base arch: {arch!r}")
