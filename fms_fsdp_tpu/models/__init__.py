from fms_fsdp_tpu.models.configs import LlamaConfig, MambaConfig

__all__ = ["LlamaConfig", "MambaConfig"]
