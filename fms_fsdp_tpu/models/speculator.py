"""MLPSpeculator — the speculative-decoding head trained by the
speculator pipeline.

Functional port of the architecture the reference imports from fms-extras
(ref:speculator/train_speculator.py:8-15, constructed with n_predict /
inner width / tie-weights / scale-input knobs from the config,
ref:config/training.py:63-70): a stack of ``n_predict`` small MLP
predictors where head i refines a running state from (a) the previous
state and (b) the embedding of the most recent known/predicted token,

    state_i = gelu(LN_i(proj_i(state_{i-1}) * w_s + emb_i(tok_i) * w_e))
    logits_i = head_i(state_i)

with w_s = 0.5 ** (0.5 / n_predict) and w_e = sqrt(1 - w_s^2) keeping the
state variance constant across heads. ``tie_weights`` shares emb/ln/head
(and proj for i >= 1) across heads; ``scale_input`` layernorms the
incoming base-model embedding (no affine) scaled by 1/sqrt(2).
"""

import pickle
from dataclasses import asdict, dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@dataclass(frozen=True)
class SpeculatorConfig:
    emb_dim: int
    inner_dim: int
    vocab_size: int
    n_predict: int
    tie_weights: bool = True
    scale_input: bool = True

    @classmethod
    def from_train_config(cls, cfg, emb_dim: int, vocab_size: int):
        return cls(
            emb_dim=emb_dim,
            inner_dim=cfg.speculator_width,
            vocab_size=vocab_size,
            n_predict=cfg.n_speculator_heads,
            tie_weights=cfg.speculator_tie_weights,
            scale_input=cfg.speculator_scale_input,
        )

    def n_params(self) -> int:
        n_unique = 1 if self.tie_weights else self.n_predict
        n_proj = min(2, self.n_predict) if self.tie_weights else self.n_predict
        proj = self.emb_dim * self.inner_dim + (n_proj - 1) * self.inner_dim**2
        per_head = (
            self.vocab_size * self.inner_dim  # emb
            + 2 * self.inner_dim  # ln w, b
            + self.inner_dim * self.vocab_size  # head
        )
        return int(n_unique * per_head + proj)


def _layer_norm(x, weight=None, bias=None, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(axis=-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def init_speculator_params(key, scfg: SpeculatorConfig, dtype=jnp.float32) -> Params:
    n_unique = 1 if scfg.tie_weights else scfg.n_predict
    n_proj = min(2, scfg.n_predict) if scfg.tie_weights else scfg.n_predict
    keys = jax.random.split(key, 2 * n_unique + n_proj)
    ki = iter(keys)
    std = 0.02

    def tn(shape, s=std):
        return (
            jax.random.truncated_normal(next(ki), -3, 3, shape, jnp.float32) * s
        ).astype(dtype)

    projs = []
    for i in range(n_proj):
        in_dim = scfg.emb_dim if i == 0 else scfg.inner_dim
        projs.append(tn((in_dim, scfg.inner_dim)))
    return {
        "emb": [tn((scfg.vocab_size, scfg.inner_dim)) for _ in range(n_unique)],
        "proj": projs,
        "ln_w": [jnp.ones((scfg.inner_dim,), dtype) for _ in range(n_unique)],
        "ln_b": [jnp.zeros((scfg.inner_dim,), dtype) for _ in range(n_unique)],
        "head": [tn((scfg.inner_dim, scfg.vocab_size)) for _ in range(n_unique)],
    }


def _pick(params, scfg: SpeculatorConfig, group, i):
    """Head-i parameter lookup honoring the tie_weights sharing rule."""
    if scfg.tie_weights:
        if group == "proj":
            return params["proj"][min(i, len(params["proj"]) - 1)]
        return params[group][0]
    return params[group][i]


def scale_input(state, scfg: SpeculatorConfig):
    """Optional input normalization applied once before the head chain —
    shared by training and inference so the rule can't diverge."""
    if scfg.scale_input:
        return _layer_norm(state) * (2**-0.5)
    return state


def head_step(params, scfg: SpeculatorConfig, state, tok, i):
    """One speculator head: fold token embedding into the state with the
    variance-preserving weights, normalize+gelu, project to logits.
    Shared by teacher-forced training (speculator_forward) and the
    inference proposal chain (models/speculative.speculator_propose)."""
    state_weight = 0.5 ** (0.5 / scfg.n_predict)
    emb_weight = (1 - state_weight**2) ** 0.5
    z = _pick(params, scfg, "emb", i)[tok].astype(state.dtype)
    state = (
        state @ _pick(params, scfg, "proj", i).astype(state.dtype) * state_weight
        + z * emb_weight
    )
    state = jax.nn.gelu(
        _layer_norm(
            state, _pick(params, scfg, "ln_w", i), _pick(params, scfg, "ln_b", i)
        )
    )
    logits = state @ _pick(params, scfg, "head", i).astype(state.dtype)
    return state, logits


def save_speculator(path: str, params: Params, scfg: SpeculatorConfig) -> None:
    """Write a serving speculator checkpoint: params + config in one
    pickle. The config MUST ship with the weights — under tie_weights
    the param tree holds one shared head, so ``n_predict`` (and with it
    the variance-preserving state/emb weights) is not recoverable from
    shapes alone."""
    import numpy as np

    payload = {
        "model_state": jax.tree.map(np.asarray, params),
        "speculator_config": asdict(scfg),
    }
    with open(path, "wb") as f:
        pickle.dump(payload, f)


def load_speculator(path: str) -> Tuple[Params, SpeculatorConfig]:
    """Restore a ``save_speculator`` checkpoint -> (params, config)."""
    with open(path, "rb") as f:
        payload = pickle.load(f)
    if "speculator_config" not in payload:
        raise ValueError(
            f"{path!r} is not a serving speculator checkpoint: expected "
            "a save_speculator pickle carrying 'speculator_config' "
            "alongside 'model_state' (n_predict is not inferrable from "
            "tied weights)"
        )
    scfg = SpeculatorConfig(**payload["speculator_config"])
    params = jax.tree.map(jnp.asarray, payload["model_state"])
    return params, scfg


def speculator_forward(params: Params, state, inds, scfg: SpeculatorConfig):
    """state (B, N, emb_dim): base-model embeddings; inds (B, >= N +
    n_predict - 1): known token indices, inds[:, i:i+N] feeding head i.
    Returns per-head logits (n_predict, B, N, V)."""
    n = state.shape[1]
    state = scale_input(state, scfg)

    out = []
    for i in range(scfg.n_predict):
        state, logits = head_step(params, scfg, state, inds[:, i : i + n], i)
        out.append(logits)

    return jnp.stack(out, axis=0)
