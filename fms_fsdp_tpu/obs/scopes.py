"""``jax.named_scope`` decorator for trace attribution.

Profiler traces (utils/train_utils.py::WindowedProfiler) are only as
useful as their op names; a scan-of-blocks model otherwise shows up as
one undifferentiated ``while`` region. ``scoped("name")`` wraps a
trace-time function so every op it emits lands under ``name`` in the
XPlane tree — zero runtime cost (named_scope only affects tracing
metadata), safe inside jit/scan/remat, and a no-op for code paths that
never run under a profiler.
"""

import functools

import jax


def scoped(name: str):
    """Decorator: run the wrapped trace function under
    ``jax.named_scope(name)``."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with jax.named_scope(name):
                return fn(*args, **kwargs)

        return wrapped

    return deco
