"""Pluggable metric sinks: JSONL, CSV summary, legacy tracker adapter,
and the heartbeat file.

Sinks receive one schema-validated record per report step via
``emit(record)`` and must never raise into the hot loop — IO failures
log once and disable the sink (a full disk must not kill a pod run).
"""

import csv
import json
import logging
import os
import tempfile
from typing import Callable, Dict, List, Optional

from fms_fsdp_tpu.obs.schema import SCHEMA_FIELDS

logger = logging.getLogger(__name__)


class Sink:
    def emit(self, record: Dict) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        pass


class _FileSink(Sink):
    """Shared broken-pipe discipline: first IO error disables the sink."""

    def __init__(self, path: str):
        self.path = path
        self._broken = False
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def _write(self, record: Dict) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def emit(self, record: Dict) -> None:
        if self._broken:
            return
        try:
            self._write(record)
        except (OSError, ValueError, TypeError) as e:
            # OSError: disk/fs; ValueError: non-finite slipped to
            # json.dumps(allow_nan=False); TypeError: unserializable
            # value in a record — all disable the sink, never the run
            self._broken = True
            logger.warning("%s sink disabled: %s", self.path, e)


class JSONLSink(_FileSink):
    """One JSON object per line per report step, append-only, flushed per
    emit so a crash loses at most the in-flight line. The schema is
    versioned (schema.py); consumers key on ``schema_version``."""

    def __init__(self, path: str):
        super().__init__(path)
        self._f = None

    def _write(self, record: Dict) -> None:
        if self._f is None:
            self._f = open(self.path, "a", buffering=1)
        # allow_nan=False backstops the observer's non-finite -> null
        # mapping: a bare NaN/Infinity token is not strict JSON and
        # must never reach the stream (ValueError disables the sink
        # loudly instead)
        self._f.write(json.dumps(record, sort_keys=True, allow_nan=False) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None


class CSVSink(_FileSink):
    """Flat summary table: the scalar schema fields as columns (``extra``
    is dropped — it is open-ended; the JSONL stream has it). Header is
    written once on first emit."""

    COLUMNS = [n for n, (tag, _) in SCHEMA_FIELDS.items() if tag != "map"]

    def __init__(self, path: str):
        super().__init__(path)
        self._f = None
        self._writer = None

    def _write(self, record: Dict) -> None:
        if self._f is None:
            fresh = not (
                os.path.exists(self.path) and os.path.getsize(self.path) > 0
            )
            self._f = open(self.path, "a", newline="")
            self._writer = csv.DictWriter(
                self._f, fieldnames=self.COLUMNS, extrasaction="ignore"
            )
            if fresh:
                self._writer.writeheader()
        self._writer.writerow({c: record.get(c) for c in self.COLUMNS})
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None


class TrackerSink(Sink):
    """Adapter over the legacy wandb/aim ``log_fn(dict, step)`` from
    ``get_tracker`` — the exact key names the pre-obs loop logged, so
    existing dashboards keep working unchanged; ``extra`` metrics ride
    along under their own names as before."""

    def __init__(self, log_fn: Callable):
        self.log_fn = log_fn
        self._broken = False

    def emit(self, record: Dict) -> None:
        if self._broken:
            return
        payload = {
            "learning rate": record.get("learning_rate"),
            "loss": record.get("loss"),
            "gradient norm": record.get("grad_norm"),
            "token seen": record.get("tokens_seen"),
            "current throughput (token per chip per sec)": record.get(
                "tokens_per_sec_per_chip"
            ),
            "overall throughput (token per chip per sec)": record.get(
                "tokens_per_sec_per_chip_overall"
            ),
            "chip reserved memory": record.get("memory_reserved_bytes"),
            "chip allocated memory": record.get("memory_allocated_bytes"),
            "skipped batches": record.get("skipped_steps"),
            **(record.get("extra") or {}),
        }
        try:
            self.log_fn(payload, step=record["step"])
        except Exception as e:  # noqa: BLE001 — tracker backends raise
            # anything (finished wandb run, aim db/network errors); the
            # sink contract is to disable itself, never kill training
            self._broken = True
            logger.warning("tracker sink disabled: %s", e)


class Heartbeat:
    """Tiny atomically-replaced JSON file — ``{step, time_unix, goodput,
    schema_version}`` — that the StepWatchdog's stall report and external
    orchestrators can poll to tell "alive and progressing" from "alive
    and wedged" without parsing the full metrics stream.

    Supervised runs (resilience/supervisor.py sets ``FMS_RUN_ID``) stamp
    the incarnation's ``run_id`` into every beat: the supervisor's
    crash-loop detector and the watchdog's stall report both need to
    tell a fresh incarnation's progress from the dead run's leftover
    file on shared storage. Unsupervised runs keep the exact legacy
    payload."""

    def __init__(self, path: str, run_id: Optional[str] = None):
        self.path = path
        self._broken = False
        if run_id is None:
            from fms_fsdp_tpu.resilience.exits import current_run_id

            run_id = current_run_id()
        self.run_id = run_id or None
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def beat(self, step: int, time_unix: float, goodput: float) -> None:
        if self._broken:
            return
        from fms_fsdp_tpu.obs.schema import SCHEMA_VERSION

        payload = {
            "step": int(step),
            "time_unix": float(time_unix),
            "goodput": float(goodput),
            "schema_version": SCHEMA_VERSION,
        }
        if self.run_id:
            payload["run_id"] = self.run_id
        try:
            d = os.path.dirname(os.path.abspath(self.path))
            fd, tmp = tempfile.mkstemp(dir=d, prefix=".heartbeat.")
            with os.fdopen(fd, "w") as f:
                f.write(json.dumps(payload))
            os.replace(tmp, self.path)
        except OSError as e:
            self._broken = True
            logger.warning("heartbeat %s disabled: %s", self.path, e)


def read_heartbeat(path: str) -> Optional[Dict]:
    """Best-effort heartbeat read (for watchdog stall reports and tests);
    None when missing/unparseable."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def build_sinks(
    obs_dir: str,
    names: List[str],
    tracker_fn: Optional[Callable] = None,
) -> List[Sink]:
    """Instantiate the configured sinks. ``jsonl``/``csv`` need
    ``obs_dir``; ``tracker`` needs a live ``tracker_fn`` (rank-0 wandb/
    aim log function). Unknown names raise — a typo'd sink list must not
    silently drop the metrics stream."""
    sinks: List[Sink] = []
    for name in names:
        name = name.strip()
        if not name:
            continue
        if name == "jsonl":
            if obs_dir:
                sinks.append(JSONLSink(os.path.join(obs_dir, "metrics.jsonl")))
        elif name == "csv":
            if obs_dir:
                sinks.append(CSVSink(os.path.join(obs_dir, "metrics.csv")))
        elif name == "tracker":
            if tracker_fn is not None:
                sinks.append(TrackerSink(tracker_fn))
        else:
            raise ValueError(
                f"unknown obs sink {name!r} (expected jsonl|csv|tracker)"
            )
    return sinks
