"""The versioned metrics-record schema shared by every sink.

One record is emitted per report step. ``SCHEMA_FIELDS`` is the
contract: field name -> (type tag, required). Changing the field set or
a type WITHOUT bumping ``SCHEMA_VERSION`` fails CI: the pinned digest in
``SCHEMA_DIGESTS`` no longer matches (tests/test_obs.py::
test_schema_digest_pins_version). To evolve the schema: edit
``SCHEMA_FIELDS``, bump ``SCHEMA_VERSION``, add the new digest (printed
by the failing test), and document the change in docs/observability.md.

Type tags: ``int`` / ``float`` (``null`` allowed only where required is
False) / ``str`` / ``map`` (flat str->number dict).
"""

import hashlib
import json
import numbers
from typing import Any, Dict, List

SCHEMA_VERSION = 15

# name -> (type, required)
SCHEMA_FIELDS = {
    "schema_version": ("int", True),
    "step": ("int", True),
    "time_unix": ("float", True),
    # nullable: a fully-poisoned report window (every step flagged
    # non-finite) has no finite loss to state — null, never bare NaN,
    # keeps each line strict-JSON parseable exactly when the post-mortem
    # matters most; skipped_steps_window == steps tells the story
    "loss": ("float", False),
    "grad_norm": ("float", False),
    "learning_rate": ("float", False),
    "tokens_seen": ("int", False),
    "tokens_per_sec_per_chip": ("float", True),
    "tokens_per_sec_per_chip_overall": ("float", False),
    "step_time_s": ("float", False),
    "mfu": ("float", False),
    "hfu": ("float", False),
    "data_wait_s": ("float", True),
    "data_wait_frac": ("float", True),
    "compute_s": ("float", True),
    # v2: checkpoint_s is the step-boundary BLOCKING time only (the
    # device→host snapshot under the async manager; the whole save when
    # running synchronously)...
    "checkpoint_s": ("float", True),
    # ...and checkpoint_bg_s is the background writer-thread wall time
    # that landed in this window (off the critical path), with
    # checkpoint_in_flight flagging a save still committing at report
    # time. Per-tier save counts and bytes ride in ``extra``
    # (checkpoint.saves.<tier>, checkpoint.bytes).
    "checkpoint_bg_s": ("float", True),
    "checkpoint_in_flight": ("int", True),
    # v5: collective time split by transport tier (docs/observability.md
    # "Multi-slice collective split"). On a multi-slice mesh the report-
    # cadence collective probe (obs/collectives.py) times one tiny
    # within-slice reduce (ICI) and one cross-slice reduce (DCN) per
    # window, so cross-slice overhead — the HSDP scaling tax — is
    # attributable per record. Single-slice runs report 0.0 for both
    # (no probe is traced; the train step's HLO stays untouched).
    "ici_collective_s": ("float", True),
    "dcn_collective_s": ("float", True),
    # v10: estimated fraction of the window's DCN collective time hidden
    # under backward compute by the bucketed overlap schedule
    # (parallel/overlap.py; docs/observability.md "DCN overlap"). Derived
    # from the probe's dcn_collective_s, the resolved bucket count, and
    # the window's compute time — 0.0 when overlap is off, the mesh is
    # single-slice, or no probe ran this window.
    "dcn_overlap_frac": ("float", True),
    "wall_s": ("float", True),
    "goodput": ("float", True),
    "goodput_overall": ("float", False),
    "skipped_steps": ("int", True),
    "skipped_steps_window": ("int", True),
    # v7: multi-corpus data-mix accounting (docs/dataloader.md
    # "Multi-corpus mixing"). Flat map keyed "<corpus>.<stat>" with
    # stats tokens_seen / target_share / realized_share / quarantined
    # (0|1) per corpus, filled at report cadence from the live loader's
    # SamplingDataset layer — realized-vs-target share drift and a
    # degraded (quarantined) mix are first-class record facts. Absent
    # (null) on dummy-data runs and in worker_mode="process" (the
    # parent's pipeline copies don't advance). The corpus lifecycle
    # counters (data.corpus_quarantined / data.corpus_rearmed) and
    # data.mix.<corpus>.tokens_seen gauges additionally ride in
    # ``extra``.
    "data_mix": ("map", False),
    # v8: state-integrity accounting (docs/checkpointing.md "State
    # integrity"). integrity_verify_s is the window's wall seconds spent
    # in manifest verification (scrubber sweeps + restore-walk
    # verifies, drained from the background event buffer);
    # scrub_verified is the cumulative count of checkpoints this
    # process has confirmed content-verified (fresh hash or matching
    # cached verdict); divergence_checks is the cumulative count of
    # cross-replica fingerprint compares performed
    # (resilience/divergence.py). Detections ride in ``extra`` as the
    # integrity.shard_corrupt_detected / integrity.divergence_detected
    # counters. Runs without the integrity layer armed report 0 / 0 /
    # 0.0.
    "integrity_verify_s": ("float", True),
    "scrub_verified": ("int", True),
    "divergence_checks": ("int", True),
    # v9: serving-engine accounting (docs/serving.md). Flat map with
    # the serving headline stats: tokens_per_s (decode throughput),
    # ttft_s (mean time-to-first-token of the window), queue_depth,
    # kv_pages_in_use, requests_completed / evicted / expired, and
    # p99_latency_s — filled from ServingEngine.serving_stats() when a
    # serving loop drives the observer. The full serve.* counter/gauge
    # set (serve.decode_tokens, serve.kv_defrag_moves, ...) rides in
    # ``extra`` via the registry snapshot as usual. Absent (null) on
    # training runs.
    # v12: the map gains ``family`` — the engine's model family as a
    # numeric code (0=llama 1=mamba 2=mixtral; serve/families/
    # FAMILY_CODES — the map is flat str->number, so the name travels
    # as its code) — and ``state_bytes_per_stream``, the decode-state
    # slab bytes one stream holds (mamba's constant-memory headline;
    # 0.0 for families whose whole decode state is paged KV).
    # v13: the map gains the disaggregation + layout fields (docs/
    # observability.md "v13"): ``role`` (serve/disagg ROLE_CODES:
    # 0=unified 1=prefill 2=decode), ``serve_layout`` (100*tp + fsdp,
    # 0 = single-chip; parallel/sharding.py::serve_layout_code),
    # ``handoff_bytes`` (cumulative PageHandoff wire bytes packed +
    # imported) and ``handoff_s`` (wall seconds packing/scattering).
    # v15: the map gains ``drained`` (1.0 once the engine stopped
    # admitting — a draining/preempted replica is visibly winding down
    # in its last heartbeats' stats).
    # v14: the map gains the raw-speed fields (docs/observability.md
    # "v14"): ``spec_accept_rate`` (accepted draft tokens over offered
    # — 0.0 when speculative serving is off), ``spec_draft_tokens``
    # (draft tokens per verify step; 0 = non-speculative),
    # ``prefill_chunks`` (cumulative chunked-prefill slices advanced;
    # 0 = whole-prompt prefill) and ``paged_kernel_impl`` (0 =
    # reference gather, 1 = single-page paged-attention kernel v1
    # path, 2 = kernel v2 engaged — multi-page DMA and/or native
    # quantized page reads).
    "serving": ("map", False),
    # v11: serving-fleet accounting (docs/serving.md "Fleet
    # resilience"). Flat map from FleetRouter.stats(): replicas /
    # replicas_live, availability (replica-seconds live over owed —
    # the restart ledger folded into one number), restarts,
    # stalls_detected, request outcome counts (admitted / completed /
    # expired / failed / requeued / rejected), duplicates_dropped
    # (exactly-once dedup hits), completion_rate, p99_latency_s under
    # churn. Absent (null) on training runs and single-engine serving.
    # v15: the map gains the streaming-transport + drain counters
    # (docs/observability.md "v15"): ``handoff_retries`` (transfers
    # that needed >= 1 chunk retransmit), ``chunks_resent`` (total
    # retransmitted chunks, router side), ``transfers_resumed``
    # (transfers that continued past an interruption — journal-seeded
    # resume or in-flight retransmit) and ``drain_migrations`` (live
    # streams migrated off a preempted replica with zero recompute).
    "serving_fleet": ("map", False),
    # v6: self-healing supervisor accounting (docs/resilience.md
    # "Self-healing supervisor"). The relaunched run reads the
    # supervisor's restart ledger (FMS_RESTART_LEDGER) at observer
    # build: ``restarts`` is how many times this run has been
    # auto-relaunched and ``restart_downtime_s`` the cumulative
    # death-to-relaunch wall time — charged against goodput (the
    # GoodputTracker's wall clock starts that far behind), so a faulted
    # run's goodput_overall is strictly below the fault-free run's.
    # Unsupervised runs report 0 / 0.0.
    "restarts": ("int", True),
    "restart_downtime_s": ("float", True),
    # v3: the kernel-tuning mode the run's step was built under
    # ("auto" | "off" | a table path). The per-kernel resolved tiles ride
    # in ``extra`` as kernel.tune.* gauges (flash block_q/block_k/kvgrid,
    # ssd chunk, ce chunk, exact/nearest/default/pinned/off counters, and
    # the block-degradation counter) — a run's perf record states which
    # tiles produced it (flash gauges reflect post-divisibility-halving
    # values; "pinned" = the call site or a non-default config value
    # named the tile explicitly while tuning was on).
    "kernel_tuning": ("str", False),
    # v4: the quantization modes the run's step was built under — the
    # GEMM path ("none" | "int8" | "int8_dgrad" | "fp8" | "fp8_dgrad",
    # ops/quant.py) and the gradient-reduction wire format ("none" |
    # "int8" | "fp8" | "fp8_delayed", parallel/sharding.py). A perf
    # record must state the numerics that produced it; the tuner's
    # resolved flash quant family additionally rides in ``extra`` as
    # kernel.tune.flash.quant_code (0=none 1=int8 2=fp8).
    "quantized_matmuls": ("str", False),
    "quantized_reduce": ("str", False),
    "memory_reserved_bytes": ("int", False),
    "memory_allocated_bytes": ("int", False),
    "extra": ("map", False),
}

# Digest of the canonical field serialization for each published
# version. A mismatch for the CURRENT version means the schema changed
# without a version bump.
SCHEMA_DIGESTS = {
    1: "01cf2035086946667a852893e38535f44bd340e20871a10be2d6f4103cd62f90",
    # v2: + checkpoint_bg_s / checkpoint_in_flight (async checkpoint
    # manager: blocking-snapshot vs background-write split)
    2: "6fe196571d7fdf02da2dc0060f5151ddbcee7fae5275ad45277c0bce95be49c8",
    # v3: + kernel_tuning (autotuner mode; resolved tiles ride in extra
    # as kernel.tune.* gauges)
    3: "f040074f56e65a7aef0e33bb7281fd38b6f1941115ee5e862412962b5f5c2a84",
    # v4: + quantized_matmuls / quantized_reduce (the step's GEMM and
    # gradient-reduce quantization modes; the tuner's flash quant family
    # rides in extra as kernel.tune.flash.quant_code)
    4: "488f2ccf06394fbc05445c7134628520fef64de1cd61a1bd6bf44000bd1ee66e",
    # v5: + ici_collective_s / dcn_collective_s (the multi-slice
    # collective split measured by the report-cadence probe)
    5: "5b3a957aa5736c7bce67ed7650ee3f5dc6fc322bc1edb85409dcc4653eddb011",
    # v6: + restarts / restart_downtime_s (self-healing supervisor:
    # restart-ledger accounting, downtime charged against goodput)
    6: "beafaf1c7f6338ad6693fe16ce1b2c4403c5447e3135e12b3776d5494864b8ce",
    # v7: + data_mix (per-corpus tokens_seen / target vs realized share /
    # quarantined flag from the weighted multi-corpus mixing layer)
    7: "fed0cc09460e2c7da58cf4519e40e8d4e0ff6c25874b65fbd9d0e7f44ff83af9",
    # v8: + integrity_verify_s / scrub_verified / divergence_checks
    # (state-integrity layer: manifest verification time, scrub-verified
    # checkpoint count, cross-replica fingerprint compares)
    8: "96ce592c9a1e990018a24d93757370679c594bfac64269b225cd2ff635ee4a3e",
    # v9: + serving (serving-engine headline map: tokens_per_s, ttft_s,
    # queue_depth, kv_pages_in_use, request outcome counts,
    # p99_latency_s — docs/serving.md)
    9: "178c0ec2d1d31834a0ae939d0df6e734ce66665f0dfccb662ab97dcc5fcc4e12",
    # v10: + dcn_overlap_frac (estimated hidden fraction of the window's
    # DCN collective time under the bucketed overlap schedule —
    # parallel/overlap.py, docs/observability.md "DCN overlap")
    10: "864cdd64b4d6f3fa3dd7e24c3e0a18f42ae118f56965c32fbfb2f0a847f7287a",
    # v11: + serving_fleet (fleet router headline map: replica
    # availability from the restart ledger, restarts, stalls, request
    # outcome counts, exactly-once dedup hits, p99 under churn —
    # docs/serving.md "Fleet resilience")
    11: "3fa631fc73a3499c0515780e834069bd2874861a64e3bab5bd14770fdb45d513",
    # v12: serving map gains family (numeric code via
    # serve/families.FAMILY_CODES) + state_bytes_per_stream (constant
    # decode-slab bytes; the field set itself is unchanged)
    12: "30df6d1be6e3214a083627b8cbb8a765d7c7e51aef6bdf4eca8fe469d13e5881",
    # v13: serving map gains role (disagg ROLE_CODES), serve_layout
    # (100*tp + fsdp layout code), handoff_bytes and handoff_s (the
    # PageHandoff wire traffic; field set itself unchanged), and the
    # serving_fleet map gains prefill_replicas / requests_handed_off /
    # handoff_bytes
    13: "598cbb44447e0667b8655a5b06dc569b2e00b33f748561f2d2ec6d365600418d",
    # v14: serving map gains spec_accept_rate / spec_draft_tokens
    # (speculative serving), prefill_chunks (chunked prefill) and
    # paged_kernel_impl (the kernel generation engaged); the field set
    # itself is unchanged
    14: "2f8909a62cde9d1cdfd1d4153c219e37d8f16b8011a7f3dca7feeb5ebb2a567a",
    # v15: serving map gains drained (engine stopped admitting — the
    # drain/preempt wind-down flag); serving_fleet map gains
    # handoff_retries / chunks_resent / transfers_resumed /
    # drain_migrations (streaming state-transfer transport +
    # drain-and-migrate preemption); the field set itself is unchanged
    15: "72f5816eded0eb4caa3a834f60eb0dc10db1a31772699bf81af6c0c40665b38a",
}


def schema_digest() -> str:
    canon = json.dumps(
        {"version": SCHEMA_VERSION, "fields": SCHEMA_FIELDS},
        sort_keys=True,
        separators=(",", ":"),
    )
    return hashlib.sha256(canon.encode()).hexdigest()


def _type_ok(tag: str, v: Any) -> bool:
    if tag == "int":
        return isinstance(v, numbers.Integral) and not isinstance(v, bool)
    if tag == "float":
        return isinstance(v, numbers.Real) and not isinstance(v, bool)
    if tag == "str":
        return isinstance(v, str)
    if tag == "map":
        return isinstance(v, dict) and all(
            isinstance(k, str)
            and (v[k] is None or isinstance(v[k], numbers.Real))
            for k in v
        )
    return False


def validate_record(rec: Dict[str, Any]) -> List[str]:
    """Return a list of violations (empty = valid). Checks: required
    fields present and non-null, all present fields well-typed, no
    fields outside the schema, version matches."""
    errs = []
    if rec.get("schema_version") != SCHEMA_VERSION:
        errs.append(
            f"schema_version {rec.get('schema_version')!r} != {SCHEMA_VERSION}"
        )
    for name, (tag, required) in SCHEMA_FIELDS.items():
        if name not in rec or rec[name] is None:
            if required:
                errs.append(f"missing required field {name!r}")
            continue
        if not _type_ok(tag, rec[name]):
            errs.append(f"field {name!r}={rec[name]!r} is not a {tag}")
    for name in rec:
        if name not in SCHEMA_FIELDS:
            errs.append(f"unknown field {name!r} (bump SCHEMA_VERSION?)")
    return errs
