"""Report-cadence collective probe: the ICI-vs-DCN split (schema v5).

On a multi-slice mesh (parallel/mesh.py: the ``dcn`` axis) the step's
collective time has two very different transports folded into it: the
within-slice ICI reduce-scatter/all-gather and the cross-slice DCN
all-reduce — the bandwidth-bound hop *Memory and Bandwidth are All You
Need for Fully Sharded Data Parallel* (PAPERS.md) says must be isolated
and attributed. The hot loop cannot split them from the host (it only
sees the once-per-window ``device_get``), so the Observer runs this
probe once per report window instead: two tiny jitted reductions —

- one over the within-slice data axes only (replica/fsdp/expert): its
  collectives stay inside each slice, so its wall time tracks ICI
  reduce latency;
- one over the ``dcn`` axis only: a pure cross-slice all-reduce, so its
  wall time tracks the DCN hop (including any slice skew the reduce has
  to absorb).

The seconds land in the PhaseTimer's ``ici_collective`` /
``dcn_collective`` phases and surface as the v5 record fields. The probe
is a latency *attribution* signal (microbenchmark at tiny shapes, once
per window), not a bytes model — trends and ratios are the point: a
healthy run holds both flat, a degrading DCN link (or a straggling
slice) shows up in ``dcn_collective_s`` alone, which is exactly the
triage split the StepWatchdog/SliceHealthMonitor reports cross-reference.

Single-slice meshes get no probe at all (``make_collective_split_probe``
returns None): nothing extra is traced, and the v5 fields stay 0.0 —
part of the "dcn=1 adds nothing" bit-identity contract.

Multi-process note: the probe's reductions are collective, so every
process must run them at the same cadence — guaranteed because every
rank calls ``Observer.report`` at the same step (non-zero ranks run it
sink-less for exactly this kind of rank-consistent timing).
"""

from functools import partial
from typing import Callable, Dict, Optional

from fms_fsdp_tpu.parallel.mesh import AXIS_DCN, DATA_AXES, num_mesh_slices


def make_collective_split_probe(
    mesh, timer, schedule: Optional[Dict] = None
) -> Optional[Callable[[], None]]:
    """Build the probe for ``mesh``, recording into ``timer``'s
    ``ici_collective`` / ``dcn_collective`` phases. None on single-slice
    meshes (the fields then stay 0.0 and no probe program exists).

    ``schedule`` is the resolved DCN-overlap bucket summary
    (parallel/overlap.py ``plan_summary()``). Without one the DCN probe
    is the historical tiny-payload latency ping. With one, the probe
    replays the step's REAL reduce schedule: one cross-slice all-reduce
    per bucket whose wire payload matches that bucket's wire bytes —
    so ``dcn_collective_s`` prices what the step actually puts on the
    DCN each backward (bytes/bandwidth + per-bucket latency), not a
    fixed toy ping, and the overlap estimate (Observer's
    ``dcn_overlap_frac``) divides time that corresponds to the schedule
    it reasons about. Probe arrays are fp32 and deduplicated by bucket
    size, so host memory is ~one bucket per distinct size, not the
    whole gradient."""
    if mesh is None or num_mesh_slices(mesh) <= 1:
        return None

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    ici_axes = tuple(
        a for a in DATA_AXES if a != AXIS_DCN and mesh.shape[a] > 1
    )
    lanes = 128  # one VREG lane row per shard keeps the payload trivial

    def _probe_pair(axes):
        """(jitted fn, input) summing an ``axes``-sharded vector to a
        replicated scalar — GSPMD inserts exactly one reduction over
        ``axes``."""
        extent = int(np.prod([mesh.shape[a] for a in axes]))
        sharding = NamedSharding(mesh, P(axes))
        x = jax.make_array_from_callback(
            (extent * lanes,),
            sharding,
            lambda idx: np.ones((extent * lanes,), np.float32)[idx],
        )
        fn = jax.jit(
            jnp.sum, out_shardings=NamedSharding(mesh, P())
        )
        return fn, x

    def _bucket_pair(nbytes):
        """(jitted fn, input) reducing a (slices, n)-sharded array over
        the dcn axis to a replicated (n,) vector: GSPMD inserts one
        cross-slice all-reduce that moves ~``nbytes`` on the wire (fp32
        elements sized to the bucket's wire bytes)."""
        extent = int(mesh.shape[AXIS_DCN])
        n = max(1, int(nbytes) // 4)
        sharding = NamedSharding(mesh, P(AXIS_DCN))
        x = jax.make_array_from_callback(
            (extent, n),
            sharding,
            lambda idx: np.ones((extent, n), np.float32)[idx],
        )
        fn = jax.jit(
            partial(jnp.sum, axis=0), out_shardings=NamedSharding(mesh, P())
        )
        return fn, x

    bucket_bytes = list((schedule or {}).get("bytes_per_bucket", []) or [])
    if bucket_bytes:
        by_size = {int(b): _bucket_pair(b) for b in sorted(set(bucket_bytes))}
        dcn_probes = [by_size[int(b)] for b in bucket_bytes]
    else:
        dcn_probes = [_probe_pair((AXIS_DCN,))]
    ici = _probe_pair(ici_axes) if ici_axes else None
    # warm every program OUTSIDE the timed phases: the first report
    # window must measure reduce latency, not XLA compile time — a
    # compile-polluted first dcn_collective_s is exactly the "degrading
    # DCN link" signature operators are told to triage on
    for fn, x in dcn_probes:
        fn(x).block_until_ready()
    if ici is not None:
        ici[0](ici[1]).block_until_ready()

    def probe() -> None:
        if ici is not None:
            with timer.phase("ici_collective"):
                ici[0](ici[1]).block_until_ready()
        with timer.phase("dcn_collective"):
            for fn, x in dcn_probes:
                fn(x).block_until_ready()

    return probe
