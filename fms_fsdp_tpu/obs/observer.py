"""The Observer facade the train loops drive.

One object owns the registry, phase timer, goodput tracker, sinks, and
heartbeat. The hot loop touches it in exactly three ways:

- ``wrap_data_iter(it)`` — times each ``next()`` as ``data_wait``;
- ``phase(name)`` — context manager around step dispatch / metric fetch
  (``compute``) and checkpoint saves (``checkpoint``);
- ``report(...)`` — once per report interval: folds the phase window,
  skipped-step counts, and MFU/HFU into a schema-validated record and
  fans it out to every sink plus the heartbeat.

Ranks other than 0 get the same timer/registry (phases are cheap and
keeping them armed avoids rank-divergent control flow) but no sinks —
only rank 0 writes files or talks to trackers.
"""

import logging
import math
import time
from typing import Callable, Dict, Iterable, Iterator, List, Optional

from fms_fsdp_tpu.obs.registry import MetricRegistry
from fms_fsdp_tpu.obs.schema import SCHEMA_VERSION, validate_record
from fms_fsdp_tpu.obs.sinks import Heartbeat, Sink, build_sinks
from fms_fsdp_tpu.obs.timing import GoodputTracker, PhaseTimer

logger = logging.getLogger(__name__)


def _nonfinite(v) -> bool:
    return isinstance(v, float) and not math.isfinite(v)


class Observer:
    def __init__(
        self,
        sinks: Optional[List[Sink]] = None,
        heartbeat: Optional[Heartbeat] = None,
        flops_per_token: Optional[float] = None,
        hfu_flops_per_token: Optional[float] = None,
        peak_flops: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        strict_schema: bool = False,
        kernel_tuning: Optional[str] = None,
        quantized_matmuls: Optional[str] = None,
        quantized_reduce: Optional[str] = None,
        restarts: int = 0,
        restart_downtime_s: float = 0.0,
    ):
        self.registry = MetricRegistry()
        # the kernel-tuning mode this run's step was built under (v3
        # schema field); resolved tiles arrive via the registry
        # (tune.lookup.attach_registry) as kernel.tune.* extras
        self.kernel_tuning = kernel_tuning
        # the quantization modes the step was built under (v4 fields):
        # a perf record must state the numerics that produced it
        self.quantized_matmuls = quantized_matmuls
        self.quantized_reduce = quantized_reduce
        self.timer = PhaseTimer(clock=clock)
        # supervisor restart accounting (schema v6): how many times this
        # run has been auto-relaunched, and the cumulative downtime —
        # pre-charged into the goodput wall clock so a faulted run's
        # goodput_overall is strictly below the fault-free run's
        self.restarts = int(restarts)
        self.restart_downtime_s = float(restart_downtime_s)
        self.goodput = GoodputTracker(
            restart_downtime_s=self.restart_downtime_s
        )
        self.sinks = sinks or []
        self.heartbeat = heartbeat
        self.flops_per_token = flops_per_token
        self.hfu_flops_per_token = hfu_flops_per_token
        self.peak_flops = peak_flops
        self.strict_schema = strict_schema
        self.last_record: Optional[Dict] = None
        self._schema_warned = False
        # set by the async checkpoint manager (ckpt/manager.py) when the
        # loop attaches this observer to it: a callable draining the
        # background-write window ({bg_s, in_flight}) for the record's
        # checkpoint_bg_s / checkpoint_in_flight fields
        self._ckpt_stats: Optional[Callable[[], Dict]] = None
        # set by the entry on multi-slice meshes (obs/collectives.py):
        # the report-cadence probe whose timings fill the v5
        # ici_collective_s / dcn_collective_s split; None (single-slice)
        # leaves both fields 0.0
        self._collective_probe: Optional[Callable[[], None]] = None
        # set by the train loop when the state-integrity layer is armed
        # (utils/train_utils.py): a callable draining the verification
        # window for the v8 integrity_verify_s / scrub_verified /
        # divergence_checks fields; absent -> 0 / 0 / 0.0
        self._integrity_stats: Optional[Callable[[], Dict]] = None
        # set by the entry when the step was built with the DCN-overlap
        # schedule (parallel/overlap.py plan_summary()): bucket count +
        # bytes, consumed by the v10 dcn_overlap_frac estimate; None
        # (overlap off / single-slice) keeps the field 0.0
        self._overlap_schedule: Optional[Dict] = None

    def attach_checkpoint_stats(self, fn: Callable[[], Dict]) -> None:
        self._ckpt_stats = fn

    def attach_integrity_stats(self, fn: Callable[[], Dict]) -> None:
        self._integrity_stats = fn

    def attach_collective_probe(self, fn: Optional[Callable[[], None]]) -> None:
        self._collective_probe = fn

    def attach_overlap_schedule(self, schedule: Optional[Dict]) -> None:
        self._overlap_schedule = dict(schedule) if schedule else None

    def _overlap_frac(self, window: Dict) -> float:
        """Estimate the fraction of the window's DCN collective time the
        bucket schedule hides under backward compute.

        With K buckets, only the first bucket's reduce has nothing to
        overlap with (the backward for later buckets runs under it), so
        the structurally exposed time is ~d/K plus whatever total DCN
        time exceeds the backward compute available to hide it (taken as
        2/3 of the window's compute — backward's share of fwd+bwd).
        Clamped to [0, 1]; 0.0 without a schedule or probe signal. An
        estimate for trend lines, not a bytes-accurate profile — the
        XPlane profiler owns exactness."""
        if not self._overlap_schedule:
            return 0.0
        d = float(window.get("dcn_collective", 0.0))
        if d <= 0.0:
            return 0.0
        k = max(1, int(self._overlap_schedule.get("buckets", 1)))
        c = float(window.get("compute", 0.0)) * (2.0 / 3.0)
        exposed = d / k + max(0.0, d - d / k - c)
        return max(0.0, min(1.0, 1.0 - exposed / d))

    # -- hot-loop hooks ----------------------------------------------------

    def phase(self, name: str):
        return self.timer.phase(name)

    def wrap_data_iter(self, it: Iterable) -> Iterator:
        """Yield from ``it`` with each ``next()`` timed as data_wait."""
        it = iter(it)
        while True:
            try:
                with self.timer.phase("data_wait"):
                    item = next(it)
            except StopIteration:
                return
            yield item

    # -- report-cadence ----------------------------------------------------

    def report(
        self,
        step: int,
        steps_in_window: int,
        *,
        loss: float,
        tokens_per_sec_per_chip: float,
        skipped_steps_total: int = 0,
        skipped_steps_window: int = 0,
        grad_norm: Optional[float] = None,
        learning_rate: Optional[float] = None,
        tokens_seen: Optional[int] = None,
        tokens_per_sec_per_chip_overall: Optional[float] = None,
        step_time_s: Optional[float] = None,
        memory_reserved_bytes: Optional[int] = None,
        memory_allocated_bytes: Optional[int] = None,
        data_mix: Optional[Dict[str, float]] = None,
        serving: Optional[Dict[str, float]] = None,
        serving_fleet: Optional[Dict[str, float]] = None,
        extra: Optional[Dict[str, float]] = None,
    ) -> Dict:
        """Close the phase window, derive goodput/MFU, emit to sinks.

        Returns the record (also kept as ``last_record`` for tests and
        callers that want the derived numbers)."""
        if self._collective_probe is not None:
            # inside the closing window, before it is folded: the
            # probe's seconds belong to the record they attribute.
            # Collective — every rank reports at the same step, so the
            # probe stays rank-consistent.
            self._collective_probe()
        window = self.timer.window()
        goodput_w, goodput_all = self.goodput.update(
            window, steps_in_window, skipped_steps_window
        )
        mfu = hfu = None
        if self.flops_per_token and self.peak_flops:
            achieved = tokens_per_sec_per_chip * self.flops_per_token
            mfu = achieved / self.peak_flops
            if self.hfu_flops_per_token:
                hfu = (
                    tokens_per_sec_per_chip
                    * self.hfu_flops_per_token
                    / self.peak_flops
                )
        # checkpoint stats BEFORE the registry snapshot: the provider
        # (ckpt/manager.py obs_stats) flushes the writer thread's
        # committed-save counters into the registry here on the main
        # thread, so they land in THIS record's extras
        ckpt_stats = self._ckpt_stats() if self._ckpt_stats else {}
        # integrity stats BEFORE the snapshot too: the provider drains
        # the scrubber/verify event buffer into the registry counters
        # (integrity.shard_corrupt_detected) so detections land in THIS
        # record's extras
        integ = self._integrity_stats() if self._integrity_stats else {}
        extras = dict(self.registry.snapshot())
        if extra:
            extras.update(extra)
        wall = window["wall"]
        record = {
            "schema_version": SCHEMA_VERSION,
            "step": int(step),
            "time_unix": time.time(),
            "loss": float(loss),
            "grad_norm": None if grad_norm is None else float(grad_norm),
            "learning_rate": (
                None if learning_rate is None else float(learning_rate)
            ),
            "tokens_seen": None if tokens_seen is None else int(tokens_seen),
            "tokens_per_sec_per_chip": float(tokens_per_sec_per_chip),
            "tokens_per_sec_per_chip_overall": (
                None
                if tokens_per_sec_per_chip_overall is None
                else float(tokens_per_sec_per_chip_overall)
            ),
            "step_time_s": (
                None if step_time_s is None else float(step_time_s)
            ),
            "mfu": mfu,
            "hfu": hfu,
            "data_wait_s": window["data_wait"],
            "data_wait_frac": (
                window["data_wait"] / wall if wall > 0 else 0.0
            ),
            "compute_s": window["compute"],
            # blocking time at the step boundary only (the snapshot,
            # under the async manager); the storage-write remainder is
            # checkpoint_bg_s, off the critical path
            "checkpoint_s": window["checkpoint"],
            "checkpoint_bg_s": float(ckpt_stats.get("bg_s", 0.0)),
            "checkpoint_in_flight": int(ckpt_stats.get("in_flight", 0)),
            # v5: the multi-slice collective split (obs/collectives.py
            # probe; 0.0 without one — single-slice runs)
            "ici_collective_s": window.get("ici_collective", 0.0),
            "dcn_collective_s": window.get("dcn_collective", 0.0),
            # v10: estimated hidden fraction of the DCN time above under
            # the bucketed overlap schedule (0.0 when overlap is off)
            "dcn_overlap_frac": self._overlap_frac(window),
            # v8: state-integrity accounting (scrub + divergence layer;
            # 0 / 0 / 0.0 when the layer is not armed)
            "integrity_verify_s": float(integ.get("verify_s", 0.0)),
            "scrub_verified": int(integ.get("scrub_verified", 0)),
            "divergence_checks": int(integ.get("divergence_checks", 0)),
            "wall_s": wall,
            "goodput": goodput_w,
            "goodput_overall": goodput_all,
            "skipped_steps": int(skipped_steps_total),
            "skipped_steps_window": int(skipped_steps_window),
            # v6: supervisor restart accounting (restart ledger)
            "restarts": self.restarts,
            "restart_downtime_s": self.restart_downtime_s,
            # v7: per-corpus data-mix accounting ("<corpus>.<stat>"
            # flat map); None when the run has no live mixing layer
            "data_mix": dict(data_mix) if data_mix else None,
            # v9: serving-engine headline map
            # (ServingEngine.serving_stats()); None on training runs
            "serving": dict(serving) if serving else None,
            # v11: fleet-router headline map (FleetRouter.stats());
            # None on training runs and single-engine serving
            "serving_fleet": (
                dict(serving_fleet) if serving_fleet else None
            ),
            "kernel_tuning": self.kernel_tuning,
            "quantized_matmuls": self.quantized_matmuls,
            "quantized_reduce": self.quantized_reduce,
            "memory_reserved_bytes": (
                None
                if memory_reserved_bytes is None
                else int(memory_reserved_bytes)
            ),
            "memory_allocated_bytes": (
                None
                if memory_allocated_bytes is None
                else int(memory_allocated_bytes)
            ),
            "extra": extras,
        }
        # non-finite scalars become null: a NaN loss (fully-poisoned
        # window) serialized bare would make the JSONL line unparseable
        # by strict parsers exactly when the post-mortem matters most
        record = {
            k: (None if _nonfinite(v) else v) for k, v in record.items()
        }
        record["extra"] = {
            k: (None if _nonfinite(v) else v) for k, v in extras.items()
        }
        errs = validate_record(record)
        if errs:
            if self.strict_schema:
                raise ValueError(f"metrics record violates schema: {errs}")
            if not self._schema_warned:
                # warn once (not per report): downstream consumers are
                # about to choke on this stream and the operator needs
                # a signal, but a per-report warning would flood logs
                self._schema_warned = True
                logger.warning(
                    "metrics record violates schema (emitting anyway; "
                    "set obs_strict_schema=True to raise): %s", errs
                )
        self.last_record = record
        for sink in self.sinks:
            sink.emit(record)
        if self.heartbeat:
            self.heartbeat.beat(step, record["time_unix"], goodput_w)
        return record

    def close(self) -> None:
        for sink in self.sinks:
            sink.close()


def build_observer(
    cfg,
    rank: int,
    model_cfg=None,
    tracker_fn: Optional[Callable] = None,
    clock: Callable[[], float] = time.monotonic,
) -> Observer:
    """Build the Observer from TrainConfig knobs (docs/observability.md).

    File sinks and the heartbeat attach only on rank 0 and only when
    ``cfg.obs_dir`` is set; the tracker sink attaches whenever a live
    ``tracker_fn`` exists (rank 0 by construction — ``get_tracker``
    returns None elsewhere). MFU/HFU need ``model_cfg`` for the FLOPs
    model; without it they are emitted as null.
    """
    import os

    obs_dir = getattr(cfg, "obs_dir", "") or ""
    names = [
        s for s in (getattr(cfg, "obs_sinks", "jsonl") or "").split(",") if s
    ]
    # the legacy tracker rides as a sink whenever configured, even if the
    # user's obs_sinks list predates the tracker sink name
    if tracker_fn is not None and "tracker" not in [n.strip() for n in names]:
        names.append("tracker")
    sinks = build_sinks(obs_dir if rank == 0 else "", names, tracker_fn)
    heartbeat = None
    if rank == 0 and obs_dir and getattr(cfg, "obs_heartbeat", True):
        heartbeat = Heartbeat(os.path.join(obs_dir, "heartbeat.json"))

    flops = hfu_flops = peak = None
    if model_cfg is not None:
        from fms_fsdp_tpu.parallel.ac import selective_ac_mask
        from fms_fsdp_tpu.utils.flops import (
            peak_flops_per_chip,
            train_flops_per_token,
        )

        seq_len = cfg.seq_length
        flops = train_flops_per_token(model_cfg, seq_len)
        ac_actual = 0.0
        if getattr(cfg, "fsdp_activation_checkpointing", False):
            n_layers = getattr(model_cfg, "nlayers", None) or getattr(
                model_cfg, "n_layer", 1
            )
            mask = selective_ac_mask(n_layers, cfg.selective_checkpointing)
            ac_actual = (sum(mask) / n_layers) if mask else 0.0
        hfu_flops = train_flops_per_token(
            model_cfg, seq_len, ac_fraction=ac_actual
        )
        peak = peak_flops_per_chip(getattr(cfg, "obs_chip_hint", "") or "")

    # self-healing supervisor accounting (schema v6): when relaunched by
    # resilience/supervisor.py, the restart ledger (FMS_RESTART_LEDGER,
    # written before each launch) carries how many restarts preceded
    # this incarnation and their cumulative downtime — folded into every
    # record and charged against goodput. Unsupervised runs: 0 / 0.0.
    from fms_fsdp_tpu.resilience.exits import read_restart_ledger

    ledger = read_restart_ledger() or {}
    restarts = int(ledger.get("restarts", 0) or 0)
    restart_downtime_s = float(ledger.get("restart_downtime_s", 0.0) or 0.0)

    obs = Observer(
        sinks=sinks,
        heartbeat=heartbeat,
        flops_per_token=flops,
        hfu_flops_per_token=hfu_flops,
        peak_flops=peak,
        clock=clock,
        strict_schema=bool(getattr(cfg, "obs_strict_schema", False)),
        kernel_tuning=getattr(cfg, "kernel_tuning", None),
        quantized_matmuls=getattr(cfg, "quantized_matmuls", None),
        quantized_reduce=getattr(cfg, "quantized_reduce", None),
        restarts=restarts,
        restart_downtime_s=restart_downtime_s,
    )
    # resolved kernel tiles (kernel.tune.* gauges) land in this
    # observer's registry from the trace-time lookup — attach before the
    # first step build so nothing is lost (already-recorded choices are
    # replayed on attach regardless)
    from fms_fsdp_tpu.tune.lookup import attach_registry

    attach_registry(obs.registry)
    return obs
