"""Step-phase wall-time decomposition and goodput accounting.

The hot loop's wall clock splits into four phases:

- ``data_wait`` — host blocked waiting for the next batch (the loop's
  ``next()`` on the feed iterator);
- ``compute``  — dispatching the jitted step plus the once-per-report
  ``device_get`` where the device actually catches up (the loop only
  *dispatches* asynchronously, so per-step host compute time is near
  zero and the report-time fetch is where a window's device time
  manifests);
- ``checkpoint`` — inside ``Checkpointer.save``;
- ``other``    — the remainder (python overhead, tracker IO, prints).

Two more phases carry the multi-slice collective split
(``ici_collective`` / ``dcn_collective``, schema v5): the report-cadence
probe (obs/collectives.py) times one tiny within-slice reduce and one
cross-slice reduce per window. They stay 0.0 on single-slice runs.

Goodput is the fraction of wall time spent making *useful* training
progress: compute time scaled by the window's clean-step fraction
(steps whose updates the anomaly guard skipped produced no progress),
over total wall time. Data stalls, checkpoint stalls, and skipped steps
all pull goodput below MFU's hardware-only story — which is exactly the
gap the metric exists to expose.
"""

import time
from contextlib import contextmanager
from typing import Callable, Dict


PHASES = (
    "data_wait",
    "compute",
    "checkpoint",
    "ici_collective",
    "dcn_collective",
    "other",
)


class PhaseTimer:
    """Accumulates wall seconds per phase; windowed at report cadence.

    ``clock`` is injectable for tests (defaults to ``time.monotonic``).
    Phases may nest across components (e.g. a checkpoint save inside the
    loop body): inner phases win — time inside an inner ``phase()`` is
    attributed to the inner phase only, via depth bookkeeping on entry
    and exit.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._acc: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._stack = []
        self._window_start = clock()

    def record(self, name: str, seconds: float) -> None:
        """Directly attribute ``seconds`` to ``name`` (for callers that
        measured a wait themselves, e.g. a feed thread)."""
        self._acc[name] = self._acc.get(name, 0.0) + seconds

    @contextmanager
    def phase(self, name: str):
        start = self._clock()
        if self._stack:
            # suspend the enclosing phase: attribute its elapsed-so-far
            # and let the inner phase own the clock from here
            outer_name, outer_start = self._stack[-1]
            self.record(outer_name, start - outer_start)
        self._stack.append((name, start))
        try:
            yield
        finally:
            end = self._clock()
            self.record(name, end - self._stack.pop()[1])
            if self._stack:
                # resume the outer phase from now
                self._stack[-1] = (self._stack[-1][0], end)

    def window(self) -> Dict[str, float]:
        """Close the current report window: return per-phase seconds with
        ``other`` as the unattributed remainder and ``wall`` as the
        window's total, then reset the accumulators."""
        now = self._clock()
        wall = max(0.0, now - self._window_start)
        self._window_start = now
        out = {p: self._acc.get(p, 0.0) for p in PHASES}
        for k in self._acc:
            if k not in out:
                out[k] = self._acc[k]
        attributed = sum(v for k, v in out.items() if k != "other")
        out["other"] += max(0.0, wall - attributed)
        out["wall"] = wall
        self._acc = {p: 0.0 for p in PHASES}
        return out


class GoodputTracker:
    """Folds phase windows + skipped-step counts into goodput.

    ``update`` consumes one report window and returns
    ``(goodput_window, goodput_overall)``; cumulative totals live here
    so the overall number survives across windows.

    ``restart_downtime_s`` (the supervisor's restart ledger,
    docs/resilience.md "Self-healing supervisor") pre-charges the wall
    clock: time the run spent dead between incarnations produced no
    progress, so ``goodput_overall`` for an auto-restarted run is
    strictly below the same run fault-free. Window goodput is untouched
    (the downtime did not happen inside any window).
    """

    def __init__(self, restart_downtime_s: float = 0.0):
        self.restart_downtime_s = max(0.0, float(restart_downtime_s))
        self.productive_s = 0.0
        self.wall_s = self.restart_downtime_s

    def update(
        self,
        window: Dict[str, float],
        steps: int,
        skipped_steps: int = 0,
    ):
        wall = window.get("wall", 0.0)
        compute = window.get("compute", 0.0)
        steps = max(1, steps)
        clean_frac = max(0.0, (steps - skipped_steps) / steps)
        productive = compute * clean_frac
        self.productive_s += productive
        self.wall_s += wall
        goodput_window = productive / wall if wall > 0 else 0.0
        goodput_overall = (
            self.productive_s / self.wall_s if self.wall_s > 0 else 0.0
        )
        return goodput_window, goodput_overall
