"""Metric registry: named counters / gauges / EWMAs / windowed histograms.

Hot-path discipline: every update is a couple of float ops on host
Python objects — no jax, no IO, no locks on the common path (the train
loop is single-threaded; background producers like DeviceFeed get their
own counters and only ever ``add`` — a GIL-atomic float += on a
dedicated cell). Aggregation (percentiles, means, window resets) happens
only in :meth:`MetricRegistry.snapshot`, called once per report
interval.
"""

from collections import deque
from typing import Dict, Optional


class Counter:
    """Monotonic accumulator. ``snapshot`` exposes both the cumulative
    total (``name``) and the delta since the last snapshot
    (``name_window``)."""

    __slots__ = ("value", "_last")

    def __init__(self):
        self.value = 0.0
        self._last = 0.0

    def add(self, n: float = 1.0) -> None:
        self.value += n

    def window(self) -> float:
        # single read of self.value: a concurrent add() between a
        # delta read and a second read for _last would be lost from
        # every window (the feed thread adds while the loop snapshots)
        v = self.value
        delta = v - self._last
        self._last = v
        return delta


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class EWMA:
    """Exponentially-weighted moving average; ``None`` until first update."""

    __slots__ = ("alpha", "value")

    def __init__(self, alpha: float = 0.1):
        self.alpha = alpha
        self.value: Optional[float] = None

    def update(self, v: float) -> None:
        v = float(v)
        self.value = v if self.value is None else (
            self.alpha * v + (1 - self.alpha) * self.value
        )


class WindowedHistogram:
    """Bounded sample window; reduced to mean/p50/p90/max at snapshot
    (then cleared, so each report describes its own window)."""

    __slots__ = ("samples",)

    def __init__(self, maxlen: int = 512):
        self.samples: deque = deque(maxlen=maxlen)

    def record(self, v: float) -> None:
        self.samples.append(float(v))

    def reduce(self, clear: bool = True) -> Dict[str, float]:
        if not self.samples:
            return {}
        xs = sorted(self.samples)
        n = len(xs)
        out = {
            "mean": sum(xs) / n,
            "p50": xs[n // 2],
            "p90": xs[min(n - 1, (9 * n) // 10)],
            "max": xs[-1],
        }
        if clear:
            self.samples.clear()
        return out


class MetricRegistry:
    """Create-on-first-use registry of named metrics.

    Names are flat strings (dot-separated by convention, e.g.
    ``feed.queue_wait_s``); ``snapshot()`` flattens everything into one
    ``{name: float}`` dict suitable for a sink record's ``extra`` map.
    """

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._ewmas: Dict[str, EWMA] = {}
        self._hists: Dict[str, WindowedHistogram] = {}

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            c = self._counters[name] = Counter()
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = Gauge()
        return g

    def ewma(self, name: str, alpha: float = 0.1) -> EWMA:
        e = self._ewmas.get(name)
        if e is None:
            e = self._ewmas[name] = EWMA(alpha)
        return e

    def hist(self, name: str, maxlen: int = 512) -> WindowedHistogram:
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = WindowedHistogram(maxlen)
        return h

    def snapshot(self, clear_windows: bool = True) -> Dict[str, float]:
        """One flat dict of everything registered. Counters contribute
        cumulative and per-window values; histograms contribute their
        window reductions (and reset when ``clear_windows``)."""
        out: Dict[str, float] = {}
        for name, c in self._counters.items():
            out[name] = c.value
            out[name + "_window"] = c.window()
        for name, g in self._gauges.items():
            out[name] = g.value
        for name, e in self._ewmas.items():
            if e.value is not None:
                out[name] = e.value
        for name, h in self._hists.items():
            for stat, v in h.reduce(clear=clear_windows).items():
                out[f"{name}_{stat}"] = v
        return out
