"""Unified observability: metrics registry, step-phase timing,
goodput/MFU accounting, pluggable sinks, and a pollable heartbeat.

The training loop historically reported a fixed print-set plus an
optional wandb/aim tracker; the resilience layer (PR 1) added
skipped-step and watchdog signals with nowhere structured to land. This
package gives every run a machine-readable record (docs/observability.md):

- :class:`~fms_fsdp_tpu.obs.registry.MetricRegistry` — counters, gauges,
  EWMAs, and windowed histograms that are cheap on the hot path (a float
  add / deque append; no host sync, no IO) and only materialize at
  report cadence;
- :class:`~fms_fsdp_tpu.obs.timing.PhaseTimer` — splits host wall time
  into data-wait / compute / checkpoint / other;
- :class:`~fms_fsdp_tpu.obs.timing.GoodputTracker` — goodput =
  productive-step time / wall time, folding in resilience skipped steps;
- sinks (:mod:`~fms_fsdp_tpu.obs.sinks`) — schema-versioned JSONL, CSV
  summary, and an adapter wrapping the legacy wandb/aim tracker so
  ``get_tracker`` becomes one sink among several;
- :class:`~fms_fsdp_tpu.obs.observer.Observer` — the facade the train
  loops drive; built from config by
  :func:`~fms_fsdp_tpu.obs.observer.build_observer`.

Everything is CPU-testable (tests/test_obs.py) and adds no device work:
the only inputs are host timestamps and the metric scalars the loop
already fetched once per report interval.
"""

from fms_fsdp_tpu.obs.observer import Observer, build_observer
from fms_fsdp_tpu.obs.registry import MetricRegistry
from fms_fsdp_tpu.obs.schema import (
    SCHEMA_VERSION,
    schema_digest,
    validate_record,
)
from fms_fsdp_tpu.obs.sinks import (
    CSVSink,
    Heartbeat,
    JSONLSink,
    TrackerSink,
)
from fms_fsdp_tpu.obs.timing import GoodputTracker, PhaseTimer

__all__ = [
    "Observer",
    "build_observer",
    "MetricRegistry",
    "SCHEMA_VERSION",
    "schema_digest",
    "validate_record",
    "JSONLSink",
    "CSVSink",
    "TrackerSink",
    "Heartbeat",
    "PhaseTimer",
    "GoodputTracker",
]
