from fms_fsdp_tpu.train.step import (
    cross_entropy_loss,
    get_lr_schedule,
    init_train_state,
    make_optimizer,
    make_train_step,
)

__all__ = [
    "cross_entropy_loss",
    "get_lr_schedule",
    "init_train_state",
    "make_optimizer",
    "make_train_step",
]
