"""The jitted train step and its pieces.

The reference's hot loop — zero_grad / forward / CE loss / backward /
clip_grad_norm / AdamW step / scheduler step
(ref:fms_fsdp/utils/train_utils.py:87-98) — becomes ONE jitted, donated
function over sharded global arrays. XLA overlaps the per-layer param
all-gathers with compute (what FSDP prefetch does by hand) and fuses the
optimizer update into the backward epilogue.

Optimizer parity: AdamW lr=cfg.learning_rate betas=(0.9, 0.95) wd=0.1
(ref:main_training_llama.py:113-115), global-norm clipping at
cfg.grad_clip_thresh (ref:train_utils.py:96), warmup+cosine schedule with
0.1 floor or linear annealing (ref:main_training_llama.py:137-148).
"""

import functools

import jax
import jax.numpy as jnp
import optax

from fms_fsdp_tpu.models import get_model_api
from fms_fsdp_tpu.parallel.ac import selective_ac_mask
from fms_fsdp_tpu.parallel.mixed_precision import get_dtype_policy
from fms_fsdp_tpu.parallel.sharding import (
    batch_pspec,
    infer_state_specs,
    init_amax_state,
    quantized_grad_reduce,
    resolve_spec,
    tree_shardings,
)

# torch CrossEntropyLoss default (ref:train_utils.py:90-91); one definition
# shared with the fused loss path
from fms_fsdp_tpu.ops.fused_ce import IGNORE_INDEX


def cross_entropy_loss(logits, labels):
    """Token-mean CE over labels != -100, matching
    ``CrossEntropyLoss()(output.view(-1, V), label.view(-1))``.

    Stable for bf16 logits: the max subtraction happens in the input dtype
    (exact — it only drops the shared exponent) and the exp/sum accumulate
    in fp32; no fp32 logits tensor is ever materialized.
    """
    mask = labels != IGNORE_INDEX
    safe_labels = jnp.where(mask, labels, 0)
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    shifted = (logits - m).astype(jnp.float32)
    logz = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0].astype(
        jnp.float32
    )
    gold = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[
        ..., 0
    ].astype(jnp.float32)
    token_loss = (logz - gold) * mask
    return token_loss.sum() / jnp.maximum(mask.sum(), 1)


def get_lr_schedule(cfg, start_step: int = 0):
    """Return optax schedule fn: count -> lr.

    initial stage: lr * min(1 - (1 - x/w)^2,  0.1 + 0.45*(1 + cos(pi x/T)))
    with w = min(2000, T/20) (quadratic warmup into cosine with 0.1 floor);
    annealing stage: lr * (1 - x/T). (ref:main_training_llama.py:137-148)
    """
    T = cfg.num_steps
    lr = cfg.learning_rate

    if cfg.training_stage == "annealing":

        def schedule(count):
            x = count + start_step
            return lr * (1 - x / T)

    else:
        warmup = max(1, min(2000, T // 20))

        def schedule(count):
            x = count + start_step
            wx = jnp.minimum(x, warmup)
            warm = 1 - (1 - wx / warmup) ** 2
            cos = 0.1 + 0.5 * (1 - 0.1) * (
                1 + jnp.cos(jnp.minimum(x, T) / T * jnp.pi)
            )
            return lr * jnp.minimum(warm, cos)

    return schedule


def make_optimizer(cfg, start_step: int = 0):
    """AdamW(0.9, 0.95, wd=0.1). Global-norm clipping happens in the train
    step (fp32 norm, like torch clip_grad_norm_).

    The learning rate is *injected* each step from the schedule evaluated at
    the train state's own step counter, not from optax's internal count —
    so a non-resume load (continued pretraining / annealing over a restored
    optimizer) restarts the schedule simply by resetting state["step"],
    exactly like the reference's fresh LambdaLR over a loaded optimizer
    (ref:main_training_llama.py:130-148).
    """
    del start_step
    return optax.inject_hyperparams(_adamw_fp32_grads)(
        learning_rate=cfg.learning_rate,
        b1=0.9,
        b2=0.95,
        weight_decay=0.1,
    )


def _adamw_fp32_grads(learning_rate, b1, b2, weight_decay):
    """adamw that upcasts incoming (bf16) grads to the param (storage)
    dtype per-leaf inside ``update``. Doing the cast here rather than as a
    whole-tree map before the optimizer keeps each upcast buffer
    leaf-local — the all-live gradient set stays in the reduce dtype,
    which is what lets 7B-shaped layers train on a 16GB chip. Casting to
    the *param* dtype (not unconditionally fp32) keeps moment dtypes
    stable under the pure_bf16 policy, and reusing adamw's own ``init``
    keeps the opt_state pytree identical to plain adamw.
    """
    inner = optax.adamw(
        learning_rate=learning_rate, b1=b1, b2=b2, weight_decay=weight_decay
    )

    def update(grads, state, params):
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
        return inner.update(grads, state, params)

    return optax.GradientTransformation(inner.init, update)


def init_train_state(
    rng,
    model_cfg,
    cfg,
    mesh,
    optimizer,
):
    """Create the fully sharded train state {params, opt_state, step} for
    any supported model family (Llama, Mamba hybrid, Mixtral MoE).

    Init runs *inside jit with sharded outputs*: each device materializes
    only its own param/opt shards — the TPU analog of the reference's
    meta-device + per-shard ``reset_parameters`` path used for 70B
    (``low_cpu_fsdp``, ref:main_training_llama.py:60-62,
    ref:policies/param_init.py:9-18) — and it is cheap enough that we always
    do it.
    """
    policy = get_dtype_policy(cfg)
    init_params, _, specs_fn, _ = get_model_api(model_cfg)

    def init_fn(rng):
        params = init_params(rng, model_cfg, dtype=policy.param_dtype)
        state = {
            "params": params,
            "opt_state": optimizer.init(params),
            "step": jnp.zeros((), jnp.int32),
        }
        if policy.reduce_quant == "fp8_delayed":
            # delayed-scaling amax history rides in the train state so
            # it checkpoints / donates / elastic-reshards (replicated)
            # like optimizer state
            state["quant"] = init_amax_state(
                params, int(getattr(cfg, "fp8_amax_history_len", 16))
            )
        return state

    shapes = jax.eval_shape(init_fn, rng)
    specs = infer_state_specs(shapes, specs_fn())
    shardings = tree_shardings(
        mesh, specs, jax.tree.map(lambda s: s.shape, shapes)
    )
    return jax.jit(init_fn, out_shardings=shardings)(rng), shardings


def wrap_step_fn(step_fn, timer):
    """Host-side observability wrapper over the jitted step: attribute
    dispatch wall time to the ``compute`` phase (obs/timing.py). Dispatch
    is asynchronous — per-step host time here is microseconds once XLA's
    queue is ahead — but it is the hook where a *blocked* dispatch
    (device queue full, i.e. genuinely compute-bound) becomes visible,
    and the once-per-report ``device_get`` (also attributed to compute
    by the loop) accounts the rest of the window's device time."""

    def stepped(state, batch):
        with timer.phase("compute"):
            return step_fn(state, batch)

    return stepped


def make_train_step(
    model_cfg,
    cfg,
    mesh,
    optimizer,
    start_step: int = 0,
):
    """Build the jitted train step: (state, (input, label)) -> (state, metrics).

    metrics = {loss, gnorm (pre-clip global grad norm, the value the
    reference logs, ref:train_utils.py:96,109), lr, nonfinite (1.0 when
    the batch produced a non-finite loss or grad norm — the anomaly
    guard's on-device flag, fetched with the rest of the window so the
    host never syncs for it)}.

    Anomaly guard (cfg.anomaly_skip_updates, default on): when the flag
    is set the update is skipped on device — the clip scale collapses to
    0 (zeroing the grads via the jnp.where select below) and params /
    optimizer state carry the previous step's values forward, so one
    poisoned batch can never write NaN into the moments. Host-side
    policy over the flags (report skipped_batches, abort after K
    consecutive) lives in resilience/guards.py.

    The LR is evaluated at ``state["step"] + start_step`` and injected into
    the optimizer each step; ``start_step`` is nonzero only when training
    should behave as if already N steps in while state["step"] starts at 0
    (the annealing-over-loaded-model flow, ref:main_training_llama.py:
    137-148). Resumed checkpoints restore state["step"] itself, so they
    pass 0.
    """
    policy = get_dtype_policy(cfg)
    from fms_fsdp_tpu.ops.attention import configure_flash_variant

    configure_flash_variant(getattr(cfg, "flash_kernel_variant", None))
    # kernel tuning mode/table resolved once per step build, same
    # discipline as the flash variant: cached jits can never disagree
    # with the config that built them
    from fms_fsdp_tpu.tune.lookup import (
        configure_kernel_tuning,
        resolve_ce_chunk,
        resolve_dcn_bucket,
    )

    configure_kernel_tuning(
        getattr(cfg, "kernel_tuning", None),
        getattr(cfg, "kernel_tuning_table", "") or None,
    )
    init_params, forward_fn, specs_fn, n_layers = get_model_api(model_cfg)
    ac_mask = None
    if cfg.fsdp_activation_checkpointing:
        ac_mask = selective_ac_mask(n_layers, cfg.selective_checkpointing)
    schedule = get_lr_schedule(cfg, start_step)

    fused = cfg.fused_loss
    chunk = cfg.loss_chunk_size
    if fused:
        # the logits-chunk knob is tunable: table override under
        # kernel_tuning="auto", exactly cfg.loss_chunk_size when "off"
        d_model = getattr(model_cfg, "emb_dim", None) or getattr(
            model_cfg, "d_model", 0
        )
        vocab = getattr(model_cfg, "src_vocab_size", None) or getattr(
            model_cfg, "vocab_size", 0
        )
        chunk = resolve_ce_chunk(
            d_model,
            vocab,
            jnp.dtype(policy.compute_dtype).name,
            requested=chunk,
        )

    # resilience: skip-on-nonfinite guard + the nan_loss injection site
    # (both resolved at trace time — no per-step host involvement)
    from fms_fsdp_tpu.resilience.faults import fault_params

    guard_updates = bool(getattr(cfg, "anomaly_skip_updates", True))
    nan_fault = fault_params("nan_loss")
    # NOTE: the sdc_grad_flip fault site deliberately does NOT inject
    # here. Any trace-level difference — even an exact multiply-by-1.0
    # gated to one process, or the same op armed identically everywhere
    # — changes XLA's fusion/precision decisions and shifts the
    # compiled program's rounding at bf16 level, silently diverging
    # replicas (or the armed run from the clean run) on every step, not
    # just the injected one. The injection lives host-side at the train
    # loop's step boundary (resilience/divergence.py::inject_sdc),
    # where it perturbs one process's addressable shards with ZERO
    # program changes.

    from fms_fsdp_tpu.models import MambaConfig, MixtralConfig

    extra_kwargs = {}
    moe = isinstance(model_cfg, MixtralConfig)
    if isinstance(model_cfg, MambaConfig):
        extra_kwargs = {"mamba_kernel": cfg.mamba_kernel}
    elif moe:
        # train with capacity-based routing + EP; the dense-mix path is the
        # frozen-base/eval formulation. The forward returns a stats dict
        # {balance, drop_frac} alongside the output: balance (the
        # already-weighted load-balancing loss) joins the objective,
        # drop_frac is reported as a metric.
        extra_kwargs = {"moe_impl": "dispatch", "return_aux": True}

    # DCN overlap (parallel/overlap.py): resolve the bucket schedule once
    # per step build — same discipline as the flash variant and the tuning
    # table above. When disabled ("off", or "auto" on a single-slice
    # mesh), bucket_plan stays None and every branch below is the
    # pre-overlap code path, so the traced program is bit-identical to
    # the unbucketed step (pinned by tests/test_overlap.py).
    from fms_fsdp_tpu.parallel import overlap as dcn_overlap
    from fms_fsdp_tpu.parallel.mesh import num_mesh_slices

    bucket_plan = None
    param_specs = None
    dcn_overlap.set_plan_summary(None)
    if dcn_overlap.overlap_enabled(getattr(cfg, "dcn_overlap", "auto"), mesh):
        param_shapes = jax.eval_shape(
            lambda k: init_params(k, model_cfg, dtype=policy.param_dtype),
            jax.random.PRNGKey(0),
        )
        wire = dcn_overlap.wire_bytes_per_element(policy.reduce_quant)
        shape_leaves = jax.tree.leaves(param_shapes)
        total_wire = sum(int(s.size) for s in shape_leaves) * wire
        bucket_mb = resolve_dcn_bucket(
            grad_mb=-(-total_wire // dcn_overlap.MB),
            leaves=len(shape_leaves),
            slices=num_mesh_slices(mesh),
            wire_bytes=wire,
            requested=int(getattr(cfg, "dcn_bucket_mb", 0)),
        )
        bucket_plan = dcn_overlap.assign_buckets(param_shapes, bucket_mb, wire)
        param_specs = specs_fn()
        dcn_overlap.set_plan_summary(bucket_plan.summary())

    def loss_fn(params, inputs, labels):
        if bucket_plan is not None:
            # bucket anchors go around the params *entering* the forward,
            # so each bucket's cotangents join the backward exactly where
            # that bucket's layers finish differentiating
            params = dcn_overlap.apply_bucket_anchors(
                params, bucket_plan, param_specs, mesh
            )
        out = forward_fn(
            params,
            inputs,
            model_cfg,
            compute_dtype=policy.compute_dtype,
            attn_impl=cfg.attention_kernel,
            ac_mask=ac_mask,
            scan_layers=cfg.scan_layers,
            mesh=mesh,
            return_hidden=fused,
            quant=cfg.quantized_matmuls,
            **extra_kwargs,
        )
        aux = 0.0
        stats = {}
        if moe:
            out, moe_stats = out
            aux = moe_stats["balance"]
            stats["moe_drop_frac"] = moe_stats["drop_frac"]
        if fused:
            from fms_fsdp_tpu.ops.fused_ce import fused_linear_cross_entropy

            w = params["lm_head"].astype(policy.compute_dtype)
            return fused_linear_cross_entropy(out, w, labels, chunk) + aux, stats
        return cross_entropy_loss(out, labels) + aux, stats

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state, batch):
        inputs, labels = batch
        bspec = jax.sharding.NamedSharding(
            mesh, resolve_spec(batch_pspec(), inputs.shape, mesh)
        )
        inputs = jax.lax.with_sharding_constraint(inputs, bspec)
        labels = jax.lax.with_sharding_constraint(labels, bspec)
        # Differentiate w.r.t. a compute-dtype copy of the params: gradients
        # then live in the policy's reduce dtype end-to-end (bf16 for the
        # bfSixteen preset, mirroring the reference's reduce_dtype=bf16,
        # ref:policies/mixed_precision.py:5-27) and the all-live grad tree
        # is half the size of fp32 grads. The fp32 upcast for Adam happens
        # per-leaf inside the optimizer chain.
        params_c = jax.tree.map(
            lambda p: p.astype(policy.compute_dtype), state["params"]
        )
        # named scopes bracket the trace so WindowedProfiler XPlane rows
        # attribute device time to fwd_bwd vs optimizer (docs/observability.md)
        with jax.named_scope("fwd_bwd"):
            (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params_c, inputs, labels
            )
        if nan_fault is not None:
            # injected non-finite batch: poison loss AND grads for steps
            # [step, step+count) — the NaN-batch failure the guard below
            # must absorb (tests/test_resilience.py)
            at = int(nan_fault.get("step", 0))
            cnt = int(nan_fault.get("count", 1))
            s = state["step"] + start_step
            poison = jnp.where(
                (s >= at) & (s < at + cnt), jnp.float32(jnp.nan), jnp.float32(1.0)
            )
            loss = loss * poison
            grads = jax.tree.map(lambda g: g * poison.astype(g.dtype), grads)
        # Global-norm clip with the norm accumulated in fp32 regardless of
        # grad dtype — matches torch clip_grad_norm_ (ref:train_utils.py:96);
        # the pre-clip norm is the value the reference logs. Computed on
        # the RAW backward output, before any reduce wire round-trip:
        # the fp8_delayed wire clamps to the representable range, so an
        # inf grad leaf would otherwise be laundered to a finite value
        # here and the anomaly flag below would miss the poisoned batch
        # (while still rolling amax=inf into the delayed-scaling
        # history — permanently NaN-ing every later scale).
        gnorm = optax.global_norm(
            jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        )
        # on-device anomaly flag: loss or grad norm went non-finite (the
        # global norm folds every grad leaf, so one bad leaf trips it)
        nonfinite = jnp.logical_not(
            jnp.logical_and(jnp.isfinite(loss), jnp.isfinite(gnorm))
        )
        # Quantized gradient reduction (policy.reduce_quant): round-trip
        # the grad tree through the scale-carrying wire format exactly
        # where the reduce-dtype boundary sits. "none" skips the call
        # entirely — the traced program is bit-identical to the seed
        # step (pinned by tests/test_quant_parity.py). The clip below
        # uses the pre-wire norm (wire noise shifts it <1%; the guard
        # semantics above are what must never depend on the wire).
        new_quant = state.get("quant")
        if policy.reduce_quant != "none":
            with jax.named_scope("quant_reduce"):
                if bucket_plan is not None:
                    grads, new_quant = dcn_overlap.bucketed_quantized_grad_reduce(
                        grads, policy.reduce_quant, new_quant, bucket_plan
                    )
                else:
                    grads, new_quant = quantized_grad_reduce(
                        grads, policy.reduce_quant, new_quant
                    )
        clip_scale = jnp.minimum(1.0, cfg.grad_clip_thresh / (gnorm + 1e-6))
        if guard_updates:
            # zero poisoned grads with a true select — scaling by 0 would
            # NOT clear NaN (0*NaN=NaN). Also select the clip scale sane:
            # a NaN gnorm makes clip_scale NaN for every leaf otherwise.
            clip_scale = jnp.where(nonfinite, jnp.float32(1.0), clip_scale)
            grads = jax.tree.map(
                lambda g: jnp.where(nonfinite, jnp.zeros_like(g), g), grads
            )
        grads = jax.tree.map(lambda g: g * clip_scale.astype(g.dtype), grads)
        lr = schedule(state["step"])
        opt_state = state["opt_state"]._replace(
            hyperparams=dict(state["opt_state"].hyperparams, learning_rate=lr)
        )
        with jax.named_scope("optimizer"):
            updates, opt_state = optimizer.update(
                grads, opt_state, state["params"]
            )
            params = optax.apply_updates(state["params"], updates)
        if guard_updates:
            # fully skip the update: even zeroed grads decay Adam moments
            # and apply weight decay — carry the old state forward. This
            # restore is the actual correctness guarantee; the grad
            # zeroing above only keeps the optimizer arithmetic finite.
            params = jax.tree.map(
                lambda new, old: jnp.where(nonfinite, old, new),
                params,
                state["params"],
            )
            opt_state = jax.tree.map(
                lambda new, old: jnp.where(nonfinite, old, new),
                opt_state,
                state["opt_state"],
            )
            if new_quant is not None:
                # a poisoned batch must not roll NaN (or a poisoned
                # amax) into the delayed-scaling history — carry the
                # old window forward like the moments
                new_quant = jax.tree.map(
                    lambda new, old: jnp.where(nonfinite, old, new),
                    new_quant,
                    state["quant"],
                )
        metrics = {
            "loss": loss,
            "gnorm": gnorm,
            "lr": lr,
            "nonfinite": nonfinite.astype(jnp.float32),
            **stats,
        }
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        if new_quant is not None:
            new_state["quant"] = new_quant
        return new_state, metrics

    return train_step
