"""Speculator training: stage-1/stage-2 losses, two-stage LR schedule, and
the host loop (ref:speculator/train_speculator_utils.py:122-427).

Stage 1 (steps <= stage2_start_step): one frozen-base forward over the
batch yields embeddings in parallel; each speculator head is scored with
CE against the ground-truth tokens it should predict.

Stage 2: the frozen base *generates* (kv-cache sampling, models/generation)
from short prompts carved out of the batch, and the speculator learns to
match the base model's own output distribution.

Both stages are jitted end-to-end; the base params are closed over and
never differentiated. The reference's manual TP input all-gather / output
chunking (ref:train_speculator_utils.py:327-338, 158-162, 224-232) has no
analog here — inputs are global arrays and GSPMD handles any tensor axis.
"""

import logging
import math
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fms_fsdp_tpu.models import get_base_api
from fms_fsdp_tpu.models.speculator import SpeculatorConfig, speculator_forward
from fms_fsdp_tpu.train.step import cross_entropy_loss

logger = logging.getLogger(__name__)

# quantized_matmuls values the step builder had to ignore (non-llama
# base archs drop the flag through their **_unused kwargs). Pending
# count drains into the observer registry as the
# ``speculator.quant_ignored`` counter once the loop attaches one —
# builders run before the observer exists, so the note is buffered.
_QUANT_IGNORED_WARNED = set()
_QUANT_IGNORED_PENDING = 0


def _note_quant_ignored(quant: str, arch: str) -> int:
    """One-shot warning + buffered obs count for a quantized_matmuls
    request the base arch cannot honor. Returns the pending count."""
    global _QUANT_IGNORED_PENDING
    _QUANT_IGNORED_PENDING += 1
    key = (quant, arch)
    if key not in _QUANT_IGNORED_WARNED:
        _QUANT_IGNORED_WARNED.add(key)
        logger.warning(
            "quantized_matmuls=%r is not supported for the %r speculator "
            "base arch (only llama bases thread quant= through the frozen "
            "forward); training proceeds UNQUANTIZED. Recorded as the "
            "speculator.quant_ignored obs counter.",
            quant, arch,
        )
    return _QUANT_IGNORED_PENDING


def _drain_quant_ignored(registry) -> None:
    """Flush buffered quant-ignored notes into an obs registry."""
    global _QUANT_IGNORED_PENDING
    if _QUANT_IGNORED_PENDING and registry is not None:
        registry.counter("speculator.quant_ignored").add(
            _QUANT_IGNORED_PENDING
        )
        _QUANT_IGNORED_PENDING = 0


def get_speculator_lr_schedule(cfg, start_step: int = 0):
    """Two-stage schedule (ref:speculator/train_speculator.py:262-299):
    stage 1 warms up then cosine-anneals to 10%; stage 2 restarts at 10%
    of max, warms up, and anneals to 1%."""
    s2_start = cfg.stage2_start_step
    warmup1 = max(1, min(2000, s2_start // 20))
    warmup2 = max(1, min(2000, (cfg.num_steps - s2_start) // 20))
    s2_span = max(1, cfg.num_steps - s2_start)

    def stage1(x):
        wx = jnp.minimum(x, warmup1)
        warm = 1 - (1 - wx / warmup1) ** 2
        cos = 0.1 + 0.5 * (1 - 0.1) * (1 + jnp.cos(x / s2_start * jnp.pi))
        return jnp.minimum(warm, cos)

    def stage2(x):
        wx = jnp.minimum(x, warmup2)
        warm = 0.1 * (1 - (1 - wx / warmup2) ** 2)
        cos = 0.01 + 0.05 * (1 - 0.1) * (
            1 + jnp.cos(jnp.minimum(x, s2_span) / s2_span * jnp.pi)
        )
        return jnp.minimum(warm, cos)

    def schedule(count):
        x = count + start_step
        return cfg.learning_rate * jnp.where(
            x <= s2_start, stage1(x), stage2(x - s2_start)
        )

    return schedule


def make_speculator_optimizer(cfg):
    """AdamW (0.9, 0.95, wd 0.1), LR injected per step like the main path
    (ref:speculator/train_speculator.py:234-239)."""
    return optax.inject_hyperparams(optax.adamw)(
        learning_rate=cfg.learning_rate, b1=0.9, b2=0.95, weight_decay=0.1
    )


def _per_head_ce(preds, targets_fn):
    """preds (n, B, N, V); targets_fn(i) -> (B, N). Returns (total, per-head)."""
    losses = []
    for i in range(preds.shape[0]):
        losses.append(cross_entropy_loss(preds[i], targets_fn(i)))
    return sum(losses), jnp.stack(losses)


def make_stage1_step(
    base_params, model_cfg, scfg: SpeculatorConfig, cfg, optimizer,
    base_api=None, mesh=None,
):
    """(spec_state, input (B, L)) -> (spec_state, metrics). Ground-truth
    feed: embeds over input[:, :-n-1], head i scored against
    input[:, i+2 : N+i+2] (ref:train_speculator_utils.py:122-171)."""
    base_api = base_api or get_base_api("embedllama")
    from fms_fsdp_tpu.ops.attention import configure_flash_variant

    configure_flash_variant(getattr(cfg, "flash_kernel_variant", None))
    n_predict = scfg.n_predict
    schedule = get_speculator_lr_schedule(cfg)
    # int8/fp8 base forward: the frozen teacher's GEMMs can run on the
    # MXU quantized path too — Llama bases only (the other archs would
    # silently ignore the flag through their **_unused kwargs, so a
    # non-llama request is warned once and counted in obs)
    quant = getattr(cfg, "quantized_matmuls", "none") or "none"
    if base_api.arch != "llama" and quant != "none":
        _note_quant_ignored(quant, base_api.arch)
        quant = "none"

    def loss_fn(spec_params, inputs):
        _, embeds = base_api.forward_embeds(
            base_params,
            inputs[:, : -n_predict - 1],
            model_cfg,
            attn_impl=cfg.attention_kernel,
            quant=quant,
            mesh=mesh,
        )
        embeds = jax.lax.stop_gradient(embeds)
        preds = speculator_forward(spec_params, embeds, inputs[:, 1:], scfg)
        n = preds.shape[2]
        return _per_head_ce(preds, lambda i: inputs[:, i + 2 : n + i + 2])

    @jax.jit
    def step(state, inputs):
        (loss, per_head), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], inputs
        )
        return _apply(
            state, grads, optimizer, schedule, loss, per_head,
            cfg.grad_clip_thresh,
        )

    return step


def make_stage2_step(
    base_params, model_cfg, scfg: SpeculatorConfig, cfg, optimizer, base_api=None
):
    """Stage 2: base generates stage2_seq_length tokens from
    stage2_prompt_length prompts (batch reshaped to stage2_batch_size rows),
    and the speculator matches the generated stream
    (ref:train_speculator_utils.py:175-242)."""
    base_api = base_api or get_base_api("embedllama")
    n_predict = scfg.n_predict
    s2_prompt = cfg.stage2_prompt_length
    s2_seq = cfg.stage2_seq_length
    grow = cfg.stage2_batch_size // cfg.batch_size
    assert s2_prompt * grow <= cfg.seq_length, (
        "Error: batch is too small for specified partition"
    )
    schedule = get_speculator_lr_schedule(cfg)

    def loss_fn(spec_params, inputs, key):
        prompts = inputs[:, : s2_prompt * grow].reshape(-1, s2_prompt)
        targs, embeds = base_api.generate(
            base_params,
            prompts,
            model_cfg,
            key=key,
            max_seq_len=s2_prompt + s2_seq,
            max_new_tokens=s2_seq,
            do_sample=True,
            include_embeds=True,
        )
        targs = jax.lax.stop_gradient(targs[:, -s2_seq:])
        embeds = jax.lax.stop_gradient(embeds[:, : s2_seq - n_predict])
        preds = speculator_forward(spec_params, embeds, targs[:, :-1], scfg)
        n = preds.shape[2]
        loss, per_head = _per_head_ce(
            preds, lambda i: targs[:, i + 1 : n + i + 1]
        )
        return loss, per_head

    @jax.jit
    def step(state, inputs, key):
        (loss, per_head), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], inputs, key
        )
        return _apply(
            state, grads, optimizer, schedule, loss, per_head,
            cfg.grad_clip_thresh,
        )

    return step


def _apply(state, grads, optimizer, schedule, loss, per_head, clip_thresh=1.0):
    gnorm = optax.global_norm(
        jax.tree.map(lambda g: g.astype(jnp.float32), grads)
    )
    clip = jnp.minimum(1.0, clip_thresh / (gnorm + 1e-6))
    grads = jax.tree.map(lambda g: g * clip.astype(g.dtype), grads)
    lr = schedule(state["step"])
    opt_state = state["opt_state"]._replace(
        hyperparams=dict(state["opt_state"].hyperparams, learning_rate=lr)
    )
    updates, opt_state = optimizer.update(grads, opt_state, state["params"])
    params = optax.apply_updates(state["params"], updates)
    new_state = {
        "params": params,
        "opt_state": opt_state,
        "step": state["step"] + 1,
    }
    return new_state, {
        "loss": loss,
        "per_head": per_head,
        "gnorm": gnorm,
        "lr": lr,
    }


def do_ckpt(ckpt_save_path, reset=False):
    """On-demand checkpoint flag: operator writes '1' to <save>/do_ckpt
    (ref:train_speculator_utils.py:246-260)."""
    ckpt_cmd_file = os.path.join(ckpt_save_path, "do_ckpt")
    if not os.path.exists(ckpt_cmd_file):
        return False
    if reset:
        with open(ckpt_cmd_file, "w") as fd:
            fd.write("0")
        return False
    with open(ckpt_cmd_file) as fd:
        return fd.read().strip() == "1"


def train_speculator(
    cfg,
    base_params,
    model_cfg,
    spec_state,
    scfg: SpeculatorConfig,
    rank,
    train_loader,
    optimizer,
    checkpointer,
    start_step=0,
    n_tok=0,
    profiler=None,
    ckpt_loader=None,
    base_api=None,
    mesh=None,
    observer=None,
):
    """Speculator host loop with the reference's reporting/ckpt cadence
    (ref:train_speculator_utils.py:263-427). ``train_loader`` yields global
    input batches (e.g. a DeviceFeed); ``ckpt_loader`` is the stateful
    pipeline object whose state gets checkpointed (defaults to
    train_loader when it exposes save_to_path).

    ``observer`` (obs/) emits the same schema-versioned metrics.jsonl /
    heartbeat as the pretraining loop; MFU/HFU are null here — the wall
    time is dominated by the *frozen* base forward (stage 1) or
    generation (stage 2), which the trained-model FLOPs convention does
    not count."""
    stage1 = make_stage1_step(
        base_params, model_cfg, scfg, cfg, optimizer, base_api, mesh=mesh
    )
    stage2 = None  # built lazily: its batch-partition constraints only
    # apply once stage 2 actually starts
    key = jax.random.PRNGKey(cfg.seed + 17)
    if ckpt_loader is None and hasattr(train_loader, "save_to_path"):
        ckpt_loader = train_loader

    # per-chip reporting normalizes by the data-parallel chip count
    world_size = max(
        1,
        jax.device_count()
        // max(1, getattr(cfg, "tensor_parallel_size", 1))
        // max(1, getattr(cfg, "context_parallel_size", 1)),
    )
    from fms_fsdp_tpu.utils.train_utils import PreemptionGuard

    if observer is None:
        from fms_fsdp_tpu.obs import build_observer

        observer = build_observer(cfg, rank)
    # the stage builders ran before the observer existed: land any
    # ignored-quant notes in THIS run's registry
    _drain_quant_ignored(observer.registry)
    # a perf record must state the numerics that actually ran: when the
    # builders dropped the quant flag (non-llama base, warned above),
    # the v4 quantized_matmuls field must say "none", not the config's
    # ignored request
    arch = base_api.arch if base_api is not None else "llama"
    if arch != "llama" and getattr(observer, "quantized_matmuls", None):
        observer.quantized_matmuls = "none"
    checkpointer.observer = observer
    train_loader = observer.wrap_data_iter(train_loader)

    window = []
    elapsed_tokens = 0
    start = time.time()
    loop_start = time.time()
    step_tok = 0
    preemption = PreemptionGuard().install()

    try:
        for batch_idx, inputs in enumerate(train_loader, start=start_step + 1):
            if batch_idx > cfg.num_steps:
                break
            if isinstance(inputs, tuple):
                inputs = inputs[0]
            if not isinstance(inputs, jax.Array):
                inputs = jnp.asarray(inputs, jnp.int32)

            with observer.phase("compute"):
                if batch_idx <= cfg.stage2_start_step:
                    spec_state, metrics = stage1(spec_state, inputs)
                    # global arrays: .size already counts the full global batch
                    step_tok = inputs.size
                else:
                    if stage2 is None:
                        stage2 = make_stage2_step(
                            base_params, model_cfg, scfg, cfg, optimizer, base_api
                        )
                    key, sub = jax.random.split(key)
                    spec_state, metrics = stage2(spec_state, inputs, sub)
                    grow = cfg.stage2_batch_size // cfg.batch_size
                    step_tok = inputs.shape[0] * grow * cfg.stage2_seq_length
            window.append(metrics)

            if profiler:
                profiler.step()

            if batch_idx % cfg.report_interval == 0:
                with observer.phase("compute"):
                    fetched = jax.device_get(window)
                window = []
                per_head = np.mean([m["per_head"] for m in fetched], axis=0)
                g_norm = float(np.mean([m["gnorm"] for m in fetched]))
                elapsed_time = time.time() - loop_start
                elapsed_tokens += cfg.report_interval * step_tok
                if rank == 0:
                    print(f"{time.time()}")
                    print("step:", batch_idx)
                    print("tokens seen:", n_tok + elapsed_tokens)
                    for i in range(len(per_head)):
                        print(f"loss {i + 1}:", float(per_head[i]))
                    print("gradient norm:", g_norm)
                    print(
                        f"speed for these {cfg.report_interval} steps:",
                        (time.time() - start) / cfg.report_interval,
                    )
                    print("overall speed:", elapsed_time / (batch_idx - start_step))
                    print("LR:", float(fetched[-1]["lr"]))
                    print(
                        "overall token per chip per sec:",
                        int(elapsed_tokens / world_size / elapsed_time),
                    )
                    print(
                        "token per day:",
                        int(elapsed_tokens / elapsed_time * 3600 * 24),
                    )
                    print()
                window_wall = max(1e-9, time.time() - start)
                # rates priced on the TRUE window step count (a resume's
                # first window is partial) at the last step's token size —
                # a window straddling the stage1->stage2 switch is an
                # approximation either way
                window_steps = max(1, len(fetched))
                observer.report(
                    batch_idx,
                    len(fetched),
                    loss=float(np.mean([m["loss"] for m in fetched])),
                    grad_norm=g_norm,
                    learning_rate=float(fetched[-1]["lr"]),
                    tokens_seen=n_tok + elapsed_tokens,
                    tokens_per_sec_per_chip=(
                        window_steps * step_tok / world_size / window_wall
                    ),
                    tokens_per_sec_per_chip_overall=(
                        elapsed_tokens / world_size / max(1e-9, elapsed_time)
                    ),
                    step_time_s=window_wall / window_steps,
                    extra={
                        f"loss_head_{i + 1}": float(per_head[i])
                        for i in range(len(per_head))
                    },
                )
                start = time.time()

            preempt_now = preemption.poll()
            interval_due = (
                checkpointer.save_due(batch_idx)
                if hasattr(checkpointer, "save_due")
                else batch_idx % cfg.checkpoint_interval == 0
            )
            demand_now = do_ckpt(cfg.ckpt_save_path) is True
            if (
                interval_due
                or batch_idx == cfg.num_steps
                or demand_now
                or preempt_now
            ):
                reason = (
                    "preempt"
                    if preempt_now
                    else "final"
                    if batch_idx == cfg.num_steps
                    else "demand"
                    if demand_now
                    else "interval"
                )
                checkpointer.save(
                    batch_idx,
                    spec_state,
                    ckpt_loader,
                    reason=reason,
                    tokens_seen=elapsed_tokens + n_tok,
                )
                do_ckpt(cfg.ckpt_save_path, reset=True)
            if preempt_now:
                if rank == 0:
                    print(
                        f"preemption signal received: checkpoint saved at step "
                        f"{batch_idx}, exiting clean"
                    )
                break
    finally:
        try:
            # never exit with a save in flight: the final/preemption
            # checkpoint must be committed, not torn (ckpt/manager.py;
            # no-op on the synchronous Checkpointer)
            checkpointer.finalize()
        finally:
            observer.close()
    return spec_state
