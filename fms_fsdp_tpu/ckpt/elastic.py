"""Elastic resume: topology fingerprinting and rescale legality.

Preemptible TPU capacity rarely comes back at the size that died: a
32-host run restarts on 16, a 4-chip slice on 8. The data layer already
reshards (``data/stateful.py`` fractional ownership over
``ScalableShardDataset`` logical shards) and the Orbax restore already
lands shards on whatever mesh the new world built — but nothing used to
*record* the save-time topology or *validate* that a restart can legally
consume it. This module owns both halves of that contract:

- ``current_fingerprint(cfg)`` builds the topology dict every checkpoint
  stamps into ``metadata.json`` under the ``"topology"`` key (both the
  synchronous ``Checkpointer.save`` and every ``AsyncCheckpointManager``
  tier);
- ``check_rescale(old, new)`` decides, *before* any collective restore
  is entered, whether the restart world can consume the checkpoint —
  returning actionable problems instead of letting the run die later in
  an opaque Orbax sharding error or a silently shifted document walk.

The field set is a cross-run contract (old checkpoints are read by new
code): changing it without bumping ``TOPOLOGY_VERSION`` fails CI via the
pinned digest, exactly like the obs metric schema
(``fms_fsdp_tpu/obs/schema.py``).

Policy (docs/checkpointing.md "Elastic resume"): the *global* batch is
preserved across a rescale — per-rank rows are recomputed from the
checkpoint's ``global_batch_rows`` (``data/loader.py::
elastic_batch_size``) so ``tokens_seen``, the LR schedule, and the loss
trajectory stay meaningful. A rescale that cannot preserve it (rows do
not divide the new data-parallel extent), or an explicit batch/seq
change, is a hard error unless ``--allow_batch_change=True``.
"""

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

TOPOLOGY_VERSION = 3

# name -> type tag. The topology fingerprint stamped into every
# checkpoint's metadata.json (key "topology"). ``loader_files`` is the
# number of per-rank loader_state files the save wrote (0 when no
# dataloader rode along) == process_count * num_workers of the saving
# run; it is the world size the loader state reshards FROM.
#
# v2 adds the slice dims (multi-slice DCN meshes, parallel/mesh.py):
# ``num_slices`` (the dcn-axis extent / fault-domain count) and the
# per-slice process/device shape. The slice is the FAULT DOMAIN:
# ``check_rescale`` admits slice-count changes (a lost or regained
# slice) but pins the per-slice shape while multi-slice — capacity that
# comes back in different slice sizes must restart single-slice or
# matching. Old (v1) fingerprints lack the fields; they load with a
# note and skip the slice checks.
#
# v3 adds the data-mix dims (weighted multi-corpus mixing,
# data/streaming.py SamplingDataset): ``corpus_names`` is the comma-
# joined corpus list in config order ("" for dummy-data runs) and
# ``mix_weights_digest`` a digest of the normalized weight vector.
# ``check_rescale`` gates corpus-SET changes (per-corpus mix state pairs
# by name and cannot follow added/removed corpora without
# ``allow_corpus_change``) while weight changes and pure reorders stay
# legal with a note (``describe_mixing_change``). Pre-v3 fingerprints
# lack the fields and skip the mixing checks.
TOPOLOGY_FIELDS = {
    "process_count": "int",
    "device_count": "int",
    "tensor_parallel_size": "int",
    "context_parallel_size": "int",
    "global_batch_rows": "int",
    "seq_length": "int",
    "n_logical_shards": "int",
    "loader_files": "int",
    "num_slices": "int",
    "slice_process_count": "int",
    "slice_device_count": "int",
    "corpus_names": "str",
    "mix_weights_digest": "str",
}

# Digest of the canonical field serialization per published version; a
# mismatch for the CURRENT version means the fingerprint contract
# changed without a version bump (pinned in CI, tests/test_elastic.py).
TOPOLOGY_DIGESTS = {
    1: "a8d823b4a35b82fa1e2c91d376e485caf15a6f4558edfe0696426dd7ea129334",
    # v2: + num_slices / slice_process_count / slice_device_count (the
    # multi-slice fault-domain dims)
    2: "41468023883ed0cf352f1e808cef04a5b5788ecb5f44d8d033773ec6ba2b66fe",
    # v3: + corpus_names / mix_weights_digest (the weighted multi-corpus
    # mix joins the elastic contract)
    3: "ed18d2b2c9ee9fb0efbe627f52a36d77a96b44ccad180430c905df9772de179c",
}


def topology_digest() -> str:
    canon = json.dumps(
        {"version": TOPOLOGY_VERSION, "fields": TOPOLOGY_FIELDS},
        sort_keys=True,
        separators=(",", ":"),
    )
    return hashlib.sha256(canon.encode()).hexdigest()


def data_parallel_rows_extent(cfg, device_count: int) -> int:
    """Data-parallel extent (replica x fsdp x expert) the global batch
    spreads over — the mesh-free mirror of ``parallel.mesh.
    data_parallel_extent`` (every mesh axis not tensor/context carries
    batch rows)."""
    tp = max(1, int(getattr(cfg, "tensor_parallel_size", 1) or 1))
    cp = max(1, int(getattr(cfg, "context_parallel_size", 1) or 1))
    return max(1, device_count // tp // cp)


def _split_names(joined: str) -> List[str]:
    return [n for n in str(joined or "").split(",") if n]


def mixing_fingerprint(cfg) -> Tuple[str, str]:
    """The data-mix dims of the fingerprint: (comma-joined corpus names
    in config order, digest of the normalized weight vector). Dummy-data
    runs (no stateful loader) fingerprint as ("", "") and skip every
    mixing check."""
    if bool(getattr(cfg, "use_dummy_dataset", False)):
        return "", ""
    from fms_fsdp_tpu.data.loader import parse_data_args

    try:
        datasets, weights = parse_data_args(
            getattr(cfg, "datasets", ""), getattr(cfg, "weights", "1")
        )
    except (ValueError, TypeError):
        return "", ""
    total = float(sum(weights)) or 1.0
    canon = json.dumps(
        [round(w / total, 12) for w in weights], separators=(",", ":")
    )
    return ",".join(datasets), hashlib.sha256(canon.encode()).hexdigest()[:16]


def current_fingerprint(
    cfg, process_count: Optional[int] = None, device_count: Optional[int] = None
) -> Dict[str, int]:
    """The live world's topology fingerprint, from TrainConfig + the
    initialized JAX world. ``loader_files`` is the EXPECTED per-rank
    loader state count (process_count x num_workers; 0 when the run has
    no stateful loader) — the save path substitutes 0 when no dataloader
    actually rides along."""
    import jax

    from fms_fsdp_tpu.parallel.mesh import process_slice_context

    pc = jax.process_count() if process_count is None else int(process_count)
    dc = jax.device_count() if device_count is None else int(device_count)
    data_extent = data_parallel_rows_extent(cfg, dc)
    stateful_loader = not bool(getattr(cfg, "use_dummy_dataset", False))
    workers = max(1, int(getattr(cfg, "num_workers", 1) or 1))
    n_slices, _ = process_slice_context(cfg)
    n_slices = max(1, int(n_slices))
    corpus_names, weights_digest = mixing_fingerprint(cfg)
    return {
        "process_count": pc,
        "device_count": dc,
        "tensor_parallel_size": max(
            1, int(getattr(cfg, "tensor_parallel_size", 1) or 1)
        ),
        "context_parallel_size": max(
            1, int(getattr(cfg, "context_parallel_size", 1) or 1)
        ),
        "global_batch_rows": int(cfg.batch_size) * data_extent,
        "seq_length": int(cfg.seq_length),
        "n_logical_shards": int(getattr(cfg, "logical_shards", 0) or 0),
        "loader_files": pc * workers if stateful_loader else 0,
        # fault-domain dims: slices partition processes/devices evenly
        # (parallel/mesh.py raises at mesh build otherwise, before any
        # save can stamp a torn shape)
        "num_slices": n_slices,
        "slice_process_count": max(1, pc // n_slices),
        "slice_device_count": max(1, dc // n_slices),
        # v3 data-mix dims: per-corpus resume state pairs by NAME, so
        # the corpus set is part of the elastic contract; the weights
        # digest makes a (legal) weight change visible at the gate
        "corpus_names": corpus_names,
        "mix_weights_digest": weights_digest,
    }


def describe_change(old: Dict, new: Dict) -> str:
    """Compact "field: old -> new" summary of the differing fields."""
    parts = [
        f"{k}: {old.get(k)} -> {new.get(k)}"
        for k in TOPOLOGY_FIELDS
        if old.get(k) != new.get(k)
    ]
    return ", ".join(parts)


def stamp_topology(metadata: Dict, fingerprint: Optional[Dict], dataloader) -> Dict:
    """Stamp ``metadata["topology"]`` for a save (no-op without a
    fingerprint). Shared by the synchronous ``Checkpointer.save`` and
    every ``AsyncCheckpointManager`` tier so the stamped contract cannot
    fork between the two save paths: ``loader_files`` records what THIS
    save wrote (the expected count, not a listdir — peers' files may not
    be visible yet on shared storage), 0 when no dataloader rode along."""
    if fingerprint is not None:
        metadata["topology"] = dict(
            fingerprint,
            loader_files=(
                fingerprint.get("loader_files", 0)
                if dataloader is not None
                else 0
            ),
        )
    return metadata


def _count_loader_files(ckp_dir: str) -> int:
    try:
        return len(
            [f for f in os.listdir(ckp_dir) if f.startswith("loader_state")]
        )
    except OSError:
        return 0


def describe_mixing_change(old: Dict, new: Dict) -> Optional[str]:
    """Human note for LEGAL data-mix changes across a resume (printed by
    the load gate), or None when the mix is unchanged / unfingerprinted.
    Corpus-SET changes are not described here — they are gated as
    problems by ``check_rescale`` unless ``allow_corpus_change``."""
    old_names = _split_names(old.get("corpus_names"))
    new_names = _split_names(new.get("corpus_names"))
    if not old_names or not new_names:
        return None
    notes = []
    if old_names != new_names and set(old_names) == set(new_names):
        notes.append(
            "corpus order changed (harmless: per-corpus mix state pairs "
            "by name, not index)"
        )
    old_d = str(old.get("mix_weights_digest") or "")
    new_d = str(new.get("mix_weights_digest") or "")
    if old_d and new_d and old_d != new_d:
        notes.append(
            "mixing weights changed: the token-share controller steers "
            "toward the new targets from here (no stream position is "
            "lost)"
        )
    return "; ".join(notes) or None


def check_rescale(
    old: Dict,
    new: Dict,
    ckp_dir: Optional[str] = None,
    allow_batch_change: bool = False,
    allow_corpus_change: bool = False,
) -> Tuple[List[str], bool]:
    """Validate that the ``new`` world may consume a checkpoint stamped
    with ``old``. Returns ``(problems, changed)`` — ``problems`` is a
    list of actionable error strings (empty = legal), ``changed`` is
    True when any topology field differs (a legal elastic resume).

    Every check runs BEFORE the collective Orbax restore, so an illegal
    rescale fails fast on every host with the same message instead of
    deadlocking half the pod inside a collective. The caller is
    responsible for making the verdict collective (``_all_agree``) —
    the on-disk loader-file count below is a local observation that
    eventually-consistent shared storage could briefly split."""
    changed = any(old.get(k) != new.get(k) for k in TOPOLOGY_FIELDS)
    if not changed:
        return [], False
    problems: List[str] = []

    # Slice fault-domain legality (docs/checkpointing.md "Elastic
    # resume", docs/resilience.md "Slice fault domains"): the slice is
    # the unit capacity is lost or regained in, so a changed SLICE COUNT
    # is legal (the batch policy recomputes via the global-batch rules
    # below; the loader walk reshards by fractional ownership exactly as
    # any other rescale) — but while both worlds are multi-slice the
    # PER-SLICE shape is pinned: an hsdp group / ICI collective layout
    # sized for one slice shape cannot silently absorb another, and a
    # rescale mixing both dims is almost always a mis-launched restart.
    # A single-slice restart (new num_slices == 1) escapes the pin: it
    # is governed by the ordinary process/device rules alone. Legacy v1
    # fingerprints carry no slice fields (all zeros) and skip this block
    # (the load gate prints a note).
    old_s = int(old.get("num_slices") or 0)
    new_s = int(new.get("num_slices") or 0)
    if old_s > 1 and new_s > 1:
        for field, unit in (
            ("slice_process_count", "process(es)"),
            ("slice_device_count", "device(s)"),
        ):
            ov, nv = int(old.get(field) or 0), int(new.get(field) or 0)
            if ov and nv and ov != nv:
                problems.append(
                    f"{field} changed across the rescale ({ov} -> {nv} "
                    f"{unit} per slice): the slice is the fault domain — "
                    f"rescale by whole slices of the saved shape "
                    f"({old.get('slice_process_count')} process(es) x "
                    f"{old.get('slice_device_count')} device(s); any "
                    f"slice count), or restart as a single slice "
                    f"(--num_slices=1) to rescale freely"
                )

    # Data-mix legality (v3, docs/dataloader.md "Multi-corpus mixing"):
    # per-corpus resume state pairs by NAME, so a changed corpus SET
    # (added/removed/renamed) cannot silently misassign another corpus's
    # walk position — it is gated behind allow_corpus_change. A pure
    # reorder or a weight change is legal (the gate prints the
    # describe_mixing_change note). Pre-v3 fingerprints carry no mix
    # fields and skip this block.
    old_corpora = _split_names(old.get("corpus_names"))
    new_corpora = _split_names(new.get("corpus_names"))
    if old_corpora and new_corpora and set(old_corpora) != set(new_corpora):
        if not allow_corpus_change:
            added = [n for n in new_corpora if n not in old_corpora]
            removed = [n for n in old_corpora if n not in new_corpora]
            problems.append(
                f"the corpus set changed across the resume (added: "
                f"{added or 'none'}, removed: {removed or 'none'}): "
                f"per-corpus mix state pairs by name and cannot follow "
                f"a changed set. Restart with "
                f"--datasets={','.join(old_corpora)}, or pass "
                f"--allow_corpus_change=True to accept it (removed "
                f"corpora drop their stream position; new corpora start "
                f"cold)"
            )

    old_logical = int(old.get("n_logical_shards") or 0)
    new_logical = int(new.get("n_logical_shards") or 0)
    if old_logical != new_logical:
        problems.append(
            f"n_logical_shards changed ({old_logical} -> {new_logical}): "
            f"the logical-shard count is fixed when the run first saves; "
            f"restart with --logical_shards={old_logical}"
        )

    old_lw = int(old.get("loader_files") or 0)
    new_lw = int(new.get("loader_files") or 0)
    if old_lw and new_lw and old_logical and old_logical % new_lw != 0:
        legal = [
            d
            for d in range(1, old_logical + 1)
            if old_logical % d == 0
        ]
        problems.append(
            f"new loader world {new_lw} (process_count x num_workers) does "
            f"not divide n_logical_shards {old_logical}; loader state "
            f"cannot be repartitioned. Legal process x worker products: "
            f"{legal} — adjust --num_workers (or the host count) to one "
            f"of them"
        )

    if old_lw and ckp_dir is not None:
        found = _count_loader_files(ckp_dir)
        # 0 on-disk files is legal: the loader resumes from its own
        # newest auto-save dir, not necessarily this model checkpoint
        if 0 < found < old_lw:
            problems.append(
                f"checkpoint {ckp_dir} holds {found} loader_state file(s) "
                f"but was written by {old_lw} loader rank(s); an elastic "
                f"resume needs every per-rank file to reassemble the "
                f"document walk — the checkpoint copy is incomplete"
            )

    old_rows = int(old.get("global_batch_rows") or 0)
    new_rows = int(new.get("global_batch_rows") or 0)
    if old_rows and new_rows and old_rows != new_rows and not allow_batch_change:
        problems.append(
            f"global batch would change across the rescale "
            f"({old_rows} -> {new_rows} rows), shifting tokens_seen, the "
            f"LR schedule, and the loss trajectory. Set --batch_size so "
            f"per-rank rows x data-parallel extent = {old_rows}, or pass "
            f"--allow_batch_change=True to accept the change"
        )

    old_seq = int(old.get("seq_length") or 0)
    new_seq = int(new.get("seq_length") or 0)
    if old_seq and new_seq and old_seq != new_seq and not allow_batch_change:
        problems.append(
            f"seq_length changed across the resume ({old_seq} -> "
            f"{new_seq}): tokens-per-step and the packed loader stream "
            f"both shift. Restart with --seq_length={old_seq}, or pass "
            f"--allow_batch_change=True to accept the change"
        )

    return problems, changed
